"""Driver-facing benchmark shim — the implementation lives in
``gan_deeplearning4j_tpu.bench`` (namespaced so the installed wheel does
not drop a generically-named top-level ``bench`` module into
site-packages).  Kept at the repo root because the driver invokes
``python bench.py`` here; prints ONE JSON line (see the package module's
docstring for the schema)."""

from gan_deeplearning4j_tpu.bench import (  # noqa: F401
    BATCH,
    METHODOLOGY_VERSION,
    _build_step_and_args,
    _fence,
    e2e_img_per_sec,
    main,
    protocol_step_time,
)

if __name__ == "__main__":
    import sys

    sys.exit(main())
