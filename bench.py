"""Driver-facing benchmark entry, hardened against a wedged device link.

The implementation lives in ``gan_deeplearning4j_tpu.bench``; this shim is
what the driver runs (``python bench.py``) and its contract is strict:

  print ONE final JSON line and exit 0 — ALWAYS.

Two shapes of that line:

  healthy link   -> the inner benchmark's own JSON
                    ({"metric": "dcgan_mnist_img_per_sec", "value": N, ...});
                    the payload is also cached to ``BENCH_LASTGOOD.json``
                    (with probe context) when it was measured on a real
                    accelerator, so a later wedged round can cite it.
  unreachable    -> {"metric": ..., "value": null, "skipped": true,
                     "reason": "...", "cached": {... last verified device
                     run, clearly labeled ...}}

Why this exists: the PJRT link to the chip is a shared tunnel whose
round-trip latency has been observed anywhere from ~70ms to wedged-for-
minutes within one day.  ``jax.devices()`` on a wedged tunnel blocks
indefinitely, so the parent process NEVER initializes a JAX backend; all
device contact happens in bounded-timeout subprocesses:

  1. probe:  ``utils/probe.py``'s dispatch+readback child, bounded by
             BENCH_PROBE_TIMEOUT, retried BENCH_PROBE_ATTEMPTS times with
             backoff (a wedged tunnel often recovers within minutes);
  2. run:    the real benchmark child, BENCH_RUN_TIMEOUT bound, one
             re-probe-and-retry on TRANSIENT failure (a tunnel can die
             mid-run); deterministic failures (argparse rc 2) skip
             immediately.

Knobs (env, all optional): BENCH_PROBE_TIMEOUT (s, default 90),
BENCH_PROBE_ATTEMPTS (default 3), BENCH_PROBE_BACKOFF (s, default 45),
BENCH_RUN_TIMEOUT (s, default 2400).  CLI flags are passed through to the
inner benchmark (see ``python -m gan_deeplearning4j_tpu.bench --help``).

Verified failure path: run with the tunnel down (or
``JAX_PLATFORMS=tpu`` on a host with no TPU) — the skip line appears
within attempts*(timeout+backoff) seconds; tests/test_bench_entry.py
pins this behavior with a guaranteed-dead backend.

Exception to the exit-0 contract: ``--dryrun`` (the CI smoke lane) runs
the inner benchmark's CPU build-and-execute smoke with NO probe and
exits nonzero when it fails — CI wants the red X, not a structured skip.
The smoke also runs the async-vs-sync checkpoint A/B (``ok`` requires
the async save's training-thread blocking time <= 25% of the
synchronous save AND byte-identical manifests — see
docs/FAULT_TOLERANCE.md).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# re-exported for tests/test_tpu_smoke.py and interactive use; the inner
# module imports no JAX at module scope, so this cannot wedge
from gan_deeplearning4j_tpu.bench import (  # noqa: F401
    BATCH,
    METHODOLOGY_VERSION,
    _build_step_and_args,
    _fence,
    e2e_img_per_sec,
    protocol_step_time,
)
from gan_deeplearning4j_tpu.utils.probe import probe_device

REPO = os.path.dirname(os.path.abspath(__file__))
LASTGOOD_PATH = os.path.join(REPO, "BENCH_LASTGOOD.json")


def _env_num(name: str, default: float, cast=float):
    """A malformed env knob must degrade to the default, not crash the
    shim before it can print its JSON line."""
    try:
        return cast(os.environ.get(name, default))
    except (TypeError, ValueError):
        print(f"[bench] ignoring malformed {name}={os.environ[name]!r}; "
              f"using {default}", file=sys.stderr, flush=True)
        return default


PROBE_TIMEOUT = _env_num("BENCH_PROBE_TIMEOUT", 90.0)
PROBE_ATTEMPTS = _env_num("BENCH_PROBE_ATTEMPTS", 3, int)
PROBE_BACKOFF = _env_num("BENCH_PROBE_BACKOFF", 45.0)
RUN_TIMEOUT = _env_num("BENCH_RUN_TIMEOUT", 2400.0)


def _log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def probe_with_retry():
    """The shared bounded retry loop (utils/probe.py) at this entry's
    env-configured knobs."""
    from gan_deeplearning4j_tpu.utils.probe import probe_with_retry as p

    return p(PROBE_TIMEOUT, cwd=REPO, attempts=PROBE_ATTEMPTS,
             backoff_s=PROBE_BACKOFF, log=_log)


def _emit(payload: dict) -> int:
    print(json.dumps(payload), flush=True)
    return 0


def _skip(reason: str) -> int:
    payload = {
        "metric": "dcgan_mnist_img_per_sec",
        "value": None,
        "unit": "img/sec/chip",
        "skipped": True,
        "reason": reason,
    }
    if os.path.exists(LASTGOOD_PATH):
        try:
            with open(LASTGOOD_PATH) as f:
                payload["cached"] = json.load(f)
            payload["cached_note"] = (
                "last verified accelerator run (see cached.captured_*); "
                "NOT measured this round")
        except (OSError, ValueError):
            pass
    return _emit(payload)


def _record_lastgood(payload: dict, platform: str, rt_ms: float) -> None:
    # only a default-shaped run (reference batch 200, e2e included,
    # reference-numerics main measurement) may replace the cached
    # headline — a debug invocation (--batch 8, --skip-e2e) or an --mp
    # run must not become what a later wedged round cites
    if (payload.get("batch") != 200 or "e2e_img_per_sec" not in payload
            or payload.get("compute_bf16")):
        _log("non-default run; BENCH_LASTGOOD.json left untouched")
        return
    record = {
        **payload,
        "captured_platform": platform,
        "captured_probe_rt_ms": round(rt_ms, 1),
        "captured_unix_time": int(time.time()),
    }
    try:
        # carry the per-series gate record forward, refreshed with this
        # capture's own series — a main-bench refresh must not un-gate
        # the fleet baseline (bench_gate.py per-series records)
        from gan_deeplearning4j_tpu import bench_gate
        try:
            with open(LASTGOOD_PATH) as f:
                series = dict(json.load(f).get("series") or {})
        except (OSError, ValueError):
            series = {}
        for label, med, iqr in bench_gate.series_stats(payload):
            series[label] = {"median_ms": med, "iqr_ms": iqr}
        if series:
            record["series"] = series
        with open(LASTGOOD_PATH, "w") as f:
            json.dump(record, f, indent=1)
    except OSError as e:  # a read-only checkout must not fail the bench
        _log(f"could not write {LASTGOOD_PATH}: {e}")


def _dryrun(argv) -> int:
    """CI smoke lane: no probe, no accelerator — run the inner bench's
    --dryrun (build + execute the fused program on CPU) in a bounded
    subprocess and relay its JSON line.  A collection/trace regression
    in the fused-step stack fails this in seconds."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "gan_deeplearning4j_tpu.bench"] + argv
    try:
        out = subprocess.run(cmd, cwd=REPO, capture_output=True, text=True,
                             timeout=600, env=env)
    except subprocess.TimeoutExpired:
        # a hung build is exactly what this lane guards — red X, not a
        # structured skip
        print(json.dumps({"metric": "dcgan_mnist_img_per_sec",
                          "dryrun": True, "ok": False,
                          "reason": "dryrun exceeded 600s"}))
        return 1
    for line in out.stderr.strip().splitlines()[-10:]:
        _log(f"inner! {line}")
    if out.returncode != 0:
        print(json.dumps({"metric": "dcgan_mnist_img_per_sec",
                          "dryrun": True, "ok": False,
                          "reason": out.stderr.strip()[-500:]}))
        return 1  # the ONE mode where a failure should fail the caller
    line = out.stdout.strip().splitlines()[-1]
    print(line)
    try:
        ok = bool(json.loads(line).get("ok"))
    except ValueError:
        ok = False
    # the smoke can fail WITHOUT crashing (ok:false, e.g. NaN losses) —
    # CI keys on the exit code, so ok:false must be red too
    return 0 if ok else 1


def _main_inner(argv) -> int:
    if "--dryrun" in argv:
        return _dryrun(argv)
    try:
        platform, rt_ms = probe_with_retry()
    except RuntimeError as e:
        return _skip(f"probe exhausted {PROBE_ATTEMPTS} attempts: {e}")

    cmd = [sys.executable, "-m", "gan_deeplearning4j_tpu.bench"] + argv
    for attempt in (1, 2):
        try:
            out = subprocess.run(cmd, cwd=REPO, capture_output=True,
                                 text=True, timeout=RUN_TIMEOUT)
        except subprocess.TimeoutExpired:
            fail = f"benchmark run exceeded {RUN_TIMEOUT:.0f}s"
            out = None
        else:
            if out.returncode == 0:
                break
            fail = ("benchmark run failed: "
                    + " | ".join(out.stderr.strip().splitlines()[-3:])[-500:])
            if out.returncode == 2:  # argparse usage error: deterministic
                return _skip(fail)
        _log(fail)
        if attempt == 1:
            # the tunnel may have died mid-run; one bounded re-probe
            # decides between retry and structured skip
            try:
                platform, rt_ms = probe_with_retry()
            except RuntimeError as e:
                return _skip(f"{fail}; re-probe also failed: {e}")
            _log("re-probe ok; retrying benchmark once")
    else:
        return _skip(f"benchmark failed twice with a live probe: {fail}")

    for line in out.stdout.strip().splitlines()[:-1]:
        _log(f"inner: {line}")
    for line in out.stderr.strip().splitlines()[-20:]:
        _log(f"inner! {line}")
    try:
        payload = json.loads(out.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return _skip(
            f"benchmark printed no JSON line: {out.stdout[-300:]!r}")
    if platform != "cpu":
        _record_lastgood(payload, platform, rt_ms)
    return _emit(payload)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    try:
        return _main_inner(argv)
    except Exception as e:  # the contract: one JSON line, exit 0, ALWAYS
        if "--dryrun" in argv:
            # ...except the CI smoke lane, which must go red on ANY
            # failure (module docstring) — a swallowed exception here
            # would green-light exactly what the lane guards against
            print(json.dumps({"metric": "dcgan_mnist_img_per_sec",
                              "dryrun": True, "ok": False,
                              "reason": f"shim error: {e!r}"}))
            return 1
        try:
            return _skip(f"unexpected shim error: {e!r}")
        except Exception:
            print(json.dumps({"metric": "dcgan_mnist_img_per_sec",
                              "value": None, "skipped": True,
                              "reason": "unexpected shim error"}))
            return 0


if __name__ == "__main__":
    sys.exit(main())
