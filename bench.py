"""Benchmark: DCGAN-on-MNIST full-protocol training throughput (img/sec).

The BASELINE.json north-star metric: the reference publishes no throughput
(BASELINE.md), so the baseline is the same three-graph protocol executed on
the host CPU (the stand-in for the reference's nd4j-native CPU run, which
cannot execute here).  The CPU number is measured once and cached in
``BENCH_BASELINE.json``; the benchmark then runs on the default JAX
platform (the TPU when attached) and reports the ratio.

Prints ONE JSON line:
  {"metric": "dcgan_mnist_img_per_sec", "value": N, "unit": "img/sec/chip",
   "vs_baseline": N}
"""

from __future__ import annotations

import json
import os
import sys
import time

BASELINE_PATH = os.path.join(os.path.dirname(__file__), "BENCH_BASELINE.json")
BATCH = 200          # batchSizePerWorker (dl4jGANComputerVision.java:59)
WARMUP = 3
STEPS = 20
# Bump when the measured step's methodology changes; a cached baseline
# from another version is discarded and re-measured (apples to apples).
METHODOLOGY_VERSION = 3  # v3: per-step host latent draws in the timed loop


def protocol_step_time(device) -> float:
    """Mean seconds per full GAN-protocol iteration (D-step + syncs +
    G-step + classifier step, batch 200) on the given device, using the
    framework's fused one-XLA-program step (train/fused_step.py)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gan_deeplearning4j_tpu.models import dcgan_mnist as M
    from gan_deeplearning4j_tpu.train import fused_step as fused

    with jax.default_device(device):
        dis, gen, gan = (
            M.build_discriminator(), M.build_generator(), M.build_gan())
        classifier = M.build_classifier(dis)
        step = fused.make_protocol_step(
            dis, gen, gan, classifier,
            M.DIS_TO_GAN, M.GAN_TO_GEN, M.DIS_TO_CLASSIFIER,
            z_size=2, num_features=784,
        )
        state = fused.state_from_graphs(dis, gen, gan, classifier)
        rng = np.random.RandomState(0)
        real = jax.device_put(rng.rand(BATCH, 784).astype(np.float32), device)
        labels = jax.device_put(
            np.eye(10, dtype=np.float32)[rng.randint(0, 10, BATCH)], device)
        ones = jnp.ones((BATCH, 1), dtype=jnp.float32)
        # pre-softened target vectors (label softening is loop-invariant,
        # dl4jGANComputerVision.java:384-385)
        y_real = ones + 0.05 * jnp.asarray(rng.randn(BATCH, 1), jnp.float32)
        y_fake = 0.05 * jnp.asarray(rng.randn(BATCH, 1), jnp.float32)
        key = jax.random.key(0)

        def run_step(i, state):
            # per-step latent draws, z ~ U[-1,1] (dl4jGANComputerVision.java:397,425)
            z1 = jax.random.uniform(jax.random.fold_in(key, 2 * i), (BATCH, 2),
                                    minval=-1.0, maxval=1.0)
            z2 = jax.random.uniform(jax.random.fold_in(key, 2 * i + 1),
                                    (BATCH, 2), minval=-1.0, maxval=1.0)
            return step(state, jax.random.fold_in(key, 10_000 + i),
                        real, labels, z1, z2, y_real, y_fake, ones)

        for i in range(WARMUP):
            state, losses = run_step(i, state)
        jax.block_until_ready(losses)
        t0 = time.perf_counter()
        for i in range(WARMUP, WARMUP + STEPS):
            state, losses = run_step(i, state)
        jax.block_until_ready(losses)
        return (time.perf_counter() - t0) / STEPS


def main() -> None:
    import jax

    default = jax.devices()[0]
    cpu = jax.devices("cpu")[0]

    # baseline: CPU protocol throughput, measured once and cached
    baseline = None
    if os.path.exists(BASELINE_PATH):
        with open(BASELINE_PATH) as f:
            cached = json.load(f)
        if cached.get("version") == METHODOLOGY_VERSION:
            baseline = cached.get("cpu_img_per_sec")
    if not baseline:
        cpu_step = protocol_step_time(cpu)
        baseline = BATCH / cpu_step
        with open(BASELINE_PATH, "w") as f:
            json.dump({
                "version": METHODOLOGY_VERSION,
                "cpu_img_per_sec": baseline,
                "note": "fused three-graph protocol step on host CPU, batch "
                        "200 (stand-in for the reference's nd4j-native CPU run)",
            }, f, indent=1)

    if default.platform == "cpu":
        value = baseline
    else:
        value = BATCH / protocol_step_time(default)

    print(json.dumps({
        "metric": "dcgan_mnist_img_per_sec",
        "value": round(value, 2),
        "unit": "img/sec/chip",
        "vs_baseline": round(value / baseline, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
