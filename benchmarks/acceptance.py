"""Acceptance battery: the reference's own evidence runs, as one command.

Probes the device link first (a tunneled PJRT backend can wedge — see
utils.device docs), then runs, on the attached device:

  1. ``bench.py``              — step/multistep/MFU/e2e JSON line
  2. CV DCGAN 10k acceptance   — accuracy + FID (+ fid_ema with --ema-decay)
  3. insurance 5k acceptance   — weighted AUROC

and prints ONE summary JSON.  This is the reproduce-everything command
behind RESULTS.md §1/§2 (the reference's 97.07% / 91.63% evidence style,
gan.ipynb raw lines 373-374).

Run: ``python benchmarks/acceptance.py [--out-dir DIR] [--ema-decay 0.999]
[--skip-insurance] [--probe-timeout 90]``
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


def probe_device(timeout_s: float) -> float:
    """Round-trip ms for a small dispatch+readback in a subprocess (a
    wedged tunnel then times out the child, not this process).  Returns
    the measured ms, or raises RuntimeError."""
    code = (
        "import os, time, numpy as np, jax, jax.numpy as jnp\n"
        # honor an explicit JAX_PLATFORMS in this FRESH child interpreter
        # (safe here: no in-process override to clobber — see the NOTE in
        # runtime/backend.py for why the library itself must not do this)
        "if os.environ.get('JAX_PLATFORMS'):\n"
        "    jax.config.update('jax_platforms', os.environ['JAX_PLATFORMS'])\n"
        "f = jax.jit(lambda a: a @ a)\n"
        "x = jnp.ones((64, 64)); np.asarray(f(x))\n"
        "t0 = time.perf_counter()\n"
        "for _ in range(5): np.asarray(f(x))\n"
        "print((time.perf_counter() - t0) * 200)\n"
    )
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout_s)
    except subprocess.TimeoutExpired:
        raise RuntimeError(
            f"device link unresponsive (> {timeout_s:.0f}s for a small "
            "round trip); retry when the tunnel recovers") from None
    if out.returncode != 0:
        raise RuntimeError(f"device probe failed:\n{out.stderr[-800:]}")
    return float(out.stdout.strip().splitlines()[-1])


def main(argv=None) -> dict:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out-dir", default="outputs/acceptance")
    p.add_argument("--ema-decay", type=float, default=0.999)
    p.add_argument("--skip-bench", action="store_true")
    p.add_argument("--skip-insurance", action="store_true")
    p.add_argument("--probe-timeout", type=float, default=90.0)
    args = p.parse_args(argv)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    summary: dict = {}

    rt_ms = probe_device(args.probe_timeout)
    summary["probe_round_trip_ms"] = round(rt_ms, 1)
    print(f"[acceptance] device round trip {rt_ms:.1f} ms", flush=True)

    def run(cmd, tag):
        t0 = time.perf_counter()
        out = subprocess.run([sys.executable] + cmd, cwd=repo,
                             capture_output=True, text=True)
        dt = time.perf_counter() - t0
        if out.returncode != 0:
            raise RuntimeError(f"{tag} failed:\n{out.stderr[-1500:]}")
        last = out.stdout.strip().splitlines()[-1]
        print(f"[acceptance] {tag} done in {dt:.0f}s: {last}", flush=True)
        return last, dt

    if not args.skip_bench:
        line, dt = run(["bench.py"], "bench")
        summary["bench"] = json.loads(line)
        summary["bench_wall_s"] = round(dt, 1)

    cv_cmd = ["-m", "gan_deeplearning4j_tpu.train.cv_main",
              "--res-path", os.path.join(args.out_dir, "cv")]
    if args.ema_decay:
        cv_cmd += ["--ema-decay", str(args.ema_decay)]
    line, dt = run(cv_cmd, "cv-10k")
    summary["cv"] = json.loads(line)
    summary["cv_wall_s"] = round(dt, 1)

    if not args.skip_insurance:
        line, dt = run(["-m", "gan_deeplearning4j_tpu.train.insurance_main",
                        "--res-path", os.path.join(args.out_dir, "insurance")],
                       "insurance-5k")
        summary["insurance"] = json.loads(line)
        summary["insurance_wall_s"] = round(dt, 1)

    print(json.dumps(summary))
    return summary


if __name__ == "__main__":
    main()
