"""Acceptance battery: the reference's own evidence runs, as one command.

Probes the device link first (a tunneled PJRT backend can wedge — see
utils.device docs), then runs, on the attached device:

  1. ``bench.py``              — step/multistep/MFU/e2e JSON line
  2. CV DCGAN 10k acceptance   — accuracy + FID (+ fid_ema with --ema-decay)
  3. insurance 5k acceptance   — weighted AUROC

and prints ONE summary JSON.  This is the reproduce-everything command
behind RESULTS.md §1/§2 (the reference's 97.07% / 91.63% evidence style,
gan.ipynb raw lines 373-374).

Run: ``python benchmarks/acceptance.py [--out-dir DIR] [--ema-decay 0.999]
[--skip-insurance] [--probe-timeout 90]``
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time


_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # runnable as `python benchmarks/acceptance.py`
    sys.path.insert(0, _REPO)

from gan_deeplearning4j_tpu.utils.probe import probe_device  # noqa: E402


def main(argv=None) -> dict:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out-dir", default="outputs/acceptance")
    p.add_argument("--ema-decay", type=float, default=0.999)
    p.add_argument("--skip-bench", action="store_true")
    p.add_argument("--skip-insurance", action="store_true")
    p.add_argument("--probe-timeout", type=float, default=90.0)
    args = p.parse_args(argv)

    repo = _REPO
    summary: dict = {}

    platform, rt_ms = probe_device(args.probe_timeout, cwd=repo)
    summary["probe_platform"] = platform
    summary["probe_round_trip_ms"] = round(rt_ms, 1)
    print(f"[acceptance] {platform} round trip {rt_ms:.1f} ms", flush=True)

    def run(cmd, tag, env_extra=None):
        t0 = time.perf_counter()
        env = {**os.environ, **(env_extra or {})}
        out = subprocess.run([sys.executable] + cmd, cwd=repo, env=env,
                             capture_output=True, text=True)
        dt = time.perf_counter() - t0
        if out.returncode != 0:
            raise RuntimeError(f"{tag} failed:\n{out.stderr[-1500:]}")
        last = out.stdout.strip().splitlines()[-1]
        print(f"[acceptance] {tag} done in {dt:.0f}s: {last}", flush=True)
        return last, dt

    if not args.skip_bench:
        # this battery already probed; one quick confirm inside the shim
        # is enough (no multi-attempt backoff window on top)
        line, dt = run(["bench.py"], "bench",
                       env_extra={"BENCH_PROBE_ATTEMPTS": "1"})
        summary["bench"] = json.loads(line)
        summary["bench_wall_s"] = round(dt, 1)

    cv_cmd = ["-m", "gan_deeplearning4j_tpu.train.cv_main",
              "--res-path", os.path.join(args.out_dir, "cv")]
    if args.ema_decay:
        cv_cmd += ["--ema-decay", str(args.ema_decay)]
    line, dt = run(cv_cmd, "cv-10k")
    summary["cv"] = json.loads(line)
    summary["cv_wall_s"] = round(dt, 1)

    if not args.skip_insurance:
        line, dt = run(["-m", "gan_deeplearning4j_tpu.train.insurance_main",
                        "--res-path", os.path.join(args.out_dir, "insurance")],
                       "insurance-5k")
        summary["insurance"] = json.loads(line)
        summary["insurance_wall_s"] = round(dt, 1)

    # the three reference-comparable headline numbers in one place
    # (97.07% / 91.63%, gan.ipynb raw 373-374; FID in the frozen space)
    headline = {}
    if "cv" in summary:
        headline["cv_accuracy"] = summary["cv"].get("test_accuracy")
        headline["fid"] = summary["cv"].get("fid_primary")
        headline["fid_source"] = summary["cv"].get("fid_primary_source")
    if "insurance" in summary:
        headline["insurance_auroc"] = summary["insurance"].get("test_auroc")
    summary["headline"] = headline

    print(json.dumps(summary))
    return summary


if __name__ == "__main__":
    main()
