"""CelebA-64 acceptance: 10k-step EMA run with a per-1k frozen-FID
trajectory (VERDICT r4 next-step #1).

The CelebA family is the one with TPU-scale convolutions, so its quality
evidence must match the MNIST family's discipline: a full 10k-iteration
EMA training run (roadmap_main's engine — GANPair multistep, checkpointed
every 1k), then the frozen 64x64 attribute-CNN extractor
(eval/fid_extractor.py, committed asset) scores FID at every checkpoint,
live and EMA weights, against a held-out surrogate draw.  Replaces the
r4 state of "eyeballed grids at 3k steps" with a committed number +
trajectory.

Prints ONE JSON line:
  {"metric": "celeba_fid_frozen", "value": <final EMA FID>,
   "trajectory": [{"step": N, "fid": F, "fid_ema": F}, ...],
   "examples_per_sec": N, ...}

Run (TPU): python benchmarks/celeba_acceptance.py
           [--iterations 10000] [--every 1000] [--fid-samples 5000]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--iterations", type=int, default=10000)
    p.add_argument("--every", type=int, default=1000)
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--n-train", type=int, default=10000)
    p.add_argument("--fid-samples", type=int, default=5000)
    p.add_argument("--ema-decay", type=float, default=0.999)
    p.add_argument("--lr-decay-steps", type=int, default=-1,
                   help="hold-then-sigmoid-decay horizon; -1 (default) = "
                        "the run length (the measured stabilizer: constant "
                        "LR degrades past ~3k as D overpowers G), 0 = "
                        "constant LR")
    p.add_argument("--ms-weight", type=float, default=0.0,
                   help="mode-seeking regularizer weight (the r5 cGAN "
                        "diversity lever, applied to the unconditional "
                        "family's measured geometric collapse)")
    p.add_argument("--res-path", default=None)
    args = p.parse_args(argv)
    if args.iterations % args.every or args.iterations <= 0:
        # roadmap_main checkpoints only at multiples of --every: a ragged
        # horizon would silently report an earlier step's FID as final
        raise SystemExit("--iterations must be a positive multiple of "
                         "--every")

    from gan_deeplearning4j_tpu.checkpoint import TrainCheckpointer
    from gan_deeplearning4j_tpu.data import datasets
    from gan_deeplearning4j_tpu.eval import fid as fid_lib
    from gan_deeplearning4j_tpu.eval import fid_extractor as fx
    from gan_deeplearning4j_tpu.models import dcgan_celeba
    from gan_deeplearning4j_tpu.train import roadmap_main

    res = args.res_path or tempfile.mkdtemp(prefix="celeba_accept_")
    n_ckpts = args.iterations // args.every + 1

    decay = args.iterations if args.lr_decay_steps < 0 \
        else (args.lr_decay_steps or None)
    result = roadmap_main.train(
        "celeba", args.iterations, args.batch, res, args.n_train,
        print_every=args.every, ema_decay=args.ema_decay,
        checkpoint_every=args.every, checkpoint_keep=n_ckpts,
        lr_decay_steps=decay, ms_weight=args.ms_weight,
        log=lambda s: print(s, file=sys.stderr, flush=True))

    # held-out real draw (training used the default seed-666 table).
    # decay_steps must match the run's: checkpoint restore validates the
    # opt_state tree and a Scheduled updater carries an extra counter.
    cfg = dcgan_celeba.CelebAConfig(decay_steps=decay)
    real = datasets.synthetic_celeba(args.fid_samples, seed=cfg.seed + 1)
    frozen = fx.load_extractor_celeba()
    f_real = fid_lib.extract_features(frozen, real, fx.FEATURE_LAYER,
                                      batch_size=250)

    gen = dcgan_celeba.build_generator(cfg)

    def fid_of(params=None) -> float:
        orig = gen.params
        if params is not None:
            gen.params = params
        try:
            gx = fid_lib.synthesize_pixels(
                gen, args.fid_samples, real.shape[1], z_size=cfg.z_size,
                batch_size=250)
        finally:
            gen.params = orig
        f = fid_lib.extract_features(frozen, gx, fx.FEATURE_LAYER,
                                     batch_size=250)
        return float(fid_lib.fid_from_features(f_real, f))

    ckpt = TrainCheckpointer(os.path.join(res, "celeba_ckpt"),
                             keep=n_ckpts)
    dis = dcgan_celeba.build_discriminator(cfg)
    trajectory = []
    for step in ckpt.steps():
        _, extra = ckpt.restore({"gen": gen, "dis": dis}, step=step)
        row = {"step": step, "fid": fid_of()}
        if "ema" in extra:
            row["fid_ema"] = fid_of(extra["ema"])
        trajectory.append(row)
        print(f"[celeba-accept] {row}", file=sys.stderr, flush=True)

    final = trajectory[-1] if trajectory else {}
    print(json.dumps({
        "metric": "celeba_fid_frozen",
        "value": final.get("fid_ema", final.get("fid")),
        "unit": "frozen-FID (64x64 attribute-CNN space)",
        "iterations": args.iterations,
        "batch": args.batch,
        "ema_decay": args.ema_decay,
        # the two recipe flags that distinguish the ablation runs — an
        # evidence JSON must be tied to the configuration that made it
        "lr_decay_steps": decay,
        "ms_weight": args.ms_weight,
        "examples_per_sec": result["examples_per_sec"],
        "d_loss": result["d_loss"],
        "g_loss": result["g_loss"],
        "trajectory": trajectory,
        "res_path": res,
    }, default=float))


if __name__ == "__main__":
    main()
