"""FID trajectory of a CV acceptance run — the r3 outlier-seed probe.

VERDICT r3 weak-#6: one of the ten acceptance seeds (555) landed
fid_primary 56.5 against a 16-38 band, with the EMA score WORSE than the
live weights — unexplained.  This script re-runs a seed with periodic
checkpoints and scores fid_frozen (live and EMA weights) at every 1k
steps, distinguishing the two candidate failure modes:

  - late collapse: the live trajectory degrades near the end;
  - EMA pathology: the live trajectory is fine but the 0.999-decay
    average trails a moving equilibrium (the adversarial weights orbit
    rather than converge, so the trajectory MEAN can sit off the orbit).

Prints one JSON line with the per-checkpoint trajectory.

Run (TPU): python benchmarks/fid_trajectory.py [--seed 555]
           [--iterations 10000] [--every 1000] [--fid-samples 10000]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seed", type=int, default=555)
    p.add_argument("--iterations", type=int, default=10000)
    p.add_argument("--every", type=int, default=1000)
    p.add_argument("--fid-samples", type=int, default=10000)
    p.add_argument("--res-path", default=None)
    args = p.parse_args(argv)

    import jax.numpy as jnp

    from gan_deeplearning4j_tpu.checkpoint import TrainCheckpointer
    from gan_deeplearning4j_tpu.data import datasets
    from gan_deeplearning4j_tpu.eval import fid as fid_lib
    from gan_deeplearning4j_tpu.eval import fid_extractor as fx
    from gan_deeplearning4j_tpu.train import cv_main
    from gan_deeplearning4j_tpu.train.gan_trainer import GANTrainer

    res = args.res_path or tempfile.mkdtemp(prefix="fid_traj_")
    n_ckpts = args.iterations // args.every + 1
    config = cv_main.default_config(
        seed=args.seed, num_iterations=args.iterations, res_path=res,
        checkpoint_every=args.every, checkpoint_keep=n_ckpts,
        ema_decay=0.999, metrics=False,
        print_every=10 ** 9, save_every=args.iterations)
    workload = cv_main.CVWorkload()
    trainer = GANTrainer(workload, config)
    trainer.train(log=lambda s: None)

    real, _ = datasets.load_split(os.path.join(res, "mnist_test.csv"),
                                  config.label_index)
    real = real[: args.fid_samples].astype("float32")
    frozen = fx.load_extractor()
    f_real = fid_lib.extract_features(frozen, real, fx.FEATURE_LAYER)
    mu_r, cov_r = f_real.mean(axis=0), np.cov(f_real, rowvar=False)

    def fid_of(gen_graph, params=None) -> float:
        orig = gen_graph.params
        if params is not None:
            gen_graph.params = params
        try:
            gx = fid_lib.synthesize_pixels(
                gen_graph, args.fid_samples, real.shape[1],
                z_size=config.z_size)
        finally:
            gen_graph.params = orig
        f = fid_lib.extract_features(frozen, gx, fx.FEATURE_LAYER)
        return float(fid_lib.frechet_distance(
            mu_r, cov_r, f.mean(axis=0), np.cov(f, rowvar=False)))

    ckpt = TrainCheckpointer(os.path.join(res, "checkpoints"),
                             keep=n_ckpts)
    trajectory = []
    graphs = {"dis": trainer.dis, "gen": trainer.gen, "gan": trainer.gan,
              "classifier": trainer.classifier}
    for step in ckpt.steps():
        _, extra = ckpt.restore(graphs, step=step)
        ema = {}
        for key, v in extra.items():
            if key.startswith("ema:"):
                _, layer, name = key.split(":", 2)
                ema.setdefault(layer, {})[name] = jnp.asarray(v)
        ema_params = ({layer: ema.get(layer, {})
                       for layer in trainer.gen.params} if ema else None)
        row = {"step": step, "fid_frozen": fid_of(trainer.gen)}
        if ema_params is not None:
            row["fid_frozen_ema"] = fid_of(trainer.gen, ema_params)
        trajectory.append(row)
        print(f"[traj] {row}", file=sys.stderr, flush=True)

    print(json.dumps({
        "metric": "fid_trajectory", "seed": args.seed,
        "iterations": args.iterations, "trajectory": trajectory,
    }))


if __name__ == "__main__":
    main()
