"""On-chip microbenchmark: Pallas one-pass RmsProp chain vs stock XLA.

The RESULTS r2 §4 profile put the updater's elementwise chain
(`multiply_subtract_fusion`) at 61ms/300 steps; ops/pallas/fused_update.py
is the hand-fused attack.  This measures the isolated chain per leaf
shape — the flagship models' big dense/conv weights — XLA vs Pallas, with
the same scan-chained readback-fenced methodology as pallas_bn_bench.py
(dispatch latency over the tunnel would otherwise swamp the kernel).

The chain is HBM-bandwidth bound (read p,g,cache; write p',cache' = 5N
floats), so the expected ceiling is bytes/bandwidth; the reported
``bound_us`` column is that floor on v5e (819 GB/s) for calibration.

Usage: python benchmarks/fused_update_bench.py [--iters 200] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from gan_deeplearning4j_tpu.ops.pallas.fused_update import fused_rmsprop_chain
from gan_deeplearning4j_tpu.optim.rmsprop import rmsprop_update_leaf

# the flagship protocol's biggest gradient-bearing leaves
SHAPES = [
    (1152, 1024),    # dis dense W (28^2 chain -> 1024)
    (3200, 6272),    # gen dense W
    (128, 64, 5, 5),  # dis conv2 W
    (1024, 10),      # classifier head
]
LR, RHO, EPS, L2, CLIP = 0.0002, 1e-8, 1e-8, 1e-4, 1.0
HBM_BW = 819e9  # v5e


def _xla_chain(p, g, c):
    g = jnp.clip(g + L2 * p, -CLIP, CLIP)
    upd, c2 = rmsprop_update_leaf(g, c, LR, RHO, EPS)
    return p - upd, c2


INTERPRET = False  # set by --interpret (CPU correctness drive, not perf)


def _pallas_chain(p, g, c):
    return fused_rmsprop_chain(p, g, c, lr=LR, rho=RHO, eps=EPS, l2=L2,
                               clip=CLIP, interpret=INTERPRET)


def _time_chain(fn, p, g, c, iters: int) -> float:
    """Per-application seconds: ``iters`` chained applications inside one
    jitted scan (p,c feed back; g fixed), fenced by a scalar readback."""

    def body(carry, _):
        p, c = carry
        p2, c2 = fn(p, g, c)
        return (p2, c2), ()

    @jax.jit
    def run(p, c):
        (p2, c2), _ = lax.scan(body, (p, c), None, length=iters)
        return p2.reshape(-1)[0] + c2.reshape(-1)[0]

    float(run(p, c))  # compile
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        float(run(p, c))  # the readback IS the fence
        ts.append((time.perf_counter() - t0) / iters)
    return statistics.median(ts)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=200)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--interpret", action="store_true",
                    help="interpret the Pallas kernel (CPU flow check; "
                         "timings are then meaningless)")
    ap.add_argument("--smoke", action="store_true",
                    help="one tiny shape only (CPU flow check)")
    args = ap.parse_args(argv)
    global INTERPRET, SHAPES
    INTERPRET = args.interpret
    if args.smoke:
        SHAPES = [(64, 130)]

    rng = np.random.RandomState(0)
    rows = []
    for shape in SHAPES:
        p = jnp.asarray(rng.randn(*shape).astype(np.float32))
        g = jnp.asarray(rng.randn(*shape).astype(np.float32))
        c = jnp.asarray(np.abs(rng.randn(*shape)).astype(np.float32))
        n = p.size
        xla_s = _time_chain(_xla_chain, p, g, c, args.iters)
        pal_s = _time_chain(_pallas_chain, p, g, c, args.iters)
        rows.append({
            "shape": list(shape),
            "elements": n,
            "xla_us": round(xla_s * 1e6, 2),
            "pallas_us": round(pal_s * 1e6, 2),
            "bound_us": round(5 * 4 * n / HBM_BW * 1e6, 2),
            "pallas_vs_xla": round(xla_s / pal_s, 3),
        })
    if args.json:
        print(json.dumps(rows))
    else:
        print(f"{'shape':>18} {'xla_us':>8} {'pallas_us':>10} "
              f"{'bound_us':>9} {'speedup':>8}")
        for r in rows:
            print(f"{str(tuple(r['shape'])):>18} {r['xla_us']:>8} "
                  f"{r['pallas_us']:>10} {r['bound_us']:>9} "
                  f"{r['pallas_vs_xla']:>8}")


if __name__ == "__main__":
    from gan_deeplearning4j_tpu.runtime import backend

    backend.apply_env_platform()
    main()
