"""Compile-time per-op attribution of the training programs' cost
(VERDICT r4 next-step #2): no profiler needed — the tunneled profiler's
op ids are opaque, but `jit.lower().compile().as_text()` yields the
optimized HLO with full shapes, windows and source metadata, enough to
compute per-op FLOPs and bytes and a roofline time estimate for every
instruction.

For each named program this script reports:
  - per-op table rows: {op, kind, flops, bytes, t_est_us, source}
    sorted by the roofline estimate t_est = max(flops/PEAK, bytes/BW);
  - aggregates: matmul/conv FLOPs vs the XLA cost model's total,
    total top-level bytes, roofline-implied step time, and the measured
    step time when the chip is reachable (--measure).

Programs: the MNIST protocol multistep at b200 f32 (the default
headline), b1600 fast mode (s2d+bf16+mp), b3200 f32 (the r4 regression),
and the CelebA-64 GANPair multistep at b128.

Run: python benchmarks/hlo_cost.py [--programs b200_f32,b1600_fast,...]
     [--measure] [--top 12]
Prints ONE JSON line; human-readable tables go to stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# v5e (TPU v5 lite): dense bf16 peak and HBM bandwidth — the roofline
# axes.  f32 convs execute through the MXU's bf16 pipeline (multiple
# passes), so PEAK is the optimistic denominator for both dtypes.
PEAK_FLOPS = 197e12
HBM_BW = 819e9

_DTYPE_BYTES = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
                "s8": 1, "u8": 1, "pred": 1, "s64": 8, "u64": 8,
                "f64": 8, "s16": 2, "u16": 2}

_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*(\([^)]*\)|\S+)\s+([\w\-]+)\(")
_OPERAND_RE = re.compile(r"%([\w.\-]+)")


def _shape_bytes(type_str: str) -> int:
    """Total logical bytes of an HLO type string (tuples summed)."""
    total = 0
    for m in _SHAPE_RE.finditer(type_str):
        dt, dims = m.group(1), m.group(2)
        if dt not in _DTYPE_BYTES:
            continue
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n * _DTYPE_BYTES[dt]
    return total


def _out_elems(type_str: str) -> int:
    m = _SHAPE_RE.search(type_str)
    if not m:
        return 0
    n = 1
    if m.group(2):
        for d in m.group(2).split(","):
            n *= int(d)
    return n


def _conv_flops(line: str, out_type: str,
                shapes: Dict[str, str]) -> Optional[float]:
    """2 * out_elems * K for a convolution instruction; K = reduction
    size = window elements x input feature depth, read off dim_labels
    and the rhs operand's shape."""
    out_n = _out_elems(out_type)
    dl = re.search(r"dim_labels=(\S+?)(?:,|$| )", line)
    if not dl:
        return None
    labels = dl.group(1)
    lhs_l, rest = labels.split("_", 1)
    rhs_l, out_l = rest.split("->")
    ops = _OPERAND_RE.findall(line.split("(", 1)[1])
    if len(ops) < 2:
        return None
    rhs_type = shapes.get(ops[1])
    if rhs_type is None:
        return None
    m = _SHAPE_RE.search(rhs_type)
    if not m or not m.group(2):
        return None
    rhs_dims = [int(d) for d in m.group(2).split(",")]
    if len(rhs_dims) != len(rhs_l):
        return None
    # reduction = input-feature dim x spatial window dims of the rhs
    k = 1
    for ch, d in zip(rhs_l, rhs_dims):
        if ch == "i" or ch.isdigit():
            k *= d
    # grouped convs (feature_group_count) divide the i-depth; the s2d/d2s
    # rewrites don't use them, but parse defensively
    g = re.search(r"feature_group_count=(\d+)", line)
    if g:
        k //= max(1, int(g.group(1)))
    return 2.0 * out_n * k


def analyze_hlo(txt: str) -> List[dict]:
    """Per-instruction rows from optimized HLO text.  Instructions in
    "executed-at-top-level" computations (ENTRY, while bodies/conds —
    targets of ``body=``/``condition=``) carry bytes; computations that
    are fusion internals or scalar lambdas (targets of ``calls=`` /
    ``to_apply=``) don't — their HBM traffic is the call site's operand/
    result bytes.  Convolution FLOPs are attributed wherever the
    instruction appears (TPU convs live INSIDE kConv fusion bodies)."""
    shapes: Dict[str, str] = {}
    for m in re.finditer(
            r"%([\w.\-]+)\s*=\s*((?:\([^)]*\))|(?:\w+\[[\d,]*\]\S*))", txt):
        shapes.setdefault(m.group(1), m.group(2))
    # call-graph pass: computations whose instructions are NOT separately
    # scheduled (inlined fusion bodies, reduction lambdas, async slices)
    inlined = set()
    for m in re.finditer(r"(?:calls|to_apply|select|scatter)=%([\w.\-]+)",
                         txt):
        inlined.add(m.group(1))

    rows: List[dict] = []
    computation = ""
    in_fusion_body = False
    for line in txt.splitlines():
        header = re.match(r"^\s*(?:ENTRY\s+)?(?:ROOT\s+)?%?([\w.\-]+)\s+\(",
                          line) if (line.rstrip().endswith("{")
                                    and "->" in line) else None
        if header:
            computation = header.group(1)
            in_fusion_body = computation in inlined
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, out_type, op = m.groups()
        if op in ("parameter", "constant", "get-tuple-element", "tuple",
                  "bitcast"):
            continue
        out_bytes = _shape_bytes(out_type)
        if op in ("slice", "dynamic-slice", "gather"):
            # reads only the sliced window, not the whole operand
            in_bytes = out_bytes
        elif op == "dynamic-update-slice":
            # writes (and reads) only the update window
            ops_ = _OPERAND_RE.findall(line.split("(", 1)[1])
            upd = _shape_bytes(shapes.get(ops_[1], "")) if len(ops_) > 1 \
                else out_bytes
            in_bytes, out_bytes = upd, upd
        elif op.endswith(("-start", "-done")) or op == "async-update":
            # DMA halves of overlapped transfers: the payload is counted
            # once at the consuming/producing op, and prefetches overlap
            # compute — charging both halves serially double-counts
            continue
        elif op == "custom-call" and "Bitcast" in line:
            in_bytes = 0  # ConcatBitcast and friends: layout fictions
        else:
            operands = _OPERAND_RE.findall(line.split("(", 1)[1])
            in_bytes = sum(_shape_bytes(shapes.get(o, ""))
                           for o in operands)
        flops = 0.0
        if op == "convolution":
            flops = _conv_flops(line, out_type, shapes) or 0.0
        meta = re.search(r'op_name="([^"]*)"', line)
        src = re.search(r'source_file="([^"]*)"', line)
        rows.append({
            "name": name, "op": op, "computation": computation,
            "in_fusion_body": in_fusion_body,
            "flops": flops,
            "bytes": 0 if in_fusion_body else in_bytes + out_bytes,
            "op_name": meta.group(1) if meta else "",
            "source": os.path.basename(src.group(1)) if src else "",
        })
    return rows


def overlap_bounds(total_flops: float, total_bytes: float,
                   peak: float = PEAK_FLOPS, bw: float = HBM_BW) -> dict:
    """The DMA/compute overlap envelope of a program (RESULTS.md
    "Overlap experiment series"): with ZERO overlap the step costs
    flops-time + bytes-time; with PERFECT overlap it costs
    max(flops-time, bytes-time).  The measured step time falling at the
    no-overlap bound (b1600 fast mode, r5: 7.6ms bytes + 3.7ms flops ~=
    12.2ms measured) is the diagnosis the overlap series attacks; the
    all-overlap MFU is the ceiling any scheduling/restructure work can
    reach without removing traffic."""
    flops_s = total_flops / peak
    bytes_s = total_bytes / bw
    no_overlap_s = flops_s + bytes_s
    all_overlap_s = max(flops_s, bytes_s)
    return {
        "flops_us": round(flops_s * 1e6, 1),
        "bytes_us": round(bytes_s * 1e6, 1),
        "no_overlap_us": round(no_overlap_s * 1e6, 1),
        "all_overlap_us": round(all_overlap_s * 1e6, 1),
        # MFU = flops-time / step-time at each envelope edge
        "mfu_at_no_overlap": (round(flops_s / no_overlap_s, 4)
                              if no_overlap_s > 0 else None),
        "mfu_at_all_overlap": (round(flops_s / all_overlap_s, 4)
                               if all_overlap_s > 0 else None),
        "bound": "bytes" if bytes_s > flops_s else "flops",
    }


def summarize(rows: List[dict], top: int) -> dict:
    for r in rows:
        r["t_est_us"] = max(r["flops"] / PEAK_FLOPS,
                            r["bytes"] / HBM_BW) * 1e6
    # a conv inside a fusion body: merge its flops into the call site's
    # row is nontrivial to resolve textually; keep both rows but mark.
    ranked = sorted(rows, key=lambda r: -r["t_est_us"])
    total_flops = sum(r["flops"] for r in rows)
    total_bytes = sum(r["bytes"] for r in rows)
    roofline_us = sum(r["t_est_us"] for r in rows)
    out_rows = []
    for r in ranked[:top]:
        out_rows.append({
            "op": f"{r['op']}:{r['name']}",
            "flops_g": round(r["flops"] / 1e9, 3),
            "mbytes": round(r["bytes"] / 1e6, 3),
            "t_est_us": round(r["t_est_us"], 2),
            "bound": ("flops" if r["flops"] / PEAK_FLOPS
                      >= r["bytes"] / HBM_BW else "bytes"),
            "where": (r["op_name"].split("/")[-1] or r["op"])
            + (f" [{r['source']}]" if r["source"] else ""),
        })
    by_kind: Dict[str, float] = {}
    for r in rows:
        by_kind[r["op"]] = by_kind.get(r["op"], 0.0) + r["t_est_us"]
    return {
        "total_conv_dot_flops": total_flops,
        "total_toplevel_bytes": total_bytes,
        "roofline_us_per_step": round(roofline_us, 1),
        "flops_us": round(total_flops / PEAK_FLOPS * 1e6, 1),
        "bytes_us": round(total_bytes / HBM_BW * 1e6, 1),
        # the overlap envelope from the per-instruction totals; the
        # canonical (cost-model-flops) version lands in run_program
        "bounds": overlap_bounds(total_flops, total_bytes),
        "top_ops": out_rows,
        "t_est_by_opkind_us": {k: round(v, 1) for k, v in
                               sorted(by_kind.items(),
                                      key=lambda kv: -kv[1])[:10]},
    }


# -- program builders ------------------------------------------------------

def _mnist_program(batch: int, fast: bool, k: int = 100):
    # k matches the trainer/bench chunk (MAX_STEPS_PER_CALL): per-call
    # dispatch overhead over the tunnel (~ms) must amortize over many
    # steps or the slope overestimates per-step time (measured 3x at k=10)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gan_deeplearning4j_tpu.models import dcgan_mnist as M
    from gan_deeplearning4j_tpu.runtime import backend
    from gan_deeplearning4j_tpu.train import fused_step as fused

    backend.configure(conv_s2d=True if fast else None,
                      matmul_bf16=fast, compute_bf16=fast)
    dev = jax.devices()[0]
    with jax.default_device(dev):
        dis, gen, gan = (M.build_discriminator(), M.build_generator(),
                         M.build_gan())
        clf = M.build_classifier(dis)
        rng = np.random.RandomState(0)
        ones = jnp.ones((batch, 1), jnp.float32)
        key = jax.random.key(0)
        step = fused.make_protocol_step(
            dis, gen, gan, clf,
            M.DIS_TO_GAN, M.GAN_TO_GEN, M.DIS_TO_CLASSIFIER,
            z_size=2, num_features=784,
            data_on_device=True, steps_per_call=k)
        state = jax.device_put(
            fused.state_from_graphs(dis, gen, gan, clf), dev)
        table = jax.device_put(
            rng.rand(4 * batch, 784).astype(np.float32), dev)
        labels = jax.device_put(
            np.eye(10, dtype=np.float32)[rng.randint(0, 10, 4 * batch)],
            dev)
        inv = (key, jax.random.fold_in(key, 1),
               ones + 0.05 * jnp.asarray(rng.randn(batch, 1), jnp.float32),
               0.05 * jnp.asarray(rng.randn(batch, 1), jnp.float32), ones)
        args = (state, table, labels) + inv
        return step, args, k


def _celeba_program(batch: int = 128, k: int = 20):
    import jax
    import jax.numpy as jnp

    from gan_deeplearning4j_tpu.data import datasets
    from gan_deeplearning4j_tpu.models import dcgan_celeba as M
    from gan_deeplearning4j_tpu.runtime import backend
    from gan_deeplearning4j_tpu.train.gan_pair import GANPair

    backend.configure(conv_s2d=None, matmul_bf16=False, compute_bf16=False)
    dev = jax.devices()[0]
    with jax.default_device(dev):
        cfg = M.CelebAConfig()
        pair = GANPair(M.build_generator(cfg), M.build_discriminator(cfg))
        table = jax.device_put(
            jnp.asarray(datasets.synthetic_celeba(4 * batch, seed=0)), dev)
        step_fn, state = pair.make_multistep(
            table, None, batch_size=batch, steps_per_call=k,
            real_label=cfg.real_label, z_size=cfg.z_size)
        state = jax.device_put(state, dev)
        return step_fn.jitted, (state,) + step_fn.invariants, k


PROGRAMS = {
    "b200_f32": lambda: _mnist_program(200, fast=False),
    "b1600_fast": lambda: _mnist_program(1600, fast=True),
    "b3200_f32": lambda: _mnist_program(3200, fast=False),
    "celeba_b128": lambda: _celeba_program(128),
}


def run_program(name: str, top: int, measure: bool,
                dump_dir: Optional[str]) -> dict:
    import jax

    jitted, args, k = PROGRAMS[name]()
    lowered = jitted.lower(*args)
    compiled = lowered.compile()
    txt = compiled.as_text()
    if dump_dir:
        os.makedirs(dump_dir, exist_ok=True)
        with open(os.path.join(dump_dir, f"{name}.hlo.txt"), "w") as f:
            f.write(txt)
    rows = analyze_hlo(txt)
    summary = summarize(rows, top)
    ca = compiled.cost_analysis() or {}
    summary["xla_cost_flops"] = float(ca.get("flops", 0.0))
    summary["xla_cost_bytes"] = float(ca.get("bytes accessed", 0.0))
    # the canonical FLOPs-time: the XLA cost model's count (the
    # per-instruction total over-counts by including while-loop PEEL
    # duplicates — e.g. conv_general_dilated.339 AND .339.clone.3 both
    # appear in the text; ranking is unaffected, totals are an upper
    # bound)
    if summary["xla_cost_flops"]:
        summary["flops_xla_us"] = round(
            summary["xla_cost_flops"] / PEAK_FLOPS * 1e6, 1)
        # canonical overlap envelope: cost-model flops (no loop-peel
        # double count) against the per-instruction byte total
        summary["bounds"] = overlap_bounds(
            summary["xla_cost_flops"], summary["total_toplevel_bytes"])
        summary["flops_us_note"] = ("per-instruction total; upper bound "
                                    "(loop-peel duplicates included) — "
                                    "flops_xla_us is canonical")
    else:
        summary["flops_us_note"] = ("per-instruction total; upper bound "
                                    "(loop-peel duplicates included); no "
                                    "cost-model count available on this "
                                    "backend")
    summary["program"] = name
    if measure:
        import statistics
        import time

        from gan_deeplearning4j_tpu.utils import device_fence

        out = jitted(*args)
        device_fence(out)

        def window(n):
            t0 = time.perf_counter()
            o = None
            for _ in range(n):
                o = jitted(*args)
            device_fence(o)
            return time.perf_counter() - t0

        # adaptive windows: the tunnel's ~0.1s round trip rides on every
        # fenced window, so the long window must be seconds — size it
        # from a first timed call, then slope over 3 repeats (median)
        t_call = max(window(1), 1e-3)
        hi = max(4, min(60, int(3.0 / t_call)))
        lo = max(1, hi // 5)
        slopes = []
        for _ in range(3):
            t_lo = window(lo)
            t_hi = window(hi)
            slopes.append((t_hi - t_lo) / ((hi - lo) * k))
        t = statistics.median(slopes)
        summary["measured_us_per_step"] = round(t * 1e6, 1)
        if summary["xla_cost_flops"]:
            summary["measured_mfu"] = round(
                summary["xla_cost_flops"] / t / PEAK_FLOPS, 4)
    return summary


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--programs", default=",".join(PROGRAMS))
    p.add_argument("--top", type=int, default=12)
    p.add_argument("--measure", action="store_true",
                   help="also time each program on the chip (slope "
                        "method) for roofline-vs-measured comparison")
    p.add_argument("--dump-dir", default=None,
                   help="also write each program's optimized HLO text")
    args = p.parse_args(argv)

    results = []
    for name in args.programs.split(","):
        name = name.strip()
        if not name:
            continue
        if name not in PROGRAMS:
            raise SystemExit(f"unknown program {name!r}; "
                             f"have {sorted(PROGRAMS)}")
        print(f"[hlo-cost] compiling {name}...", file=sys.stderr,
              flush=True)
        s = run_program(name, args.top, args.measure, args.dump_dir)
        results.append(s)
        print(f"[hlo-cost] {name}: roofline {s['roofline_us_per_step']}us "
              f"(flops-bound {s['flops_us']}us, bytes {s['bytes_us']}us)"
              + (f", measured {s['measured_us_per_step']}us"
                 if "measured_us_per_step" in s else ""),
              file=sys.stderr, flush=True)
        for r in s["top_ops"]:
            print(f"[hlo-cost]   {r['t_est_us']:>9.1f}us {r['bound']:>5} "
                  f"{r['flops_g']:>8.2f}GF {r['mbytes']:>8.2f}MB "
                  f"{r['op'][:46]:<46} {r['where'][:60]}",
                  file=sys.stderr, flush=True)
    print(json.dumps({"metric": "hlo_cost_attribution",
                      "peak_flops": PEAK_FLOPS, "hbm_bw": HBM_BW,
                      "programs": results}, default=float))


if __name__ == "__main__":
    main()
