"""Per-flag A/B driver for the DMA/compute overlap series (RESULTS.md
"Overlap experiment series").

Why a subprocess per lane: XLA parses ``XLA_FLAGS`` exactly ONCE, at
backend initialization, and a flag unknown to the build is a hard
``F``-check abort (parse_flags_from_env.cc), not an exception.  An
in-process loop over flag sets would either measure the first lane's
flags forever (silently — the A/B lie) or die on the first lane the
build doesn't know.  So each lane re-execs
``python -m gan_deeplearning4j_tpu.bench`` with its own environment and
classifies the outcome:

  measured      — the inner bench printed its JSON line;
  flag-rejected — the backend aborted on an unknown flag (recorded with
                  the stderr tail: ON THIS BUILD the flag doesn't exist,
                  which is itself a result for the experiment log);
  failed        — anything else (timeout, crash), stderr tail kept.

Lanes (the experiment matrix; restructure lanes measure the OLD lowering
via the bench's --no-* flags so the committed default is the candidate):

  baseline                 the shipped configuration, no extra flags
  no-carry-dedup           scan carry WITH the mirrored-W/b copies
  no-upsample-sum-bwd      autodiff broadcast+reduce upsample backward
  no-pool-argmax-bwd       select-and-scatter maxpool backward
  lhs                      --xla_tpu_enable_latency_hiding_scheduler
  lhs-async-copy           + async copy/DMA scheduling knobs

Run:  python benchmarks/overlap_ab.py [--lanes baseline,lhs,...]
      [--output FILE] [--timeout SEC] [--bench-args "--skip-celeba ..."]
Prints ONE JSON line (the lane table); human-readable rows to stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# lane -> (extra XLA_FLAGS or None, extra bench argv)
LANES: Dict[str, Tuple[Optional[str], List[str]]] = {
    "baseline": (None, []),
    "no-carry-dedup": (None, ["--no-carry-dedup"]),
    "no-upsample-sum-bwd": (None, ["--no-upsample-sum-bwd"]),
    "no-pool-argmax-bwd": (None, ["--no-pool-argmax-bwd"]),
    # the latency-hiding scheduler: XLA's own DMA/compute overlap pass,
    # off by default for TPU while-loop programs of this shape
    "lhs": ("--xla_tpu_enable_latency_hiding_scheduler=true", []),
    # + async copy scheduling: let the scheduler issue the big HBM
    # copies as overlapped async pairs it can hide under the MXU work
    "lhs-async-copy": (
        "--xla_tpu_enable_latency_hiding_scheduler=true "
        "--xla_tpu_enable_async_collective_fusion=true", []),
}

# the default per-lane inner-bench arguments: the protocol multistep +
# fast-mode blocks carry the overlap story; e2e/celeba ride full runs
DEFAULT_BENCH_ARGS = ["--skip-e2e", "--skip-celeba"]


def run_lane(name: str, xla_flags: Optional[str], bench_args: List[str],
             timeout_s: float) -> dict:
    env = dict(os.environ)
    if xla_flags:
        prev = env.get("XLA_FLAGS", "")
        env["XLA_FLAGS"] = (prev + " " + xla_flags).strip()
    cmd = [sys.executable, "-m", "gan_deeplearning4j_tpu.bench",
           *bench_args]
    rec: dict = {"lane": name, "xla_flags": xla_flags,
                 "bench_args": bench_args}
    try:
        proc = subprocess.run(cmd, env=env, cwd=_REPO,
                              capture_output=True, text=True,
                              timeout=timeout_s)
    except subprocess.TimeoutExpired:
        rec["status"] = "failed"
        rec["error"] = f"timeout after {timeout_s}s"
        return rec
    tail = (proc.stderr or "")[-2000:]
    if proc.returncode != 0:
        rejected = "Unknown flags in XLA_FLAGS" in (proc.stderr or "")
        rec["status"] = "flag-rejected" if rejected else "failed"
        rec["error"] = tail[-400:]
        return rec
    # the inner bench prints ONE JSON line last; tolerate log lines above
    payload = None
    for line in reversed((proc.stdout or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                payload = json.loads(line)
                break
            except ValueError:
                continue
    if payload is None:
        rec["status"] = "failed"
        rec["error"] = "no JSON line in bench stdout; stderr: " + tail[-300:]
        return rec
    rec["status"] = "measured"
    rec["capture"] = payload
    rec["summary"] = _summarize(payload)
    return rec


def _summarize(cap: dict) -> dict:
    """The experiment-table row: the numbers RESULTS.md's per-experiment
    table cites per lane."""
    out = {"multistep_step_ms": cap.get("multistep_step_ms"),
           "mfu": cap.get("mfu")}
    spread = cap.get("spread")
    if isinstance(spread, dict):
        out["iqr_ms"] = spread.get("iqr_ms")
    fast = cap.get("fast_mode")
    if isinstance(fast, dict):
        out["fast_step_ms"] = fast.get("multistep_step_ms")
        out["fast_mfu"] = fast.get("multistep_mfu")
        if isinstance(fast.get("spread"), dict):
            out["fast_iqr_ms"] = fast["spread"].get("iqr_ms")
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--lanes", default=",".join(LANES),
                   help="comma-separated lane names to run "
                        f"(default: all of {sorted(LANES)})")
    p.add_argument("--output", default=None, metavar="FILE",
                   help="also write the lane table (indented) here")
    p.add_argument("--timeout", type=float, default=2400.0,
                   help="per-lane subprocess timeout (seconds)")
    p.add_argument("--bench-args", default=" ".join(DEFAULT_BENCH_ARGS),
                   help="inner-bench argv shared by every lane "
                        "(lane-specific --no-* flags append to these)")
    args = p.parse_args(argv)

    shared = args.bench_args.split()
    lanes = []
    for name in args.lanes.split(","):
        name = name.strip()
        if not name:
            continue
        if name not in LANES:
            raise SystemExit(f"unknown lane {name!r}; have {sorted(LANES)}")
        lanes.append(name)

    results = []
    for name in lanes:
        xla_flags, extra = LANES[name]
        print(f"[overlap-ab] lane {name}"
              + (f" (XLA_FLAGS: {xla_flags})" if xla_flags else ""),
              file=sys.stderr, flush=True)
        rec = run_lane(name, xla_flags, shared + extra, args.timeout)
        results.append(rec)
        if rec["status"] == "measured":
            s = rec["summary"]
            print(f"[overlap-ab]   {name}: step "
                  f"{s.get('multistep_step_ms')}ms mfu {s.get('mfu')}"
                  + (f" | fast {s.get('fast_step_ms')}ms "
                     f"mfu {s.get('fast_mfu')}"
                     if s.get("fast_step_ms") else ""),
                  file=sys.stderr, flush=True)
        else:
            print(f"[overlap-ab]   {name}: {rec['status']} — "
                  f"{rec.get('error', '')[:160]}",
                  file=sys.stderr, flush=True)
    table = {"metric": "overlap_ab", "lanes": results}
    if args.output:
        with open(args.output, "w") as f:
            json.dump(table, f, indent=1)
    print(json.dumps(table))
    # exit 0 when every lane is at least CLASSIFIED (a rejected flag is
    # a result); nonzero only when a lane failed outright
    return 1 if any(r["status"] == "failed" for r in results) else 0


if __name__ == "__main__":
    sys.exit(main())
