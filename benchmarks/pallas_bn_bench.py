"""On-chip microbenchmark: Pallas fused BN+act vs the stock-XLA lowering.

The VERDICT-mandated evidence that the Pallas kernel earns its place: per
invocation microseconds for the models' actual heavy BatchNorm shapes
(the generator's [200, 6272] BN, the dense [200, 1024] BNs —
dl4jGANComputerVision.java:183-189, :141-151) on the real TPU, forward
and forward+backward, XLA vs Pallas.  The 4-D per-channel BNs
([B, 1, 28, 28]) are measured XLA-only: they stay on the XLA path by
design (C=1 over 28x28 maps — a bandwidth-bound column reduce XLA
already emits optimally; a Pallas kernel would need an HBM-traffic
transpose to tile lanes over channels).

Methodology: the op is applied ``iters`` times inside one jitted
``lax.scan`` (output fed back as input — BN preserves shape) and the
whole program timed; per-op time = total/iters.  This removes dispatch
latency, which over a tunneled PJRT link is milliseconds — larger than
the kernel itself.

Usage: python benchmarks/pallas_bn_bench.py [--iters 200] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from gan_deeplearning4j_tpu.ops import activations as act_lib
from gan_deeplearning4j_tpu.ops.batchnorm import batch_norm_train
from gan_deeplearning4j_tpu.ops.pallas.bn_act import (
    fused_bn_act_train,
    fused_bn_act_train_4d,
)

SHAPES_2D = [(200, 6272), (200, 1024), (400, 6272), (1024, 6272)]
# the CelebA-64 family's per-channel BNs (VERDICT r3 weak-#8: C in
# {64..512} at the discriminator/generator resolutions) + the flagship's
SHAPES_4D = [(200, 1, 28, 28), (200, 64, 12, 12),
             (128, 64, 32, 32), (128, 128, 16, 16),
             (128, 256, 8, 8), (128, 512, 4, 4)]
ACT = "tanh"


def _xla_bn_act(x, gamma, beta):
    y, _, _ = batch_norm_train(x, gamma, beta, jnp.zeros_like(gamma),
                               jnp.ones_like(gamma))
    return act_lib.get(ACT)(y)


def _pallas_bn_act(x, gamma, beta):
    y, _, _ = fused_bn_act_train(x, gamma, beta, 1e-5, ACT)
    return y


def _pallas_bn_act_4d(x, gamma, beta):
    y, _, _ = fused_bn_act_train_4d(x, gamma, beta, 1e-5, ACT)
    return y


def _scan_time(fn, x, args, iters: int, repeats: int = 5) -> float:
    """Median seconds per application of ``fn``: two jitted scans (short
    and long) each ending in a scalar readback, per-op time = slope.

    block_until_ready is a NO-OP on the tunneled axon backend, so every
    timed window must end with an actual transfer; the slope between the
    two window lengths cancels the tunnel round trip and the constant
    per-program overhead (including the summary reduce)."""

    def make(n):
        @jax.jit
        def run(x, *args):
            def body(carry, _):
                return fn(carry, *args), ()

            y, _ = lax.scan(body, x, None, length=n)
            return jnp.sum(y)

        return run

    lo, hi = iters, iters * 5
    run_lo, run_hi = make(lo), make(hi)
    float(run_lo(x, *args))    # compile + warm
    float(run_hi(x, *args))
    slopes = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        float(run_lo(x, *args))
        t_lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        float(run_hi(x, *args))
        t_hi = time.perf_counter() - t0
        slopes.append((t_hi - t_lo) / (hi - lo))
    return statistics.median(slopes)


def _grad_fn(fn):
    def loss(x, *args):
        return jnp.sum(jnp.square(fn(x, *args)))

    g = jax.grad(loss)

    def step(x, *args):
        return x - 1e-6 * g(x, *args)

    return step


def bench_shape(shape, iters: int):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(*shape).astype(np.float32))
    nfeat = shape[1]
    gamma = jnp.asarray(rng.rand(nfeat).astype(np.float32) + 0.5)
    beta = jnp.asarray(rng.randn(nfeat).astype(np.float32))
    args = (gamma, beta)
    row = {"shape": "x".join(map(str, shape))}
    row["xla_fwd_us"] = _scan_time(_xla_bn_act, x, args, iters) * 1e6
    row["xla_fwdbwd_us"] = _scan_time(
        _grad_fn(_xla_bn_act), x, args, iters) * 1e6
    pallas_fn = None
    if len(shape) == 2:
        pallas_fn = _pallas_bn_act
    elif shape[1] > 1:  # 4-D per-channel kernel (C=1 stays XLA-only)
        from gan_deeplearning4j_tpu.ops.pallas.bn_act import supports_4d

        if supports_4d(shape):
            pallas_fn = _pallas_bn_act_4d
        else:
            row["pallas_note"] = "vmem-fallback (block > scoped budget)"
    if pallas_fn is not None:
        row["pallas_fwd_us"] = _scan_time(pallas_fn, x, args, iters) * 1e6
        row["pallas_fwdbwd_us"] = _scan_time(
            _grad_fn(pallas_fn), x, args, iters) * 1e6
        row["fwd_speedup"] = row["xla_fwd_us"] / row["pallas_fwd_us"]
        row["fwdbwd_speedup"] = row["xla_fwdbwd_us"] / row["pallas_fwdbwd_us"]
    return row


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--iters", type=int, default=200)
    p.add_argument("--json", action="store_true")
    args = p.parse_args(argv)

    dev = jax.devices()[0]
    rows = [bench_shape(s, args.iters) for s in SHAPES_2D + SHAPES_4D]
    if args.json:
        print(json.dumps({"device": str(dev), "rows": rows}))
        return
    kind = getattr(dev, "device_kind", "?")
    print(f"device: {dev} ({kind})")
    hdr = ("{:>16} {:>9} {:>11} {:>8} {:>9} {:>11} {:>8}".format(
        "shape", "xla fwd", "pallas fwd", "speedup",
        "xla f+b", "pallas f+b", "speedup"))
    print(hdr)
    for r in rows:
        pf = r.get("pallas_fwd_us")
        if pf:
            p_fwd = "{:>9.1f}us".format(pf)
            s_fwd = "{:.2f}x".format(r["fwd_speedup"])
            p_bwd = "{:>9.1f}us".format(r["pallas_fwdbwd_us"])
            s_bwd = "{:.2f}x".format(r["fwdbwd_speedup"])
        else:
            p_fwd = p_bwd = "      (xla)"
            s_fwd = s_bwd = "-"
        print("{:>16} {:>7.1f}us {:>11} {:>8} {:>7.1f}us {:>11} {:>8}".format(
            r["shape"], r["xla_fwd_us"], p_fwd, s_fwd,
            r["xla_fwdbwd_us"], p_bwd, s_bwd))


if __name__ == "__main__":
    main()
