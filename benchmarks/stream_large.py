"""Streaming-path scale proof: a >2 GiB dataset past the residency budget.

VERDICT r3 weak-#1 asked for evidence that the 2 GiB ``data_on_device``
budget no longer gates throughput: any table larger than HBM's budget
falls onto the streaming path, which in r3 ran two orders of magnitude
below resident.  This benchmark builds a synthetic >2 GiB dataset in the
2-decimal fixed-point contract (MNIST-shaped — the flagship protocol's
shapes, so the step program is the benchmarked one), hands it to the REAL
trainer (in-memory table, same iterator/trainer code path as a decoded
CSV), and measures steady-state streaming throughput: the auto residency
gate must refuse the table and the chunked uint8 transport path must
carry it at near-resident rate.

Prints one JSON line:
  {"rows": N, "table_gib": G, "resident": false, "codec": "u8x100",
   "stream_img_per_sec": N, ...}

Run (TPU): python benchmarks/stream_large.py [--rows N] [--iterations N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # runnable as `python benchmarks/stream_large.py`
    sys.path.insert(0, _REPO)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--rows", type=int, default=720_000,
                   help="dataset rows; 720k x 784 f32 = 2.26 GiB > the "
                        "2 GiB residency budget")
    p.add_argument("--iterations", type=int, default=300)
    p.add_argument("--batch", type=int, default=200)
    args = p.parse_args(argv)

    from gan_deeplearning4j_tpu.train import cv_main
    from gan_deeplearning4j_tpu.train.gan_trainer import GANTrainer

    from gan_deeplearning4j_tpu.data.codec import u8x100_decode_np

    # synthetic pixels already in the %.2f contract: n/100, n in [0, 255]
    rng = np.random.RandomState(666)
    codes = rng.randint(0, 256, (args.rows, 784), dtype=np.uint8)
    features = u8x100_decode_np(codes)
    del codes
    labels = rng.randint(0, 10, (args.rows, 1)).astype(np.float32)
    table = np.concatenate([features, labels], axis=1)
    del features, labels
    table_gib = table.nbytes / (1 << 30)

    class LargeSyntheticWorkload(cv_main.CVWorkload):
        """CV workload over the in-memory table (the iterator accepts
        arrays and paths alike — same trainer code path either way)."""

        def ensure_data(self, res_path):
            test = table[: args.batch]
            return table, test

    with tempfile.TemporaryDirectory() as tmp:
        # data_on_device=False: since r4's u8 residency codec, a
        # 2.1 GiB contract table fits HBM as 538 MB of codes and would
        # stay RESIDENT under auto — good for users, but this benchmark
        # exists to prove the STREAMING path at past-budget scale, so
        # force it.  (Auto-residency of codec-eligible tables up to 4x
        # the budget is covered by tests/test_train.py.)
        config = cv_main.default_config(
            num_iterations=args.iterations, batch_size=args.batch,
            res_path=tmp, print_every=10 ** 9, save_every=10 ** 9,
            metrics=False, data_on_device=False)
        trainer = GANTrainer(LargeSyntheticWorkload(), config)
        t0 = time.perf_counter()
        result = trainer.train(log=lambda s: None)
        wall = time.perf_counter() - t0

    print(json.dumps({
        "metric": "stream_large_img_per_sec",
        "rows": args.rows,
        "table_gib": round(table_gib, 3),
        # codec engages ONLY on the streaming path, so it doubles as the
        # residency-gate witness; the byte check is the gate's own input
        "over_residency_budget": bool(
            table.nbytes > config.data_on_device_max_bytes),
        "codec": trainer._stream_codec,
        "steps_per_call": trainer._steps_per_call,
        "stream_img_per_sec": round(result["examples_per_sec"], 1),
        "wall_s": round(wall, 1),
    }))


if __name__ == "__main__":
    main()
