"""Round-3 TPU measurement queue — everything waiting on the tunnel.

One command to run when a probe finally passes: executes, in priority
order, (1) the acceptance battery, (2) the MFU-sink A/B (baseline vs
--s2d vs --pallas-updater, plus the fused-updater microbench), and
(3) the CelebA 5k roadmap run — each as a bounded subprocess with its
stdout captured to ``outputs/tpu_queue_r3/``, re-probing between stages
so a mid-queue tunnel death skips the remainder with a structured note
instead of hanging.

Usage: python benchmarks/tpu_queue.py [--skip-celeba] [--probe-timeout 90]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from gan_deeplearning4j_tpu.utils.probe import (  # noqa: E402
    probe_with_retry,
)

OUT_DIR = os.path.join(_REPO, "outputs", "tpu_queue_r3")


def run_stage(name: str, cmd: list, timeout_s: float, summary: dict) -> bool:
    """Run one stage; capture tail + last JSON line; False on failure."""
    import signal

    log_path = os.path.join(OUT_DIR, f"{name}.log")
    t0 = time.perf_counter()
    # own process group: a timeout must kill the stage's GRANDCHILDREN too
    # (bench.py spawns the real benchmark as a subprocess) or an orphan
    # keeps holding the chip while later stages probe against it
    proc = subprocess.Popen([sys.executable] + cmd, cwd=_REPO,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True, start_new_session=True)
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s)
        timed_out = False
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, signal.SIGKILL)
        stdout, stderr = proc.communicate()
        timed_out = True
    with open(log_path, "w") as f:
        f.write((stdout or "") + "\n--- stderr ---\n" + (stderr or ""))
    rec: dict = {"ok": (not timed_out) and proc.returncode == 0,
                 "wall_s": round(time.perf_counter() - t0, 1)}
    for line in reversed((stdout or "").strip().splitlines()):
        try:
            parsed = json.loads(line)
        except ValueError:
            continue
        if isinstance(parsed, dict):  # the result object, not a stray scalar
            rec["result"] = parsed
            break
    if timed_out:
        rec["error"] = f"timeout >{timeout_s:.0f}s (partial log kept)"
    elif proc.returncode != 0:
        rec["error"] = (stderr or "").strip().splitlines()[-1:]
    elif isinstance(rec.get("result"), dict) and rec["result"].get("skipped"):
        # bench.py's exit-0 structured-skip contract: rc 0 but NOT a
        # measurement — never report it as a successful stage; surface
        # ITS reason (tunnel, bad flag, ...) rather than guessing one
        rec["ok"] = False
        rec["error"] = ("stage self-skipped: "
                        + str(rec["result"].get("reason", "no reason given")))
    summary[name] = rec
    print(f"[queue] {name}: ok={rec['ok']} wall={rec['wall_s']}s",
          flush=True)
    return rec["ok"]


def probe_ok(timeout_s: float) -> bool:
    """Bounded retry (the shared loop): one blip must not skip a stage;
    the wedged-tunnel fast path is the caller's consecutive-failure
    counter."""
    try:
        platform, rt = probe_with_retry(
            timeout_s, cwd=_REPO, attempts=2, backoff_s=30.0,
            log=lambda m: print(f"[queue] {m}", flush=True))
        return platform not in ("cpu",)
    except RuntimeError:
        return False


def main(argv=None) -> dict:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--skip-celeba", action="store_true")
    ap.add_argument("--probe-timeout", type=float, default=90.0)
    args = ap.parse_args(argv)
    os.makedirs(OUT_DIR, exist_ok=True)
    summary: dict = {"started_unix": int(time.time())}

    stages = [
        ("acceptance",
         ["benchmarks/acceptance.py", "--out-dir", "outputs/acceptance_r3"],
         7200),
        ("bench_baseline", ["bench.py", "--skip-e2e"], 6000),
        ("bench_s2d", ["bench.py", "--skip-e2e", "--s2d"], 6000),
        # 6000s > bench.py's own worst case (probe retries + one full
        # internal retry), so the shim's structured-skip contract always
        # gets to fire before the queue's SIGKILL
        ("bench_pallas_updater",
         ["bench.py", "--skip-e2e", "--pallas-updater"], 6000),
        ("fused_update_bench",
         ["benchmarks/fused_update_bench.py", "--json"], 1800),
        ("pallas_bn_bench",
         ["benchmarks/pallas_bn_bench.py", "--iters", "500", "--json"], 1800),
    ]
    if not args.skip_celeba:
        stages.append((
            "celeba_5k",
            ["-m", "gan_deeplearning4j_tpu.train.roadmap_main",
             "--family", "celeba", "--iterations", "5000",
             "--ema-decay", "0.999", "--checkpoint-every", "500",
             "--res-path", "outputs/celeba_r3"],
            7200))

    dead_probes = 0
    for name, cmd, timeout_s in stages:
        if dead_probes >= 2:
            # two consecutive dead probes: the tunnel is wedged, not
            # blipping — record the rest as skipped without paying a
            # full probe timeout per stage
            summary[name] = {"ok": False, "error": "tunnel down; skipped"}
            continue
        if not probe_ok(args.probe_timeout):
            dead_probes += 1
            summary[name] = {"ok": False, "error": "tunnel down; skipped"}
            print(f"[queue] {name}: SKIPPED (tunnel down)", flush=True)
            continue
        dead_probes = 0
        run_stage(name, cmd, timeout_s, summary)

    path = os.path.join(OUT_DIR, "summary.json")
    with open(path, "w") as f:
        json.dump(summary, f, indent=1)
    print(json.dumps(summary))
    return summary


if __name__ == "__main__":
    main()
