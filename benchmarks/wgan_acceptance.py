"""WGAN-GP 10k acceptance with frozen-space FID (r5: the family was
previously validated only at 2k steps with an eyeballed grid).

The wgan-gp roadmap family trains on the MNIST-shaped surrogate in
[0, 1], which is exactly the committed frozen MNIST extractor's domain
(eval/fid_extractor.py) — so its quality evidence can ride the same
cross-round-comparable FID as the CV flagship, live and EMA weights.

Prints ONE JSON line:
  {"metric": "wgan_gp_fid_frozen", "value": <final EMA FID>, ...}

Run (TPU): python benchmarks/wgan_acceptance.py [--iterations 10000]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--iterations", type=int, default=10000)
    p.add_argument("--batch", type=int, default=128)
    p.add_argument("--n-train", type=int, default=10000)
    p.add_argument("--fid-samples", type=int, default=5000)
    p.add_argument("--ema-decay", type=float, default=0.999)
    p.add_argument("--res-path", default=None)
    args = p.parse_args(argv)

    from gan_deeplearning4j_tpu.data import datasets
    from gan_deeplearning4j_tpu.eval import fid as fid_lib
    from gan_deeplearning4j_tpu.eval import fid_extractor as fx
    from gan_deeplearning4j_tpu.models import wgan_gp
    from gan_deeplearning4j_tpu.train import roadmap_main

    res = args.res_path or tempfile.mkdtemp(prefix="wgan_accept_")
    result = roadmap_main.train(
        "wgan-gp", args.iterations, args.batch, res, args.n_train,
        print_every=max(1000, args.iterations // 10),
        ema_decay=args.ema_decay,
        log=lambda s: print(s, file=sys.stderr, flush=True))

    cfg = wgan_gp.WGANGPConfig()
    # held-out real draw; the family's data law is the CALIBRATED
    # MNIST surrogate in [0,1] (roadmap_main._data)
    real, _ = datasets.synthetic_mnist(args.fid_samples,
                                       seed=cfg.seed + 1)
    real = real.astype("float32")

    from gan_deeplearning4j_tpu.graph import serialization

    fids = {}
    for tag, fname in (("fid_frozen", "wgan-gp_gen_model.zip"),
                       ("fid_frozen_ema", "wgan-gp_gen_ema_model.zip")):
        path = os.path.join(res, fname)
        if not os.path.exists(path):
            continue
        gen = serialization.read_model(path)
        gx = fid_lib.synthesize_pixels(gen, args.fid_samples,
                                       real.shape[1], z_size=cfg.z_size)
        fids[tag] = float(fx.frozen_fid(real, gx))
        print(f"[wgan-accept] {tag} {fids[tag]:.2f}", file=sys.stderr,
              flush=True)

    print(json.dumps({
        "metric": "wgan_gp_fid_frozen",
        "value": fids.get("fid_frozen_ema", fids.get("fid_frozen")),
        "unit": "frozen-FID (MNIST extractor space)",
        "iterations": args.iterations,
        "batch": args.batch,
        "d_loss": result["d_loss"],
        "g_loss": result["g_loss"],
        "examples_per_sec": result["examples_per_sec"],
        **fids,
        "res_path": res,
    }, default=float))


if __name__ == "__main__":
    main()
