"""Build (and execute) docs/walkthrough.ipynb from docs/walkthrough.py.

The reference's user-facing deliverable is a real notebook
(`/root/reference/Python/gan.ipynb`); `docs/walkthrough.py` reproduces
its evaluation cells as a CI-tested percent-format script.  This
converter completes the form factor (VERDICT r4 missing-#3): it parses
the percent cells into an `nbformat` notebook, executes it top to bottom
with `nbclient` (so the committed .ipynb carries REAL outputs), and
writes `docs/walkthrough.ipynb`.

No jupytext in this environment — the percent format is simple enough
to parse directly, and `tests/test_walkthrough.py` pins the committed
notebook's sources to the script so the two cannot drift.

Run: python docs/make_notebook.py [--no-execute]
"""

from __future__ import annotations

import argparse
import os
import sys

DOCS = os.path.dirname(os.path.abspath(__file__))
SCRIPT = os.path.join(DOCS, "walkthrough.py")
NOTEBOOK = os.path.join(DOCS, "walkthrough.ipynb")


def parse_percent_cells(source: str):
    """[(cell_type, source_str)] from a jupytext percent-format script."""
    cells = []
    kind, lines = None, []

    def flush():
        if kind is None:
            return
        text = "\n".join(lines).strip("\n")
        if kind == "markdown":
            # strip the leading "# " comment prefix of markdown cells
            text = "\n".join(
                ln[2:] if ln.startswith("# ") else ("" if ln == "#" else ln)
                for ln in text.splitlines())
        if text:
            cells.append((kind, text))

    for line in source.splitlines():
        marker = line.strip()
        if marker.startswith("# %%"):
            flush()
            kind = "markdown" if "[markdown]" in marker else "code"
            lines = []
        elif kind is not None:
            lines.append(line)
    flush()
    return cells


def build_notebook():
    import nbformat

    nb = nbformat.v4.new_notebook()
    nb.metadata["kernelspec"] = {
        "display_name": "Python 3", "language": "python", "name": "python3"}
    nb.metadata["language_info"] = {"name": "python"}
    with open(SCRIPT) as f:
        src = f.read()
    for kind, text in parse_percent_cells(src):
        if kind == "markdown":
            nb.cells.append(nbformat.v4.new_markdown_cell(text))
        else:
            nb.cells.append(nbformat.v4.new_code_cell(text))
    return nb


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--no-execute", action="store_true",
                   help="write the notebook without running it")
    p.add_argument("--out", default=NOTEBOOK)
    args = p.parse_args(argv)

    import nbformat

    nb = build_notebook()
    if not args.no_execute:
        from nbclient import NotebookClient

        # the walkthrough script self-inserts the repo root into sys.path,
        # but the kernel needs it too (cells import the package directly)
        env_root = os.path.dirname(DOCS)
        os.environ["PYTHONPATH"] = (
            env_root + os.pathsep + os.environ.get("PYTHONPATH", ""))
        NotebookClient(nb, timeout=900, kernel_name="python3",
                       resources={"metadata": {"path": env_root}}).execute()
    with open(args.out, "w") as f:
        nbformat.write(nb, f)
    n_out = sum(1 for c in nb.cells
                if c.cell_type == "code" and c.get("outputs"))
    print(f"wrote {args.out} ({len(nb.cells)} cells, "
          f"{n_out} code cells with outputs)")


if __name__ == "__main__":
    sys.exit(main())
