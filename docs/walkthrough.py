# %% [markdown]
# # GAN feature engineering on TPU — the reference notebook, re-run
#
# The reference's user-facing deliverable is `Python/gan.ipynb`: theory,
# data preparation, the two Java training listings, and the evaluation
# cells that turn training artifacts into the published numbers (97.07%
# CV accuracy at raw line 373, 91.63% insurance AUROC at 374) and the
# lattice figures.  This is that document for the TPU framework —
# executable top to bottom in CI-minutes (`tests/test_walkthrough.py`
# runs it), jupytext percent format (`jupytext --to ipynb
# docs/walkthrough.py` for the .ipynb rendering).
#
# Theory background lives in `docs/THEORY.md` (the minimax game,
# convergence, and the parameter-averaging math — the reference's
# markdown cells 3-5); migration notes from DL4J in `docs/MIGRATION.md`.

# %%
import json
import os
import sys
import tempfile

import numpy as np

# runnable from anywhere: the repo root is the package home (inside the
# .ipynb rendering there is no __file__ — the kernel starts at the root)
_REPO = (os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
         if "__file__" in globals() else os.path.abspath(os.getcwd()))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

import jax

# CPU is fine for the walkthrough's scale; on a TPU host, delete this
# line and the same code runs on the chip unchanged.
jax.config.update("jax_platforms", "cpu")

RES = tempfile.mkdtemp(prefix="gan4j_walkthrough_")
print("artifacts land in", RES)

# %% [markdown]
# ## 1. Computer-vision task (reference cells 6-7)
#
# The reference trains its three-graph protocol for 10,000 iterations in
# Java, then cell 7 reads the dumped artifacts.  Here the SAME protocol
# (D-step, cross-graph weight sync, G-step, transfer classifier — one
# fused XLA program per chunk of steps) runs in-process; the walkthrough
# budget is a few steps, enough to produce every artifact kind the
# reference evaluates.  (`--iterations 10000` on a TPU host reproduces
# the acceptance numbers in RESULTS.md §1.)

# %%
from gan_deeplearning4j_tpu.train import cv_main

cv_res = os.path.join(RES, "cv")
cv_result = cv_main.main([
    "--iterations", "4", "--batch-size", "16", "--n-train", "256",
    "--n-test", "64", "--print-every", "2", "--save-every", "4",
    "--steps-per-call", "1", "--res-path", cv_res,
])
print(json.dumps(cv_result, indent=2, default=float))

# %% [markdown]
# ### Accuracy over the prediction dump (cell 7's first half)
#
# The trainer dumps `mnist_test_predictions_{k}.csv` at the reference's
# `saveEvery` cadence — softmax rows over the 10 classes.  Accuracy is
# argmax agreement with the test labels, exactly the notebook's
# computation.

# %%
from gan_deeplearning4j_tpu.eval import mnist_accuracy

acc = mnist_accuracy(
    os.path.join(cv_res, "mnist_test_predictions_4.csv"),
    os.path.join(cv_res, "mnist_test.csv"))
print(f"classifier accuracy after 4 steps: {acc:.4f} "
      "(the 10k acceptance run reaches ~0.97 — RESULTS.md §1)")

# %% [markdown]
# ### The lattice figures (cell 7's second half)
#
# The reference's signature artifact: the generator sampled over the
# z in [-1,1]^2 cartesian grid, rendered as a pixel lattice.  The
# trainer already wrote the grid CSV (`mnist_out_{k}.csv`, 50x50 rows of
# 784 features by default; 10x10 here); the eval module renders the same
# three PNGs the reference publishes.

# %%
from gan_deeplearning4j_tpu.eval import grid_to_lattices
from gan_deeplearning4j_tpu.eval.plots import save_grid_png

grid_csv = os.path.join(cv_res, "mnist_out_4.csv")
lattices = grid_to_lattices(grid_csv, rows=28, cols=28)  # per-sample shape
print("lattice tensor:", lattices.shape)
save_grid_png(os.path.join(RES, "DCGAN_Generated_Images.png"),
              grid_csv, (28, 28))
print("wrote", sorted(f for f in os.listdir(RES) if f.endswith(".png")))

# %% [markdown]
# ## 2. Insurance task (reference cells 8-10)
#
# Cell 8 prepares the claim-risk table (70/30 split at seed 666, train-
# stat min-max scaling — `data/datasets.py` reproduces the contract);
# cell 9 lists the Java; cell 10 scores the weighted AUROC over the
# prediction dump.  One command here:

# %%
from gan_deeplearning4j_tpu.train import insurance_main

ins_res = os.path.join(RES, "insurance")
ins_result = insurance_main.main([
    "--iterations", "4", "--print-every", "2", "--save-every", "4",
    "--steps-per-call", "1", "--res-path", ins_res,
])
print(json.dumps(ins_result, indent=2, default=float))

# %%
from gan_deeplearning4j_tpu.eval import insurance_auroc

auroc = insurance_auroc(
    os.path.join(ins_res, "insurance_test_predictions_4.csv"),
    os.path.join(ins_res, "insurance_test.csv"))
print(f"weighted AUROC after 4 steps: {auroc:.4f} "
      "(the 5k acceptance run reaches ~0.92 vs the reference's 0.9163)")

# %% [markdown]
# ### The generated-feature grid (cell 10's extra artifact)
#
# The insurance main also dumps the classifier's prediction over the
# GENERATED latent grid (`insurance_out_pred_{k}.csv`,
# dl4jGANInsurance.java:422-437) — the "risk surface" of the synthetic
# feature space.

# %%
pred_grid = np.loadtxt(os.path.join(ins_res, "insurance_out_pred_4.csv"),
                       delimiter=",", ndmin=2)
print("risk surface over the 50x50 latent grid:", pred_grid.shape,
      f"mean risk {pred_grid.mean():.3f}")

# %% [markdown]
# ### The transaction-lattice figures (the reference's
# `DCGAN_Generated_Lattice_Example[_Plotted].png`)
#
# One generated insurance "transaction lattice" (period rows x
# premium/service/claim columns), raw and annotated — the reference's
# signature insurance artifacts.

# %%
from gan_deeplearning4j_tpu.eval.plots import save_lattice_example_pngs

save_lattice_example_pngs(
    os.path.join(RES, "DCGAN_Generated_Lattice_Example.png"),
    os.path.join(RES, "DCGAN_Generated_Lattice_Example_Plotted.png"),
    os.path.join(ins_res, "insurance_out_4.csv"))
print("wrote", sorted(f for f in os.listdir(RES) if f.endswith(".png")))

# %% [markdown]
# ## 3. Where to go deeper
#
# - `RESULTS.md` — every measured number (throughput/MFU, acceptance
#   accuracy/FID/AUROC with 10-seed bands, streaming-path scaling).
# - `docs/THEORY.md` — the reference's theory cells, expanded.
# - `docs/MIGRATION.md` — DL4J-to-this-framework mapping, including
#   `graph.import_dl4j` for the reference's own model zips and
#   `graph.import_keras` for Keras models.
# - `python -m gan_deeplearning4j_tpu.bench` — the benchmark harness.

# %%
print("walkthrough complete; artifacts in", RES)
