"""gan_deeplearning4j_tpu — a TPU-native deep-learning framework.

A from-scratch JAX/XLA/Pallas re-design of the capability surface of
``javadev-berlin/gan_deeplearning4j`` (DL4J ComputationGraph + ND4J + libnd4j +
dl4j-spark): a named-layer computation-graph API with per-layer optimizers and
transfer-learning surgery, ops lowered to XLA/Pallas instead of libnd4j
CPU/CUDA kernels, and data-parallel replica sync over ICI all-reduce instead of
Spark parameter averaging / Aeron gradient sharing.

Layer map (reference SURVEY.md §1 -> this package):
  L1/L2 ndarray+kernels  -> jax.Array on PJRT + ops/ (XLA, Pallas)
  L3 ComputationGraph    -> graph/ (named-layer graph builder, autodiff via jax.grad)
  L4 dl4j-spark DP       -> parallel/ (pjit/shard_map + psum over ICI)
  L5 DataVec CSV         -> data/ (CSV pipeline, native C++ fast loader)
  L7 the two mains       -> train/ (cv_main, insurance_main)
"""

__version__ = "0.3.0"

from gan_deeplearning4j_tpu.runtime import backend  # noqa: F401
