"""gan4j-lint: JAX-aware static analysis + runtime trace sanitizers.

The static half (engine.py + rules_jax.py + rules_concurrency.py) is
an AST rule engine with per-line suppressions, a baseline mechanism
and human/JSON reporters, shipped as the ``gan4j-lint`` console entry
(cli.py) and enforced as a zero-findings CI gate (tier1.yml).  The
runtime half (sanitizers.py) proves on the REAL program what the AST
can only pattern-match: zero post-warmup recompiles
(``RecompileSentinel``) and zero implicit transfers
(``no_implicit_transfers``) in the fused hot loop.

docs/STATIC_ANALYSIS.md is the operator manual: rule catalogue,
suppression/baseline semantics, sanitizer wiring.
"""

from gan_deeplearning4j_tpu.analysis.engine import (  # noqa: F401
    FileContext,
    Finding,
    LintResult,
    Rule,
    all_rules,
    lint_package,
    lint_paths,
    package_root,
    register,
)
from gan_deeplearning4j_tpu.analysis.sanitizers import (  # noqa: F401
    RECOMPILE_EVENT,
    RECOMPILE_METRIC,
    RecompileError,
    RecompileSentinel,
    TransferGuardError,
    no_implicit_transfers,
)
