"""gan4j-lint: JAX-aware static analysis + runtime trace sanitizers.

The static half (engine.py + rules_jax.py + rules_concurrency.py) is
an AST rule engine with per-line suppressions, a baseline mechanism
and human/JSON reporters, shipped as the ``gan4j-lint`` console entry
(cli.py) and enforced as a zero-findings CI gate (tier1.yml).  The
runtime half (sanitizers.py) proves on the REAL program what the AST
can only pattern-match: zero post-warmup recompiles
(``RecompileSentinel``) and zero implicit transfers
(``no_implicit_transfers``) in the fused hot loop.

The PROGRAM half (program.py + contracts.py, the ``gan4j-prove``
console entry in prove_cli.py) verifies a layer neither can see: the
lowered jaxpr/HLO itself.  Each jitted entry point — fused single
step, fused multi/scan, sharded SPMD step, pair multistep, serving
inference — is lowered on abstract inputs and checked against a
versioned JSON contract (``analysis/contracts/``): donation aliasing,
dtype discipline, collective budgets, peak-HBM ceilings and
compile-bucket coverage, enforced as a second zero-violations CI gate.

The CONCURRENCY half (locks.py + the race rules in
rules_concurrency.py, the ``gan4j-race`` console entry in race_cli.py)
sees the threads and locks: a whole-package lock acquisition-order
graph (lock-order cycles = potential deadlocks, reported with both
chains), blocking calls made under locks, and thread-construction
hygiene — plus the runtime ``lockdep`` sanitizer (sanitizers.py) that
wraps lock allocations in order-tracking proxies and reports an
observed inversion immediately with both stacks.  Third zero-findings
CI gate (tier1.yml race lane).

docs/STATIC_ANALYSIS.md is the operator manual: rule catalogue,
suppression/baseline semantics, sanitizer wiring, program contracts.
"""

from gan_deeplearning4j_tpu.analysis.engine import (  # noqa: F401
    FileContext,
    Finding,
    LintResult,
    Rule,
    all_rules,
    lint_package,
    lint_paths,
    package_root,
    register,
)
from gan_deeplearning4j_tpu.analysis.rules_concurrency import (  # noqa: F401,E501
    RACE_RULES,
)
from gan_deeplearning4j_tpu.analysis.sanitizers import (  # noqa: F401
    LOCK_INVERSION_EVENT,
    LOCK_INVERSION_METRIC,
    LOCK_WAIT_METRIC,
    RECOMPILE_EVENT,
    RECOMPILE_METRIC,
    LockdepSanitizer,
    LockOrderError,
    RecompileError,
    RecompileSentinel,
    ThreadLeakError,
    TransferGuardError,
    lockdep,
    no_implicit_transfers,
)

# gan4j-prove (program.py/contracts.py) is imported lazily by its
# consumers — pulling the entry-point registry in here would make every
# ``import gan_deeplearning4j_tpu.analysis`` pay for bench/model
# imports the lint/sanitizer half never needs.
