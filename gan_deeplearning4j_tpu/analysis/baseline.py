"""Baseline file: findings a codebase tolerates while paying down debt.

THIS repo ships with an empty baseline (the PR 6 dogfooding pass fixed
every finding instead of grandfathering them — docs/STATIC_ANALYSIS.md)
but the mechanism exists so the linter can be adopted anywhere without
a fix-everything-first flag day: ``gan4j-lint --baseline lint_baseline
.json --write-baseline`` freezes today's findings; the gate then fails
only on NEW ones, and the frozen set shrinks monotonically (a fixed
finding simply stops matching — stale entries are reported so they get
pruned).

Fingerprints are content-addressed (rule + path + stripped source line
+ occurrence index, engine.Finding.fingerprint): insertions above a
baselined finding do not un-baseline it, and FIXING the line does."""

from __future__ import annotations

import json
import os
from typing import Dict, List, Set, Tuple

from gan_deeplearning4j_tpu.analysis.engine import Finding

BASELINE_VERSION = 1


def load(path: str) -> Set[str]:
    """The fingerprint set of a baseline file; empty set when the file
    does not exist (absent baseline == empty baseline, the strict
    default)."""
    if not os.path.exists(path):
        return set()
    with open(path) as f:
        doc = json.load(f)
    if doc.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path} has version {doc.get('version')!r}, "
            f"expected {BASELINE_VERSION} — regenerate with "
            f"--write-baseline")
    return set(doc.get("fingerprints", {}))


def write(path: str, findings: List[Finding]) -> int:
    """Freeze ``findings`` as the new baseline (sorted, with enough
    context per entry that a human can audit what was grandfathered).
    Returns the number of fingerprints written."""
    seen: Dict[Tuple[str, str, str], int] = {}
    entries: Dict[str, Dict] = {}
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        key = (f.rule, f.path, f.snippet)
        idx = seen.get(key, 0)
        seen[key] = idx + 1
        entries[f.fingerprint(idx)] = {
            "rule": f.rule, "path": f.path, "line": f.line,
            "snippet": f.snippet,
        }
    doc = {"version": BASELINE_VERSION, "fingerprints": entries}
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return len(entries)
