"""``gan4j-lint`` console entry point — the zero-findings CI gate.

Exit codes (the CI contract, tier1.yml lint lane):

  0  no active findings (suppressed/baselined ones do not count)
  1  at least one active finding or parse error
  2  usage error (unknown rule, bad baseline version)

With no paths, lints the installed ``gan_deeplearning4j_tpu`` package —
``gan4j-lint`` alone IS the repo gate.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import List, Optional, Sequence

from gan_deeplearning4j_tpu.analysis import baseline as baseline_mod
from gan_deeplearning4j_tpu.analysis import reporters
from gan_deeplearning4j_tpu.analysis.engine import (
    all_rules,
    lint_paths,
    package_root,
)


def build_parser(prog: str = "gan4j-lint",
                 description: Optional[str] = None
                 ) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog=prog, description=description or __doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("paths", nargs="*",
                   help="files/directories to lint (default: the "
                        "installed gan_deeplearning4j_tpu package)")
    p.add_argument("--format", choices=("human", "json"),
                   default="human", help="report format (json is the "
                                         "CI artifact format)")
    p.add_argument("--output", default=None, metavar="FILE",
                   help="write the report there instead of stdout "
                        "(the exit code is unchanged)")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="fingerprint file of tolerated findings "
                        "(absent file = empty baseline); this repo "
                        "ships with an empty one")
    p.add_argument("--write-baseline", action="store_true",
                   help="freeze the current active findings into "
                        "--baseline and exit 0 (adoption mode)")
    p.add_argument("--changed", default=None, metavar="GIT_REF",
                   help="lint only the .py files changed vs this git "
                        "ref (tracked diffs + untracked files), "
                        "restricted to the given paths — the fast "
                        "pre-commit mode; zero changed files is a "
                        "clean pass")
    p.add_argument("--warn-unused-suppressions", action="store_true",
                   help="also flag disable= directives whose rule no "
                        "longer fires on their line (stale-suppression "
                        "audit; findings gate like any other)")
    p.add_argument("--rules", default=None, metavar="LIST",
                   help="comma-separated rule names to run "
                        "(default: all)")
    p.add_argument("--disable", default="", metavar="LIST",
                   help="comma-separated rule names to skip")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalogue and exit")
    p.add_argument("--verbose", action="store_true",
                   help="human format: also list suppressed/baselined "
                        "findings")
    return p


def changed_py_files(ref: str, scope_paths: List[str]) -> List[str]:
    """The ``.py`` files changed vs ``ref`` (tracked diff + untracked),
    restricted to ``scope_paths``.  Raises ValueError when git cannot
    answer (not a repo, unknown ref) — a usage error upstream."""
    anchor = scope_paths[0]
    anchor_dir = (anchor if os.path.isdir(anchor)
                  else os.path.dirname(os.path.abspath(anchor)) or ".")

    def git(*cmd):
        return subprocess.run(["git", "-C", anchor_dir, *cmd],
                              capture_output=True, text=True)

    top = git("rev-parse", "--show-toplevel")
    if top.returncode != 0:
        raise ValueError(f"--changed: {anchor_dir} is not inside a git "
                         f"repository")
    root = top.stdout.strip()
    diff = git("diff", "--name-only", ref, "--")
    if diff.returncode != 0:
        raise ValueError(f"--changed: git diff vs {ref!r} failed: "
                         f"{diff.stderr.strip()}")
    # ls-files prints paths relative to (and only under) its cwd —
    # run it from the repo ROOT so they join like the diff's
    # root-relative names even when the scope anchor is a subdirectory
    untracked = subprocess.run(
        ["git", "-C", root, "ls-files", "--others",
         "--exclude-standard"], capture_output=True, text=True)
    names = set(diff.stdout.splitlines())
    if untracked.returncode == 0:
        names |= set(untracked.stdout.splitlines())
    scope = [os.path.abspath(p) for p in scope_paths]
    out = []
    for name in sorted(names):
        if not name.endswith(".py"):
            continue
        path = os.path.abspath(os.path.join(root, name))
        if not os.path.exists(path):
            continue  # deleted vs ref: nothing to lint
        if any(path == s or path.startswith(s + os.sep) for s in scope):
            out.append(path)
    return out


def main(argv: Optional[list] = None, *,
         rule_subset: Optional[Sequence[str]] = None,
         prog: str = "gan4j-lint",
         description: Optional[str] = None,
         allow_changed: bool = True) -> int:
    """``rule_subset`` restricts the selectable rules (the
    ``gan4j-race`` CLI passes its concurrency set); everything else —
    baseline, suppressions, reporters, exit codes — is shared verbatim
    between the two gates.  ``allow_changed=False`` rejects
    ``--changed``: a tool whose rules reason over the whole-package
    graph must not answer from a file subset (a cycle's other half may
    live in an unchanged module — exit 2, not a false clean pass)."""
    parser = build_parser(prog=prog, description=description)
    args = parser.parse_args(argv)
    if args.changed is not None and not allow_changed:
        print(f"{prog}: error: --changed is not supported: the "
              f"lock-order graph is a whole-package property (a cycle "
              f"closed by your edit may have its other half in an "
              f"unchanged module) — run {prog} with no paths instead; "
              f"the full run costs well under a second",
              file=sys.stderr)
        return 2
    registry = all_rules()
    # gan4j-lint's own set is the FILE-scope rules: the package-scope
    # concurrency rules (lock-order-cycle et al.) belong to gan4j-race,
    # whose whole-package default invocation is the only shape their
    # graph analysis is meaningful in (`--changed` over a file subset
    # would see a partial graph).  lint_package() — the bench/test repo
    # gate — still runs everything.
    selectable = (sorted(rule_subset) if rule_subset is not None
                  else sorted(r for r in registry
                              if registry[r].scope == "file"))

    if args.list_rules:
        for name in selectable:
            print(f"{name}: {registry[name].summary}")
        return 0
    if args.write_baseline and not args.baseline:
        parser.error("--write-baseline requires --baseline FILE")
    if args.write_baseline and args.changed:
        parser.error("--write-baseline over a --changed subset would "
                     "freeze a partial baseline")

    paths = args.paths or [package_root()]
    # a gate that lints nothing must not answer green: a typo'd path
    # (or a moved package dir) is a usage error, not a pass
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"{prog}: error: no such path(s): "
              f"{', '.join(missing)}", file=sys.stderr)
        return 2
    if args.changed is not None:
        try:
            paths = changed_py_files(args.changed, paths)
        except ValueError as e:
            print(f"{prog}: error: {e}", file=sys.stderr)
            return 2
        if not paths:
            # unlike a typo'd path, an empty diff is a REAL verdict:
            # nothing in scope changed, so there is nothing to gate
            print(f"{prog}: no changed .py files vs "
                  f"{args.changed} — clean")
            return 0
    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else list(selectable))
    disable = [r.strip() for r in args.disable.split(",") if r.strip()]
    # --disable gets the same jurisdiction check as --rules: silently
    # no-op'ing a rule name from the OTHER tool would read as "narrowed
    # the run" while changing nothing
    outside = [r for r in rules + disable if r not in selectable]
    if outside:
        print(f"{prog}: error: rule(s) outside this tool's set: "
              f"{', '.join(outside)}; selectable: "
              f"{', '.join(selectable)}", file=sys.stderr)
        return 2

    try:
        fingerprints = (baseline_mod.load(args.baseline)
                        if args.baseline and not args.write_baseline
                        else set())
        result = lint_paths(
            paths, rules=rules, disable=disable,
            baseline_fingerprints=fingerprints,
            audit_suppressions=args.warn_unused_suppressions,
            # this tool's own catalogue is the universe a run must
            # cover to call a disable=all stale — the default run of
            # EITHER gate keeps auditing "all" within its jurisdiction
            audit_universe=set(selectable))
    except ValueError as e:
        print(f"{prog}: error: {e}", file=sys.stderr)
        return 2
    if result.files_checked == 0:
        print(f"{prog}: error: no .py files under the given "
              "path(s) — refusing to report a vacuous pass",
              file=sys.stderr)
        return 2

    if args.write_baseline:
        n = baseline_mod.write(args.baseline, result.findings)
        print(f"{prog}: baseline written: {n} fingerprint(s) -> "
              f"{args.baseline}")
        return 0

    report = (reporters.render_json(result, tool=prog)
              if args.format == "json"
              else reporters.render_human(result, verbose=args.verbose,
                                          tool=prog))
    if args.output:
        with open(args.output, "w") as f:
            f.write(report)
        # a one-line verdict still lands in the log next to the gate
        print(f"{prog}: {len(result.findings)} finding(s) "
              f"({'ok' if result.ok else 'FAIL'}) -> {args.output}")
    else:
        sys.stdout.write(report)
    return 0 if result.ok else 1


def cli(argv: Optional[list] = None) -> None:
    sys.exit(main(argv))


if __name__ == "__main__":
    cli()
