"""gan4j-prove contracts: versioned, human-diffable JSON invariants per
jitted entry point, checked against the ACTUAL lowering (program.py).

One file per entry point under ``analysis/contracts/<entry>.json``:

```json
{
 "version": 1,
 "entry_point": "fused_single",
 "donation": {"declared_leaves": 129, "aliased_leaves": 107,
              "exemption": null},
 "dtypes": {"allowed": ["f32", "i1", "i32", "i64", "ui32"]},
 "collectives": {"all-reduce": 0},
 "peak_hbm": {"bytes_ceiling": 220200960, "measured": 146566916,
              "source": "memory_analysis"},
 "buckets": {"mode": "exact", "declared": [8, 50, 200, 1600]}
}
```

Five contract classes, each a distinct silent-failure mode:

* ``donation`` — the compiled ``input_output_alias`` must carry exactly
  the contracted number of aliased parameters.  A donation dropped by
  jit or XLA doubles the state's HBM footprint without changing a
  single loss value.  The scan-path exemption (donation + scan crashes
  the axon TPU runtime) is an explicit ``exemption`` entry — the
  contract then asserts aliasing is ABSENT, proving the builder really
  dropped the flag, instead of a comment hoping it did.
* ``dtype`` — every element type in the stablehlo must be in the
  allowed set; f64 (or any unintended widening) fails before it ships.
* ``collectives`` — static per-step collective-op counts must match
  exactly; an accidental extra all-reduce per step can never land
  silently.
* ``peak-hbm`` — the compile's memory analysis must stay under the
  contracted byte ceiling (written with 1.5x headroom for compiler
  drift; a real regression blows well past it).
* ``buckets`` — every batch shape reachable from the bench/serving
  configs must map to a declared compile bucket ("exact" membership for
  training shapes, "round-up" coverage for serving requests), making
  recompile-per-request-shape statically impossible.

Adoption follows gan4j-lint's baseline semantics: ``gan4j-prove
--write-contracts`` freezes today's facts; the gate then fails only on
drift, and every intentional change is a reviewable contract diff.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence

from gan_deeplearning4j_tpu.analysis import program as program_mod
from gan_deeplearning4j_tpu.analysis.program import EntryPoint, ProgramFacts

CONTRACT_VERSION = 1
# headroom multiplier applied at --write-contracts time: absorbs
# XLA-version scratch-size drift without masking a real 2x regression
HBM_CEILING_HEADROOM = 1.5

CONTRACT_CLASSES = ("donation", "dtype", "collectives", "peak-hbm",
                    "buckets")


@dataclasses.dataclass
class Violation:
    """One broken contract at one entry point.  ``contract_class`` is
    the failing check family; ``field`` names the exact contract field
    so the report points at the line to re-review, not just the file."""

    entry: str
    contract_class: str
    field: str
    message: str

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


def contracts_dir() -> str:
    """The committed contract files' home: ``analysis/contracts/``
    inside the installed package (shipped as package data)."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "contracts")


def contract_path(directory: str, entry: str) -> str:
    return os.path.join(directory, f"{entry}.json")


def load_contract(directory: str, entry: str) -> Optional[Dict]:
    """The contract document for ``entry``, or None when the file does
    not exist (reported as a violation by ``check_entry`` — an
    uncontracted entry point is a hole in the gate, not a pass)."""
    path = contract_path(directory, entry)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        doc = json.load(f)
    if doc.get("version") != CONTRACT_VERSION:
        raise ValueError(
            f"contract {path} has version {doc.get('version')!r}, "
            f"expected {CONTRACT_VERSION} — regenerate with "
            f"--write-contracts")
    return doc


def build_contract(entry: EntryPoint, facts: List[ProgramFacts]) -> Dict:
    """Compose the contract document from measured facts (the
    --write-contracts adoption path)."""
    dtypes = sorted({d for f in facts for d in f.dtypes})
    collectives: Dict[str, int] = {}
    for f in facts:
        for k, v in f.collectives.items():
            collectives[k] = max(collectives.get(k, 0), v)
    peak = max(f.peak_bytes for f in facts)
    doc: Dict = {
        "version": CONTRACT_VERSION,
        "entry_point": entry.name,
        "summary": entry.summary,
        "mesh_shape": facts[0].mesh_shape,
        "variants": [f.variant for f in facts],
        "donation": {
            "declared_leaves": facts[0].declared_donated_leaves,
            "aliased_leaves": len(facts[0].aliased_params),
            "exemption": entry.exemption,
        },
        "dtypes": {"allowed": dtypes},
        "collectives": collectives,
        "peak_hbm": {
            "bytes_ceiling": int(peak * HBM_CEILING_HEADROOM),
            "measured": int(peak),
            "source": facts[0].memory_source,
        },
    }
    if entry.bucket_spec is not None:
        spec = entry.bucket_spec()
        doc["buckets"] = {
            "mode": spec["mode"],
            "declared": list(spec["code_declared"]),
        }
        if "max_request" in spec:
            doc["buckets"]["max_request"] = spec["max_request"]
    return doc


def write_contract(directory: str, entry: EntryPoint,
                   facts: List[ProgramFacts]) -> str:
    os.makedirs(directory, exist_ok=True)
    path = contract_path(directory, entry.name)
    with open(path, "w") as f:
        json.dump(build_contract(entry, facts), f, indent=1,
                  sort_keys=True)
        f.write("\n")
    return path


# -- the five checks ----------------------------------------------------------


def _check_donation(entry: str, contract: Dict,
                    facts: List[ProgramFacts]) -> List[Violation]:
    out: List[Violation] = []
    spec = contract.get("donation", {})
    f = facts[0]
    exemption = spec.get("exemption")
    if exemption:
        # the exemption asserts donation is genuinely OFF in the
        # artifact — if aliasing appears, the builder stopped dropping
        # the flag and the contract (not a comment) must be updated
        if f.aliased_params:
            out.append(Violation(
                entry, "donation", "donation.exemption",
                f"{entry}: contract exempts donation "
                f"({exemption.get('id')}) but the compiled program "
                f"aliases {len(f.aliased_params)} parameter(s) — the "
                f"builder no longer drops the flag; update the "
                f"contract entry if this is intentional"))
        return out
    declared = spec.get("declared_leaves", 0)
    expected = spec.get("aliased_leaves", 0)
    if f.declared_donated_leaves != declared:
        out.append(Violation(
            entry, "donation", "donation.declared_leaves",
            f"{entry}: contract declares {declared} donated leaves, "
            f"entry point donates {f.declared_donated_leaves} — the "
            f"donated-state pytree changed; re-run --write-contracts "
            f"and review the diff"))
    if len(f.aliased_params) != expected:
        out.append(Violation(
            entry, "donation", "donation.aliased_leaves",
            f"{entry}: contract expects {expected} input->output "
            f"aliases in the compiled program, found "
            f"{len(f.aliased_params)} — a dropped donation doubles "
            f"the state's HBM footprint"))
    return out


def _check_dtypes(entry: str, contract: Dict,
                  facts: List[ProgramFacts]) -> List[Violation]:
    allowed = set(contract.get("dtypes", {}).get("allowed", []))
    seen = {d for f in facts for d in f.dtypes}
    extra = sorted(seen - allowed)
    if extra:
        return [Violation(
            entry, "dtype", "dtypes.allowed",
            f"{entry}: stablehlo contains dtype(s) outside the "
            f"contract: {', '.join(extra)} (allowed: "
            f"{', '.join(sorted(allowed))}) — an unintended widening "
            f"multiplies HBM traffic and disables the MXU fast path")]
    return []


def _check_collectives(entry: str, contract: Dict,
                       facts: List[ProgramFacts]) -> List[Violation]:
    out: List[Violation] = []
    expected: Dict[str, int] = dict(contract.get("collectives", {}))
    seen: Dict[str, int] = {}
    for f in facts:
        for k, v in f.collectives.items():
            seen[k] = max(seen.get(k, 0), v)
    for op in sorted(set(expected) | set(seen)):
        if seen.get(op, 0) != expected.get(op, 0):
            out.append(Violation(
                entry, "collectives", f"collectives.{op}",
                f"{entry}: contract budgets {expected.get(op, 0)} "
                f"{op} op(s) per step, lowering contains "
                f"{seen.get(op, 0)} — an unbudgeted sync per step is "
                f"invisible in losses and fatal to step time"))
    return out


def _check_peak_hbm(entry: str, contract: Dict,
                    facts: List[ProgramFacts]) -> List[Violation]:
    ceiling = contract.get("peak_hbm", {}).get("bytes_ceiling")
    if ceiling is None:
        return [Violation(entry, "peak-hbm", "peak_hbm.bytes_ceiling",
                          f"{entry}: contract has no byte ceiling")]
    worst = max(facts, key=lambda f: f.peak_bytes)
    if worst.peak_bytes > ceiling:
        return [Violation(
            entry, "peak-hbm", "peak_hbm.bytes_ceiling",
            f"{entry}: peak program memory {worst.peak_bytes} B "
            f"(variant {worst.variant}, {worst.memory_source}) exceeds "
            f"the contract ceiling {ceiling} B")]
    return []


def _check_buckets(entry: str, contract: Dict,
                   facts: List[ProgramFacts],
                   spec: Optional[Dict]) -> List[Violation]:
    block = contract.get("buckets")
    if block is None and spec is None:
        return []
    if block is None or spec is None:
        side = "contract" if block is None else "entry point"
        return [Violation(
            entry, "buckets", "buckets",
            f"{entry}: bucket contract and code disagree on whether "
            f"the entry has one (missing on the {side} side)")]
    out: List[Violation] = []
    declared = sorted(block.get("declared", []))
    code_declared = sorted(spec.get("code_declared", []))
    if declared != code_declared:
        out.append(Violation(
            entry, "buckets", "buckets.declared",
            f"{entry}: contract declares buckets {declared}, code "
            f"declares {code_declared} — every bucket change must be "
            f"a contract diff"))
    if block.get("mode") == "round-up":
        max_request = block.get("max_request", 0)
        top = declared[-1] if declared else 0
        if max_request > top:
            out.append(Violation(
                entry, "buckets", "buckets.max_request",
                f"{entry}: max_request {max_request} exceeds the "
                f"largest declared bucket {top} — requests above it "
                f"have no compile bucket to round up into"))
        # lowered variants must cover the declared set exactly: the
        # bucket list IS the complete set of dispatchable shapes
        lowered = sorted(f.batch for f in facts)
        if lowered != declared:
            out.append(Violation(
                entry, "buckets", "buckets.declared",
                f"{entry}: lowered variants cover shapes {lowered} "
                f"but the contract declares {declared}"))
    else:
        reachable = sorted(spec.get("reachable", []))
        missing = [b for b in reachable if b not in declared]
        if missing:
            out.append(Violation(
                entry, "buckets", "buckets.declared",
                f"{entry}: reachable batch shape(s) "
                f"{missing} map to no declared compile bucket "
                f"(declared: {declared}) — each would recompile at "
                f"first dispatch"))
    return out


def check_entry(entry: EntryPoint, contract: Optional[Dict],
                facts: List[ProgramFacts]) -> List[Violation]:
    """All five contract classes for one entry point.  A missing
    contract is itself a violation — an entry point the gate cannot
    see is a hole, not a pass."""
    if contract is None:
        return [Violation(
            entry.name, "contract", "contract",
            f"{entry.name}: no contract file — adopt it with "
            f"gan4j-prove --write-contracts")]
    out: List[Violation] = []
    if contract.get("entry_point") != entry.name:
        out.append(Violation(
            entry.name, "contract", "entry_point",
            f"{entry.name}: contract file names entry point "
            f"{contract.get('entry_point')!r}"))
    out.extend(_check_donation(entry.name, contract, facts))
    out.extend(_check_dtypes(entry.name, contract, facts))
    out.extend(_check_collectives(entry.name, contract, facts))
    out.extend(_check_peak_hbm(entry.name, contract, facts))
    spec = entry.bucket_spec() if entry.bucket_spec else None
    out.extend(_check_buckets(entry.name, contract, facts, spec))
    return out


# -- repo-level verify / adopt ------------------------------------------------


def verify_repo(names: Optional[Sequence[str]] = None,
                directory: Optional[str] = None,
                write: bool = False) -> Dict:
    """Lower every resolvable entry point and check (or, with
    ``write``, freeze) its contract.  Returns the report document the
    reporters render; ``summary.ok`` is the gate verdict."""
    directory = directory or contracts_dir()
    entries, skipped = program_mod.resolve(names)
    report: Dict = {
        "tool": "gan4j-prove",
        "contracts_dir": directory,
        "entries": {},
        "skipped": [{"entry": n, "reason": r} for n, r in skipped],
    }
    violations: List[Violation] = []
    for entry in entries:
        facts = program_mod.build_facts(entry)
        if write:
            path = write_contract(directory, entry, facts)
            entry_violations: List[Violation] = []
            report["entries"][entry.name] = {
                "facts": [f.to_dict() for f in facts],
                "written": path,
                "violations": [],
            }
        else:
            try:
                contract = load_contract(directory, entry.name)
            except ValueError as e:
                entry_violations = [Violation(
                    entry.name, "contract", "version", str(e))]
            else:
                entry_violations = check_entry(entry, contract, facts)
            report["entries"][entry.name] = {
                "facts": [f.to_dict() for f in facts],
                "violations": [v.to_dict() for v in entry_violations],
            }
        violations.extend(entry_violations)
    report["summary"] = {
        "entry_points": len(entries),
        "skipped": len(skipped),
        "violations": len(violations),
        "written": bool(write),
        "ok": not violations and bool(entries),
    }
    return report


# -- selftest: prove the gate CAN fail ----------------------------------------


def _selftest_donation() -> List[Violation]:
    """A wrapper that drops donate_argnums must turn the gate red."""
    entry = program_mod.all_entry_points()["fused_single"]
    contract = load_contract(contracts_dir(), entry.name)
    if contract is None:
        contract = build_contract(entry, program_mod.build_facts(entry))
    facts = program_mod.build_facts(entry, donate=False)
    # the build declared nothing donated, so pin the declared count to
    # the contract's: the injected failure is the MISSING aliasing
    facts[0].declared_donated_leaves = (
        contract["donation"]["declared_leaves"])
    return [v for v in check_entry(entry, contract, facts)
            if v.contract_class == "donation"]


def _tiny_entry(name: str, build) -> EntryPoint:
    return EntryPoint(name=name, summary="selftest scaffold",
                      build=build)


def _selftest_dtype() -> List[Violation]:
    """An op forced to f64 must escape the allowed-dtype set."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import enable_x64

    def build(donate: bool = False) -> List[program_mod.Built]:
        del donate
        jitted = jax.jit(lambda x: x * 2.0)
        args = (jax.ShapeDtypeStruct((4,), jnp.float64),)
        return [program_mod.Built("b4", jitted, args, 0, 4)]

    entry = _tiny_entry("selftest_dtype", build)
    with enable_x64():
        facts = program_mod.build_facts(entry)
    contract = build_contract(entry, facts)
    contract["dtypes"]["allowed"] = ["f32"]
    return [v for v in check_entry(entry, contract, facts)
            if v.contract_class == "dtype"]


def _selftest_collectives() -> List[Violation]:
    """An extra all-reduce over the budget must fail the count."""
    import jax
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from gan_deeplearning4j_tpu.compat.jaxver import shard_map

    mesh = Mesh(np.asarray(jax.devices()[:2]), ("data",))

    def two_syncs(x):
        return jax.lax.psum(jax.lax.psum(x, "data"), "data")

    def build(donate: bool = False) -> List[program_mod.Built]:
        del donate
        jitted = jax.jit(shard_map(two_syncs, mesh=mesh,
                                   in_specs=P("data"), out_specs=P(),
                                   check_vma=False))
        args = (jax.ShapeDtypeStruct((8,), np.float32),)
        return [program_mod.Built("b8", jitted, args, 0, 8,
                                  mesh_shape={"data": 2})]

    entry = _tiny_entry("selftest_collectives", build)
    facts = program_mod.build_facts(entry)
    contract = build_contract(entry, facts)
    contract["collectives"]["all-reduce"] = 1  # program has 2
    return [v for v in check_entry(entry, contract, facts)
            if v.contract_class == "collectives"]


def _selftest_peak_hbm() -> List[Violation]:
    """A fat temp over a tiny ceiling must blow the budget."""
    import jax
    import jax.numpy as jnp

    def build(donate: bool = False) -> List[program_mod.Built]:
        del donate
        jitted = jax.jit(
            lambda x: (jnp.broadcast_to(x, (512, 1024, 32)) * 2.0).sum())
        args = (jax.ShapeDtypeStruct((32,), jnp.float32),)
        return [program_mod.Built("b32", jitted, args, 0, 32)]

    entry = _tiny_entry("selftest_hbm", build)
    facts = program_mod.build_facts(entry)
    contract = build_contract(entry, facts)
    contract["peak_hbm"]["bytes_ceiling"] = 1 << 20  # 1 MiB vs ~64 MiB
    return [v for v in check_entry(entry, contract, facts)
            if v.contract_class == "peak-hbm"]


def _selftest_buckets() -> List[Violation]:
    """An undeclared reachable batch shape must fail coverage."""
    import jax
    import jax.numpy as jnp

    def build(donate: bool = False) -> List[program_mod.Built]:
        del donate
        jitted = jax.jit(lambda x: x + 1.0)
        args = (jax.ShapeDtypeStruct((8, 4), jnp.float32),)
        return [program_mod.Built("b8", jitted, args, 0, 8)]

    spec = {"mode": "exact", "code_declared": [8, 16],
            "reachable": [8, 24]}  # 24 has no bucket
    entry = EntryPoint(name="selftest_buckets",
                       summary="selftest scaffold", build=build,
                       bucket_spec=lambda: spec)
    facts = program_mod.build_facts(entry)
    contract = build_contract(entry, facts)
    return [v for v in check_entry(entry, contract, facts)
            if v.contract_class == "buckets"]


def selftest() -> Dict:
    """One injected violation per contract class, each through the SAME
    build->lower->check machinery as the real gate: a class whose
    injection does not fire means the gate cannot go red there —
    decoration, not verification.  ``ok`` iff all five fired."""
    injectors = {
        "donation": _selftest_donation,
        "dtype": _selftest_dtype,
        "collectives": _selftest_collectives,
        "peak-hbm": _selftest_peak_hbm,
        "buckets": _selftest_buckets,
    }
    results: Dict = {"tool": "gan4j-prove-selftest", "classes": {}}
    ok = True
    for cls, fn in injectors.items():
        violations = fn()
        fired = any(v.contract_class == cls for v in violations)
        ok = ok and fired
        results["classes"][cls] = {
            "fired": fired,
            "violations": [v.to_dict() for v in violations],
        }
    results["ok"] = ok
    return results
