"""gan4j-lint core: file walking, AST parsing, suppressions, registry.

Generic linters cannot see the hazards this codebase actually dies of:
a PRNG key consumed twice (silently correlated noise — the
rollback-with-perturbation replay depends on ``fold_in`` discipline), a
closure mutated under ``jit`` (runs once at trace time, then never
again), a host sync inside the fused hot loop (the MFU headline dies on
one silent ``float()``), a jit-wrap inside a loop (a recompile per
iteration), an unlocked shared-attribute write in the thread-heavy ops
layer, or a swallowed exception (the PR 4 restart-marker bug class).
Each is a RULE here (rules_jax.py / rules_concurrency.py); this module
is the engine that runs them.

Vocabulary shared by every rule:

* **suppression** — ``# gan4j-lint: disable=<rule>[,<rule>] <reason>``
  on the finding's line or the line directly above silences exactly
  those rules there (``disable=all`` silences everything).  The policy
  (docs/STATIC_ANALYSIS.md): a suppression without a reason is a review
  rejection — the comment IS the justification record.
* **hot-path marker** — ``# gan4j-lint: hot-path`` on or above a
  ``def`` opts the whole function into host-sync-in-hot-path's loop
  scrutiny even when the engine's step-call heuristic would not
  recognize its loops as hot.
* **baseline** — a fingerprint file (baseline.py) of findings to
  tolerate; this repo ships with an EMPTY one (the dogfooding pass
  fixed everything), the knob exists for adopting the linter on a
  codebase that cannot fix all debt at once.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# gan4j-lint and gan4j-race share one directive namespace: NAMED rule
# tokens are globally unique, so they are unambiguous under either
# prefix.  ``disable=all`` is NOT — it is scoped to the prefix's own
# jurisdiction (gan4j-race's "all" = the race rules, gan4j-lint's =
# the file-scope rules), or a race-justified "all" would silently
# bypass the lint gate on the same line.
SUPPRESS_RE = re.compile(
    r"#\s*gan4j-(lint|race):\s*disable=([A-Za-z0-9_,\-]+)")
HOT_PATH_RE = re.compile(r"#\s*gan4j-lint:\s*hot-path")


def _all_jurisdiction(prefix: str) -> Set[str]:
    """The rules a ``disable=all`` under this prefix may silence."""
    from gan_deeplearning4j_tpu.analysis.rules_concurrency import (
        RACE_RULES,
    )

    registry = all_rules()
    if prefix == "race":
        return set(RACE_RULES)
    return {name for name, cls in registry.items()
            if cls.scope == "file"}


@dataclasses.dataclass
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str            # as given to the engine (report-facing)
    line: int            # 1-based
    message: str
    snippet: str = ""    # stripped source line, for reports + baseline

    def fingerprint(self, index: int = 0) -> str:
        """Content-addressed identity for the baseline: rule + path +
        the STRIPPED offending line (+ an occurrence index so two
        identical lines in one file stay distinct) — line numbers are
        deliberately excluded, so unrelated edits above a baselined
        finding do not un-baseline it."""
        basis = f"{self.rule}\x00{self.path}\x00{self.snippet}\x00{index}"
        return hashlib.sha256(basis.encode()).hexdigest()[:16]

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


class FileContext:
    """Parsed view of one source file handed to every rule: the AST,
    raw lines, per-line suppressions and hot-path markers."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # line (1-based) -> set of suppressed rule names (or {"all"})
        self.suppressions: Dict[int, Set[str]] = {}
        # line -> the tool prefixes that wrote a disable=all there (an
        # "all" only silences rules in its own prefix's jurisdiction)
        self.all_prefixes: Dict[int, Set[str]] = {}
        self.hot_lines: Set[int] = set()
        # directives count only inside REAL comment tokens: a docstring
        # that merely documents the syntax must neither suppress a
        # finding on the next line nor trip the unused-suppression audit
        for lineno, text in self._comment_lines(source):
            m = SUPPRESS_RE.search(text)
            if m:
                tokens = {r.strip() for r in m.group(2).split(",")
                          if r.strip()}
                self.suppressions[lineno] = tokens
                if "all" in tokens:
                    self.all_prefixes.setdefault(lineno, set()).add(
                        m.group(1))
            if HOT_PATH_RE.search(text):
                self.hot_lines.add(lineno)

    def _comment_lines(self, source: str):
        import io

        try:
            return [(tok.start[0], tok.string)
                    for tok in tokenize.generate_tokens(
                        io.StringIO(source).readline)
                    if tok.type == tokenize.COMMENT]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            # the AST parsed, so this is near-unreachable; raw lines
            # keep the directive mechanism alive regardless
            return list(enumerate(self.lines, start=1))

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def suppressed(self, lineno: int, rule: str) -> bool:
        """A finding at ``lineno`` is suppressed by a directive on the
        SAME line or the line DIRECTLY above (the convention that
        survives black-style reflowing of long lines)."""
        return self.suppression_site(lineno, rule) is not None

    def suppression_site(self, lineno: int, rule: str
                         ) -> Optional[Tuple[int, str]]:
        """The ``(directive_line, matched_token)`` that silences
        ``rule`` at ``lineno`` — the exact-rule token when present,
        ``"all"`` otherwise; None when nothing matches.  The engine's
        unused-suppression audit keys on these sites."""
        for cand in (lineno, lineno - 1):
            rules = self.suppressions.get(cand)
            if not rules:
                continue
            if rule in rules:
                return (cand, rule)
            if "all" in rules and any(
                    rule in _all_jurisdiction(prefix)
                    for prefix in self.all_prefixes.get(cand, ())):
                return (cand, "all")
        return None

    def is_hot_marked(self, node: ast.AST) -> bool:
        """``# gan4j-lint: hot-path`` on the def line, the line above
        it, or the line above its first decorator."""
        lineno = getattr(node, "lineno", None)
        if lineno is None:
            return False
        candidates = {lineno, lineno - 1}
        for dec in getattr(node, "decorator_list", []):
            candidates.add(dec.lineno - 1)
        return bool(candidates & self.hot_lines)

    def finding(self, rule: str, node_or_line, message: str) -> Finding:
        lineno = (node_or_line if isinstance(node_or_line, int)
                  else node_or_line.lineno)
        return Finding(rule=rule, path=self.path, line=lineno,
                       message=message, snippet=self.line_text(lineno))


# -- rule registry ------------------------------------------------------------


class Rule:
    """A named check over one FileContext.  Subclasses set ``name`` and
    ``summary`` and implement ``check``; ``@register`` adds them to the
    engine's default set.

    ``scope = "package"`` rules see the WHOLE lint set at once: they
    implement ``check_package`` over every parsed FileContext instead
    of ``check`` — the shape a lock-order cycle needs (one acquisition
    chain per module, the deadlock only visible across them)."""

    name: str = ""
    summary: str = ""
    scope: str = "file"          # or "package"

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        raise NotImplementedError

    def check_package(self, ctxs: Dict[str, FileContext]
                      ) -> Iterable[Finding]:
        raise NotImplementedError


_REGISTRY: Dict[str, type] = {}


def register(cls):
    assert cls.name and cls.name not in _REGISTRY, cls
    _REGISTRY[cls.name] = cls
    return cls


def all_rules() -> Dict[str, type]:
    """name -> Rule class, importing the rule modules on first use (the
    registry is populated by their ``@register`` decorators)."""
    from gan_deeplearning4j_tpu.analysis import (  # noqa: F401
        rules_concurrency,
        rules_jax,
    )

    return dict(_REGISTRY)


# -- shared AST helpers (used by both rule modules) ---------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """``jax.random.uniform`` for the matching Attribute/Name chain,
    None for anything dynamic (subscripts, calls)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def last_segment(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def walk_skipping_defs(node: ast.AST) -> Iterable[ast.AST]:
    """Yield descendants of ``node`` WITHOUT entering nested function/
    class definitions (their scopes have their own rule context)."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef, ast.Lambda)):
            continue
        yield child
        yield from walk_skipping_defs(child)


def function_defs(tree: ast.Module):
    """Every (Async)FunctionDef in the module, at any nesting depth."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def bound_names(fn) -> Set[str]:
    """Names the function binds locally: params, assignment targets,
    for/with/except targets, comprehension targets, imports and nested
    def/class names — the complement is its free (closed-over) names."""
    names: Set[str] = set()
    a = fn.args
    for arg in (list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
                + ([a.vararg] if a.vararg else [])
                + ([a.kwarg] if a.kwarg else [])):
        names.add(arg.arg)

    def targets(t):
        if isinstance(t, ast.Name):
            names.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                targets(e)
        elif isinstance(t, ast.Starred):
            targets(t.value)

    body = fn.body if not isinstance(fn, ast.Lambda) else []
    for node in body:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign):
                for t in sub.targets:
                    targets(t)
            elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                targets(sub.target)
            elif isinstance(sub, (ast.For, ast.AsyncFor)):
                targets(sub.target)
            elif isinstance(sub, (ast.With, ast.AsyncWith)):
                for item in sub.items:
                    if item.optional_vars is not None:
                        targets(item.optional_vars)
            elif isinstance(sub, ast.ExceptHandler) and sub.name:
                names.add(sub.name)
            elif isinstance(sub, (ast.Import, ast.ImportFrom)):
                for alias in sub.names:
                    names.add((alias.asname or alias.name).split(".")[0])
            elif isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                names.add(sub.name)
            elif isinstance(sub, ast.comprehension):
                targets(sub.target)
            elif isinstance(sub, (ast.Global, ast.Nonlocal)):
                # declared, but NOT local — handled by the caller
                pass
    return names


# -- the engine ---------------------------------------------------------------


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]          # active (not suppressed/baselined)
    suppressed: List[Finding]        # silenced by an inline directive
    baselined: List[Finding]         # silenced by the baseline file
    errors: List[Finding]            # unparseable files (rule parse-error)
    files_checked: int = 0

    @property
    def ok(self) -> bool:
        return not self.findings and not self.errors


def iter_python_files(paths: Sequence[str]) -> Iterable[str]:
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs
                             if d != "__pycache__"
                             and not d.startswith("."))
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def lint_paths(paths: Sequence[str],
               rules: Optional[Sequence[str]] = None,
               disable: Sequence[str] = (),
               baseline_fingerprints: Optional[Set[str]] = None,
               audit_suppressions: bool = False,
               audit_universe: Optional[Set[str]] = None,
               ) -> LintResult:
    """Run the (selected) rules over every ``.py`` under ``paths``.

    ``rules``: restrict to these names (default: all registered);
    ``disable``: drop these from whatever was selected;
    ``baseline_fingerprints``: findings whose fingerprint is in here are
    reported as ``baselined`` instead of active;
    ``audit_suppressions``: additionally flag every ``disable=``
    directive whose rule no longer fires on its line (the
    stale-suppression rot killer) as an ``unused-suppression`` finding.
    Directives naming rules that exist but were not selected this run
    are left alone — only a full-rule-set run can call them stale.
    ``audit_universe``: the rule set a run must cover to have standing
    to call ``disable=all`` stale (default: every registered rule).
    The CLIs pass their own catalogue — gan4j-lint's file-scope set,
    gan4j-race's concurrency set — so each tool's default run keeps
    auditing ``all`` within its jurisdiction; a ``disable=all`` that
    guards the OTHER tool's finding should be narrowed to the exact
    rule token (the audit message says so)."""
    registry = all_rules()
    selected = list(rules) if rules else sorted(registry)
    unknown = [r for r in list(selected) + list(disable)
               if r not in registry]
    if unknown:
        raise ValueError(f"unknown rule(s): {', '.join(unknown)}; "
                         f"known: {', '.join(sorted(registry))}")
    instances = [registry[r]() for r in selected if r not in set(disable)]
    active = {r.name for r in instances}
    baseline_fingerprints = baseline_fingerprints or set()
    if audit_universe is None:
        audit_universe = set(registry)

    file_rules = [r for r in instances if r.scope == "file"]
    package_rules = [r for r in instances if r.scope == "package"]

    result = LintResult([], [], [], [])
    ctx_by_path: Dict[str, FileContext] = {}
    findings_by_path: Dict[str, List[Finding]] = {}
    for path in iter_python_files(paths):
        result.files_checked += 1
        try:
            with tokenize.open(path) as f:   # honors coding declarations
                source = f.read()
            ctx = FileContext(path, source)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            lineno = getattr(e, "lineno", None) or 1
            result.errors.append(Finding(
                rule="parse-error", path=path, line=int(lineno),
                message=f"could not parse: {e.__class__.__name__}: {e}"))
            continue
        ctx_by_path[path] = ctx
        findings_by_path[path] = []
        for rule in file_rules:
            findings_by_path[path].extend(rule.check(ctx))
    # package-scope rules run once over every parsed file: a lock-order
    # cycle's two halves usually live in two modules
    for rule in package_rules:
        for f in rule.check_package(ctx_by_path):
            if f.path in findings_by_path:
                findings_by_path[f.path].append(f)
            else:  # defensive: a finding pointing outside the lint set
                result.findings.append(f)
    for path, ctx in ctx_by_path.items():
        file_findings = findings_by_path[path]
        file_findings.sort(key=lambda f: (f.line, f.rule))
        used_sites: Set[Tuple[int, str]] = set()
        classify: List[Finding] = []
        for f in file_findings:
            site = ctx.suppression_site(f.line, f.rule)
            if site is not None:
                used_sites.add(site)
                result.suppressed.append(f)
                continue
            classify.append(f)
        if audit_suppressions:
            classify.extend(_audit_suppressions(ctx, used_sites, active,
                                                registry, result,
                                                audit_universe))
            classify.sort(key=lambda f: (f.line, f.rule))
        # occurrence index per (rule, snippet) so identical lines get
        # distinct baseline fingerprints
        seen: Dict[Tuple[str, str], int] = {}
        for f in classify:
            key = (f.rule, f.snippet)
            idx = seen.get(key, 0)
            seen[key] = idx + 1
            if f.fingerprint(idx) in baseline_fingerprints:
                result.baselined.append(f)
            else:
                result.findings.append(f)
    return result


def _audit_suppressions(ctx: FileContext,
                        used_sites: Set[Tuple[int, str]],
                        active: Set[str], registry: Dict[str, type],
                        result: LintResult,
                        audit_universe: Set[str]) -> List[Finding]:
    """``unused-suppression`` findings for every directive token that
    silenced nothing this run (its own suppression/baseline treatment
    happens in the caller's classification pass, so a justified
    ``disable=unused-suppression`` works like any other rule)."""
    out: List[Finding] = []
    for line, tokens in sorted(ctx.suppressions.items()):
        for token in sorted(tokens):
            if (line, token) in used_sites:
                continue
            if token == "all":
                # "all" is spent if ANY rule was silenced at this site
                if any(site_line == line for site_line, _ in used_sites):
                    continue
                if not audit_universe <= active:
                    # a partial run (vs the auditing tool's own
                    # catalogue) cannot call "all" stale: the finding
                    # it silences may belong to a rule that did not
                    # run (same unknowability as the exact-token
                    # branch below)
                    continue
                message = ("'disable=all' silenced nothing here — "
                           "stale; remove it or narrow it to a rule")
            elif token == "unused-suppression":
                # the audit's own escape hatch is never audited (its
                # usage depends on audit-finding order, not rule runs)
                continue
            elif token in registry:
                if token not in active:
                    continue  # rule exists but was not run: unknowable
                message = (f"suppression '{token}' never fired on this "
                           f"line — the finding it silenced is gone; "
                           f"remove the stale directive (policy: "
                           f"docs/STATIC_ANALYSIS.md)")
            else:
                message = (f"suppression names unknown rule "
                           f"{token!r} — renamed or removed; the "
                           f"directive is dead")
            f = ctx.finding("unused-suppression", line, message)
            # only an EXPLICIT disable=unused-suppression token can
            # silence an audit finding — honoring the audited
            # directive's own "all" here would let every stale
            # disable=all hide its own staleness (and its neighbor's,
            # via the line-above convention), which is the exact rot
            # this audit exists to kill
            site = next(((cand, "unused-suppression")
                         for cand in (line, line - 1)
                         if "unused-suppression"
                         in ctx.suppressions.get(cand, set())), None)
            if site is not None:
                used_sites.add(site)
                result.suppressed.append(f)
                continue
            out.append(f)
    return out


def package_root() -> str:
    """The installed ``gan_deeplearning4j_tpu`` package directory — the
    default lint target for ``gan4j-lint`` with no arguments and for the
    bench ``--dryrun`` lint gate."""
    import gan_deeplearning4j_tpu

    return os.path.dirname(os.path.abspath(gan_deeplearning4j_tpu.__file__))


def lint_package(**kw) -> LintResult:
    """Lint the whole installed package with the default rule set and no
    baseline — the zero-findings contract bench ``--dryrun`` asserts."""
    return lint_paths([package_root()], **kw)
