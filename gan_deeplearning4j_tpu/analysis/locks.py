"""Whole-package lock model — the shared substrate of the gan4j-race
static rules (rules_concurrency.py: ``lock-order-cycle``,
``lock-held-blocking-call``, ``thread-hygiene``).

The ops layer built in PRs 2-8 is deeply concurrent: ~12 modules own
``threading.Lock``/``RLock``/``Event``, and the one concurrency rule
that predates this (``unlocked-shared-write``) only sees a single lock
inside a single class.  A deadlock needs TWO locks and usually two
modules — so this module extracts a package-wide view from the ASTs
the engine already parsed:

* **lock identities** — ``self._lock = threading.Lock()`` in class C of
  module M becomes the node ``M.C._lock`` (one node per *declaration
  site*, the static analogue of a lockdep lock class); module-level
  ``lock = threading.Lock()`` becomes ``M.lock``.  The factory kind is
  kept: an RLock may be re-acquired by its holder, a Lock may not.
* **acquisition order** — every function is walked with a held-lock
  stack (``with self._lock:`` nesting plus straight-line
  ``acquire()``/``release()`` pairs); acquiring B while holding A adds
  the edge A→B with a witness chain (file:line frames a human can
  follow).
* **a direct call graph** — ``self.method()``, same-module ``f()`` and
  imported-module ``mod.f()`` calls are resolved where unambiguous, so
  nested acquisition propagates: if ``f`` holds A and calls ``g`` which
  takes B, the edge A→B exists even though no single function shows it.
* **blocking sites** — calls that park the thread (``join``, queue
  ``get``/``put``, ``Event.wait``, ``block_until_ready``/
  ``device_fence``, ``fsync``, ``sleep``, socket ops), again propagated
  through the call graph, for the lock-held-across-blocking-call rule.
* **thread construction sites** — every ``threading.Thread(...)`` call
  with its ``name``/``daemon`` kwargs and, for non-daemon threads, the
  bounded ``join`` reachability the hygiene rule demands.

Everything here is a heuristic over source, deliberately conservative:
dynamic dispatch (callback lists, ``getattr``) is unresolvable and
silently skipped — the runtime half (``sanitizers.lockdep``) exists to
catch what this cannot.  docs/STATIC_ANALYSIS.md § Concurrency
discipline is the contract.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from gan_deeplearning4j_tpu.analysis.engine import (
    FileContext,
    dotted_name,
    last_segment,
)

LOCK_FACTORIES = {"Lock": "lock", "RLock": "rlock",
                  "Condition": "lock", "Semaphore": "lock",
                  "BoundedSemaphore": "lock"}

# calls that park the calling thread (or serialize it on the device /
# the disk / the network) — the things that must never run while a
# shared lock is held: every other thread then stalls with you, which
# is exactly how a slow checkpoint save becomes a fleet-wide hang
_SOCKET_BLOCKERS = {"recv", "recvfrom", "sendall", "accept", "connect",
                    "urlopen"}
_QUEUE_RECV_RE = re.compile(r"^_?q(ueue)?s?$|^_?(job|task|work)s?(_q)?$",
                            re.IGNORECASE)

CLOSE_METHODS = {"close", "stop", "shutdown", "__exit__", "__del__",
                 "join", "quiesce", "terminate", "wait", "finish"}


@dataclasses.dataclass(frozen=True)
class Frame:
    """One step of a witness chain: a place in the source a human can
    click through when reconstructing an acquisition order."""

    path: str
    line: int
    what: str

    def render(self) -> str:
        return f"{os.path.basename(self.path)}:{self.line} {self.what}"


@dataclasses.dataclass
class ThreadSite:
    """One ``threading.Thread(...)`` construction."""

    path: str
    line: int
    func: str                    # enclosing function qualname
    has_name: bool
    has_daemon: bool
    daemon_false: bool           # explicitly daemon=False
    target_attr: Optional[str]   # self.<attr> it was assigned to
    target_local: Optional[str]  # local name it was assigned to
    cls: Optional[str]           # enclosing class name


@dataclasses.dataclass
class _FnInfo:
    qualname: str                          # Module.Class.method display
    path: str
    cls: Optional[str]
    name: str
    # (lock_id, line, held_tuple) per acquisition in source order
    acquisitions: List[Tuple[str, int, Tuple[str, ...]]]
    # (callee_candidates, line, held_tuple) per resolvable call site
    calls: List[Tuple[Tuple[str, ...], int, Tuple[str, ...]]]
    # (description, line, held_tuple) per blocking call
    blocking: List[Tuple[str, int, Tuple[str, ...]]]


class LockModel:
    """The package-wide lock/call/thread view (module docstring)."""

    def __init__(self, ctxs: Dict[str, FileContext]):
        self.ctxs = ctxs
        # module key per path: the dotted-ish display name; import
        # resolution goes through the basename index below
        self._mod_name: Dict[str, str] = {}
        basenames: Dict[str, List[str]] = {}
        for path in ctxs:
            base = os.path.splitext(os.path.basename(path))[0]
            basenames.setdefault(base, []).append(path)
        for base, paths in basenames.items():
            if len(paths) == 1:
                self._mod_name[paths[0]] = base
                continue
            # two files named worker.py must NOT merge their lock
            # identities (a false cross-file cycle): qualify colliding
            # names with as many parent directories as the GROUP needs
            # to be pairwise distinct (paths are dict keys, so full
            # paths always differ and the loop terminates)
            def suffix(p: str, d: int) -> str:
                parts = os.path.normpath(p).split(os.sep)
                parts[-1] = base
                return "/".join(parts[-d:])

            depth = 2
            while len({suffix(p, depth) for p in paths}) < len(paths):
                depth += 1
            for path in paths:
                self._mod_name[path] = suffix(path, depth)
        # unambiguous basename -> path (two files named util.py cannot
        # be told apart from an import site: skip, stay conservative)
        self._by_basename = {b: ps[0] for b, ps in basenames.items()
                             if len(ps) == 1}

        self.lock_kinds: Dict[str, str] = {}      # lock id -> lock|rlock
        self.lock_sites: Dict[str, Frame] = {}    # lock id -> declaration
        self.threads: List[ThreadSite] = []
        self._fns: Dict[Tuple[str, str], _FnInfo] = {}
        # per (path, class): attr -> lock id, plus module-level names
        self._class_locks: Dict[Tuple[str, str], Dict[str, str]] = {}
        self._module_locks: Dict[str, Dict[str, str]] = {}

        for path, ctx in ctxs.items():
            self._collect_locks(path, ctx)
        for path, ctx in ctxs.items():
            self._collect_functions(path, ctx)
        self._trans_cache: Dict[Tuple[str, str],
                                Dict[str, List[Frame]]] = {}
        self._block_cache: Dict[Tuple[str, str],
                                Optional[List[Frame]]] = {}
        self._edges_cache: Optional[Dict[Tuple[str, str],
                                         List[Frame]]] = None

    # -- collection -----------------------------------------------------------

    def _lock_factory_kind(self, call: ast.AST) -> Optional[str]:
        if not isinstance(call, ast.Call):
            return None
        seg = last_segment(call.func)
        return LOCK_FACTORIES.get(seg or "")

    def _collect_locks(self, path: str, ctx: FileContext) -> None:
        mod = self._mod_name[path]
        module_locks: Dict[str, str] = {}
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.Assign):
                kind = self._lock_factory_kind(stmt.value)
                if kind:
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            lock_id = f"{mod}.{t.id}"
                            module_locks[t.id] = lock_id
                            self.lock_kinds[lock_id] = kind
                            self.lock_sites[lock_id] = Frame(
                                path, stmt.lineno,
                                f"declares {lock_id}")
        self._module_locks[path] = module_locks
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            attrs: Dict[str, str] = {}
            for sub in ast.walk(node):
                if not isinstance(sub, ast.Assign):
                    continue
                kind = self._lock_factory_kind(sub.value)
                if not kind:
                    continue
                for t in sub.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        lock_id = f"{mod}.{node.name}.{t.attr}"
                        attrs[t.attr] = lock_id
                        self.lock_kinds[lock_id] = kind
                        self.lock_sites[lock_id] = Frame(
                            path, sub.lineno, f"declares {lock_id}")
            if attrs:
                self._class_locks[(path, node.name)] = attrs

    def _collect_functions(self, path: str, ctx: FileContext) -> None:
        mod = self._mod_name[path]
        imports = self._import_map(ctx.tree)

        def visit(node: ast.AST, cls: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, child.name)
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    qual = (f"{mod}.{cls}.{child.name}" if cls
                            else f"{mod}.{child.name}")
                    info = _FnInfo(qual, path, cls, child.name,
                                   [], [], [])
                    self._walk_body(child.body, [], info, path, cls,
                                    imports)
                    key = (path, f"{cls}.{child.name}" if cls
                           else child.name)
                    self._fns[key] = info
                    visit(child, cls)  # nested defs keep the class
                else:
                    visit(child, cls)

        visit(ctx.tree, None)

    @staticmethod
    def _import_map(tree: ast.Module) -> Dict[str, str]:
        """local name -> imported module basename (``from x import
        events`` and ``import x.y as z`` both land here)."""
        out: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        # `import a.b as z`: z is bound to module a.b
                        out[alias.asname] = alias.name.split(".")[-1]
                    else:
                        # `import a.b`: the bound name is the TOP
                        # package a, not b
                        top = alias.name.split(".")[0]
                        out[top] = top
            elif isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    out[alias.asname or alias.name] = alias.name
        return out

    # -- the per-function walk ------------------------------------------------

    def _lock_id_for_expr(self, expr: ast.AST, path: str,
                          cls: Optional[str]) -> Optional[str]:
        """``self._lock`` / module-level ``lockname`` (possibly behind
        ``.acquire_timeout(...)``-style helpers) -> lock id."""
        for node in ast.walk(expr):
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self" and cls):
                lid = self._class_locks.get((path, cls), {}).get(
                    node.attr)
                if lid:
                    return lid
            if isinstance(node, ast.Name):
                lid = self._module_locks.get(path, {}).get(node.id)
                if lid:
                    return lid
        return None

    def _walk_body(self, body: Sequence[ast.stmt], held: List[str],
                   info: _FnInfo, path: str, cls: Optional[str],
                   imports: Dict[str, str]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # separate scope, walked separately
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                pushed = 0
                for item in stmt.items:
                    lid = self._lock_id_for_expr(item.context_expr,
                                                 path, cls)
                    if lid:
                        self._acquire(lid, item.context_expr.lineno,
                                      held, info)
                        held.append(lid)
                        pushed += 1
                    else:
                        self._scan_expr(item.context_expr, held, info,
                                        path, cls, imports)
                self._walk_body(stmt.body, held, info, path, cls,
                                imports)
                for _ in range(pushed):
                    held.pop()
                continue
            lid = self._explicit_lock_call(stmt, path, cls, "acquire")
            if lid:
                self._acquire(lid, stmt.lineno, held, info)
                held.append(lid)
                continue
            lid = self._explicit_lock_call(stmt, path, cls, "release")
            if lid:
                if lid in held:
                    held.remove(lid)
                continue
            if isinstance(stmt, ast.If):
                self._scan_expr(stmt.test, held, info, path, cls,
                                imports)
                self._walk_body(stmt.body, list(held), info, path, cls,
                                imports)
                self._walk_body(stmt.orelse, list(held), info, path,
                                cls, imports)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._scan_expr(stmt.iter, held, info, path, cls,
                                imports)
                self._walk_body(list(stmt.body), list(held), info,
                                path, cls, imports)
                self._walk_body(list(stmt.orelse), list(held), info,
                                path, cls, imports)
            elif isinstance(stmt, ast.While):
                self._scan_expr(stmt.test, held, info, path, cls,
                                imports)
                self._walk_body(list(stmt.body), list(held), info,
                                path, cls, imports)
                self._walk_body(list(stmt.orelse), list(held), info,
                                path, cls, imports)
            elif isinstance(stmt, ast.Try):
                # body/else/finally share the live held list: the
                # canonical `acquire(); try: ... finally: release()`
                # idiom must propagate its release OUT of the try — a
                # copied list would leave the lock phantom-held for the
                # rest of the function (false blocking/order findings).
                # Handlers stay on a copy: they may or may not run.
                for handler in stmt.handlers:
                    self._walk_body(handler.body, list(held), info,
                                    path, cls, imports)
                for block in (stmt.body, stmt.orelse, stmt.finalbody):
                    self._walk_body(block, held, info, path, cls,
                                    imports)
            else:
                self._scan_expr(stmt, held, info, path, cls, imports)

    def _explicit_lock_call(self, stmt: ast.stmt, path: str,
                            cls: Optional[str],
                            which: str) -> Optional[str]:
        if not (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Call)
                and isinstance(stmt.value.func, ast.Attribute)
                and stmt.value.func.attr == which):
            return None
        return self._lock_id_for_expr(stmt.value.func.value, path, cls)

    def _acquire(self, lid: str, line: int, held: List[str],
                 info: _FnInfo) -> None:
        info.acquisitions.append((lid, line, tuple(held)))

    def _scan_expr(self, node: ast.AST, held: List[str], info: _FnInfo,
                   path: str, cls: Optional[str],
                   imports: Dict[str, str]) -> None:
        """Record resolvable calls, blocking calls and thread
        constructions inside one statement/expression."""
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            self._record_thread(sub, node, info, path, cls)
            desc = _blocking_desc(sub)
            effective_held = tuple(held)
            if desc:
                cond_lid = self._held_condition_wait_lock(sub, held,
                                                          path, cls)
                if cond_lid is not None:
                    # `with self._cond: self._cond.wait()` — the
                    # canonical condition-variable idiom: wait()
                    # atomically RELEASES the condition's OWN lock
                    # while parked.  Any OTHER lock held across the
                    # wait stays held for the whole park, so those
                    # still count — and the entry is kept even with
                    # nothing else held, so a CALLER holding a lock
                    # across this function still sees it as blocking.
                    effective_held = tuple(h for h in held
                                           if h != cond_lid)
            if desc:
                info.blocking.append((desc, sub.lineno, effective_held))
                continue
            cands = self._callee_candidates(sub, path, cls, imports)
            if cands:
                info.calls.append((cands, sub.lineno, tuple(held)))

    def _held_condition_wait_lock(self, call: ast.Call,
                                  held: List[str], path: str,
                                  cls: Optional[str]) -> Optional[str]:
        """The held lock id a ``cond.wait()`` call atomically releases
        (the receiver's own lock), None when this is not that shape."""
        f = call.func
        if not (isinstance(f, ast.Attribute)
                and f.attr in ("wait", "wait_for")):
            return None
        lid = self._lock_id_for_expr(f.value, path, cls)
        return lid if lid is not None and lid in held else None

    def _callee_candidates(self, call: ast.Call, path: str,
                           cls: Optional[str],
                           imports: Dict[str, str]
                           ) -> Tuple[str, ...]:
        """(path, fn_key) candidates encoded as "path::key" strings for
        a call we can resolve statically; empty when dynamic.
        Candidates are recorded WITHOUT checking they exist — collection
        order must not matter (a callee defined later in the file, or in
        a file walked later, still counts); the graph consumers resolve
        against the finished function table and drop misses."""
        f = call.func
        out: List[str] = []
        if (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                and f.value.id == "self" and cls):
            out.append(f"{path}::{cls}.{f.attr}")
        elif isinstance(f, ast.Name):
            out.append(f"{path}::{f.id}")
        elif isinstance(f, ast.Attribute) and isinstance(f.value,
                                                         ast.Name):
            mod = imports.get(f.value.id)
            if mod:
                target = self._by_basename.get(mod)
                if target:
                    out.append(f"{target}::{f.attr}")
        return tuple(out)

    def _record_thread(self, call: ast.Call, stmt: ast.AST,
                       info: _FnInfo, path: str,
                       cls: Optional[str]) -> None:
        name = dotted_name(call.func)
        if not (name == "threading.Thread"
                or (isinstance(call.func, ast.Name)
                    and call.func.id == "Thread")):
            return
        kwargs = {k.arg for k in call.keywords if k.arg}
        daemon_false = any(
            k.arg == "daemon" and isinstance(k.value, ast.Constant)
            and k.value.value is False for k in call.keywords)
        target_attr = target_local = None
        if isinstance(stmt, ast.Assign) and stmt.value is call:
            for t in stmt.targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    target_attr = t.attr
                elif isinstance(t, ast.Name):
                    target_local = t.id
        self.threads.append(ThreadSite(
            path=path, line=call.lineno, func=info.qualname,
            has_name="name" in kwargs, has_daemon="daemon" in kwargs,
            daemon_false=daemon_false, target_attr=target_attr,
            target_local=target_local, cls=cls))

    # -- derived views --------------------------------------------------------

    def functions(self) -> Iterable[_FnInfo]:
        return self._fns.values()

    def transitive_locks(self, key: Tuple[str, str],
                         _seen: Optional[Set] = None
                         ) -> Dict[str, List[Frame]]:
        """lock id -> witness frames for every lock ``key``'s function
        acquires directly or through resolvable calls."""
        if key in self._trans_cache:
            return self._trans_cache[key]
        _seen = _seen or set()
        if key in _seen or key not in self._fns:
            return {}
        _seen.add(key)
        info = self._fns[key]
        out: Dict[str, List[Frame]] = {}
        for lid, line, _held in info.acquisitions:
            out.setdefault(lid, [Frame(info.path, line,
                                       f"{info.qualname} acquires "
                                       f"{lid}")])
        for cands, line, _held in info.calls:
            for cand in cands:
                cpath, ckey = cand.split("::", 1)
                sub = self.transitive_locks((cpath, ckey), _seen)
                for lid, frames in sub.items():
                    if lid not in out:
                        out[lid] = [Frame(info.path, line,
                                          f"{info.qualname} calls "
                                          f"{ckey.split('.')[-1]}()")
                                    ] + frames
        # cached unconditionally: inside a call cycle the result may be
        # conservative (a lint under-approximation, never a crash)
        self._trans_cache[key] = out
        return out

    def blocking_chain(self, key: Tuple[str, str],
                       _seen: Optional[Set] = None
                       ) -> Optional[List[Frame]]:
        """Witness frames to the first blocking site reachable from
        ``key``'s function WITHOUT an intervening release — approximated
        as: any blocking call in it or any resolvable callee.  None when
        the function provably (at this heuristic's strength) never
        blocks."""
        if key in self._block_cache:
            return self._block_cache[key]
        _seen = _seen or set()
        if key in _seen or key not in self._fns:
            return None
        _seen.add(key)
        info = self._fns[key]
        result: Optional[List[Frame]] = None
        for desc, line, _held in info.blocking:
            result = [Frame(info.path, line,
                            f"{info.qualname} blocks in {desc}")]
            break
        if result is None:
            for cands, line, _held in info.calls:
                for cand in cands:
                    cpath, ckey = cand.split("::", 1)
                    sub = self.blocking_chain((cpath, ckey), _seen)
                    if sub:
                        result = [Frame(info.path, line,
                                        f"{info.qualname} calls "
                                        f"{ckey.split('.')[-1]}()")
                                  ] + sub
                        break
                if result:
                    break
        self._block_cache[key] = result
        return result

    def acquisition_edges(self) -> Dict[Tuple[str, str], List[Frame]]:
        """(held, acquired) -> witness chain, over direct nesting AND
        call-propagated nesting.  Reentrant (rlock) self-edges are
        dropped; a plain-Lock self-edge is kept — that one is not an
        ordering hazard but a guaranteed self-deadlock.  Memoized: the
        cycle finder and the rule both read the same edge set."""
        if self._edges_cache is not None:
            return self._edges_cache
        edges: Dict[Tuple[str, str], List[Frame]] = {}

        def add(a: str, b: str, frames: List[Frame]) -> None:
            if a == b and self.lock_kinds.get(a) == "rlock":
                return  # reentrant: the holder may re-enter
            edges.setdefault((a, b), frames)

        for (path, fkey), info in self._fns.items():
            for lid, line, held in info.acquisitions:
                for h in held:
                    add(h, lid,
                        [Frame(info.path, line,
                               f"{info.qualname} acquires {lid} "
                               f"while holding {h}")])
            for cands, line, held in info.calls:
                if not held:
                    continue
                for cand in cands:
                    cpath, ckey = cand.split("::", 1)
                    sub = self.transitive_locks((cpath, ckey))
                    for lid, frames in sub.items():
                        for h in held:
                            # add() drops reentrant self-edges; a
                            # plain-Lock self-edge through a call chain
                            # is kept — holder re-entering its own
                            # non-reentrant lock is a self-deadlock
                            add(h, lid,
                                [Frame(info.path, line,
                                       f"{info.qualname} holds {h} and "
                                       f"calls {ckey.split('.')[-1]}()")
                                 ] + frames)
        self._edges_cache = edges
        return edges

    def lock_cycles(self) -> List[List[Tuple[str, str]]]:
        """Cycles in the acquisition-order graph, each as a list of
        (held, acquired) edges — a 2-cycle [(A,B),(B,A)] is the classic
        AB/BA deadlock; a self-loop [(A,A)] is a plain Lock re-entered
        by its own holder."""
        edges = self.acquisition_edges()
        adj: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)
        cycles: List[List[Tuple[str, str]]] = []
        seen_cycles: Set[Tuple[str, ...]] = set()

        for (a, b) in sorted(edges):
            if a == b:
                cycles.append([(a, a)])
                continue
            # path b -> ... -> a closes the cycle a -> b -> ... -> a
            path = shortest_path(adj, b, a)
            if path is None:
                continue
            nodes = [a] + path
            canon = tuple(sorted(set(nodes)))
            if canon in seen_cycles:
                continue  # one report per distinct lock set
            seen_cycles.add(canon)
            cycles.append([(nodes[i], nodes[i + 1])
                           for i in range(len(nodes) - 1)])
        return cycles

    def held_blocking_sites(self) -> List[Tuple[str, int, str, str,
                                                List[Frame]]]:
        """(path, line, lock, desc, chain) for every blocking call made
        while a known lock is held — directly, or through a resolvable
        call chain."""
        out = []
        for (path, fkey), info in self._fns.items():
            for desc, line, held in info.blocking:
                for h in held:
                    out.append((info.path, line, h, desc,
                                [Frame(info.path, line,
                                       f"{info.qualname} blocks in "
                                       f"{desc} holding {h}")]))
            for cands, line, held in info.calls:
                if not held:
                    continue
                for cand in cands:
                    cpath, ckey = cand.split("::", 1)
                    chain = self.blocking_chain((cpath, ckey))
                    if not chain:
                        continue
                    for h in held:
                        out.append((
                            info.path, line, h,
                            chain[-1].what,
                            [Frame(info.path, line,
                                   f"{info.qualname} holds {h} and "
                                   f"calls {ckey.split('.')[-1]}()")
                             ] + chain))
                    break  # one candidate witness is enough
        return out

    def join_bounded(self, site: ThreadSite) -> bool:
        """True when a bounded ``X.join(timeout)`` for the thread is
        reachable from a close/stop-style path: a method of the OWNING
        class whose name is in ``CLOSE_METHODS`` joining
        ``self.<attr>``, or — for a function-local thread — a bounded
        join anywhere in the same file (locals rarely outlive their
        function).  A join in an unrelated class or in the worker loop
        itself does not count: the contract is that the thread's owner
        can shut it down."""
        ctx = self.ctxs.get(site.path)
        if ctx is None:
            return False
        attr = site.target_attr
        local = site.target_local
        if attr and site.cls:
            owner = next(
                (n for n in ast.walk(ctx.tree)
                 if isinstance(n, ast.ClassDef) and n.name == site.cls),
                None)
            if owner is None:
                return False
            for method in owner.body:
                if not isinstance(method, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                    continue
                if method.name not in CLOSE_METHODS:
                    continue
                if self._joins_self_attr(method, attr):
                    return True
            return False
        if local:
            for node in ast.walk(ctx.tree):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "join"
                        and _bounded_join(node)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == local):
                    return True
        return False

    @staticmethod
    def _joins_self_attr(method: ast.AST, attr: str) -> bool:
        for node in ast.walk(method):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "join"
                    and _bounded_join(node)):
                recv = node.func.value
                if (isinstance(recv, ast.Attribute)
                        and recv.attr == attr
                        and isinstance(recv.value, ast.Name)
                        and recv.value.id == "self"):
                    return True
                # the swap-then-join pattern (watchdog.stop): the attr
                # is copied to a local under the lock and joined outside
                if isinstance(recv, ast.Name) and _local_holds_attr(
                        method, recv.id, attr):
                    return True
        return False


def shortest_path(adj: Dict[str, Set[str]], src: str,
                  dst: str) -> Optional[List[str]]:
    """Shortest src->...->dst node list (both ends included), BFS; None
    when unreachable.  Shared by the static cycle finder above and the
    runtime lockdep graph (sanitizers.LockdepSanitizer) — one
    implementation, deterministic via sorted expansion."""
    from collections import deque

    if src == dst:
        return [src]
    prev: Dict[str, str] = {}
    dq = deque([src])
    seen = {src}
    while dq:
        cur = dq.popleft()
        for nxt in sorted(adj.get(cur, ())):
            if nxt in seen:
                continue
            prev[nxt] = cur
            if nxt == dst:
                out = [dst]
                while out[-1] != src:
                    out.append(prev[out[-1]])
                return list(reversed(out))
            seen.add(nxt)
            dq.append(nxt)
    return None


# one LockModel per lint run: the engine hands every package-scope rule
# the SAME ctxs dict, and building the model (a whole-package AST walk)
# three times for identical input would triple the gate's cost.  Single
# slot, identity-keyed — a new run's dict is a new object.
_MODEL_MEMO: List[Tuple[object, "LockModel"]] = []


def build_lock_model(ctxs: Dict[str, FileContext]) -> "LockModel":
    if _MODEL_MEMO and _MODEL_MEMO[0][0] is ctxs:
        return _MODEL_MEMO[0][1]
    model = LockModel(ctxs)
    _MODEL_MEMO[:] = [(ctxs, model)]
    return model


def _local_holds_attr(method: ast.AST, local: str, attr: str) -> bool:
    """True when ``local`` is assigned from ``self.<attr>`` somewhere in
    the method — the swap-under-the-lock, join-outside pattern."""
    for node in ast.walk(method):
        if not isinstance(node, ast.Assign):
            continue
        values = (node.value.elts
                  if isinstance(node.value, ast.Tuple) else [node.value])
        for t in node.targets:
            targets = t.elts if isinstance(t, ast.Tuple) else [t]
            for tt, vv in zip(targets, values):
                if (isinstance(tt, ast.Name) and tt.id == local
                        and isinstance(vv, ast.Attribute)
                        and vv.attr == attr
                        and isinstance(vv.value, ast.Name)
                        and vv.value.id == "self"):
                    return True
    return False


def _bounded_join(call: ast.Call) -> bool:
    """``t.join(5)`` / ``t.join(timeout=...)``: bounded.  ``t.join()``
    is unbounded; ``", ".join(xs)`` is not a thread join at all."""
    if any(k.arg == "timeout" for k in call.keywords):
        return True
    return (len(call.args) == 1
            and isinstance(call.args[0], (ast.Constant, ast.Name,
                                          ast.BinOp, ast.Attribute))
            and not (isinstance(call.args[0], ast.Constant)
                     and isinstance(call.args[0].value, str)))


def _blocking_desc(call: ast.Call) -> Optional[str]:
    """A short description when ``call`` is a thread-parking primitive,
    None otherwise.  The allowlist (module docstring) is deliberately
    narrow: ``dict.get(key)`` must never match, ``q.get()`` must."""
    f = call.func
    name = dotted_name(f)
    seg = last_segment(f)
    if seg is None:
        return None
    if seg == "join":
        # thread/queue join: no positional args (timeout kw allowed) or
        # one numeric timeout; str.join always has a non-numeric arg
        if isinstance(f, ast.Attribute) and isinstance(
                f.value, ast.Constant):
            return None
        if not call.args:
            return f"{seg}()"
        if (len(call.args) == 1
                and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, (int, float))):
            return f"{seg}()"
        return None
    if seg == "wait":
        return f"{seg}()"
    if seg in ("get", "put"):
        recv = last_segment(f.value) if isinstance(f, ast.Attribute) \
            else None
        if not (recv and _QUEUE_RECV_RE.match(recv)):
            return None
        if seg == "get" and not all(
                isinstance(a, ast.Constant)
                and isinstance(a.value, (bool, int, float))
                for a in call.args):
            # Queue.get takes only (block, timeout); a non-numeric
            # positional is a KEY — `jobs.get(key)` on a dict that
            # happens to carry a queue-ish name must never match
            return None
        # bounded or not: holding a shared lock for up to a queue
        # timeout still stalls every other thread for that long
        return f"{recv}.{seg}()"
    if seg in ("block_until_ready", "device_fence", "fsync"):
        return f"{seg}()"
    if seg == "sleep":
        return "sleep()"
    if seg in _SOCKET_BLOCKERS:
        return f"{seg}()"
    if name and name.startswith("subprocess."):
        return name
    return None
