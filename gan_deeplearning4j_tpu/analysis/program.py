"""gan4j-prove program layer: lower the repo's jitted entry points and
extract checkable facts from the ACTUAL lowering.

gan4j-lint (engine.py) sees the AST; this module sees what XLA will
really execute.  Every registered :class:`EntryPoint` builds one of the
repo's jitted programs — the fused single step, the fused multi/scan
step, the sharded SPMD step, the GANPair multistep scan, the serving
inference dispatch — against abstract ``jax.ShapeDtypeStruct`` inputs
(no device buffers, no TPU: the whole thing runs on the CPU CI lane)
and lowers it via ``jax.jit(...).lower(...)``.  From the lowering and
its CPU compile we extract :class:`ProgramFacts`:

* **donation** — which flat parameters are actually aliased to outputs
  in the compiled module's ``input_output_alias`` (a donation silently
  dropped by jit/XLA doubles the state's HBM footprint and no Python
  test can see it);
* **dtypes** — every tensor element type in the stablehlo (f64 or an
  unintended widening shows up here before it ships);
* **collectives** — static per-step counts of all-reduce / all-gather /
  collective-permute / all-to-all / reduce-scatter ops (an accidental
  extra sync per step is invisible in loss curves and fatal to MFU);
* **peak HBM** — ``compile().memory_analysis()`` byte totals, with an
  aval-size estimate as the fallback where the backend offers none.

contracts.py checks these facts against the versioned JSON contracts in
``analysis/contracts/``; prove_cli.py is the ``gan4j-prove`` console
entry and CI gate.  docs/STATIC_ANALYSIS.md#program-contracts is the
operator manual.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Callable, Dict, List, Optional, Sequence, Tuple

# Collective stablehlo op name -> contract key.  Counted statically in
# the lowered module: a scan body is counted ONCE, matching the
# "per-step cost" meaning of the contract budget.
COLLECTIVE_OPS = {
    "all_reduce": "all-reduce",
    "all_gather": "all-gather",
    "collective_permute": "collective-permute",
    "all_to_all": "all-to-all",
    "reduce_scatter": "reduce-scatter",
    "collective_broadcast": "collective-broadcast",
}

_TENSOR_RE = re.compile(r"tensor<([^>]+)>")
_ALIAS_ENTRY_RE = re.compile(r"\((\d+), \{[^)]*?\}, (?:may|must)-alias\)")


@dataclasses.dataclass
class ProgramFacts:
    """What one lowered program variant actually does — the evidence the
    contract checks run against."""

    entry: str
    variant: str                 # "b8" etc.; one per compile bucket
    batch: int
    mesh_shape: Optional[Dict[str, int]]
    declared_donated_leaves: int  # leaves of the args the entry donates
    aliased_params: List[int]     # flat param indices aliased to outputs
    dtypes: List[str]             # sorted element types in the stablehlo
    collectives: Dict[str, int]   # contract key -> static op count
    peak_bytes: int
    memory_source: str            # "memory_analysis" | "aval-estimate"

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


def abstractify(tree):
    """Concrete pytree -> matching ShapeDtypeStruct pytree (sharding
    dropped; use explicit ShapeDtypeStruct(sharding=...) leaves for SPMD
    entries)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), jnp.asarray(x).dtype),
        tree)


def _aval_bytes(tree) -> int:
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree.leaves(tree):
        total += int(np.prod(leaf.shape or (1,))) * leaf.dtype.itemsize
    return total


def extract_facts(entry_name: str, variant: str, jitted, args,
                  donated_leaves: int, batch: int,
                  mesh_shape: Optional[Dict[str, int]]) -> ProgramFacts:
    """Lower ``jitted`` on the abstract ``args``, compile it for the
    host platform, and read the facts off the artifacts themselves —
    never off source flags."""
    lowered = jitted.lower(*args)
    stablehlo = lowered.as_text()
    compiled = lowered.compile()
    hlo = compiled.as_text()

    # donation ground truth: the COMPILED module's input_output_alias —
    # what the runtime will actually alias, after both jit and XLA had
    # their chance to silently drop a donation
    aliased: List[int] = []
    for line in hlo.splitlines():
        # the HloModule header line carries the whole alias map:
        # input_output_alias={ {0}: (0, {}, may-alias), ... }
        if "input_output_alias=" in line:
            aliased = sorted(
                {int(p) for p in _ALIAS_ENTRY_RE.findall(line)})
            break

    dtypes = set()
    for ty in _TENSOR_RE.findall(stablehlo):
        dtypes.add(ty.split("x")[-1].strip())

    collectives = {}
    for op, key in COLLECTIVE_OPS.items():
        n = len(re.findall(rf"stablehlo\.{op}\b", stablehlo))
        if n:
            collectives[key] = n

    try:
        mem = compiled.memory_analysis()
    except Exception:
        # memory_analysis is per-backend optional; the aval estimate
        # below IS the handled fallback
        mem = None
    if mem is not None and getattr(mem, "argument_size_in_bytes", None
                                   ) is not None:
        # live-at-entry args + live-at-exit outputs (donated aliases
        # counted once) + XLA's scratch high-water mark
        peak = int(mem.argument_size_in_bytes + mem.output_size_in_bytes
                   - mem.alias_size_in_bytes + mem.temp_size_in_bytes)
        source = "memory_analysis"
    else:
        peak = _aval_bytes(args) + _aval_bytes(
            jitted.eval_shape(*args) if hasattr(jitted, "eval_shape")
            else args)
        source = "aval-estimate"

    return ProgramFacts(
        entry=entry_name, variant=variant, batch=batch,
        mesh_shape=mesh_shape, declared_donated_leaves=donated_leaves,
        aliased_params=aliased, dtypes=sorted(dtypes),
        collectives=collectives, peak_bytes=peak, memory_source=source)


# -- reachable batch shapes ---------------------------------------------------
#
# The bucket-coverage contract class: every batch shape a bench or
# serving config can reach must map to a declared compile bucket, so
# "recompile per request shape" is statically impossible.  Reachability
# is computed LIVE from the code (constants and config defaults) — the
# contract pins the declared set; drift on either side is a red prove.


def reachable_protocol_batches() -> List[int]:
    """Batch shapes the fused protocol step is dispatched at by the
    bench and the protocol mains' defaults."""
    from gan_deeplearning4j_tpu import bench
    from gan_deeplearning4j_tpu.train import cv_main, insurance_main

    shapes = {bench.DEFAULT_BATCH, bench.DRYRUN_BATCH, bench.FAST_BATCH}
    for mod in (cv_main, insurance_main):
        shapes.add(int(mod.default_config().batch_size))
    return sorted(shapes)


def reachable_pair_batches() -> List[int]:
    """Batch shapes the GANPair multistep scan is dispatched at (the
    roadmap families' engine)."""
    from gan_deeplearning4j_tpu import bench
    from gan_deeplearning4j_tpu.train import roadmap_main

    return sorted({bench.CELEBA_BATCH, roadmap_main.DEFAULT_BATCH_SIZE})


# -- entry-point registry -----------------------------------------------------


class Built:
    """One lowerable program variant: the jit object, its abstract args,
    and how many flat leaves the entry declares donated."""

    def __init__(self, variant: str, jitted, args, donated_leaves: int,
                 batch: int, mesh_shape: Optional[Dict[str, int]] = None):
        self.variant = variant
        self.jitted = jitted
        self.args = args
        self.donated_leaves = donated_leaves
        self.batch = batch
        self.mesh_shape = mesh_shape


@dataclasses.dataclass
class EntryPoint:
    """A registered jitted entry point of the repo.

    ``build(donate=...)`` returns the program variants to lower; the
    ``donate`` override exists for the CI selftest (a wrapper that drops
    ``donate_argnums`` must turn the gate red).  ``exemption`` names a
    contract-owned donation exemption (e.g. scan-donation) instead of a
    code comment; ``bucket_spec`` returns the live bucket-coverage
    inputs, None when the entry has no bucket contract."""

    name: str
    summary: str
    build: Callable[..., List[Built]]
    needs_devices: int = 1
    exemption: Optional[Dict[str, str]] = None
    bucket_spec: Optional[Callable[[], Dict]] = None


_ENTRIES: Dict[str, EntryPoint] = {}

# The donation/scan exemption, encoded ONCE as data (the contract files
# reference it; train/fused_step.py and train/gan_pair.py point here
# instead of hand-maintaining the rationale in comments).
SCAN_DONATION_EXEMPTION = {
    "id": "scan-donation",
    "reason": "donation + lax.scan trips an INVALID_ARGUMENT runtime "
              "error in the axon TPU backend (single-step donated "
              "programs are fine); the builders flip donate off under "
              "scan and emit a 'donation.disabled' telemetry event — "
              "the cost is one extra copy of the MB-scale state",
}


def register_entry(entry: EntryPoint) -> EntryPoint:
    assert entry.name not in _ENTRIES, entry.name
    _ENTRIES[entry.name] = entry
    return entry


def all_entry_points() -> Dict[str, EntryPoint]:
    return dict(_ENTRIES)


def resolve(names: Optional[Sequence[str]] = None,
            ) -> Tuple[List[EntryPoint], List[Tuple[str, str]]]:
    """Entry points runnable on the current topology.  Returns
    ``(entries, skipped)`` where skipped is ``[(name, reason), ...]`` —
    mesh entries skip (with a reason, never silently) on a single-device
    host.  Unknown names raise ValueError (a usage error upstream)."""
    import jax

    unknown = [n for n in (names or []) if n not in _ENTRIES]
    if unknown:
        raise ValueError(
            f"unknown entry point(s): {', '.join(unknown)}; known: "
            f"{', '.join(sorted(_ENTRIES))}")
    selected = [_ENTRIES[n] for n in names] if names else [
        _ENTRIES[n] for n in sorted(_ENTRIES)]
    n_dev = len(jax.devices())
    entries, skipped = [], []
    for e in selected:
        if e.needs_devices > n_dev:
            skipped.append((e.name, f"needs {e.needs_devices} devices, "
                                    f"host has {n_dev}"))
        else:
            entries.append(e)
    return entries, skipped


def build_facts(entry: EntryPoint, donate: Optional[bool] = None,
                ) -> List[ProgramFacts]:
    """Build + lower every variant of ``entry`` and extract its facts.
    ``donate`` overrides the entry's donation wiring (selftest only)."""
    kwargs = {} if donate is None else {"donate": donate}
    return [
        extract_facts(entry.name, b.variant, b.jitted, b.args,
                      b.donated_leaves, b.batch, b.mesh_shape)
        for b in entry.build(**kwargs)
    ]


# -- the registered entries ---------------------------------------------------
#
# All builds are CI-sized (batch = bench.DRYRUN_BATCH, tiny tables):
# the verified invariants — aliasing, collective counts, dtype set —
# are batch-independent program properties, and the HBM ceiling is
# pinned at the shape the contract records.


def _mnist_protocol(mesh=None, **mk_kwargs):
    import jax
    import jax.numpy as jnp

    from gan_deeplearning4j_tpu import bench
    from gan_deeplearning4j_tpu.models import dcgan_mnist as M
    from gan_deeplearning4j_tpu.train import fused_step as fused

    b = bench.DRYRUN_BATCH
    dis, gen, gan = (
        M.build_discriminator(), M.build_generator(), M.build_gan())
    classifier = M.build_classifier(dis)
    step = fused.make_protocol_step(
        dis, gen, gan, classifier,
        M.DIS_TO_GAN, M.GAN_TO_GEN, M.DIS_TO_CLASSIFIER,
        z_size=2, num_features=784, mesh=mesh, **mk_kwargs)
    state = fused.state_from_graphs(dis, gen, gan, classifier)
    key = jax.random.key(0)
    ones = jnp.ones((b, 1), jnp.float32)
    rows = (4 * b if mk_kwargs.get("data_on_device") else b)
    args = (state, jnp.zeros((rows, 784), jnp.float32),
            jnp.zeros((rows, 10), jnp.float32),
            key, jax.random.fold_in(key, 1), ones, 0.0 * ones, ones)
    return step, abstractify(args), state, b


def _build_fused_single(donate: bool = True) -> List[Built]:
    import jax

    step, args, state, b = _mnist_protocol(donate=donate)
    return [Built("single", step, args,
                  len(jax.tree.leaves(state)) if donate else 0, b)]


def _build_fused_multi(donate: bool = True) -> List[Built]:
    # donate=True on purpose: the module itself must flip it off under
    # scan (the contract-owned exemption), and the facts must show zero
    # aliasing REGARDLESS of what the caller asked for
    step, args, _, b = _mnist_protocol(
        donate=donate, data_on_device=True, steps_per_call=2)
    return [Built("scan2", step, args, 0, b)]


def _build_sharded_step(donate: bool = True) -> List[Built]:
    import jax
    import numpy as np
    from jax.sharding import Mesh

    # TWO mesh sizes on purpose (elastic resume, parallel/elastic.py):
    # a shrink/grow resume moves the SAME program between device
    # counts, so the contract must hold at both — identical collective
    # schedule and dtype set, only the per-device shard changes.  The
    # 4-device variant joins wherever the host attaches enough devices
    # (the CI lane forces 8; a 2-device host proves spmd2 alone).
    built = []
    for n in (2, 4):
        if n > len(jax.devices()):
            continue
        mesh = Mesh(np.asarray(jax.devices()[:n]), ("data",))
        step, args, state, b = _mnist_protocol(mesh=mesh, donate=donate)
        built.append(Built(
            f"spmd{n}", step, args,
            len(jax.tree.leaves(state)) if donate else 0, b,
            mesh_shape={"data": n}))
    return built


def _build_pair_multi(donate: bool = False) -> List[Built]:
    import jax.numpy as jnp

    from gan_deeplearning4j_tpu import bench
    from gan_deeplearning4j_tpu.models import mlpgan_insurance as I
    from gan_deeplearning4j_tpu.train.gan_pair import GANPair

    del donate  # the pair scan never donates (scan-donation exemption)
    b = bench.DRYRUN_BATCH
    pair = GANPair(I.build_generator(), I.build_discriminator())
    table = jnp.zeros((4 * b, I.InsuranceConfig().num_features),
                      jnp.float32)
    step_fn, state0 = pair.make_multistep(
        table, None, batch_size=b, steps_per_call=2, z_size=2)
    args = abstractify((state0, *step_fn.invariants))
    return [Built("scan2", step_fn.jitted, args, 0, b)]


def _build_serving_infer(donate: bool = False) -> List[Built]:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from gan_deeplearning4j_tpu.models import dcgan_mnist as M
    from gan_deeplearning4j_tpu.parallel.inference import (
        DEFAULT_SERVING_BUCKETS,
        ParallelInference,
    )

    del donate  # inference dispatch has no state to donate
    gen = M.build_generator()
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("data",))
    # the ACTUAL serving dispatch: ParallelInference's own jit object
    # and shardings, the same path serve/engine.py drives.  The engine
    # pads every batch host-side to a declared bucket before dispatch,
    # so this bucket set IS the complete set of program shapes serving
    # may run — if the engine could reach any other shape, the contract
    # would miss it and the zero-recompile claim would be unproven.
    pi = ParallelInference(gen, mesh=mesh,
                           buckets=DEFAULT_SERVING_BUCKETS)
    params = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       sharding=pi._rep),
        abstractify(gen.params))
    built = []
    for b in pi.buckets:
        z = {gen.input_names[0]: jax.ShapeDtypeStruct(
            (b, 2), jnp.float32, sharding=pi._batch_sh)}
        built.append(Built(f"b{b}", pi._jit, (params, z), 0, b,
                           mesh_shape={"data": 2}))
    return built


def _build_fleet_step(donate: bool = True) -> List[Built]:
    import jax
    import jax.numpy as jnp

    from gan_deeplearning4j_tpu import bench
    from gan_deeplearning4j_tpu.models import mlpgan_insurance as I
    from gan_deeplearning4j_tpu.train import fleet, fused_step as fused
    from gan_deeplearning4j_tpu.parallel import fleet as pfleet

    b = bench.DRYRUN_BATCH
    cfg = I.InsuranceConfig()
    dis, gen = I.build_discriminator(), I.build_generator()
    gan, classifier = I.build_gan(), I.build_classifier(dis)

    def fleet_args(n):
        state = fleet.replicate_state(
            fused.state_from_graphs(dis, gen, gan, classifier), n)
        keys = fleet.tenant_keys(jax.random.key(0), n)
        ones = jnp.ones((b, 1), jnp.float32)
        return state, (
            state, jnp.zeros((n, b, cfg.num_features), jnp.float32),
            jnp.zeros((n, b, 1), jnp.float32),
            keys, fleet.tenant_keys(jax.random.key(1), n),
            ones, 0.0 * ones, ones)

    mk = dict(z_size=cfg.z_size, num_features=cfg.num_features,
              per_tenant_data=True, donate=donate)
    built = []
    # t8: CI-sized; t1024: the flagship tenant count the HBM ceiling is
    # pinned at.  Donation aliasing and the (empty) collective schedule
    # are tenant-count-independent; lowering both proves it.
    for n in (8, 1024):
        step = fleet.make_fleet_step(
            dis, gen, gan, classifier, I.DIS_TO_GAN, I.GAN_TO_GEN,
            I.DIS_TO_CLASSIFIER, **mk)
        state, args = fleet_args(n)
        built.append(Built(
            f"t{n}", step, abstractify(args),
            len(jax.tree.leaves(state)) if donate else 0, b))
    # the shard_map tenant-parallel variant: same program spread over a
    # tenant mesh — the contract claim is ZERO collectives (tenants
    # never communicate)
    n_dev = min(8, len(jax.devices()))
    if n_dev >= 2:
        mesh = pfleet.tenant_mesh(n_dev)
        step = pfleet.make_sharded_fleet_step(
            dis, gen, gan, classifier, I.DIS_TO_GAN, I.GAN_TO_GEN,
            I.DIS_TO_CLASSIFIER, mesh=mesh, **mk)
        n = 2 * n_dev
        state, args = fleet_args(n)
        built.append(Built(
            f"spmd{n_dev}", step, abstractify(args),
            len(jax.tree.leaves(state)) if donate else 0, b,
            mesh_shape={pfleet.AXIS: n_dev}))
    # the lifecycle cohort form (train/lifecycle.py): the MASKED fleet
    # step — signature gains an (N,) bool mask after rng_keys; ghost
    # slots, quarantine freezes and onboard fills are mask VALUES in
    # these exact programs — lowered at the smallest and a mid tenant
    # bucket.  The contract claim is unchanged by masking: donation
    # still aliased, zero collectives.
    from gan_deeplearning4j_tpu.train.lifecycle import (
        DEFAULT_TENANT_BUCKETS,
    )

    mkm = dict(mk, masked=True)
    for n in (DEFAULT_TENANT_BUCKETS[0], 8):
        step = fleet.make_fleet_step(
            dis, gen, gan, classifier, I.DIS_TO_GAN, I.GAN_TO_GEN,
            I.DIS_TO_CLASSIFIER, **mkm)
        state, args = fleet_args(n)
        margs = args[:5] + (jnp.ones((n,), jnp.bool_),) + args[5:]
        built.append(Built(
            f"masked_t{n}", step, abstractify(margs),
            len(jax.tree.leaves(state)) if donate else 0, b))
    # a non-default-architecture cohort (h64_l2): the heterogeneous
    # fleet's OTHER compiled program family — each cohort lowers its
    # own masked step, so the narrower/shallower variant must satisfy
    # the same contract
    cfg64 = I.InsuranceConfig(hidden=64, gen_layers=2)
    dis64, gen64 = I.build_discriminator(cfg64), I.build_generator(cfg64)
    gan64, clf64 = I.build_gan(cfg64), I.build_classifier(dis64, cfg64)
    step64 = fleet.make_fleet_step(
        dis64, gen64, gan64, clf64, I.DIS_TO_GAN,
        I.gan_to_gen_map(cfg64), I.DIS_TO_CLASSIFIER, **mkm)
    n = DEFAULT_TENANT_BUCKETS[0]
    state64 = fleet.replicate_state(
        fused.state_from_graphs(dis64, gen64, gan64, clf64), n)
    ones = jnp.ones((b, 1), jnp.float32)
    args64 = (state64,
              jnp.zeros((n, b, cfg64.num_features), jnp.float32),
              jnp.zeros((n, b, 1), jnp.float32),
              fleet.tenant_keys(jax.random.key(0), n),
              fleet.tenant_keys(jax.random.key(1), n),
              jnp.ones((n,), jnp.bool_), ones, 0.0 * ones, ones)
    built.append(Built(
        f"masked_h64l2_t{n}", step64, abstractify(args64),
        len(jax.tree.leaves(state64)) if donate else 0, b))
    return built


def _tenant_bucket_spec() -> Dict:
    # the tenant-axis bucket discipline (train/lifecycle.py): cohort
    # capacity is always one of DEFAULT_TENANT_BUCKETS, so those counts
    # are the complete set of fleet-step shapes lifecycle warmup can
    # compile — "exact" membership, pinned in the contract so changing
    # the bucket set is a contract diff, never a silent recompile
    from gan_deeplearning4j_tpu.train.lifecycle import (
        DEFAULT_TENANT_BUCKETS,
    )

    return {
        "mode": "exact",
        "code_declared": sorted(DEFAULT_TENANT_BUCKETS),
        "reachable": sorted(DEFAULT_TENANT_BUCKETS),
    }


def _serving_bucket_spec() -> Dict:
    from gan_deeplearning4j_tpu.parallel.inference import (
        DEFAULT_SERVING_BUCKETS,
    )

    return {
        "mode": "round-up",
        "code_declared": sorted(DEFAULT_SERVING_BUCKETS),
        "max_request": max(DEFAULT_SERVING_BUCKETS),
    }


register_entry(EntryPoint(
    name="fused_single",
    summary="fused three-graph protocol step, single-step donated path "
            "(train/fused_step.py; the bench headline program)",
    build=_build_fused_single,
    bucket_spec=lambda: {"mode": "exact",
                         "code_declared": reachable_protocol_batches(),
                         "reachable": reachable_protocol_batches()},
))

register_entry(EntryPoint(
    name="fused_multi",
    summary="fused protocol step under lax.scan (steps_per_call>1, "
            "device-resident data) — the trainer's chunked fast path",
    build=_build_fused_multi,
    exemption=SCAN_DONATION_EXEMPTION,
))

register_entry(EntryPoint(
    name="sharded_step",
    summary="fused protocol step as a shard_map SPMD program, lowered "
            "at 2- AND 4-device data meshes (parallel/ collective "
            "schedule; elastic resume moves between device counts)",
    build=_build_sharded_step,
    needs_devices=2,
))

register_entry(EntryPoint(
    name="pair_multi",
    summary="GANPair multistep scan (train/gan_pair.py; the roadmap "
            "families' engine, insurance-sized for CI)",
    build=_build_pair_multi,
    exemption=SCAN_DONATION_EXEMPTION,
    bucket_spec=lambda: {"mode": "exact",
                         "code_declared": reachable_pair_batches(),
                         "reachable": reachable_pair_batches()},
))

register_entry(EntryPoint(
    name="fleet_step",
    summary="multi-tenant fleet step: the fused protocol step vmapped "
            "over the tenant axis (train/fleet.py), lowered at 8 and "
            "1024 tenants plus the shard_map tenant-mesh variant "
            "(parallel/fleet.py; zero collectives by construction) "
            "and the lifecycle cohort forms — the masked step at "
            "bucketed tenant capacities incl. a non-default h64_l2 "
            "cohort (train/lifecycle.py; mask flips are runtime "
            "values, never program changes)",
    build=_build_fleet_step,
    bucket_spec=_tenant_bucket_spec,
))

register_entry(EntryPoint(
    name="serving_infer",
    summary="the serving plane's compiled dispatch: ParallelInference "
            "(parallel/inference.py) lowered at every declared bucket "
            "shape — the complete program set serve/engine.py can "
            "reach, since the engine pads every batch host-side to a "
            "bucket before dispatching",
    build=_build_serving_infer,
    needs_devices=2,
    bucket_spec=_serving_bucket_spec,
))
