"""``gan4j-prove`` console entry point — the program-contract CI gate.

Verifies the repo's jitted entry points against the versioned contracts
in ``analysis/contracts/`` (contracts.py): donation aliasing, dtype
discipline, collective budgets, peak-HBM ceilings and compile-bucket
coverage — all read off the ACTUAL ``jax.jit(...).lower()`` artifacts
on abstract inputs, so the tool needs no accelerator and runs on the
CPU CI lane.

Exit codes (the CI contract, tier1.yml prove lane):

  0  every resolved entry point satisfies its contract
     (or --write-contracts / --selftest / --list-entries succeeded)
  1  at least one contract violation (or a selftest class not firing)
  2  usage error — including ZERO resolved entry points: a prover that
     proves nothing must not answer green
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional


def _force_cpu_topology() -> None:
    """gan4j-prove is a static verifier: contracts are written and
    checked against the CPU lowering, deterministically, with enough
    virtual devices that the SPMD entry points resolve.  Must run
    before the JAX backend initializes (conftest.py uses the same
    dance; this environment's TPU plugin force-sets jax_platforms at
    interpreter startup, so the env var alone is not enough)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # gan4j-lint: disable=swallowed-exception — backend already initialized (in-process use): the caller's topology stands
        pass


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="gan4j-prove", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--contracts", default=None, metavar="DIR",
                   help="contract directory (default: the committed "
                        "analysis/contracts/ inside the package)")
    p.add_argument("--entries", default=None, metavar="LIST",
                   help="comma-separated entry-point names "
                        "(default: all resolvable)")
    p.add_argument("--write-contracts", action="store_true",
                   help="freeze the current facts as the contracts "
                        "(adoption mode — same semantics as gan4j-lint "
                        "--write-baseline) and exit 0")
    p.add_argument("--format", choices=("human", "json"),
                   default="human", help="report format (json is the "
                                         "CI artifact format)")
    p.add_argument("--output", default=None, metavar="FILE",
                   help="write the report there instead of stdout "
                        "(the exit code is unchanged)")
    p.add_argument("--list-entries", action="store_true",
                   help="print the entry-point catalogue and exit")
    p.add_argument("--selftest", action="store_true",
                   help="prove the gate CAN fail: one injected "
                        "violation per contract class must fire; "
                        "exit 1 if any class stays green")
    return p


def main(argv: Optional[list] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)

    from gan_deeplearning4j_tpu.analysis import (
        contracts as contracts_mod,
        program as program_mod,
        reporters,
    )

    if args.list_entries:
        for name, entry in sorted(program_mod.all_entry_points().items()):
            print(f"{name}: {entry.summary}")
        return 0

    if args.selftest:
        result = contracts_mod.selftest()
        for cls, rec in result["classes"].items():
            verdict = ("FAILED-AS-EXPECTED" if rec["fired"]
                       else "DID-NOT-FIRE")
            print(f"gan4j-prove selftest: {cls}: {verdict}")
        print(f"gan4j-prove selftest: "
              f"{'ok' if result['ok'] else 'GATE CANNOT GO RED'}")
        return 0 if result["ok"] else 1

    names = ([e.strip() for e in args.entries.split(",") if e.strip()]
             if args.entries else None)
    try:
        report = contracts_mod.verify_repo(
            names=names, directory=args.contracts,
            write=args.write_contracts)
    except ValueError as e:
        print(f"gan4j-prove: error: {e}", file=sys.stderr)
        return 2
    if report["summary"]["entry_points"] == 0:
        # a prover that resolved nothing (single-device host asking
        # only for mesh entries, say) must not answer green
        for rec in report["skipped"]:
            print(f"gan4j-prove: skipped {rec['entry']}: "
                  f"{rec['reason']}", file=sys.stderr)
        print("gan4j-prove: error: zero entry points resolved — "
              "refusing to report a vacuous pass", file=sys.stderr)
        return 2

    if args.write_contracts:
        for name, rec in sorted(report["entries"].items()):
            print(f"gan4j-prove: contract written: {name} -> "
                  f"{rec['written']}")
        return 0

    rendered = (reporters.render_prove_json(report)
                if args.format == "json"
                else reporters.render_prove_human(report))
    if args.output:
        with open(args.output, "w") as f:
            f.write(rendered)
        s = report["summary"]
        print(f"gan4j-prove: {s['violations']} violation(s) over "
              f"{s['entry_points']} entry point(s) "
              f"({'ok' if s['ok'] else 'FAIL'}) -> {args.output}")
    else:
        sys.stdout.write(rendered)
    return 0 if report["summary"]["ok"] else 1


def cli(argv: Optional[list] = None) -> None:
    _force_cpu_topology()
    sys.exit(main(argv))


if __name__ == "__main__":
    cli()
