"""``gan4j-race`` console entry point — the concurrency gate.

The third pillar of the static-analysis story: gan4j-lint sees the AST,
gan4j-prove sees the lowered program, gan4j-race sees the THREADS AND
LOCKS — the whole-package acquisition-order graph, blocking calls made
under locks, and thread construction hygiene
(docs/STATIC_ANALYSIS.md § Concurrency discipline).  Same engine, exit
codes and baseline semantics as gan4j-lint, restricted to the
concurrency rule set (``rules_concurrency.RACE_RULES``):

  lock-order-cycle          potential deadlock across modules
  lock-held-blocking-call   slow op under a lock = fleet hang shape
  thread-hygiene            name= / explicit daemon= / bounded join
  unlocked-shared-write     the PR 6 single-class lock rule

Exit codes: 0 no active findings, 1 findings or parse errors, 2 usage
error.  With no paths, checks the installed package — ``gan4j-race``
alone IS the repo gate (tier1.yml race lane).  Suppressions use
``# gan4j-race: disable=<rule> — <reason>`` (the comment is the
justification record; same policy as gan4j-lint).  The runtime half of
the same contract is the ``lockdep()`` sanitizer
(analysis/sanitizers.py), which catches the dynamic-dispatch orderings
this static view cannot resolve.
"""

from __future__ import annotations

import sys
from typing import Optional

from gan_deeplearning4j_tpu.analysis import cli as lint_cli
from gan_deeplearning4j_tpu.analysis.rules_concurrency import RACE_RULES


def main(argv: Optional[list] = None) -> int:
    # allow_changed=False: a whole-package graph gate must not answer
    # from a --changed file subset (the cycle's other half may live in
    # an unchanged module) — and the full run costs under a second
    return lint_cli.main(argv, rule_subset=RACE_RULES,
                         prog="gan4j-race", description=__doc__,
                         allow_changed=False)


def cli(argv: Optional[list] = None) -> None:
    sys.exit(main(argv))


if __name__ == "__main__":
    cli()
