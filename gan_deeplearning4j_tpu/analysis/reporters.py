"""Report rendering for gan4j-lint AND gan4j-prove: human text + JSON.

Human format is the conventional ``path:line: rule: message`` one line
per finding (editors and CI log scrapers both parse it); JSON is the
CI-artifact format tier1.yml uploads — stable keys, a summary block,
and the full finding list including what was suppressed/baselined (the
gate keys on ``findings`` alone, but the artifact shows the whole
picture).  The prove renderers take the report document
``contracts.verify_repo`` returns and follow the same conventions:
one ``entry: class: field: message`` line per violation, a one-line
verdict, and the full facts in the JSON artifact."""

from __future__ import annotations

import json
from typing import Dict

from gan_deeplearning4j_tpu.analysis.engine import LintResult


def render_human(result: LintResult, verbose: bool = False,
                 tool: str = "gan4j-lint") -> str:
    lines = []
    for f in result.errors:
        lines.append(f"{f.path}:{f.line}: {f.rule}: {f.message}")
    for f in result.findings:
        lines.append(f"{f.path}:{f.line}: {f.rule}: {f.message}")
        if f.snippet:
            lines.append(f"    {f.snippet}")
    if verbose:
        for f in result.suppressed:
            lines.append(f"{f.path}:{f.line}: {f.rule}: suppressed "
                         f"inline: {f.message}")
        for f in result.baselined:
            lines.append(f"{f.path}:{f.line}: {f.rule}: baselined: "
                         f"{f.message}")
    lines.append(
        f"{tool}: {len(result.findings)} finding(s), "
        f"{len(result.suppressed)} suppressed, "
        f"{len(result.baselined)} baselined, "
        f"{len(result.errors)} parse error(s) "
        f"in {result.files_checked} file(s)")
    return "\n".join(lines) + "\n"


def render_json(result: LintResult, tool: str = "gan4j-lint") -> str:
    doc: Dict = {
        "tool": tool,
        "summary": {
            "findings": len(result.findings),
            "suppressed": len(result.suppressed),
            "baselined": len(result.baselined),
            "parse_errors": len(result.errors),
            "files_checked": result.files_checked,
            "ok": result.ok,
        },
        "findings": [f.to_dict() for f in result.findings],
        "suppressed": [f.to_dict() for f in result.suppressed],
        "baselined": [f.to_dict() for f in result.baselined],
        "errors": [f.to_dict() for f in result.errors],
    }
    return json.dumps(doc, indent=1) + "\n"


def render_prove_human(report: Dict) -> str:
    """One line per violation (``entry: class: field: message``), the
    per-entry verdicts, and a one-line summary — the terminal face of
    the prove gate."""
    lines = []
    for name in sorted(report["entries"]):
        rec = report["entries"][name]
        for v in rec["violations"]:
            lines.append(f"{v['entry']}: {v['contract_class']}: "
                         f"{v['field']}: {v['message']}")
    for rec in report.get("skipped", []):
        lines.append(f"gan4j-prove: skipped {rec['entry']}: "
                     f"{rec['reason']}")
    s = report["summary"]
    lines.append(
        f"gan4j-prove: {s['violations']} violation(s) over "
        f"{s['entry_points']} entry point(s), {s['skipped']} skipped "
        f"({'ok' if s['ok'] else 'FAIL'})")
    return "\n".join(lines) + "\n"


def render_prove_json(report: Dict) -> str:
    """The CI artifact: the full verify_repo document (facts included —
    the artifact shows what was measured, not just the verdict)."""
    return json.dumps(report, indent=1) + "\n"
