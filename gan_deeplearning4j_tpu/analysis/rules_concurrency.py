"""Concurrency lint rules for the thread-heavy ops layer.

The trainer shares state with six background threads (checkpoint
worker, prefetch worker, metrics logger, watchdog poller, exporter
handler threads, artifact writer).  Two bug classes have actually
bitten or nearly bitten:

* ``unlocked-shared-write`` — a shared attribute written outside the
  instance's lock (a torn read on a scrape thread is a wrong /healthz
  answer, not a crash — the worst kind);
* ``swallowed-exception``  — ``except: pass`` with no trace left.  The
  PR 4 restart-marker bug was exactly this shape (an over-narrow
  swallow masking real errors); the rule makes the pattern
  un-reintroducible without a written justification.

PR 9 adds the gan4j-race set on top — the whole-package view a
deadlock needs (one ``threading.Lock`` per class is survivable; the
ORDER two classes take each other's locks in is where the watchdog-bait
hangs live).  Built on the lock model in ``analysis/locks.py``:

* ``lock-order-cycle``      — a cycle in the package-wide acquisition-
  order graph (potential deadlock; both acquisition chains reported);
* ``lock-held-blocking-call`` — ``join``/queue ``get``/``put``/
  ``Event.wait``/``block_until_ready``/``device_fence``/``fsync``/
  socket ops under ``with self._lock`` — the exact shape that turns a
  slow save into a fleet hang;
* ``thread-hygiene``        — every ``threading.Thread`` names itself
  and states its daemon-ness, and a non-daemon thread has a bounded
  ``join`` reachable from a ``close()``/``stop()`` path.

Their suppressions use the ``# gan4j-race: disable=<rule> — <reason>``
prefix (same engine, same policy: the comment IS the justification).
``RACE_RULES`` below is the subset the ``gan4j-race`` CLI runs.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from gan_deeplearning4j_tpu.analysis.engine import (
    FileContext,
    Finding,
    Rule,
    last_segment,
    register,
)

# the subset the `gan4j-race` CLI runs (race_cli.py): the three
# whole-package lock rules plus the single-class lock rule they extend
RACE_RULES = ("lock-order-cycle", "lock-held-blocking-call",
              "thread-hygiene", "unlocked-shared-write")

# ONE lock-factory catalogue: analysis/locks.py owns the kind-map (it
# needs Lock-vs-RLock to honor reentrancy); this rule only needs the
# names — deriving the set keeps the two halves of the gate agreeing
# about what a lock is
from gan_deeplearning4j_tpu.analysis.locks import (  # noqa: E402
    LOCK_FACTORIES as _LOCK_FACTORY_KINDS,
)

LOCK_FACTORIES = frozenset(_LOCK_FACTORY_KINDS)
# methods exempt from the lock discipline: construction happens-before
# publication; *_locked is the repo's documented "caller holds the
# lock" convention (telemetry/exporter.py, telemetry/events.py).
EXEMPT_METHODS = {"__init__", "__new__", "__post_init__"}


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """self.<attr> names assigned a threading lock anywhere in the
    class (usually __init__)."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        if not (isinstance(node.value, ast.Call)
                and last_segment(node.value.func) in LOCK_FACTORIES):
            continue
        for t in node.targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                out.add(t.attr)
    return out


def _with_holds_lock(item: ast.withitem, locks: Set[str]) -> bool:
    """True when the with-item's context expression mentions one of the
    instance's lock attributes (``with self._lock:``, ``with
    self._lock, open(...)``, or a helper like
    ``self._lock.acquire_timeout(...)``)."""
    for node in ast.walk(item.context_expr):
        if (isinstance(node, ast.Attribute) and node.attr in locks
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return True
    return False


@register
class UnlockedSharedWrite(Rule):
    """In a class that OWNS a lock (``self._lock = threading.Lock()``
    et al.), every ``self.<attr> = ...`` in a regular method must
    happen inside ``with self._lock:`` (or a with-statement whose
    expression mentions the lock).  Exempt: ``__init__``-family methods
    (construction happens-before publication), methods named
    ``*_locked`` (the documented caller-holds-the-lock convention), the
    lock attributes themselves, and explicit ``.acquire()``-balanced
    regions the heuristic tracks within a straight-line body.

    The class owning a lock is the signal that its state IS shared —
    that is exactly when an unlocked write is a torn-read bug waiting
    for a scrape/worker thread to find it."""

    name = "unlocked-shared-write"
    summary = ("shared attribute written outside the instance's lock "
               "in a lock-owning class")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            locks = _lock_attrs(cls)
            if not locks:
                continue
            for method in cls.body:
                if not isinstance(method, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                    continue
                if (method.name in EXEMPT_METHODS
                        or method.name.endswith("_locked")):
                    continue
                self._check_body(method.body, locks, False, ctx,
                                 findings, method.name)
        return findings

    def _check_body(self, body: List[ast.stmt], locks: Set[str],
                    held: bool, ctx: FileContext,
                    findings: List[Finding], method: str) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested scope: its own thread context
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                now_held = held or any(
                    _with_holds_lock(i, locks) for i in stmt.items)
                self._check_body(stmt.body, locks, now_held, ctx,
                                 findings, method)
                continue
            # explicit acquire()/release() in straight-line code
            if self._is_lock_call(stmt, locks, "acquire"):
                held = True
                continue
            if self._is_lock_call(stmt, locks, "release"):
                held = False
                continue
            if isinstance(stmt, (ast.If,)):
                self._check_body(stmt.body, locks, held, ctx, findings,
                                 method)
                self._check_body(stmt.orelse, locks, held, ctx,
                                 findings, method)
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                self._check_body(list(stmt.body), locks, held, ctx,
                                 findings, method)
                self._check_body(list(stmt.orelse), locks, held, ctx,
                                 findings, method)
            elif isinstance(stmt, ast.Try):
                for block in (stmt.body, stmt.orelse, stmt.finalbody):
                    self._check_body(block, locks, held, ctx, findings,
                                     method)
                for handler in stmt.handlers:
                    self._check_body(handler.body, locks, held, ctx,
                                     findings, method)
            elif not held:
                self._flag_writes(stmt, locks, ctx, findings, method)

    @staticmethod
    def _is_lock_call(stmt: ast.stmt, locks: Set[str],
                      which: str) -> bool:
        return (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Call)
                and isinstance(stmt.value.func, ast.Attribute)
                and stmt.value.func.attr == which
                and isinstance(stmt.value.func.value, ast.Attribute)
                and stmt.value.func.value.attr in locks
                and isinstance(stmt.value.func.value.value, ast.Name)
                and stmt.value.func.value.value.id == "self")

    def _flag_writes(self, stmt: ast.stmt, locks: Set[str],
                     ctx: FileContext, findings: List[Finding],
                     method: str) -> None:
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        for t in targets:
            base: Optional[ast.AST] = t
            if isinstance(t, ast.Subscript):
                base = t.value  # self.d[k] = v mutates shared self.d
            if (isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self"
                    and base.attr not in locks):
                findings.append(ctx.finding(
                    self.name, stmt,
                    f"'self.{base.attr}' written outside the lock in "
                    f"'{method}' of a lock-owning class — take the "
                    f"lock, or rename the method '*_locked' if the "
                    f"caller holds it"))


@register
class SwallowedException(Rule):
    """Exception handlers that destroy the evidence:

    * a handler whose body is ONLY ``pass``/``...``/``continue`` —
      nothing logged, nothing recorded, nothing re-raised;
    * a BARE ``except:`` that does not re-raise — it also eats
      ``KeyboardInterrupt``/``SystemExit`` (and a watchdog's async-
      raised ``WatchdogTimeout``), turning every cancellation path
      into silence.

    Some swallows are legitimate (best-effort cleanup where the
    original error must not be masked) — those carry a justified
    ``# gan4j-lint: disable=swallowed-exception`` on the handler line,
    which doubles as the written record the review asks for anyway."""

    name = "swallowed-exception"
    summary = "except:-pass / bare except without re-raise"

    SILENT = (ast.Pass, ast.Continue, ast.Break)
    # exception classes that ARE control flow, not errors: catching and
    # dropping them is the documented way to poll a bounded queue or
    # drain an iterator — no evidence is destroyed
    CONTROL_FLOW = {"Empty", "Full", "StopIteration",
                    "StopAsyncIteration", "BlockingIOError",
                    "InterruptedError", "GeneratorExit"}

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if self._control_flow_only(node.type):
                continue
            silent = all(
                isinstance(s, self.SILENT)
                or (isinstance(s, ast.Expr)
                    and isinstance(s.value, ast.Constant))
                for s in node.body)
            if silent:
                what = ("bare except" if node.type is None
                        else "exception handler")
                findings.append(ctx.finding(
                    self.name, node,
                    f"{what} swallows the error with no trace — log "
                    f"it, record it, or re-raise (never-mask "
                    f"discipline, docs/STATIC_ANALYSIS.md)"))
                continue
            if node.type is None and not self._reraises(node):
                findings.append(ctx.finding(
                    self.name, node,
                    "bare except: catches KeyboardInterrupt/SystemExit "
                    "(and async-raised watchdog timeouts) — name the "
                    "exception class, or re-raise"))
        return findings

    @classmethod
    def _control_flow_only(cls, type_node) -> bool:
        if type_node is None:
            return False
        types = (type_node.elts if isinstance(type_node, ast.Tuple)
                 else [type_node])
        return all(last_segment(t) in cls.CONTROL_FLOW for t in types)

    @staticmethod
    def _reraises(handler: ast.ExceptHandler) -> bool:
        return any(isinstance(n, ast.Raise) for n in ast.walk(handler))


# -- the gan4j-race set (PR 9) — whole-package lock analysis ------------------


@register
class LockOrderCycle(Rule):
    """A cycle in the package-wide lock acquisition-order graph
    (analysis/locks.py): somewhere thread 1 can take A then B while
    thread 2 takes B then A — a potential deadlock no single file
    shows.  Each finding carries BOTH acquisition chains (file:line
    witness frames), anchored at the first chain's acquisition site.
    Reentrant (RLock) self-edges are exempt; a plain ``Lock`` acquired
    by code already holding it — directly or through a call chain — is
    reported as a self-cycle, the guaranteed single-thread deadlock."""

    name = "lock-order-cycle"
    summary = ("lock-order cycle across the package — a potential "
               "deadlock (both acquisition chains reported)")
    scope = "package"

    def check_package(self, ctxs) -> Iterable[Finding]:
        from gan_deeplearning4j_tpu.analysis.locks import (
            build_lock_model,
        )

        model = build_lock_model(ctxs)
        edges = model.acquisition_edges()
        findings: List[Finding] = []
        for cycle in model.lock_cycles():
            chains = []
            for i, edge in enumerate(cycle, 1):
                frames = edges.get(edge, [])
                chain = " -> ".join(fr.render() for fr in frames)
                chains.append(f"chain {i}: {chain}")
            order = " -> ".join([cycle[0][0]] + [b for _, b in cycle])
            anchor = edges.get(cycle[0], [None])[0]
            if anchor is None:
                continue
            ctx = ctxs.get(anchor.path)
            if ctx is None:
                continue
            findings.append(ctx.finding(
                self.name, anchor.line,
                ("potential deadlock: lock-order cycle "
                 f"{order}; " + "; ".join(chains)
                 + " — pick ONE order and document it "
                   "(docs/STATIC_ANALYSIS.md, concurrency discipline)")
                if len(cycle) > 1 else
                (f"self-deadlock: non-reentrant {cycle[0][0]} acquired "
                 f"while already held; {chains[0]} — use an RLock or "
                 f"the *_locked caller-holds-it convention")))
        return findings


@register
class LockHeldBlockingCall(Rule):
    """A blocking call — ``join``, queue ``get``/``put``,
    ``Event.wait``, ``block_until_ready``/``device_fence``, ``fsync``,
    ``sleep``, socket ops — made while a known lock is held, directly
    or through a statically resolvable call chain.  Every other thread
    needing that lock (a /healthz scrape, the watchdog's report feed, a
    worker handing off records) then stalls behind the slow operation:
    the exact shape that turns a slow checkpoint save into a
    fleet-wide hang.  Move the slow call outside the critical section
    (snapshot under the lock, do the work after — the pattern
    ``train/watchdog.py`` ``stop()`` documents)."""

    name = "lock-held-blocking-call"
    summary = ("blocking call (join/queue/wait/fence/fsync/socket) "
               "while holding a lock")
    scope = "package"

    def check_package(self, ctxs) -> Iterable[Finding]:
        from gan_deeplearning4j_tpu.analysis.locks import (
            build_lock_model,
        )

        model = build_lock_model(ctxs)
        findings: List[Finding] = []
        seen = set()
        for path, line, lock, desc, chain in model.held_blocking_sites():
            key = (path, line, lock)
            if key in seen:
                continue
            seen.add(key)
            ctx = ctxs.get(path)
            if ctx is None:
                continue
            via = " -> ".join(fr.render() for fr in chain)
            findings.append(ctx.finding(
                self.name, line,
                f"{desc} while holding {lock} ({via}) — every thread "
                f"needing the lock stalls behind it; move the blocking "
                f"call outside the critical section"))
        return findings


@register
class ThreadHygiene(Rule):
    """Every ``threading.Thread(...)`` must pass ``name=`` (a nameless
    thread is an unreadable flight record, an unattributable lock hold
    and an undebuggable stack dump) and an EXPLICIT ``daemon=`` (the
    default silently inherits the creator's daemon-ness — whether the
    process can exit while this thread runs is a decision, not an
    accident).  A ``daemon=False`` thread must additionally have a
    bounded ``join(timeout)`` reachable from a ``close()``/``stop()``
    path — a non-daemon thread nobody joins is a process that never
    exits."""

    name = "thread-hygiene"
    summary = ("threading.Thread without name=/explicit daemon=, or a "
               "non-daemon thread with no bounded join on a close path")
    scope = "package"

    def check_package(self, ctxs) -> Iterable[Finding]:
        from gan_deeplearning4j_tpu.analysis.locks import (
            build_lock_model,
        )

        model = build_lock_model(ctxs)
        findings: List[Finding] = []
        for site in model.threads:
            ctx = ctxs.get(site.path)
            if ctx is None:
                continue
            missing = []
            if not site.has_name:
                missing.append("name=")
            if not site.has_daemon:
                missing.append("explicit daemon=")
            if missing:
                findings.append(ctx.finding(
                    self.name, site.line,
                    f"threading.Thread in {site.func} without "
                    f"{' and '.join(missing)} — name the thread "
                    f"(flight records and lock reports key on it) and "
                    f"state its daemon-ness explicitly"))
            if site.daemon_false and not model.join_bounded(site):
                findings.append(ctx.finding(
                    self.name, site.line,
                    f"non-daemon thread in {site.func} with no bounded "
                    f"join(timeout) reachable from a close()/stop() "
                    f"path — an unjoined non-daemon thread is a "
                    f"process that never exits"))
        return findings
