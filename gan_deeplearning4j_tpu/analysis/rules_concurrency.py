"""Concurrency lint rules for the thread-heavy ops layer.

The trainer shares state with six background threads (checkpoint
worker, prefetch worker, metrics logger, watchdog poller, exporter
handler threads, artifact writer).  Two bug classes have actually
bitten or nearly bitten:

* ``unlocked-shared-write`` — a shared attribute written outside the
  instance's lock (a torn read on a scrape thread is a wrong /healthz
  answer, not a crash — the worst kind);
* ``swallowed-exception``  — ``except: pass`` with no trace left.  The
  PR 4 restart-marker bug was exactly this shape (an over-narrow
  swallow masking real errors); the rule makes the pattern
  un-reintroducible without a written justification.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from gan_deeplearning4j_tpu.analysis.engine import (
    FileContext,
    Finding,
    Rule,
    last_segment,
    register,
)

LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                  "BoundedSemaphore"}
# methods exempt from the lock discipline: construction happens-before
# publication; *_locked is the repo's documented "caller holds the
# lock" convention (telemetry/exporter.py, telemetry/events.py).
EXEMPT_METHODS = {"__init__", "__new__", "__post_init__"}


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """self.<attr> names assigned a threading lock anywhere in the
    class (usually __init__)."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        if not (isinstance(node.value, ast.Call)
                and last_segment(node.value.func) in LOCK_FACTORIES):
            continue
        for t in node.targets:
            if (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                out.add(t.attr)
    return out


def _with_holds_lock(item: ast.withitem, locks: Set[str]) -> bool:
    """True when the with-item's context expression mentions one of the
    instance's lock attributes (``with self._lock:``, ``with
    self._lock, open(...)``, or a helper like
    ``self._lock.acquire_timeout(...)``)."""
    for node in ast.walk(item.context_expr):
        if (isinstance(node, ast.Attribute) and node.attr in locks
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return True
    return False


@register
class UnlockedSharedWrite(Rule):
    """In a class that OWNS a lock (``self._lock = threading.Lock()``
    et al.), every ``self.<attr> = ...`` in a regular method must
    happen inside ``with self._lock:`` (or a with-statement whose
    expression mentions the lock).  Exempt: ``__init__``-family methods
    (construction happens-before publication), methods named
    ``*_locked`` (the documented caller-holds-the-lock convention), the
    lock attributes themselves, and explicit ``.acquire()``-balanced
    regions the heuristic tracks within a straight-line body.

    The class owning a lock is the signal that its state IS shared —
    that is exactly when an unlocked write is a torn-read bug waiting
    for a scrape/worker thread to find it."""

    name = "unlocked-shared-write"
    summary = ("shared attribute written outside the instance's lock "
               "in a lock-owning class")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            locks = _lock_attrs(cls)
            if not locks:
                continue
            for method in cls.body:
                if not isinstance(method, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                    continue
                if (method.name in EXEMPT_METHODS
                        or method.name.endswith("_locked")):
                    continue
                self._check_body(method.body, locks, False, ctx,
                                 findings, method.name)
        return findings

    def _check_body(self, body: List[ast.stmt], locks: Set[str],
                    held: bool, ctx: FileContext,
                    findings: List[Finding], method: str) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested scope: its own thread context
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                now_held = held or any(
                    _with_holds_lock(i, locks) for i in stmt.items)
                self._check_body(stmt.body, locks, now_held, ctx,
                                 findings, method)
                continue
            # explicit acquire()/release() in straight-line code
            if self._is_lock_call(stmt, locks, "acquire"):
                held = True
                continue
            if self._is_lock_call(stmt, locks, "release"):
                held = False
                continue
            if isinstance(stmt, (ast.If,)):
                self._check_body(stmt.body, locks, held, ctx, findings,
                                 method)
                self._check_body(stmt.orelse, locks, held, ctx,
                                 findings, method)
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                self._check_body(list(stmt.body), locks, held, ctx,
                                 findings, method)
                self._check_body(list(stmt.orelse), locks, held, ctx,
                                 findings, method)
            elif isinstance(stmt, ast.Try):
                for block in (stmt.body, stmt.orelse, stmt.finalbody):
                    self._check_body(block, locks, held, ctx, findings,
                                     method)
                for handler in stmt.handlers:
                    self._check_body(handler.body, locks, held, ctx,
                                     findings, method)
            elif not held:
                self._flag_writes(stmt, locks, ctx, findings, method)

    @staticmethod
    def _is_lock_call(stmt: ast.stmt, locks: Set[str],
                      which: str) -> bool:
        return (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Call)
                and isinstance(stmt.value.func, ast.Attribute)
                and stmt.value.func.attr == which
                and isinstance(stmt.value.func.value, ast.Attribute)
                and stmt.value.func.value.attr in locks
                and isinstance(stmt.value.func.value.value, ast.Name)
                and stmt.value.func.value.value.id == "self")

    def _flag_writes(self, stmt: ast.stmt, locks: Set[str],
                     ctx: FileContext, findings: List[Finding],
                     method: str) -> None:
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        for t in targets:
            base: Optional[ast.AST] = t
            if isinstance(t, ast.Subscript):
                base = t.value  # self.d[k] = v mutates shared self.d
            if (isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self"
                    and base.attr not in locks):
                findings.append(ctx.finding(
                    self.name, stmt,
                    f"'self.{base.attr}' written outside the lock in "
                    f"'{method}' of a lock-owning class — take the "
                    f"lock, or rename the method '*_locked' if the "
                    f"caller holds it"))


@register
class SwallowedException(Rule):
    """Exception handlers that destroy the evidence:

    * a handler whose body is ONLY ``pass``/``...``/``continue`` —
      nothing logged, nothing recorded, nothing re-raised;
    * a BARE ``except:`` that does not re-raise — it also eats
      ``KeyboardInterrupt``/``SystemExit`` (and a watchdog's async-
      raised ``WatchdogTimeout``), turning every cancellation path
      into silence.

    Some swallows are legitimate (best-effort cleanup where the
    original error must not be masked) — those carry a justified
    ``# gan4j-lint: disable=swallowed-exception`` on the handler line,
    which doubles as the written record the review asks for anyway."""

    name = "swallowed-exception"
    summary = "except:-pass / bare except without re-raise"

    SILENT = (ast.Pass, ast.Continue, ast.Break)
    # exception classes that ARE control flow, not errors: catching and
    # dropping them is the documented way to poll a bounded queue or
    # drain an iterator — no evidence is destroyed
    CONTROL_FLOW = {"Empty", "Full", "StopIteration",
                    "StopAsyncIteration", "BlockingIOError",
                    "InterruptedError", "GeneratorExit"}

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if self._control_flow_only(node.type):
                continue
            silent = all(
                isinstance(s, self.SILENT)
                or (isinstance(s, ast.Expr)
                    and isinstance(s.value, ast.Constant))
                for s in node.body)
            if silent:
                what = ("bare except" if node.type is None
                        else "exception handler")
                findings.append(ctx.finding(
                    self.name, node,
                    f"{what} swallows the error with no trace — log "
                    f"it, record it, or re-raise (never-mask "
                    f"discipline, docs/STATIC_ANALYSIS.md)"))
                continue
            if node.type is None and not self._reraises(node):
                findings.append(ctx.finding(
                    self.name, node,
                    "bare except: catches KeyboardInterrupt/SystemExit "
                    "(and async-raised watchdog timeouts) — name the "
                    "exception class, or re-raise"))
        return findings

    @classmethod
    def _control_flow_only(cls, type_node) -> bool:
        if type_node is None:
            return False
        types = (type_node.elts if isinstance(type_node, ast.Tuple)
                 else [type_node])
        return all(last_segment(t) in cls.CONTROL_FLOW for t in types)

    @staticmethod
    def _reraises(handler: ast.ExceptHandler) -> bool:
        return any(isinstance(n, ast.Raise) for n in ast.walk(handler))
