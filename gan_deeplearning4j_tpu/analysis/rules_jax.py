"""JAX-specific lint rules: the hazard classes generic linters miss.

Every rule here encodes a failure mode this codebase has either hit or
structurally depends on avoiding:

* ``prng-key-reuse``     — correlated randomness (the rollback replay's
                           ``fold_in`` discipline made checkable);
* ``tracer-side-effect`` — Python effects inside traced functions run
                           once at trace time, then never again;
* ``host-sync-in-hot-path`` — one silent ``float()`` in the fused loop
                           serializes a device round trip per step;
* ``recompile-hazard``   — jit-wraps in loops / per-call lambdas /
                           non-hashable statics, each a silent
                           recompile that eats the MFU headline.

Static analysis is heuristic by nature: each rule documents exactly
what it matches, and a justified ``# gan4j-lint: disable=<rule>``
(engine.py) is the escape hatch for the cases it cannot see past.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from gan_deeplearning4j_tpu.analysis.engine import (
    FileContext,
    Finding,
    Rule,
    bound_names,
    dotted_name,
    function_defs,
    last_segment,
    register,
    walk_skipping_defs,
)

# jax.random samplers that CONSUME a key (first positional argument).
SAMPLERS = {
    "uniform", "normal", "randint", "bernoulli", "permutation", "choice",
    "categorical", "gumbel", "truncated_normal", "laplace", "beta",
    "gamma", "poisson", "exponential", "bits", "rademacher", "cauchy",
    "dirichlet", "multivariate_normal", "t", "orthogonal", "ball",
    "loggamma", "rayleigh", "maxwell", "weibull_min", "double_sided_maxwell",
}
# derivation ops: take a key, return fresh key(s) — the FIX for reuse,
# so they never count as a consumption.
KEY_DERIVERS = {"split", "fold_in", "clone", "wrap_key_data"}
KEY_MAKERS = {"key", "PRNGKey"}

# transforms whose function argument is traced (side effects run once).
TRACE_WRAPPERS = {"jit", "pjit", "vmap", "pmap", "shard_map", "xmap"}
TRACE_ENTRY = TRACE_WRAPPERS | {
    "scan", "while_loop", "fori_loop", "cond", "switch", "checkpoint",
    "remat", "grad", "value_and_grad", "custom_vjp", "custom_jvp",
    "associative_scan", "map",
}

# callee names the hot-loop heuristic treats as "dispatches the step":
# the repo's step-callable naming convention plus anything locally bound
# from a jit/make_*_step constructor (detected per function).
STEP_CALLEE_NAMES = {"step", "step_fn", "run_step", "_fused_step",
                     "_fused_multi", "train_step", "fused_step"}
STEP_CONSTRUCTORS = {"jit", "pjit", "make_protocol_step", "make_multistep"}

# host-materialization calls that have no business inside a hot loop
HOST_SYNC_CALLS = {"asarray", "array"}      # on a numpy module alias
NUMPY_ALIASES = {"np", "numpy", "onp"}


def _is_trace_entry(func: ast.AST) -> bool:
    """True when the callee is a tracing entry point.  ``map`` and
    ``checkpoint`` collide with non-tracing names everywhere
    (``jax.tree.map``, checkpoint writers) — they only count with an
    explicit ``lax``/``jax`` module context."""
    seg = last_segment(func)
    if seg not in TRACE_ENTRY:
        return False
    if seg == "map":        # only jax.lax.map traces; jax.tree.map maps
        name = dotted_name(func) or ""
        return "lax" in name.split(".")[:-1]
    if seg == "checkpoint":  # only jax.checkpoint (remat) traces
        name = dotted_name(func) or ""
        return "jax" in name.split(".")[:-1]
    return True


def _is_random_chain(func: ast.AST) -> bool:
    """True when the callee's dotted chain goes through a ``random``
    module segment (``jax.random.uniform``, ``jrandom.split``) — the
    guard that keeps ``str.split`` and friends out of the key rules."""
    name = dotted_name(func)
    if name is None:
        return False
    segments = name.split(".")
    return "random" in segments[:-1] or segments[-1] in {
        "PRNGKey", "fold_in"}


def _sampler_call(node: ast.Call) -> Optional[str]:
    """The consumed key NAME when ``node`` is a jax.random sampler
    called with a Name as its key argument, else None."""
    seg = last_segment(node.func)
    if seg in SAMPLERS and _is_random_chain(node.func):
        if node.args and isinstance(node.args[0], ast.Name):
            return node.args[0].id
        for kw in node.keywords:
            if kw.arg == "key" and isinstance(kw.value, ast.Name):
                return kw.value.id
    return None


@register
class PrngKeyReuse(Rule):
    """A PRNG key consumed by two or more random ops without a
    ``split``/``fold_in`` between them, or consumed inside a loop whose
    body never derives a fresh key — both produce CORRELATED samples
    silently (jax keys are values, not stateful generators).

    Matching model (per function scope, module top level included):
    sequential statement walk tracking a per-name generation counter;
    any assignment to the name bumps it.  ``if``/``else`` branches are
    walked independently and merged by INTERSECTION (a key is "already
    consumed" afterwards only if every branch consumed it) — runtime
    executes one branch, so union would be a false positive."""

    name = "prng-key-reuse"
    summary = ("PRNG key consumed >= 2 times without split/fold_in "
               "(correlated randomness)")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        scopes = [ast.Module(body=ctx.tree.body, type_ignores=[])]
        scopes.extend(function_defs(ctx.tree))
        for scope in scopes:
            body = scope.body
            if not isinstance(scope, ast.Module):
                # nested defs get their own scope entry — skip them in
                # the parent's statement walk (_walk does too)
                pass
            gen: Dict[str, int] = {}
            consumed: Dict[Tuple[str, int], int] = {}
            self._walk(body, gen, consumed, findings, ctx)
        return findings

    # -- sequential consumption tracking --------------------------------------

    def _bump(self, stmt: ast.stmt, gen: Dict[str, int]) -> None:
        """Any assignment to a name starts a new key generation."""
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        for t in targets:
            for node in ast.walk(t):
                if isinstance(node, ast.Name):
                    gen[node.id] = gen.get(node.id, 0) + 1

    def _scan_expr(self, stmt: ast.AST, gen, consumed, findings,
                   ctx) -> None:
        for node in [stmt, *walk_skipping_defs(stmt)]:
            if not isinstance(node, ast.Call):
                continue
            key_name = _sampler_call(node)
            if key_name is None:
                continue
            ident = (key_name, gen.get(key_name, 0))
            first = consumed.get(ident)
            if first is not None:
                findings.append(ctx.finding(
                    self.name, node,
                    f"PRNG key '{key_name}' already consumed by a "
                    f"random op at line {first}; derive a fresh key "
                    f"(jax.random.split / fold_in) before reusing it"))
            else:
                consumed[ident] = node.lineno

    def _walk(self, body: List[ast.stmt], gen, consumed, findings,
              ctx) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # separate scope (checked independently)
            if isinstance(stmt, ast.If):
                # walk branches on INDEPENDENT copies, merge by
                # intersection (see class docstring)
                self._scan_expr(stmt.test, gen, consumed, findings, ctx)
                self._branches([stmt.body, stmt.orelse], gen, consumed,
                               findings, ctx)
            elif isinstance(stmt, ast.Match):
                # match/case: one case runs at runtime, same merge
                # discipline as if/else.  A non-exhaustive match may
                # run NO case, so the unchanged pre-match state joins
                # the intersection — unless the last case is an
                # unguarded wildcard (`case _:` / `case x:`), which
                # always matches
                self._scan_expr(stmt.subject, gen, consumed, findings,
                                ctx)
                bodies = [case.body for case in stmt.cases]
                last = stmt.cases[-1] if stmt.cases else None
                exhaustive = (
                    last is not None and last.guard is None
                    and isinstance(last.pattern, ast.MatchAs)
                    and last.pattern.pattern is None)
                if not exhaustive:
                    bodies.append([])
                self._branches(bodies, gen, consumed, findings, ctx)
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                self._loop(stmt, gen, consumed, findings, ctx)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._scan_expr(item.context_expr, gen, consumed,
                                    findings, ctx)
                self._walk(stmt.body, gen, consumed, findings, ctx)
            elif isinstance(stmt, ast.Try):
                self._walk(stmt.body, gen, consumed, findings, ctx)
                for handler in stmt.handlers:
                    self._walk(handler.body, gen, consumed, findings, ctx)
                self._walk(stmt.orelse, gen, consumed, findings, ctx)
                self._walk(stmt.finalbody, gen, consumed, findings, ctx)
            else:
                self._scan_expr(stmt, gen, consumed, findings, ctx)
                self._bump(stmt, gen)

    def _branches(self, bodies, gen, consumed, findings, ctx) -> None:
        """Walk mutually exclusive branch bodies on independent state
        copies, then merge: generations by max, consumptions by
        INTERSECTION (a key counts as already-consumed afterwards only
        if EVERY branch consumed it — runtime executes one)."""
        states = []
        for body in bodies:
            g, c = dict(gen), dict(consumed)
            self._walk(list(body), g, c, findings, ctx)
            states.append((g, c))
        gen.clear()
        for g, _ in states:
            for k, v in g.items():
                gen[k] = max(gen.get(k, 0), v)
        merged = states[0][1]
        for _, c in states[1:]:
            merged = {k: v for k, v in merged.items() if k in c}
        consumed.clear()
        consumed.update(merged)

    def _loop(self, stmt, gen, consumed, findings, ctx) -> None:
        """A sampler consumption inside a loop body is a reuse unless
        the loop body itself reassigns the key name (the per-iteration
        ``key, sub = split(key)`` idiom)."""
        if isinstance(stmt, ast.While):
            self._scan_expr(stmt.test, gen, consumed, findings, ctx)
        reassigned: Set[str] = set()
        for node in walk_skipping_defs(stmt):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            reassigned.add(n.id)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                for n in ast.walk(node.target):
                    if isinstance(n, ast.Name):
                        reassigned.add(n.id)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                for n in ast.walk(node.target):
                    if isinstance(n, ast.Name):
                        reassigned.add(n.id)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            loop_vars = {n.id for n in ast.walk(stmt.target)
                         if isinstance(n, ast.Name)}
        else:
            loop_vars = set()
        for node in walk_skipping_defs(stmt):
            if not isinstance(node, ast.Call):
                continue
            key_name = _sampler_call(node)
            if key_name is None:
                continue
            if key_name in reassigned:
                continue  # fresh key each iteration
            if key_name in loop_vars:
                continue  # iterating over pre-split keys
            findings.append(ctx.finding(
                self.name, node,
                f"PRNG key '{key_name}' consumed inside a loop without "
                f"a per-iteration split/fold_in — every iteration "
                f"draws the same randomness"))
        # after the loop, treat names consumed in the body as consumed
        self._walk(list(stmt.body), gen, consumed, [], ctx)


@register
class TracerSideEffect(Rule):
    """Python side effects inside a function handed to ``jit``/``vmap``/
    ``shard_map``/``scan``/... run ONCE at trace time and never again —
    the classic silently-wrong-after-warmup bug.  Flags, inside traced
    functions: ``global``/``nonlocal`` declarations, mutation calls
    (``append``/``extend``/``add``/``update``/...) on closed-over
    names, and subscript/attribute stores to closed-over names.

    "Traced" = decorated with a trace wrapper (``@jax.jit``, including
    through ``functools.partial``), or passed by name / as a lambda to
    one (``jax.jit(f)``, ``jax.lax.scan(f, ...)``)."""

    name = "tracer-side-effect"
    summary = ("Python side effect inside a jit/vmap/shard_map/scan-"
               "traced function (runs once at trace time)")

    MUTATORS = {"append", "extend", "insert", "add", "update",
                "setdefault", "remove", "discard", "clear", "pop",
                "popitem", "appendleft", "extendleft", "write"}

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        defs = {fn.name: fn for fn in function_defs(ctx.tree)}
        traced: List[ast.AST] = []
        for fn in defs.values():
            if any(self._is_trace_wrapper(d) for d in fn.decorator_list):
                traced.append(fn)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if not _is_trace_entry(node.func):
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Lambda):
                    traced.append(arg)
                elif isinstance(arg, ast.Name) and arg.id in defs:
                    traced.append(defs[arg.id])
        seen_ids = set()
        for fn in traced:
            if id(fn) in seen_ids:
                continue
            seen_ids.add(id(fn))
            findings.extend(self._check_traced(fn, ctx))
        return findings

    @staticmethod
    def _is_trace_wrapper(dec: ast.AST) -> bool:
        if last_segment(dec) in TRACE_WRAPPERS:
            return True
        # @partial(jax.jit, ...) / @functools.partial(shard_map, ...)
        if (isinstance(dec, ast.Call)
                and last_segment(dec.func) == "partial" and dec.args):
            return last_segment(dec.args[0]) in TRACE_WRAPPERS
        return False

    def _check_traced(self, fn, ctx: FileContext) -> Iterable[Finding]:
        local = bound_names(fn) if not isinstance(fn, ast.Lambda) else {
            a.arg for a in fn.args.args}
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        for stmt in body:
            for node in [stmt, *walk_skipping_defs(stmt)]:
                if isinstance(node, (ast.Global, ast.Nonlocal)):
                    kind = ("global" if isinstance(node, ast.Global)
                            else "nonlocal")
                    yield ctx.finding(
                        self.name, node,
                        f"{kind} mutation inside a traced function "
                        f"executes once at trace time, not per call")
                elif isinstance(node, ast.Call):
                    seg = last_segment(node.func)
                    if (seg in self.MUTATORS
                            and isinstance(node.func, ast.Attribute)
                            and isinstance(node.func.value, ast.Name)
                            and node.func.value.id not in local):
                        yield ctx.finding(
                            self.name, node,
                            f"'{node.func.value.id}.{seg}(...)' mutates "
                            f"closed-over state inside a traced "
                            f"function — the effect happens at trace "
                            f"time only")
                elif isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    for t in targets:
                        base: Optional[ast.AST] = None
                        if isinstance(t, (ast.Subscript, ast.Attribute)):
                            base = t.value
                        if (isinstance(base, ast.Name)
                                and base.id not in local):
                            yield ctx.finding(
                                self.name, t,
                                f"store into closed-over "
                                f"'{base.id}' inside a traced function "
                                f"— the effect happens at trace time "
                                f"only")


def _jit_bound_names(fn) -> Set[str]:
    """Local names bound from a jit/step-constructor call in ``fn`` —
    ``step = jax.jit(f)`` makes later ``step(...)`` calls step-like."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and last_segment(node.value.func) in STEP_CONSTRUCTORS):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _loop_calls_step(loop: ast.AST, step_names: Set[str]) -> bool:
    for node in walk_skipping_defs(loop):
        if isinstance(node, ast.Call):
            seg = last_segment(node.func)
            if seg in STEP_CALLEE_NAMES or seg in step_names:
                return True
    return False


@register
class HostSyncInHotPath(Rule):
    """Host synchronization inside a hot loop.  Two match classes:

    1. ``block_until_ready`` anywhere: on the tunneled PJRT backends
       this repo targets it is NOT a fence (utils/device.py) — use
       ``utils.device.device_fence`` / ``overlap_device_get``.
    2. Inside a HOT loop — one that dispatches a step callable
       (``step``/``step_fn``/``run_step``/``_fused_step``/
       ``_fused_multi``/... or any name locally bound from
       ``jax.jit``/``make_protocol_step``/``make_multistep``), or any
       loop in a function marked ``# gan4j-lint: hot-path`` —
       ``.item()``, ``float(...)``/``int(...)``, and numpy
       materialization (``np.asarray``/``np.array``) are flagged: each
       serializes a device round trip per iteration.  Materialize once
       after the loop, or hand the values to the async writer."""

    name = "host-sync-in-hot-path"
    summary = ("host sync (.item()/float()/np.asarray/"
               "block_until_ready) inside the hot loop")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Call)
                    and last_segment(node.func) == "block_until_ready"):
                findings.append(ctx.finding(
                    self.name, node,
                    "block_until_ready is not a reliable fence on "
                    "tunneled backends — use utils.device.device_fence "
                    "(readback) instead"))
        for fn in function_defs(ctx.tree):
            step_names = _jit_bound_names(fn)
            hot_fn = ctx.is_hot_marked(fn)
            for node in walk_skipping_defs(fn):
                if not isinstance(node, (ast.For, ast.AsyncFor,
                                         ast.While)):
                    continue
                if not (hot_fn or _loop_calls_step(node, step_names)):
                    continue
                findings.extend(self._check_loop_body(node, ctx))
        return findings

    def _check_loop_body(self, loop, ctx: FileContext):
        for node in walk_skipping_defs(loop):
            if not isinstance(node, ast.Call):
                continue
            seg = last_segment(node.func)
            if seg == "item" and isinstance(node.func, ast.Attribute):
                yield ctx.finding(
                    self.name, node,
                    ".item() in a hot loop blocks on a device->host "
                    "round trip every iteration — materialize after "
                    "the loop (utils.device.overlap_device_get)")
            elif (isinstance(node.func, ast.Name)
                    and node.func.id in {"float", "int"} and node.args):
                yield ctx.finding(
                    self.name, node,
                    f"{node.func.id}() in a hot loop forces a "
                    f"synchronous device readback per iteration — "
                    f"keep values on device until after the loop")
            elif (seg in HOST_SYNC_CALLS
                    and isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in NUMPY_ALIASES):
                yield ctx.finding(
                    self.name, node,
                    f"np.{seg}() in a hot loop materializes to host "
                    f"every iteration — batch the readback after the "
                    f"loop (utils.device.overlap_device_get)")


@register
class RecompileHazard(Rule):
    """Constructs that silently retrace/recompile a jitted program:

    1. jit/vmap/pmap/shard_map wrapping INSIDE a loop — a fresh
       callable (and compile-cache entry) per iteration;
    2. a lambda passed per-iteration to a trace entry point or to a
       locally jit-bound callable — fresh identity, fresh trace;
    3. a list/dict/set literal passed in a ``static_argnums`` position
       (or by ``static_argnames`` keyword) of a locally-bound jitted
       callable — unhashable static = TypeError at best, a retrace per
       call if converted blindly.

    The RecompileSentinel (analysis/sanitizers.py) is the RUNTIME half
    of this rule: whatever slips past the static patterns shows up as a
    post-warmup compile in bench ``--dryrun``."""

    name = "recompile-hazard"
    summary = ("jit-wrap inside a loop / per-call lambda / non-hashable "
               "static arg (silent recompiles)")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        scopes: List[ast.AST] = [ctx.tree, *function_defs(ctx.tree)]
        for scope in scopes:
            static_specs = self._static_specs(scope)
            jit_names = _jit_bound_names(scope) if not isinstance(
                scope, ast.Module) else set()
            for loop in walk_skipping_defs(scope):
                if not isinstance(loop, (ast.For, ast.AsyncFor,
                                         ast.While)):
                    continue
                findings.extend(
                    self._check_loop(loop, jit_names, ctx))
            findings.extend(self._check_statics(scope, static_specs, ctx))
        # dedupe (nested loops are walked from every enclosing scope)
        unique = {}
        for f in findings:
            unique[(f.line, f.message)] = f
        return list(unique.values())

    def _check_loop(self, loop, jit_names: Set[str], ctx: FileContext):
        for node in walk_skipping_defs(loop):
            if not isinstance(node, ast.Call):
                continue
            seg = last_segment(node.func)
            if seg in TRACE_WRAPPERS:
                yield ctx.finding(
                    self.name, node,
                    f"{seg}(...) inside a loop builds a fresh traced "
                    f"callable every iteration — hoist the wrap out of "
                    f"the loop")
                continue
            if (seg == "partial" and node.args
                    and last_segment(node.args[0]) in TRACE_WRAPPERS):
                yield ctx.finding(
                    self.name, node,
                    "partial(jit, ...) inside a loop builds a fresh "
                    "traced callable every iteration — hoist it")
                continue
            if _is_trace_entry(node.func) or seg in jit_names:
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    if isinstance(arg, ast.Lambda):
                        yield ctx.finding(
                            self.name, arg,
                            f"lambda passed to {seg}(...) inside a loop "
                            f"is a fresh callable identity per "
                            f"iteration — a retrace every call; define "
                            f"it once outside")

    def _static_specs(self, scope) -> Dict[str, Tuple[Set[int], Set[str]]]:
        """name -> (static positional indices, static kwarg names) for
        locals bound as ``f = jax.jit(g, static_argnums=..., ...)``."""
        specs: Dict[str, Tuple[Set[int], Set[str]]] = {}
        for node in walk_skipping_defs(scope):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and last_segment(node.value.func) in {"jit", "pjit"}):
                continue
            nums: Set[int] = set()
            names: Set[str] = set()
            for kw in node.value.keywords:
                if kw.arg == "static_argnums":
                    nums |= self._int_values(kw.value)
                elif kw.arg == "static_argnames":
                    names |= self._str_values(kw.value)
            if not nums and not names:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    specs[t.id] = (nums, names)
        return specs

    @staticmethod
    def _int_values(node) -> Set[int]:
        out: Set[int] = set()
        elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) \
            else [node]
        for e in elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.add(e.value)
        return out

    @staticmethod
    def _str_values(node) -> Set[str]:
        out: Set[str] = set()
        elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) \
            else [node]
        for e in elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.add(e.value)
        return out

    UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.SetComp,
                  ast.DictComp, ast.GeneratorExp)

    def _check_statics(self, scope, specs, ctx: FileContext):
        if not specs:
            return
        for node in walk_skipping_defs(scope):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in specs):
                continue
            nums, names = specs[node.func.id]
            for i, arg in enumerate(node.args):
                if i in nums and isinstance(arg, self.UNHASHABLE):
                    yield ctx.finding(
                        self.name, arg,
                        f"non-hashable literal in static_argnums "
                        f"position {i} of '{node.func.id}' — statics "
                        f"must be hashable (and a fresh object per "
                        f"call retraces)")
            for kw in node.keywords:
                if kw.arg in names and isinstance(kw.value,
                                                  self.UNHASHABLE):
                    yield ctx.finding(
                        self.name, kw.value,
                        f"non-hashable literal for static argname "
                        f"'{kw.arg}' of '{node.func.id}' — statics "
                        f"must be hashable")
