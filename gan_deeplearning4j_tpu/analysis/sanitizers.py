"""Runtime trace sanitizers — the dynamic half of gan4j-lint.

Static rules (rules_jax.py) catch the hazard PATTERNS; these two catch
whatever slips past them, on the real program:

* ``RecompileSentinel`` — counts XLA compiles via jax's compile-logging
  hook (the ``Compiling <name> ...`` record ``jax._src.interpreters.
  pxla`` emits on every cache miss; cache hits emit nothing — verified
  against jax 0.4).  ``arm()`` after warmup; every compile after that
  is a RECOMPILE: counted, exported as ``gan4j_recompiles_total``,
  traced as a ``compile.recompile`` event, and fatal in strict
  consumers (bench ``--dryrun`` ``sanitizer_ok``, the pytest fixture).
  The hook costs one logging-handler dispatch per COMPILE, not per
  step — zero steady-state overhead, safe to leave on in production
  (``--sanitize``).

* ``no_implicit_transfers`` — ``jax.transfer_guard("disallow")`` around
  the hot loop: any implicit host<->device transfer raises at the
  offending op (explicit ``jax.device_put`` stays allowed — staging IS
  explicit).  Platform note: on CPU backends device->host is zero-copy
  and does not trip the guard; host->device does.  On TPU both
  directions are guarded — the CI (CPU) gate therefore proves the
  host->device half and the TPU bench run proves both.

Wiring: bench ``--dryrun`` (``sanitizer_ok`` folded into ``ok``),
``GANTrainer(sanitize=True)`` / ``--sanitize`` (observational: metric +
event + warning, never kills a production run), and the
``recompile_sentinel`` / ``transfer_guard`` pytest fixtures
(tests/conftest.py).  docs/STATIC_ANALYSIS.md has the full contract.
"""

from __future__ import annotations

import logging
import threading
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional

# the logger that emits one "Compiling <name> with global shapes and
# types ..." record per XLA compile (DEBUG when jax_log_compiles is
# off, which is why the sentinel lowers the logger level instead of
# flipping that config flag and spamming stderr)
_COMPILE_LOGGER = "jax._src.interpreters.pxla"
_COMPILE_PREFIX = "Compiling "

RECOMPILE_METRIC = "gan4j_recompiles_total"
RECOMPILE_EVENT = "compile.recompile"


class RecompileError(RuntimeError):
    """A post-warmup recompile in a region that promised none."""


class TransferGuardError(RuntimeError):
    """An implicit host<->device transfer in a guarded hot loop."""


class _CompileLogHandler(logging.Handler):
    def __init__(self, sentinel: "RecompileSentinel"):
        super().__init__(level=logging.DEBUG)
        self._sentinel = sentinel

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:
            # best-effort: a malformed log record must not break
            # compilation itself (return-only, so outside the
            # swallowed-exception rule's pass/continue scope)
            return
        if msg.startswith(_COMPILE_PREFIX):
            name = msg[len(_COMPILE_PREFIX):].split(" ", 1)[0]
            self._sentinel._on_compile(name)


class RecompileSentinel:
    """Counts XLA compiles; any compile after ``arm()`` is a recompile.

    ``registry``: a telemetry MetricsRegistry — post-arm compiles
    increment ``gan4j_recompiles_total`` there.  ``step_fn``: optional
    step-number source stamped onto the ``compile.recompile`` event so
    the plot/live-UI overlays can place it on the step axis.
    ``on_recompile``: extra callback per post-arm compile (the trainer
    hangs its warning log here).

    Context-manager use installs/removes the logging hook; ``arm()``
    marks the end of the legitimate-compile window (post-warmup);
    ``check()`` raises ``RecompileError`` listing what recompiled.
    Thread-safe — compiles can land from any dispatching thread.

    Scoping: by default every post-arm compile anywhere in the process
    counts (right for a bench loop or a test body that owns the whole
    window).  A long-lived consumer whose process ALSO legitimately
    compiles auxiliary programs after warmup (the trainer's first
    eval-cadence inference program, a metrics reader) instead wraps
    only its hot dispatches in ``with sentinel.watch():`` — once any
    watch region has been used, post-arm compiles only count when the
    compiling thread is inside one (jit traces/compiles synchronously
    on the calling thread, so the thread-local scope is exact).
    Unwatched post-arm compiles are recorded in ``benign_compiles`` —
    visible, just not violations."""

    def __init__(self, registry=None,
                 step_fn: Optional[Callable[[], int]] = None,
                 on_recompile: Optional[Callable[[str], None]] = None):
        self.registry = registry
        self.step_fn = step_fn
        self.on_recompile = on_recompile
        self.compiles: List[str] = []       # warmup window
        self.recompiles: List[str] = []     # post-arm = violations
        self.benign_compiles: List[str] = []  # post-arm, outside watch
        self._armed = False
        self._watch_used = False
        self._tls = threading.local()
        self._lock = threading.Lock()
        self._handler: Optional[_CompileLogHandler] = None
        self._logger: Optional[logging.Logger] = None
        self._prev_level: Optional[int] = None
        self._prev_propagate: bool = True

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "RecompileSentinel":
        with self._lock:
            if self._handler is not None:
                return self
            self._logger = logging.getLogger(_COMPILE_LOGGER)
            self._handler = _CompileLogHandler(self)
            self._prev_level = self._logger.level
            # the compile record is emitted at DEBUG (with
            # jax_log_compiles off); lowering THIS logger's level routes
            # it to our handler without enabling the flag's stderr
            # warnings.  Root handlers sit at >= WARNING, so nothing
            # extra prints.
            if (self._prev_level == logging.NOTSET
                    or self._prev_level > logging.DEBUG):
                self._logger.setLevel(logging.DEBUG)
            # stop propagation while attached: jax installs its own
            # stderr handler on the parent "jax" logger, and the DEBUG
            # records we just unlocked would spam it — the sentinel is
            # the sole consumer for the duration
            self._prev_propagate = self._logger.propagate
            self._logger.propagate = False
            self._logger.addHandler(self._handler)
        if self.registry is not None:
            # the series must exist from the first scrape even if no
            # recompile ever happens (same discipline as nonfinite)
            self.registry.inc(RECOMPILE_METRIC, 0.0)
        return self

    def stop(self) -> None:
        with self._lock:
            if self._handler is None:
                return
            self._logger.removeHandler(self._handler)
            self._logger.setLevel(self._prev_level)
            self._logger.propagate = self._prev_propagate
            self._handler = None
            self._logger = None

    def __enter__(self) -> "RecompileSentinel":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- the hook -------------------------------------------------------------

    def arm(self) -> None:
        """End of the warmup window: every compile from here on is a
        recompile (the program was supposed to be cached)."""
        with self._lock:
            self._armed = True

    @property
    def armed(self) -> bool:
        return self._armed

    @contextmanager
    def watch(self):
        """Scope violation counting to this region (see class
        docstring): wrap exactly the hot dispatches whose programs
        must stay cached."""
        with self._lock:
            self._watch_used = True
        depth = getattr(self._tls, "depth", 0)
        self._tls.depth = depth + 1
        try:
            yield
        finally:
            self._tls.depth = depth

    def _on_compile(self, name: str) -> None:
        watched = getattr(self._tls, "depth", 0) > 0
        with self._lock:
            armed = self._armed
            if not armed:
                self.compiles.append(name)
            elif self._watch_used and not watched:
                # a legitimate first-time compile of an auxiliary
                # program (eval inference, a reader) — recorded, not a
                # violation of the hot path's cache promise
                self.benign_compiles.append(name)
                return
            else:
                self.recompiles.append(name)
        if not armed:
            return
        attrs: Dict = {"fn": name}
        if self.step_fn is not None:
            try:
                attrs["step"] = self.step_fn()
            except Exception:  # gan4j-lint: disable=swallowed-exception — a broken step source must not mask the recompile signal itself
                pass
        from gan_deeplearning4j_tpu.telemetry import events

        events.instant(RECOMPILE_EVENT, **attrs)
        if self.registry is not None:
            self.registry.inc(RECOMPILE_METRIC)
        if self.on_recompile is not None:
            self.on_recompile(name)

    # -- verdicts -------------------------------------------------------------

    @property
    def ok(self) -> bool:
        return not self.recompiles

    def check(self) -> None:
        if self.recompiles:
            raise RecompileError(
                f"{len(self.recompiles)} post-warmup recompile(s): "
                f"{', '.join(sorted(set(self.recompiles)))} — the hot "
                f"path promised a cached program (see "
                f"docs/STATIC_ANALYSIS.md, rule recompile-hazard)")


@contextmanager
def no_implicit_transfers():
    """``jax.transfer_guard("disallow")`` region: implicit host<->device
    transfers inside raise ``TransferGuardError`` naming the offender
    (explicit ``jax.device_put`` remains allowed).  Keep device fences/
    readbacks OUTSIDE the region — a readback is a transfer by design.

    Emits a ``transfer.violation`` instant event before re-raising, so
    the flight recorder carries the evidence even when a caller
    swallows the exception."""
    import jax

    from gan_deeplearning4j_tpu.telemetry import events

    try:
        with jax.transfer_guard("disallow"):
            yield
    except Exception as e:
        # jax raises XlaRuntimeError/RuntimeError with a "Disallowed
        # ... transfer" message; anything else is not the guard's
        if "isallowed" not in str(e):
            raise
        events.instant("transfer.violation", error=str(e)[:200])
        raise TransferGuardError(
            f"implicit transfer in a guarded hot-loop region: {e} "
            f"(see docs/STATIC_ANALYSIS.md, rule "
            f"host-sync-in-hot-path)") from e
