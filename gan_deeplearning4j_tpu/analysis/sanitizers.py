"""Runtime trace sanitizers — the dynamic half of gan4j-lint.

Static rules (rules_jax.py) catch the hazard PATTERNS; these two catch
whatever slips past them, on the real program:

* ``RecompileSentinel`` — counts XLA compiles via jax's compile-logging
  hook (the ``Compiling <name> ...`` record ``jax._src.interpreters.
  pxla`` emits on every cache miss; cache hits emit nothing — verified
  against jax 0.4).  ``arm()`` after warmup; every compile after that
  is a RECOMPILE: counted, exported as ``gan4j_recompiles_total``,
  traced as a ``compile.recompile`` event, and fatal in strict
  consumers (bench ``--dryrun`` ``sanitizer_ok``, the pytest fixture).
  The hook costs one logging-handler dispatch per COMPILE, not per
  step — zero steady-state overhead, safe to leave on in production
  (``--sanitize``).

* ``no_implicit_transfers`` — ``jax.transfer_guard("disallow")`` around
  the hot loop: any implicit host<->device transfer raises at the
  offending op (explicit ``jax.device_put`` stays allowed — staging IS
  explicit).  Platform note: on CPU backends device->host is zero-copy
  and does not trip the guard; host->device does.  On TPU both
  directions are guarded — the CI (CPU) gate therefore proves the
  host->device half and the TPU bench run proves both.

* ``lockdep`` (PR 9) — the runtime half of gan4j-race: while active,
  ``threading.Lock``/``RLock`` allocations return order-tracking
  proxies.  Each thread carries a held-set; every blocking acquisition
  of B while holding A adds the edge A->B to a global acquisition-order
  graph (keyed by ALLOCATION SITE — the lockdep "lock class", so two
  instances of the same registry share one node), and an acquisition
  that closes a cycle is an INVERSION: reported immediately with both
  stacks (the current one and the first witness of the reverse path),
  counted in ``gan4j_lock_inversions_total``, traced as a
  ``lock.inversion`` event.  Wait time paid blocking on tracked locks
  feeds ``gan4j_lock_wait_seconds_total``.  ``check()`` raises
  ``LockOrderError`` on inversions and ``ThreadLeakError`` when
  non-daemon threads born inside the window outlive it (the exit-time
  thread-leak audit).  Non-blocking (``acquire(False)``) probes never
  add edges — a trylock cannot deadlock.  Shipped as the ``lockdep``
  pytest fixture and, under ``GAN4J_LOCKDEP=1``, wrapped around every
  test in the chaos/supervision CI lanes (tests/conftest.py).

Wiring: bench ``--dryrun`` (``sanitizer_ok`` folded into ``ok``),
``GANTrainer(sanitize=True)`` / ``--sanitize`` (observational: metric +
event + warning, never kills a production run), and the
``recompile_sentinel`` / ``transfer_guard`` pytest fixtures
(tests/conftest.py).  docs/STATIC_ANALYSIS.md has the full contract.
"""

from __future__ import annotations

import logging
import os
import threading
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional

# the logger that emits one "Compiling <name> with global shapes and
# types ..." record per XLA compile (DEBUG when jax_log_compiles is
# off, which is why the sentinel lowers the logger level instead of
# flipping that config flag and spamming stderr)
_COMPILE_LOGGER = "jax._src.interpreters.pxla"
_COMPILE_PREFIX = "Compiling "

RECOMPILE_METRIC = "gan4j_recompiles_total"
RECOMPILE_EVENT = "compile.recompile"


class RecompileError(RuntimeError):
    """A post-warmup recompile in a region that promised none."""


class TransferGuardError(RuntimeError):
    """An implicit host<->device transfer in a guarded hot loop."""


class _CompileLogHandler(logging.Handler):
    def __init__(self, sentinel: "RecompileSentinel"):
        super().__init__(level=logging.DEBUG)
        self._sentinel = sentinel

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:
            # best-effort: a malformed log record must not break
            # compilation itself (return-only, so outside the
            # swallowed-exception rule's pass/continue scope)
            return
        if msg.startswith(_COMPILE_PREFIX):
            name = msg[len(_COMPILE_PREFIX):].split(" ", 1)[0]
            self._sentinel._on_compile(name)


class RecompileSentinel:
    """Counts XLA compiles; any compile after ``arm()`` is a recompile.

    ``registry``: a telemetry MetricsRegistry — post-arm compiles
    increment ``gan4j_recompiles_total`` there.  ``step_fn``: optional
    step-number source stamped onto the ``compile.recompile`` event so
    the plot/live-UI overlays can place it on the step axis.
    ``on_recompile``: extra callback per post-arm compile (the trainer
    hangs its warning log here).

    Context-manager use installs/removes the logging hook; ``arm()``
    marks the end of the legitimate-compile window (post-warmup);
    ``check()`` raises ``RecompileError`` listing what recompiled.
    Thread-safe — compiles can land from any dispatching thread.

    Scoping: by default every post-arm compile anywhere in the process
    counts (right for a bench loop or a test body that owns the whole
    window).  A long-lived consumer whose process ALSO legitimately
    compiles auxiliary programs after warmup (the trainer's first
    eval-cadence inference program, a metrics reader) instead wraps
    only its hot dispatches in ``with sentinel.watch():`` — once any
    watch region has been used, post-arm compiles only count when the
    compiling thread is inside one (jit traces/compiles synchronously
    on the calling thread, so the thread-local scope is exact).
    Unwatched post-arm compiles are recorded in ``benign_compiles`` —
    visible, just not violations."""

    def __init__(self, registry=None,
                 step_fn: Optional[Callable[[], int]] = None,
                 on_recompile: Optional[Callable[[str], None]] = None):
        self.registry = registry
        self.step_fn = step_fn
        self.on_recompile = on_recompile
        self.compiles: List[str] = []       # warmup window
        self.recompiles: List[str] = []     # post-arm = violations
        self.benign_compiles: List[str] = []  # post-arm, outside watch
        self._armed = False
        self._watch_used = False
        self._tls = threading.local()
        self._lock = threading.Lock()
        self._handler: Optional[_CompileLogHandler] = None
        self._logger: Optional[logging.Logger] = None
        self._prev_level: Optional[int] = None
        self._prev_propagate: bool = True

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "RecompileSentinel":
        with self._lock:
            if self._handler is not None:
                return self
            self._logger = logging.getLogger(_COMPILE_LOGGER)
            self._handler = _CompileLogHandler(self)
            self._prev_level = self._logger.level
            # the compile record is emitted at DEBUG (with
            # jax_log_compiles off); lowering THIS logger's level routes
            # it to our handler without enabling the flag's stderr
            # warnings.  Root handlers sit at >= WARNING, so nothing
            # extra prints.
            if (self._prev_level == logging.NOTSET
                    or self._prev_level > logging.DEBUG):
                self._logger.setLevel(logging.DEBUG)
            # stop propagation while attached: jax installs its own
            # stderr handler on the parent "jax" logger, and the DEBUG
            # records we just unlocked would spam it — the sentinel is
            # the sole consumer for the duration
            self._prev_propagate = self._logger.propagate
            self._logger.propagate = False
            self._logger.addHandler(self._handler)
        if self.registry is not None:
            # the series must exist from the first scrape even if no
            # recompile ever happens (same discipline as nonfinite)
            self.registry.inc(RECOMPILE_METRIC, 0.0)
        return self

    def stop(self) -> None:
        with self._lock:
            if self._handler is None:
                return
            self._logger.removeHandler(self._handler)
            self._logger.setLevel(self._prev_level)
            self._logger.propagate = self._prev_propagate
            self._handler = None
            self._logger = None

    def __enter__(self) -> "RecompileSentinel":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- the hook -------------------------------------------------------------

    def arm(self) -> None:
        """End of the warmup window: every compile from here on is a
        recompile (the program was supposed to be cached)."""
        with self._lock:
            self._armed = True

    @property
    def armed(self) -> bool:
        return self._armed

    @contextmanager
    def watch(self):
        """Scope violation counting to this region (see class
        docstring): wrap exactly the hot dispatches whose programs
        must stay cached."""
        with self._lock:
            self._watch_used = True
        depth = getattr(self._tls, "depth", 0)
        self._tls.depth = depth + 1
        try:
            yield
        finally:
            self._tls.depth = depth

    def _on_compile(self, name: str) -> None:
        watched = getattr(self._tls, "depth", 0) > 0
        with self._lock:
            armed = self._armed
            if not armed:
                self.compiles.append(name)
            elif self._watch_used and not watched:
                # a legitimate first-time compile of an auxiliary
                # program (eval inference, a reader) — recorded, not a
                # violation of the hot path's cache promise
                self.benign_compiles.append(name)
                return
            else:
                self.recompiles.append(name)
        if not armed:
            return
        attrs: Dict = {"fn": name}
        if self.step_fn is not None:
            try:
                attrs["step"] = self.step_fn()
            except Exception:  # gan4j-lint: disable=swallowed-exception — a broken step source must not mask the recompile signal itself
                pass
        from gan_deeplearning4j_tpu.telemetry import events

        events.instant(RECOMPILE_EVENT, **attrs)
        if self.registry is not None:
            self.registry.inc(RECOMPILE_METRIC)
        if self.on_recompile is not None:
            self.on_recompile(name)

    # -- verdicts -------------------------------------------------------------

    @property
    def ok(self) -> bool:
        return not self.recompiles

    def check(self) -> None:
        if self.recompiles:
            raise RecompileError(
                f"{len(self.recompiles)} post-warmup recompile(s): "
                f"{', '.join(sorted(set(self.recompiles)))} — the hot "
                f"path promised a cached program (see "
                f"docs/STATIC_ANALYSIS.md, rule recompile-hazard)")


LOCK_WAIT_METRIC = "gan4j_lock_wait_seconds_total"
LOCK_INVERSION_METRIC = "gan4j_lock_inversions_total"
LOCK_INVERSION_EVENT = "lock.inversion"


class LockOrderError(RuntimeError):
    """An observed lock-order inversion under the lockdep sanitizer."""


class ThreadLeakError(RuntimeError):
    """Non-daemon threads created inside a lockdep window were still
    alive at its end — a process that may never exit."""


class _LockProxy:
    """Order-tracking wrapper around one threading.Lock/RLock.

    Bookkeeping happens AFTER a successful inner acquire and after a
    successful inner release, never while the tracker's graph lock and
    the wrapped lock interleave the other way — the sanitizer must not
    introduce the bug class it hunts.  Once the owning tracker
    deactivates (uninstall), the proxy degrades to a plain forwarder;
    locks allocated during a window keep working forever after it."""

    __slots__ = ("_inner", "_dep", "site", "_reentrant", "__weakref__")

    def __init__(self, inner, dep: "LockdepSanitizer", site: str,
                 reentrant: bool):
        self._inner = inner
        self._dep = dep
        self.site = site
        self._reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1):
        dep = self._dep
        if dep is None or not dep.active or dep._in_hook():
            return self._inner.acquire(blocking, timeout)
        import time as _time

        t0 = _time.perf_counter()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            dep._acquired(self, blocking,
                          _time.perf_counter() - t0 if blocking else 0.0)
        return ok

    def release(self) -> None:
        self._inner.release()
        dep = self._dep
        if dep is not None and dep.active and not dep._in_hook():
            dep._released(self)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __getattr__(self, name):
        # RLock internals Condition probes for (_is_owned,
        # _release_save, _acquire_restore) forward to the real lock —
        # those paths bypass tracking, which is conservative, never
        # wrong (a missed edge, not a false inversion)
        return getattr(self._inner, name)


class LockdepSanitizer:
    """Runtime lock-order verifier (module docstring).  Use via the
    ``lockdep()`` context manager / pytest fixture; ``install()``/
    ``uninstall()`` patch and restore ``threading.Lock``/``RLock``.

    ``registry``: a telemetry MetricsRegistry — inversions increment
    ``gan4j_lock_inversions_total`` and blocking-acquire wait time
    accumulates into ``gan4j_lock_wait_seconds_total`` there (both
    pre-created at 0 so the series exist before the first incident).
    ``on_inversion``: extra callback per inversion report dict."""

    def __init__(self, registry=None, on_inversion=None,
                 stack_depth: int = 12):
        self.registry = registry
        self.on_inversion = on_inversion
        self.stack_depth = int(stack_depth)
        self.active = False
        self.inversions: List[Dict] = []
        self.acquisitions = 0              # proof the hook is alive
        self.wait_seconds = 0.0
        self.hold_seconds: Dict[str, float] = {}   # site -> total held
        # edge (site_a, site_b) -> first witness {thread, stack}
        self._edges: Dict = {}
        self._adj: Dict[str, set] = {}
        # inversion pairs already reported: one report per DISTINCT
        # (held, acquiring) pair — an inverted pair inside a step loop
        # must not flood the event log / grow memory per iteration
        self._reported: set = set()
        # id(proxy) -> live held entry [proxy, count, t0, holder_list].
        # threading.Lock explicitly permits release from ANY thread
        # (the handoff pattern), so release bookkeeping must find the
        # HOLDER's entry, not the releasing thread's — keyed here,
        # mutated only under the graph lock
        self._live: Dict[int, list] = {}
        self._tls = threading.local()
        # the graph lock is a RAW lock from the ORIGINAL factory — the
        # tracker must never route its own bookkeeping through a proxy
        self._orig: Dict[str, Callable] = {}
        self._graph_lock = threading.Lock()
        self._baseline_threads: set = set()

    # -- lifecycle ------------------------------------------------------------

    def install(self) -> "LockdepSanitizer":
        if self.active:
            return self
        with self._graph_lock:
            self._orig = {"Lock": threading.Lock,
                          "RLock": threading.RLock}
            self._baseline_threads = {
                t.ident for t in threading.enumerate()}
        dep = self

        def make_lock():
            return _LockProxy(dep._orig["Lock"](), dep,
                              dep._alloc_site("Lock"), reentrant=False)

        def make_rlock():
            return _LockProxy(dep._orig["RLock"](), dep,
                              dep._alloc_site("RLock"), reentrant=True)

        threading.Lock = make_lock
        threading.RLock = make_rlock
        with self._graph_lock:
            self.active = True
        if self.registry is not None:
            # both series visible from the first scrape, incident or not
            self.registry.inc(LOCK_INVERSION_METRIC, 0.0)
            self.registry.inc(LOCK_WAIT_METRIC, 0.0)
        return self

    def uninstall(self) -> None:
        if not self.active:
            return
        with self._graph_lock:
            self.active = False
            wait_total = self.wait_seconds
        threading.Lock = self._orig["Lock"]
        threading.RLock = self._orig["RLock"]
        if self.registry is not None and wait_total > 0.0:
            # one flush per window, outside any user lock (see
            # _acquired) — the series carries the window's total
            self.registry.inc(LOCK_WAIT_METRIC, wait_total)

    def __enter__(self) -> "LockdepSanitizer":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    def _alloc_site(self, kind: str) -> str:
        """dir/file:line of the Lock()/RLock() call — the lock-class
        identity the order graph is keyed on.  The parent directory is
        kept so two same-named files (utils/config.py vs
        server/config.py) cannot merge into one lock class — a merge
        would both exclude their real inversions (same-site pairs are
        skipped) and pair unrelated locks into false ones."""
        import traceback

        for frame in reversed(traceback.extract_stack(limit=8)[:-2]):
            fn = frame.filename
            if fn.endswith("sanitizers.py") or "threading" in fn:
                continue
            tail = "/".join(os.path.normpath(fn).split(os.sep)[-2:])
            return f"{tail}:{frame.lineno}({kind})"
        return f"?({kind})"

    # -- per-acquisition hooks -------------------------------------------------

    def _in_hook(self) -> bool:
        return getattr(self._tls, "in_hook", False)

    def _held(self) -> List:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _acquired(self, proxy: _LockProxy, blocking: bool,
                  waited: float) -> None:
        self._tls.in_hook = True
        try:
            import time as _time

            held = self._held()
            report = None
            with self._graph_lock:
                entry = self._live.get(id(proxy))
                if entry is not None:
                    if entry[3] is held:
                        entry[1] += 1   # reentrant re-acquire (RLock)
                        return
                    # stale entry from a holder whose release was never
                    # seen (pre-window acquire): adopt the lock fresh
                    if entry in entry[3]:
                        entry[3].remove(entry)
                self.acquisitions += 1
                if waited >= 50e-6:
                    # below ~50µs is uncontended acquire latency (plus
                    # proxy bookkeeping), not time spent BLOCKED — the
                    # series is a contention trend, not an op counter
                    self.wait_seconds += waited
                if blocking:
                    for e in held:
                        report = (self._add_edge_locked(e[0], proxy)
                                  or report)
                entry = [proxy, 1, _time.perf_counter(), held]
                self._live[id(proxy)] = entry
                held.append(entry)
            # wait time accumulates in self.wait_seconds (above, under
            # the graph lock) and flushes to the registry at
            # uninstall() — a per-acquire registry.inc here would take
            # the shared registry lock while the USER's lock is held,
            # serializing every proxied thread through one global lock
            # and inflating the very contention being measured
            if report is not None:
                self._report(report)
        finally:
            self._tls.in_hook = False

    def _released(self, proxy: _LockProxy) -> None:
        """Release bookkeeping resolves the HOLDER's entry via the live
        map — a Lock handed off and released by another thread (legal
        for threading.Lock) must not leave a phantom held entry on the
        acquiring thread."""
        self._tls.in_hook = True
        try:
            import time as _time

            with self._graph_lock:
                entry = self._live.get(id(proxy))
                if entry is None:
                    return  # acquired before the window: untracked
                entry[1] -= 1
                if entry[1] > 0:
                    return
                dt = _time.perf_counter() - entry[2]
                self.hold_seconds[proxy.site] = (
                    self.hold_seconds.get(proxy.site, 0.0) + dt)
                del self._live[id(proxy)]
                if entry in entry[3]:
                    entry[3].remove(entry)
        finally:
            self._tls.in_hook = False

    def _add_edge_locked(self, held_proxy: _LockProxy,
                         new_proxy: _LockProxy) -> Optional[Dict]:
        """Record held->new in the site graph (caller holds the graph
        lock — the *_locked convention); returns an inversion report
        when the reverse path already exists.  Same-site different-instance pairs are skipped — the
        classic lockdep false positive (two queues born on one line)."""
        a, b = held_proxy.site, new_proxy.site
        if a == b or (a, b) in self._reported:
            return None
        import traceback

        if (a, b) not in self._edges:
            self._edges[(a, b)] = {
                "thread": threading.current_thread().name,
                "stack": "".join(traceback.format_stack(
                    limit=self.stack_depth)[:-3]),
            }
            self._adj.setdefault(a, set()).add(b)
        # inversion iff b can already reach a: taking b while holding a
        # closes the cycle a -> b -> ... -> a (one BFS implementation,
        # shared with the static model)
        from gan_deeplearning4j_tpu.analysis.locks import shortest_path

        path = shortest_path(self._adj, b, a)
        if path is None:
            return None
        self._reported.add((a, b))
        witness = self._edges.get((path[0], path[1])) or {}
        return {
            "lock_acquiring": b, "lock_held": a,
            "cycle": [a] + path,  # a -> b -> ... -> a
            "thread": threading.current_thread().name,
            "stack": "".join(traceback.format_stack(
                limit=self.stack_depth)[:-3]),
            "prior_thread": witness.get("thread"),
            "prior_stack": witness.get("stack"),
        }

    def _report(self, report: Dict) -> None:
        """An inversion reports IMMEDIATELY (metric + event + record),
        with both stacks — the observing run may be about to deadlock
        on exactly this pair."""
        self.inversions.append(report)
        if self.registry is not None:
            self.registry.inc(LOCK_INVERSION_METRIC)
        from gan_deeplearning4j_tpu.telemetry import events

        events.instant(LOCK_INVERSION_EVENT,
                       acquiring=report["lock_acquiring"],
                       held=report["lock_held"],
                       thread=report["thread"])
        if self.on_inversion is not None:
            self.on_inversion(report)

    # -- verdicts -------------------------------------------------------------

    def leaked_threads(self) -> List[threading.Thread]:
        """Non-daemon threads born after install() and still alive —
        the exit-time audit half of the thread-hygiene rule."""
        return [t for t in threading.enumerate()
                if t.ident not in self._baseline_threads
                and t.is_alive() and not t.daemon]

    @property
    def ok(self) -> bool:
        return not self.inversions

    def report(self) -> Dict:
        with self._graph_lock:
            return {"acquisitions": self.acquisitions,
                    "edges": len(self._edges),
                    "inversions": len(self.inversions),
                    "wait_seconds": round(self.wait_seconds, 6),
                    "hold_seconds": {k: round(v, 6) for k, v
                                     in self.hold_seconds.items()}}

    def check(self, threads: bool = True) -> None:
        """Raise on any observed inversion (both stacks in the message)
        and, with ``threads=True``, on leaked non-daemon threads."""
        if self.inversions:
            r = self.inversions[0]
            raise LockOrderError(
                f"{len(self.inversions)} lock-order inversion(s); "
                f"first: acquiring {r['lock_acquiring']} while holding "
                f"{r['lock_held']} on thread {r['thread']} inverts the "
                f"established order (first taken the other way on "
                f"thread {r['prior_thread']}).\n"
                f"--- current acquisition stack ---\n{r['stack']}"
                f"--- prior (reverse-order) stack ---\n"
                f"{r['prior_stack']}"
                f"(see docs/STATIC_ANALYSIS.md, rule lock-order-cycle)")
        if threads:
            leaked = self.leaked_threads()
            if leaked:
                names = ", ".join(t.name for t in leaked)
                raise ThreadLeakError(
                    f"{len(leaked)} non-daemon thread(s) created in "
                    f"this lockdep window still alive at its end: "
                    f"{names} — join them from a close()/stop() path "
                    f"(rule thread-hygiene)")


@contextmanager
def lockdep(registry=None, strict: bool = True, threads: bool = True):
    """Context-managed lockdep window: patch lock allocation on entry,
    restore on exit; with ``strict`` (default) re-raise any observed
    inversion / thread leak at exit via ``check()``.  The pytest
    fixture (tests/conftest.py) and the chaos/supervision CI lanes
    (``GAN4J_LOCKDEP=1``) are the standing consumers."""
    dep = LockdepSanitizer(registry=registry)
    dep.install()
    try:
        yield dep
    finally:
        dep.uninstall()
    if strict:
        dep.check(threads=threads)


@contextmanager
def no_implicit_transfers():
    """``jax.transfer_guard("disallow")`` region: implicit host<->device
    transfers inside raise ``TransferGuardError`` naming the offender
    (explicit ``jax.device_put`` remains allowed).  Keep device fences/
    readbacks OUTSIDE the region — a readback is a transfer by design.

    Emits a ``transfer.violation`` instant event before re-raising, so
    the flight recorder carries the evidence even when a caller
    swallows the exception."""
    import jax

    from gan_deeplearning4j_tpu.telemetry import events

    try:
        with jax.transfer_guard("disallow"):
            yield
    except Exception as e:
        # jax raises XlaRuntimeError/RuntimeError with a "Disallowed
        # ... transfer" message; anything else is not the guard's
        if "isallowed" not in str(e):
            raise
        events.instant("transfer.violation", error=str(e)[:200])
        raise TransferGuardError(
            f"implicit transfer in a guarded hot-loop region: {e} "
            f"(see docs/STATIC_ANALYSIS.md, rule "
            f"host-sync-in-hot-path)") from e
