"""Benchmark: DCGAN-on-MNIST full-protocol training throughput (img/sec).

The BASELINE.json north-star metric: the reference publishes no throughput
(BASELINE.md), so the baseline is the same three-graph protocol executed on
the host CPU (the stand-in for the reference's nd4j-native CPU run, which
cannot execute here).  The CPU number is measured once and cached in
``BENCH_BASELINE.json``; the benchmark then runs on the default JAX
platform (the TPU when attached) and reports the ratio.

Prints ONE JSON line:
  {"metric": "dcgan_mnist_img_per_sec", "value": N, "unit": "img/sec/chip",
   "vs_baseline": N, "mfu": N, "e2e_img_per_sec": N, ...}

``value`` is the fused protocol-step throughput on device-resident data;
``e2e_img_per_sec`` is the same protocol through the real trainer loop at
its defaults (device-resident dataset, on-device batch slicing) and
``e2e_stream_img_per_sec`` through the streaming path (CSV batches,
prefetch thread, per-step host->device transfer) — the stream/value gap
is the data pipeline's cost.  ``mfu`` divides the XLA cost model's FLOPs
for the compiled step by measured step time and the chip's bf16 peak;
note f32 convs execute at DEFAULT (bf16-multiply) precision on the MXU
and the cost model counts pre-fusion FLOPs, so treat it as approximate.

Flags: --profile DIR captures a jax.profiler trace of the timed section.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
from typing import Optional

def _baseline_path() -> str:
    """The cached-CPU-baseline location: the repo root (parent of the
    package dir) for a checkout — where the committed cache lives —
    falling back to the working directory when that dir isn't writable
    (installed wheel: site-packages ships no cache, may be read-only)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cached = os.path.join(root, "BENCH_BASELINE.json")
    if os.path.exists(cached):
        return cached
    # no committed cache next to the package (installed wheel): the
    # working directory is the cache home — never write site-packages
    return os.path.join(os.getcwd(), "BENCH_BASELINE.json")


BASELINE_PATH = _baseline_path()
# batchSizePerWorker (dl4jGANComputerVision.java:59).  DEFAULT_BATCH /
# DRYRUN_BATCH / FAST_BATCH / CELEBA_BATCH are the bench's complete set
# of protocol batch shapes — gan4j-prove's bucket-coverage contract
# (analysis/program.py reachable_protocol_batches) enumerates THESE
# constants, so adding a new dispatch shape without a contract diff is
# a red prove, not a silent recompile.
DEFAULT_BATCH = 200
BATCH = DEFAULT_BATCH
WARMUP = 3
STEPS_LO = 30
STEPS_HI = 180
REPEATS = 3
# 300 steps = 3 chunks of the trainer's auto steps_per_call (100): the
# steady window then spans whole chunks and the per-chunk dispatch gap
# (a tunnel round trip here) amortizes as it does in real multi-thousand
# -iteration runs; at 60 steps the window was 2 chunks of 20 and the
# gap dominated the measurement.
E2E_STEPS = 300
# the documented TPU fast mode measured alongside the reference-numerics
# default: s2d/d2s conv rewrites + bf16 MXU operands + full mixed
# precision (f32 master params/BN/loss) — runtime/backend.py
FAST_BATCH = 1600
# the --dryrun smoke's toy batch and the CelebA block's default —
# both part of the bucket-coverage contract (see DEFAULT_BATCH note)
DRYRUN_BATCH = 8
CELEBA_BATCH = 128
# Bump when the measured step's methodology changes; a cached baseline
# from another version is discarded and re-measured (apples to apples).
# v5: readback-fenced slope timing — jax.block_until_ready is a NO-OP on
# the tunneled axon PJRT backend (verified: returns in 0.1ms with seconds
# of queued work), so each timed window ends with a scalar loss readback
# (the only reliable device fence) and the step time is the SLOPE between
# a short and a long window, cancelling the ~70ms tunnel round trip.
# v6: ``value`` is the MULTISTEP (steps_per_call) throughput — the
# trainer's actual default execution path, and the reproducible number:
# the single-dispatch rate rides the shared tunnel's load (observed
# 34k-99k img/s across days ON THE SAME CODE) and is reported separately
# as single_dispatch_img_per_sec.  The CPU baseline is unchanged in kind
# (per-step time on CPU, where dispatch overhead is negligible).
# v7: every multistep timer sizes its windows ADAPTIVELY to ~3s of
# device work (hlo_cost.py's recipe — the r5 celeba capture's fixed
# 6-call window left an 11% min/max spread riding the tunnel) and the
# JSON carries a median±IQR spread block per capture; the headline stays
# the median slope, so v6 numbers remain comparable.
METHODOLOGY_VERSION = 7

# Adaptive-window slope timing (the hlo_cost.py --measure recipe): a
# fenced window must hold SECONDS of device work or the tunnel's ~0.1s
# round-trip noise rides the slope (the r5 celeba_multistep_time bug:
# fixed windows of 2/6 calls -> 11% spread between repeat sets).
WINDOW_TARGET_S = 3.0


def _adaptive_windows(t_call: float,
                      target_s: float = WINDOW_TARGET_S) -> tuple:
    """(lo, hi) call counts sized so the hi window holds ~``target_s``
    of work: hi = clamp(target/t_call, 4, 60), lo = hi//5 (>=1).  The
    slope between them cancels the per-window fence round trip."""
    t_call = max(t_call, 1e-3)
    hi = max(4, min(60, int(target_s / t_call)))
    lo = max(1, hi // 5)
    return lo, hi


def _slope_stats(window, k: int, repeats: int,
                 target_s: float = WINDOW_TARGET_S) -> dict:
    """Median ± IQR per-step slope seconds over ``repeats`` slope sets
    with adaptively sized windows.  ``window(n)`` runs n fenced calls
    of a k-step program and returns wall seconds; the first (sizing)
    call doubles as extra warmup.  Returns the spread block every
    BENCH_*.json capture carries: the median is the headline, the IQR
    is the stability evidence the regression gate scales by."""
    import statistics

    lo, hi = _adaptive_windows(window(1), target_s)
    slopes = []
    for _ in range(max(1, repeats)):
        t_lo = window(lo)
        t_hi = window(hi)
        slopes.append((t_hi - t_lo) / ((hi - lo) * k))
    med = statistics.median(slopes)
    if len(slopes) >= 2:
        q1, _, q3 = statistics.quantiles(slopes, n=4, method="inclusive")
        iqr = q3 - q1
    else:
        iqr = 0.0
    return {
        "seconds": med,
        "spread": {
            "median_ms": round(med * 1e3, 4),
            "iqr_ms": round(iqr * 1e3, 4),
            "min_ms": round(min(slopes) * 1e3, 4),
            "max_ms": round(max(slopes) * 1e3, 4),
            "repeats": len(slopes),
            "window_calls": [lo, hi],
            "window_steps_per_call": k,
        },
    }

# Dense bf16 peak FLOP/s by TPU generation (the conventional MFU
# denominator).  This benchmark computes in float32, which the MXU
# executes below bf16 peak — so the reported MFU is conservative.
_PEAK_FLOPS = {
    "v4": 275e12,
    "v5 lite": 197e12,   # v5e
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,   # v6e (Trillium)
    "v6e": 918e12,
}


def _peak_flops(device) -> float | None:
    kind = getattr(device, "device_kind", "").lower()
    for key, peak in _PEAK_FLOPS.items():
        if key in kind:
            return peak
    return None


def _build_step_and_args(device):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gan_deeplearning4j_tpu.models import dcgan_mnist as M
    from gan_deeplearning4j_tpu.train import fused_step as fused

    dis, gen, gan = (
        M.build_discriminator(), M.build_generator(), M.build_gan())
    classifier = M.build_classifier(dis)
    rng = np.random.RandomState(0)
    ones = jnp.ones((BATCH, 1), dtype=jnp.float32)
    # pre-softened target vectors (label softening is loop-invariant,
    # dl4jGANComputerVision.java:384-385); latent draws happen inside the
    # step (z ~ U[-1,1] under a counter-based key stream,
    # dl4jGANComputerVision.java:397,425)
    key = jax.random.key(0)
    step = fused.make_protocol_step(
        dis, gen, gan, classifier,
        M.DIS_TO_GAN, M.GAN_TO_GEN, M.DIS_TO_CLASSIFIER,
        z_size=2, num_features=784,
    )
    # committed state: the program's outputs are committed, so an
    # uncommitted initial state would change the arg-sharding signature
    # after call 1 and trigger a full recompile inside the timed window
    state = jax.device_put(
        fused.state_from_graphs(dis, gen, gan, classifier), device)
    real = jax.device_put(rng.rand(BATCH, 784).astype(np.float32), device)
    labels = jax.device_put(
        np.eye(10, dtype=np.float32)[rng.randint(0, 10, BATCH)], device)
    invariants = (
        key, jax.random.fold_in(key, 1),
        ones + 0.05 * jnp.asarray(rng.randn(BATCH, 1), jnp.float32),
        0.05 * jnp.asarray(rng.randn(BATCH, 1), jnp.float32),
        ones,
    )
    return step, state, real, labels, invariants


def _fence(tree) -> None:
    """A reliable device fence: readback of one (scalar) leaf.  On the
    tunneled axon backend ``jax.block_until_ready`` returns immediately
    with work still queued — only an actual transfer waits for in-order
    completion of everything dispatched before it."""
    from gan_deeplearning4j_tpu.utils import device_fence

    device_fence(tree)


def protocol_step_time(device, want_flops: bool = False,
                       steps_lo: int = STEPS_LO, steps_hi: int = STEPS_HI,
                       repeats: int = REPEATS):
    """Median-of-``repeats`` SLOPE seconds per full GAN-protocol iteration
    (D-step + syncs + G-step + classifier step, batch 200) on the given
    device, using the framework's fused one-XLA-program step
    (train/fused_step.py).  Each timed window dispatches N steps and ends
    with a scalar loss readback; the per-step time is
    (t(steps_hi) - t(steps_lo)) / (steps_hi - steps_lo), which cancels
    the readback round trip and any constant dispatch overhead.
    Returns (seconds, flops_per_step_or_None)."""
    import jax

    with jax.default_device(device):
        step, state, real, labels, inv = _build_step_and_args(device)

        flops = None
        if want_flops:
            try:
                cost = step.lower(
                    state, real, labels, *inv).compile().cost_analysis()
                flops = float(cost.get("flops", 0.0)) or None
            except Exception:
                flops = None

        import statistics

        for _ in range(WARMUP):
            state, losses = step(state, real, labels, *inv)
        _fence(losses)

        def window(n):
            nonlocal state
            t0 = time.perf_counter()
            losses = None
            for _ in range(n):
                state, losses = step(state, real, labels, *inv)
            _fence(losses)
            return time.perf_counter() - t0

        slopes = []
        for _ in range(repeats):
            t_lo = window(steps_lo)
            t_hi = window(steps_hi)
            slopes.append((t_hi - t_lo) / (steps_hi - steps_lo))
        return statistics.median(slopes), flops


def protocol_multistep_time(device, k: Optional[int] = None,
                            repeats: int = REPEATS,
                            want_flops: bool = False,
                            batch: Optional[int] = None,
                            telemetry: bool = False,
                            carry_dedup: bool = True,
                            detail: bool = False,
                            target_s: float = WINDOW_TARGET_S):
    """Seconds per protocol step when ONE dispatch advances ``k`` steps
    (lax.scan inside the program, device-resident data — the trainer's
    steps_per_call fast path).  Removes the per-dispatch latency bound
    that protocol_step_time includes; the gap between the two numbers IS
    the dispatch overhead.  Windows are sized adaptively to ~``target_s``
    of device work (``_slope_stats``).

    ``telemetry``: measure the program WITH the in-graph numerics block
    (norms/NaN counters, train/fused_step.py) — the stacked telemetry
    outputs stay on device (only a loss fences each window), so this
    times exactly what a telemetry-on trainer dispatches.

    ``carry_dedup``: False measures the pre-restructure scan carry (the
    mirrored-W/b per-step HBM copies) — the overlap series' A/B
    baseline.  ``detail``: return ``{"seconds", "flops", "spread"}``
    instead of the bare float / (t, flops) pair."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gan_deeplearning4j_tpu.models import dcgan_mnist as M
    from gan_deeplearning4j_tpu.train import fused_step as fused

    if k is None:
        k = fused.MAX_STEPS_PER_CALL  # the trainer's own chunk size
    b = batch if batch is not None else BATCH

    with jax.default_device(device):
        dis, gen, gan = (
            M.build_discriminator(), M.build_generator(), M.build_gan())
        classifier = M.build_classifier(dis)
        rng = np.random.RandomState(0)
        ones = jnp.ones((b, 1), dtype=jnp.float32)
        key = jax.random.key(0)
        step = fused.make_protocol_step(
            dis, gen, gan, classifier,
            M.DIS_TO_GAN, M.GAN_TO_GEN, M.DIS_TO_CLASSIFIER,
            z_size=2, num_features=784,
            data_on_device=True, steps_per_call=k, telemetry=telemetry,
            carry_dedup=carry_dedup,
        )

        def run_step(state, *args):
            out = step(state, *args)
            # telemetry rides as ((losses), tel); only losses are fenced
            return (out[0], out[1][0]) if telemetry else out
        state = jax.device_put(  # committed: keep one signature across calls
            fused.state_from_graphs(dis, gen, gan, classifier), device)
        table = jax.device_put(
            rng.rand(4 * b, 784).astype(np.float32), device)
        labels = jax.device_put(
            np.eye(10, dtype=np.float32)[rng.randint(0, 10, 4 * b)],
            device)
        inv = (
            key, jax.random.fold_in(key, 1),
            ones + 0.05 * jnp.asarray(rng.randn(b, 1), jnp.float32),
            0.05 * jnp.asarray(rng.randn(b, 1), jnp.float32),
            ones,
        )

        flops = None
        if want_flops or detail:
            try:
                cost = step.lower(
                    state, table, labels, *inv).compile().cost_analysis()
                # XLA's cost model counts a while/scan BODY once (verified:
                # the k-step program reports ~the single-step figure), so
                # the number IS per-step — no division by k
                flops = float(cost.get("flops", 0.0)) or None
            except Exception:
                flops = None

        state, losses = run_step(state, table, labels, *inv)  # compile
        _fence(losses)

        def window(n_calls):
            nonlocal state
            t0 = time.perf_counter()
            losses = None
            for _ in range(n_calls):
                state, losses = run_step(state, table, labels, *inv)
            _fence(losses)
            return time.perf_counter() - t0

        stats = _slope_stats(window, k, repeats, target_s)
        if detail:
            return {"seconds": stats["seconds"], "flops": flops,
                    "spread": stats["spread"]}
        t = stats["seconds"]
        return (t, flops) if want_flops else t


def celeba_multistep_time(device, batch: int = 128, k: int = 20,
                          repeats: int = REPEATS, detail: bool = False,
                          target_s: float = WINDOW_TARGET_S):
    """Seconds per CelebA-64 DCGAN iteration (1 D-step + 1 G-step, the
    GANPair multistep program of train/gan_pair.py — the roadmap-family
    engine) with the dataset device-resident, plus the XLA cost model's
    FLOPs for the compiled program.  The one model family with TPU-scale
    convolutions (VERDICT r4 #1): its MFU is the framework's
    performance story where the MXU actually matters, not the 90-GFLOP
    MNIST protocol.  Returns (seconds_per_iteration, flops_per_iteration);
    ``detail`` adds the median±IQR spread block.  Windows are sized
    adaptively (v7 — the r5 capture's fixed 2/6-call windows produced an
    11% spread between repeat sets at k=20)."""
    import jax
    import jax.numpy as jnp

    from gan_deeplearning4j_tpu.data import datasets
    from gan_deeplearning4j_tpu.models import dcgan_celeba as M
    from gan_deeplearning4j_tpu.train.gan_pair import GANPair

    with jax.default_device(device):
        cfg = M.CelebAConfig()
        pair = GANPair(M.build_generator(cfg), M.build_discriminator(cfg))
        table = jax.device_put(
            jnp.asarray(datasets.synthetic_celeba(4 * batch, seed=0)),
            device)
        step_fn, state = pair.make_multistep(
            table, None, batch_size=batch, steps_per_call=k,
            real_label=cfg.real_label, z_size=cfg.z_size)
        state = jax.device_put(state, device)  # committed: one signature

        flops = None
        try:
            cost = step_fn.jitted.lower(
                state, *step_fn.invariants).compile().cost_analysis()
            # scan body counted once by the cost model == per-iteration
            flops = float(cost.get("flops", 0.0)) or None
        except Exception:  # gan4j-lint: disable=swallowed-exception — cost model unavailable on some backends; flops=None IS the handled outcome
            pass

        state, losses = step_fn(state)  # compile
        _fence(losses)

        def window(n_calls):
            nonlocal state
            t0 = time.perf_counter()
            losses = None
            for _ in range(n_calls):
                state, losses = step_fn(state)
            _fence(losses)
            return time.perf_counter() - t0

        stats = _slope_stats(window, k, repeats, target_s)
        if detail:
            return {"seconds": stats["seconds"], "flops": flops,
                    "spread": stats["spread"]}
        return stats["seconds"], flops


def e2e_img_per_sec(res_path: str, data_on_device=None,
                    telemetry: bool = False, detail: bool = False,
                    events_enabled: bool = True,
                    metrics_port: Optional[int] = None):
    """Protocol throughput through the REAL trainer loop on the default
    device (steady-state wall clock, excluding the compile step).
    ``data_on_device`` None = the trainer's default (device-resident
    dataset); False = force the streaming CSV/prefetch/transfer path.
    ``res_path`` holds the dataset CSVs, shared between measurements.
    ``telemetry``: run the trainer with the in-graph numerics block on.
    ``events_enabled``: record the event timeline (the default the
    published number ships with; ``--no-events`` is the A/B baseline
    for the recorder's overhead budget).  ``metrics_port``: serve the
    /metrics + /healthz exporter for the run's duration.
    ``detail``: return ``(img_per_sec, {"goodput": ..., "run_id": ...})``
    — the run's phase breakdown and manifest id — instead of the bare
    float."""
    from gan_deeplearning4j_tpu.train import cv_main
    from gan_deeplearning4j_tpu.train.gan_trainer import GANTrainer

    n_train = 20 * BATCH  # small CSV, loops multi-epoch like the loop
    config = cv_main.default_config(
        num_iterations=E2E_STEPS, batch_size=BATCH, res_path=res_path,
        print_every=10 ** 9, save_every=10 ** 9, metrics=False,
        data_on_device=data_on_device, telemetry=telemetry,
        events=events_enabled, metrics_port=metrics_port,
    )
    trainer = GANTrainer(
        cv_main.CVWorkload(n_train=n_train, n_test=BATCH), config)
    result = trainer.train(log=lambda s: None)
    value = float(result["examples_per_sec"])
    if detail:
        return value, {"goodput": result["goodput"],
                       "run_id": result["run_id"]}
    return value


# -- multi-tenant fleet bench (train/fleet.py): tenants*steps/sec ----------
#
# The fleet sweep: each tenant count is ONE bounded subprocess stage
# (--fleet-stage N prints one JSON line), so an OOM or wedge at the
# 4096-tenant end records a structured failure and the sweep continues —
# the request-queue machinery folded in from the retired
# benchmarks/tpu_queue.py round-3 queue.
FLEET_SWEEP = (1, 64, 256, 1024, 4096)
FLEET_FLAGSHIP = 1024
FLEET_BATCH = 16        # FleetConfig's per-tenant batch default
FLEET_RUN_STEPS = 100   # FleetConfig's num_iterations default: the run
#                         length the sequential-equivalent accounting
#                         charges per segment (insurance_main's 5000
#                         would amortize compile away; a tiny K would
#                         inflate it)
FLEET_OUT_DIR = "outputs/fleet_bench"


def _build_fleet_step_and_args(device, n_tenants: int, batch: int):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gan_deeplearning4j_tpu.models import mlpgan_insurance as I
    from gan_deeplearning4j_tpu.train import fleet, fused_step as fused

    cfg = I.InsuranceConfig()
    dis, gen = I.build_discriminator(), I.build_generator()
    gan, classifier = I.build_gan(), I.build_classifier(dis)
    step = fleet.make_fleet_step(
        dis, gen, gan, classifier,
        I.DIS_TO_GAN, I.GAN_TO_GEN, I.DIS_TO_CLASSIFIER,
        z_size=cfg.z_size, num_features=cfg.num_features,
        per_tenant_data=True)
    state = jax.device_put(fleet.replicate_state(
        fused.state_from_graphs(dis, gen, gan, classifier), n_tenants),
        device)
    rng = np.random.RandomState(0)
    real = jax.device_put(
        rng.rand(n_tenants, batch, cfg.num_features).astype(np.float32),
        device)
    labels = jax.device_put(np.ones((n_tenants, batch, 1), np.float32),
                            device)
    key = jax.random.key(0)
    ones = jnp.ones((batch, 1), jnp.float32)
    inv = (
        fleet.tenant_keys(key, n_tenants),
        fleet.tenant_keys(jax.random.fold_in(key, 1), n_tenants),
        ones + 0.05 * jnp.asarray(rng.randn(batch, 1), jnp.float32),
        0.05 * jnp.asarray(rng.randn(batch, 1), jnp.float32),
        ones,
    )
    return step, state, real, labels, inv


def fleet_stage_time(n_tenants: int, batch: int = FLEET_BATCH,
                     repeats: int = REPEATS,
                     target_s: float = WINDOW_TARGET_S,
                     want_flops: bool = False,
                     want_hlo: bool = False) -> dict:
    """One fleet measurement: seconds per FUSED fleet dispatch (all
    ``n_tenants`` advance one protocol step in one XLA program), via the
    v7 adaptive-window slope recipe.  The published rate is
    tenants*steps/sec = n_tenants / step_seconds.  ``want_hlo`` adds the
    hlo_cost.py roofline attribution of THIS tenant count's compiled
    program (the knee diagnosis)."""
    import jax

    device = jax.devices()[0]
    with jax.default_device(device):
        step, state, real, labels, inv = _build_fleet_step_and_args(
            device, n_tenants, batch)
        flops, hlo_block, hlo_error = None, None, None
        if want_flops or want_hlo:
            try:
                compiled = step.lower(
                    state, real, labels, *inv).compile()
            except Exception as e:
                compiled, hlo_error = None, str(e)[:200]
            if compiled is not None and want_flops:
                try:
                    cost = compiled.cost_analysis()
                    # the CPU backend returns a one-element list of the
                    # per-computation dicts; TPU returns the dict
                    if isinstance(cost, (list, tuple)):
                        cost = cost[0] if cost else {}
                    flops = float(cost.get("flops", 0.0)) or None
                except Exception:
                    flops = None  # per-backend optional, like _peak_flops
            if compiled is not None and want_hlo:
                try:
                    import sys as _sys
                    root = os.path.dirname(os.path.dirname(
                        os.path.abspath(__file__)))
                    if root not in _sys.path:
                        _sys.path.insert(0, root)
                    from benchmarks import hlo_cost

                    rows = hlo_cost.analyze_hlo(compiled.as_text())
                    hlo_block = hlo_cost.summarize(rows, top=5)
                except Exception as e:
                    hlo_error = str(e)[:200]

        for _ in range(WARMUP):
            state, losses = step(state, real, labels, *inv)
        _fence(losses)

        def window(n):
            nonlocal state
            losses = None
            t0 = time.perf_counter()
            for _ in range(n):
                state, losses = step(state, real, labels, *inv)
            _fence(losses)
            return time.perf_counter() - t0

        stats = _slope_stats(window, 1, repeats, target_s)
    t = stats["seconds"]
    out = {
        "tenants": n_tenants,
        "batch": batch,
        "step_ms": round(t * 1e3, 4),
        "steps_per_sec": round(1.0 / t, 3),
        "tenants_steps_per_sec": round(n_tenants / t, 2),
        "spread": stats["spread"],
    }
    if flops:
        out["flops_per_step"] = flops
    if hlo_block:
        out["hlo_cost"] = hlo_block
    if hlo_error and want_hlo:
        out["hlo_cost_error"] = hlo_error
    return out


def fleet_run_wall(n_tenants: int, steps: int,
                   batch: int = FLEET_BATCH) -> dict:
    """Wall seconds of a complete fleet RUN at ``n_tenants``: model
    build + XLA compile + ``steps`` fused dispatches, fenced.  With
    ``n_tenants=0`` it measures the SINGLE-MODEL run instead — the
    plain ``make_protocol_step`` program an independently-launched
    single-tenant run executes, not the vmapped program at N=1.  The
    pair is the sequential-equivalent comparison: a fleet run pays the
    build+compile once; N sequential runs re-pay it N times."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    device = jax.devices()[0]
    t0 = time.perf_counter()
    with jax.default_device(device):
        if n_tenants:
            step, state, real, labels, inv = _build_fleet_step_and_args(
                device, n_tenants, batch)
        else:
            from gan_deeplearning4j_tpu.models import mlpgan_insurance as I
            from gan_deeplearning4j_tpu.train import fused_step as fused

            cfg = I.InsuranceConfig()
            dis, gen = I.build_discriminator(), I.build_generator()
            gan, classifier = I.build_gan(), I.build_classifier(dis)
            step = fused.make_protocol_step(
                dis, gen, gan, classifier,
                I.DIS_TO_GAN, I.GAN_TO_GEN, I.DIS_TO_CLASSIFIER,
                z_size=cfg.z_size, num_features=cfg.num_features)
            state = jax.device_put(fused.state_from_graphs(
                dis, gen, gan, classifier), device)
            rng = np.random.RandomState(0)
            real = jax.device_put(
                rng.rand(batch, cfg.num_features).astype(np.float32),
                device)
            labels = jax.device_put(np.ones((batch, 1), np.float32),
                                    device)
            key = jax.random.key(0)
            ones = jnp.ones((batch, 1), jnp.float32)
            inv = (key, jax.random.fold_in(key, 1),
                   ones + 0.05 * jnp.asarray(rng.randn(batch, 1),
                                             jnp.float32),
                   0.05 * jnp.asarray(rng.randn(batch, 1), jnp.float32),
                   ones)
        losses = None
        for _ in range(steps):
            state, losses = step(state, real, labels, *inv)
        _fence(losses)
    return {"tenants": n_tenants, "batch": batch, "steps": steps,
            "run_wall_s": round(time.perf_counter() - t0, 3),
            "includes_compile": True}


def _run_fleet_stage(name: str, cmd: list, timeout_s: float,
                     out_dir: str, summary: dict) -> bool:
    """Run one sweep stage as a bounded subprocess; capture tail + last
    JSON line; False on failure.  Folded from the retired
    benchmarks/tpu_queue.py: own process group (a timeout must kill the
    stage's grandchildren too), last-JSON-line result parse, and the
    exit-0 structured-skip contract (rc 0 with ``"skipped"`` is NOT a
    measurement and never reports as a successful stage)."""
    import signal
    import subprocess

    log_path = os.path.join(out_dir, f"{name}.log")
    t0 = time.perf_counter()
    proc = subprocess.Popen([sys.executable] + cmd,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True, start_new_session=True)
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s)
        timed_out = False
    except subprocess.TimeoutExpired:
        os.killpg(proc.pid, signal.SIGKILL)
        stdout, stderr = proc.communicate()
        timed_out = True
    with open(log_path, "w") as f:
        f.write((stdout or "") + "\n--- stderr ---\n" + (stderr or ""))
    rec: dict = {"ok": (not timed_out) and proc.returncode == 0,
                 "wall_s": round(time.perf_counter() - t0, 1)}
    for line in reversed((stdout or "").strip().splitlines()):
        try:
            parsed = json.loads(line)
        except ValueError:  # gan4j-lint: disable=swallowed-exception — scanning the tail for the one JSON result line; non-JSON progress lines are expected, the full stdout is already in the stage log
            continue
        if isinstance(parsed, dict):  # the result object, not a scalar
            rec["result"] = parsed
            break
    if timed_out:
        rec["error"] = f"timeout >{timeout_s:.0f}s (partial log kept)"
    elif proc.returncode != 0:
        rec["error"] = (stderr or "").strip().splitlines()[-1:]
    elif isinstance(rec.get("result"), dict) and rec["result"].get("skipped"):
        rec["ok"] = False
        rec["error"] = ("stage self-skipped: "
                        + str(rec["result"].get("reason",
                                                "no reason given")))
    summary[name] = rec
    print(f"[fleet] {name}: ok={rec['ok']} wall={rec['wall_s']}s",
          file=sys.stderr, flush=True)
    return rec["ok"]


def fleet_bench(sweep=FLEET_SWEEP, flagship: int = FLEET_FLAGSHIP,
                batch: int = FLEET_BATCH, stage_timeout_s: float = 900.0,
                run_steps: int = FLEET_RUN_STEPS,
                out_dir: str = FLEET_OUT_DIR) -> dict:
    """The fleet bench of record: sweep tenant counts as bounded
    subprocess stages, publish the flagship tenants*steps/sec with the
    v7 spread block, the multiple over the sequential one-model-at-a-
    time equivalent (run-wall accounting: each sequential run re-pays
    build + XLA compile; the fused fleet pays once), and the
    hlo_cost.py roofline attribution of the scaling knee."""
    os.makedirs(out_dir, exist_ok=True)
    summary: dict = {}
    sweep = sorted(set(sweep) | {1, flagship})
    for n in sweep:
        cmd = ["-m", "gan_deeplearning4j_tpu.bench",
               "--fleet-stage", str(n), "--fleet-batch", str(batch)]
        _run_fleet_stage(f"fleet_t{n}", cmd, stage_timeout_s,
                         out_dir, summary)
    stages = {n: summary[f"fleet_t{n}"]["result"] for n in sweep
              if summary[f"fleet_t{n}"]["ok"]
              and isinstance(summary[f"fleet_t{n}"].get("result"), dict)}
    failed = {f"fleet_t{n}": summary[f"fleet_t{n}"].get("error")
              for n in sweep if n not in stages}

    import jax

    out: dict = {
        "metric": "gan4j_fleet_tenants_steps_per_sec",
        "unit": "tenants*steps/sec",
        "platform": jax.devices()[0].platform,
        "batch_per_tenant": batch,
        "methodology_version": METHODOLOGY_VERSION,
        "scaling": [stages[n] for n in sorted(stages)],
    }
    if failed:
        out["failed_stages"] = failed
    if not stages:
        out.update({"skipped": True,
                    "reason": "every fleet stage failed"})
        return out

    flag_n = flagship if flagship in stages else max(stages)
    flag = stages[flag_n]
    out["value"] = flag["tenants_steps_per_sec"]
    out["tenants"] = flag_n
    # the gate-compatible series block ("fleet" in bench_gate.SERIES):
    # per-dispatch median ms + spread, like every other series
    out["fleet"] = {"multistep_step_ms": flag["step_ms"],
                    "spread": flag["spread"],
                    "tenants": flag_n}
    # the lifecycle headline next to tenants*steps/sec: median onboard
    # latency over in-process onboard/offboard cycles on a warmed
    # heterogeneous fleet (zero post-warmup recompiles is part of the
    # probe's own ok), gate-compatible as the "fleet_lifecycle" series
    lc = lifecycle_dryrun()
    out["onboard_latency_ms"] = lc["onboard_latency_ms"]
    out["lifecycle_ok"] = lc["ok"]
    out["fleet_lifecycle"] = {
        "multistep_step_ms": lc["onboard_latency_ms"],
        "spread": {"median_ms": lc["onboard_latency_ms"],
                   "iqr_ms": lc["onboard_iqr_ms"]},
        "cycles": lc["cycles"],
        "post_warmup_recompiles": lc["post_warmup_recompiles"],
    }
    if 1 in stages:
        t1, tn = stages[1]["step_ms"], flag["step_ms"]
        # per-dispatch slope ratio: honest but partial — the slope
        # cancels exactly the dispatch + build + compile costs a
        # sequential fleet pays per run, so it bounds the fused win
        # from below on a compute-bound host
        out["steady_state"] = {
            "single_tenant_step_ms": t1,
            "fleet_step_ms": tn,
            "multiple": round(flag_n * t1 / tn, 1) if tn else None,
        }
    # sequential-equivalent RUN accounting: a production sweep trains
    # each segment for a run of K steps.  flag_n sequential runs re-pay
    # model build + XLA compile + per-dispatch overhead K times each;
    # the fused fleet run pays ONE build + compile for all tenants.
    # Both sides measured as fresh subprocesses (cold jit caches).
    for name, n in (("fleet_run_single", 0),
                    (f"fleet_run_t{flag_n}", flag_n)):
        _run_fleet_stage(
            name, ["-m", "gan_deeplearning4j_tpu.bench",
                   "--fleet-run-wall", str(n),
                   "--fleet-run-steps", str(run_steps),
                   "--fleet-batch", str(batch)],
            stage_timeout_s, out_dir, summary)
    single = summary["fleet_run_single"]
    fleet_run = summary[f"fleet_run_t{flag_n}"]
    if single["ok"] and fleet_run["ok"]:
        t_seq = single["result"]["run_wall_s"]
        t_fleet = fleet_run["result"]["run_wall_s"]
        out["sequential_equivalent"] = {
            "steps_per_run": run_steps,
            "single_run_wall_s": t_seq,
            "sequential_runs_wall_s": round(flag_n * t_seq, 1),
            "fleet_run_wall_s": t_fleet,
            "multiple": round(flag_n * t_seq / t_fleet, 1)
            if t_fleet else None,
            "note": ("run-wall accounting: each of the "
                     f"{flag_n} sequential runs re-pays model build + "
                     "XLA compile; the fused fleet run pays one"),
        }
    # scaling knee: the first sweep point whose tenants*steps/sec gain
    # falls under 75% of the ideal (linear) gain over the previous point
    ns = sorted(stages)
    knee_n, knee_eff = None, None
    for a, b in zip(ns, ns[1:]):
        gain = (stages[b]["tenants_steps_per_sec"]
                / max(stages[a]["tenants_steps_per_sec"], 1e-9))
        eff = gain / (b / a)
        if knee_n is None and eff < 0.75:
            knee_n, knee_eff = b, round(eff, 3)
    if knee_n is None and len(ns) >= 2:     # no knee inside the sweep
        knee_n, knee_eff = ns[-1], round(
            (stages[ns[-1]]["tenants_steps_per_sec"]
             / stages[ns[-2]]["tenants_steps_per_sec"])
            / (ns[-1] / ns[-2]), 3)
    if knee_n is not None:
        knee = {"tenants": knee_n, "scaling_efficiency": knee_eff}
        # attribute it: the roofline decomposition of the knee point's
        # OWN compiled program (in-process; the sweep subprocesses have
        # exited, so this is the only program this process compiles)
        try:
            knee["hlo_cost"] = fleet_stage_time(
                knee_n, batch=batch, repeats=1, target_s=0.2,
                want_hlo=True).get("hlo_cost")
        except Exception as e:   # attribution is best-effort diagnosis
            knee["hlo_cost_error"] = str(e)[:200]
        out["knee"] = knee

    from gan_deeplearning4j_tpu import bench_gate

    out["regression_gate"] = bench_gate.check_against_lastgood(
        out, os.path.join(os.path.dirname(BASELINE_PATH),
                          "BENCH_LASTGOOD.json"))
    with open(os.path.join(out_dir, "summary.json"), "w") as f:
        json.dump({"stages": summary, "capture": out}, f, indent=1)
    return out


def serve_bench(start_rps: float = 50.0, stage_s: float = 2.0,
                repeats: int = 5, load_frac: float = 0.8,
                growth: float = 1.6, max_stages: int = 12,
                seed: int = 0, gateway: bool = False,
                mesh: bool = False) -> dict:
    """The serving bench of record (serve/): ramp an open-loop Poisson
    load to the engine's saturation throughput, then measure p50/p95/
    p99 request latency over ``repeats`` stages at ``load_frac`` of
    saturation — the SLO operating point RESULTS.md reports.  The p50
    spread block is the regression-gated "serve" series; the whole run
    executes under an armed RecompileSentinel, and the capture carries
    the post-warmup compile count (the zero-recompile claim, measured
    not asserted).

    ``gateway=True`` re-measures the SAME SLO operating point through
    the HTTP front door — gateway + router + retrying client over a
    real socket (serve/gateway.py) — as the regression-gated "gateway"
    series: one replica over the SAME compiled dispatch, so
    gateway-p50 minus serve-p50 IS the wire cost (parse + validate +
    route + encode + loopback TCP), not a different model.

    ``mesh=True`` measures the operating point once more through the
    MESH TIER (serve/replica.py + mesh.py): the same load balanced
    over TWO standalone replica PROCESSES by ``MeshRouter`` — the
    regression-gated "mesh" series, so mesh-p50 vs gateway-p50 is the
    cost/benefit of going multi-process (two GILs and two dispatch
    loops vs one, against per-process compile caches).
    """
    import statistics

    import jax
    import numpy as np

    from gan_deeplearning4j_tpu import bench_gate
    from gan_deeplearning4j_tpu.analysis import RecompileSentinel
    from gan_deeplearning4j_tpu.models import dcgan_mnist as M
    from gan_deeplearning4j_tpu.parallel import data_mesh
    from gan_deeplearning4j_tpu.parallel.inference import (
        DEFAULT_SERVING_BUCKETS,
        ParallelInference,
    )
    from gan_deeplearning4j_tpu.serve import (
        ServeEngine,
        measure_saturation,
        run_load,
        z_inputs,
    )
    from gan_deeplearning4j_tpu.serve.loadgen import DEFAULT_SIZE_MIX

    buckets = DEFAULT_SERVING_BUCKETS
    # widest mesh the bucket set shards evenly across on this host
    n_dev = max(n for n in (1, 2, 4, 8)
                if n <= len(jax.devices())
                and all(b % n == 0 for b in buckets))
    gen = M.build_generator()
    pi = ParallelInference(gen, mesh=data_mesh(n_dev), buckets=buckets)
    make_inputs = z_inputs(2, seed=seed)
    sentinel = RecompileSentinel()
    out: dict = {
        "metric": "gan4j_serve_saturation_rps",
        "unit": "req/s",
        "platform": jax.devices()[0].platform,
        "devices": n_dev,
        "buckets": list(buckets),
        "size_mix": [list(p) for p in DEFAULT_SIZE_MIX],
        "methodology_version": METHODOLOGY_VERSION,
    }
    with sentinel:
        with ServeEngine(infer=pi, watchdog_deadline_s=60.0) as eng:
            eng.warmup(np.zeros((1, 2), np.float32))
            sentinel.arm()
            sat = measure_saturation(
                eng, make_inputs, start_rps=start_rps, growth=growth,
                stage_s=stage_s, max_stages=max_stages, seed=seed)
            out["saturation"] = sat
            out["value"] = out["saturation_rps"] = sat["saturation_rps"]
            if sat["saturation_rps"] <= 0:
                out.update({"skipped": True,
                            "reason": "no load stage was sustained — "
                                      "see saturation.failed_stage"})
                return out
            # the SLO operating point: repeats stages at load_frac of
            # saturation, p50 per stage -> the gated spread block
            rate = load_frac * sat["saturation_rps"]
            stages = []
            for i in range(max(1, repeats)):
                stages.append(run_load(
                    eng, rate, duration_s=stage_s,
                    make_inputs=make_inputs, seed=seed + 100 + i))
            out["slo_load_frac"] = load_frac
            out["slo_rate_rps"] = round(rate, 2)
            out["slo_stages"] = stages
            rep = eng.report()
            out["engine"] = {k: rep[k] for k in
                             ("requests_total", "batches_total",
                              "shed_total", "batch_fill",
                              "rate_rows_per_s", "timeouts_total")}
        gw_stages = []
        if gateway:
            # the front-door A/B: same compiled dispatch, same SLO
            # rate, but through gateway + router + client over a real
            # socket — the still-armed sentinel extends the zero-
            # recompile claim across the wire path
            from gan_deeplearning4j_tpu.serve import (
                Gateway,
                GatewayClient,
                Router,
                run_socket_load,
            )
            g_eng = ServeEngine(infer=pi, watchdog_deadline_s=60.0)
            g_eng.warmup(np.zeros((1, 2), np.float32))
            g_eng.start()
            router = Router(replicas=[g_eng])
            try:
                with Gateway(router) as gw:
                    client = GatewayClient("127.0.0.1", gw.port,
                                           retries=2, seed=seed)
                    for i in range(max(1, repeats)):
                        gw_stages.append(run_socket_load(
                            client, rate, duration_s=stage_s,
                            make_inputs=make_inputs,
                            encoding="npy", seed=seed + 200 + i))
                    gw_rep = gw.report()
                    out["gateway_report"] = gw_rep
            finally:
                router.stop()
            out["gateway_slo_stages"] = gw_stages
    mesh_stages = []
    if mesh:
        # the mesh-tier A/B: the same SLO rate balanced over TWO
        # replica PROCESSES — each compiles its own programs, so this
        # stage runs outside the sentinel (the zero-recompile claim
        # for a replica process lives in ITS dryrun/tests, not here)
        import tempfile as _tempfile

        from gan_deeplearning4j_tpu.serve import (
            MeshRouter,
            ReplicaLauncher,
            RemoteReplica,
            run_socket_load,
        )
        m_router = MeshRouter(recheck_s=1.0)
        m_procs = []
        with _tempfile.TemporaryDirectory(
                prefix="gan4j_meshbench_") as m_logs:
            launcher = ReplicaLauncher(buckets=buckets,
                                       log_dir=m_logs)
            try:
                for _ in range(2):
                    proc = launcher.spawn()
                    m_procs.append(proc)
                    m_router.add(RemoteReplica(proc.host, proc.port))
                for i in range(max(1, repeats)):
                    mesh_stages.append(run_socket_load(
                        m_router, rate, duration_s=stage_s,
                        make_inputs=make_inputs,
                        encoding="npy", seed=seed + 300 + i))
                out["mesh_report"] = m_router.report()
            finally:
                m_router.close()
                for proc in m_procs:
                    proc.stop()
        out["mesh_slo_stages"] = mesh_stages
    p50s = [s["p50_ms"] for s in stages if s["p50_ms"] is not None]
    p99s = [s["p99_ms"] for s in stages if s["p99_ms"] is not None]
    if p50s:
        med = statistics.median(p50s)
        if len(p50s) >= 2:
            q1, _, q3 = statistics.quantiles(
                p50s, n=4, method="inclusive")
            iqr = q3 - q1
        else:
            iqr = 0.0
        # the gate-compatible series block ("serve" in
        # bench_gate.SERIES): request p50 at the SLO operating point
        out["serve"] = {
            "multistep_step_ms": round(med, 4),
            "spread": {
                "median_ms": round(med, 4),
                "iqr_ms": round(iqr, 4),
                "min_ms": round(min(p50s), 4),
                "max_ms": round(max(p50s), 4),
                "repeats": len(p50s),
                "window_calls": [min(s["completed"] for s in stages),
                                 max(s["completed"] for s in stages)],
                "window_steps_per_call": 1,
            },
        }
        out["p99_ms"] = round(statistics.median(p99s), 4) if p99s \
            else None
    g50s = [s["p50_ms"] for s in gw_stages if s["p50_ms"] is not None]
    if g50s:
        g_med = statistics.median(g50s)
        if len(g50s) >= 2:
            q1, _, q3 = statistics.quantiles(
                g50s, n=4, method="inclusive")
            g_iqr = q3 - q1
        else:
            g_iqr = 0.0
        # the gate-compatible "gateway" series: socket-path request
        # p50 at the same SLO operating point as "serve" above
        out["gateway"] = {
            "multistep_step_ms": round(g_med, 4),
            "spread": {
                "median_ms": round(g_med, 4),
                "iqr_ms": round(g_iqr, 4),
                "min_ms": round(min(g50s), 4),
                "max_ms": round(max(g50s), 4),
                "repeats": len(g50s),
                "window_calls": [
                    min(s["completed"] for s in gw_stages),
                    max(s["completed"] for s in gw_stages)],
                "window_steps_per_call": 1,
            },
        }
        out["gateway_p99_ms"] = round(statistics.median(
            [s["p99_ms"] for s in gw_stages
             if s["p99_ms"] is not None]), 4) if gw_stages else None
        out["gateway_errors"] = sum(s["errors"] for s in gw_stages)
    m50s = [s["p50_ms"] for s in mesh_stages
            if s["p50_ms"] is not None]
    if m50s:
        m_med = statistics.median(m50s)
        if len(m50s) >= 2:
            q1, _, q3 = statistics.quantiles(
                m50s, n=4, method="inclusive")
            m_iqr = q3 - q1
        else:
            m_iqr = 0.0
        # the gate-compatible "mesh" series: request p50 at the same
        # SLO operating point, balanced over two replica processes
        out["mesh"] = {
            "multistep_step_ms": round(m_med, 4),
            "spread": {
                "median_ms": round(m_med, 4),
                "iqr_ms": round(m_iqr, 4),
                "min_ms": round(min(m50s), 4),
                "max_ms": round(max(m50s), 4),
                "repeats": len(m50s),
                "window_calls": [
                    min(s["completed"] for s in mesh_stages),
                    max(s["completed"] for s in mesh_stages)],
                "window_steps_per_call": 1,
            },
        }
        out["mesh_p99_ms"] = round(statistics.median(
            [s["p99_ms"] for s in mesh_stages
             if s["p99_ms"] is not None]), 4) if mesh_stages else None
        out["mesh_errors"] = sum(s["errors"] for s in mesh_stages)
    out["post_warmup_recompiles"] = len(sentinel.recompiles)
    out["regression_gate"] = bench_gate.check_against_lastgood(
        out, os.path.join(os.path.dirname(BASELINE_PATH),
                          "BENCH_LASTGOOD.json"))
    return out


def checkpoint_dryrun() -> dict:
    """Async-vs-sync checkpoint A/B on the real four-graph model set:
    the training-thread BLOCKING time of an ``AsyncCheckpointer.save``
    (host snapshot only) against a full synchronous
    ``TrainCheckpointer.save`` (snapshot + zip/DEFLATE + fsync + rename),
    plus a manifest-hash comparison proving the two paths commit
    IDENTICAL bytes.  Best-of-2 each (fsync and scheduler noise are
    one-sided, and each sync save costs ~10s of DEFLATE on a CI host).
    The acceptance bar: blocking_ratio <= 0.25."""
    import tempfile

    from gan_deeplearning4j_tpu.checkpoint import (
        AsyncCheckpointer,
        TrainCheckpointer,
    )
    from gan_deeplearning4j_tpu.checkpoint.checkpointer import MANIFEST_NAME
    from gan_deeplearning4j_tpu.models import dcgan_mnist as M

    dis, gen, gan = (
        M.build_discriminator(), M.build_generator(), M.build_gan())
    graphs = {"dis": dis, "gen": gen, "gan": gan,
              "classifier": M.build_classifier(dis)}
    steps = (1, 2)  # best-of-2: each sync save is ~10s of DEFLATE on CPU
    with tempfile.TemporaryDirectory() as d:
        sync = TrainCheckpointer(os.path.join(d, "sync"), keep=len(steps))
        t_sync = float("inf")
        for s in steps:
            t0 = time.perf_counter()
            sync.save(s, graphs)
            t_sync = min(t_sync, time.perf_counter() - t0)
        ack = AsyncCheckpointer(
            TrainCheckpointer(os.path.join(d, "async"), keep=len(steps)))
        t_async = float("inf")
        for s in steps:
            ack.wait()  # isolate THIS save's blocking portion
            t0 = time.perf_counter()
            ack.save(s, graphs)
            t_async = min(t_async, time.perf_counter() - t0)
        ack.close()

        def manifest(root, s):
            with open(os.path.join(d, root, f"ckpt_{s}",
                                   MANIFEST_NAME)) as f:
                return json.load(f)["files"]

        match = all(manifest("sync", s) == manifest("async", s)
                    for s in steps)
    return {
        "sync_save_ms": round(t_sync * 1e3, 3),
        "async_blocking_ms": round(t_async * 1e3, 3),
        "blocking_ratio": round(t_async / t_sync, 4) if t_sync else None,
        "manifest_match": bool(match),
    }


def publish_bench_series(registry, capture: dict, gate=None) -> None:
    """Land a capture's step-time stats on the exporter as the
    ``gan4j_bench_*`` series (docs/OBSERVABILITY.md): per-series
    median/IQR gauges, MFU where the capture carries one, the
    methodology version, and the regression-gate verdict — so a
    dashboard tracks the bench of record without parsing
    ``BENCH_*.json`` artifacts."""
    from gan_deeplearning4j_tpu import bench_gate

    for label, med, iqr in bench_gate.series_stats(capture):
        registry.set("gan4j_bench_step_ms", med, labels={"series": label})
        registry.set("gan4j_bench_step_ms_iqr", iqr,
                     labels={"series": label})
    mfu = capture.get("mfu")
    if isinstance(mfu, (int, float)):
        registry.set("gan4j_bench_mfu", mfu,
                     labels={"series": "multistep"})
    fast = capture.get("fast_mode")
    if isinstance(fast, dict) and isinstance(fast.get("multistep_mfu"),
                                             (int, float)):
        registry.set("gan4j_bench_mfu", fast["multistep_mfu"],
                     labels={"series": "fast_mode"})
    registry.set("gan4j_bench_methodology_version",
                 capture.get("methodology_version", METHODOLOGY_VERSION))
    if gate is not None:
        registry.set("gan4j_bench_regression_ok",
                     1.0 if gate.get("ok") else 0.0)


def sanitizer_dryrun(registry=None) -> dict:
    """Runtime trace sanitizers on the MNIST fused loop (the
    acceptance half of gan4j-lint, analysis/sanitizers.py): compile the
    fused protocol step, warm it up, then ARM the RecompileSentinel and
    drive further steps inside a ``no_implicit_transfers`` region.
    ``ok`` requires ZERO post-warmup recompiles and ZERO implicit
    transfers — the two silent ways the hot path loses its headline.
    The fence (an explicit readback) stays OUTSIDE the guarded region:
    a readback is a transfer by design."""
    import jax

    from gan_deeplearning4j_tpu.analysis import (
        RecompileSentinel,
        TransferGuardError,
        no_implicit_transfers,
    )

    device = jax.devices()[0]
    with jax.default_device(device):
        step, state, real, labels, inv = _build_step_and_args(device)
        sentinel = RecompileSentinel(registry=registry)
        with sentinel:
            for _ in range(2):   # warmup: the one legitimate compile
                state, losses = step(state, real, labels, *inv)
            _fence(losses)
            sentinel.arm()
            transfer_ok, transfer_error = True, None
            try:
                with no_implicit_transfers():
                    for _ in range(3):
                        state, losses = step(state, real, labels, *inv)
            except TransferGuardError as e:
                transfer_ok, transfer_error = False, str(e)[:200]
            _fence(losses)
    out = {
        "warmup_compiles": len(sentinel.compiles),
        "post_warmup_recompiles": len(sentinel.recompiles),
        "transfer_ok": bool(transfer_ok),
        # the sentinel must have SEEN the warmup compile — otherwise
        # "zero recompiles" would also describe a dead hook
        "ok": bool(sentinel.ok and transfer_ok
                   and len(sentinel.compiles) >= 1),
    }
    if transfer_error:
        out["transfer_error"] = transfer_error
    return out


def prove_dryrun() -> dict:
    """The program-contract gate as a bench verdict (gan4j-prove,
    analysis/contracts.py): lower every entry point resolvable on the
    CURRENT topology and check it against the committed contracts —
    donation aliasing, dtype discipline, collective budgets, peak-HBM
    ceilings, bucket coverage, all read off the actual lowering.  The
    three meshless entry points (fused single, fused multi/scan, pair
    multistep) resolve on any host, so ``ok`` requires >= 3 proved with
    zero violations; the SPMD entries join automatically when the host
    has >= 2 devices (the tier1.yml prove lane always runs all five)."""
    from gan_deeplearning4j_tpu.analysis import contracts as contracts_mod

    report = contracts_mod.verify_repo()
    s = report["summary"]
    return {"entry_points": s["entry_points"],
            "skipped": [rec["entry"] for rec in report["skipped"]],
            "violations": s["violations"],
            "ok": bool(s["ok"] and s["entry_points"] >= 3)}


def lint_dryrun() -> dict:
    """The static gate as a bench verdict: gan4j-lint over the whole
    installed package, default rules, EMPTY baseline — ``ok`` iff zero
    findings (docs/STATIC_ANALYSIS.md's zero-findings contract)."""
    from gan_deeplearning4j_tpu import analysis

    res = analysis.lint_package()
    return {"findings": len(res.findings),
            "suppressed": len(res.suppressed),
            "parse_errors": len(res.errors),
            "files_checked": res.files_checked,
            "ok": res.ok}


def race_dryrun(registry=None) -> dict:
    """The concurrency gate as a bench verdict (gan4j-race,
    docs/STATIC_ANALYSIS.md § Concurrency discipline), both halves:

    * static — the race rule set (lock-order cycles, lock-held blocking
      calls, thread hygiene, unlocked shared writes) over the whole
      package with an EMPTY baseline, zero findings;
    * runtime — a short ``lockdep`` window driving the exact shape the
      exporter runs in production (a MetricsRegistry + EventRecorder
      hammered from worker threads, locks allocated UNDER the proxies)
      with zero observed inversions — and at least one TRACKED
      acquisition, so a dead patch cannot pass as clean.

    The wait/inversion series land in ``registry`` (pre-created at 0;
    the dryrun scrape asserts both are present)."""
    import queue
    import threading

    from gan_deeplearning4j_tpu import analysis
    from gan_deeplearning4j_tpu.telemetry import events as events_mod

    from gan_deeplearning4j_tpu.telemetry import MetricsRegistry

    static = analysis.lint_package(rules=list(analysis.RACE_RULES))
    with analysis.lockdep(registry=registry, strict=False) as dep:
        # all three allocated INSIDE the window: their locks are the
        # order-tracking proxies, so the hammering below is tracked
        scratch = MetricsRegistry()             # proxied RLock
        recorder = events_mod.EventRecorder()   # proxied RLock
        q: "queue.Queue" = queue.Queue()        # proxied mutex

        def worker() -> None:
            for k in range(50):
                scratch.observe_record({"step": k, "d_loss": 0.1})
                recorder.instant("race.dryrun", k=k)
                q.put(k)

        threads = [threading.Thread(target=worker,
                                    name=f"gan4j-race-dryrun-{i}",
                                    daemon=True) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        while not q.empty():
            q.get_nowait()
    rep = dep.report()
    return {"static_findings": len(static.findings),
            "static_parse_errors": len(static.errors),
            "tracked_acquisitions": rep["acquisitions"],
            "order_edges": rep["edges"],
            "inversions": rep["inversions"],
            "lock_wait_s": rep["wait_seconds"],
            "ok": bool(static.ok and dep.ok
                       and rep["acquisitions"] >= 1
                       and not dep.leaked_threads())}


def lifecycle_dryrun(registry=None, cycles: int = 3) -> dict:
    """Tenant-lifecycle probe (train/lifecycle.py, docs/FLEET.md
    "Tenant lifecycle and fault domains"): a tiny HETEROGENEOUS fleet
    — two cohorts of different width/depth, each padded to its warmed
    bucket — runs ``cycles`` onboard/offboard cycles plus masked
    training windows under an armed ``RecompileSentinel``.  Membership
    churn is host surgery on the tenant axis, so the warmed programs
    are the whole set: ``ok`` demands ZERO post-warmup recompiles,
    finite survivor losses through the churn, a measured onboard
    latency, and a restorable final checkpoint from the offboard path.

    The median/IQR over the cycle latencies is the gate-compatible
    ``fleet_lifecycle`` series (bench_gate.SERIES) and the
    ``onboard_latency_ms`` headline in ``bench --fleet``."""
    import math
    import shutil

    import numpy as np

    from gan_deeplearning4j_tpu.analysis import RecompileSentinel
    from gan_deeplearning4j_tpu.train.lifecycle import (
        FleetManager,
        LifecycleConfig,
        TenantSpec,
    )

    B = 4
    segments = 4
    tmp = tempfile.mkdtemp(prefix="gan4j_lifecycle_dryrun_")
    try:
        specs = [TenantSpec(0),                           # h100_l3
                 TenantSpec(1, hidden=64, gen_layers=2)]  # h64_l2
        cfg = LifecycleConfig(
            batch_size=B, res_path=tmp, buckets=(2,), warm_buckets=(2,),
            num_segments=segments, record_timelines=False)
        mgr = FleetManager(specs, cfg, registry=registry)
        rng = np.random.RandomState(7)

        def feed():
            feats = rng.rand(segments * B, 12).astype(np.float32)
            labs = (rng.rand(segments * B, 1) > 0.5).astype(np.float32)
            return feats, labs

        latencies: list = []
        sentinel = RecompileSentinel(registry=registry)
        with sentinel:
            mgr.warmup()
            sentinel.arm()
            mgr.step_window(*feed(), steps=1)
            # churn: tenant 2 rides the h100 cohort's ghost slot —
            # onboard fills it (host surgery + eager key rebuild),
            # offboard vacates it and writes the final per-tenant
            # checkpoint; every cycle is one latency sample
            ckpt_path = None
            for _ in range(max(1, int(cycles))):
                latencies.append(mgr.onboard(TenantSpec(2)))
                mgr.step_window(*feed(), steps=1)
                ckpt_path = mgr.offboard(2)
            win = mgr.step_window(*feed(), steps=1)
        losses_ok = all(
            math.isfinite(float(v))
            for rec in win["losses"].values() for v in rec["d"])
        med = float(np.median(latencies)) if latencies else 0.0
        q1, q3 = (np.percentile(latencies, [25, 75])
                  if latencies else (0.0, 0.0))
        rec = {
            "tenants": len(mgr.active_ids()),
            "cohorts": len(mgr.cohorts),
            "cycles": len(latencies),
            "onboard_latency_ms": round(med, 3),
            "onboard_iqr_ms": round(float(q3 - q1), 3),
            "post_warmup_recompiles": len(sentinel.recompiles),
            "compiles": len(sentinel.compiles),
            "offboard_checkpoint": bool(
                ckpt_path and os.path.isdir(ckpt_path)),
            "quarantined": sorted(mgr.quarantined),
        }
        rec["ok"] = bool(
            rec["post_warmup_recompiles"] == 0
            and rec["compiles"] >= 1
            and losses_ok
            and rec["onboard_latency_ms"] > 0.0
            and rec["offboard_checkpoint"]
            and not rec["quarantined"]
            and rec["tenants"] == len(specs))
        return rec
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def dryrun(telemetry: bool = True,
           metrics_port: Optional[int] = None) -> dict:
    """CI smoke: build and execute the fused protocol program — single
    step AND a 2-step scanned multistep, telemetry on — at a toy batch
    on whatever the default platform is (CPU in CI).  Catches exactly
    the class of regression that has bitten before: an import/trace
    error that breaks every consumer of the fused step without any
    benchmark running.  No probe, no baseline, seconds not minutes.
    Also runs the checkpoint A/B (``checkpoint_dryrun``): ok requires
    async blocking <= 25% of the sync save AND identical manifests.

    The smoke also exercises the EVENT layer end to end: the work runs
    under a file-backed event recorder (``events_ok`` requires a
    non-empty ``events.jsonl``) and the /metrics + /healthz exporter is
    served and scraped over a real socket (``exporter_ok`` requires 200s
    and the step/goodput/NaN series in the payload).  ``metrics_port``
    picks the port (default: ephemeral).

    The training-health layer rides the same smoke (``watchdog_ok``):
    the step runs with a HEARTBEAT WATCHDOG armed, the per-beat cost is
    measured and must be in the noise (<< the 2% telemetry budget —
    the bar here is 50µs/beat, ~3 orders below a step), and one REAL
    /healthz scrape during the live (beating) run must report
    ``"stalled": false`` with a 200 — the stalled contract's healthy
    half, the 503 half being pinned by tests/test_supervision.py.

    The resilient data plane rides it too (``data_ok``): the
    ``gan4j_data_*`` series must exist from the first scrape and the
    /healthz ``"data"`` block must report a budget-intact ``ok`` —
    the healthy half of the quarantine contract
    (tests/test_resilient.py pins the failure half).

    gan4j-lint rides it last (PR 6): ``lint_ok`` asserts ZERO static
    findings over the whole package with an empty baseline, and
    ``sanitizer_ok`` asserts zero post-warmup recompiles + zero
    implicit transfers on the fused loop (``sanitizer_dryrun``) — the
    static and runtime halves of the same hot-path-stays-clean
    contract, both folded into ``ok``.

    gan4j-prove joins them (PR 7): ``prove_ok`` checks every entry
    point resolvable on this topology against its committed program
    contract (``prove_dryrun``) — donation aliasing, dtype discipline,
    collective budget, peak-HBM ceiling and bucket coverage, verified
    from the actual lowering, also folded into ``ok``.

    gan4j-race completes the set (PR 9): ``race_ok`` asserts zero
    static concurrency findings (lock-order cycles, lock-held blocking
    calls, thread hygiene) over the package AND a clean ``lockdep``
    runtime window (``race_dryrun``) with the ``gan4j_lock_*`` series
    present in the scrape, folded into ``ok``."""
    global BATCH
    prev_batch, BATCH = BATCH, DRYRUN_BATCH
    try:
        import math
        import tempfile
        import urllib.request

        import jax

        from gan_deeplearning4j_tpu.telemetry import (
            GoodputTimer,
            MetricsRegistry,
            events as events_mod,
            serve_exporter,
        )

        with tempfile.TemporaryDirectory() as tmp:
            events_path = os.path.join(tmp, events_mod.EVENTS_NAME)
            recorder = events_mod.EventRecorder(path=events_path)
            prev_rec = events_mod.install(recorder)
            registry = MetricsRegistry()
            goodput = GoodputTimer()
            registry.observe_goodput(goodput.report)
            # data-plane feed (data/resilient.py), as a trainer wires it
            from gan_deeplearning4j_tpu.data.resilient import DataHealth

            data_health = DataHealth()
            registry.observe_data(data_health.report)
            # resource telemetry (telemetry/resources.py): a live
            # sampler feeds the gan4j_resource_* gauges for the whole
            # smoke — the scrape below must carry them and /healthz
            # must grow the "resources" block
            from gan_deeplearning4j_tpu.telemetry.resources import (
                ResourceMonitor,
            )

            rmon = ResourceMonitor(interval_s=0.5)
            rmon.start()
            registry.observe_resources(rmon.report)
            stop = serve_exporter(registry,
                                  0 if metrics_port is None
                                  else metrics_port)
            from gan_deeplearning4j_tpu.train.watchdog import (
                HeartbeatWatchdog,
            )

            watchdog = HeartbeatWatchdog(deadline_s=3600.0)
            watchdog.start()
            registry.observe_watchdog(watchdog.report)
            try:
                device = jax.devices()[0]
                with goodput.phase("dispatch"), \
                        events_mod.span("bench.single_step"):
                    step, state, real, labels, inv = \
                        _build_step_and_args(device)
                    state, losses = step(state, real, labels, *inv)
                watchdog.beat(step=1)  # a live, beating run
                ok = all(math.isfinite(float(l)) for l in losses)
                # per-beat cost: the whole heartbeat layer must be in
                # the noise (beats ride the hot loop's phase wrappers)
                n_beats = 2000
                t0 = time.perf_counter()
                for k in range(n_beats):
                    watchdog.beat(step=k + 2)
                beat_us = (time.perf_counter() - t0) / n_beats * 1e6
                with events_mod.span("bench.multistep"):
                    # 3 slope sets through the REAL adaptive-window path
                    # (target shrunk to keep the smoke seconds-fast):
                    # the spread block below is the bench-stability
                    # harness's own capture, fed straight into the gate
                    multi = protocol_multistep_time(
                        device, k=2, repeats=3, telemetry=telemetry,
                        detail=True, target_s=0.4)
                    t = multi["seconds"]
                # bench_stable_ok (the bench-of-record lane): the spread
                # block must be complete, the gate must PASS the capture
                # against itself, and it must provably FAIL an injected
                # 10x-regressed copy — a gate that cannot go red is
                # decoration (the lint/prove/race lane rule)
                from gan_deeplearning4j_tpu import bench_gate

                spread = multi["spread"]
                cap = {"multistep_step_ms": round(t * 1e3, 4),
                       "spread": spread}
                regressed = {
                    "multistep_step_ms": cap["multistep_step_ms"] * 10,
                    "spread": {**spread,
                               "median_ms": spread["median_ms"] * 10}}
                self_gate = bench_gate.check_capture(cap, cap)
                fail_gate = bench_gate.check_capture(regressed, cap)
                bench_stable_ok = (
                    spread.get("repeats", 0) >= 3
                    and all(key in spread for key in
                            ("median_ms", "iqr_ms", "min_ms", "max_ms"))
                    and spread["min_ms"] <= spread["median_ms"]
                    <= spread["max_ms"]
                    and self_gate["ok"] and self_gate["compared"] >= 1
                    and not fail_gate["ok"])
                # the bench stats ride the same exporter a trainer
                # serves: gan4j_bench_* must appear in the scrape below
                publish_bench_series(registry, cap, gate=self_gate)
                with events_mod.span("bench.checkpoint_ab"):
                    ckpt = checkpoint_dryrun()
                ckpt_ok = (ckpt["manifest_match"]
                           and ckpt["blocking_ratio"] is not None
                           and ckpt["blocking_ratio"] <= 0.25)
                # gan4j-lint, both halves (analysis/): the static
                # zero-findings gate and the runtime sanitizers on the
                # fused loop — a recompile-hazard or host-sync
                # regression is a red dryrun before it is a slow TPU run
                with events_mod.span("bench.sanitizers"):
                    sanitizer = sanitizer_dryrun(registry=registry)
                with events_mod.span("bench.lint"):
                    lint = lint_dryrun()
                # gan4j-prove (PR 7): the program-contract gate over
                # every entry point this topology can lower — donation
                # still aliased, no f64, collective budget intact,
                # peak-HBM under ceiling, batch shapes inside buckets
                with events_mod.span("bench.prove"):
                    prove = prove_dryrun()
                # gan4j-race (PR 9): the concurrency gate both ways —
                # zero static race findings AND a lockdep window over
                # the registry/recorder/queue shape with zero observed
                # inversions; feeds gan4j_lock_* into the scrape below
                with events_mod.span("bench.race"):
                    race = race_dryrun(registry=registry)
                # the multi-tenant fleet (train/fleet.py): one FUSED
                # fleet dispatch — every tenant advances one protocol
                # step in one XLA program — under an armed recompile
                # sentinel, its stats fed to the exporter so the scrape
                # below must carry the gan4j_fleet_* series and the
                # "fleet" bench series
                from gan_deeplearning4j_tpu.analysis import (
                    RecompileSentinel,
                )

                with events_mod.span("bench.fleet"):
                    fleet_n = 8
                    fstep, fstate, freal, flabels, finv = \
                        _build_fleet_step_and_args(
                            device, fleet_n, DRYRUN_BATCH)
                    fsentinel = RecompileSentinel(registry=registry)
                    with fsentinel:
                        for _ in range(2):  # warmup: the one compile
                            fstate, flosses = fstep(
                                fstate, freal, flabels, *finv)
                        _fence(flosses)
                        fsentinel.arm()
                        t0 = time.perf_counter()
                        fstate, flosses = fstep(
                            fstate, freal, flabels, *finv)
                        _fence(flosses)
                        f_ms = (time.perf_counter() - t0) * 1e3
                    d_losses = flosses[0]
                    fleet_rec = {
                        "tenants": fleet_n,
                        "dispatch_ms": round(f_ms, 3),
                        "steps_per_sec": round(1e3 / f_ms, 3)
                        if f_ms else 0.0,
                        "post_warmup_recompiles":
                            len(fsentinel.recompiles),
                        "losses_shape": list(d_losses.shape),
                    }
                    fleet_feed = {**fleet_rec, "ok": True}
                    registry.observe_fleet(lambda: fleet_feed)
                    publish_bench_series(
                        registry,
                        {"fleet": {"multistep_step_ms": round(f_ms, 4),
                                   "spread": {"median_ms": round(f_ms, 4),
                                              "iqr_ms": 0.0}}})
                    fleet_losses_ok = (
                        d_losses.shape == (fleet_n,)
                        and all(math.isfinite(float(v))
                                for v in d_losses))
                # the tenant-lifecycle fault domains (train/
                # lifecycle.py): a heterogeneous two-cohort fleet runs
                # onboard/offboard cycles + masked windows under its
                # own armed sentinel — membership churn must compile
                # NOTHING post-warmup; median onboard latency becomes
                # the "fleet_lifecycle" bench series the gate watches
                with events_mod.span("bench.lifecycle"):
                    lifecycle_rec = lifecycle_dryrun(registry=registry)
                    publish_bench_series(
                        registry,
                        {"fleet_lifecycle": {
                            "multistep_step_ms":
                                lifecycle_rec["onboard_latency_ms"],
                            "spread": {
                                "median_ms":
                                    lifecycle_rec["onboard_latency_ms"],
                                "iqr_ms":
                                    lifecycle_rec["onboard_iqr_ms"]}}})
                # the serving plane (serve/): a real engine — dispatch
                # thread, admission queue, host-side bucket padding —
                # serving a short load burst under an armed recompile
                # sentinel, its report fed to the exporter so the
                # scrape below must carry the gan4j_serve_* series,
                # the "serve" bench series, and a healthy /healthz
                # serving block
                with events_mod.span("bench.serve"):
                    import numpy as _np

                    from gan_deeplearning4j_tpu.models import (
                        dcgan_mnist as _dcgan,
                    )
                    from gan_deeplearning4j_tpu.parallel import (
                        data_mesh,
                    )
                    from gan_deeplearning4j_tpu.parallel.inference \
                        import ParallelInference
                    from gan_deeplearning4j_tpu.serve import (
                        ServeEngine,
                        run_load,
                        z_inputs,
                    )
                    s_pi = ParallelInference(
                        _dcgan.build_generator(), mesh=data_mesh(1),
                        buckets=(8, 32, 64))
                    ssentinel = RecompileSentinel(registry=registry)
                    with ssentinel:
                        with ServeEngine(
                                infer=s_pi,
                                watchdog_deadline_s=60.0) as s_eng:
                            s_eng.warmup(
                                _np.zeros((1, 2), _np.float32))
                            ssentinel.arm()
                            s_stats = run_load(
                                s_eng, rate_rps=100.0, n_requests=20,
                                make_inputs=z_inputs(2, seed=1),
                                seed=2)
                            serve_rec = s_eng.report()
                    serve_rec["post_warmup_recompiles"] = len(
                        ssentinel.recompiles)
                    registry.observe_serve(lambda: serve_rec)
                    s_p50 = serve_rec["p50_ms"] or 0.0
                    publish_bench_series(
                        registry,
                        {"serve": {
                            "multistep_step_ms": round(s_p50, 4),
                            "spread": {"median_ms": round(s_p50, 4),
                                       "iqr_ms": 0.0}}})
                # the network front door (serve/gateway.py): a short
                # Poisson burst through gateway + router + client over
                # a REAL loopback socket, reusing the serve block's
                # already-compiled dispatch so a still-armed sentinel
                # proves the wire path adds ZERO compiles; the report
                # feeds the exporter so the scrape below must carry
                # the gan4j_gateway_* series and the /healthz gateway
                # block
                with events_mod.span("bench.gateway"):
                    from gan_deeplearning4j_tpu.serve import (
                        Gateway,
                        GatewayClient,
                        Router,
                        run_socket_load,
                    )
                    gsentinel = RecompileSentinel(registry=registry)
                    g_eng = ServeEngine(infer=s_pi,
                                        watchdog_deadline_s=60.0)
                    g_eng.warmup(_np.zeros((1, 2), _np.float32))
                    g_router = Router(replicas=[g_eng])
                    with gsentinel:
                        gsentinel.arm()
                        g_eng.start()
                        try:
                            with Gateway(g_router) as g_gw:
                                g_client = GatewayClient(
                                    "127.0.0.1", g_gw.port,
                                    retries=2, seed=3)
                                g_stats = run_socket_load(
                                    g_client, rate_rps=60.0,
                                    n_requests=12,
                                    make_inputs=z_inputs(2, seed=4),
                                    encoding="npy", seed=5)
                                gw_rec = g_gw.report()
                                client_rec = g_client.report()
                        finally:
                            g_router.stop()
                    gw_rec["post_warmup_recompiles"] = len(
                        gsentinel.recompiles)
                    registry.observe_gateway(lambda: gw_rec)
                    # caller-side wire counters (satellite of the
                    # tracing PR): the gan4j_client_* series must ride
                    # the same scrape
                    registry.observe_client(lambda: client_rec)
                    g_p50 = g_stats["p50_ms"] or 0.0
                    publish_bench_series(
                        registry,
                        {"gateway": {
                            "multistep_step_ms": round(g_p50, 4),
                            "spread": {"median_ms": round(g_p50, 4),
                                       "iqr_ms": 0.0}}})
                # the mesh tier (serve/replica.py + mesh.py +
                # controlplane.py): a REAL control plane spawning
                # replica PROCESSES — min 1, hair-trigger autoscaler
                # so the smoke exercises one genuine scale-up — then
                # finite generates routed over their sockets; both
                # reports feed the exporter so the scrape below must
                # carry the gan4j_mesh_*/gan4j_controlplane_* series
                # and the serving_mesh/controlplane /healthz blocks
                with events_mod.span("bench.mesh"):
                    from gan_deeplearning4j_tpu.serve import (
                        Autoscaler,
                        ControlPlane,
                        MeshRouter,
                        ReplicaLauncher,
                    )
                    m_mesh = MeshRouter(recheck_s=0.5)
                    m_outs = []
                    with tempfile.TemporaryDirectory(
                            prefix="gan4j_mesh_") as m_logs:
                        m_cp = ControlPlane(
                            ReplicaLauncher(
                                buckets=(8,), log_dir=m_logs,
                                events_dir=m_logs,
                                env={"JAX_PLATFORMS": "cpu"}),
                            mesh=m_mesh,
                            autoscaler=Autoscaler(
                                min_replicas=1, max_replicas=2,
                                up_queue_depth=0.0, up_after=1,
                                down_after=10_000, cooldown_ticks=2),
                            tick_s=0.25)
                        try:
                            m_cp.start()
                            m_deadline = time.monotonic() + 90.0
                            while (time.monotonic() < m_deadline
                                   and len(m_cp.replica_names()) < 2):
                                time.sleep(0.2)
                            for _ in range(3):
                                m_outs.append(m_mesh.generate(
                                    [_np.zeros((4, 2),
                                               _np.float32)])[0])
                            mesh_rec = m_mesh.report()
                            cp_rec = m_cp.report()
                        finally:
                            m_cp.stop()
                            m_mesh.close()
                        # cross-process trace merge: must run INSIDE
                        # this with-block (the replica events files
                        # live in m_logs) and AFTER stop() (SIGTERM
                        # makes each replica flush its tail)
                        from gan_deeplearning4j_tpu.telemetry import (
                            tracing as tracing_mod,
                        )
                        import glob as _glob

                        recorder.flush()
                        trace_merged = tracing_mod.merge_trace_files(
                            [events_path] + sorted(_glob.glob(
                                os.path.join(
                                    m_logs, "*.events.jsonl"))))
                    registry.observe_serving_mesh(lambda: mesh_rec)
                    registry.observe_controlplane(lambda: cp_rec)
                # one record through the registry feed, then a REAL
                # scrape over the socket: the CI assertion that the
                # exporter answers with the step/goodput/NaN series
                registry.observe_record(
                    {"step": 1, "d_loss": float(losses[0]),
                     "nonfinite": 0})

                def get(path):
                    url = f"http://127.0.0.1:{stop.port}{path}"
                    with urllib.request.urlopen(url, timeout=10) as r:
                        return r.status, r.read().decode()

                try:
                    m_status, m_body = get("/metrics")
                    h_status, h_body = get("/healthz")
                except OSError:
                    m_status = h_status = 0
                    m_body = h_body = ""
                exporter_ok = (
                    m_status == 200 and h_status == 200
                    # trailing space: "gan4j_step" alone would be a
                    # vacuous substring of gan4j_steps_total
                    and "gan4j_step " in m_body
                    and "gan4j_steps_total " in m_body
                    and "gan4j_nonfinite_total " in m_body
                    and "gan4j_goodput_seconds" in m_body
                    and "gan4j_watchdog_last_beat_age_seconds" in m_body
                    and "gan4j_rollback_total " in m_body
                    and "gan4j_recompiles_total " in m_body)
                # lockdep surface: both gan4j_lock_* series must exist
                # from the first scrape (pre-created at 0, fed by the
                # race_dryrun window above)
                race_ok = (race["ok"]
                           and "gan4j_lock_wait_seconds_total " in m_body
                           and "gan4j_lock_inversions_total " in m_body)
                # bench-of-record surface: the published series must
                # survive a real scrape (labeled, so match the brace)
                bench_stable_ok = (
                    bench_stable_ok
                    and 'gan4j_bench_step_ms{series="multistep"}' in m_body
                    and 'gan4j_bench_step_ms_iqr{series="multistep"}'
                    in m_body
                    and "gan4j_bench_regression_ok " in m_body
                    and "gan4j_bench_methodology_version " in m_body)
                # stalled contract, healthy half: the scrape above ran
                # against a LIVE (beating) watchdog-armed run and must
                # say so — 200 with "stalled": false
                try:
                    health = json.loads(h_body) if h_body else {}
                except ValueError:
                    health = {}
                watchdog_ok = (h_status == 200
                               and health.get("stalled") is False
                               and beat_us < 50.0)
                # resilient-data-plane surface: the gan4j_data_* series
                # exist from the first scrape and /healthz carries the
                # "data" block with a healthy (budget-intact) verdict
                data_block = health.get("data")
                data_ok = (
                    "gan4j_data_retries_total " in m_body
                    and "gan4j_data_quarantined_total " in m_body
                    and "gan4j_data_last_error_age_seconds " in m_body
                    and isinstance(data_block, dict)
                    and data_block.get("ok") is True)
                # fleet surface: zero post-warmup recompiles on the
                # fused fleet dispatch, per-tenant losses finite, the
                # gan4j_fleet_* series live in the scrape (fed, not
                # just pre-created: /healthz must report the real
                # tenant count), and the "fleet" bench series present
                fleet_block = health.get("fleet")
                fleet_ok = (
                    fleet_losses_ok
                    and fleet_rec["post_warmup_recompiles"] == 0
                    and len(fsentinel.compiles) >= 1
                    and "gan4j_fleet_tenants " in m_body
                    and "gan4j_fleet_steps_per_sec " in m_body
                    and "gan4j_fleet_dispatch_ms " in m_body
                    and 'gan4j_bench_step_ms{series="fleet"}' in m_body
                    and isinstance(fleet_block, dict)
                    and fleet_block.get("tenants") == fleet_n
                    and fleet_block.get("ok") is True)
                # lifecycle surface: the churn probe passed (zero
                # post-warmup recompiles through onboard/offboard
                # cycles, finite survivors, restorable final
                # checkpoint), its per-tenant lifecycle counters are
                # live in the scrape (fed by the probe's manager, not
                # just pre-created), and the "fleet_lifecycle" bench
                # series survived a real scrape
                lifecycle_ok = (
                    lifecycle_rec["ok"]
                    and "gan4j_fleet_tenant_onboarded_total " in m_body
                    and "gan4j_fleet_tenant_offboarded_total " in m_body
                    and 'gan4j_bench_step_ms{series="fleet_lifecycle"}'
                    in m_body)
                # serving surface: the short load run completed with
                # zero errors and ZERO post-warmup recompiles (the
                # engine pads host-side, so the warmed buckets are the
                # whole program set), the gan4j_serve_* series live in
                # the scrape (fed: requests_total must be the real
                # count), the "serve" bench series present, and the
                # /healthz serving block healthy
                serve_blk = health.get("serve")
                serve_ok = (
                    serve_rec["requests_total"] >= 1
                    and s_stats["errors"] == 0
                    and s_stats["undrained"] == 0
                    and serve_rec["post_warmup_recompiles"] == 0
                    and len(ssentinel.compiles) >= 1
                    and "gan4j_serve_requests_total " in m_body
                    and "gan4j_serve_shed_total " in m_body
                    and "gan4j_serve_queue_depth " in m_body
                    and "gan4j_serve_batch_fill " in m_body
                    and "gan4j_serve_p99_ms " in m_body
                    and 'gan4j_bench_step_ms{series="serve"}' in m_body
                    and isinstance(serve_blk, dict)
                    and serve_blk.get("requests_total", 0) >= 1
                    and serve_blk.get("ok") is True)
                # front-door surface: the socket burst completed with
                # zero failures of ANY kind and zero post-warmup
                # compiles (the wire path is parse/validate/route —
                # it must never touch the compiler), the
                # gan4j_gateway_* series live in the scrape (fed: the
                # request count must be the real one), the "gateway"
                # bench series present, and the /healthz gateway block
                # healthy with the replica behind it
                gateway_blk = health.get("gateway")
                gateway_ok = (
                    g_stats["completed"] == 12
                    and g_stats["errors"] == 0
                    and g_stats["shed"] == 0
                    and g_stats["unavailable"] == 0
                    and g_stats["undrained"] == 0
                    and gw_rec["requests_total"] >= 12
                    and gw_rec["post_warmup_recompiles"] == 0
                    and "gan4j_gateway_requests_total " in m_body
                    and "gan4j_gateway_rejected_total " in m_body
                    and "gan4j_gateway_active_connections " in m_body
                    and "gan4j_gateway_replica_healthy " in m_body
                    and 'gan4j_bench_step_ms{series="gateway"}'
                    in m_body
                    and isinstance(gateway_blk, dict)
                    and gateway_blk.get("requests_total", 0) >= 12
                    and gateway_blk.get("replicas_healthy") == 1
                    and gateway_blk.get("ok") is True)
                # mesh-tier surface: the control plane spawned the
                # fleet (one GENUINE scale event past min_replicas),
                # every routed generate over the real sockets came
                # back finite, zero tick-loop errors (every failure
                # typed and handled), the gan4j_mesh_* /
                # gan4j_controlplane_* series live in the scrape, and
                # both /healthz blocks healthy
                mesh_blk = health.get("serving_mesh")
                cp_blk = health.get("controlplane")
                mesh_ok = (
                    mesh_rec["replicas"] == 2
                    and mesh_rec["replicas_healthy"] == 2
                    and mesh_rec["ok"] is True
                    and cp_rec["scale_up_total"] >= 1
                    and cp_rec["tick_errors_total"] == 0
                    and cp_rec["ok"] is True
                    and len(m_outs) == 3
                    and all(bool(_np.isfinite(o).all())
                            for o in m_outs)
                    and "gan4j_mesh_replicas " in m_body
                    and "gan4j_mesh_replicas_healthy " in m_body
                    and "gan4j_mesh_ejected_total " in m_body
                    and "gan4j_controlplane_replicas " in m_body
                    and "gan4j_controlplane_scale_events_total "
                    in m_body
                    and "gan4j_controlplane_rollbacks_total " in m_body
                    and isinstance(mesh_blk, dict)
                    and mesh_blk.get("replicas") == 2
                    and mesh_blk.get("ok") is True
                    and isinstance(cp_blk, dict)
                    and cp_blk.get("replicas") == 2
                    and cp_blk.get("ok") is True)
                # distributed-tracing surface: every traced request in
                # the smoke (12 gateway socket requests + 3 mesh
                # generates) must resolve to a COMPLETE span tree after
                # the cross-process merge — one root, every parent id
                # resolving — and the mesh-rooted traces must span >= 2
                # processes (the main process's route/hop spans joined
                # with the replica's request/engine spans purely
                # through the wire header).  The caller-side and
                # resource series ride the same scrape, and span
                # recording itself must cost well under the 2%
                # telemetry budget at the gateway's own p50.
                n_probe = 200
                t0 = time.perf_counter()
                for i in range(n_probe):
                    events_mod.complete("bench.trace_probe", dur=0.0,
                                        probe=i)
                per_event_us = ((time.perf_counter() - t0)
                                / n_probe * 1e6)
                # ~14 trace.* records ride one fully traced gateway
                # request (client 3, gateway 6, engine 5)
                trace_overhead_frac = (
                    (14.0 * per_event_us / 1e3) / g_p50
                    if g_p50 else 0.0)
                t_stats = trace_merged["stats"]
                route_traces = [
                    tr for tr in trace_merged["traces"].values()
                    if tr["root"] == "trace.route"]
                resources_blk = health.get("resources")
                trace_ok = (
                    t_stats["traces"] >= 15
                    and t_stats["complete_frac"] >= 0.95
                    and len(route_traces) >= 3
                    and all(tr["complete"]
                            and len(tr["processes"]) >= 2
                            for tr in route_traces)
                    and t_stats["cross_process"] >= 3
                    and "gan4j_client_reused_total " in m_body
                    and "gan4j_client_reconnects_total " in m_body
                    and "gan4j_client_retried_total " in m_body
                    and "gan4j_resource_rss_bytes " in m_body
                    and "gan4j_resource_open_fds " in m_body
                    and "gan4j_resource_threads " in m_body
                    and isinstance(resources_blk, dict)
                    and resources_blk.get("rss_bytes", 0) > 0
                    and resources_blk.get("ok") is True
                    and trace_overhead_frac < 0.02)
                recorder.flush()
                try:
                    events_ok = len(events_mod.read_events(
                        events_path)) >= 4  # header + three spans
                except OSError:
                    events_ok = False
            finally:
                watchdog.stop()
                rmon.stop()
                stop()
                events_mod.install(prev_rec)
                recorder.close()
        # publication-pipeline surface (serve/publisher.py): promote /
        # reject / scrape series, in-process — the cheap slice of the
        # combined-chaos scenario the CI scenario lane runs in full
        scenario_rec = publication_smoke()
        scenario_ok = scenario_rec["ok"]
        return {"metric": "dcgan_mnist_img_per_sec", "dryrun": True,
                "ok": bool(ok and math.isfinite(t) and ckpt_ok
                           and exporter_ok and events_ok
                           and watchdog_ok and data_ok
                           and lint["ok"] and sanitizer["ok"]
                           and prove["ok"] and race_ok
                           and bench_stable_ok and fleet_ok
                           and lifecycle_ok
                           and serve_ok and gateway_ok and mesh_ok
                           and trace_ok and scenario_ok),
                "platform": device.platform,
                "telemetry": telemetry,
                "checkpoint": ckpt,
                "exporter_ok": bool(exporter_ok),
                "events_ok": bool(events_ok),
                "watchdog_ok": bool(watchdog_ok),
                "data_ok": bool(data_ok),
                "lint_ok": bool(lint["ok"]),
                "lint": lint,
                "sanitizer_ok": bool(sanitizer["ok"]),
                "sanitizer": sanitizer,
                "prove_ok": bool(prove["ok"]),
                "prove": prove,
                "race_ok": bool(race_ok),
                "race": race,
                "fleet_ok": bool(fleet_ok),
                "fleet": fleet_rec,
                "lifecycle_ok": bool(lifecycle_ok),
                "lifecycle": lifecycle_rec,
                "serve_ok": bool(serve_ok),
                "serve": serve_rec,
                "gateway_ok": bool(gateway_ok),
                "gateway": gw_rec,
                "mesh_ok": bool(mesh_ok),
                "mesh": mesh_rec,
                "controlplane": cp_rec,
                "trace_ok": bool(trace_ok),
                "trace": t_stats,
                "scenario_ok": bool(scenario_ok),
                "scenario": scenario_rec,
                "trace_overhead_frac": round(trace_overhead_frac, 6),
                "trace_span_record_us": round(per_event_us, 3),
                "bench_stable_ok": bool(bench_stable_ok),
                "bench_spread": spread,
                "watchdog_beat_us": round(beat_us, 3)}
    finally:
        BATCH = prev_batch


def publication_smoke() -> dict:
    """In-process checkpoint-publication pipeline smoke (the --dryrun
    slice of the combined-chaos scenario): a verified fleet checkpoint
    promotes through the publisher's deploy seam, the poisoned forge
    (testing.chaos.poison_fleet_checkpoint_dir) is REJECTED by the
    finite-params probe without ever reaching a deploy, and the
    ``gan4j_publish_*`` scrape surface + the ``/healthz`` publication
    block carry both outcomes."""
    import tempfile

    from gan_deeplearning4j_tpu.models import mlpgan_insurance as _ins
    from gan_deeplearning4j_tpu.serve.publisher import (
        CheckpointPublisher,
    )
    from gan_deeplearning4j_tpu.telemetry.exporter import (
        MetricsRegistry,
    )
    from gan_deeplearning4j_tpu.testing.chaos import (
        poison_fleet_checkpoint_dir,
    )
    from gan_deeplearning4j_tpu.train import fused_step as _fused
    from gan_deeplearning4j_tpu.train.fleet import (
        FleetCheckpointer,
        replicate_state,
    )

    cfg = _ins.InsuranceConfig()
    dis = _ins.build_discriminator(cfg)
    graphs = (dis, _ins.build_generator(cfg), _ins.build_gan(cfg),
              _ins.build_classifier(dis, cfg))
    state = replicate_state(_fused.state_from_graphs(*graphs), 2)
    deploys = []
    with tempfile.TemporaryDirectory(prefix="gan4j_pub_") as d:
        FleetCheckpointer(d, keep=8).save(1, state)
        pub = CheckpointPublisher(
            d, deploy_fn=lambda directory, step:
            (deploys.append(step), "promoted")[1])
        pub.poll_once()
        bad = poison_fleet_checkpoint_dir(d, tenant=0)
        pub.poll_once()
        rep = pub.report()
        reg = MetricsRegistry()
        reg.observe_publication(pub.report)
        body = reg.render()
        health = reg.health()
    blk = health.get("publication") or {}
    ok = (deploys == [1]
          and rep["promoted_total"] == 1
          and rep["rejected_total"] == 1
          and rep["last_step"] == 1
          and bad not in rep["promoted_steps"]
          and "gan4j_publish_promoted_total 1" in body
          and "gan4j_publish_rejected_total 1" in body
          and "gan4j_publish_last_step 1" in body
          and blk.get("last_step") == 1 and blk.get("ok") is True
          and health.get("serving_stale") is False)
    return {"ok": bool(ok), "deploys": deploys, "poisoned_step": bad,
            "publish": {k: rep[k] for k in
                        ("last_step", "promoted_total",
                         "rejected_total", "ok")}}


def scenario_bench(*, seed: int = 23, soak: bool = False,
                   budget_s: float = 180.0,
                   artifacts_dir: Optional[str] = None) -> dict:
    """The combined-chaos train→serve scenario (scenario/runner.py) as
    a bench verb: fleet-trains-while-mesh-serves under the seeded
    chaos schedule, typed verdict printed as one JSON line.  With
    ``soak`` the run additionally samples resources and must pass the
    ``bench_gate.check_soak`` leak gate — the scenario as a soak
    payload."""
    import tempfile

    from gan_deeplearning4j_tpu import bench_gate
    from gan_deeplearning4j_tpu.scenario import run_scenario

    if artifacts_dir is None:
        artifacts_dir = tempfile.mkdtemp(prefix="gan4j_scenario_")
    rec = run_scenario(artifacts_dir, seed=seed, soak=soak,
                       budget_s=budget_s)
    if soak:
        gate = bench_gate.check_soak(rec)
        rec["gate"] = gate
        rec["ok"] = bool(rec["ok"] and gate["ok"])
        if not gate["ok"]:
            rec["failures"].append(f"soak_gate: {gate}")
    return rec


def soak_bench(soak_seconds: float = 30.0, *, rate_rps: float = 40.0,
               leak: bool = False, leak_bytes: int = 256 << 10,
               artifacts_dir: Optional[str] = None) -> dict:
    """Wall-clock soak with a LEAK GATE: run the full serving stack
    (engine → router → gateway → client, real loopback sockets) under
    open-loop Poisson load for ``soak_seconds`` while a
    ``ResourceMonitor`` samples RSS / device bytes / fds / threads,
    then gate on ``telemetry.resources.leak_verdict`` — a robust
    (Theil–Sen) linear-trend test, not an absolute ceiling, so the
    verdict names WHICH resource grows and by how much per second.

    ``leak=True`` installs ``testing.chaos.LeakyDispatchSource`` — a
    reference-hoarding injector on the engine's dispatch seam — which
    MUST turn the verdict red (``"rss_bytes" in leaking``): the CI
    lane that proves the gate can fail.  Artifacts (the events
    timeline, the merged trace, the raw sample ring) land in
    ``artifacts_dir`` for post-mortem upload.

    ``ok`` folds: zero non-typed load failures, the
    ``gan4j_resource_*``/``gan4j_client_*`` series live in a REAL
    scrape, >= 95% complete trace trees over the soak's own traffic,
    and a clean ``bench_gate.check_soak`` verdict."""
    import tempfile
    import urllib.request

    import numpy as _np

    from gan_deeplearning4j_tpu import bench_gate
    from gan_deeplearning4j_tpu.models import dcgan_mnist as _dcgan
    from gan_deeplearning4j_tpu.parallel.inference import (
        ParallelInference,
    )
    from gan_deeplearning4j_tpu.serve import (
        Gateway,
        GatewayClient,
        Router,
        ServeEngine,
        run_socket_load,
        z_inputs,
    )
    from gan_deeplearning4j_tpu.telemetry import (
        MetricsRegistry,
        events as events_mod,
        serve_exporter,
        tracing as tracing_mod,
    )
    from gan_deeplearning4j_tpu.telemetry.resources import (
        ResourceMonitor,
        leak_verdict,
    )

    if artifacts_dir is None:
        artifacts_dir = tempfile.mkdtemp(prefix="gan4j_soak_")
    os.makedirs(artifacts_dir, exist_ok=True)
    events_path = os.path.join(artifacts_dir, "soak.events.jsonl")
    recorder = events_mod.EventRecorder(path=events_path)
    prev_rec = events_mod.install(recorder)
    registry = MetricsRegistry()
    rmon = ResourceMonitor(interval_s=0.25)
    rmon.start()
    registry.observe_resources(rmon.report)
    stop = serve_exporter(registry, 0)
    injector = None
    m_body = ""
    try:
        if leak:
            from gan_deeplearning4j_tpu.testing.chaos import (
                LeakyDispatchSource,
            )

            injector = LeakyDispatchSource(
                bytes_per_dispatch=leak_bytes).install()
        pi = ParallelInference(_dcgan.build_generator(),
                               buckets=(8, 32))
        engine = ServeEngine(infer=pi, watchdog_deadline_s=120.0)
        engine.warmup(_np.zeros((1, 2), _np.float32))
        router = Router(replicas=[engine])
        engine.start()
        try:
            with Gateway(router) as gw:
                client = GatewayClient("127.0.0.1", gw.port,
                                       retries=2, seed=11)
                registry.observe_serve(engine.report)
                registry.observe_gateway(gw.report)
                registry.observe_client(client.report)
                stats = run_socket_load(
                    client, rate_rps=rate_rps,
                    duration_s=float(soak_seconds),
                    make_inputs=z_inputs(2, seed=12),
                    encoding="npy", seed=13)
                url = f"http://127.0.0.1:{stop.port}/metrics"
                try:
                    with urllib.request.urlopen(url, timeout=10) as r:
                        m_body = (r.read().decode()
                                  if r.status == 200 else "")
                except OSError:
                    m_body = ""
                client.close()
        finally:
            router.stop()
    finally:
        # stop sampling BEFORE the injector releases its hoard — the
        # ring must end on the leaked state, not the cleaned-up one
        rmon.stop()
        if injector is not None:
            injector.uninstall()
        stop()
        events_mod.install(prev_rec)
        recorder.close()
    series_ok = all(s in m_body for s in (
        "gan4j_resource_rss_bytes ", "gan4j_resource_open_fds ",
        "gan4j_resource_threads ", "gan4j_client_reused_total ",
        "gan4j_client_retried_total "))
    samples = rmon.samples()
    verdict = leak_verdict(samples)
    merged = tracing_mod.merge_trace_files([events_path])
    with open(os.path.join(artifacts_dir,
                           "merged_trace.json"), "w") as f:
        json.dump(merged, f)
    with open(os.path.join(artifacts_dir,
                           "soak_samples.json"), "w") as f:
        json.dump(samples, f)
    load_ok = (stats["errors"] == 0 and stats["undrained"] == 0)
    trace_frac = merged["stats"]["complete_frac"]
    rec = {
        "metric": "dcgan_mnist_img_per_sec", "soak": True,
        "soak_seconds": float(soak_seconds),
        "rate_rps": float(rate_rps),
        "leak_injected": bool(leak),
        "leaked_dispatches": (injector.dispatches
                              if injector is not None else 0),
        "load": {k: stats[k] for k in
                 ("submitted", "completed", "errors", "shed",
                  "unavailable", "undrained", "p50_ms", "p99_ms")
                 if k in stats},
        "series_ok": bool(series_ok),
        "trace_complete_frac": round(trace_frac, 4),
        "trace": merged["stats"],
        "leak": verdict,
        "artifacts_dir": artifacts_dir,
    }
    gate = bench_gate.check_soak(rec)
    rec["gate"] = gate
    rec["ok"] = bool(load_ok and series_ok
                     and trace_frac >= 0.95 and gate["ok"])
    with open(os.path.join(artifacts_dir, "soak.json"), "w") as f:
        json.dump(rec, f, indent=1)
    return rec


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="capture a jax.profiler trace of the timed steps")
    p.add_argument("--skip-e2e", action="store_true")
    p.add_argument("--dryrun", action="store_true",
                   help="CI smoke: build + execute the fused program "
                        "(single and 2-step scanned, telemetry on) at a "
                        "toy batch and print one JSON line — no probe, "
                        "no measurement")
    tele = p.add_mutually_exclusive_group()
    tele.add_argument("--telemetry", dest="telemetry", action="store_true",
                      default=True,
                      help="measure the multistep/e2e paths WITH the "
                           "in-graph numerics telemetry block (default: "
                           "on — it rides the same dispatch; the <2%% "
                           "budget is part of the published number)")
    tele.add_argument("--no-telemetry", dest="telemetry",
                      action="store_false",
                      help="measure without the telemetry block (the "
                           "A/B baseline for its cost)")
    p.add_argument("--no-events", dest="events", action="store_false",
                   default=True,
                   help="run the e2e trainer WITHOUT the event recorder "
                        "(telemetry/events.py) — the A/B baseline for "
                        "its <2%% overhead budget; default: on, like "
                        "real runs")
    p.add_argument("--metrics-port", type=int, default=None,
                   metavar="PORT",
                   help="serve /metrics + /healthz during the e2e "
                        "trainer run (and the --dryrun smoke's "
                        "self-scrape); 0 = ephemeral")
    p.add_argument("--scenario", action="store_true",
                   help="combined-chaos train→serve scenario "
                        "(scenario/runner.py): a fleet trainer "
                        "checkpoints through preemption/device-loss "
                        "while a fleet serving mesh answers traffic, "
                        "the publisher carries every verified "
                        "checkpoint across via canary, and a seeded "
                        "chaos schedule breaks both planes; one typed-"
                        "verdict JSON line.  Combine with --soak to "
                        "also sample resources and ride the leak gate")
    p.add_argument("--scenario-seed", type=int, default=23,
                   help="chaos schedule / data / trainer seed")
    p.add_argument("--scenario-budget-s", type=float, default=180.0,
                   metavar="S",
                   help="wall budget recorded in the verdict (CI "
                        "lanes enforce it with their own timeout)")
    p.add_argument("--scenario-artifacts", default=None, metavar="DIR",
                   help="write scenario artifacts (scenario.json, "
                        "merged trace, child logs/events) here "
                        "instead of a fresh tempdir")
    p.add_argument("--soak", action="store_true",
                   help="wall-clock soak with the LEAK GATE: run the "
                        "full serving stack under load for "
                        "--soak-seconds while sampling process "
                        "resources, then gate on a robust linear-"
                        "trend leak verdict (telemetry/resources.py) "
                        "and print one JSON line")
    p.add_argument("--soak-seconds", type=float, default=30.0,
                   metavar="S",
                   help="soak wall-clock budget (default 30)")
    p.add_argument("--soak-rps", type=float, default=40.0,
                   help="open-loop arrival rate during the soak")
    p.add_argument("--soak-leak", action="store_true",
                   help="inject a reference-hoarding dispatch leak "
                        "(testing.chaos.LeakyDispatchSource) — the "
                        "verdict MUST go red; proves the gate can "
                        "fail")
    p.add_argument("--soak-artifacts", default=None, metavar="DIR",
                   help="write soak artifacts (events timeline, "
                        "merged trace, sample ring, soak.json) here "
                        "instead of a fresh tempdir")
    p.add_argument("--serve", action="store_true",
                   help="serving bench of record (serve/): ramp an "
                        "open-loop Poisson load to the continuous-"
                        "batching engine's saturation throughput and "
                        "print one JSON line — saturation req/s plus "
                        "p50/p99 at --serve-load-frac of it as the v7 "
                        "spread block (the regression-gated 'serve' "
                        "series), measured under an armed recompile "
                        "sentinel")
    p.add_argument("--serve-stage-s", type=float, default=2.0,
                   metavar="S",
                   help="seconds per load stage (ramp and SLO repeats)")
    p.add_argument("--serve-repeats", type=int, default=5,
                   help="SLO-point repeat stages for the spread block")
    p.add_argument("--serve-load-frac", type=float, default=0.8,
                   help="fraction of measured saturation the SLO "
                        "latency numbers are reported at")
    p.add_argument("--serve-start-rps", type=float, default=50.0,
                   help="first rung of the geometric saturation ramp")
    p.add_argument("--gateway", action="store_true",
                   help="(with --serve) re-measure the SLO operating "
                        "point through the HTTP front door — gateway + "
                        "router + retrying client over a real socket "
                        "(serve/gateway.py) — publishing the "
                        "regression-gated 'gateway' series; the p50 "
                        "delta vs the 'serve' series is the wire cost")
    p.add_argument("--mesh", action="store_true",
                   help="(with --serve) measure the SLO operating "
                        "point once more through the MESH TIER — the "
                        "same load balanced over two standalone "
                        "replica processes by MeshRouter "
                        "(serve/replica.py + mesh.py) — publishing "
                        "the regression-gated 'mesh' series; the p50 "
                        "delta vs 'gateway' is the multi-process cost")
    p.add_argument("--fleet", action="store_true",
                   help="multi-tenant fleet bench of record "
                        "(train/fleet.py): sweep tenant counts as "
                        "bounded subprocess stages and print one JSON "
                        "line — flagship tenants*steps/sec with the v7 "
                        "spread block, the multiple over the "
                        "sequential single-model equivalent, and the "
                        "hlo_cost.py attribution of the scaling knee")
    p.add_argument("--fleet-stage", type=int, default=None, metavar="N",
                   help="(internal sweep unit) measure ONE tenant "
                        "count in this process and print one JSON line")
    p.add_argument("--fleet-sweep", default=",".join(
                       str(n) for n in FLEET_SWEEP), metavar="N,N,...",
                   help="tenant counts for the --fleet sweep")
    p.add_argument("--fleet-flagship", type=int, default=FLEET_FLAGSHIP,
                   help="the tenant count the headline number and the "
                        "regression-gated 'fleet' series report")
    p.add_argument("--fleet-batch", type=int, default=FLEET_BATCH,
                   help="per-tenant batch (default: FleetConfig's 16)")
    p.add_argument("--fleet-run-wall", type=int, default=None,
                   metavar="N",
                   help="(internal sweep unit) wall seconds of one "
                        "complete RUN — build + compile + "
                        "--fleet-run-steps steps — at N tenants (0 = "
                        "the plain single-model program); one JSON line")
    p.add_argument("--fleet-run-steps", type=int,
                   default=FLEET_RUN_STEPS, metavar="K",
                   help="steps per run for the sequential-equivalent "
                        "accounting (default: FleetConfig's 100)")
    p.add_argument("--fleet-stage-timeout", type=float, default=900.0,
                   metavar="S",
                   help="per-stage subprocess budget; a stage killed at "
                        "the deadline records a structured failure and "
                        "the sweep continues")
    p.add_argument("--batch", type=int, default=DEFAULT_BATCH,
                   help="global batch (default: the reference's 200; the "
                        "CPU-baseline ratio is only reported at 200, "
                        "apples to apples)")
    from gan_deeplearning4j_tpu.runtime import backend

    backend.add_bf16_flag(p)
    s2d = p.add_mutually_exclusive_group()
    s2d.add_argument("--s2d", dest="s2d", action="store_true", default=None,
                     help="force ON the space-to-depth rewrite of the "
                          "C_in=1 first conv (exact reindexing; "
                          "ops/conv.py).  Default: auto — on for TPU "
                          "(measured +5%% multistep, RESULTS r3), off on "
                          "CPU")
    s2d.add_argument("--no-s2d", dest="s2d", action="store_false",
                     help="force OFF the space-to-depth rewrite (the A/B "
                          "baseline on TPU)")
    p.add_argument("--pallas-updater", action="store_true",
                   help="Pallas one-pass RmsProp update chain for big "
                        "leaves (ops/pallas/fused_update.py)")
    p.add_argument("--mp", action="store_true",
                   help="full mixed precision for the MAIN measurement "
                        "(bf16 params/activations, f32 master/BN/loss — "
                        "backend.compute_bf16).  The fast-mode block "
                        "always measures with it on")
    p.add_argument("--skip-fast", action="store_true",
                   help="skip the fast-mode (s2d+bf16+mp, batch 1600) "
                        "multistep measurement block")
    p.add_argument("--skip-celeba", action="store_true",
                   help="skip the CelebA-64 GANPair multistep MFU block")
    p.add_argument("--celeba-batch", type=int, default=CELEBA_BATCH,
                   help="CelebA block batch (default: the roadmap "
                        "trainer's 128)")
    # -- the overlap experiment series' A/B axes (RESULTS.md): each
    # restructure is default-ON; its --no- flag measures the previous
    # lowering in the same process, and --xla-flags drives the XLA
    # scheduling experiments (one flag set per PROCESS — see below) --
    p.add_argument("--xla-flags", default=None, metavar="FLAGS",
                   help="extra XLA flags for the measured programs "
                        "(XLA_FLAGS syntax, space-separated), e.g. "
                        "'--xla_tpu_enable_latency_hiding_scheduler="
                        "true'.  XLA reads them ONCE at backend init, so "
                        "this fails loudly if a backend already exists — "
                        "benchmarks/overlap_ab.py re-execs one process "
                        "per flag set")
    p.add_argument("--no-carry-dedup", dest="carry_dedup",
                   action="store_false", default=True,
                   help="measure the multistep program WITHOUT the scan-"
                        "carry weight dedup (the pre-restructure carry "
                        "with its mirrored-W/b per-step HBM copies — the "
                        "A/B baseline; train/fused_step.py)")
    p.add_argument("--no-upsample-sum-bwd", dest="upsample_sum_bwd",
                   action="store_false", default=True,
                   help="measure with the autodiff broadcast+reduce "
                        "upsample backward (the 60.2MB sink of "
                        "hlo_cost_r5) instead of the restructured "
                        "reshape+strided-sum (ops/upsample.py)")
    p.add_argument("--no-pool-argmax-bwd", dest="pool_argmax_bwd",
                   action="store_false", default=True,
                   help="measure with the select-and-scatter maxpool "
                        "backward (the 41.9MB sink of hlo_cost_r5) "
                        "instead of the recomputed-argmax scatter "
                        "(ops/pool.py)")
    args = p.parse_args(argv)

    if args.xla_flags:
        # before ANY backend init in this process; strict — a silently
        # ignored flag set would A/B two identical programs
        backend.apply_xla_flags(args.xla_flags, strict=True)
    from gan_deeplearning4j_tpu.ops import pool as pool_mod
    from gan_deeplearning4j_tpu.ops import upsample as upsample_mod

    upsample_mod.set_sum_bwd(args.upsample_sum_bwd)
    pool_mod.set_argmax_bwd(args.pool_argmax_bwd)

    if args.dryrun:
        print(json.dumps(dryrun(telemetry=args.telemetry,
                                metrics_port=args.metrics_port)))
        return
    if args.scenario:
        rec = scenario_bench(seed=args.scenario_seed,
                             soak=args.soak,
                             budget_s=args.scenario_budget_s,
                             artifacts_dir=args.scenario_artifacts)
        print(json.dumps(rec, default=float))
        sys.exit(0 if rec["ok"] else 1)
    if args.soak:
        rec = soak_bench(soak_seconds=args.soak_seconds,
                         rate_rps=args.soak_rps,
                         leak=args.soak_leak,
                         artifacts_dir=args.soak_artifacts)
        print(json.dumps(rec))
        return
    if args.serve:
        print(json.dumps(serve_bench(
            start_rps=args.serve_start_rps,
            stage_s=args.serve_stage_s,
            repeats=args.serve_repeats,
            load_frac=args.serve_load_frac,
            gateway=args.gateway,
            mesh=args.mesh)))
        return
    if args.fleet_stage is not None:
        print(json.dumps(fleet_stage_time(
            args.fleet_stage, batch=args.fleet_batch, want_flops=True)))
        return
    if args.fleet_run_wall is not None:
        print(json.dumps(fleet_run_wall(
            args.fleet_run_wall, args.fleet_run_steps,
            batch=args.fleet_batch)))
        return
    if args.fleet:
        sweep = tuple(int(n) for n in args.fleet_sweep.split(",") if n)
        print(json.dumps(fleet_bench(
            sweep=sweep, flagship=args.fleet_flagship,
            batch=args.fleet_batch,
            stage_timeout_s=args.fleet_stage_timeout,
            run_steps=args.fleet_run_steps)))
        return

    # idempotent (not latch-on): repeated in-process main() calls — the
    # A/B measurement pattern — must reset state for the baseline run
    backend.configure(conv_s2d=args.s2d, compute_bf16=args.mp)
    from gan_deeplearning4j_tpu.ops import pallas as pallas_mod

    pallas_mod.enable(args.pallas_updater)

    global BATCH
    BATCH = args.batch

    import jax

    from gan_deeplearning4j_tpu.utils import maybe_trace

    default = jax.devices()[0]
    cpu = jax.devices("cpu")[0]

    # baseline: CPU protocol throughput, measured once and cached
    # (defined at the reference's batch 200 — no baseline row otherwise)
    baseline = None
    if BATCH == 200 and os.path.exists(BASELINE_PATH):
        with open(BASELINE_PATH) as f:
            cached = json.load(f)
        if cached.get("version") == METHODOLOGY_VERSION:
            baseline = cached.get("cpu_img_per_sec")
    if not baseline and BATCH == 200:
        # a CPU step is seconds long — a short schedule is precise enough
        # for a denominator three orders of magnitude below the TPU number
        cpu_step, _ = protocol_step_time(
            cpu, steps_lo=1, steps_hi=4, repeats=1)
        baseline = BATCH / cpu_step
        with open(BASELINE_PATH, "w") as f:
            json.dump({
                "version": METHODOLOGY_VERSION,
                "cpu_img_per_sec": baseline,
                "note": "fused three-graph protocol step on host CPU, batch "
                        "200 (stand-in for the reference's nd4j-native CPU run)",
            }, f, indent=1)

    # bf16 applies to the DEVICE measurement only — the cached CPU
    # baseline (measured above when absent) is always reference-f32
    measured_bf16 = args.bf16 and default.platform != "cpu"
    if measured_bf16:
        backend.configure(matmul_bf16=True)

    with maybe_trace(args.profile):
        if default.platform == "cpu":
            if not baseline:
                raise SystemExit(
                    "CPU-only host with --batch != 200: no baseline to "
                    "report (the cached baseline is batch-200 only)")
            value, flops = baseline, None
            step_s = BATCH / baseline
            multi_s = None
            multi_spread = None
        else:
            step_s, flops = protocol_step_time(default, want_flops=True)
            value = BATCH / step_s
            multi = protocol_multistep_time(
                default, telemetry=args.telemetry,
                carry_dedup=args.carry_dedup, detail=True)
            multi_s = multi["seconds"]
            multi_spread = multi["spread"]

    # v6: the headline is the multistep (trainer-default) path; the
    # single-dispatch rate is tunnel-load-dependent and secondary
    headline = BATCH / multi_s if multi_s else value
    out = {
        "metric": "dcgan_mnist_img_per_sec",
        "value": round(headline, 2),
        "unit": "img/sec/chip",
        "batch": BATCH,
        "step_ms": round((multi_s if multi_s else step_s) * 1e3, 3),
        # keyed on what RAN, not on the flag: --bf16 on a CPU-only host
        # still reports the f32 baseline
        "dtype": "bf16" if measured_bf16 else "f32",
        # full mixed precision active for the MAIN measurement (--mp)
        "compute_bf16": bool(backend.config().compute_bf16
                             and default.platform != "cpu"),
        "conv_s2d": backend.conv_s2d_enabled(),
        # whether the in-graph numerics block rode the measured programs
        # (the e2e blocks honor it on every platform; the CPU headline
        # itself comes from the cached telemetry-free baseline)
        "telemetry": bool(args.telemetry),
        # the overlap series' A/B axes, recorded so every capture is
        # attributable to an exact lowering configuration
        "carry_dedup": bool(args.carry_dedup),
        "upsample_sum_bwd": bool(args.upsample_sum_bwd),
        "pool_argmax_bwd": bool(args.pool_argmax_bwd),
        "xla_flags": args.xla_flags,
        "methodology_version": METHODOLOGY_VERSION,
    }
    if baseline:
        out["vs_baseline"] = round(headline / baseline, 3)
    out["single_dispatch_img_per_sec"] = round(value, 2)
    out["single_dispatch_step_ms"] = round(step_s * 1e3, 3)
    if multi_s:
        # kept under their historical keys for cross-round comparability
        out["multistep_img_per_sec"] = round(BATCH / multi_s, 2)
        out["multistep_step_ms"] = round(multi_s * 1e3, 3)
        out["spread"] = multi_spread
    peak = _peak_flops(default)
    if flops:
        out["flops_per_step"] = flops
        if peak:
            # v6: headline mfu follows the headline (multistep) time
            out["mfu"] = round(flops / (multi_s or step_s) / peak, 4)
        if peak and multi_s:
            out["multistep_mfu"] = round(flops / multi_s / peak, 4)

    if default.platform != "cpu" and not args.skip_fast:
        # the documented TPU fast mode, measured every run alongside the
        # reference-numerics default: conv rewrites (s2d + the r4
        # output-side d2s) + bf16 MXU operands + full mixed precision.
        # Its MFU uses the cost model of ITS OWN compiled program (the
        # rewrites change logical flops slightly).
        prev = backend.config()
        backend.configure(conv_s2d=True, matmul_bf16=True,
                          compute_bf16=True)
        try:
            fast_d = protocol_multistep_time(
                default, repeats=REPEATS, batch=FAST_BATCH,
                telemetry=args.telemetry, carry_dedup=args.carry_dedup,
                detail=True)
            fast_s, fast_flops = fast_d["seconds"], fast_d["flops"]
            fast = {
                "batch": FAST_BATCH,
                "multistep_img_per_sec": round(FAST_BATCH / fast_s, 2),
                "multistep_step_ms": round(fast_s * 1e3, 3),
                "spread": fast_d["spread"],
            }
            if fast_flops and peak:
                fast["flops_per_step"] = fast_flops
                fast["multistep_mfu"] = round(
                    fast_flops / fast_s / peak, 4)
            out["fast_mode"] = fast
        finally:
            backend.configure(
                conv_s2d=prev.conv_s2d, matmul_bf16=prev.matmul_bf16,
                compute_bf16=prev.compute_bf16)
    if default.platform != "cpu" and not args.skip_celeba:
        # CelebA-64: the TPU-scale-conv flagship (VERDICT r4 #1).  Default
        # numerics first (comparable with roadmap_main's examples_per_sec,
        # which counts batch*(n_critic+1) — both the D and G passes), then
        # the fast mode (bf16 MXU operands + mixed precision) at the same
        # batch; MFU divides each program's OWN cost-model FLOPs.
        def celeba_block(b):
            d = celeba_multistep_time(default, batch=b, detail=True)
            t, fl = d["seconds"], d["flops"]
            blk = {
                "batch": b,
                "multistep_img_per_sec": round(2 * b / t, 2),
                "multistep_step_ms": round(t * 1e3, 3),
                "spread": d["spread"],
            }
            if fl and peak:
                blk["flops_per_step"] = fl
                blk["multistep_mfu"] = round(fl / t / peak, 4)
            return blk

        out["celeba"] = celeba_block(args.celeba_batch)
        if not args.skip_fast:
            prev = backend.config()
            backend.configure(matmul_bf16=True, compute_bf16=True)
            try:
                out["celeba_fast"] = celeba_block(args.celeba_batch)
            finally:
                backend.configure(
                    matmul_bf16=prev.matmul_bf16,
                    compute_bf16=prev.compute_bf16)
    out["events"] = bool(args.events)
    if not args.skip_e2e:
        with tempfile.TemporaryDirectory() as tmp:
            e2e, e2e_detail = e2e_img_per_sec(
                tmp, telemetry=args.telemetry, detail=True,
                events_enabled=args.events,
                metrics_port=args.metrics_port)
            out["e2e_img_per_sec"] = round(e2e, 2)
            # the run's goodput ledger + manifest id: every second of
            # the e2e window attributed, and the number traceable to the
            # exact config/versions run_manifest.json recorded
            out["e2e_goodput"] = e2e_detail["goodput"]
            out["e2e_run_id"] = e2e_detail["run_id"]
            out["e2e_stream_img_per_sec"] = round(
                e2e_img_per_sec(tmp, data_on_device=False,
                                telemetry=args.telemetry,
                                events_enabled=args.events), 2)
        if default.platform != "cpu":
            # host->device link bandwidth at measurement time: the
            # streaming path's sensitivity axis.  With the r5 dedup tier
            # the e2e_stream number no longer rides it (only the index
            # schedule streams per chunk), but epoch >> chunk datasets
            # still do: sustainable img/s there = link_BW / bytes_per_row
            # (u8: 824 B for the CV workload).
            import jax.numpy as jnp
            import numpy as np

            blob = np.zeros((8 << 20,), np.uint8)
            total = jax.jit(lambda a: jnp.sum(a.astype(jnp.int32)))
            t_best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                # fence via a scalar REDUCTION of the uploaded buffer —
                # device_fence would read the 8 MB back and time the
                # downlink too
                _fence(total(jax.device_put(blob, default)))
                t_best = min(t_best, time.perf_counter() - t0)
            out["link_mb_s"] = round(blob.nbytes / t_best / 1e6, 1)
    if multi_s and default.platform != "cpu":
        # variance-aware regression verdict against the cached last-good
        # device capture (bench_gate.py): tolerance scales with BOTH
        # captures' measured IQRs, floored at 5% — informational in the
        # JSON line (the shim's exit-0 contract holds; CI's red X is the
        # dryrun's bench_stable_ok, and the driver reads this verdict)
        from gan_deeplearning4j_tpu import bench_gate

        out["regression_gate"] = bench_gate.check_against_lastgood(
            out, os.path.join(os.path.dirname(BASELINE_PATH),
                              "BENCH_LASTGOOD.json"))
    print(json.dumps(out))


def cli(argv=None) -> None:
    """Console-script entry (gan4j-bench): a fresh process by definition,
    so honoring the env platform here cannot clobber an in-process
    override — unlike main(), which tests may import and call."""
    from gan_deeplearning4j_tpu.runtime import backend

    backend.apply_env_platform()
    main(argv)


if __name__ == "__main__":
    sys.exit(cli())
