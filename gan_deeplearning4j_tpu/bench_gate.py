"""Variance-aware bench regression gate (the "bench of record" half of
the overlap series, RESULTS.md).

A fixed percentage threshold over a noisy capture is either deaf (too
wide) or a flake machine (too tight) — the r5 celeba capture's 11%
min/max spread would trip any <11% gate on pure tunnel noise.  The v7
captures carry a median±IQR spread block per multistep series
(bench._slope_stats), so the gate can scale its tolerance to the
MEASURED dispersion of both captures:

    allowed slowdown (ms) = max(rel_floor * old_median,
                                iqr_mult * (old_IQR + new_IQR))

A regression verdict therefore means "slower by more than the noise of
both measurements plus the floor", not "slower than a guess".  Series
present in only one capture are reported as ``skipped`` (a new bench
block must not fail the gate retroactively; a REMOVED one is loud).

Used by ``bench.py --dryrun`` (bench_stable_ok: the gate must PASS the
capture against itself and provably FAIL an injected 2x-regressed copy)
and by the measured bench run, which checks its fresh capture against
``BENCH_LASTGOOD.json`` and ships the verdict in the JSON line.
"""

from __future__ import annotations

import json
from typing import List, Optional, Tuple

# (human label, path to the step-time block) — every multistep series a
# capture can carry.  step_ms medians compare LOWER-IS-BETTER.
SERIES: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("multistep", ()),
    ("fast_mode", ("fast_mode",)),
    ("celeba", ("celeba",)),
    ("celeba_fast", ("celeba_fast",)),
    ("fleet", ("fleet",)),
    ("fleet_lifecycle", ("fleet_lifecycle",)),
    ("serve", ("serve",)),
    ("gateway", ("gateway",)),
    ("mesh", ("mesh",)),
)

# Tolerance floor: 5% — the day-to-day jitter of a healthy capture on
# the shared tunnel (BENCH_r0*.json history), below which a "regression"
# is indistinguishable from load.  IQR multiplier: 3 — the slope sets
# are medians-of-windows already, so their IQR understates tail noise.
REL_FLOOR = 0.05
IQR_MULT = 3.0


def _dig(capture: dict, path: Tuple[str, ...]) -> Optional[dict]:
    node = capture
    for key in path:
        if not isinstance(node, dict) or key not in node:
            return None
        node = node[key]
    return node if isinstance(node, dict) else None


def _median_iqr(block: dict) -> Tuple[Optional[float], float]:
    """(median_ms, iqr_ms) of a bench block: the spread block when the
    capture is v7+, falling back to the flat step_ms (IQR 0 — the gate
    then runs on the floor alone against legacy captures)."""
    spread = block.get("spread") if isinstance(block.get("spread"),
                                               dict) else None
    if spread is not None:
        med = spread.get("median_ms")
        iqr = spread.get("iqr_ms", 0.0)
        if isinstance(med, (int, float)):
            return float(med), float(iqr or 0.0)
    # the per-series LASTGOOD record form: the stats live flat
    med = block.get("median_ms")
    if isinstance(med, (int, float)):
        return float(med), float(block.get("iqr_ms") or 0.0)
    med = block.get("multistep_step_ms", block.get("step_ms"))
    if isinstance(med, (int, float)):
        return float(med), 0.0
    return None, 0.0


def series_stats(capture: dict) -> List[Tuple[str, float, float]]:
    """``[(label, median_ms, iqr_ms)]`` for every series the capture
    carries — the exporter feed (``gan4j_bench_*``,
    docs/OBSERVABILITY.md) and the gate read the capture one way."""
    out: List[Tuple[str, float, float]] = []
    for label, path in SERIES:
        block = _dig(capture, path)
        if block is None:
            continue
        med, iqr = _median_iqr(block)
        if med is not None:
            out.append((label, med, iqr))
    return out


def _lastgood_block(lastgood: dict, label: str,
                    path: Tuple[str, ...]) -> Optional[dict]:
    """The last-good side of one series.  A PER-SERIES-KEYED record
    (``{"series": {label: {median_ms, iqr_ms}}}``, written by
    ``update_lastgood``) wins over the legacy whole-capture form: the
    fleet bench and the main bench are separate invocations, so a
    single-capture LASTGOOD can never hold both and whichever ran last
    would silently un-gate the other."""
    series = lastgood.get("series")
    if isinstance(series, dict) and isinstance(series.get(label), dict):
        return series[label]
    return _dig(lastgood, path)


def check_capture(capture: dict, lastgood: dict,
                  rel_floor: float = REL_FLOOR,
                  iqr_mult: float = IQR_MULT) -> dict:
    """Gate ``capture`` against ``lastgood``.  Returns a verdict dict:
    ``ok`` (no series regressed), per-series ``checks`` rows with the
    allowed/observed slowdown, and ``skipped`` for series missing from
    either side.  Only step-time medians are gated — throughput and MFU
    are derived from them, and flops change legitimately with lowering
    work."""
    checks: List[dict] = []
    skipped: List[str] = []
    for label, path in SERIES:
        new_block = _dig(capture, path)
        old_block = _lastgood_block(lastgood, label, path)
        if new_block is None or old_block is None:
            skipped.append(label)
            continue
        new_med, new_iqr = _median_iqr(new_block)
        old_med, old_iqr = _median_iqr(old_block)
        if new_med is None or old_med is None:
            skipped.append(label)
            continue
        allowed = max(rel_floor * old_med, iqr_mult * (old_iqr + new_iqr))
        slower_by = new_med - old_med
        checks.append({
            "series": label,
            "old_median_ms": old_med,
            "new_median_ms": new_med,
            "old_iqr_ms": old_iqr,
            "new_iqr_ms": new_iqr,
            "allowed_slowdown_ms": round(allowed, 4),
            "slower_by_ms": round(slower_by, 4),
            "regressed": bool(slower_by > allowed),
        })
    verdict = {
        "ok": bool(checks) and not any(c["regressed"] for c in checks),
        "compared": len(checks),
        "checks": checks,
        "skipped": skipped,
        "rel_floor": rel_floor,
        "iqr_mult": iqr_mult,
    }
    if not checks and series_stats(capture):
        # The capture carries measurable series but NONE overlap the
        # lastgood record (e.g. a first fleet run against a legacy
        # main-only baseline): that is the documented "new series must
        # not fail retroactively" case, so the verdict is a vacuous
        # pass with a reason — promote via update_lastgood to arm the
        # gate.  A capture with no series at all stays not-ok.
        verdict["ok"] = True
        verdict["reason"] = ("no overlapping series with lastgood; "
                             "vacuous pass — promote with update_lastgood")
    return verdict


def update_lastgood(lastgood_path: str, capture: dict) -> dict:
    """Merge a capture the operator accepts as good into the per-series
    LASTGOOD record: only the series THIS capture carries are updated,
    so a fleet run and a main bench run maintain their own baselines in
    one file.  A legacy whole-capture record is converted on first
    merge.  Returns the record written.  (Deliberately not called by
    the bench itself — auto-accepting every run would turn regressions
    into baselines; the driver promotes a run after reading the gate.)"""
    try:
        with open(lastgood_path) as f:
            prior = json.load(f)
    except (OSError, ValueError):
        prior = {}
    series = dict(prior.get("series") or {})
    for label, path in SERIES:   # convert a legacy record once
        if label not in series:
            block = _dig(prior, path)
            if block is not None:
                med, iqr = _median_iqr(block)
                if med is not None:
                    series[label] = {"median_ms": med, "iqr_ms": iqr}
    for label, med, iqr in series_stats(capture):
        series[label] = {"median_ms": med, "iqr_ms": iqr}
    # prior top-level keys survive the merge: the headline capture the
    # bench shim cites on skipped rounds ("cached") must not be eaten
    # by a fleet promotion that only knows its own series
    record = dict(prior)
    record["series"] = series
    record["methodology_version"] = (
        capture.get("methodology_version")
        or prior.get("methodology_version"))
    with open(lastgood_path, "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    return record


def check_against_lastgood(capture: dict, lastgood_path: str) -> dict:
    """The measured-run entry: gate a fresh capture against the cached
    last-good record.  Missing/unparsable cache = vacuous pass with a
    reason (first capture on a fresh checkout must not fail)."""
    try:
        with open(lastgood_path) as f:
            lastgood = json.load(f)
    except (OSError, ValueError) as e:
        return {"ok": True, "compared": 0, "checks": [],
                "skipped": [s for s, _ in SERIES],
                "reason": f"no usable lastgood: {e}"}
    return check_capture(capture, lastgood)


def check_soak(capture: dict) -> dict:
    """Gate a ``bench --soak`` capture: the leak verdict must EXIST
    with its full typed structure (a soak that forgot to sample, or a
    verdict missing a resource block, is a broken gate — fail loudly,
    not vacuously) and must be clean.  Returns the familiar
    ``{"ok", "checks", "failures"}`` shape; a red verdict fails with
    the leaking resource names so CI logs say WHAT grew, not just
    that something did."""
    checks: list = []
    failures: list = []
    verdict = capture.get("leak")
    if not isinstance(verdict, dict):
        return {"ok": False, "checks": checks,
                "failures": ["capture has no leak verdict"]}
    if verdict.get("type") != "resource_leak":
        failures.append(
            f"verdict type {verdict.get('type')!r} != 'resource_leak'")
    for key in ("ok", "samples", "window_s", "leaking", "resources"):
        if key not in verdict:
            failures.append(f"verdict missing {key!r}")
    resources = verdict.get("resources")
    if isinstance(resources, dict):
        for res in ("rss_bytes", "device_bytes", "open_fds",
                    "threads"):
            if res not in resources:
                failures.append(f"verdict missing resource {res!r}")
            else:
                checks.append(res)
    else:
        failures.append("verdict resources is not a dict")
    # a no-trend-claim verdict (too few samples) is a broken soak,
    # not a clean one: the gate must not pass vacuously
    if not failures and "reason" in verdict:
        failures.append(f"no trend claim: {verdict['reason']}")
    if not failures and verdict.get("ok") is not True:
        failures.append(
            "resource leak: " + ",".join(verdict.get("leaking") or
                                         ["<unnamed>"]))
    return {"ok": not failures, "checks": checks,
            "failures": failures}
