"""Checkpoint / resume — closing the reference's save-only gap.

The reference persists its four models ONCE, at the end of the run
(``ModelSerializer.writeModel(..., saveUpdater=true)``,
dl4jGANComputerVision.java:529-533) and has no restore path at all
(SURVEY.md §5).  This module adds periodic multi-graph training-state
checkpoints with pruning and resume: all graphs' params + updater state
(via graph/serialization.py), the step counter, and arbitrary extra state
(e.g. the pre-loop softened-label noise, which is part of run state
because the reference samples it once — SURVEY.md appendix).

Layout: ``{dir}/ckpt_{step}/`` with one model zip per graph plus
``state.json`` / ``state.npz`` and a ``MANIFEST.json`` (per-file SHA-256
+ sizes, written and fsynced last); everything is written to a temp dir,
fsynced, and atomically renamed, so a kill at ANY byte leaves either no
checkpoint entry or one whose manifest verifies.  ``restore()`` verifies
before loading and falls back to the newest checkpoint that passes.
``AsyncCheckpointer`` moves the serialize/fsync half onto a background
worker (the training thread pays only the host snapshot) with barriers
at the next save, at every read, and at exit.  Failure model and format:
docs/FAULT_TOLERANCE.md.
"""

from gan_deeplearning4j_tpu.checkpoint.async_checkpointer import (
    AsyncCheckpointer,
)
from gan_deeplearning4j_tpu.checkpoint.checkpointer import (
    CheckpointCorruptError,
    CheckpointMeshMismatchError,
    NoVerifiedCheckpointError,
    TrainCheckpointer,
)

__all__ = ["AsyncCheckpointer", "CheckpointCorruptError",
           "CheckpointMeshMismatchError", "NoVerifiedCheckpointError",
           "TrainCheckpointer"]
