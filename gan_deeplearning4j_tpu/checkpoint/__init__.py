"""Checkpoint / resume — closing the reference's save-only gap.

The reference persists its four models ONCE, at the end of the run
(``ModelSerializer.writeModel(..., saveUpdater=true)``,
dl4jGANComputerVision.java:529-533) and has no restore path at all
(SURVEY.md §5).  This module adds periodic multi-graph training-state
checkpoints with pruning and resume: all graphs' params + updater state
(via graph/serialization.py), the step counter, and arbitrary extra state
(e.g. the pre-loop softened-label noise, which is part of run state
because the reference samples it once — SURVEY.md appendix).

Layout: ``{dir}/ckpt_{step}/`` with one model zip per graph plus
``state.json`` / ``state.npz``; written to a temp dir and atomically
renamed, so a killed run never leaves a half checkpoint.
"""

from gan_deeplearning4j_tpu.checkpoint.checkpointer import TrainCheckpointer

__all__ = ["TrainCheckpointer"]
