"""Crash-safe ASYNC checkpointing — unblock the training thread.

A synchronous ``TrainCheckpointer.save`` pays snapshot + zip/DEFLATE +
fsync on the training thread; at real checkpoint cadences that is the
dominant entry in the goodput ``checkpoint`` phase.  ``AsyncCheckpointer``
splits the save at the natural seam ``checkpointer.py`` already exposes:

  training thread:  ``snapshot_state``  — host copies of device state
                    (cheap; overlapped transfers), then hand off
  worker thread:    ``write_snapshot``  — serialize, fsync, atomic
                    rename, prune

so the goodput ``checkpoint`` phase measures ONLY the blocking snapshot
portion (the before/after number bench ``--dryrun`` reports).  The
on-disk artifact is byte-identical to a synchronous save of the same
state (deterministic serialization — graph/serialization.py), manifest
hashes included.

Barriers (the crash-safety half of the contract):

* at the NEXT ``save()`` — at most one save is ever in flight, so a
  checkpoint can never be overtaken by its successor;
* at every read (``restore``/``steps``/``latest_step``/``verify``) — a
  reader can never observe the directory mid-write;
* at ``wait()``/``close()`` and interpreter exit (atexit, same WeakSet
  discipline as utils/metrics.py) — the final save of a run is durable
  before the process goes away.

A worker failure is re-raised on the training thread at the next
barrier — a failing checkpoint is a training fault, not a silent gap in
the save history.
"""

from __future__ import annotations

import atexit
import os
import queue
import threading
import weakref
from typing import Dict, Optional

from gan_deeplearning4j_tpu.checkpoint.checkpointer import (
    _NO_TARGET,
    TrainCheckpointer,
    snapshot_state,
)

_OPEN: "weakref.WeakSet" = weakref.WeakSet()
_ATEXIT_REGISTERED = False


def _close_open() -> None:
    for ck in list(_OPEN):
        try:
            ck.close()
        except Exception:  # gan4j-lint: disable=swallowed-exception — interpreter exit: never raise from the atexit hook
            pass


class AsyncCheckpointer:
    """Background-serializing wrapper around a ``TrainCheckpointer``.

    Drop-in for the trainer's checkpoint calls: ``save`` returns after
    the host snapshot; everything else barriers first, so observable
    directory state is exactly the synchronous checkpointer's.
    """

    def __init__(self, inner: TrainCheckpointer):
        self.inner = inner
        self._q: "queue.Queue[Optional[Dict]]" = queue.Queue(maxsize=1)
        self._error: Optional[BaseException] = None
        self._closed = False
        self._thread = threading.Thread(
            target=self._worker, name="gan4j-ckpt-writer", daemon=True)
        self._thread.start()
        global _ATEXIT_REGISTERED
        _OPEN.add(self)
        if not _ATEXIT_REGISTERED:
            atexit.register(_close_open)
            _ATEXIT_REGISTERED = True

    @property
    def directory(self) -> str:
        return self.inner.directory

    @property
    def keep(self) -> int:
        return self.inner.keep

    # -- worker --------------------------------------------------------------

    def _worker(self) -> None:
        from gan_deeplearning4j_tpu.telemetry import events

        while True:
            snap = self._q.get()
            try:
                if snap is None:
                    return
                # the span (and write_snapshot's serialize/commit
                # sub-spans) land in the run's event log from THIS
                # thread — a crash mid-save shows up in the flight
                # record as the in-flight/errored checkpoint.write
                with events.span("checkpoint.write",
                                 step=snap["scalars"]["step"]):
                    self.inner.write_snapshot(snap)
            except BaseException as e:  # re-raised at the next barrier
                if self._error is None:
                    self._error = e
            finally:
                self._q.task_done()

    def _reraise(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    # -- API -----------------------------------------------------------------

    def save(self, step: int, graphs: Dict[str, object],
             extra: Optional[Dict] = None,
             mesh_spec: Optional[Dict] = None) -> str:
        """Barrier on the previous save, snapshot on THIS thread, enqueue
        serialization.  Returns the final checkpoint path (valid once the
        worker commits it — call ``wait()`` for durability)."""
        from gan_deeplearning4j_tpu.telemetry import events

        self.wait()  # barrier at the next save; surfaces worker errors
        with events.span("checkpoint.snapshot", step=step):
            snap = snapshot_state(graphs, step, extra,
                                  mesh_spec=mesh_spec)
        if self._closed:  # post-close (atexit ordering): degrade to sync
            return self.inner.write_snapshot(snap)
        self._q.put(snap)
        return os.path.join(self.inner.directory, f"ckpt_{step}")

    def wait(self) -> None:
        """Block until every enqueued save is durable on disk; surface
        any worker error."""
        self._q.join()
        self._reraise()

    def close(self) -> None:
        """Drain, stop the worker, surface pending errors.  Idempotent;
        the instance degrades to synchronous saves afterwards."""
        if not self._closed:
            self._q.join()
            self._closed = True
            self._q.put(None)
            self._thread.join(timeout=10)
            _OPEN.discard(self)
        self._reraise()

    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        try:
            self.close()
        except BaseException:
            if exc == (None, None, None):
                raise

    # -- barriered reads ------------------------------------------------------

    def steps(self) -> list:
        self.wait()
        return self.inner.steps()

    def latest_step(self) -> Optional[int]:
        self.wait()
        return self.inner.latest_step()

    def latest_verified_step(self) -> Optional[int]:
        self.wait()
        return self.inner.latest_verified_step()

    def verify(self, step: int) -> bool:
        self.wait()
        return self.inner.verify(step)

    def restore(self, graphs: Dict[str, object],
                step: Optional[int] = None,
                max_step: Optional[int] = None, target_mesh=_NO_TARGET):
        self.wait()
        return self.inner.restore(graphs, step, max_step=max_step,
                                  target_mesh=target_mesh)

    def mesh_spec(self, step: int) -> Optional[Dict]:
        self.wait()
        return self.inner.mesh_spec(step)

    def prune_above(self, step: int) -> list:
        self.wait()
        return self.inner.prune_above(step)
