"""Multi-graph training-state checkpointer (see package docstring)."""

from __future__ import annotations

import json
import os
import re
import shutil
import tempfile
from typing import Dict, Optional, Tuple

import numpy as np

from gan_deeplearning4j_tpu.graph import serialization


class TrainCheckpointer:
    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = keep
        os.makedirs(directory, exist_ok=True)

    # -- save ----------------------------------------------------------------

    def save(self, step: int, graphs: Dict[str, object],
             extra: Optional[Dict] = None) -> str:
        """Write ``ckpt_{step}`` atomically; prune beyond ``keep``."""
        final = os.path.join(self.directory, f"ckpt_{step}")
        tmp = tempfile.mkdtemp(prefix=".ckpt_tmp_", dir=self.directory)
        try:
            for name, graph in graphs.items():
                serialization.write_model(
                    graph, os.path.join(tmp, f"{name}_model.zip"), save_updater=True
                )
            arrays = {}
            scalars = {"step": step, "graphs": sorted(graphs.keys())}
            pytrees = []
            for k, v in (extra or {}).items():
                if isinstance(v, (int, float, str, bool)) or v is None:
                    scalars[k] = v
                elif isinstance(v, dict):
                    # nested param-tree extra (e.g. a generator EMA):
                    # flattened under its key, rebuilt on restore
                    pytrees.append(k)
                    arrays.update(serialization._flatten(v, f"{k}/"))
                else:
                    arrays[k] = np.asarray(v)
            if pytrees:
                scalars["pytree_extras"] = sorted(pytrees)
            with open(os.path.join(tmp, "state.json"), "w") as f:
                json.dump(scalars, f, indent=1)
            if arrays:
                np.savez(os.path.join(tmp, "state.npz"), **arrays)
            if os.path.exists(final):
                shutil.rmtree(final)
            os.rename(tmp, final)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._prune()
        return final

    def _prune(self) -> None:
        steps = self.steps()
        for s in steps[: max(0, len(steps) - self.keep)]:
            shutil.rmtree(os.path.join(self.directory, f"ckpt_{s}"), ignore_errors=True)

    # -- restore -------------------------------------------------------------

    def steps(self) -> list:
        out = []
        for name in os.listdir(self.directory):
            m = re.fullmatch(r"ckpt_(\d+)", name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    def restore(
        self, graphs: Dict[str, object], step: Optional[int] = None
    ) -> Tuple[int, Dict]:
        """Load params + updater state into the given graphs (in place) from
        ``ckpt_{step}`` (default: latest).  Returns (step, extra)."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints in {self.directory}")
        path = os.path.join(self.directory, f"ckpt_{step}")
        with open(os.path.join(path, "state.json")) as f:
            scalars = json.load(f)
        # Validate BOTH directions before mutating anything, so a mismatch
        # never leaves the caller with a half-restored graph set.
        saved, supplied = set(scalars["graphs"]), set(graphs.keys())
        if saved != supplied:
            raise ValueError(
                f"checkpoint graphs {sorted(saved)} != supplied {sorted(supplied)}"
            )
        # Load everything first and validate tree structure against the
        # live graphs before assigning (same no-half-restore discipline):
        # a run resumed with different updater flags (e.g. a schedule
        # wrapper added via --lr-decay-steps) has a structurally
        # different opt_state, and assigning it would surface later as
        # an opaque pytree error inside the jitted step.
        import jax

        loaded_all = {}
        for name, graph in graphs.items():
            loaded = serialization.read_model(os.path.join(path, f"{name}_model.zip"))
            for field, mismatch_hint in (
                    ("params", "different architecture"),
                    ("opt_state", "different updater configuration "
                                  "(e.g. a schedule flag the original "
                                  "run did not use)")):
                saved_td = jax.tree_util.tree_structure(getattr(loaded, field))
                live_td = jax.tree_util.tree_structure(getattr(graph, field))
                if saved_td != live_td:
                    raise ValueError(
                        f"checkpoint {field} structure for graph "
                        f"{name!r} does not match this run's — "
                        f"{mismatch_hint}; resume with the original "
                        f"run's flags")
            loaded_all[name] = loaded
        for name, graph in graphs.items():
            graph.params = loaded_all[name].params
            graph.opt_state = loaded_all[name].opt_state
        pytrees = set(scalars.pop("pytree_extras", []))
        extra = {k: v for k, v in scalars.items() if k not in ("step", "graphs")}
        npz_path = os.path.join(path, "state.npz")
        if os.path.exists(npz_path):
            flat_trees: Dict[str, Dict] = {k: {} for k in pytrees}
            with np.load(npz_path) as z:
                for k in z.files:
                    root = k.split("/", 1)[0]
                    if root in pytrees:
                        flat_trees[root][k.split("/", 1)[1]] = z[k]
                    else:
                        extra[k] = z[k]
            for k, flat in flat_trees.items():
                extra[k] = serialization._unflatten(flat)
        return scalars["step"], extra
