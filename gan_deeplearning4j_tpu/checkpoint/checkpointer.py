"""Multi-graph training-state checkpointer (see package docstring).

Crash-safety contract (the failure model docs/FAULT_TOLERANCE.md spells
out):

* ``save()`` is split into a **snapshot** half (``snapshot_state`` —
  host copies of every device value, run on the training thread) and a
  **serialize** half (``write_snapshot`` — bytes, fsync, atomic rename;
  safe to run on a background worker, see ``AsyncCheckpointer``).
* Every file is fsynced, then ``MANIFEST.json`` (per-file SHA-256 +
  size) is written and fsynced LAST, then the temp dir is renamed into
  place and the parent directory fsynced — a kill at ANY byte leaves
  either no ``ckpt_{step}`` entry at all or one whose manifest verifies.
* Re-saving an existing step swaps via rename/rename/rmtree (never
  rmtree-then-rename): at no instant is the step's data unlinked before
  its replacement is in place, so a kill between the two renames demotes
  that step to "absent" (recoverable from an older verified checkpoint)
  instead of destroying it with nothing written yet.
* ``restore()`` verifies the manifest of the chosen checkpoint and, when
  no explicit step was requested, **falls back to the newest checkpoint
  that verifies and loads** — a torn or corrupt latest checkpoint makes
  the restart start slightly earlier, it does not crash the restart.
* ``__init__`` purges stale ``.ckpt_tmp_*`` / ``.ckpt_del_*`` debris a
  hard kill mid-save leaves behind (they would otherwise accumulate
  forever under ``--max-restarts``).

``_chaos_hook`` is the fault-injection seam: ``testing/chaos.py``
installs a callable that raises at an enumerated write/rename point to
prove the contract above (tests/test_chaos.py walks every point).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import shutil
import tempfile
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from gan_deeplearning4j_tpu.graph import serialization

MANIFEST_NAME = "MANIFEST.json"

# fault-injection seam (testing/chaos.py): called as _chaos_hook(event)
# at each named point of write_snapshot; a raised exception with
# ``simulates_kill = True`` is treated as a hard kill (no graceful temp
# cleanup — exactly what SIGKILL leaves behind)
_chaos_hook: Optional[Callable[[str], None]] = None

_log = logging.getLogger(__name__)


class CheckpointCorruptError(RuntimeError):
    """An explicitly requested checkpoint failed manifest verification."""


class CheckpointMeshMismatchError(ValueError):
    """The checkpoint was written under a different device topology and
    the caller named no target mesh to reshard onto.  A ValueError on
    purpose: the recovery wrapper classifies it FATAL — a blind restart
    replays the identical mismatch; only a caller decision (pass
    ``target_mesh=`` / re-form the mesh elastically) fixes it.  Before
    this error existed the mismatch surfaced as an opaque
    shape/sharding error deep inside ``device_put``."""


class NoVerifiedCheckpointError(FileNotFoundError):
    """No checkpoint in the directory verifies and loads.  Callers that
    can fall back to a from-scratch run (deterministic replay) should
    catch this; it is NOT raised when a fallback checkpoint exists."""


def _chaos(event: str) -> None:
    if _chaos_hook is not None:
        _chaos_hook(event)


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platform without directory fds: rename is still atomic
    try:
        os.fsync(fd)
    except OSError:  # gan4j-lint: disable=swallowed-exception — some filesystems refuse directory fsync; rename atomicity still holds
        pass
    finally:
        os.close(fd)


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


# explicitly-not-passed sentinel for restore(target_mesh=...): ``None``
# is a VALID target (the single-device, no-mesh trainer), so absence
# needs its own value
_NO_TARGET = object()


def snapshot_state(graphs: Dict[str, object], step: int,
                   extra: Optional[Dict] = None,
                   mesh_spec: Optional[Dict] = None) -> Dict:
    """The training-thread half of a save: capture config dicts and HOST
    copies of every param/updater/extra array.  After this returns, the
    live graphs may keep training — serialization reads only the
    snapshot.  Device->host copies are overlapped (one round trip on a
    tunneled link, not one per leaf)."""
    from gan_deeplearning4j_tpu.utils.device import start_host_copy

    # start every device->host transfer before materializing any
    start_host_copy([g.params for g in graphs.values()]
                    + [g.opt_state for g in graphs.values()]
                    + [v for v in (extra or {}).values()])
    graph_parts = {
        name: serialization.snapshot_model_parts(g, save_updater=True)
        for name, g in graphs.items()
    }
    arrays: Dict[str, np.ndarray] = {}
    scalars: Dict = {"step": step, "graphs": sorted(graphs.keys())}
    pytrees = []
    for k, v in (extra or {}).items():
        if isinstance(v, (int, float, str, bool)) or v is None:
            scalars[k] = v
        elif isinstance(v, dict):
            # nested param-tree extra (e.g. a generator EMA):
            # flattened under its key, rebuilt on restore
            pytrees.append(k)
            arrays.update({kk: np.asarray(vv) for kk, vv in
                           serialization._flatten(v, f"{k}/").items()})
        else:
            arrays[k] = np.asarray(v)
    if pytrees:
        scalars["pytree_extras"] = sorted(pytrees)
    snap = {"graphs": graph_parts, "scalars": scalars, "arrays": arrays}
    if mesh_spec is not None:
        # the saving topology (parallel/elastic.py MeshSpec.to_dict),
        # committed into MANIFEST.json by write_snapshot so a restore
        # can detect a world-size change BEFORE touching any array
        snap["mesh_spec"] = dict(mesh_spec)
    return snap


class TrainCheckpointer:
    def __init__(self, directory: str, keep: int = 3,
                 sweep_debris: bool = True):
        """``sweep_debris=False`` makes this a READ-SIDE handle: no
        debris purge / orphan adoption at init.  Anything watching a
        directory some OTHER process is actively saving into — the
        checkpoint publisher, a serving bank hotswap — must pass False:
        the owner's in-flight ``.ckpt_tmp_*`` is indistinguishable from
        crash debris, and sweeping it tears the save mid-write.  Only
        the directory's owner (the trainer, at startup) sweeps."""
        self.directory = directory
        self.keep = keep
        os.makedirs(directory, exist_ok=True)
        if sweep_debris:
            self._purge_debris()

    def _purge_debris(self) -> None:
        """Reclaim temp/swap dirs a hard kill mid-save left behind —
        without this they leak forever and accumulate one per crash
        under ``--max-restarts``.

        An orphan whose manifest VERIFIES is a complete checkpoint that
        only missed its rename (kill after the last fsync, or between
        the two renames of a re-save): if its step has no committed
        ``ckpt_{step}`` entry, ADOPT it — rename it into place instead
        of deleting it.  This closes the last availability gap: with at
        least one fully-written save ever, no kill point leaves the
        directory unrestorable (tests/test_chaos.py enumerates them)."""
        debris = [n for n in sorted(os.listdir(self.directory))
                  if n.startswith((".ckpt_tmp_", ".ckpt_del_"))]
        changed = False
        # adoption preference: a .ckpt_tmp_ orphan holds the NEWER bytes
        # of an interrupted re-save swap (.ckpt_del_ is the superseded
        # copy) — when both verify for the same missing step, the
        # replacement that was fully fsynced must win, not the stale one
        adopted = set()
        for prefix in (".ckpt_tmp_", ".ckpt_del_"):
            for name in debris:
                if not name.startswith(prefix):
                    continue
                path = os.path.join(self.directory, name)
                step = self._orphan_step(path)
                if step is None:
                    continue
                final = os.path.join(self.directory, f"ckpt_{step}")
                if not os.path.exists(final):
                    _log.warning(
                        "adopting orphaned complete checkpoint %s as "
                        "ckpt_%d (killed before its rename)", name, step)
                    os.rename(path, final)
                    adopted.add(name)
                    changed = True
        for name in debris:
            if name in adopted:
                continue
            shutil.rmtree(os.path.join(self.directory, name),
                          ignore_errors=True)
            changed = True
        if changed:
            _fsync_dir(self.directory)

    def _orphan_step(self, path: str) -> Optional[int]:
        """The step of a debris dir IF it verifies as a complete
        checkpoint (manifest present, every file intact); else None."""
        if not self._verify_dir(path):
            return None
        try:
            with open(os.path.join(path, MANIFEST_NAME)) as f:
                return int(json.load(f)["step"])
        except (OSError, ValueError, KeyError, TypeError):
            return None

    # -- save ----------------------------------------------------------------

    def save(self, step: int, graphs: Dict[str, object],
             extra: Optional[Dict] = None,
             mesh_spec: Optional[Dict] = None) -> str:
        """Write ``ckpt_{step}`` atomically (manifest-verified, fsynced);
        prune beyond ``keep``.  Snapshot + serialize on this thread; the
        async wrapper calls the two halves on different threads.
        ``mesh_spec``: the saving topology (elastic resume), landed in
        the manifest."""
        return self.write_snapshot(
            snapshot_state(graphs, step, extra, mesh_spec=mesh_spec))

    def write_snapshot(self, snap: Dict) -> str:
        """Serialize a ``snapshot_state`` result to ``ckpt_{step}`` —
        pure file work, no device or graph access (background-thread
        safe).  Every file is fsynced; the manifest is written last; the
        final rename is the commit point.  The serialize/fsync and
        commit (rename) stages are event spans (telemetry/events.py) so
        the flight recorder names the stage a kill landed in."""
        from gan_deeplearning4j_tpu.telemetry import events

        step = snap["scalars"]["step"]
        final = os.path.join(self.directory, f"ckpt_{step}")
        tmp = tempfile.mkdtemp(prefix=".ckpt_tmp_", dir=self.directory)
        try:
            entries: Dict[str, Dict] = {}

            def put(name: str, data: bytes) -> None:
                path = os.path.join(tmp, name)
                with open(path, "wb") as f:
                    f.write(data)
                _fsync_file(path)
                # hash the in-memory bytes (a re-read would only go
                # through the page cache — same hash, double the IO)
                entries[name] = {"bytes": len(data),
                                 "sha256": hashlib.sha256(data)
                                 .hexdigest()}
                _chaos(f"wrote:{name}")

            with events.span("checkpoint.serialize", step=step):
                for name, (cfg, flat_params, flat_updater) in \
                        sorted(snap["graphs"].items()):
                    put(f"{name}_model.zip", serialization.model_zip_bytes(
                        cfg, flat_params, flat_updater))
                put("state.json",
                    json.dumps(snap["scalars"], indent=1).encode())
                if snap["arrays"]:
                    put("state.npz",
                        serialization.npz_bytes(snap["arrays"]))
                # the manifest commits the file set: written + fsynced
                # LAST, so a manifest that parses implies every listed
                # byte hit the disk before it
                mpath = os.path.join(tmp, MANIFEST_NAME)
                manifest: Dict = {"step": step, "files": entries}
                if snap.get("mesh_spec") is not None:
                    manifest["mesh_spec"] = snap["mesh_spec"]
                with open(mpath, "w") as f:
                    json.dump(manifest, f, indent=1)
                _fsync_file(mpath)
                _fsync_dir(tmp)
                _chaos("manifest")
            with events.span("checkpoint.commit", step=step):
                if os.path.exists(final):
                    # swap, never rmtree-then-rename: a kill between the
                    # renames loses the step's DIRECTORY ENTRY (restore
                    # falls back one checkpoint) but never both copies
                    # of the data
                    trash = tempfile.mkdtemp(prefix=".ckpt_del_",
                                             dir=self.directory)
                    os.rmdir(trash)
                    _chaos("pre_swap")
                    os.rename(final, trash)
                    _chaos("mid_swap")
                    os.rename(tmp, final)
                    _chaos("post_swap")
                    shutil.rmtree(trash, ignore_errors=True)
                else:
                    _chaos("pre_swap")
                    os.rename(tmp, final)
                    _chaos("post_swap")
                _fsync_dir(self.directory)
        except BaseException as e:
            # a SIMULATED hard kill must leave the directory exactly as
            # a real one would — debris and all (purged at next init)
            if not getattr(e, "simulates_kill", False):
                shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._prune()
        return final

    def _prune(self) -> None:
        steps = self.steps()
        for s in steps[: max(0, len(steps) - self.keep)]:
            shutil.rmtree(os.path.join(self.directory, f"ckpt_{s}"), ignore_errors=True)

    # -- verification --------------------------------------------------------

    def verify(self, step: int) -> bool:
        """True iff ``ckpt_{step}``'s manifest parses and every listed
        file exists with matching size and SHA-256 (and no file the
        checkpoint needs is missing from the manifest's view)."""
        return self._verify_dir(os.path.join(self.directory,
                                             f"ckpt_{step}"))

    @staticmethod
    def _verify_dir(path: str) -> bool:
        try:
            with open(os.path.join(path, MANIFEST_NAME)) as f:
                manifest = json.load(f)
            files = manifest["files"]
            if "state.json" not in files:
                return False
            for name, meta in files.items():
                fp = os.path.join(path, name)
                if (not os.path.isfile(fp)
                        or os.path.getsize(fp) != meta["bytes"]
                        or _sha256(fp) != meta["sha256"]):
                    return False
            return True
        except (OSError, ValueError, KeyError, TypeError):
            return False  # torn manifest / pre-manifest layout: unverified

    @staticmethod
    def _is_legacy_dir(path: str) -> bool:
        """A COMMITTED checkpoint written before the manifest existed:
        no MANIFEST.json but a state.json.  Distinguishable from a torn
        save because a kill before the manifest write leaves only a
        temp dir, never a committed ``ckpt_{step}`` entry — so a
        committed dir without a manifest can only be the old layout.
        Unverifiable but not corrupt: restore accepts it (loudly) so an
        upgrade does not silently discard a long run's progress."""
        return (not os.path.exists(os.path.join(path, MANIFEST_NAME))
                and os.path.isfile(os.path.join(path, "state.json")))

    def latest_verified_step(self) -> Optional[int]:
        for s in reversed(self.steps()):
            if self.verify(s):
                return s
        return None

    def mesh_spec(self, step: int) -> Optional[Dict]:
        """The saving topology stamped into ``ckpt_{step}``'s manifest
        (a ``parallel/elastic.py`` MeshSpec dict), or None for
        pre-elastic checkpoints — whose restores keep the old trust-
        the-caller behavior, there being nothing to check against."""
        path = os.path.join(self.directory, f"ckpt_{step}", MANIFEST_NAME)
        try:
            with open(path) as f:
                spec = json.load(f).get("mesh_spec")
        except (OSError, ValueError):
            return None
        return spec if isinstance(spec, dict) else None

    # -- restore -------------------------------------------------------------

    def steps(self) -> list:
        out = []
        for name in os.listdir(self.directory):
            m = re.fullmatch(r"ckpt_(\d+)", name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    def prune_above(self, step: int) -> list:
        """Remove every committed checkpoint with a step STRICTLY above
        ``step`` — the rollback path's poisoned-suffix cleanup
        (train/rollback.py): once a run has rolled back to ``step``,
        the later checkpoints hold the diverged/NaN state and a plain
        restart must never resume into them.  Returns the pruned
        steps."""
        pruned = [s for s in self.steps() if s > step]
        for s in pruned:
            _log.warning(
                "pruning checkpoint ckpt_%d (> rollback restore point "
                "%d: holds post-divergence state)", s, step)
            shutil.rmtree(os.path.join(self.directory, f"ckpt_{s}"),
                          ignore_errors=True)
        if pruned:
            _fsync_dir(self.directory)
        return pruned

    def restore(
        self, graphs: Dict[str, object], step: Optional[int] = None,
        max_step: Optional[int] = None, target_mesh=_NO_TARGET,
    ) -> Tuple[int, Dict]:
        """Load params + updater state into the given graphs (in place).

        ``step=None`` (the resume path): newest-first over the directory,
        skipping — with a loud warning — any checkpoint that fails
        manifest verification or whose files turn out unreadable, so a
        checkpoint torn by a mid-write kill degrades the restart to the
        previous save instead of crashing it.  ``max_step`` bounds the
        walk: checkpoints ABOVE it are skipped outright (the rollback
        path restores strictly before the first known-bad step).  Raises
        ``NoVerifiedCheckpointError`` when nothing survives.

        An EXPLICIT ``step`` is a user decision: verification failure
        raises ``CheckpointCorruptError`` (no silent substitution).

        Structure mismatches (graph set / params / opt_state trees) are
        NOT corruption — they mean the caller resumed with different
        flags and always raise ``ValueError`` (the recovery wrapper
        classifies that as fatal, not retryable).

        ``target_mesh`` (elastic resume, parallel/elastic.py): the mesh
        this restore lands on — a ``jax.sharding.Mesh`` or ``None`` for
        the single-device no-mesh trainer.  When the checkpoint's
        recorded ``mesh_spec`` differs, params/opt-state are RESHARDED
        onto the target (gather-to-host → ``device_put`` replicated;
        bit-equal post-gather) and ``extra["__reshard__"]`` reports the
        from/to topologies and the time paid.  NOT passing it keeps the
        legacy behavior — except that a checkpoint whose saved topology
        cannot even be rebuilt on this host (more devices than
        attached) now raises ``CheckpointMeshMismatchError`` naming
        both shapes instead of an opaque sharding error downstream."""
        if step is not None:
            path = os.path.join(self.directory, f"ckpt_{step}")
            if not os.path.isdir(path):
                # absent is absent — calling it "corrupt" would both
                # mislead the user and misclassify in the recovery
                # wrapper (corruption is fatal; a mistyped step is not
                # a statement about the data)
                raise FileNotFoundError(
                    f"no checkpoint ckpt_{step} in {self.directory}")
            if not self.verify(step):
                if self._is_legacy_dir(path):
                    _log.warning(
                        "checkpoint ckpt_%d predates the manifest "
                        "format (unverifiable, accepted)", step)
                else:
                    raise CheckpointCorruptError(
                        f"checkpoint ckpt_{step} in {self.directory} "
                        "fails manifest verification (torn or corrupt)")
            return self._load_elastic(step, graphs, target_mesh)
        candidates = self.steps()
        if max_step is not None:
            candidates = [s for s in candidates if s <= max_step]
        if not candidates:
            raise NoVerifiedCheckpointError(
                f"no checkpoints in {self.directory}"
                + (f" at or below step {max_step}"
                   if max_step is not None else ""))
        legacy = []
        for s in reversed(candidates):
            if not self.verify(s):
                if self._is_legacy_dir(
                        os.path.join(self.directory, f"ckpt_{s}")):
                    legacy.append(s)  # second-choice tier, tried below
                    continue
                _log.warning(
                    "checkpoint ckpt_%d fails verification (torn or "
                    "corrupt); falling back to the previous one", s)
                continue
            try:
                return self._load_elastic(s, graphs, target_mesh)
            except ValueError:
                raise  # structure mismatch: fatal, not corruption
            except Exception as e:  # unreadable despite the manifest
                _log.warning(
                    "checkpoint ckpt_%d failed to load (%r); falling "
                    "back to the previous one", s, e)
        # pre-manifest checkpoints: unverifiable but not corrupt — a
        # silent restart-from-0 after an upgrade would throw away a long
        # run's progress, so try them (loudly) before giving up
        for s in legacy:
            _log.warning(
                "checkpoint ckpt_%d predates the manifest format "
                "(unverifiable); attempting restore", s)
            try:
                return self._load_elastic(s, graphs, target_mesh)
            except ValueError:
                raise
            except Exception as e:
                _log.warning("legacy checkpoint ckpt_%d failed to load "
                             "(%r)", s, e)
        raise NoVerifiedCheckpointError(
            f"no VERIFIED checkpoint in {self.directory} "
            f"(all of {candidates} torn or corrupt)")

    def _load_elastic(self, step: int, graphs: Dict[str, object],
                      target_mesh) -> Tuple[int, Dict]:
        """``_load`` plus the elastic-mesh contract: guard the
        topology mismatch BEFORE touching any array, then reshard the
        loaded params/opt-state onto the target mesh when the saved
        spec differs (parallel/elastic.py).  Pre-elastic checkpoints
        (no recorded mesh_spec) keep the legacy load."""
        saved = self.mesh_spec(step)
        if saved is None:
            return self._load(step, graphs)
        from gan_deeplearning4j_tpu.parallel.elastic import (
            MeshSpec,
            reshard,
        )

        saved_spec = MeshSpec.from_dict(saved)
        if target_mesh is _NO_TARGET:
            import jax

            avail = len(jax.devices())
            if saved_spec.device_count > avail:
                raise CheckpointMeshMismatchError(
                    f"checkpoint ckpt_{step} in {self.directory} was "
                    f"written on mesh {saved_spec.describe()} but this "
                    f"host attaches only {avail} device(s); pass "
                    f"target_mesh= to reshard onto the surviving "
                    f"topology (docs/FAULT_TOLERANCE.md § Elastic "
                    f"resume)")
            return self._load(step, graphs)
        target_spec = MeshSpec.from_mesh(target_mesh)
        out = self._load(step, graphs)
        if saved_spec.same_topology(target_spec):
            return out
        import time as _time

        import jax

        t0 = _time.perf_counter()
        if target_mesh is None:
            sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
        else:
            from jax.sharding import NamedSharding, PartitionSpec

            sharding = NamedSharding(target_mesh, PartitionSpec())
        for graph in graphs.values():
            graph.params = reshard(graph.params, sharding)
            graph.opt_state = reshard(graph.opt_state, sharding)
        dt = _time.perf_counter() - t0
        _log.warning(
            "resharded checkpoint ckpt_%d from mesh %s onto %s in "
            "%.3fs (values bit-equal post-gather)", step,
            saved_spec.describe(), target_spec.describe(), dt)
        step_out, extra = out
        extra["__reshard__"] = {"from": saved_spec.to_dict(),
                                "to": target_spec.to_dict(),
                                "seconds": dt}
        return step_out, extra

    def _load(self, step: int, graphs: Dict[str, object]) -> Tuple[int, Dict]:
        path = os.path.join(self.directory, f"ckpt_{step}")
        with open(os.path.join(path, "state.json")) as f:
            scalars = json.load(f)
        # Validate BOTH directions before mutating anything, so a mismatch
        # never leaves the caller with a half-restored graph set.
        saved, supplied = set(scalars["graphs"]), set(graphs.keys())
        if saved != supplied:
            raise ValueError(
                f"checkpoint graphs {sorted(saved)} != supplied {sorted(supplied)}"
            )
        # Load everything first and validate tree structure against the
        # live graphs before assigning (same no-half-restore discipline):
        # a run resumed with different updater flags (e.g. a schedule
        # wrapper added via --lr-decay-steps) has a structurally
        # different opt_state, and assigning it would surface later as
        # an opaque pytree error inside the jitted step.
        import jax

        loaded_all = {}
        for name, graph in graphs.items():
            loaded = serialization.read_model(os.path.join(path, f"{name}_model.zip"))
            for field, mismatch_hint in (
                    ("params", "different architecture"),
                    ("opt_state", "different updater configuration "
                                  "(e.g. a schedule flag the original "
                                  "run did not use)")):
                saved_td = jax.tree_util.tree_structure(getattr(loaded, field))
                live_td = jax.tree_util.tree_structure(getattr(graph, field))
                if saved_td != live_td:
                    raise ValueError(
                        f"checkpoint {field} structure for graph "
                        f"{name!r} does not match this run's — "
                        f"{mismatch_hint}; resume with the original "
                        f"run's flags")
            loaded_all[name] = loaded
        for name, graph in graphs.items():
            graph.params = loaded_all[name].params
            graph.opt_state = loaded_all[name].opt_state
        pytrees = set(scalars.pop("pytree_extras", []))
        extra = {k: v for k, v in scalars.items() if k not in ("step", "graphs")}
        npz_path = os.path.join(path, "state.npz")
        if os.path.exists(npz_path):
            flat_trees: Dict[str, Dict] = {k: {} for k in pytrees}
            with np.load(npz_path) as z:
                for k in z.files:
                    root = k.split("/", 1)[0]
                    if root in pytrees:
                        flat_trees[root][k.split("/", 1)[1]] = z[k]
                    else:
                        extra[k] = z[k]
            for k, flat in flat_trees.items():
                extra[k] = serialization._unflatten(flat)
        return scalars["step"], extra
