"""Migration-compat shims for reference-stack idioms (DL4J/ND4J)."""

from gan_deeplearning4j_tpu.compat.nd4j import INDArray, Nd4j

__all__ = ["INDArray", "Nd4j"]
