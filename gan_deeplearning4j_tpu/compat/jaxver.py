"""JAX version-compat shims.

The package targets the modern JAX surface (pyproject pins >= 0.7 where
``jax.shard_map`` is top-level and takes ``check_vma``), but the baked-in
toolchain of some hosts carries an older jax whose only spelling is
``jax.experimental.shard_map.shard_map(check_rep=...)``.  Importing
``shard_map`` from here instead of ``jax`` keeps every call site on the
new-style API on both: the wrapper translates the ``check_vma`` keyword
to ``check_rep`` when the experimental fallback is what's available.
"""

from __future__ import annotations

try:  # jax >= 0.7: the supported top-level export
    from jax import shard_map as _shard_map

    _TRANSLATE_CHECK_VMA = False
except ImportError:  # older jax: the experimental spelling
    from jax.experimental.shard_map import shard_map as _shard_map

    _TRANSLATE_CHECK_VMA = True


def shard_map(f, /, **kwargs):
    """``jax.shard_map`` with new-style keywords on any supported jax.

    Call sites pass ``mesh=``, ``in_specs=``, ``out_specs=`` and
    (optionally) ``check_vma=`` exactly as with jax >= 0.7; on an older
    jax the keyword is renamed to its ``check_rep`` predecessor (same
    semantics: disable the replication-consistency check)."""
    if _TRANSLATE_CHECK_VMA and "check_vma" in kwargs:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    return _shard_map(f, **kwargs)
