"""``Nd4j`` / ``INDArray`` migration shim — the ND4J host-array idioms
the reference's mains are written in (``Nd4j.randn(b, z).muli(2).subi(1)``
for latent draws, ``Nd4j.linspace`` grids, ``vstack`` batch assembly,
``getDouble`` scalar reads — dl4jGANComputerVision.java:363-397,479-496),
so that data-prep code ports line-for-line.

Deliberately numpy-backed: every call the mains make with this API is
HOST-side batch assembly and artifact formatting — exactly the work that
should stay off the TPU (SURVEY §3.2 flags the reference's per-scalar
``getDouble`` CSV writes as a hot-loop pitfall).  Arrays enter JAX at the
graph boundary (``graph.fit/output`` accept these wrappers via
``__array__``).  In-place ``-i`` methods mutate and return self (ND4J
semantics); the non-``i`` variants copy.

Covered surface = every Nd4j/INDArray call in the two reference mains
(verified by grep, see tests): randn, rand, ones, zeros, linspace,
vstack, create, setDataType, getRandom().setSeed, getMemoryManager,
getBackend; add/addi, sub/subi, mul/muli, div/divi, reshape, dup,
getDouble, putScalar, transpose, shape/rows/columns.
"""

from __future__ import annotations

import numpy as np


class INDArray:
    """Thin mutable wrapper over a numpy array with ND4J method names."""

    __slots__ = ("a",)

    def __init__(self, a):
        self.a = np.asarray(a)

    # numpy/jax interop: jnp.asarray(x) / np.asarray(x) both work, so
    # these wrappers pass straight into graph.fit/output
    def __array__(self, dtype=None):
        return self.a if dtype is None else self.a.astype(dtype)

    def data(self) -> np.ndarray:
        return self.a

    # -- elementwise (non-i: copy; -i: in-place, returns self) ----------
    def add(self, o): return INDArray(self.a + _raw(o))
    def sub(self, o): return INDArray(self.a - _raw(o))
    def mul(self, o): return INDArray(self.a * _raw(o))
    def div(self, o): return INDArray(self.a / _raw(o))

    def addi(self, o):
        self.a += _raw(o)
        return self

    def subi(self, o):
        self.a -= _raw(o)
        return self

    def muli(self, o):
        self.a *= _raw(o)
        return self

    def divi(self, o):
        self.a /= _raw(o)
        return self

    # -- shape / access ---------------------------------------------------
    def reshape(self, *shape):
        return INDArray(self.a.reshape(
            shape[0] if len(shape) == 1 and isinstance(shape[0], (tuple, list))
            else shape))

    def dup(self):
        return INDArray(self.a.copy())

    def transpose(self):
        return INDArray(self.a.T)

    def ravel(self):
        return INDArray(self.a.ravel())

    def getDouble(self, *idx) -> float:
        return float(self.a[idx if len(idx) > 1 else idx[0]])

    def putScalar(self, idx, value):
        self.a[tuple(idx) if isinstance(idx, (tuple, list)) else idx] = value
        return self

    def shape(self):
        return self.a.shape

    def rows(self) -> int:
        return self.a.shape[0]

    def columns(self) -> int:
        return self.a.shape[1]

    def length(self) -> int:
        return self.a.size

    def __repr__(self):
        return f"INDArray{self.a.shape}\n{self.a!r}"


def _raw(o):
    return o.a if isinstance(o, INDArray) else o


class _Random:
    def __init__(self):
        self.state = np.random.RandomState(666)  # the reference's seed

    def setSeed(self, seed: int) -> None:
        self.state = np.random.RandomState(seed)


class _MemoryManager:
    """``Nd4j.getMemoryManager().setAutoGcWindow(5000)`` shim: XLA/PJRT
    owns device memory, so there is nothing to configure — kept so the
    reference's setup lines port without edits."""

    def setAutoGcWindow(self, ms: int) -> None:
        pass


class _Nd4j:
    """Module-style singleton mirroring the ``Nd4j`` static surface."""

    def __init__(self):
        self._random = _Random()
        self._dtype = np.float32
        self._memory = _MemoryManager()

    # -- factories (DL4J shapes: (rows, cols) args or a shape tuple) ------
    def _shape(self, args):
        if len(args) == 1 and isinstance(args[0], (tuple, list)):
            return tuple(args[0])
        return tuple(int(a) for a in args)

    def randn(self, *shape) -> INDArray:
        return INDArray(self._random.state.randn(
            *self._shape(shape)).astype(self._dtype))

    def rand(self, *shape) -> INDArray:
        return INDArray(self._random.state.rand(
            *self._shape(shape)).astype(self._dtype))

    def ones(self, *shape) -> INDArray:
        return INDArray(np.ones(self._shape(shape), self._dtype))

    def zeros(self, *shape) -> INDArray:
        return INDArray(np.zeros(self._shape(shape), self._dtype))

    def linspace(self, lower, upper, num) -> INDArray:
        # ND4J returns a 1 x num ROW VECTOR (the reference reshapes it
        # into its z-grid, dl4jGANComputerVision.java:363-370)
        return INDArray(np.linspace(lower, upper, int(num),
                                    dtype=self._dtype).reshape(1, -1))

    def vstack(self, *arrays) -> INDArray:
        arrs = arrays[0] if (len(arrays) == 1
                             and isinstance(arrays[0], (list, tuple))) else arrays
        return INDArray(np.vstack([_raw(a) for a in arrs]))

    def create(self, data) -> INDArray:
        return INDArray(np.asarray(_raw(data), dtype=self._dtype))

    # -- runtime config ----------------------------------------------------
    def setDataType(self, dtype) -> None:
        """``Nd4j.setDataType(DataBuffer.Type.FLOAT)``: accepts 'float' /
        'double' / a numpy dtype."""
        if isinstance(dtype, str):
            dtype = {"float": np.float32, "double": np.float64}[dtype.lower()]
        self._dtype = np.dtype(dtype).type
        from gan_deeplearning4j_tpu.runtime import backend

        backend.configure(dtype=np.dtype(dtype))

    def getRandom(self) -> _Random:
        return self._random

    def getMemoryManager(self) -> _MemoryManager:
        return self._memory

    def getBackend(self) -> str:
        import jax

        return f"jax-{jax.default_backend()}"


Nd4j = _Nd4j()
