"""Data pipeline — TPU-native DataVec equivalent (SURVEY.md §1 L5).

CSV record readers and batch iterators matching the reference's
``CSVRecordReader`` + ``RecordReaderDataSetIterator`` semantics, dataset
modules reproducing the notebook's export pipelines, and an optional
native C++ fast-decode path.
"""

from gan_deeplearning4j_tpu.data.csv import (
    CSVRecordReader,
    CSVRowError,
    DataSet,
    RecordReaderDataSetIterator,
    read_csv_matrix,
    write_csv_matrix,
)
from gan_deeplearning4j_tpu.data.resilient import (
    DataHealth,
    DataQuarantineError,
    DataSourceError,
    RecordQuarantine,
    RetryingReader,
    RetryingSource,
    ValidatingSource,
)
from gan_deeplearning4j_tpu.data.normalizers import (  # noqa: F401
    NormalizerMinMaxScaler,
    NormalizerStandardize,
)
from gan_deeplearning4j_tpu.data.datasets import (
    ensure_insurance_csv,
    ensure_mnist_csv,
    export_mnist_csv,
    load_split,
    prepare_insurance,
    synthetic_mnist,
    synthetic_transactions,
)

__all__ = [
    "NormalizerMinMaxScaler",
    "NormalizerStandardize",
    "CSVRecordReader",
    "CSVRowError",
    "DataHealth",
    "DataQuarantineError",
    "DataSourceError",
    "RecordQuarantine",
    "RetryingReader",
    "RetryingSource",
    "ValidatingSource",
    "DataSet",
    "RecordReaderDataSetIterator",
    "read_csv_matrix",
    "write_csv_matrix",
    "ensure_insurance_csv",
    "ensure_mnist_csv",
    "export_mnist_csv",
    "load_split",
    "prepare_insurance",
    "synthetic_mnist",
    "synthetic_transactions",
]
