"""Build the native fastcsv shared library with g++.

Usage: ``python -m gan_deeplearning4j_tpu.data.build_native``
No external dependencies; output lands next to the source as
``native_src/libfastcsv.so`` where data/native.py looks for it.
"""

from __future__ import annotations

import os
import subprocess
import sys


def build(verbose: bool = True) -> str:
    src_dir = os.path.join(os.path.dirname(__file__), "native_src")
    src = os.path.join(src_dir, "fastcsv.cpp")
    out = os.path.join(src_dir, "libfastcsv.so")
    cmd = [
        "g++", "-O3", "-march=native", "-shared", "-fPIC",
        "-o", out, src,
    ]
    if verbose:
        print(" ".join(cmd))
    subprocess.run(cmd, check=True)
    return out


if __name__ == "__main__":
    path = build()
    from gan_deeplearning4j_tpu.data import native

    native._LIB_TRIED = False  # force reload after a rebuild
    ok = native.available()
    print(f"built {path}; loadable: {ok}")
    sys.exit(0 if ok else 1)
