"""Exact fixed-point transport codec for the dataset contract.

The reference's datasets cross process boundaries as 2-decimal fixed
point: the notebook writes MNIST pixels with ``%.2f`` and integer labels
(`gan.ipynb` raw lines 44-110 — the cell-2 export contract this
framework's ``data/datasets.py`` reproduces), and DL4J itself ships
compressed ``INDArray`` buffers over its wire paths (nd4j-compression on
the reference classpath).  The TPU-native analog: when every feature
value is exactly ``n/100`` with ``n in [0, 255]``, ship **uint8 codes**
over the host->device link — 4x fewer bytes on a bandwidth-bound
link — and dequantize on device through a 256-entry f32 table, which
reproduces the host-parsed float32 values BITWISE (each table entry is
the correctly-rounded f32 of n/100, exactly what the CSV parser
produced for the text "n/100").

Losslessness is VERIFIED against the actual data before the codec is
engaged (``u8x100_lossless``); data that is not 2-decimal fixed point
(e.g. the insurance min-max features) streams as plain f32.  Training
with the codec on is therefore bit-identical to training without it —
proven in tests/test_train.py and tests/test_data.py.
"""

from __future__ import annotations

import numpy as np

# table[n] = correctly-rounded float32 of n/100 (f64 divide is exact to
# <0.5 ulp f64, so the f64->f32 rounding lands on the correctly-rounded
# f32 — the same value decimal parsing yields for "0.37" etc.)
U8X100_TABLE = (np.arange(256, dtype=np.float64) / 100.0).astype(np.float32)


def u8x100_encode(features) -> np.ndarray:
    """f32 (n/100)-valued array -> uint8 codes.  Caller must have
    verified ``u8x100_lossless`` first; rounding here matches its
    quantizer exactly.  Block-scanned like the gate, so the f64
    temporaries stay ~tens of MB for arbitrarily large chunks."""
    f = np.asarray(features)
    out = np.empty(f.shape, np.uint8)
    flat_in, flat_out = f.reshape(-1), out.reshape(-1)
    block = 8 << 20
    for lo in range(0, flat_in.size, block):
        part = flat_in[lo:lo + block]
        flat_out[lo:lo + block] = np.rint(
            part.astype(np.float64) * 100.0).astype(np.uint8)
    return out


def u8x100_lossless(features) -> bool:
    """True iff every value decodes back BITWISE through the table —
    the gate for engaging the transport codec.  Scans in row blocks so
    the transient f64 temporaries stay ~tens of MB even for multi-GiB
    tables; NaN/inf values fail the range check (not an IndexError)."""
    f = np.asarray(features)
    if f.dtype != np.float32 or f.size == 0:
        return False
    flat = f.reshape(-1)
    block = 8 << 20  # 8M elements -> ~64 MB of f64 temporary
    for lo in range(0, flat.size, block):
        part = flat[lo:lo + block]
        q = np.rint(part.astype(np.float64) * 100.0)
        # element-wise comparisons are False for NaN, so non-finite
        # values are rejected here rather than crashing the gather below
        if not np.all((q >= 0) & (q <= 255)):
            return False
        if not np.array_equal(U8X100_TABLE[q.astype(np.intp)], part):
            return False
    return True


def u8x100_decode_np(codes) -> np.ndarray:
    """Host-side decode (tests / host consumers); the device-side decode
    is the same table gather inside the fused program
    (train/fused_step.py).  Block-scanned: the intp index temporary is
    8 bytes/element, so an unblocked gather over a multi-GiB table would
    transiently double-plus its footprint."""
    c = np.asarray(codes)
    out = np.empty(c.shape, np.float32)
    flat_in, flat_out = c.reshape(-1), out.reshape(-1)
    block = 8 << 20
    for lo in range(0, flat_in.size, block):
        flat_out[lo:lo + block] = U8X100_TABLE[
            flat_in[lo:lo + block].astype(np.intp)]
    return out
