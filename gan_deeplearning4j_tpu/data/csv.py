"""CSV record pipeline — TPU-native DataVec equivalent.

The reference's data layer is DataVec's ``CSVRecordReader`` + ``FileSplit`` +
``RecordReaderDataSetIterator`` (reference
``Java/src/main/java/org/deeplearning4j/dl4jGANComputerVision.java:355-379``),
which decodes a features+label CSV row-by-row per batch, every iteration,
on the JVM heap.  Here the whole file is decoded once into a host numpy
array (C-parser via numpy) and batches are zero-copy views; the device
transfer happens once per batch at the jit boundary instead of per-scalar
(the reference's ``getDouble(i,j)`` per-element writes are an anti-pattern
SURVEY.md §3.2 flags).

Semantics matched:
  - ``label_index`` column split (``labelIndex=784`` / ``12``)
  - ``num_classes >= 2`` -> one-hot labels (CV: ``numClasses=10``);
    ``num_classes == 1`` -> raw single-column label (insurance)
  - ``has_next``/``next``/``reset`` wraparound protocol
    (dl4jGANComputerVision.java:387,524-526): a partial final batch IS
    served, like DL4J (the insurance test sweep depends on it — 300 test
    rows iterated with ``batchSizePred=700``, dl4jGANInsurance.java:59);
    pass ``strict=True`` to raise at construction when the row count is
    not a multiple of the batch size (train loops want exact batches)
"""

from __future__ import annotations

import dataclasses
import io
import os
from typing import Iterator, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class DataSet:
    """Features+labels pair — DL4J ``org.nd4j.linalg.dataset.DataSet``."""

    features: np.ndarray
    labels: np.ndarray

    def num_examples(self) -> int:
        return self.features.shape[0]


class CSVRecordReader:
    """DataVec ``CSVRecordReader(numLinesToSkip, delimiter)`` equivalent.

    Decodes the entire file eagerly with numpy's C parser.  A native C++
    fast path (data/native) is used automatically for large files when the
    extension is built.
    """

    def __init__(self, skip_lines: int = 0, delimiter: str = ","):
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def read(self, path: str, dtype=np.float32) -> np.ndarray:
        from gan_deeplearning4j_tpu.data import native as _native

        arr = _native.read_csv(path, self.skip_lines, self.delimiter, dtype)
        if arr is not None:
            return arr
        return np.loadtxt(
            path,
            delimiter=self.delimiter,
            skiprows=self.skip_lines,
            dtype=dtype,
            ndmin=2,
        )


class RecordReaderDataSetIterator:
    """DL4J ``RecordReaderDataSetIterator(reader, batch, labelIndex, numClasses)``.

    Iterates fixed-size batches over a decoded table; ``reset()`` rewinds
    (the reference calls it for multi-epoch wraparound,
    dl4jGANComputerVision.java:524-526, and before each test sweep, :503).
    """

    def __init__(
        self,
        source,
        batch_size: int,
        label_index: Optional[int] = None,
        num_classes: int = 1,
        reader: Optional[CSVRecordReader] = None,
        dtype=np.float32,
        strict: bool = False,
    ):
        if isinstance(source, (str, os.PathLike)):
            reader = reader or CSVRecordReader()
            table = reader.read(str(source), dtype=dtype)
        else:
            table = np.asarray(source, dtype=dtype)
            if table.ndim != 2:
                raise ValueError(f"expected 2-D table, got shape {table.shape}")
        if strict and table.shape[0] % batch_size != 0:
            raise ValueError(
                f"{table.shape[0]} rows is not a multiple of batch_size={batch_size}"
            )
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        if label_index is None:
            self._features = table
            self._labels = None
        else:
            self._features = np.ascontiguousarray(
                np.delete(table, label_index, axis=1)
            )
            raw = table[:, label_index]
            if num_classes >= 2:
                # one-hot (CV path: numClasses=10 -> softmax labels)
                idx = raw.astype(np.int64)
                if idx.min() < 0 or idx.max() >= num_classes:
                    raise ValueError(
                        f"label column has values outside [0, {num_classes})"
                    )
                labels = np.zeros((table.shape[0], num_classes), dtype=dtype)
                labels[np.arange(table.shape[0]), idx] = 1.0
                self._labels = labels
            else:
                # numClasses=1: raw sigmoid target column (insurance path)
                self._labels = raw.reshape(-1, 1).astype(dtype)
        self._cursor = 0
        self._preprocessor = None

    @property
    def features(self) -> np.ndarray:
        return self._features

    @property
    def labels(self) -> Optional[np.ndarray]:
        return self._labels

    def num_examples(self) -> int:
        return self._features.shape[0]

    def has_next(self) -> bool:
        return self._cursor < self._features.shape[0]

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        lo = self._cursor
        hi = min(lo + self.batch_size, self._features.shape[0])
        self._cursor = hi
        feats = self._features[lo:hi]
        labels = (
            self._labels[lo:hi]
            if self._labels is not None
            else np.zeros((hi - lo, 0), dtype=feats.dtype)
        )
        ds = DataSet(feats, labels)
        if self._preprocessor is not None:
            # contract: preprocess REPLACES ds.features (the normalizers
            # do), never mutates it — feats is a view of the backing table
            self._preprocessor.preprocess(ds)
        return ds

    @property
    def preprocessor(self):
        return self._preprocessor

    def set_preprocessor(self, preprocessor) -> None:
        """ND4J ``iterator.setPreProcessor(normalizer)``: applied to every
        ``next()``'s DataSet (data/normalizers.py fit/transform objects,
        or any object with ``preprocess(DataSet)`` that REPLACES
        ``features`` rather than mutating the passed view)."""
        self._preprocessor = preprocessor

    def reset(self) -> None:
        self._cursor = 0

    def __iter__(self) -> Iterator[DataSet]:
        self.reset()
        while self.has_next():
            yield self.next()


def write_csv_matrix(path: str, matrix, delimiter: str = ",", fmt: str = "%.8g") -> None:
    """Dump a 2-D array as CSV in the reference's artifact format (comma
    delimiter, no trailing newline — dl4jGANComputerVision.java:482-495),
    but vectorized instead of per-scalar ``getDouble`` writes.  Uses the
    threaded C++ formatter (data/native.py) when built; numpy otherwise."""
    import re

    m = np.asarray(matrix)
    if m.ndim == 1:
        m = m.reshape(1, -1)
    spec = re.fullmatch(r"%\.(\d+)([fg])", fmt)
    if spec and m.dtype.kind == "f":
        from gan_deeplearning4j_tpu.data import native

        raw = native.format_csv(m, delimiter, spec.group(2),
                                int(spec.group(1)))
        if raw is not None:
            with open(path, "wb") as f:
                f.write(raw)
            return
    buf = io.StringIO()
    np.savetxt(buf, m, delimiter=delimiter, fmt=fmt)
    text = buf.getvalue().rstrip("\n")
    with open(path, "w") as f:
        f.write(text)


def read_csv_matrix(path: str, delimiter: str = ",") -> np.ndarray:
    return np.loadtxt(path, delimiter=delimiter, ndmin=2)
