"""CSV record pipeline — TPU-native DataVec equivalent.

The reference's data layer is DataVec's ``CSVRecordReader`` + ``FileSplit`` +
``RecordReaderDataSetIterator`` (reference
``Java/src/main/java/org/deeplearning4j/dl4jGANComputerVision.java:355-379``),
which decodes a features+label CSV row-by-row per batch, every iteration,
on the JVM heap.  Here the whole file is decoded once into a host numpy
array (C-parser via numpy) and batches are zero-copy views; the device
transfer happens once per batch at the jit boundary instead of per-scalar
(the reference's ``getDouble(i,j)`` per-element writes are an anti-pattern
SURVEY.md §3.2 flags).

Semantics matched:
  - ``label_index`` column split (``labelIndex=784`` / ``12``)
  - ``num_classes >= 2`` -> one-hot labels (CV: ``numClasses=10``);
    ``num_classes == 1`` -> raw single-column label (insurance)
  - ``has_next``/``next``/``reset`` wraparound protocol
    (dl4jGANComputerVision.java:387,524-526): a partial final batch IS
    served, like DL4J (the insurance test sweep depends on it — 300 test
    rows iterated with ``batchSizePred=700``, dl4jGANInsurance.java:59);
    pass ``strict=True`` to raise at construction when the row count is
    not a multiple of the batch size (train loops want exact batches)
"""

from __future__ import annotations

import dataclasses
import io
import os
from typing import Iterator, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class DataSet:
    """Features+labels pair — DL4J ``org.nd4j.linalg.dataset.DataSet``."""

    features: np.ndarray
    labels: np.ndarray

    def num_examples(self) -> int:
        return self.features.shape[0]


class CSVRowError(ValueError):
    """A malformed CSV record, with file:line provenance.  A ValueError
    subclass so ``train_with_recovery`` keeps classifying it FATAL (a
    restart re-reads the identical bad row) — but the message names
    the exact record instead of numpy's bare parse error."""

    def __init__(self, path: str, line: int, reason: str, raw: str = ""):
        self.path = path
        self.line = line
        self.reason = reason
        self.raw = raw
        super().__init__(
            f"{path}:{line}: {reason}"
            + (f" (row: {raw[:120]!r})" if raw else ""))


class CSVRecordReader:
    """DataVec ``CSVRecordReader(numLinesToSkip, delimiter)`` equivalent.

    Decodes the entire file eagerly with numpy's C parser.  A native C++
    fast path (data/native) is used automatically for large files when the
    extension is built.

    With a ``quarantine`` (data/resilient.py ``RecordQuarantine``) the
    decode is ROW-TOLERANT: malformed records — wrong column count,
    unparseable fields, non-finite values — are skipped, charged
    against the quarantine budget with file:line provenance, and the
    surviving rows become the table.  Without one, a malformed record
    raises ``CSVRowError`` naming the exact file:line (the strict path
    re-parses on numpy failure purely to recover the provenance).
    """

    def __init__(self, skip_lines: int = 0, delimiter: str = ","):
        self.skip_lines = skip_lines
        self.delimiter = delimiter

    def read(self, path: str, dtype=np.float32,
             quarantine=None) -> np.ndarray:
        from gan_deeplearning4j_tpu.data import native as _native

        if quarantine is not None:
            # row-tolerant path: the native parser (data/native.py) is
            # all-or-nothing with no row provenance, so tolerant decode
            # always takes the python row parser
            return self._read_rows(path, dtype, quarantine.charge)
        arr = _native.read_csv(path, self.skip_lines, self.delimiter, dtype)
        if arr is not None:
            return arr
        try:
            # comments=None: the contract is pure numeric CSV — without
            # it numpy silently DROPS any '#'-prefixed line, so a row
            # corrupted into '#…' garbage would shrink the table without
            # any error (and the strict/tolerant decodes would disagree
            # on the same file)
            return np.loadtxt(
                path,
                delimiter=self.delimiter,
                skiprows=self.skip_lines,
                dtype=dtype,
                ndmin=2,
                comments=None,
            )
        except ValueError:
            # strict mode still owes the caller provenance: re-parse
            # row-by-row and raise CSVRowError at the first bad record
            # (file:line) instead of numpy's positionless message
            def raise_row(file, line=None, row=None, reason="", raw=""):
                raise CSVRowError(file, line, reason, raw)

            return self._read_rows(path, dtype, raise_row)

    def _read_rows(self, path: str, dtype, on_bad_row) -> np.ndarray:
        """Two-phase decode with per-record validation: float parse and
        finiteness per line, then column count against the MAJORITY
        width of the parseable rows — so one torn-but-parseable record
        (wherever it sits, including line 1) gets rejected instead of
        poisoning the expected width and condemning every healthy row
        after it.  Bad records go to ``on_bad_row(file=, line=,
        reason=, raw=)`` in line order — the quarantine's ``charge``
        (skip-and-log, budget permitting) or a raiser (strict
        provenance path)."""
        from collections import Counter

        parsed = []   # (lineno, vals, raw) — parseable AND finite
        bad = []      # (lineno, reason, raw)
        with open(path, "r") as f:
            for lineno, line in enumerate(f, start=1):
                if lineno <= self.skip_lines:
                    continue
                s = line.strip()
                if not s:
                    continue  # blank line: numpy skips these too
                parts = s.split(self.delimiter)
                try:
                    vals = np.asarray(parts, dtype=np.float64)
                except ValueError:
                    bad.append((lineno, "unparseable field", s))
                    continue
                if not np.all(np.isfinite(vals)):
                    bad.append((lineno, "non-finite value", s))
                    continue
                parsed.append((lineno, vals, s))
        ncols = None
        if parsed:
            widths = Counter(v.shape[0] for _, v, _ in parsed)
            # majority wins; a tie breaks to the width seen first (the
            # file's leading contract) — deterministic either way
            top = widths.most_common()
            best = max(c for _, c in top)
            ncols = next(v.shape[0] for _, v, _ in parsed
                         if widths[v.shape[0]] == best)
            bad.extend(
                (ln, f"expected {ncols} columns, got {v.shape[0]}", s)
                for ln, v, s in parsed if v.shape[0] != ncols)
        for lineno, reason, raw in sorted(bad):
            on_bad_row(path, line=lineno, reason=reason, raw=raw)
        rows = [v.astype(dtype) for _, v, _ in parsed
                if v.shape[0] == ncols]
        if not rows:
            raise ValueError(
                f"{path}: no valid rows survived the tolerant decode")
        return np.stack(rows)


class RecordReaderDataSetIterator:
    """DL4J ``RecordReaderDataSetIterator(reader, batch, labelIndex, numClasses)``.

    Iterates fixed-size batches over a decoded table; ``reset()`` rewinds
    (the reference calls it for multi-epoch wraparound,
    dl4jGANComputerVision.java:524-526, and before each test sweep, :503).
    """

    def __init__(
        self,
        source,
        batch_size: int,
        label_index: Optional[int] = None,
        num_classes: int = 1,
        reader: Optional[CSVRecordReader] = None,
        dtype=np.float32,
        strict: bool = False,
        shuffle: bool = False,
        shuffle_seed: int = 0,
        quarantine=None,
    ):
        src_name = "<array>"
        if isinstance(source, (str, os.PathLike)):
            src_name = str(source)
            reader = reader or CSVRecordReader()
            if quarantine is not None:
                table = reader.read(str(source), dtype=dtype,
                                    quarantine=quarantine)
            else:
                table = reader.read(str(source), dtype=dtype)
        else:
            table = np.asarray(source, dtype=dtype)
            if table.ndim != 2:
                raise ValueError(f"expected 2-D table, got shape {table.shape}")
            if quarantine is not None:
                # array sources skip the reader's row validation: apply
                # the finite-value half of the ingest contract here
                bad = ~np.isfinite(table).all(axis=1)
                if bad.any():
                    for i in np.nonzero(bad)[0]:
                        quarantine.charge(src_name, row=int(i),
                                          reason="non-finite value")
                    table = np.ascontiguousarray(table[~bad])
        if strict and table.shape[0] % batch_size != 0:
            raise ValueError(
                f"{table.shape[0]} rows is not a multiple of batch_size={batch_size}"
            )
        self.batch_size = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        if label_index is not None and num_classes >= 2 \
                and quarantine is not None and table.shape[0]:
            # label validation belongs to ingest too: a row whose label
            # is outside [0, num_classes) is a corrupt RECORD, not a
            # reason to kill the run while the budget holds
            raw = table[:, label_index]
            idx = raw.astype(np.int64)
            bad = (idx < 0) | (idx >= num_classes)
            if bad.any():
                for i in np.nonzero(bad)[0]:
                    quarantine.charge(
                        src_name, row=int(i),
                        reason=f"label {raw[i]!r} outside "
                               f"[0, {num_classes})")
                table = np.ascontiguousarray(table[~bad])
        if label_index is None:
            self._features = table
            self._labels = None
        else:
            self._features = np.ascontiguousarray(
                np.delete(table, label_index, axis=1)
            )
            raw = table[:, label_index]
            if num_classes >= 2:
                # one-hot (CV path: numClasses=10 -> softmax labels)
                idx = raw.astype(np.int64)
                if table.shape[0] and (
                        idx.min() < 0 or idx.max() >= num_classes):
                    raise ValueError(
                        f"label column has values outside [0, {num_classes})"
                    )
                labels = np.zeros((table.shape[0], num_classes), dtype=dtype)
                labels[np.arange(table.shape[0]), idx] = 1.0
                self._labels = labels
            else:
                # numClasses=1: raw sigmoid target column (insurance path)
                self._labels = raw.reshape(-1, 1).astype(dtype)
        self._cursor = 0
        self._epoch = 0
        self._shuffle = bool(shuffle)
        self._shuffle_seed = int(shuffle_seed)
        self._order = self._epoch_order(0) if self._shuffle else None
        self._preprocessor = None

    @property
    def features(self) -> np.ndarray:
        return self._features

    @property
    def labels(self) -> Optional[np.ndarray]:
        return self._labels

    def num_examples(self) -> int:
        return self._features.shape[0]

    def has_next(self) -> bool:
        return self._cursor < self._features.shape[0]

    def next(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        lo = self._cursor
        hi = min(lo + self.batch_size, self._features.shape[0])
        self._cursor = hi
        if self._order is not None:
            idx = self._order[lo:hi]
            feats = self._features[idx]
            labels = (self._labels[idx] if self._labels is not None
                      else np.zeros((hi - lo, 0), dtype=feats.dtype))
        else:
            feats = self._features[lo:hi]
            labels = (
                self._labels[lo:hi]
                if self._labels is not None
                else np.zeros((hi - lo, 0), dtype=feats.dtype)
            )
        ds = DataSet(feats, labels)
        if self._preprocessor is not None:
            # contract: preprocess REPLACES ds.features (the normalizers
            # do), never mutates it — feats is a view of the backing table
            self._preprocessor.preprocess(ds)
        return ds

    @property
    def preprocessor(self):
        return self._preprocessor

    def set_preprocessor(self, preprocessor) -> None:
        """ND4J ``iterator.setPreProcessor(normalizer)``: applied to every
        ``next()``'s DataSet (data/normalizers.py fit/transform objects,
        or any object with ``preprocess(DataSet)`` that REPLACES
        ``features`` rather than mutating the passed view)."""
        self._preprocessor = preprocessor

    def reset(self) -> None:
        """Rewind for the next pass.  The epoch counter advances so a
        SHUFFLED iterator re-permutes per pass (and ``state()`` can
        name the pass); the ordered iterator's batch content is
        untouched — every pass replays the file order, as before."""
        self._cursor = 0
        self._epoch += 1
        if self._shuffle:
            self._order = self._epoch_order(self._epoch)

    # -- O(1) resumable state (the resilient-data-plane contract) ------------

    def _epoch_order(self, epoch: int) -> np.ndarray:
        """Row permutation for ``epoch`` — a PURE function of
        (shuffle_seed, epoch), so any epoch's order is recomputable
        from two integers.  That property is what makes the iterator
        state O(1): no RNG object to serialize, no replay needed."""
        rng = np.random.RandomState(
            (self._shuffle_seed * 1000003 + epoch) % (2 ** 31 - 1))
        return rng.permutation(self._features.shape[0])

    def state(self) -> dict:
        """Resumable position in O(1): (epoch, cursor) plus the shuffle
        contract.  An exhausted position normalizes to the NEXT epoch's
        start — the wrap the consumer loops would perform anyway — so a
        restored iterator always answers ``has_next()`` truthfully
        instead of stranding a fresh prefetch worker on a spent pass."""
        n = self._features.shape[0]
        epoch, cursor = self._epoch, self._cursor
        if n and cursor >= n:
            epoch, cursor = epoch + 1, 0
        return {"v": 1, "epoch": int(epoch), "cursor": int(cursor),
                "shuffle": self._shuffle,
                "shuffle_seed": self._shuffle_seed}

    def restore_state(self, state: dict) -> None:
        """Resume at a ``state()``/``state_for_step()`` position in
        O(1) — the checkpoint-resume replacement for replaying every
        consumed batch.  The shuffle contract must match: silently
        resuming an ordered run from a shuffled checkpoint (or with a
        different seed) would desynchronize the batch sequence."""
        if state.get("v") != 1:
            raise ValueError(f"unknown iterator state version: {state!r}")
        if bool(state.get("shuffle", False)) != self._shuffle or (
                self._shuffle
                and int(state.get("shuffle_seed", 0)) != self._shuffle_seed):
            raise ValueError(
                "iterator state shuffle contract mismatch: checkpoint "
                f"carries shuffle={state.get('shuffle')}/"
                f"seed={state.get('shuffle_seed')}, iterator is "
                f"shuffle={self._shuffle}/seed={self._shuffle_seed}")
        self._epoch = int(state["epoch"])
        self._cursor = int(state["cursor"])
        if self._shuffle:
            self._order = self._epoch_order(self._epoch)

    def state_for_step(self, step: int) -> dict:
        """The ``state()`` after ``step`` consumed FULL batches under
        the training loops' canonical pattern (partial tails consumed-
        and-skipped, exhaustion wraps) — pure O(1) arithmetic, no
        iteration.  Used by the trainer to stamp checkpoints on paths
        that never touch the host iterator (the device-resident loop
        slices batches on device)."""
        n = self._features.shape[0]
        full = n // self.batch_size
        if full <= 0:
            raise ValueError(
                f"no full batch of {self.batch_size} in {n} rows — the "
                "consumption pattern never advances")
        return {"v": 1, "epoch": int(step // full),
                "cursor": int((step % full) * self.batch_size),
                "shuffle": self._shuffle,
                "shuffle_seed": self._shuffle_seed}

    def __iter__(self) -> Iterator[DataSet]:
        self.reset()
        while self.has_next():
            yield self.next()


def write_csv_matrix(path: str, matrix, delimiter: str = ",", fmt: str = "%.8g") -> None:
    """Dump a 2-D array as CSV in the reference's artifact format (comma
    delimiter, no trailing newline — dl4jGANComputerVision.java:482-495),
    but vectorized instead of per-scalar ``getDouble`` writes.  Uses the
    threaded C++ formatter (data/native.py) when built; numpy otherwise."""
    import re

    m = np.asarray(matrix)
    if m.ndim == 1:
        m = m.reshape(1, -1)
    spec = re.fullmatch(r"%\.(\d+)([fg])", fmt)
    if spec and m.dtype.kind == "f":
        from gan_deeplearning4j_tpu.data import native

        raw = native.format_csv(m, delimiter, spec.group(2),
                                int(spec.group(1)))
        if raw is not None:
            with open(path, "wb") as f:
                f.write(raw)
            return
    buf = io.StringIO()
    np.savetxt(buf, m, delimiter=delimiter, fmt=fmt)
    text = buf.getvalue().rstrip("\n")
    with open(path, "w") as f:
        f.write(text)


def read_csv_matrix(path: str, delimiter: str = ",") -> np.ndarray:
    return np.loadtxt(path, delimiter=delimiter, ndmin=2)
