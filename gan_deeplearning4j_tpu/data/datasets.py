"""Dataset modules — the notebook's data-prep pipelines as code.

The reference prepares data in ``Python/gan.ipynb``:
  - cell 2 (raw lines 44-110): Keras MNIST -> flatten 784 -> /255 ->
    ``mnist_{train,test}.csv`` with the label appended as column 784.
  - cell 8 (raw lines 959-1000): R-generated ``data/claim_risk.csv`` +
    ``data/transactions.csv`` (1000 policies x 4 periods x 3 types) ->
    reshape (1000, 12) -> 70/30 split seed 666 -> min-max scaling by
    *train* stats -> ``insurance_{train,test}.csv`` with label column 12.

This module reproduces both contracts.  Because this environment has no
network egress and the reference's raw inputs (Keras download, R script
output) are unavailable, each dataset also has a deterministic synthetic
generator with real class structure so end-to-end training/eval is
meaningful: a procedural bitmap-font digit renderer for MNIST and a
label-dependent Poisson transaction-lattice model for insurance (the
reference's own insurance data is synthetic too).  If contract CSVs exist
at the given path they are always preferred.
"""

from __future__ import annotations

import os
from typing import Tuple

import numpy as np

from gan_deeplearning4j_tpu.data.csv import CSVRecordReader

SEED = 666  # numberOfTheBeast — the reference's seed everywhere

# ---------------------------------------------------------------------------
# MNIST (surrogate): procedural 5x7 bitmap-font digits -> 28x28
# ---------------------------------------------------------------------------

_DIGIT_FONT = [
    # 5x7 bitmaps, row-major, one string per digit
    "01110100011001110101110011000101110",  # 0
    "00100011000010000100001000010001110",  # 1
    "01110100010000100010001000100011111",  # 2
    "11111000100010000010000011000101110",  # 3
    "00010001100101010010111110001000010",  # 4
    "11111100001111000001000011000101110",  # 5
    "00110010001000011110100011000101110",  # 6
    "11111000010001000100010000100001000",  # 7
    "01110100011000101110100011000101110",  # 8
    "01110100011000101111000010001001100",  # 9
]


def _digit_bitmap(d: int) -> np.ndarray:
    bits = np.frombuffer(_DIGIT_FONT[d].encode(), dtype=np.uint8) - ord("0")
    return bits.reshape(7, 5).astype(np.float32)


# Symmetric confusable-glyph pairing for the calibrated difficulty tier:
# morphing happens WITHIN these pairs, and symmetry is what creates a
# genuine Bayes floor (a blend of 4-and-9 at mix 0.5 is equally likely to
# have come from either class; an asymmetric pairing would leak the source
# class through the pair identity and the ceiling would silently return
# to 1.0).
_CONFUSABLE = {0: 8, 8: 0, 1: 7, 7: 1, 3: 5, 5: 3, 4: 9, 9: 4, 2: 6, 6: 2}

# difficulty presets: affine pose ranges + the morph mixture
_MNIST_DIFFICULTY = {
    # v1 (rounds 1-2): clean glyphs, mild pose — classifier saturates at
    # 1.000 by step 2000 (RESULTS r2 §1), so the headline metric could
    # not move.  Kept for comparison runs.
    "v1": dict(theta=0.26, smin=2.4, smax=3.2, shear=0.15, trans=2.0,
               p_tail=0.0, morph=False),
    # calibrated (VERDICT r2 next-step #2): harder pose + confusable-pair
    # morphing with mix alpha ~ 95% U(0,.3) + 5% U(.3,.7).  P(alpha>.5) =
    # 0.025 puts the Bayes accuracy ceiling at ~0.975 BY CONSTRUCTION
    # (those samples are past the class midpoint, labeled by source);
    # raw-pixel linear probe measures 0.930 (real MNIST: ~0.92), so a
    # strong classifier lands in a discriminative 0.95-0.975 band that
    # CAN regress — honestly comparable in kind to the reference's 97.07%
    # (gan.ipynb raw line 373).
    "calibrated": dict(theta=0.35, smin=2.2, smax=3.3, shear=0.22,
                       trans=2.5, p_tail=0.05, morph=True),
}


def synthetic_mnist(
    n: int, seed: int = SEED, noise: float = 0.08, chunk: int = 4096,
    difficulty: str = "calibrated",
) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic MNIST-like digits: bitmap glyphs pushed through a
    random affine (rotation, anisotropic scale, shear, translation) with
    bilinear sampling, per-sample intensity variation and pixel noise;
    features in [0,1] like the notebook's /255 scaling.

    The affine variability matters for GAN *dynamics*, not just for
    classifier difficulty: with rigid axis-aligned glyphs the
    discriminator wins almost immediately (real handwriting never gives
    it pixel-grid shortcuts), its loss collapses, and the transfer
    classifier's features degrade — the failure mode observed on the
    un-augmented v1 of this generator.  Handwriting-like pose variation
    keeps D challenged the way real MNIST does.

    ``difficulty`` picks the ``_MNIST_DIFFICULTY`` preset: "calibrated"
    (default) adds confusable-pair glyph morphing whose mixture tail sets
    a ~0.975 Bayes accuracy ceiling, de-saturating the headline metric;
    "v1" is the rounds-1/2 separable tier.

    Returns (features[n,784] float32, labels[n] int64).
    """
    cfg = _MNIST_DIFFICULTY[difficulty]
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, size=n)
    glyphs = np.stack([_digit_bitmap(d) for d in range(10)])  # [10, 7, 5]
    partners = np.array([_CONFUSABLE[d] for d in range(10)])
    if cfg["morph"]:
        tail = rng.rand(n) < cfg["p_tail"]
        alpha = np.where(tail, rng.uniform(0.3, 0.7, n),
                         rng.uniform(0.0, 0.3, n)).astype(np.float32)
    else:
        alpha = np.zeros(n, dtype=np.float32)
    out = np.empty((n, 784), dtype=np.float32)
    # output pixel grid, centered
    yy, xx = np.meshgrid(np.arange(28, dtype=np.float32),
                         np.arange(28, dtype=np.float32), indexing="ij")
    for lo in range(0, n, chunk):
        hi = min(lo + chunk, n)
        m = hi - lo
        lab = labels[lo:hi]
        al = alpha[lo:hi, None, None]
        # per-sample affine params (inverse map: output px -> glyph coords)
        theta = rng.uniform(-cfg["theta"], cfg["theta"], m).astype(np.float32)
        sx = rng.uniform(cfg["smin"], cfg["smax"], m).astype(np.float32)
        sy = rng.uniform(cfg["smin"], cfg["smax"], m).astype(np.float32)
        shear = rng.uniform(-cfg["shear"], cfg["shear"], m).astype(np.float32)
        tx = rng.uniform(-cfg["trans"], cfg["trans"], m).astype(np.float32)
        ty = rng.uniform(-cfg["trans"], cfg["trans"], m).astype(np.float32)
        cos, sin = np.cos(theta), np.sin(theta)
        # centered output coords [m, 28, 28]
        xo = xx[None] - 13.5 - tx[:, None, None]
        yo = yy[None] - 13.5 - ty[:, None, None]
        # inverse rotation then inverse shear then inverse scale
        xr = cos[:, None, None] * xo + sin[:, None, None] * yo
        yr = -sin[:, None, None] * xo + cos[:, None, None] * yo
        xr = xr - shear[:, None, None] * yr
        gx = xr / sx[:, None, None] + 2.0   # glyph is 5 wide (center 2)
        gy = yr / sy[:, None, None] + 3.0   # glyph is 7 tall (center 3)
        # bilinear sample with zero outside
        x0 = np.floor(gx).astype(np.int32)
        y0 = np.floor(gy).astype(np.int32)
        fx, fy = gx - x0, gy - y0
        # the morph blend commutes with the (linear) bilinear sampling, so
        # the rendered image is exactly (1-a)*render(c) + a*render(partner)
        # at the SAME pose — a true pixel-space class interpolation
        g = (1.0 - al) * glyphs[lab] + al * glyphs[partners[lab]]
        gpad = np.pad(g, ((0, 0), (1, 1), (1, 1)))  # zero border
        x0c = np.clip(x0 + 1, 0, 5 + 1)
        y0c = np.clip(y0 + 1, 0, 7 + 1)
        x1c = np.clip(x0 + 2, 0, 5 + 1)
        y1c = np.clip(y0 + 2, 0, 7 + 1)
        idx = np.arange(m)[:, None, None]
        img = ((1 - fx) * (1 - fy) * gpad[idx, y0c, x0c]
               + fx * (1 - fy) * gpad[idx, y0c, x1c]
               + (1 - fx) * fy * gpad[idx, y1c, x0c]
               + fx * fy * gpad[idx, y1c, x1c])
        img *= rng.uniform(0.7, 1.0, m)[:, None, None]        # intensity
        img += rng.randn(m, 28, 28).astype(np.float32) * noise
        np.clip(img, 0.0, 1.0, out=img)
        out[lo:hi] = img.reshape(m, 784).astype(np.float32)
    return out, labels.astype(np.int64)


def export_mnist_csv(
    out_dir: str,
    n_train: int = 60000,
    n_test: int = 10000,
    seed: int = SEED,
) -> Tuple[str, str]:
    """Write ``mnist_{train,test}.csv`` in the notebook's contract (cell 2):
    784 feature columns formatted %.2f, integer label as column 784."""
    os.makedirs(out_dir, exist_ok=True)
    paths = []
    for split, n, s in (("train", n_train, seed), ("test", n_test, seed + 1)):
        path = os.path.join(out_dir, f"mnist_{split}.csv")
        feats, labels = synthetic_mnist(n, seed=s)
        table = np.concatenate([feats, labels.reshape(-1, 1).astype(np.float32)], axis=1)
        from gan_deeplearning4j_tpu.data import native

        raw = native.format_csv(table, ",", "f", 2, int_last=True)
        if raw is not None:  # threaded C++ formatter (scales with cores;
            # parity with np.savetxt on a single-core host)
            with open(path, "wb") as f:
                f.write(raw + b"\n")
        else:
            fmt = ["%.2f"] * 784 + ["%d"]
            np.savetxt(path, table, delimiter=",", fmt=fmt)
        paths.append(path)
    return tuple(paths)


def ensure_mnist_csv(data_dir: str, n_train: int = 60000, n_test: int = 10000) -> Tuple[str, str]:
    """Return (train_csv, test_csv), generating the synthetic surrogate only
    if the contract files don't already exist (real exported MNIST wins;
    a half-present pair is an error rather than a silent overwrite)."""
    train = os.path.join(data_dir, "mnist_train.csv")
    test = os.path.join(data_dir, "mnist_test.csv")
    have = (os.path.exists(train), os.path.exists(test))
    if have == (True, True):
        return train, test
    if have != (False, False):
        raise FileExistsError(
            f"one of {train} / {test} exists without the other; refusing to "
            "overwrite — delete the stray file or provide both"
        )
    export_mnist_csv(data_dir, n_train, n_test)
    return train, test


# ---------------------------------------------------------------------------
# Insurance: synthetic transaction lattices (notebook cell 8 pipeline)
# ---------------------------------------------------------------------------

N_POLICIES = 1000
N_PERIODS = 4       # tensorDimOneSize (dl4jGANInsurance.java:70)
N_TYPES = 3         # tensorDimTwoSize (:71)


def synthetic_transactions(
    n_policies: int = N_POLICIES, seed: int = SEED,
    difficulty: str = "calibrated",
) -> Tuple[np.ndarray, np.ndarray]:
    """Label-dependent transaction lattices: (transactions[n,4,3], risk[n]).

    Stands in for the reference's R-generated ``data/transactions.csv`` +
    ``data/claim_risk.csv`` (gitignored upstream, reference ``.gitignore:6``).
    High-risk policies (P=0.3) have escalating claim-type activity across
    periods; low-risk have flat premium-type activity — a structure a GAN
    discriminator's features can separate, like the real data's.

    ``difficulty="calibrated"`` (default; VERDICT r2 next-step #2) makes
    the risk signal heterogeneous so AUROC cannot saturate: each risky
    policy's escalation is scaled by a Gamma(2) random effect (some risky
    policies look benign) and 8% of benign policies get claim bursts
    (look risky).  Raw-feature logistic probe: AUROC 0.907 +/- 0.011
    across seeds — a discriminative counterpart to the reference's 91.63%
    (gan.ipynb raw line 374).  "v1" is the rounds-1/2 cleanly separable
    tier (AUROC pinned at 1.000).
    """
    rng = np.random.RandomState(seed)
    risk = (rng.rand(n_policies) < 0.3).astype(np.int64)
    base = np.array([[6.0, 3.0, 0.5]] * N_PERIODS)  # premium, service, claim
    lam = np.tile(base, (n_policies, 1, 1))
    escalate = np.array([0.5, 1.0, 2.0, 4.0]).reshape(1, N_PERIODS)
    if difficulty == "calibrated":
        gamma = rng.gamma(2.0, 0.5, n_policies)     # mean-1 random effect
        eff = risk * gamma
        burst = (risk == 0) & (rng.rand(n_policies) < 0.08)
        eff = eff + burst * rng.uniform(0.4, 1.0, n_policies)
        lam[:, :, 2] += eff.reshape(-1, 1) * escalate * 1.5
        lam[:, :, 0] -= eff.reshape(-1, 1) * escalate * 0.5
    elif difficulty == "v1":
        lam[:, :, 2] += risk.reshape(-1, 1) * escalate * 2.0
        lam[:, :, 0] -= risk.reshape(-1, 1) * escalate * 0.8
    else:
        raise KeyError(difficulty)
    lam = np.clip(lam, 0.1, None)
    trans = rng.poisson(lam).astype(np.float64)
    return trans, risk


def prepare_insurance(
    out_dir: str,
    n_policies: int = N_POLICIES,
    test_fraction: float = 0.3,
    seed: int = SEED,
) -> Tuple[str, str]:
    """The notebook's cell-8 pipeline: reshape (n, 12), 70/30 split seed 666,
    min-max scale **by train-split stats**, write
    ``insurance_{train,test}.csv`` (12 features + label column 12)."""
    os.makedirs(out_dir, exist_ok=True)
    trans, risk = synthetic_transactions(n_policies, seed)
    flat = trans.reshape(n_policies, N_PERIODS * N_TYPES)

    # train_test_split(..., test_size=0.3, random_state=666) semantics
    rng = np.random.RandomState(seed)
    perm = rng.permutation(n_policies)
    n_test = int(round(n_policies * test_fraction))
    test_idx, train_idx = perm[:n_test], perm[n_test:]
    x_train, x_test = flat[train_idx], flat[test_idx]
    y_train, y_test = risk[train_idx], risk[test_idx]

    lo = x_train.min(axis=0)
    hi = x_train.max(axis=0)
    span = np.where(hi > lo, hi - lo, 1.0)
    x_train = (x_train - lo) / span
    x_test = (x_test - lo) / span  # train stats, per the notebook

    paths = []
    for split, x, y in (("train", x_train, y_train), ("test", x_test, y_test)):
        path = os.path.join(out_dir, f"insurance_{split}.csv")
        table = np.concatenate([x, y.reshape(-1, 1).astype(np.float64)], axis=1)
        np.savetxt(path, table, delimiter=",", fmt="%.6f")
        paths.append(path)
    return tuple(paths)


def ensure_insurance_csv(data_dir: str) -> Tuple[str, str]:
    train = os.path.join(data_dir, "insurance_train.csv")
    test = os.path.join(data_dir, "insurance_test.csv")
    have = (os.path.exists(train), os.path.exists(test))
    if have == (True, True):
        return train, test
    if have != (False, False):
        raise FileExistsError(
            f"one of {train} / {test} exists without the other; refusing to "
            "overwrite — delete the stray file or provide both"
        )
    prepare_insurance(data_dir)
    return train, test


def load_split(path: str, label_index: int) -> Tuple[np.ndarray, np.ndarray]:
    """Decode a contract CSV into (features, raw label column)."""
    table = CSVRecordReader().read(path)
    feats = np.delete(table, label_index, axis=1)
    labels = table[:, label_index]
    return feats, labels


# ---------------------------------------------------------------------------
# Roadmap synthetic datasets (BASELINE.json configs 3-5; no network egress,
# so CIFAR-10 / CelebA get deterministic surrogates with class/appearance
# structure, like the MNIST surrogate above)
# ---------------------------------------------------------------------------


def synthetic_cifar10(
    n: int, seed: int = SEED, size: int = 32,
    difficulty: str = "v1",
) -> Tuple[np.ndarray, np.ndarray]:
    """CIFAR-10 surrogate: 10 classes = glyph shape in a class hue over a
    random background tint, random affine pose.  Returns
    (features[n, 3*size*size] float32 in [-1, 1] NCHW-flattened,
    labels[n] int64) — tanh-range, matching the cGAN generator head.

    ``difficulty``: "v1" (crisp class identity) or "calibrated"
    (VERDICT r4 #4): an 18% tail of samples carries LABEL-PRESERVING
    ambiguity — the glyph faded to 3-35% contrast, extra pixel noise,
    and the hue shifted to the exact boundary with a random neighbor
    class.  Unlike the MNIST
    calibrated tier's cross-class morphs, no sample is generated from
    another class's parameters (which would be a data bug for a
    CONDITIONAL model's training set — r4 note): tail samples are
    information-degraded, like blurry photos in real CIFAR, so a probe
    classifier's Bayes ceiling sits below 1.0 and the conditional-
    fidelity headline cannot saturate.  Tail draws use a separate RNG
    stream: non-tail pixels are bit-identical across the two tiers.
    """
    if difficulty not in ("v1", "calibrated"):
        raise ValueError(f"unknown difficulty {difficulty!r}")
    rng = np.random.RandomState(seed)
    gray, labels = synthetic_mnist(n, seed=seed + 1, noise=0.04,
                                   difficulty="v1")
    gray = gray.reshape(n, 28, 28)
    # class hues spread around the wheel; shape colored, background tinted
    hues = np.linspace(0.0, 1.0, 10, endpoint=False)
    out = np.empty((n, 3, size, size), dtype=np.float32)
    pad = (size - 28) // 2
    rng_tail = (np.random.RandomState(seed + 9001)
                if difficulty == "calibrated" else None)

    def hue_rgb(h):
        phase = h[:, None, None]
        return np.stack([
            0.5 + 0.5 * np.cos(2 * np.pi * (phase + off))
            for off in (0.0, 1 / 3, 2 / 3)], axis=1).astype(np.float32)

    for lo in range(0, n, 4096):
        hi = min(lo + 4096, n)
        m = hi - lo
        g = np.zeros((m, size, size), dtype=np.float32)
        g[:, pad:pad + 28, pad:pad + 28] = gray[lo:hi]
        h = hues[labels[lo:hi]] + rng.uniform(-0.03, 0.03, m)
        rgb = hue_rgb(h)  # cheap hue -> rgb (cosine color wheel)
        bg = rng.uniform(-0.25, 0.25, (m, 3, 1, 1)).astype(np.float32)
        img = bg + g[:, None] * (2.0 * rgb - 1.0 - bg)
        if rng_tail is not None:
            # the ambiguous tail: hue at the EXACT boundary with a random
            # neighbor class, glyph faded toward invisibility, extra
            # pixel noise — the deep-faded half of the tail carries
            # essentially only the boundary hue (a ~50/50 cue between
            # two classes), setting the probe's Bayes ceiling
            tail = rng_tail.rand(m) < 0.18
            nb = rng_tail.choice([-1.0, 1.0], m)
            h2 = (hues[labels[lo:hi]] + nb * 0.05
                  + rng_tail.uniform(-0.008, 0.008, m))
            fade = rng_tail.uniform(0.03, 0.35, m).astype(np.float32)
            noise = rng_tail.randn(m, 3, size, size).astype(np.float32)
            rgb2 = hue_rgb(h2)
            g2 = g * fade[:, None, None]
            img2 = (bg + g2[:, None] * (2.0 * rgb2 - 1.0 - bg)
                    + 0.12 * noise)
            img[tail] = img2[tail]
        out[lo:hi] = np.clip(img, -1.0, 1.0)
    return out.reshape(n, -1), labels


# CelebA-style binary attribute names for the surrogate (real CelebA is a
# 40-binary-attribute dataset; these 8 are the ones the procedural
# generator controls).  Thresholds split each ~50/50 over the draw laws.
CELEBA_ATTR_NAMES = (
    "face_right", "face_low", "big_face", "pale_skin",
    "bright_bg", "dark_hair", "wide_mouth", "tall_face",
)


def synthetic_celeba(n: int, seed: int = SEED, size: int = 64,
                     return_attrs: bool = False):
    """CelebA surrogate: procedural 64x64 'faces' — skin-toned ellipse,
    two eyes, mouth, hair band, varying pose/colors/background.  Returns
    [n, 3*size*size] float32 in [-1, 1], NCHW-flattened; with
    ``return_attrs`` also [n, 8] float32 binary attributes (the analog of
    CelebA's attribute labels, ``CELEBA_ATTR_NAMES``) derived from the
    SAME procedural draws — the pixel stream is bit-identical either way.
    The DCGAN itself is unconditional; the attributes exist to train the
    frozen 64x64 FID feature extractor (eval/fid_extractor.py)."""
    rng = np.random.RandomState(seed)
    yy, xx = np.meshgrid(np.linspace(-1, 1, size), np.linspace(-1, 1, size),
                         indexing="ij")
    out = np.empty((n, 3, size, size), dtype=np.float32)
    attrs = np.empty((n, len(CELEBA_ATTR_NAMES)), dtype=np.float32)
    for i in range(n):
        cx, cy = rng.uniform(-0.15, 0.15, 2)
        rx = rng.uniform(0.45, 0.6)
        ry = rng.uniform(0.55, 0.75)
        face = (((xx - cx) / rx) ** 2 + (((yy - cy) / ry) ** 2)) < 1.0
        skin_scale = rng.uniform(0.7, 1.1)
        skin = np.array([0.9, 0.65, 0.5]) * skin_scale
        bg = rng.uniform(-1.0, 1.0, 3)
        img = np.empty((3, size, size), dtype=np.float32)
        for c in range(3):
            img[c] = np.where(face, 2 * skin[c] - 1, bg[c])
        # hair: top band of the face ellipse
        hair_color = rng.uniform(-1.0, 0.0, 3)
        hair = face & (yy < cy - 0.25 * ry)
        for c in range(3):
            img[c] = np.where(hair, hair_color[c], img[c])
        # eyes and mouth
        for ex in (-0.22, 0.22):
            eye = (((xx - cx - ex) / 0.07) ** 2
                   + ((yy - cy + 0.12) / 0.05) ** 2) < 1.0
            img[:, eye] = -0.9
        mouth_rx = rng.uniform(0.12, 0.25)
        mouth = (((xx - cx) / mouth_rx) ** 2
                 + (((yy - cy - 0.35) / 0.05) ** 2)) < 1.0
        img[0, mouth] = 0.6
        img[1:, mouth] = -0.6
        img += rng.randn(3, size, size).astype(np.float32) * 0.04
        out[i] = np.clip(img, -1.0, 1.0)
        attrs[i] = (cx > 0.0, cy > 0.0, rx * ry > 0.34,
                    skin_scale > 0.9, bg.mean() > 0.0,
                    hair_color.mean() < -0.5, mouth_rx > 0.185, ry > 0.65)
    if return_attrs:
        return out.reshape(n, -1), attrs
    return out.reshape(n, -1)
