"""Image record reader — DataVec's image pipeline, TPU-native.

The reference's classpath carries DataVec's image readers
(``datavec-data-image`` + OpenCV/leptonica, ``dl4jGAN.iml`` — SURVEY.md
§2b: unused by the mains, whose data arrives as CSV, and slated for
"PIL/numpy loaders" in the rebuild).  This is that loader: a directory
of images becomes an NCHW float32 table, with DataVec's
``ParentPathLabelGenerator`` convention (label = parent directory name)
when subdirectories are present.

No OpenCV: PIL decodes/resizes (already in the environment via
matplotlib), numpy lays out [N, C, H, W] scaled to [0, 1] — matching the
notebook's /255 convention (gan.ipynb cell 2) — with an optional
[-1, 1] tanh range for the roadmap GAN families.
"""

from __future__ import annotations

import dataclasses
import os
from typing import List, Optional, Tuple

import numpy as np

_EXTENSIONS = (".png", ".jpg", ".jpeg", ".bmp", ".gif")


@dataclasses.dataclass(frozen=True)
class ImageRecordReader:
    """Decode images to [C, height, width] float32.

    ``channels``: 1 (grayscale) or 3 (RGB).  ``tanh_range``: scale to
    [-1, 1] instead of [0, 1] (the roadmap generators' output range).
    """

    height: int
    width: int
    channels: int = 3
    tanh_range: bool = False

    def read_image(self, path: str) -> np.ndarray:
        from PIL import Image

        with Image.open(path) as im:
            im = im.convert("L" if self.channels == 1 else "RGB")
            im = im.resize((self.width, self.height), Image.BILINEAR)
            arr = np.asarray(im, dtype=np.float32) / 255.0
        if self.channels == 1:
            arr = arr[None]                       # [1, H, W]
        else:
            arr = np.transpose(arr, (2, 0, 1))    # HWC -> CHW
        if self.tanh_range:
            arr = arr * 2.0 - 1.0
        return arr

    def read_folder(
        self, root: str, flatten: bool = True,
        limit: Optional[int] = None,
    ) -> Tuple[np.ndarray, Optional[np.ndarray], List[str]]:
        """Read a directory tree of images.

        Layout A (labelled, DataVec ParentPathLabelGenerator):
        ``root/<class_name>/img.png`` — returns (features, labels,
        class_names) with labels indexing the sorted class names.
        Layout B (unlabelled): images directly under ``root`` — returns
        (features, None, []).

        ``flatten``: [N, C*H*W] (the graph APIs' cnn_flat input layout)
        instead of [N, C, H, W].
        """
        def images_in(d: str) -> List[str]:
            return sorted(f for f in os.listdir(d)
                          if f.lower().endswith(_EXTENSIONS))

        # a directory is a class dir only if it actually holds images —
        # a stray .thumbnails/ must not flip a flat folder into
        # labelled mode
        classes = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d))
            and images_in(os.path.join(root, d)))
        files: List[Tuple[str, int]] = []
        if classes:
            # interleave classes so a ``limit`` keeps class balance
            # (a class-sorted list would drop later classes entirely)
            per_class = [
                [(os.path.join(root, cls, f), idx)
                 for f in images_in(os.path.join(root, cls))]
                for idx, cls in enumerate(classes)]
            longest = max(len(lst) for lst in per_class)
            for i in range(longest):
                for lst in per_class:
                    if i < len(lst):
                        files.append(lst[i])
        else:
            files = [(os.path.join(root, f), -1) for f in images_in(root)]
        if limit is not None:
            files = files[:limit]
        if not files:
            raise FileNotFoundError(f"no images under {root}")
        feats = np.stack([self.read_image(p) for p, _ in files])
        labels = (np.asarray([lab for _, lab in files], dtype=np.int64)
                  if classes else None)
        if flatten:
            feats = feats.reshape(feats.shape[0], -1)
        return feats, labels, classes
