"""Native (C++) fast CSV decode path — libnd4j/DataVec-style native IO.

The reference's IO runs on the JVM with native BLAS underneath; its CSV
decode is pure Java (DataVec).  Here the hot decode is optionally offloaded
to a small C++ shared library (see ``native_src/fastcsv.cpp``), loaded via
ctypes.  Falls back to numpy transparently when the library isn't built.

Build: ``python -m gan_deeplearning4j_tpu.data.build_native`` (uses g++;
no external deps).
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

_LIB = None
_LIB_TRIED = False


def _lib_path() -> str:
    return os.path.join(os.path.dirname(__file__), "native_src", "libfastcsv.so")


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    path = _lib_path()
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.fastcsv_count.restype = ctypes.c_long
        lib.fastcsv_count.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.c_char,
            ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long),
        ]
        lib.fastcsv_parse.restype = ctypes.c_long
        lib.fastcsv_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.c_char,
            ctypes.POINTER(ctypes.c_float), ctypes.c_long,
        ]
        if hasattr(lib, "fastcsv_format"):  # older .so without the writer
            lib.fastcsv_format.restype = ctypes.c_long
            lib.fastcsv_format.argtypes = [
                ctypes.POINTER(ctypes.c_float), ctypes.c_long, ctypes.c_long,
                ctypes.c_char, ctypes.c_char, ctypes.c_int, ctypes.c_int,
                ctypes.c_char_p, ctypes.c_long,
            ]
        _LIB = lib
    except OSError:
        _LIB = None
    return _LIB


def available() -> bool:
    return _load() is not None


def read_csv(path: str, skip_lines: int, delimiter: str, dtype) -> Optional[np.ndarray]:
    """Decode a numeric CSV via the C++ parser; None if unavailable (caller
    falls back to numpy).

    Resilience seam (data/resilient.py): this parser is ALL-OR-NOTHING
    and carries no per-row provenance, so the row-tolerant quarantine
    decode (``CSVRecordReader.read(..., quarantine=...)``) deliberately
    bypasses it — corrupt-record handling needs file:line attribution
    the C side doesn't produce.  Transient I/O faults (the open/read
    below) surface as OSError and are retried by ``RetryingReader``
    like any other reader's."""
    if dtype != np.float32 or len(delimiter) != 1:
        return None
    lib = _load()
    if lib is None:
        return None
    with open(path, "rb") as f:
        data = f.read()
    for _ in range(skip_lines):
        nl = data.find(b"\n")
        if nl < 0:
            return None
        data = data[nl + 1:]
    rows = ctypes.c_long()
    cols = ctypes.c_long()
    ok = lib.fastcsv_count(
        data, len(data), delimiter.encode()[0], ctypes.byref(rows), ctypes.byref(cols)
    )
    if ok != 0 or rows.value <= 0 or cols.value <= 0:
        return None
    out = np.empty((rows.value, cols.value), dtype=np.float32)
    n = lib.fastcsv_parse(
        data, len(data), delimiter.encode()[0],
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), out.size,
    )
    if n != out.size:
        return None
    return out


def format_csv(matrix: np.ndarray, delimiter: str = ",", fmt: str = "g",
               precision: int = 8, int_last: bool = False,
               chunk_rows: int = 8192) -> Optional[bytes]:
    """Format a float32 matrix as CSV bytes via the threaded C++ writer
    (the decoder's write-side twin); None if unavailable — caller falls
    back to numpy.  ``fmt``: 'f' (fixed ``precision`` decimals) or 'g'
    (``precision`` significant digits); ``int_last`` prints the final
    column as an integer (truncated toward zero like numpy's "%d";
    non-finite labels write 0 where numpy would raise).

    Formats in row chunks so peak memory is bounded by the chunk, not the
    table (a 60000x785 export would otherwise allocate ~GB transiently);
    if a chunk's tight capacity estimate is exceeded it retries once with
    the worst-case bound (63 bytes/value, the C side's snprintf clamp)."""
    lib = _load()
    if lib is None or not hasattr(lib, "fastcsv_format"):
        return None
    if len(delimiter) != 1 or fmt not in ("f", "g") or precision > 32:
        return None
    m = np.asarray(matrix)
    if m.dtype != np.float32:
        # a float64 table would silently lose digits through the f32
        # formatter — let the caller's numpy fallback keep full precision
        return None
    m = np.ascontiguousarray(m)
    if m.ndim != 2 or m.size == 0:
        return None

    def fmt_chunk(chunk: np.ndarray) -> Optional[bytes]:
        for per_value in (precision + 10, 64):
            capacity = chunk.size * per_value
            buf = ctypes.create_string_buffer(capacity)
            n = lib.fastcsv_format(
                chunk.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                chunk.shape[0], chunk.shape[1], delimiter.encode()[0],
                fmt.encode()[0], precision, int(int_last), buf, capacity,
            )
            if n >= 0:
                # copies exactly n bytes (buf.raw would materialize the
                # whole over-allocated capacity first)
                return ctypes.string_at(buf, n)
        return None

    parts = []
    for lo in range(0, m.shape[0], chunk_rows):
        part = fmt_chunk(np.ascontiguousarray(m[lo:lo + chunk_rows]))
        if part is None:
            return None
        parts.append(part)
    return b"\n".join(parts)
