"""Native (C++) fast CSV decode path — libnd4j/DataVec-style native IO.

The reference's IO runs on the JVM with native BLAS underneath; its CSV
decode is pure Java (DataVec).  Here the hot decode is optionally offloaded
to a small C++ shared library (see ``native_src/fastcsv.cpp``), loaded via
ctypes.  Falls back to numpy transparently when the library isn't built.

Build: ``python -m gan_deeplearning4j_tpu.data.build_native`` (uses g++;
no external deps).
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

_LIB = None
_LIB_TRIED = False


def _lib_path() -> str:
    return os.path.join(os.path.dirname(__file__), "native_src", "libfastcsv.so")


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _LIB_TRIED
    if _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    path = _lib_path()
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.fastcsv_count.restype = ctypes.c_long
        lib.fastcsv_count.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.c_char,
            ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_long),
        ]
        lib.fastcsv_parse.restype = ctypes.c_long
        lib.fastcsv_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_long, ctypes.c_char,
            ctypes.POINTER(ctypes.c_float), ctypes.c_long,
        ]
        _LIB = lib
    except OSError:
        _LIB = None
    return _LIB


def available() -> bool:
    return _load() is not None


def read_csv(path: str, skip_lines: int, delimiter: str, dtype) -> Optional[np.ndarray]:
    """Decode a numeric CSV via the C++ parser; None if unavailable (caller
    falls back to numpy)."""
    if dtype != np.float32 or len(delimiter) != 1:
        return None
    lib = _load()
    if lib is None:
        return None
    with open(path, "rb") as f:
        data = f.read()
    for _ in range(skip_lines):
        nl = data.find(b"\n")
        if nl < 0:
            return None
        data = data[nl + 1:]
    rows = ctypes.c_long()
    cols = ctypes.c_long()
    ok = lib.fastcsv_count(
        data, len(data), delimiter.encode()[0], ctypes.byref(rows), ctypes.byref(cols)
    )
    if ok != 0 or rows.value <= 0 or cols.value <= 0:
        return None
    out = np.empty((rows.value, cols.value), dtype=np.float32)
    n = lib.fastcsv_parse(
        data, len(data), delimiter.encode()[0],
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), out.size,
    )
    if n != out.size:
        return None
    return out
