// fastcsv — minimal native numeric-CSV decoder.
//
// TPU-native stand-in for the reference stack's native IO layer: DataVec's
// CSV decode runs on the JVM, but the runtime underneath (libnd4j,
// nd4j-native — reference Java/dl4jGAN.iml:255) is C++; this keeps the
// framework's hot host-side decode native too.  Exposed to Python via
// ctypes (no pybind11 in this image).
//
// Contract: numeric CSV, single-char delimiter, '\n' rows (optional '\r'),
// no quoting.  Returns row-major float32.  Fixed-notation numbers take a
// hand-rolled parse loop; scientific notation falls back to strtod.  Rows
// are decoded in parallel across hardware threads.
//
// Build: python -m gan_deeplearning4j_tpu.data.build_native

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

// Parse one number at p (must not pass end); advances p. NaN-free fast path
// for [-+]?digits[.digits]; falls back to strtod for exponents/inf/nan.
inline float parse_value(const char*& p, const char* end, bool& ok) {
    const char* start = p;
    bool neg = false;
    if (p < end && (*p == '-' || *p == '+')) { neg = (*p == '-'); p++; }
    double v = 0.0;
    const char* digits_start = p;
    while (p < end && *p >= '0' && *p <= '9') v = v * 10.0 + (*p++ - '0');
    if (p < end && *p == '.') {
        p++;
        double scale = 0.1;
        while (p < end && *p >= '0' && *p <= '9') { v += (*p++ - '0') * scale; scale *= 0.1; }
    }
    if (p == digits_start || (p < end && (*p == 'e' || *p == 'E' ||
                                          *p == 'n' || *p == 'N' ||
                                          *p == 'i' || *p == 'I'))) {
        char* next = nullptr;
        double sv = strtod(start, &next);
        if (next == start) { ok = false; return 0.0f; }
        p = next;
        ok = true;
        return (float)sv;
    }
    ok = true;
    return (float)(neg ? -v : v);
}

// Parse rows whose byte ranges are [begin, end) into out (already offset).
long parse_range(const char* p, const char* end, char delim, float* out, long capacity) {
    long n = 0;
    while (p < end) {
        while (p < end && (*p == '\n' || *p == '\r')) p++;
        if (p >= end) break;
        for (;;) {
            bool ok = false;
            float v = parse_value(p, end, ok);
            if (!ok || n >= capacity) return -1;
            out[n++] = v;
            while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) p++;
            if (p < end && *p == delim) { p++; continue; }
            break;
        }
        while (p < end && *p != '\n') p++;
    }
    return n;
}

}  // namespace

extern "C" {

// Count rows/cols. Returns 0 on success, nonzero on ragged/invalid input.
long fastcsv_count(const char* data, long len, char delim, long* rows, long* cols) {
    long r = 0, c = -1, cur = 1;
    const char* end = data + len;
    const char* p = data;
    bool any = false;
    while (p < end) {
        char ch = *p++;
        if (ch == delim) {
            cur++;
        } else if (ch == '\n') {
            if (any || cur > 1) {
                if (c < 0) c = cur;
                else if (c != cur) return 1;
                r++;
            }
            cur = 1;
            any = false;
        } else if (ch != '\r' && ch != ' ' && ch != '\t') {
            any = true;
        }
    }
    if (any) {  // final row without trailing newline
        if (c < 0) c = cur;
        else if (c != cur) return 1;
        r++;
    }
    *rows = r;
    *cols = c < 0 ? 0 : c;
    return 0;
}

// Parse into out[capacity]; returns number of values written (or -1 on error).
// Splits the buffer at line boundaries and decodes chunks across threads;
// each chunk's output offset is chunk_start_row * cols (cols uniform, as
// validated by fastcsv_count).
long fastcsv_parse(const char* data, long len, char delim, float* out, long capacity) {
    long rows = 0, cols = 0;
    if (fastcsv_count(data, len, delim, &rows, &cols) != 0) return -1;
    if (rows * cols > capacity) return -1;
    if (rows == 0) return 0;

    unsigned hw = std::thread::hardware_concurrency();
    long nthreads = hw ? (long)hw : 1;
    if (nthreads > rows) nthreads = rows;
    if (rows * cols < 1 << 16) nthreads = 1;  // not worth spawning

    // Chunk boundaries: walk to the nearest newline after each even split,
    // counting rows so far so each chunk knows its output offset.
    struct Chunk { const char* begin; const char* end; long row0; };
    std::vector<Chunk> chunks;
    const char* end = data + len;
    const char* p = data;
    long row0 = 0;
    for (long t = 0; t < nthreads; t++) {
        const char* target = data + (len * (t + 1)) / nthreads;
        const char* q = (t == nthreads - 1) ? end : target;
        while (q < end && *q != '\n') q++;
        if (q < end) q++;  // include the newline
        long chunk_rows = 0;
        for (const char* s = p; s < q; s++) if (*s == '\n') chunk_rows++;
        if (q == end && len > 0 && end[-1] != '\n') chunk_rows++;  // last row, no trailing \n
        chunks.push_back({p, q, row0});
        row0 += chunk_rows;
        p = q;
        if (p >= end) break;
    }

    std::vector<long> results(chunks.size());
    std::vector<std::thread> threads;
    for (size_t i = 0; i < chunks.size(); i++) {
        threads.emplace_back([&, i]() {
            const Chunk& ck = chunks[i];
            results[i] = parse_range(ck.begin, ck.end, delim,
                                     out + ck.row0 * cols,
                                     rows * cols - ck.row0 * cols);
        });
    }
    for (auto& t : threads) t.join();

    long total = 0;
    for (long r : results) {
        if (r < 0) return -1;
        total += r;
    }
    return total;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Writer: format a row-major float32 matrix as CSV text (the reverse of
// fastcsv_parse; completes the native data layer's read+write pair).
// ---------------------------------------------------------------------------

namespace {

// Format rows [row0, row1) into a string. fmt: 'f' (fixed, %.*f) or 'g'
// (significant digits, %.*g). int_last: last column printed as %ld
// (the dataset contract's integer label column).
std::string format_rows(const float* data, long row0, long row1, long cols,
                        char delim, char fmt, int precision, int int_last) {
    std::string out;
    out.reserve((size_t)(row1 - row0) * cols * (precision + 8));
    char buf[64];
    const char f_or_g[2][5] = {"%.*f", "%.*g"};
    const char* spec = (fmt == 'f') ? f_or_g[0] : f_or_g[1];
    for (long r = row0; r < row1; r++) {
        const float* row = data + r * cols;
        for (long c = 0; c < cols; c++) {
            int n;
            if (int_last && c == cols - 1) {
                // truncate toward zero like numpy's "%d"; guard the cast
                // (out-of-range/NaN float->long is UB) by writing 0
                double dv = (double)row[c];
                if (!(dv > -9.2e18 && dv < 9.2e18)) dv = 0.0;
                n = snprintf(buf, sizeof buf, "%lld", (long long)dv);
            } else {
                n = snprintf(buf, sizeof buf, spec, precision,
                             (double)row[c]);
            }
            // snprintf returns the WOULD-BE length; clamp to what was
            // actually written when the value overflows buf
            if (n > (int)sizeof buf - 1) n = (int)sizeof buf - 1;
            out.append(buf, (size_t)n);
            if (c + 1 < cols) out.push_back(delim);
        }
        out.push_back('\n');
    }
    return out;
}

}  // namespace

extern "C" {

// Format the matrix into out[capacity]. Returns bytes written (WITHOUT a
// trailing newline, matching the artifact contract), or -1 if the buffer
// is too small. Threaded across row chunks.
long fastcsv_format(const float* data, long rows, long cols, char delim,
                    char fmt, int precision, int int_last,
                    char* out, long capacity) {
    if (rows <= 0 || cols <= 0) return 0;
    unsigned hw = std::thread::hardware_concurrency();
    long nthreads = hw ? (long)hw : 1;
    if (nthreads > rows) nthreads = rows;
    if (rows * cols < 1 << 15) nthreads = 1;

    std::vector<std::string> parts((size_t)nthreads);
    std::vector<std::thread> threads;
    for (long t = 0; t < nthreads; t++) {
        long r0 = rows * t / nthreads;
        long r1 = rows * (t + 1) / nthreads;
        threads.emplace_back([&, t, r0, r1]() {
            parts[(size_t)t] = format_rows(data, r0, r1, cols, delim, fmt,
                                           precision, int_last);
        });
    }
    for (auto& th : threads) th.join();

    long total = 0;
    for (const auto& s : parts) total += (long)s.size();
    // the memcpy loop writes ALL total bytes (incl. the final newline the
    // returned count excludes) — capacity must cover every written byte
    if (total > capacity) return -1;
    char* p = out;
    for (const auto& s : parts) {
        memcpy(p, s.data(), s.size());
        p += s.size();
    }
    return total - 1;  // exclude the final trailing newline from the count
}

}  // extern "C"
