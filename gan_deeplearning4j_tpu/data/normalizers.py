"""Dataset normalizers — ND4J's ``DataNormalization`` preprocessors.

The DL4J stack ships fit/transform normalizers
(``org.nd4j.linalg.dataset.api.preprocessor``: NormalizerMinMaxScaler,
NormalizerStandardize) that are fit on the TRAIN split and applied to
every ``DataSet`` an iterator yields; the reference's notebook does the
same min-max-by-train-stats scaling by hand (``gan.ipynb`` cell 8, raw
lines 959-1000 — reimplemented in data/datasets.py).  These classes are
the framework-level API a DL4J user expects, with the same semantics:

    scaler = NormalizerMinMaxScaler()
    scaler.fit(iter_train)          # train-split stats only
    iter_train.set_preprocessor(scaler)   # applied to every next()
    iter_test.set_preprocessor(scaler)    # test scaled by TRAIN stats

Both serialize to/from a small ``.npz`` (the HDF5-normalizer-save
equivalent) so inference services can restore the exact train-time
scaling.
"""

from __future__ import annotations

import numpy as np


class _FitNormalizer:
    """fit over an iterator or array; transform features on a DataSet
    (labels untouched, like ND4J's default).

    ``preprocess`` REPLACES ``dataset.features`` with a new array — it
    must not mutate the passed array, which may be a view of the
    iterator's backing table."""

    _STAT_NAMES: tuple = ()     # fitted arrays persisted in save()
    _CONFIG_NAMES: tuple = ()   # constructor scalars persisted in save()

    def __init__(self):
        for n in self._STAT_NAMES:
            setattr(self, n, None)

    # -- fitting -------------------------------------------------------------

    def fit(self, data) -> "_FitNormalizer":
        """``data``: a DataSetIterator or a [N, F] array.  Stats are
        always computed on the RAW features — an iterator's backing table
        is read directly, so a preprocessor already attached to it (even
        this one) cannot leak into the fit."""
        if hasattr(data, "features") and not isinstance(data, np.ndarray):
            x = np.asarray(data.features)
        elif hasattr(data, "reset") and hasattr(data, "next"):
            data.reset()
            batches = []
            while data.has_next():
                batches.append(np.asarray(data.next().features))
            data.reset()
            x = np.concatenate(batches, axis=0)
        else:
            x = np.asarray(data)
        self._fit_array(x)
        return self

    def _fit_array(self, x: np.ndarray) -> None:
        raise NotImplementedError

    def _check_fit(self) -> None:
        if getattr(self, self._STAT_NAMES[0]) is None:
            raise ValueError(f"{type(self).__name__} must be fit first")

    # -- application ---------------------------------------------------------

    def transform(self, features: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def revert(self, features: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def preprocess(self, dataset) -> None:
        """In-place DataSet preprocessing — ND4J ``preProcess(DataSet)``."""
        dataset.features = self.transform(dataset.features)

    def __call__(self, dataset):
        self.preprocess(dataset)
        return dataset

    # -- persistence ---------------------------------------------------------

    def save(self, path: str) -> None:
        self._check_fit()
        np.savez(path, __type__=type(self).__name__,
                 **{n: getattr(self, n)
                    for n in self._STAT_NAMES + self._CONFIG_NAMES})

    @staticmethod
    def load(path: str) -> "_FitNormalizer":
        with np.load(path) as f:
            kind = str(f["__type__"])
            cls = {c.__name__: c for c in
                   (NormalizerMinMaxScaler, NormalizerStandardize)}[kind]
            out = cls()
            for n in cls._STAT_NAMES:
                setattr(out, n, f[n])
            for n in cls._CONFIG_NAMES:
                if n in f:  # older files lack config scalars
                    setattr(out, n, float(f[n]))
        return out


class NormalizerMinMaxScaler(_FitNormalizer):
    """Scale features to [min_range, max_range] by train-split min/max —
    ND4J NormalizerMinMaxScaler (the notebook's insurance scaling)."""

    _STAT_NAMES = ("data_min", "data_max")
    _CONFIG_NAMES = ("min_range", "max_range")

    def __init__(self, min_range: float = 0.0, max_range: float = 1.0):
        super().__init__()
        self.min_range = float(min_range)
        self.max_range = float(max_range)

    def _fit_array(self, x):
        self.data_min = x.min(axis=0)
        self.data_max = x.max(axis=0)

    def _scale(self):
        span = self.data_max - self.data_min
        return np.where(span == 0, 1.0, span)  # constant columns -> min_range

    def transform(self, features):
        self._check_fit()
        unit = (np.asarray(features) - self.data_min) / self._scale()
        return (unit * (self.max_range - self.min_range)
                + self.min_range).astype(np.float32)

    def revert(self, features):
        self._check_fit()
        unit = (np.asarray(features) - self.min_range) / (
            self.max_range - self.min_range)
        return (unit * self._scale() + self.data_min).astype(np.float32)


class NormalizerStandardize(_FitNormalizer):
    """Zero-mean unit-variance by train-split stats — ND4J
    NormalizerStandardize."""

    _STAT_NAMES = ("mean", "std")

    def _fit_array(self, x):
        self.mean = x.mean(axis=0)
        std = x.std(axis=0)
        self.std = np.where(std == 0, 1.0, std)  # constant columns pass through

    def transform(self, features):
        self._check_fit()
        return ((np.asarray(features) - self.mean) / self.std).astype(
            np.float32)

    def revert(self, features):
        self._check_fit()
        return (np.asarray(features) * self.std + self.mean).astype(np.float32)
