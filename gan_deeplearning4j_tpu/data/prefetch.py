"""Prefetching batch iterator — overlap host IO with device compute.

The reference decodes CSV rows on the training thread every iteration
(``iterTrain.next()`` inside the hot loop, dl4jGANComputerVision.java:389
— disk IO each iteration, SURVEY.md §3.2).  Here a background thread
stays ``prefetch_depth`` batches ahead: it pulls from the underlying
iterator, converts, and (optionally) starts the host->device transfer via
``jax.device_put``, so when the training loop asks for batch k the
transfer of batch k is already in flight while the device still computes
batch k-1.  JAX's async dispatch does the rest.

Wraps any iterator with the ``has_next``/``next``/``reset`` protocol.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional

import jax

# a consumer blocked on the prefetch queue longer than this records a
# ``data.prefetch_stall`` event (telemetry/events.py): the pipeline
# failed to stay ahead of the device — the signal a goodput data_wait
# spike needs a timeline for.  Short waits are normal double-buffer
# jitter and would only be noise.
STALL_EVENT_S = 0.05


class PrefetchIterator:
    """Double (or deeper) buffered wrapper around a DataSet iterator.

    ``sharding``: optional jax sharding — batches are device_put with it
    on the prefetch thread.  ``loop``: wrap around on exhaustion forever
    (the GAN trainers' multi-epoch semantics); otherwise one pass.
    ``min_rows``: skip batches with fewer rows BEFORE any device_put —
    a partial epoch tail is not divisible by a mesh's batch sharding, so
    it must be dropped on the host side (the reference's skip-and-wrap
    tail semantics, dl4jGANComputerVision.java:524-526).
    """

    def __init__(self, source, prefetch_depth: int = 2,
                 sharding=None, loop: bool = False,
                 min_rows: Optional[int] = None):
        if prefetch_depth < 1:
            raise ValueError("prefetch_depth must be >= 1")
        self.source = source
        self.sharding = sharding
        self.loop = loop
        self.min_rows = min_rows
        self.prefetch_depth = prefetch_depth
        # first worker exception, kept OUT of band as well as enqueued:
        # close() may drain the queue while the worker is still putting,
        # and a decode error must survive that drain (retrievable via
        # ``error`` / raised by a post-close __next__), never be dropped
        self.error: Optional[BaseException] = None
        # O(1) resumable-state tracking (data/resilient.py contract):
        # the worker runs AHEAD of the consumer, so the source's live
        # cursor describes staged batches, not consumed ones — each
        # enqueued item therefore CARRIES the source state as of right
        # after it was pulled, and __next__ publishes it on delivery.
        # ``state()`` then answers "where is everything the consumer
        # has actually consumed" without touching the racing source.
        self._consumed_state = self._source_state()
        self._q: queue.Queue = queue.Queue(maxsize=prefetch_depth)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._worker,
                                        name="gan4j-prefetch",
                                        daemon=True)
        self._thread.start()

    def _source_state(self):
        """The wrapped source's ``state()`` if it has one (None
        otherwise — state capture is strictly optional)."""
        fn = getattr(self.source, "state", None)
        if fn is None:
            return None
        try:
            return fn()
        except Exception:
            return None  # a broken state feed must not break the stream

    def _convert(self, ds):
        if self.sharding is not None:
            return (jax.device_put(ds.features, self.sharding),
                    jax.device_put(ds.labels, self.sharding))
        return (ds.features, ds.labels)

    def _worker(self):
        try:
            emitted_this_pass = 0
            while not self._stop.is_set():
                if not self.source.has_next():
                    # loop only if the pass produced something — a dataset
                    # with no full batch must end in the sentinel, not spin
                    if self.loop and emitted_this_pass:
                        self.source.reset()
                        emitted_this_pass = 0
                        if self.source.has_next():
                            continue
                    break  # exhausted (or empty/filtered-empty after reset)
                ds = self.source.next()
                if self.min_rows and ds.num_examples() < self.min_rows:
                    continue  # partial tail: skip (wraps via has_next above)
                st = self._source_state()
                item = self._convert(ds)
                if not self._put_stop_aware((item, st)):
                    return
                emitted_this_pass += 1
            self._put_stop_aware(None)  # sentinel: exhausted
        except BaseException as e:  # surface decode errors to the consumer
            if self.error is None:
                self.error = e
            self._put_stop_aware(e)

    def _put_stop_aware(self, item) -> bool:
        """put() that gives up once close() sets the stop flag, so the
        worker can never block forever on a full queue after the consumer
        has stopped reading.  Returns False if stopped before enqueue."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def __iter__(self):
        return self

    def __next__(self):
        try:  # fast path: the worker stayed ahead, no stall to record
            item = self._q.get_nowait()
        except queue.Empty:
            t0 = time.perf_counter()
            # bounded-poll wait, NOT a bare blocking get: a thread
            # parked inside a C-level acquire never reaches a bytecode
            # boundary, so the hang watchdog's async-raised
            # WatchdogTimeout (train/watchdog.py) could not be
            # delivered to a consumer stuck on a dead source.  The
            # re-armed get returns to Python every 0.25s, where a
            # pending async exception fires.
            while True:
                try:
                    item = self._q.get(timeout=0.25)
                    break
                except queue.Empty:
                    continue
            waited = time.perf_counter() - t0
            if waited >= STALL_EVENT_S:
                from gan_deeplearning4j_tpu.telemetry import events

                events.instant("data.prefetch_stall",
                               seconds=round(waited, 6))
        if item is None:
            if self.error is not None:
                # the worker died; its enqueued exception may have been
                # drained by close() — deliver it, don't end cleanly
                err, self.error = self.error, None
                raise err
            raise StopIteration
        if isinstance(item, BaseException):
            if item is self.error:
                self.error = None  # delivered; don't re-raise at close
            raise item
        payload, st = item  # data entries carry (batch, source state)
        if st is not None:
            self._consumed_state = st
        return payload

    # -- O(1) resumable state -------------------------------------------------

    def state(self):
        """Source state as of the batches already DELIVERED to the
        consumer (None when the source doesn't expose ``state()``):
        restoring a fresh source to this state and re-wrapping yields
        exactly the batches the consumer has not seen yet — the value a
        checkpoint records.  O(1): a dict handoff per delivered batch,
        no source access here."""
        return self._consumed_state

    def restore_state(self, state) -> None:
        """Reposition the WHOLE pipeline at ``state``: quiesce the
        worker, discard everything staged (those batches predate the
        restore point), restore the underlying source, and restart a
        fresh worker from there.  Only legal on sources that implement
        ``restore_state``; the dedup chunk tier refuses (its shipped
        distinct-row table is assembled from the first pass, which a
        mid-pass restore would tear)."""
        if getattr(self, "dedup", False):
            raise RuntimeError(
                "restore_state is not supported in dedup chunk mode — "
                "restore the raw source before wrapping instead")
        restore = getattr(self.source, "restore_state", None)
        if restore is None:
            raise AttributeError(
                f"{type(self.source).__name__} does not expose "
                "restore_state")
        self._stop.set()
        try:
            while True:  # unblock a worker parked mid-put; drop staged
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=10.0)
        if self._thread.is_alive():
            raise RuntimeError(
                "prefetch worker did not quiesce for restore_state "
                "(source wedged in next()?)")
        self.error = None  # pre-restore failures died with the worker
        restore(state)
        self._consumed_state = self._source_state()
        self._q = queue.Queue(maxsize=self.prefetch_depth)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._worker,
                                        name="gan4j-prefetch",
                                        daemon=True)
        self._thread.start()

    def close(self, timeout: float = 5.0):
        """Stop the worker and release both sides.  Safe to call while
        the worker is mid-``put`` (the stop flag breaks its bounded put
        loop) or wedged inside ``source.next()`` (the join gives up
        after ``timeout`` rather than deadlocking the caller — the
        daemon worker then dies with the process).  A worker exception
        that was still queued is preserved on ``error``, never dropped
        (tests/test_chaos.py pins both properties)."""
        self._stop.set()
        # drain so the worker's blocked put can finish — preserving, not
        # discarding, any queued worker exception
        try:
            while True:
                item = self._q.get_nowait()
                if isinstance(item, BaseException) and self.error is None:
                    self.error = item
        except queue.Empty:
            pass
        # release any reader blocked in __next__ (the stopped worker will
        # no longer deliver its exhaustion sentinel)
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass
        self._thread.join(timeout=timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class ChunkPrefetchIterator(PrefetchIterator):
    """Prefetcher that assembles ``chunk_batches`` consecutive full batches
    into ONE (K*B, F) array pair and starts a single host->device transfer
    per chunk on the worker thread.

    Why: on a high-latency (tunneled) PJRT link, K small per-batch
    ``device_put`` calls pay K round-trip latencies; one K-batch transfer
    pays one and rides bandwidth for the rest — the host->device analog of
    the multi-step dispatch chunking in train/fused_step.py.  The consumer
    feeds each chunk to the ``data_on_device`` multi-step program, which
    slices batch ``it % K`` on device, so chunk k+1's transfer overlaps
    chunk k's K training steps (JAX transfers are async).  Up to
    ``prefetch_depth + 2`` chunks are device-resident at once: the one
    training, ``prefetch_depth`` queued, and the one the worker is
    staging — size chunks accordingly (the trainer uses depth 1: three
    chunks in flight, which already fully overlaps transfer with
    compute).

    Epoch semantics are the streaming loop's exactly: partial tails are
    skipped, exhaustion wraps (the ``min_rows``/``loop`` machinery of the
    base class), so a chunked run sees the identical batch sequence.

    ``encode_features``: optional host-side transport encoder applied to
    the assembled feature chunk before device_put (e.g. the exact uint8
    fixed-point codec, data/codec.py — 4x fewer bytes on the wire; the
    consuming program dequantizes on device).

    ``dedup``: the adaptive epoch-in-chunk tier.  When one chunk spans
    whole passes of a DETERMINISTIC source (chunk_batches >= batches per
    pass), assembling K batches re-ships every distinct row once per
    occurrence — pure waste on a bandwidth-bound link.  In dedup mode
    the iterator uploads the distinct-row tables ONCE (the first pass's
    batches, verified against every later pass by exact comparison) and
    each chunk yields a 3-tuple ``(features_table, labels_table,
    row_idx[int32 K*B])`` — only the index schedule crosses the link per
    chunk; the consuming program (fused_step ``chunk_indexed``) gathers
    batches on device.  A source that changes batch content or pass
    structure between passes raises (the contract is the reference's
    fixed CSV order, dl4jGANComputerVision.java:524-526).
    """

    def __init__(self, source, chunk_batches: int, batch_size: int,
                 prefetch_depth: int = 2, sharding=None,
                 encode_features=None, dedup: bool = False):
        if chunk_batches < 1:
            raise ValueError("chunk_batches must be >= 1")
        self.chunk_batches = chunk_batches
        self.encode_features = encode_features
        self.dedup = dedup
        super().__init__(source, prefetch_depth=prefetch_depth,
                         sharding=sharding, loop=True, min_rows=batch_size)

    def _worker(self):
        if self.dedup:
            return self._worker_dedup()
        import numpy as np

        try:
            feats, labs = [], []
            appended_this_pass = 0
            while not self._stop.is_set():
                if not self.source.has_next():
                    # wrap only if THIS pass surfaced a full batch — a
                    # pass yielding none (empty, or all-partial after a
                    # mid-run truncation) must end in the sentinel, not
                    # spin reset->skip->reset forever (the base worker's
                    # per-pass guard, same semantics)
                    if not appended_this_pass:
                        break
                    self.source.reset()
                    appended_this_pass = 0
                    if not self.source.has_next():
                        break
                    continue
                ds = self.source.next()
                if self.min_rows and ds.num_examples() < self.min_rows:
                    continue  # partial epoch tail: skip-and-wrap
                feats.append(np.asarray(ds.features))
                labs.append(np.asarray(ds.labels))
                appended_this_pass += 1
                if len(feats) < self.chunk_batches:
                    continue
                st = self._source_state()  # position after the chunk
                f_chunk = np.concatenate(feats)
                if self.encode_features is not None:
                    f_chunk = self.encode_features(f_chunk)
                chunk = (f_chunk, np.concatenate(labs))
                feats, labs = [], []
                if self.sharding is not None:
                    chunk = (jax.device_put(chunk[0], self.sharding),
                             jax.device_put(chunk[1], self.sharding))
                if not self._put_stop_aware((chunk, st)):
                    return
            self._put_stop_aware(None)
        except BaseException as e:  # surface decode errors to the consumer
            if self.error is None:
                self.error = e
            self._put_stop_aware(e)

    def _worker_dedup(self):
        import numpy as np

        try:
            host_feats, host_labs = [], []   # first pass = the tables
            table = None                      # (dev_feats, dev_labels)
            first_pass_done = False
            pos = 0                           # batch position in pass
            idx_parts, appended = [], 0
            while not self._stop.is_set():
                if not self.source.has_next():
                    if not host_feats:
                        break  # empty (or all-partial) dataset
                    first_pass_done = True
                    self.source.reset()
                    pos = 0
                    if not self.source.has_next():
                        break
                    continue
                ds = self.source.next()
                if self.min_rows and ds.num_examples() < self.min_rows:
                    continue  # partial epoch tail: skip-and-wrap
                f = np.asarray(ds.features)
                lab = np.asarray(ds.labels)
                if not first_pass_done and pos == len(host_feats):
                    if table is not None:
                        # the table already shipped but the first pass is
                        # STILL producing new batches: chunk_batches does
                        # not cover a pass — later indices would exceed
                        # the table and jnp.take would silently clip
                        raise RuntimeError(
                            "dedup=True requires chunk_batches >= batches "
                            "per pass (the shipped distinct-row table "
                            f"held {len(host_feats)} batches but the "
                            "first pass keeps going); use plain chunking "
                            "for chunk-smaller-than-epoch streams")
                    host_feats.append(f)
                    host_labs.append(lab)
                elif pos >= len(host_feats) or not (
                        np.array_equal(f, host_feats[pos])
                        and np.array_equal(lab, host_labs[pos])):
                    raise RuntimeError(
                        "dedup chunk streaming requires a deterministic "
                        f"source: batch at pass position {pos} differs "
                        "from (or extends) the first pass; disable dedup "
                        "for shuffling/nondeterministic iterators")
                idx_parts.append(np.arange(
                    pos * f.shape[0], (pos + 1) * f.shape[0],
                    dtype=np.int32))
                pos += 1
                appended += 1
                if appended < self.chunk_batches:
                    continue
                if table is None:
                    # tables cross the link ONCE, here (the chunk covers
                    # >= one full pass, so the first pass is complete)
                    tf = np.concatenate(host_feats)
                    if self.encode_features is not None:
                        tf = self.encode_features(tf)
                    tl = np.concatenate(host_labs)
                    if self.sharding is not None:
                        tf = jax.device_put(tf, self.sharding)
                        tl = jax.device_put(tl, self.sharding)
                    table = (tf, tl)
                st = self._source_state()  # position after the chunk
                chunk_idx = np.concatenate(idx_parts)
                idx_parts, appended = [], 0
                if self.sharding is not None:
                    chunk_idx = jax.device_put(chunk_idx, self.sharding)
                if not self._put_stop_aware(((*table, chunk_idx), st)):
                    return
            self._put_stop_aware(None)
        except BaseException as e:  # surface errors to the consumer
            if self.error is None:
                self.error = e
            self._put_stop_aware(e)
