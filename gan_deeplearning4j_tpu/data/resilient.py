"""Resilient ingestion — retries, quarantine, and health for the data plane.

The reference's DataVec pipeline assumes a local, intact CSV: any
transient I/O error or single malformed row is fatal
(``dl4jGANComputerVision.java:355-379`` never handles either).  The
checkpoint/recovery layers (PRs 2 and 4) made crashes, hangs and
divergence survivable; this module closes the INPUT side — a flaky
disk, an NFS blip or a poisoned shard becomes a bounded, observable
incident instead of a dead or silently-corrupted run:

* **RetryingSource / RetryingReader** — wrap any record source (the
  ``has_next``/``next``/``reset`` protocol) or CSV reader with bounded
  retries and exponential backoff + jitter on TRANSIENT errors
  (``OSError``/``EOFError`` — the I/O class; truncated reads surface as
  both).  Every attempt emits a ``data.retry`` event and feeds the
  ``gan4j_data_retries_total`` series; exhaustion raises
  ``DataSourceError``, which ``train_with_recovery`` classifies as
  RETRYABLE (restart from the last checkpoint, fresh file handles).
* **RecordQuarantine / ValidatingSource** — per-record shape/dtype/
  finite-value validation at ingest.  A bad record is skipped, logged
  to a per-run ``quarantine.jsonl`` with file/line (or stream/row)
  provenance, announced as a ``data.quarantine`` event, and charged
  against a ``--max-quarantine`` budget; exhausting the budget raises
  ``DataQuarantineError``, which the recovery wrapper treats as FATAL
  (a restart would re-read the same poisoned data) — the same
  budget-then-escalate semantics as the rollback budget.
* **DataHealth** — thread-safe counters behind the scrape surface: the
  ``gan4j_data_*`` series and the ``/healthz`` ``"data"`` block
  (telemetry/exporter.py ``observe_data``).

The O(1) resumable-iterator half of the resilient data plane lives on
the iterators themselves (``RecordReaderDataSetIterator.state()`` /
``restore_state()`` in data/csv.py, mirrored by the prefetch wrappers)
— this module only defines the failure vocabulary they share.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Callable, Dict, Optional

import numpy as np

# The transient-error class: real I/O faults (flaky disk, NFS blip,
# torn NFS handle) surface as OSError; a truncated read of a framed
# format surfaces as EOFError.  ValueError is deliberately NOT here —
# a parse failure replays identically, retrying it only burns time
# (that class goes to quarantine instead).
TRANSIENT_ERRORS = (OSError, EOFError)

QUARANTINE_NAME = "quarantine.jsonl"


class DataSourceError(RuntimeError):
    """A data source failed even after bounded retries.  RETRYABLE in
    ``train_with_recovery``: the restart rebuilds the reader stack with
    fresh file handles and resumes from the last checkpoint — exactly
    the medicine for storage-layer flakiness that outlives one read."""


class DataQuarantineError(RuntimeError):
    """The corrupt-record quarantine budget is exhausted.  FATAL in
    ``train_with_recovery``: a restart re-reads the same poisoned
    data and re-exhausts the same budget — the dataset needs a human,
    and ``quarantine.jsonl`` carries the per-record provenance the
    human needs."""


class DataHealth:
    """Thread-safe data-plane counters — the one feed behind the
    ``gan4j_data_*`` scrape series and the ``/healthz`` ``"data"``
    block.  Fed by the retry/quarantine machinery (any thread), read
    at scrape time (``report()``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._retries = 0
        self._quarantined = 0
        self._last_error_wall: Optional[float] = None
        self._last_error: Optional[str] = None
        self._exhausted = False

    def record_retry(self, error: BaseException) -> None:
        with self._lock:
            self._retries += 1
            self._last_error_wall = time.time()
            self._last_error = repr(error)

    def record_quarantine(self, n: int = 1, reason: str = "") -> None:
        with self._lock:
            self._quarantined += n
            self._last_error_wall = time.time()
            if reason:
                self._last_error = reason

    def mark_exhausted(self) -> None:
        with self._lock:
            self._exhausted = True

    @property
    def retries_total(self) -> int:
        with self._lock:
            return self._retries

    @property
    def quarantined_total(self) -> int:
        with self._lock:
            return self._quarantined

    def report(self) -> Dict:
        """Scrape-time snapshot (telemetry/exporter.py observe_data)."""
        with self._lock:
            age = (None if self._last_error_wall is None
                   else round(time.time() - self._last_error_wall, 3))
            return {"retries_total": self._retries,
                    "quarantined_total": self._quarantined,
                    "last_error_age_s": age,
                    "last_error": self._last_error,
                    "ok": not self._exhausted}


class RecordQuarantine:
    """Budgeted corrupt-record sink: every charged record lands as one
    JSON line in ``path`` (file/line or stream/row provenance, reason,
    a truncated raw excerpt) and as a ``data.quarantine`` event; the
    charge that EXCEEDS ``budget`` raises ``DataQuarantineError`` —
    tolerate-and-log up to the budget, then refuse to train on a
    dataset this damaged (the rollback-budget semantics, applied to
    input corruption)."""

    def __init__(self, path: str, budget: int,
                 health: Optional[DataHealth] = None):
        if budget < 0:
            raise ValueError(f"quarantine budget must be >= 0, got {budget}")
        self.path = path
        self.budget = budget
        self.health = health
        self._lock = threading.Lock()
        self._count = 0
        # charges are idempotent per provenance key: a RetryingReader
        # re-reading a file after a transient I/O error re-encounters
        # the SAME corrupt records, and re-charging them would burn the
        # budget (and double-count the scrape series) on no new damage
        self._seen = set()

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def charge(self, file: str, line: Optional[int] = None,
               row: Optional[int] = None, reason: str = "",
               raw: str = "") -> None:
        """Quarantine ONE bad record.  Appends the provenance line,
        emits the event, feeds the health counters — and raises once
        the budget is exceeded.  Idempotent per (file, line, row): a
        retried read re-charging the same record is a no-op, so the
        budget counts DISTINCT corrupt records, not read attempts.
        The jsonl write is best-effort (a full disk must not turn a
        tolerated bad row into a crash); the budget accounting is
        not."""
        key = (file, line, row)
        with self._lock:
            if line is not None or row is not None:  # positional key
                if key in self._seen:
                    return  # same record, seen on an earlier read
                self._seen.add(key)
            self._count += 1
            n = self._count
        entry = {"wall": round(time.time(), 3), "file": file,
                 "line": line, "row": row, "reason": reason,
                 "raw": raw[:200], "n": n, "budget": self.budget}
        try:
            with open(self.path, "a") as f:
                f.write(json.dumps(entry) + "\n")
        except OSError:  # gan4j-lint: disable=swallowed-exception — provenance is diagnostics; the charge (quarantine budget) is the product
            pass
        from gan_deeplearning4j_tpu.telemetry import events

        events.instant("data.quarantine", file=file, line=line, row=row,
                       reason=reason, n=n, budget=self.budget)
        if self.health is not None:
            self.health.record_quarantine(
                reason=f"quarantined {file}:{line or row}: {reason}")
        if n > self.budget:
            if self.health is not None:
                self.health.mark_exhausted()
            raise DataQuarantineError(
                f"quarantine budget exhausted ({n - 1}/{self.budget} "
                f"records already quarantined) at {file}"
                + (f":{line}" if line is not None else "")
                + (f" row {row}" if row is not None else "")
                + f": {reason} — see {self.path}")


def read_quarantine(path: str) -> list:
    """Decode a ``quarantine.jsonl`` back into dicts (tests, tools)."""
    out = []
    with open(path) as f:
        for ln in f:
            ln = ln.strip()
            if ln:
                out.append(json.loads(ln))
    return out


def call_with_retries(fn: Callable, what: str, retries: int = 3,
                      backoff_s: float = 0.1, max_backoff_s: float = 5.0,
                      health: Optional[DataHealth] = None,
                      rng: Optional[random.Random] = None,
                      sleep: Callable[[float], None] = time.sleep):
    """Run ``fn()`` with bounded retries on ``TRANSIENT_ERRORS``:
    exponential backoff (``backoff_s * 2^attempt``, capped) with
    jitter x[0.5, 1.5) — a fleet recovering from a shared storage blip
    must not hammer it back down in lockstep (the train_with_recovery
    backoff discipline, applied per read).  Each failed attempt emits
    ``data.retry`` and feeds ``health``; exhaustion raises
    ``DataSourceError`` chained on the last transient error."""
    from gan_deeplearning4j_tpu.telemetry import events

    rng = rng or random
    attempt = 0
    while True:
        try:
            return fn()
        except TRANSIENT_ERRORS as e:
            attempt += 1
            if health is not None:
                health.record_retry(e)
            events.instant("data.retry", what=what, attempt=attempt,
                           retries=retries, error=repr(e))
            if attempt > retries:
                raise DataSourceError(
                    f"{what} still failing after {retries} retries: "
                    f"{e!r}") from e
            delay = min(max_backoff_s, backoff_s * (2 ** (attempt - 1)))
            if delay > 0:
                sleep(delay * (0.5 + rng.random()))


class RetryingReader:
    """CSV-reader wrapper: ``read()`` goes through ``call_with_retries``
    (a transiently unreadable file is re-opened fresh each attempt).
    Everything else delegates to the wrapped reader."""

    def __init__(self, reader, retries: int = 3, backoff_s: float = 0.1,
                 max_backoff_s: float = 5.0,
                 health: Optional[DataHealth] = None,
                 seed: int = 0,
                 sleep: Callable[[float], None] = time.sleep):
        self.reader = reader
        self.retries = retries
        self.backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self.health = health
        self._rng = random.Random(seed)
        self._sleep = sleep

    def read(self, path, *a, **kw):
        return call_with_retries(
            lambda: self.reader.read(path, *a, **kw),
            what=f"read {path}", retries=self.retries,
            backoff_s=self.backoff_s, max_backoff_s=self.max_backoff_s,
            health=self.health, rng=self._rng, sleep=self._sleep)

    def __getattr__(self, name):
        return getattr(self.reader, name)


class RetryingSource:
    """DataSet-iterator wrapper: ``has_next``/``next``/``reset`` retry
    transient errors with the shared backoff discipline; everything
    else (``state``/``restore_state``/``features``/...) delegates, so
    the wrapper is transparent to the residency checks, the prefetch
    state capture and the dedup verification."""

    def __init__(self, source, retries: int = 3, backoff_s: float = 0.1,
                 max_backoff_s: float = 5.0,
                 health: Optional[DataHealth] = None,
                 seed: int = 0,
                 sleep: Callable[[float], None] = time.sleep):
        self.source = source
        self.retries = retries
        self.backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self.health = health
        self._rng = random.Random(seed)
        self._sleep = sleep

    def _retry(self, fn, what):
        return call_with_retries(
            fn, what=what, retries=self.retries,
            backoff_s=self.backoff_s, max_backoff_s=self.max_backoff_s,
            health=self.health, rng=self._rng, sleep=self._sleep)

    def has_next(self):
        return self._retry(self.source.has_next, "source.has_next")

    def next(self):
        return self._retry(self.source.next, "source.next")

    def reset(self):
        return self._retry(self.source.reset, "source.reset")

    def __getattr__(self, name):
        return getattr(self.source, name)


class ValidatingSource:
    """DataSet-iterator wrapper enforcing the per-record contract at
    ingest: features 2-D of the expected width, every value finite
    (labels included).  A bad ROW is removed from the batch and charged
    to the quarantine individually (stream/row provenance); a
    structurally broken batch (wrong rank/width — rows can't even be
    addressed) is charged once and replaced by an EMPTY batch.  Either
    way the emitted batch may be undersized: the prefetch layer's
    ``min_rows`` skip-and-wrap machinery (data/prefetch.py) already
    handles that — the same path a partial epoch tail takes — so no
    consumer needs new cases, and an all-bad pass ends in the
    exhaustion sentinel instead of spinning."""

    def __init__(self, source, quarantine: RecordQuarantine,
                 num_features: Optional[int] = None,
                 name: str = "<stream>"):
        self.source = source
        self.quarantine = quarantine
        self.num_features = num_features
        self.name = name
        self._rows_seen = 0

    def has_next(self):
        return self.source.has_next()

    def reset(self):
        self._rows_seen = 0
        return self.source.reset()

    def next(self):
        from gan_deeplearning4j_tpu.data.csv import DataSet

        ds = self.source.next()
        feats = np.asarray(ds.features)
        labels = np.asarray(ds.labels)
        row0 = self._rows_seen
        self._rows_seen += 0 if feats.ndim != 2 else feats.shape[0]
        if feats.ndim != 2 or (self.num_features is not None
                               and feats.shape[1] != self.num_features):
            want = (self.num_features if self.num_features is not None
                    else "2-D")
            self.quarantine.charge(
                self.name, row=row0,
                reason=f"batch shape {feats.shape} does not match the "
                       f"expected ({want}-wide) record contract")
            width = self.num_features or 0
            return DataSet(np.zeros((0, width), dtype=np.float32),
                           np.zeros((0,) + labels.shape[1:],
                                    dtype=labels.dtype if labels.size
                                    else np.float32))
        bad = ~np.isfinite(feats).all(axis=1)
        if labels.ndim == 2 and labels.shape[0] == feats.shape[0] \
                and labels.size:
            bad |= ~np.isfinite(labels).all(axis=1)
        if not bad.any():
            return ds
        for i in np.nonzero(bad)[0]:
            self.quarantine.charge(
                self.name, row=row0 + int(i),
                reason="non-finite value in record")
        keep = ~bad
        return DataSet(np.ascontiguousarray(feats[keep]),
                       np.ascontiguousarray(labels[keep]))

    def __getattr__(self, name):
        return getattr(self.source, name)
