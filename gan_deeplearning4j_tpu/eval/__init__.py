"""Evaluation — the notebook's scoring cells as code (SURVEY.md §3.5).

The reference's evaluation lives in ``Python/gan.ipynb``: cell 7 recomputes
MNIST classification accuracy from the Java-dumped prediction CSVs (raw
lines 925-955) and cell 10 computes the insurance weighted AUROC plus the
latent-grid lattice renderings (raw lines 1483-1516).
"""

from gan_deeplearning4j_tpu.eval.evaluation import Evaluation
from gan_deeplearning4j_tpu.eval.fid import (
    compute_fid,
    fid_from_features,
    frechet_distance,
    generator_fid,
)
from gan_deeplearning4j_tpu.eval.metrics import (
    accuracy_from_predictions,
    auroc_from_predictions,
    grid_to_lattices,
    mnist_accuracy,
    insurance_auroc,
)

__all__ = [
    "Evaluation",
    "accuracy_from_predictions",
    "auroc_from_predictions",
    "compute_fid",
    "fid_from_features",
    "frechet_distance",
    "generator_fid",
    "grid_to_lattices",
    "mnist_accuracy",
    "insurance_auroc",
]
