"""Conditional fidelity — does a conditional generator OBEY its label?

VERDICT r3 weak-#3 asked for a falsifiable conditioning metric for the
cGAN family: a probe classifier is trained on the REAL labeled table,
then the generator synthesizes n samples per class and the metric is the
agreement rate between the probe's prediction and the conditioned label
(the class-prediction analog of the frozen-extractor FID protocol in
eval/fid_extractor.py).  A class-collapsed generator scores ~1/K no
matter how sharp its two surviving glyphs look; a faithful conditional
generator scores near the probe's own training accuracy.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from gan_deeplearning4j_tpu.graph import (
    Conv2D,
    Dense,
    GraphBuilder,
    InputSpec,
    Output,
)
from gan_deeplearning4j_tpu.optim.adam import Adam
from gan_deeplearning4j_tpu.runtime import prng


def build_probe(channels: int, height: int, width: int, num_classes: int,
                seed: int = prng.NUMBER_OF_THE_BEAST):
    """Small conv classifier: enough capacity to separate the surrogate's
    classes, cheap enough to train inside an evaluation."""
    lr = Adam(1e-3, 0.9, 0.999)
    b = GraphBuilder(seed=seed, activation="relu", weight_init="xavier")
    b.add_inputs("in")
    b.set_input_types(InputSpec.convolutional(channels, height, width))
    b.add_layer("p_conv1", Conv2D(kernel=(3, 3), stride=(2, 2),
                                  padding=(1, 1), n_in=channels, n_out=32,
                                  updater=lr), "in")
    b.add_layer("p_conv2", Conv2D(kernel=(3, 3), stride=(2, 2),
                                  padding=(1, 1), n_in=32, n_out=64,
                                  updater=lr), "p_conv1")
    b.add_layer("p_dense", Dense(n_out=128, updater=lr), "p_conv2")
    b.add_layer("p_out", Output(n_out=num_classes, n_in=128, loss="mcxent",
                                activation="softmax", updater=lr), "p_dense")
    b.set_outputs("p_out")
    return b.build().init()


def conditional_fidelity(
    gen,
    x: np.ndarray,
    y_onehot: np.ndarray,
    *,
    sample_shape,
    z_size: int,
    n_per_class: int = 64,
    probe_steps: int = 400,
    probe_batch: int = 128,
    seed: int = prng.NUMBER_OF_THE_BEAST,
    use_ema: bool = False,
    probe=None,
) -> Dict[str, object]:
    """Train the probe on (x, y), then score label agreement of the
    generator's conditioned samples.

    ``x``: real features, flat [n, C*H*W] (tanh range — whatever the
    generator emits); ``y_onehot``: [n, K].  ``use_ema``: evaluate the
    EMA weights (gen.ema_params) instead of the live ones.  ``probe``:
    a previously-returned trained probe — the probe depends only on
    (x, y, seed), so scoring several parameter sets (live + EMA) should
    train it once and pass it back in.
    Returns {fidelity, per_class, probe_train_acc, n_per_class, probe}.
    """
    c, h, w = sample_shape
    k = y_onehot.shape[1]
    x4 = np.asarray(x, np.float32).reshape(-1, c, h, w)
    y = np.asarray(y_onehot, np.float32)

    if probe is None:
        probe = build_probe(c, h, w, k, seed=seed)
        rng = np.random.RandomState(seed)
        for _ in range(probe_steps):
            idx = rng.randint(0, x4.shape[0], probe_batch)
            probe.fit(jnp.asarray(x4[idx]), jnp.asarray(y[idx]))

    # probe sanity: training-set accuracy (evaluated on a capped slice)
    n_eval = min(2000, x4.shape[0])
    pred_real = np.argmax(
        np.asarray(probe.output(jnp.asarray(x4[:n_eval]))[0]), axis=1)
    probe_acc = float(np.mean(pred_real == np.argmax(y[:n_eval], axis=1)))

    params = None
    if use_ema:
        params = getattr(gen, "ema_params", None)
        if params is None:
            raise ValueError("use_ema=True but the generator carries no "
                             "ema_params")
    z_key = prng.stream(prng.root_key(seed), "fidelity-z")
    labels = np.repeat(np.arange(k), n_per_class)
    cond = jnp.asarray(np.eye(k, dtype=np.float32)[labels])
    z = jax.random.uniform(z_key, (labels.size, z_size),
                           minval=-1.0, maxval=1.0)
    # the public jitted inference path (one dispatch), parameterized so
    # EMA weights evaluate without mutating the graph
    samples = gen.output(z, cond, params=params)[0].reshape(-1, c, h, w)
    pred = np.argmax(np.asarray(probe.output(samples)[0]), axis=1)
    agree = pred == labels
    per_class = [float(np.mean(agree[labels == i])) for i in range(k)]
    return {
        "fidelity": float(np.mean(agree)),
        "per_class": per_class,
        "probe_train_acc": probe_acc,
        "n_per_class": n_per_class,
        "probe": probe,
    }


def conditional_class_metrics(
    gen,
    x: np.ndarray,
    y_onehot: np.ndarray,
    *,
    sample_shape,
    z_size: int,
    frozen=None,
    n_per_class: int = 400,
    real_cap: int = 1000,
    seed: int = prng.NUMBER_OF_THE_BEAST,
    use_ema: bool = False,
    batch_size: int = 250,
    real_features=None,
) -> Dict[str, object]:
    """Per-class FROZEN-SPACE FID and intra-class diversity — the
    non-saturating companions to ``conditional_fidelity`` (VERDICT r4
    #4: agreement-rate fidelity hits the probe's ceiling and stops
    moving; distribution distances keep discriminating above it).

    ``frozen``: a frozen feature extractor graph (default: the committed
    CIFAR-32 asset, eval/fid_extractor.py).  For each class c, FID is
    computed between the real rows labeled c and ``n_per_class``
    conditioned samples, in the frozen 256-d feature space; intra-class
    diversity is the generated class's mean per-feature std over the
    real class's (ratio ~1 healthy, -> 0 under within-class collapse —
    detectable even at fidelity == ceiling).

    ``real_features``: the previous call's ``_real_features`` return —
    the real side depends only on (x, y, frozen), so scoring several
    parameter sets (live + EMA) should extract it once and pass it back.
    Returns {per_class_fid, mean_class_fid, diversity_ratio,
    mean_diversity_ratio, _real_features}.
    """
    from gan_deeplearning4j_tpu.eval import fid as fid_lib
    from gan_deeplearning4j_tpu.eval import fid_extractor as fx

    if frozen is None:
        frozen = fx.load_extractor_cifar()
    c, h, w = sample_shape
    k = y_onehot.shape[1]
    y = np.argmax(np.asarray(y_onehot), axis=1)
    x = np.asarray(x, np.float32)

    params = None
    if use_ema:
        params = getattr(gen, "ema_params", None)
        if params is None:
            raise ValueError("use_ema=True but the generator carries no "
                             "ema_params")
    z_key = prng.stream(prng.root_key(seed), "class-metrics-z")
    labels = np.repeat(np.arange(k), n_per_class)
    cond = jnp.asarray(np.eye(k, dtype=np.float32)[labels])
    z = jax.random.uniform(z_key, (labels.size, z_size),
                           minval=-1.0, maxval=1.0)
    gen_rows = np.empty((labels.size, c * h * w), np.float32)
    for i in range(0, labels.size, batch_size):
        j = min(i + batch_size, labels.size)
        out = gen.output(z[i:j], cond[i:j], params=params)[0]
        gen_rows[i:j] = np.asarray(out).reshape(j - i, -1)

    f_gen = fid_lib.extract_features(frozen, gen_rows, fx.FEATURE_LAYER,
                                     batch_size=batch_size)
    if real_features is None:
        real_features = [
            fid_lib.extract_features(frozen, x[y == cls][:real_cap],
                                     fx.FEATURE_LAYER,
                                     batch_size=batch_size)
            for cls in range(k)]
    per_fid, div_ratio = [], []
    for cls in range(k):
        f_real = real_features[cls]
        f_g = f_gen[labels == cls]
        per_fid.append(float(fid_lib.fid_from_features(f_real, f_g)))
        div_ratio.append(float(f_g.std(axis=0).mean()
                               / max(f_real.std(axis=0).mean(), 1e-9)))
    return {
        "per_class_fid": per_fid,
        "mean_class_fid": float(np.mean(per_fid)),
        "diversity_ratio": div_ratio,
        "mean_diversity_ratio": float(np.mean(div_ratio)),
        "_real_features": real_features,
    }
