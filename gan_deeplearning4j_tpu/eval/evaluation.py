"""Batch-accumulating classifier evaluation — DL4J's ``Evaluation`` class.

The DL4J stack the reference builds on ships
``org.deeplearning4j.eval.Evaluation`` (via deeplearning4j-nn,
Java/pom.xml:100-103): feed ``eval(labels, predictions)`` batch by batch,
then read accuracy / per-class precision / recall / F1 and a printable
stats block off the accumulated confusion matrix.  The reference's own
notebook computes plain accuracy (gan.ipynb cell 7); this object is the
framework-level equivalent a DL4J user expects for everything beyond it.

Macro averages follow DL4J's ``EvaluationAveraging.Macro``: classes whose
denominator is zero (the metric is undefined there — e.g. zero predicted
positives for precision) are EXCLUDED from the average, not counted as 0.
F1 averages over classes with any tp/fp/fn at all (2tp+fp+fn > 0).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class Evaluation:
    def __init__(self, num_classes: int):
        self.num_classes = int(num_classes)
        self._confusion = np.zeros((num_classes, num_classes), dtype=np.int64)

    # -- accumulation --------------------------------------------------------

    def eval(self, labels, predictions) -> None:
        """Accumulate one batch.  ``labels``: [N] class ids or [N, C]
        one-hot/probabilities; ``predictions``: [N, C] scores (argmax is
        taken, like DL4J) or [N] class ids."""
        y = np.asarray(labels)
        p = np.asarray(predictions)
        # [N,1] columns are NOT one-hot: a label column holds class ids;
        # a single-column prediction is a binary sigmoid score (DL4J
        # thresholds it at 0.5).  argmax over one column would silently
        # map everything to class 0.
        if y.ndim == 2 and y.shape[1] == 1:
            y = y.ravel()
        if p.ndim == 2 and p.shape[1] == 1:
            if self.num_classes != 2:
                raise ValueError(
                    "single-column predictions are binary sigmoid scores; "
                    f"this Evaluation has num_classes={self.num_classes}")
            p = (p.ravel() >= 0.5).astype(np.int64)
        if y.ndim == 2:
            y = y.argmax(axis=1)
        if p.ndim == 2:
            p = p.argmax(axis=1)
        y = y.astype(np.int64).ravel()
        p = p.astype(np.int64).ravel()
        if y.shape != p.shape:
            raise ValueError(f"labels {y.shape} vs predictions {p.shape}")
        np.add.at(self._confusion, (y, p), 1)

    # -- scalar metrics ------------------------------------------------------

    def confusion_matrix(self) -> np.ndarray:
        """[true, predicted] counts."""
        return self._confusion.copy()

    def num_examples(self) -> int:
        return int(self._confusion.sum())

    def accuracy(self) -> float:
        n = self._confusion.sum()
        return float(np.trace(self._confusion) / n) if n else 0.0

    def _per_class(self, numer: np.ndarray, denom: np.ndarray) -> np.ndarray:
        out = np.zeros(self.num_classes)
        nz = denom > 0
        out[nz] = numer[nz] / denom[nz]
        return out

    def precision(self, cls: Optional[int] = None) -> float:
        tp = np.diag(self._confusion).astype(float)
        pred_pos = self._confusion.sum(axis=0).astype(float)
        per = self._per_class(tp, pred_pos)
        if cls is not None:
            return float(per[cls])
        return self._macro(per, defined=pred_pos > 0)

    def recall(self, cls: Optional[int] = None) -> float:
        tp = np.diag(self._confusion).astype(float)
        actual_pos = self._confusion.sum(axis=1).astype(float)
        per = self._per_class(tp, actual_pos)
        if cls is not None:
            return float(per[cls])
        return self._macro(per, defined=actual_pos > 0)

    def f1(self, cls: Optional[int] = None) -> float:
        if cls is not None:
            p, r = self.precision(cls), self.recall(cls)
            return 2 * p * r / (p + r) if (p + r) else 0.0
        per = np.array([self.f1(c) for c in range(self.num_classes)])
        return self._macro(per)

    def _macro(self, per_class: np.ndarray,
               defined: Optional[np.ndarray] = None) -> float:
        """DL4J Macro averaging: mean over classes where the metric is
        DEFINED (nonzero denominator), skipping the rest entirely.  The
        default mask (classes appearing in labels or predictions at all)
        is F1's definedness condition, 2tp+fp+fn > 0."""
        if defined is None:
            defined = (self._confusion.sum(axis=0)
                       + self._confusion.sum(axis=1)) > 0
        return float(per_class[defined].mean()) if defined.any() else 0.0

    # -- report --------------------------------------------------------------

    def stats(self) -> str:
        """DL4J-style printable block: headline metrics + the confusion
        matrix (predicted columns, actual rows)."""
        lines = [
            f"Examples: {self.num_examples()}  Classes: {self.num_classes}",
            f"Accuracy:  {self.accuracy():.4f}",
            f"Precision: {self.precision():.4f}",
            f"Recall:    {self.recall():.4f}",
            f"F1 Score:  {self.f1():.4f}",
            "Confusion matrix (rows = actual, cols = predicted):",
        ]
        width = max(5, len(str(self._confusion.max())) + 1)
        header = " " * 6 + "".join(f"{c:>{width}}" for c in range(self.num_classes))
        lines.append(header)
        for r in range(self.num_classes):
            row = "".join(f"{v:>{width}}" for v in self._confusion[r])
            lines.append(f"{r:>5} {row}")
        return "\n".join(lines)
