"""Fréchet distance between real and generated feature distributions.

The BASELINE.json north-star metric names "generator FID at 10k steps".
The standard FID recipe embeds both sets in an InceptionV3 pool3 space —
unavailable offline — so this uses the accepted classifier-feature
fallback: features from the penultimate layer of the trained transfer
classifier (the reference's own evaluation network,
dl4jGANComputerVision.java:322-351), Gaussian moments per set, Fréchet
distance between the Gaussians:

    FID = ||mu_r - mu_g||^2 + Tr(C_r + C_g - 2 (C_r C_g)^(1/2))

The feature layer defaults to ``dis_dense_layer_6`` — the 1024-wide dense
the classifier transfers from the discriminator (the same features the
97.07% accuracy claim rests on, gan.ipynb raw line 373).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

DEFAULT_FEATURE_LAYER = "dis_dense_layer_6"


def _feature_fn(graph, layer: str):
    """Per-(graph, layer) jitted forward, cached on the graph so repeated
    extractions (real set then generated set) compile once."""
    cache = graph.__dict__.setdefault("_fid_feature_jits", {})
    if layer not in cache:
        @jax.jit
        def feats(params, xb):
            values, _ = graph._forward(
                params, {graph.input_names[0]: xb}, False, None)
            return values[layer]

        cache[layer] = feats
    return cache[layer]


def extract_features(graph, x: np.ndarray, layer: str = DEFAULT_FEATURE_LAYER,
                     batch_size: int = 500) -> np.ndarray:
    """Inference-mode activations of ``layer`` over ``x``, batched so the
    whole set never has to be device-resident at once."""
    import jax.numpy as jnp

    feats = _feature_fn(graph, layer)
    pending = []
    n = x.shape[0]
    # fixed batch so one compile serves every slice; remainder pads + trims
    for i in range(0, n, batch_size):
        xb = np.asarray(x[i:i + batch_size], dtype=np.float32)
        k = xb.shape[0]
        if k < batch_size:
            xb = np.concatenate(
                [xb, np.zeros((batch_size - k, *xb.shape[1:]), np.float32)])
        pending.append((feats(graph.params, jnp.asarray(xb)), k))
    # all batches dispatched; one overlapped readback
    from gan_deeplearning4j_tpu.utils import overlap_device_get

    pending = overlap_device_get(pending)
    return np.concatenate([np.asarray(o)[:k] for o, k in pending])


def frechet_distance(mu1: np.ndarray, cov1: np.ndarray,
                     mu2: np.ndarray, cov2: np.ndarray,
                     eps: float = 1e-6) -> float:
    """Fréchet distance between N(mu1, cov1) and N(mu2, cov2).

    Tr((C1 C2)^1/2) is computed symmetrically as
    Tr((C1^1/2 C2 C1^1/2)^1/2) via two Hermitian eigendecompositions —
    numerically stable for PSD covariances and free of scipy.sqrtm's
    non-symmetric iteration (and its deprecation churn)."""
    diff = mu1 - mu2
    # C1^1/2 by eigendecomposition (clip tiny negative eigenvalues)
    w1, v1 = np.linalg.eigh(cov1 + np.eye(cov1.shape[0]) * eps)
    sqrt_c1 = (v1 * np.sqrt(np.clip(w1, 0.0, None))) @ v1.T
    inner = sqrt_c1 @ (cov2 + np.eye(cov2.shape[0]) * eps) @ sqrt_c1
    # inner is PSD up to round-off; symmetrize before eigh
    w2 = np.linalg.eigvalsh((inner + inner.T) / 2.0)
    tr_sqrt = np.sqrt(np.clip(w2, 0.0, None)).sum()
    return float(diff @ diff + np.trace(cov1) + np.trace(cov2)
                 - 2.0 * tr_sqrt)


def fid_from_features(feat_real: np.ndarray, feat_gen: np.ndarray) -> float:
    mu_r = feat_real.mean(axis=0)
    mu_g = feat_gen.mean(axis=0)
    cov_r = np.cov(feat_real, rowvar=False)
    cov_g = np.cov(feat_gen, rowvar=False)
    return frechet_distance(mu_r, cov_r, mu_g, cov_g)


def compute_fid(classifier, real: np.ndarray, generated: np.ndarray,
                layer: str = DEFAULT_FEATURE_LAYER,
                batch_size: int = 500) -> float:
    """FID of ``generated`` against ``real`` in the classifier's feature
    space.  Both arrays are [N, num_features] in the data domain ([0,1]
    pixels for MNIST)."""
    f_r = extract_features(classifier, real, layer, batch_size)
    f_g = extract_features(classifier, generated, layer, batch_size)
    return fid_from_features(f_r, f_g)


def synthesize_pixels(gen, n_samples: int, num_features: int,
                      z_size: int = 2, seed: int = 666,
                      batch_size: int = 500,
                      rng: Optional[np.random.RandomState] = None
                      ) -> np.ndarray:
    """``n_samples`` generator outputs from z ~ U[-1,1]^z (the training
    latent law, dl4jGANComputerVision.java:397), flattened to
    [n, num_features] — synthesized once, scoreable in several feature
    spaces."""
    import jax.numpy as jnp

    rng = rng or np.random.RandomState(seed)
    pending = []
    for i in range(0, n_samples, batch_size):
        k = min(batch_size, n_samples - i)
        z = rng.rand(batch_size, z_size).astype(np.float32) * 2.0 - 1.0
        pending.append((gen.output(jnp.asarray(z))[0], k))
    # all synthesis batches dispatched; one overlapped readback
    from gan_deeplearning4j_tpu.utils import overlap_device_get

    pending = overlap_device_get(pending)
    return np.concatenate(
        [np.asarray(o).reshape(batch_size, num_features)[:k]
         for o, k in pending])


def generator_fid(gen, classifier, real: np.ndarray, n_samples: int,
                  z_size: int = 2, seed: int = 666,
                  layer: str = DEFAULT_FEATURE_LAYER,
                  batch_size: int = 500,
                  rng: Optional[np.random.RandomState] = None) -> float:
    """End-to-end generator FID: synthesize then score against ``real``."""
    num_features = int(np.prod(real.shape[1:]))
    generated = synthesize_pixels(gen, n_samples, num_features, z_size,
                                  seed, batch_size, rng)
    return compute_fid(classifier, real.reshape(-1, num_features), generated,
                       layer, batch_size)
