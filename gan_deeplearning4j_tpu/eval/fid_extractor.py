"""Frozen deterministic FID feature extractor (VERDICT r2 next-step #3).

Rounds 1-2 computed FID in the feature space of each run's OWN trained
transfer classifier, so the metric's embedding moved with every run —
round-over-round FID was noise (honest range 117.9-218.7 across float
rounding paths, RESULTS r2 §1).  The standard recipe freezes the embedding
(InceptionV3 pool3 — unavailable offline), so this module is the offline
equivalent: a small CNN classifier trained ONCE on the calibrated MNIST
surrogate under a fully pinned recipe (seed 666, fixed data budget, fixed
step count) and committed as an asset zip.  Every FID after that loads
the SAME weights — the embedding never moves again, making FID comparable
across runs, rounds, and code changes.

Regenerate (only if the recipe version bumps):
    python -m gan_deeplearning4j_tpu.eval.fid_extractor
which retrains deterministically and overwrites the asset; the recipe
version is embedded in the filename so a stale asset cannot be loaded
silently.  (Verified: a from-scratch retrain reproduces the committed
v1 asset bit-for-bit on the CPU backend, 2026-07-31.)

The feature layer is the 256-wide penultimate dense ("feat"), the
classifier-feature FID convention (same role as the reference evaluation
network's dis_dense_layer_6 features, dl4jGANComputerVision.java:322-351).
"""

from __future__ import annotations

import os

import numpy as np

RECIPE_VERSION = 1
FEATURE_LAYER = "feat"
_ASSET_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "assets")
ASSET_PATH = os.path.join(_ASSET_DIR,
                          f"fid_extractor_v{RECIPE_VERSION}.zip")

# pinned training recipe — changing ANY of these requires a version bump
_SEED = 666
_N_TRAIN = 20000
_BATCH = 200
_STEPS = 1500
_LR = 1e-3


def build_extractor():
    """The fixed architecture: 2 strided convs -> 256-d dense ("feat")
    -> 10-way softmax.  ~0.4M params, small enough to commit."""
    from gan_deeplearning4j_tpu.graph import (
        Conv2D,
        Dense,
        GraphBuilder,
        InputSpec,
        Output,
    )
    from gan_deeplearning4j_tpu.optim.rmsprop import RmsProp

    lr = RmsProp(_LR, 1e-8, 1e-8)
    b = GraphBuilder(seed=_SEED, l2=1e-4, activation="relu",
                     weight_init="xavier", clip_threshold=1.0)
    b.add_inputs("in")
    b.set_input_types(InputSpec.convolutional_flat(28, 28, 1))
    b.add_layer("conv1", Conv2D(kernel=(5, 5), stride=(2, 2), n_in=1,
                                n_out=16, updater=lr), "in")
    b.add_layer("conv2", Conv2D(kernel=(5, 5), stride=(2, 2), n_in=16,
                                n_out=32, updater=lr), "conv1")
    b.add_layer(FEATURE_LAYER, Dense(n_out=256, updater=lr), "conv2")
    b.add_layer("out", Output(n_out=10, loss="xent", activation="softmax",
                              updater=lr), FEATURE_LAYER)
    b.set_outputs("out")
    return b.build().init()


def train_extractor(log=print):
    """The pinned recipe: calibrated-surrogate train split, seed-666
    batches, ``_STEPS`` steps.  Deterministic end to end — rerunning
    reproduces the committed weights bit-for-bit on the same backend."""
    from gan_deeplearning4j_tpu.data import datasets

    x, y = datasets.synthetic_mnist(_N_TRAIN, seed=_SEED)
    onehot = np.eye(10, dtype=np.float32)[y]
    graph = build_extractor()
    order = np.random.RandomState(_SEED)
    for step in range(_STEPS):
        idx = order.randint(0, _N_TRAIN, _BATCH)
        loss = graph.fit(x[idx], onehot[idx])
        if log and (step + 1) % 300 == 0:
            log(f"[fid-extractor] step {step + 1}/{_STEPS} "
                f"loss {float(loss):.4f}")
    return graph


def save_asset(graph, path: str = ASSET_PATH) -> str:
    from gan_deeplearning4j_tpu.graph import serialization

    os.makedirs(os.path.dirname(path), exist_ok=True)
    serialization.write_model(graph, path, save_updater=False)
    return path


# --------------------------------------------------------------------------
# CelebA-64 frozen extractor (VERDICT r4 next-step #1): same recipe
# discipline at the one shape with TPU-scale convs.  Real CelebA is an
# attribute-labeled dataset (40 binary attributes), so the domain-matched
# frozen embedding is an attribute-prediction CNN trained ONCE on the
# procedural surrogate's 8 controllable attributes
# (data/datasets.py CELEBA_ATTR_NAMES) under a fully pinned recipe and
# committed as an asset zip.  Features = the 256-wide penultimate dense
# ("feat"), same convention as the MNIST extractor above.

CELEBA_RECIPE_VERSION = 1
CELEBA_ASSET_PATH = os.path.join(
    _ASSET_DIR, f"fid_extractor_celeba_v{CELEBA_RECIPE_VERSION}.zip")

# pinned CelebA-extractor recipe — changing ANY of these bumps the version
_CELEBA_SEED = 666
_CELEBA_N_TRAIN = 8000
_CELEBA_BATCH = 100
_CELEBA_STEPS = 600
_CELEBA_LR = 1e-3


def build_extractor_celeba():
    """Fixed 64x64 architecture: 4 stride-2 convs (3->16->32->64->128,
    4x4 pad 1 — the DCGAN-D shape family) -> 256-d dense ("feat") ->
    8 sigmoid attribute heads.  ~0.8M params."""
    from gan_deeplearning4j_tpu.graph import (
        Conv2D,
        Dense,
        GraphBuilder,
        InputSpec,
        Output,
    )
    from gan_deeplearning4j_tpu.optim.rmsprop import RmsProp

    lr = RmsProp(_CELEBA_LR, 1e-8, 1e-8)
    b = GraphBuilder(seed=_CELEBA_SEED, l2=1e-4, activation="relu",
                     weight_init="xavier", clip_threshold=1.0)
    b.add_inputs("in")
    b.set_input_types(InputSpec.convolutional_flat(64, 64, 3))
    chans = [3, 16, 32, 64, 128]
    prev = "in"
    for i in range(4):
        name = f"conv{i + 1}"
        b.add_layer(name, Conv2D(kernel=(4, 4), stride=(2, 2),
                                 padding=(1, 1), n_in=chans[i],
                                 n_out=chans[i + 1], updater=lr), prev)
        prev = name
    b.add_layer(FEATURE_LAYER, Dense(n_out=256, updater=lr), prev)
    b.add_layer("out", Output(n_out=8, loss="xent", activation="sigmoid",
                              updater=lr), FEATURE_LAYER)
    b.set_outputs("out")
    return b.build().init()


def train_extractor_celeba(log=print):
    """The pinned CelebA recipe: attribute-labeled surrogate, seed-666
    batches, ``_CELEBA_STEPS`` steps.  Deterministic end to end."""
    import jax.numpy as jnp

    from gan_deeplearning4j_tpu.data import datasets

    x, attrs = datasets.synthetic_celeba(
        _CELEBA_N_TRAIN, seed=_CELEBA_SEED, return_attrs=True)
    graph = build_extractor_celeba()
    order = np.random.RandomState(_CELEBA_SEED)
    for step in range(_CELEBA_STEPS):
        idx = order.randint(0, _CELEBA_N_TRAIN, _CELEBA_BATCH)
        loss = graph.fit(jnp.asarray(x[idx]), jnp.asarray(attrs[idx]))
        if log and (step + 1) % 100 == 0:
            log(f"[fid-extractor-celeba] step {step + 1}/{_CELEBA_STEPS} "
                f"loss {float(loss):.4f}")
    return graph


_cached_celeba = None


def load_extractor_celeba():
    """The committed frozen 64x64 extractor (cached per process)."""
    global _cached_celeba
    if _cached_celeba is None:
        if not os.path.exists(CELEBA_ASSET_PATH):
            raise FileNotFoundError(
                f"{CELEBA_ASSET_PATH} missing — regenerate with: python -m "
                "gan_deeplearning4j_tpu.eval.fid_extractor --family celeba")
        from gan_deeplearning4j_tpu.graph import serialization

        _cached_celeba = serialization.read_model(CELEBA_ASSET_PATH)
    return _cached_celeba


def frozen_fid_celeba(real: np.ndarray, generated: np.ndarray,
                      batch_size: int = 250) -> float:
    """FID between 64x64 pixel sets ([n, 3*64*64], tanh range) in the
    FROZEN CelebA feature space."""
    from gan_deeplearning4j_tpu.eval import fid as fid_lib

    return fid_lib.compute_fid(load_extractor_celeba(), real, generated,
                               layer=FEATURE_LAYER, batch_size=batch_size)


# --------------------------------------------------------------------------
# CIFAR-32 frozen extractor (VERDICT r4 next-step #4): the frozen feature
# space for the cGAN family's per-class FID and intra-class diversity
# metrics (eval/conditional.py).  Trained ONCE on the CALIBRATED surrogate
# tier (probe Bayes ceiling ~0.96 — label-preserving ambiguous tail, see
# data/datasets.synthetic_cifar10) under a pinned recipe.

CIFAR_RECIPE_VERSION = 1
CIFAR_ASSET_PATH = os.path.join(
    _ASSET_DIR, f"fid_extractor_cifar_v{CIFAR_RECIPE_VERSION}.zip")

_CIFAR_SEED = 666
_CIFAR_N_TRAIN = 8000
_CIFAR_BATCH = 100
_CIFAR_STEPS = 600
_CIFAR_LR = 1e-3


def build_extractor_cifar():
    """Fixed 32x32x3 architecture: 3 stride-2 convs (3->16->32->64) ->
    256-d dense ("feat") -> 10-way softmax."""
    from gan_deeplearning4j_tpu.graph import (
        Conv2D,
        Dense,
        GraphBuilder,
        InputSpec,
        Output,
    )
    from gan_deeplearning4j_tpu.optim.rmsprop import RmsProp

    lr = RmsProp(_CIFAR_LR, 1e-8, 1e-8)
    b = GraphBuilder(seed=_CIFAR_SEED, l2=1e-4, activation="relu",
                     weight_init="xavier", clip_threshold=1.0)
    b.add_inputs("in")
    b.set_input_types(InputSpec.convolutional_flat(32, 32, 3))
    chans = [3, 16, 32, 64]
    prev = "in"
    for i in range(3):
        name = f"conv{i + 1}"
        b.add_layer(name, Conv2D(kernel=(4, 4), stride=(2, 2),
                                 padding=(1, 1), n_in=chans[i],
                                 n_out=chans[i + 1], updater=lr), prev)
        prev = name
    b.add_layer(FEATURE_LAYER, Dense(n_out=256, updater=lr), prev)
    b.add_layer("out", Output(n_out=10, loss="mcxent",
                              activation="softmax", updater=lr),
                FEATURE_LAYER)
    b.set_outputs("out")
    return b.build().init()


def train_extractor_cifar(log=print):
    import jax.numpy as jnp

    from gan_deeplearning4j_tpu.data import datasets

    x, y = datasets.synthetic_cifar10(_CIFAR_N_TRAIN, seed=_CIFAR_SEED,
                                      difficulty="calibrated")
    onehot = np.eye(10, dtype=np.float32)[y]
    graph = build_extractor_cifar()
    order = np.random.RandomState(_CIFAR_SEED)
    for step in range(_CIFAR_STEPS):
        idx = order.randint(0, _CIFAR_N_TRAIN, _CIFAR_BATCH)
        loss = graph.fit(jnp.asarray(x[idx]), jnp.asarray(onehot[idx]))
        if log and (step + 1) % 100 == 0:
            log(f"[fid-extractor-cifar] step {step + 1}/{_CIFAR_STEPS} "
                f"loss {float(loss):.4f}")
    return graph


_cached_cifar = None


def load_extractor_cifar():
    """The committed frozen 32x32 extractor (cached per process)."""
    global _cached_cifar
    if _cached_cifar is None:
        if not os.path.exists(CIFAR_ASSET_PATH):
            raise FileNotFoundError(
                f"{CIFAR_ASSET_PATH} missing — regenerate with: python -m "
                "gan_deeplearning4j_tpu.eval.fid_extractor --family cifar")
        from gan_deeplearning4j_tpu.graph import serialization

        _cached_cifar = serialization.read_model(CIFAR_ASSET_PATH)
    return _cached_cifar


_cached = None


def load_extractor():
    """The committed frozen extractor (cached per process).  Raises
    FileNotFoundError with the regeneration command if the asset for
    RECIPE_VERSION is absent."""
    global _cached
    if _cached is None:
        if not os.path.exists(ASSET_PATH):
            raise FileNotFoundError(
                f"{ASSET_PATH} missing — regenerate the frozen FID "
                "extractor with: python -m "
                "gan_deeplearning4j_tpu.eval.fid_extractor")
        from gan_deeplearning4j_tpu.graph import serialization

        _cached = serialization.read_model(ASSET_PATH)
    return _cached


def frozen_fid(real: np.ndarray, generated: np.ndarray,
               batch_size: int = 500) -> float:
    """FID between pixel sets in the FROZEN feature space — the
    cross-round-comparable headline metric."""
    from gan_deeplearning4j_tpu.eval import fid as fid_lib

    return fid_lib.compute_fid(load_extractor(), real, generated,
                               layer=FEATURE_LAYER, batch_size=batch_size)


def main(argv=None) -> None:
    import argparse

    from gan_deeplearning4j_tpu.eval import metrics  # noqa: F401 (package init)

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--family", choices=("mnist", "celeba", "cifar"),
                   default="mnist")
    args = p.parse_args(argv)

    import jax.numpy as jnp

    from gan_deeplearning4j_tpu.data import datasets

    if args.family == "cifar":
        graph = train_extractor_cifar()
        xt, yt = datasets.synthetic_cifar10(2000, seed=_CIFAR_SEED + 1,
                                            difficulty="calibrated")
        pred = np.asarray(graph.output(jnp.asarray(xt))[0]).argmax(axis=1)
        acc = float((pred == yt).mean())
        print(f"[fid-extractor-cifar] held-out accuracy {acc:.4f} "
              "(calibrated tier: Bayes ceiling ~0.96)")
        path = save_asset(graph, CIFAR_ASSET_PATH)
        print(f"[fid-extractor-cifar] wrote {path} "
              f"(recipe v{CIFAR_RECIPE_VERSION}, acc {acc:.4f})")
        return

    if args.family == "celeba":
        graph = train_extractor_celeba()
        # held-out self-check: per-attribute accuracy before freezing
        xt, at = datasets.synthetic_celeba(2000, seed=_CELEBA_SEED + 1,
                                           return_attrs=True)
        pred = np.asarray(graph.output(jnp.asarray(xt))[0]) > 0.5
        per_attr = (pred == (at > 0.5)).mean(axis=0)
        acc = float(per_attr.mean())
        print("[fid-extractor-celeba] held-out per-attr acc "
              + " ".join(f"{a:.3f}" for a in per_attr))
        path = save_asset(graph, CELEBA_ASSET_PATH)
        print(f"[fid-extractor-celeba] wrote {path} "
              f"(recipe v{CELEBA_RECIPE_VERSION}, mean acc {acc:.4f})")
        return

    graph = train_extractor()
    # quick self-check on held-out data before freezing
    xt, yt = datasets.synthetic_mnist(4000, seed=_SEED + 1)
    pred = np.asarray(graph.output(jnp.asarray(xt))[0]).argmax(axis=1)
    acc = float((pred == yt).mean())
    print(f"[fid-extractor] held-out accuracy {acc:.4f}")
    path = save_asset(graph)
    print(f"[fid-extractor] wrote {path} (recipe v{RECIPE_VERSION}, "
          f"acc {acc:.4f})")


if __name__ == "__main__":
    from gan_deeplearning4j_tpu.runtime import backend as _backend

    _backend.apply_env_platform()
    main()
