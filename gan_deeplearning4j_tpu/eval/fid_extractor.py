"""Frozen deterministic FID feature extractor (VERDICT r2 next-step #3).

Rounds 1-2 computed FID in the feature space of each run's OWN trained
transfer classifier, so the metric's embedding moved with every run —
round-over-round FID was noise (honest range 117.9-218.7 across float
rounding paths, RESULTS r2 §1).  The standard recipe freezes the embedding
(InceptionV3 pool3 — unavailable offline), so this module is the offline
equivalent: a small CNN classifier trained ONCE on the calibrated MNIST
surrogate under a fully pinned recipe (seed 666, fixed data budget, fixed
step count) and committed as an asset zip.  Every FID after that loads
the SAME weights — the embedding never moves again, making FID comparable
across runs, rounds, and code changes.

Regenerate (only if the recipe version bumps):
    python -m gan_deeplearning4j_tpu.eval.fid_extractor
which retrains deterministically and overwrites the asset; the recipe
version is embedded in the filename so a stale asset cannot be loaded
silently.  (Verified: a from-scratch retrain reproduces the committed
v1 asset bit-for-bit on the CPU backend, 2026-07-31.)

The feature layer is the 256-wide penultimate dense ("feat"), the
classifier-feature FID convention (same role as the reference evaluation
network's dis_dense_layer_6 features, dl4jGANComputerVision.java:322-351).
"""

from __future__ import annotations

import os

import numpy as np

RECIPE_VERSION = 1
FEATURE_LAYER = "feat"
_ASSET_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "assets")
ASSET_PATH = os.path.join(_ASSET_DIR,
                          f"fid_extractor_v{RECIPE_VERSION}.zip")

# pinned training recipe — changing ANY of these requires a version bump
_SEED = 666
_N_TRAIN = 20000
_BATCH = 200
_STEPS = 1500
_LR = 1e-3


def build_extractor():
    """The fixed architecture: 2 strided convs -> 256-d dense ("feat")
    -> 10-way softmax.  ~0.4M params, small enough to commit."""
    from gan_deeplearning4j_tpu.graph import (
        Conv2D,
        Dense,
        GraphBuilder,
        InputSpec,
        Output,
    )
    from gan_deeplearning4j_tpu.optim.rmsprop import RmsProp

    lr = RmsProp(_LR, 1e-8, 1e-8)
    b = GraphBuilder(seed=_SEED, l2=1e-4, activation="relu",
                     weight_init="xavier", clip_threshold=1.0)
    b.add_inputs("in")
    b.set_input_types(InputSpec.convolutional_flat(28, 28, 1))
    b.add_layer("conv1", Conv2D(kernel=(5, 5), stride=(2, 2), n_in=1,
                                n_out=16, updater=lr), "in")
    b.add_layer("conv2", Conv2D(kernel=(5, 5), stride=(2, 2), n_in=16,
                                n_out=32, updater=lr), "conv1")
    b.add_layer(FEATURE_LAYER, Dense(n_out=256, updater=lr), "conv2")
    b.add_layer("out", Output(n_out=10, loss="xent", activation="softmax",
                              updater=lr), FEATURE_LAYER)
    b.set_outputs("out")
    return b.build().init()


def train_extractor(log=print):
    """The pinned recipe: calibrated-surrogate train split, seed-666
    batches, ``_STEPS`` steps.  Deterministic end to end — rerunning
    reproduces the committed weights bit-for-bit on the same backend."""
    from gan_deeplearning4j_tpu.data import datasets

    x, y = datasets.synthetic_mnist(_N_TRAIN, seed=_SEED)
    onehot = np.eye(10, dtype=np.float32)[y]
    graph = build_extractor()
    order = np.random.RandomState(_SEED)
    for step in range(_STEPS):
        idx = order.randint(0, _N_TRAIN, _BATCH)
        loss = graph.fit(x[idx], onehot[idx])
        if log and (step + 1) % 300 == 0:
            log(f"[fid-extractor] step {step + 1}/{_STEPS} "
                f"loss {float(loss):.4f}")
    return graph


def save_asset(graph, path: str = ASSET_PATH) -> str:
    from gan_deeplearning4j_tpu.graph import serialization

    os.makedirs(os.path.dirname(path), exist_ok=True)
    serialization.write_model(graph, path, save_updater=False)
    return path


_cached = None


def load_extractor():
    """The committed frozen extractor (cached per process).  Raises
    FileNotFoundError with the regeneration command if the asset for
    RECIPE_VERSION is absent."""
    global _cached
    if _cached is None:
        if not os.path.exists(ASSET_PATH):
            raise FileNotFoundError(
                f"{ASSET_PATH} missing — regenerate the frozen FID "
                "extractor with: python -m "
                "gan_deeplearning4j_tpu.eval.fid_extractor")
        from gan_deeplearning4j_tpu.graph import serialization

        _cached = serialization.read_model(ASSET_PATH)
    return _cached


def frozen_fid(real: np.ndarray, generated: np.ndarray,
               batch_size: int = 500) -> float:
    """FID between pixel sets in the FROZEN feature space — the
    cross-round-comparable headline metric."""
    from gan_deeplearning4j_tpu.eval import fid as fid_lib

    return fid_lib.compute_fid(load_extractor(), real, generated,
                               layer=FEATURE_LAYER, batch_size=batch_size)


def main() -> None:
    from gan_deeplearning4j_tpu.eval import metrics  # noqa: F401 (package init)

    graph = train_extractor()
    # quick self-check on held-out data before freezing
    from gan_deeplearning4j_tpu.data import datasets

    xt, yt = datasets.synthetic_mnist(4000, seed=_SEED + 1)
    import jax.numpy as jnp

    pred = np.asarray(graph.output(jnp.asarray(xt))[0]).argmax(axis=1)
    acc = float((pred == yt).mean())
    print(f"[fid-extractor] held-out accuracy {acc:.4f}")
    path = save_asset(graph)
    print(f"[fid-extractor] wrote {path} (recipe v{RECIPE_VERSION}, "
          f"acc {acc:.4f})")


if __name__ == "__main__":
    from gan_deeplearning4j_tpu.runtime import backend as _backend

    _backend.apply_env_platform()
    main()
