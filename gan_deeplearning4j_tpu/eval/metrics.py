"""Metric computations matching the notebook's scoring cells.

``gan.ipynb`` cell 7 (raw lines 925-955): read the test CSV's label column
and the trainer's ``mnist_test_predictions_{k}.csv``, take argmax over the
10 softmax columns, compare — the published 97.07% accuracy.  Cell 10
(raw lines 1483-1516): ``sklearn.metrics.roc_auc_score(y, p,
average="weighted")`` over ``insurance_test_predictions_{k}.csv`` — the
published 91.63% AUROC.
"""

from __future__ import annotations

import numpy as np

from gan_deeplearning4j_tpu.data import read_csv_matrix


def accuracy_from_predictions(predictions: np.ndarray, labels: np.ndarray) -> float:
    """argmax-match accuracy; ``predictions`` [N, C] scores, ``labels`` [N]."""
    pred = np.asarray(predictions).argmax(axis=1)
    return float((pred == np.asarray(labels).astype(np.int64)).mean())


def auroc_from_predictions(scores: np.ndarray, labels: np.ndarray,
                           average: str = "weighted") -> float:
    """Weighted AUROC, the notebook's exact call (cell 10)."""
    from sklearn.metrics import roc_auc_score

    return float(roc_auc_score(np.asarray(labels).astype(np.int64),
                               np.asarray(scores).ravel(), average=average))


def mnist_accuracy(predictions_csv: str, test_csv: str,
                   label_index: int = 784) -> float:
    preds = read_csv_matrix(predictions_csv)
    labels = read_csv_matrix(test_csv)[:, label_index]
    return accuracy_from_predictions(preds, labels)


def insurance_auroc(predictions_csv: str, test_csv: str,
                    label_index: int = 12) -> float:
    scores = read_csv_matrix(predictions_csv)
    labels = read_csv_matrix(test_csv)[:, label_index]
    return auroc_from_predictions(scores, labels)


def grid_to_lattices(grid_csv_or_array, rows: int, cols: int) -> np.ndarray:
    """Reshape a latent-grid dump [n^2, rows*cols] into [n^2, rows, cols]
    lattices (the notebook's plotting layout for 4x3 transaction lattices
    and 28x28 digit grids)."""
    arr = (
        read_csv_matrix(grid_csv_or_array)
        if isinstance(grid_csv_or_array, str) else np.asarray(grid_csv_or_array)
    )
    return arr.reshape(arr.shape[0], rows, cols)


def write_evaluation_report(res_path: str, predictions, labels,
                            num_classes: int, f1_cls=None,
                            metrics_jsonl=None, smooth: int = 25) -> dict:
    """Shared end-of-run report for the mains: DL4J-style Evaluation over
    the (already loaded) final prediction dump — stats block written to
    ``evaluation_stats.txt`` — plus, when a metrics JSONL has records, the
    loss-curve PNG.  Returns {"test_f1": ...} (class ``f1_cls`` if given,
    else macro)."""
    import os

    from gan_deeplearning4j_tpu.eval.evaluation import Evaluation

    ev = Evaluation(num_classes)
    ev.eval(labels, predictions)
    with open(os.path.join(res_path, "evaluation_stats.txt"), "w") as f:
        f.write(ev.stats() + "\n")
    if metrics_jsonl and os.path.exists(metrics_jsonl):
        try:
            from gan_deeplearning4j_tpu.utils.plot_metrics import plot_losses

            plot_losses(metrics_jsonl, smooth=smooth)
        except ImportError:  # gan4j-lint: disable=swallowed-exception — matplotlib is an optional extra; the stats file above is the product
            pass
        except ValueError:  # gan4j-lint: disable=swallowed-exception — e.g. a resumed-to-completion run truncates the jsonl; the plot is best-effort
            pass
    return {"test_f1": ev.f1(f1_cls) if f1_cls is not None else ev.f1()}
