"""Grid renderings — the notebook's visual-inspection artifacts as code.

``gan.ipynb`` cells 7/10 render the generator's latent-grid samples as
PNG mosaics: the 10x10 MNIST digit grid (``DCGAN_Generated_Images.png``)
and the 50x50 insurance transaction-lattice grid
(``DCGAN_Generated_Lattices.png``) — SURVEY.md §4.3 "visual inspection".
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def tile_grid(samples: np.ndarray, rows: int, cols: int,
              pad: int = 1) -> np.ndarray:
    """[n, H, W] -> one [rows*(H+pad), cols*(W+pad)] mosaic (row-major)."""
    n, h, w = samples.shape
    if n < rows * cols:
        raise ValueError(f"need {rows * cols} samples, got {n}")
    out = np.zeros((rows * (h + pad) - pad, cols * (w + pad) - pad),
                   dtype=samples.dtype)
    for i in range(rows):
        for j in range(cols):
            out[i * (h + pad): i * (h + pad) + h,
                j * (w + pad): j * (w + pad) + w] = samples[i * cols + j]
    return out


def _render_mosaic_png(path: str, arr: np.ndarray,
                       grid_edge: Optional[int], w: int, h: int) -> str:
    """Shared renderer: ``arr`` is [n, C, H, W] in [0, 1]; tiles each
    channel and writes the PNG (grayscale when C == 1)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    n, c = arr.shape[0], arr.shape[1]
    edge = grid_edge or int(round(np.sqrt(n)))
    mosaic = np.stack(
        [tile_grid(arr[:, ch], edge, edge) for ch in range(c)], axis=-1)
    if c == 1:
        mosaic = mosaic[..., 0]
    plt.figure(figsize=(max(4, edge * w / 28), max(4, edge * h / 28)))
    plt.imshow(mosaic, interpolation="nearest",
               **({"cmap": "gray"} if c == 1 else {}))
    plt.axis("off")
    plt.tight_layout(pad=0)
    plt.savefig(path, dpi=150, bbox_inches="tight")
    plt.close()
    return path


def save_grid_png(path: str, grid_csv_or_array, sample_shape,
                  grid_edge: Optional[int] = None) -> str:
    """Render a trainer grid dump (``{name}_out_{k}.csv``) to a PNG mosaic.

    ``sample_shape``: (H, W) of one sample (28, 28 for MNIST; 4, 3 for the
    insurance lattices).  ``grid_edge``: mosaic edge length (defaults to
    sqrt of the sample count — the trainers dump n^2 rows).
    """
    from gan_deeplearning4j_tpu.data import read_csv_matrix

    arr = (read_csv_matrix(grid_csv_or_array)
           if isinstance(grid_csv_or_array, str)
           else np.asarray(grid_csv_or_array))
    h, w = sample_shape
    return _render_mosaic_png(
        path, arr.reshape(arr.shape[0], 1, h, w), grid_edge, w, h)


def save_lattice_example_pngs(path_raw: str, path_plotted: str,
                              grid_csv_or_array, sample_shape=(4, 3),
                              index: int = 0,
                              col_labels=("premium", "service", "claim"),
                              ) -> tuple:
    """The reference's single-lattice artifacts
    (``Python/DCGAN_Generated_Lattice_Example.png`` and
    ``..._Example_Plotted.png``): one generated transaction lattice as a
    raw pixel blow-up and as an annotated heatmap (period rows x
    transaction-type columns, value-labeled cells)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    from gan_deeplearning4j_tpu.data import read_csv_matrix

    arr = (read_csv_matrix(grid_csv_or_array)
           if isinstance(grid_csv_or_array, str)
           else np.asarray(grid_csv_or_array))
    h, w = sample_shape
    lattice = arr[index].reshape(h, w)

    plt.figure(figsize=(3, 4))
    plt.imshow(lattice, cmap="gray", interpolation="nearest")
    plt.axis("off")
    plt.tight_layout(pad=0)
    plt.savefig(path_raw, dpi=150, bbox_inches="tight")
    plt.close()

    fig, ax = plt.subplots(figsize=(4, 5))
    im = ax.imshow(lattice, cmap="viridis", interpolation="nearest")
    ax.set_xlabel("transaction type")
    ax.set_ylabel("period")
    # fall back to numeric labels when the given names don't cover w
    names = (list(col_labels) if col_labels and len(col_labels) >= w
             else [str(j) for j in range(w)])
    ax.set_xticks(range(w), names[:w])
    ax.set_yticks(range(h))
    for i in range(h):
        for j in range(w):
            ax.text(j, i, f"{lattice[i, j]:.2f}", ha="center", va="center",
                    color="white", fontsize=8)
    fig.colorbar(im, ax=ax, shrink=0.8)
    fig.tight_layout()
    fig.savefig(path_plotted, dpi=150, bbox_inches="tight")
    plt.close(fig)
    return path_raw, path_plotted


def save_rgb_grid_png(path: str, samples: np.ndarray, sample_shape,
                      grid_edge: Optional[int] = None,
                      value_range=(-1.0, 1.0)) -> str:
    """RGB mosaic for the roadmap model families: ``samples`` is
    [n, C*H*W] NCHW-flattened (the generators' flat output layout),
    ``sample_shape`` = (C, H, W), values in ``value_range`` (tanh heads
    emit [-1, 1])."""
    c, h, w = sample_shape
    arr = np.asarray(samples, dtype=np.float32).reshape(-1, c, h, w)
    lo, hi = value_range
    arr = np.clip((arr - lo) / (hi - lo), 0.0, 1.0)
    return _render_mosaic_png(path, arr, grid_edge, w, h)
