from gan_deeplearning4j_tpu.graph.graph import (  # noqa: F401
    ComputationGraph,
    GraphBuilder,
    InputSpec,
)
from gan_deeplearning4j_tpu.graph.layers import (  # noqa: F401
    BatchNorm,
    ConditionalBatchNorm,
    Conv2D,
    ConvTranspose2D,
    Dense,
    Dropout,
    ElementWise,
    MaxPool2D,
    Merge,
    MinibatchStdDev,
    Output,
    ProjectionOutput,
    Upsampling2D,
)
from gan_deeplearning4j_tpu.graph.preprocessors import (  # noqa: F401
    CnnToFeedForward,
    FeedForwardToCnn,
)
from gan_deeplearning4j_tpu.graph.keras_import import import_keras  # noqa: F401
from gan_deeplearning4j_tpu.graph.dl4j_import import (  # noqa: F401
    export_dl4j,
    import_dl4j,
)
from gan_deeplearning4j_tpu.graph.serialization import read_model, write_model  # noqa: F401
from gan_deeplearning4j_tpu.graph.transfer import (  # noqa: F401
    FineTuneConfiguration,
    TransferLearning,
)
