"""DL4J ModelSerializer zip interop — read (and write) the reference's
own model artifacts.

The ONLY artifact the reference ever persists is a DL4J
``ModelSerializer`` zip (``dl4jGANComputerVision.java:529-533``,
``dl4jGANInsurance.java:471-475``): a zip holding

  - ``configuration.json`` — the ``ComputationGraphConfiguration``
    (Jackson JSON: ``networkInputs`` / ``networkOutputs`` /
    ``vertexInputs`` / ``vertices`` with ``@class``-typed layer configs),
  - ``coefficients.bin`` — ALL parameters as ONE flattened row vector in
    topological order, serialized by ``Nd4j.write``: two DataBuffer
    records (shape-info, then data), each ``writeUTF(allocationMode)``,
    ``writeLong(length)``, ``writeUTF(dataType)``, big-endian elements
    (the 1.0.0-beta3 layout of the reference's classpath),
  - optionally ``updaterState.bin`` — the updater's state view as one
    flat ``Nd4j.write`` vector (the reference saves with
    ``saveUpdater=true``): per-parameter RmsProp accumulators in
    coefficient order, EXCEPT batch-norm mean/var (NoOp updater, zero
    state elements).  Imported into ``opt_state`` when an RmsProp
    ``updater=`` is supplied (``load_updater=False`` opts out), written
    back by ``export_dl4j(..., save_updater=True)`` — a migrating DL4J
    user continues training with optimizer state intact.

``import_dl4j`` reads such a zip into a native ``ComputationGraph`` for
the layer types the reference uses (Dense, Output, Convolution
[Truncate], Subsampling[MAX], BatchNormalization, Upsampling2D, plus
FeedForwardToCnn/CnnToFeedForward preprocessors).  Per-parameter
layouts follow DL4J's initializers: dense/output views are
weights-first with column-major (``'f'``) ``W``
(``WeightInitUtil.DEFAULT_WEIGHT_INIT_ORDER``), convolution views are
bias-FIRST with row-major OIHW kernels (``ConvolutionParamInitializer``
carves bias at ``[0, nOut)``), batch
norm contributes ``[gamma, beta, mean, var]``
(``BatchNormalizationParamInitializer``) — DL4J counts the running
stats as parameters, which is exactly this framework's BN params set.

``export_dl4j`` writes the same format, completing the migration story
in both directions and providing spec-conformant fixtures: with no JVM
or DL4J jar in this environment (zero egress), compatibility is
validated by round-trip + parity tests against self-generated fixtures
and by field-level fidelity to the beta3 JSON/binary layout documented
above (tests/test_dl4j_import.py).
"""

from __future__ import annotations

import io
import json
import struct
import zipfile
from typing import Dict, List, Optional, Tuple

import numpy as np

from gan_deeplearning4j_tpu.graph.graph import (
    ComputationGraph,
    GraphBuilder,
    InputSpec,
)
from gan_deeplearning4j_tpu.graph.layers import (
    BatchNorm,
    Conv2D,
    Dense,
    Dropout,
    MaxPool2D,
    Output,
    Upsampling2D,
)
from gan_deeplearning4j_tpu.graph.preprocessors import (
    CnnToFeedForward,
    FeedForwardToCnn,
)

# -- ND4J binary DataBuffer / INDArray codec (Nd4j.write, beta3) ----------

_DTYPES = {"FLOAT": ("f", 4), "DOUBLE": ("d", 8),
           "INT": ("i", 4), "LONG": ("q", 8)}


def _write_utf(out: io.BufferedIOBase, s: str) -> None:
    data = s.encode("utf-8")  # Java modified-UTF8 == UTF-8 for ASCII
    out.write(struct.pack(">H", len(data)))
    out.write(data)


def _read_utf(src: io.BufferedIOBase) -> str:
    (n,) = struct.unpack(">H", src.read(2))
    return src.read(n).decode("utf-8")


def _write_buffer(out, values: np.ndarray, dtype: str) -> None:
    """One DataBuffer record: UTF allocation mode, long length, UTF
    data type, then big-endian elements (BaseDataBuffer.write)."""
    _write_utf(out, "MIXED_DATA_TYPES")  # beta3's allocation mode tag
    out.write(struct.pack(">q", values.size))
    _write_utf(out, dtype)
    code, _ = _DTYPES[dtype]
    out.write(np.ascontiguousarray(values).astype(f">{code}").tobytes())


def _read_buffer(src) -> np.ndarray:
    _read_utf(src)  # allocation mode: any token accepted, ignored
    (length,) = struct.unpack(">q", src.read(8))
    dtype = _read_utf(src)
    try:
        code, width = _DTYPES[dtype]
    except KeyError:
        raise ValueError(f"unsupported ND4J data type: {dtype!r}")
    raw = src.read(length * width)
    if len(raw) != length * width:
        raise ValueError("truncated ND4J data buffer")
    return np.frombuffer(raw, dtype=f">{code}").astype(code)


def write_nd4j(out, arr: np.ndarray) -> None:
    """``Nd4j.write``: shape-info buffer (LONG: rank, shape, c-order
    strides, extras=0, elementWiseStride=1, order char) then data."""
    arr = np.ascontiguousarray(arr, dtype=np.float32)
    rank = arr.ndim
    strides = [int(np.prod(arr.shape[i + 1:], dtype=np.int64))
               for i in range(rank)]
    shape_info = np.asarray(
        [rank, *arr.shape, *strides, 0, 1, ord("c")], dtype=np.int64)
    _write_buffer(out, shape_info, "LONG")
    _write_buffer(out, arr, "FLOAT")


def read_nd4j(src) -> np.ndarray:
    shape_info = _read_buffer(src).astype(np.int64)
    rank = int(shape_info[0])
    shape = tuple(int(s) for s in shape_info[1:1 + rank])
    order = chr(int(shape_info[-1])) if shape_info[-1] in (99, 102) else "c"
    data = _read_buffer(src).astype(np.float32)
    if data.size != int(np.prod(shape, dtype=np.int64)):
        raise ValueError(
            f"ND4J data length {data.size} != shape product of {shape}")
    return data.reshape(shape, order=order.upper() if order == "f" else "C")


# -- layer config <-> JSON ------------------------------------------------

_NS = "org.deeplearning4j.nn.conf"
_ACT_NS = "org.nd4j.linalg.activations.impl.Activation"
_LOSS_NS = "org.nd4j.linalg.lossfunctions.impl.Loss"

# DL4J activation class simple-name suffix <-> ops.activations name
_ACT_FROM_DL4J = {
    "Identity": "identity", "TanH": "tanh", "Sigmoid": "sigmoid",
    "Softmax": "softmax", "ReLU": "relu", "LReLU": "leakyrelu",
    "ELU": "elu", "SELU": "selu", "SoftPlus": "softplus",
    "SoftSign": "softsign", "Cube": "cube",
    "RationalTanh": "rationaltanh", "HardTanH": "hardtanh",
    "HardSigmoid": "hardsigmoid", "Swish": "swish", "GELU": "gelu",
    "ReLU6": "relu6", "ThresholdedReLU": "thresholdedrelu",
}
_ACT_TO_DL4J = {v: k for k, v in _ACT_FROM_DL4J.items()}

_LOSS_FROM_DL4J = {
    "BinaryXENT": "xent", "MCXENT": "mcxent", "MSE": "mse",
    "L2": "l2", "L1": "l1",
    "NegativeLogLikelihood": "negativeloglikelihood",
    "Wasserstein": "wasserstein", "Hinge": "hinge",
}
_LOSS_TO_DL4J = {v: k for k, v in _LOSS_FROM_DL4J.items()}

# pre-1.0 "legacy" JSON wraps the layer in a lowercase type key instead
# of @class typing — tolerated on read
_LEGACY_LAYER_KEYS = {
    "dense": "DenseLayer", "output": "OutputLayer",
    "convolution": "ConvolutionLayer", "subsampling": "SubsamplingLayer",
    "batchNormalization": "BatchNormalization",
    "upsampling2d": "Upsampling2D",
}


def _simple_class(d, *, what: str) -> Tuple[str, dict]:
    """(simple class name, config dict) from an @class-typed (or legacy
    single-key-wrapped) JSON object."""
    if "@class" in d:
        return d["@class"].rsplit(".", 1)[-1].rsplit("$", 1)[-1], d
    if len(d) == 1:
        key, cfg = next(iter(d.items()))
        if key in _LEGACY_LAYER_KEYS and isinstance(cfg, dict):
            return _LEGACY_LAYER_KEYS[key], cfg
    raise ValueError(f"{what}: no @class type information in {list(d)[:6]}")


def _get(d: dict, *names, default=None, required=False):
    for n in names:
        if n in d:
            return d[n]
    if required:
        raise ValueError(f"missing field {names[0]!r} in {list(d)[:8]}")
    return default


def _act_name(cfg: dict) -> str:
    fn = _get(cfg, "activationFn", "activationFunction",
              default={"@class": _ACT_NS + "Identity"})
    if isinstance(fn, str):  # very old format: plain string name
        return fn.lower()
    simple = fn["@class"].rsplit(".", 1)[-1]
    suffix = simple[len("Activation"):] if simple.startswith(
        "Activation") else simple
    try:
        return _ACT_FROM_DL4J[suffix]
    except KeyError:
        raise NotImplementedError(f"unsupported DL4J activation: {simple}")


def _pair(v) -> Tuple[int, int]:
    if isinstance(v, (list, tuple)):
        return (int(v[0]), int(v[1] if len(v) > 1 else v[0]))
    return (int(v), int(v))


# -- import ---------------------------------------------------------------

def _param_order(layer) -> List[Tuple[str, str]]:
    """Per-layer (param name, flatten order) in DL4J's parameter order —
    how the flat coefficients vector is segmented.  Dense/Output views
    are weights-FIRST, column-major ('F', WeightInitUtil's default
    order); convolution views are bias-FIRST with row-major OIHW kernels
    (ConvolutionParamInitializer carves bias at [0, nOut) and weights
    after — the reverse of DefaultParamInitializer's layout)."""
    if isinstance(layer, Conv2D):
        return [("b", "C"), ("W", "C")]
    if isinstance(layer, Dense):  # Output subclasses Dense
        return [("W", "F"), ("b", "C")]
    if isinstance(layer, BatchNorm):
        return [("gamma", "C"), ("beta", "C"), ("mean", "C"), ("var", "C")]
    return []


def _updater_state_order(layer) -> List[Tuple[str, str]]:
    """Per-layer (param, flatten order) segments of ``updaterState.bin``.
    Same parameter order as the coefficients vector, EXCEPT batch norm's
    running mean/var: DL4J assigns those a NoOp updater
    (``BatchNormalization.getUpdaterByParam`` — they are advanced by the
    forward pass's running average, not by gradients), and NoOp
    contributes zero state elements to the view."""
    if isinstance(layer, BatchNorm):
        return [("gamma", "C"), ("beta", "C")]
    return _param_order(layer)


def _parse_layer(simple: str, cfg: dict):
    """DL4J layer JSON -> (native layer, needs_n_in_fixup)."""
    if simple in ("DenseLayer", "OutputLayer"):
        kw = dict(
            n_out=int(_get(cfg, "nout", "nOut", required=True)),
            n_in=int(_get(cfg, "nin", "nIn", required=True)),
            activation=_act_name(cfg))
        if simple == "OutputLayer":
            fn = _get(cfg, "lossFn", "lossFunction", required=True)
            if isinstance(fn, str):
                lname = fn.lower().replace("_", "")
                loss = {"xent": "xent", "mcxent": "mcxent"}.get(lname, lname)
            else:
                lsimple = fn["@class"].rsplit(".", 1)[-1]
                suffix = (lsimple[len("Loss"):]
                          if lsimple.startswith("Loss") else lsimple)
                try:
                    loss = _LOSS_FROM_DL4J[suffix]
                except KeyError:
                    raise NotImplementedError(
                        f"unsupported DL4J loss: {lsimple}")
            return Output(loss=loss, **kw)
        return Dense(**kw)
    if simple == "ConvolutionLayer":
        mode = _get(cfg, "convolutionMode", default="Truncate")
        if mode not in (None, "Truncate"):
            raise NotImplementedError(
                f"convolutionMode={mode!r}; only Truncate (the reference's "
                "mode, with its output-size arithmetic) is implemented")
        return Conv2D(
            kernel=_pair(_get(cfg, "kernelSize", required=True)),
            stride=_pair(_get(cfg, "stride", default=(1, 1))),
            padding=_pair(_get(cfg, "padding", default=(0, 0))),
            n_out=int(_get(cfg, "nout", "nOut", required=True)),
            n_in=int(_get(cfg, "nin", "nIn", required=True)),
            activation=_act_name(cfg))
    if simple == "SubsamplingLayer":
        pooling = _get(cfg, "poolingType", default="MAX")
        if str(pooling).upper() != "MAX":
            raise NotImplementedError(
                f"poolingType={pooling!r}; only MAX (the reference's) "
                "is implemented")
        if _pair(_get(cfg, "padding", default=(0, 0))) != (0, 0):
            raise NotImplementedError("padded subsampling")
        return MaxPool2D(kernel=_pair(_get(cfg, "kernelSize", required=True)),
                         stride=_pair(_get(cfg, "stride", default=(1, 1))))
    if simple == "BatchNormalization":
        return BatchNorm(
            n=int(_get(cfg, "nout", "nOut", "nin", "nIn", required=True)),
            decay=float(_get(cfg, "decay", default=0.9)),
            eps=float(_get(cfg, "eps", default=1e-5)),
            activation=_act_name(cfg))
    if simple == "Upsampling2D":
        size = _pair(_get(cfg, "size", required=True))
        if size[0] != size[1]:
            raise NotImplementedError("non-square Upsampling2D")
        return Upsampling2D(size=size[0])
    if simple == "DropoutLayer":
        # DL4J's Dropout(p) carries the RETAIN probability; a null/absent
        # iDropout is the reference's `new DropoutLayer()` identity quirk
        idrop = _get(cfg, "idropout", "iDropout", default=None)
        if idrop is None:
            return Dropout(rate=0.0)
        p = float(_get(idrop, "p", "dropout", required=True))
        return Dropout(rate=1.0 - p)
    raise NotImplementedError(f"unsupported DL4J layer type: {simple}")


def _parse_preprocessor(d: Optional[dict]):
    if d is None:
        return None
    simple, cfg = _simple_class(d, what="preProcessor")
    if simple == "FeedForwardToCnnPreProcessor":
        return FeedForwardToCnn(
            height=int(_get(cfg, "inputHeight", "height", required=True)),
            width=int(_get(cfg, "inputWidth", "width", required=True)),
            channels=int(_get(cfg, "numChannels", "channels",
                              required=True)))
    if simple == "CnnToFeedForwardPreProcessor":
        # the native graph auto-flattens conv->dense in the same (c, h, w)
        # order DL4J does, so this is a no-op marker
        return CnnToFeedForward()
    raise NotImplementedError(f"unsupported preProcessor: {simple}")


def _parse_input_type(d: dict) -> InputSpec:
    simple, cfg = _simple_class(d, what="inputTypes")
    if simple == "InputTypeFeedForward":
        return InputSpec.feed_forward(int(_get(cfg, "size", required=True)))
    if simple == "InputTypeConvolutionalFlat":
        return InputSpec.convolutional_flat(
            int(_get(cfg, "height", required=True)),
            int(_get(cfg, "width", required=True)),
            int(_get(cfg, "depth", "channels", required=True)))
    if simple == "InputTypeConvolutional":
        return InputSpec.convolutional(
            int(_get(cfg, "channels", "depth", required=True)),
            int(_get(cfg, "height", required=True)),
            int(_get(cfg, "width", required=True)))
    raise NotImplementedError(f"unsupported input type: {simple}")


def _topo_order(inputs: List[str], vertex_inputs: Dict[str, List[str]]
                ) -> List[str]:
    """Topological order of vertices (DL4J flattens parameters in this
    order); deterministic for the linear chains the reference builds and
    for any DAG via Kahn's algorithm over the declared edges."""
    pending = {name: list(ins) for name, ins in vertex_inputs.items()}
    done = set(inputs)
    order: List[str] = []
    while pending:
        ready = [n for n, ins in pending.items()
                 if all(i in done for i in ins)]
        if not ready:
            raise ValueError(
                f"configuration has a cycle or dangling input: "
                f"{sorted(pending)[:4]}")
        for n in ready:
            order.append(n)
            done.add(n)
            del pending[n]
    return order


def import_dl4j(path: str, *, updater=None, seed: int = 666,
                load_updater: bool = True) -> ComputationGraph:
    """Read a DL4J ModelSerializer zip into a native ComputationGraph
    with identical inference behavior.  ``updater``: optimizer for
    subsequent ``fit`` calls.  ``load_updater``: when the zip carries
    ``updaterState.bin`` (the reference saves with ``saveUpdater=true``,
    dl4jGANComputerVision.java:529-533) and ``updater`` is RmsProp, the
    saved accumulators are restored into ``opt_state`` so training
    CONTINUES from the artifact rather than restarting the optimizer —
    the ``ModelSerializer.restoreComputationGraph(file, loadUpdater)``
    semantic."""
    with zipfile.ZipFile(path) as zf:
        names = set(zf.namelist())
        if "configuration.json" not in names:
            raise ValueError(f"{path}: not a DL4J model zip "
                             f"(no configuration.json; has {sorted(names)})")
        conf = json.loads(zf.read("configuration.json"))
        flat = None
        if "coefficients.bin" in names:
            flat = read_nd4j(io.BytesIO(zf.read("coefficients.bin")))
            flat = np.asarray(flat, np.float32).ravel()
        state_flat = None
        if load_updater and updater is not None \
                and "updaterState.bin" in names:
            from gan_deeplearning4j_tpu.optim.rmsprop import RmsProp

            if not isinstance(updater, RmsProp):
                raise NotImplementedError(
                    "updaterState.bin import is implemented for RmsProp "
                    "(the only updater the reference persists); pass "
                    "load_updater=False to import weights only")
            state_flat = read_nd4j(io.BytesIO(zf.read("updaterState.bin")))
            state_flat = np.asarray(state_flat, np.float32).ravel()

    net_inputs = _get(conf, "networkInputs", required=True)
    net_outputs = _get(conf, "networkOutputs", required=True)
    vertex_inputs = _get(conf, "vertexInputs", required=True)
    vertices = _get(conf, "vertices", required=True)

    builder = GraphBuilder(seed=seed, activation="identity")
    builder.add_inputs(*net_inputs)
    input_types = _get(conf, "inputTypes", default=None)
    if input_types:
        builder.set_input_types(
            *[_parse_input_type(t) for t in input_types])

    order = _topo_order(list(net_inputs), vertex_inputs)
    parsed: List[Tuple[str, object]] = []
    for name in order:
        vertex = vertices[name]
        vsimple, vcfg = _simple_class(vertex, what=f"vertex {name}")
        if vsimple != "LayerVertex":
            raise NotImplementedError(
                f"unsupported vertex type: {vsimple} ({name})")
        layer_conf = _get(vcfg, "layerConf", required=True)
        layer_json = _get(layer_conf, "layer", required=True)
        lsimple, lcfg = _simple_class(layer_json, what=f"layer {name}")
        layer = _parse_layer(lsimple, lcfg)
        if updater is not None:
            layer.updater = updater
        builder.add_layer(name, layer, *vertex_inputs[name])
        pre = _parse_preprocessor(_get(vcfg, "preProcessor", default=None))
        if isinstance(pre, FeedForwardToCnn):
            builder.input_preprocessor(name, pre)
        parsed.append((name, layer))
    builder.set_outputs(*net_outputs)
    graph = builder.build().init()

    if flat is not None:
        off = 0
        for name, layer in parsed:
            for pname, forder in _param_order(layer):
                # the initialized graph's own shapes segment the vector
                # (nin/nout from the JSON determined them above)
                shape = tuple(graph.params[name][pname].shape)
                n = int(np.prod(shape, dtype=np.int64))
                if off + n > flat.size:
                    raise ValueError(
                        f"coefficients.bin too short at {name}.{pname}: "
                        f"need {off + n}, have {flat.size}")
                seg = flat[off:off + n].reshape(shape, order=forder)
                graph.set_param(name, pname, np.ascontiguousarray(seg))
                off += n
        if off != flat.size:
            raise ValueError(
                f"coefficients.bin has {flat.size} values; configuration "
                f"accounts for {off}")

    if state_flat is not None:
        import jax.numpy as jnp

        off = 0
        for name, layer in parsed:
            for pname, forder in _updater_state_order(layer):
                shape = tuple(graph.params[name][pname].shape)
                n = int(np.prod(shape, dtype=np.int64))
                if off + n > state_flat.size:
                    raise ValueError(
                        f"updaterState.bin too short at {name}.{pname}: "
                        f"need {off + n}, have {state_flat.size}")
                seg = state_flat[off:off + n].reshape(shape, order=forder)
                graph.opt_state[name][pname] = jnp.asarray(
                    np.ascontiguousarray(seg))
                off += n
        if off != state_flat.size:
            raise ValueError(
                f"updaterState.bin has {state_flat.size} values; "
                f"configuration accounts for {off}")
    return graph


# -- export ---------------------------------------------------------------

def _layer_to_json(name: str, layer, params: Dict[str, np.ndarray]) -> dict:
    """The resolved native layer as beta3 layer JSON.  nIn/nOut come
    from the ACTUAL parameter shapes (a built graph may have inferred
    them; the dataclass fields can be None)."""

    def act(a):
        a = (a or "identity").lower()
        try:
            return {"@class": _ACT_NS + _ACT_TO_DL4J[a]}
        except KeyError:
            raise NotImplementedError(
                f"{name}: activation {a!r} has no DL4J class equivalent")

    base = {"layerName": name}
    if isinstance(layer, Conv2D):
        n_out, n_in = params["W"].shape[:2]
        return {
            "@class": f"{_NS}.layers.ConvolutionLayer", **base,
            "nin": int(n_in), "nout": int(n_out),
            "kernelSize": list(layer.kernel), "stride": list(layer.stride),
            "padding": list(layer.padding), "convolutionMode": "Truncate",
            "activationFn": act(layer.activation)}
    if isinstance(layer, Output):
        try:
            loss_cls = _LOSS_NS + _LOSS_TO_DL4J[layer.loss.lower()]
        except KeyError:
            raise NotImplementedError(
                f"{name}: loss {layer.loss!r} has no DL4J class equivalent")
        n_in, n_out = params["W"].shape
        return {"@class": f"{_NS}.layers.OutputLayer", **base,
                "nin": int(n_in), "nout": int(n_out),
                "lossFn": {"@class": loss_cls},
                "activationFn": act(layer.activation)}
    if isinstance(layer, Dense):
        n_in, n_out = params["W"].shape
        return {"@class": f"{_NS}.layers.DenseLayer", **base,
                "nin": int(n_in), "nout": int(n_out),
                "activationFn": act(layer.activation)}
    if isinstance(layer, BatchNorm):
        n = params["gamma"].shape[0]
        return {"@class": f"{_NS}.layers.BatchNormalization", **base,
                "nin": int(n), "nout": int(n),
                "decay": float(layer.decay), "eps": float(layer.eps),
                "activationFn": act(layer.activation)}
    if isinstance(layer, MaxPool2D):
        return {"@class": f"{_NS}.layers.SubsamplingLayer", **base,
                "poolingType": "MAX", "kernelSize": list(layer.kernel),
                "stride": list(layer.stride), "padding": [0, 0],
                "convolutionMode": "Truncate"}
    if isinstance(layer, Upsampling2D):
        return {"@class": f"{_NS}.layers.Upsampling2D", **base,
                "size": [int(layer.size), int(layer.size)]}
    if isinstance(layer, Dropout):
        out = {"@class": f"{_NS}.layers.DropoutLayer", **base}
        if layer.rate:
            out["idropout"] = {
                "@class": "org.nd4j.linalg.api.ops.random.impl.Dropout"
                          "Config",  # retain probability, DL4J convention
                "p": float(1.0 - layer.rate)}
        return out
    raise NotImplementedError(
        f"{name}: {type(layer).__name__} has no DL4J export mapping")


def _input_type_to_json(spec: InputSpec) -> dict:
    prefix = f"{_NS}.inputs.InputType$"
    if spec.kind == "ff":
        return {"@class": prefix + "InputTypeFeedForward",
                "size": int(spec.shape[0])}
    if spec.kind == "cnn_flat":
        h, w, c = spec.shape
        return {"@class": prefix + "InputTypeConvolutionalFlat",
                "height": int(h), "width": int(w), "depth": int(c)}
    c, h, w = spec.shape
    return {"@class": prefix + "InputTypeConvolutional",
            "channels": int(c), "height": int(h), "width": int(w)}


def export_dl4j(graph: ComputationGraph, path: str,
                save_updater: bool = True) -> None:
    """Write the graph as a DL4J ModelSerializer zip (beta3 layout) —
    the reverse migration path, and the fixture generator for the
    import parity tests.  ``save_updater``: also write
    ``updaterState.bin`` (RmsProp accumulators in DL4J's state-view
    layout) when the graph carries RmsProp-style optimizer state — the
    ``ModelSerializer.writeModel(model, path, true)`` semantic the
    reference uses (dl4jGANComputerVision.java:529-533).  Graphs with
    non-RmsProp state (Adam/Scheduled — DL4J's Adam view layout is
    per-updater-block, not implemented) degrade to a weights-only zip
    with a logged warning."""
    vertices, vertex_inputs = {}, {}
    segments: List[np.ndarray] = []
    state_segments: Optional[List[np.ndarray]] = []
    for name, node in graph.nodes.items():
        layer = node.layer
        params = {p: np.asarray(v, np.float32)
                  for p, v in graph.params.get(name, {}).items()}
        if save_updater and getattr(graph, "opt_state", None) \
                and state_segments is not None:
            from gan_deeplearning4j_tpu.optim.rmsprop import RmsProp

            # the guard is by updater TYPE, not leaf shape: AdaGrad's
            # sum-of-squares leaf is shape-identical to an RmsProp cache
            # (a shape check would silently serialize wrong dynamics)
            # and Sgd's scalar leaf would corrupt the segmentation.
            # A missing per-layer updater is the frozen (_FROZEN RmsProp)
            # case — exportable zeros.
            up = getattr(getattr(graph, "updater", None),
                         "layer_updaters", {}).get(name)
            st = graph.opt_state.get(name, {})
            for pname, forder in _updater_state_order(layer):
                leaf = st.get(pname)
                if leaf is None:
                    continue
                if isinstance(leaf, dict) or (
                        up is not None and not isinstance(up, RmsProp)):
                    # Adam/Scheduled/Sgd/AdaGrad state has no DL4J
                    # RmsProp view equivalent: degrade to the
                    # weights-only zip (the pre-r5 behavior) rather
                    # than failing the export
                    import logging

                    logging.getLogger(__name__).warning(
                        "%s.%s carries non-RmsProp updater state; "
                        "updaterState.bin not written (weights-only "
                        "zip)", name, pname)
                    state_segments = None
                    break
                state_segments.append(
                    np.asarray(leaf, np.float32).ravel(order=forder))
        vertex = {"@class": f"{_NS}.graph.LayerVertex",
                  "layerConf": {
                      "@class": f"{_NS}.NeuralNetConfiguration",
                      "layer": _layer_to_json(name, layer, params)}}
        pre = node.preprocessor
        if isinstance(pre, FeedForwardToCnn):
            vertex["preProcessor"] = {
                "@class": f"{_NS}.preprocessor.FeedForwardToCnnPreProcessor",
                "inputHeight": int(pre.height),
                "inputWidth": int(pre.width),
                "numChannels": int(pre.channels)}
        vertices[name] = vertex
        vertex_inputs[name] = list(node.inputs)
        for pname, forder in _param_order(layer):
            segments.append(params[pname].ravel(order=forder))

    conf = {
        "networkInputs": list(graph.input_names),
        "networkOutputs": list(graph.output_names),
        "vertexInputs": vertex_inputs,
        "vertices": vertices,
        "inputTypes": [_input_type_to_json(graph.input_specs[i])
                       for i in graph.input_names],
    }
    coeffs = io.BytesIO()
    if segments:
        flat = np.concatenate(segments).reshape(1, -1)
        write_nd4j(coeffs, flat)
    state = io.BytesIO()
    if state_segments:
        write_nd4j(state, np.concatenate(state_segments).reshape(1, -1))
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("configuration.json", json.dumps(conf, indent=2))
        if segments:
            zf.writestr("coefficients.bin", coeffs.getvalue())
        if state_segments:
            zf.writestr("updaterState.bin", state.getvalue())
