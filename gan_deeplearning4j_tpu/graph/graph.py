"""Named-layer computation graph — DL4J ``ComputationGraph`` re-designed
TPU-first.

API surface mirrors what the reference exercises
(dl4jGANComputerVision.java:111-160, 322-351, 387-527): a builder with named
layers and explicit wiring, per-layer updaters (freezing = lr 0.0), input
types with automatic preprocessor insertion, ``init`` / ``output`` / ``fit`` /
``get_param`` / ``set_param`` / ``summary``.

The execution model is nothing like DL4J's: parameters are an immutable
pytree ``{layer_name: {param_name: jax.Array}}``; forward/backward/update is
ONE jitted XLA computation per step (traced once, cached), instead of DL4J's
per-layer native-kernel dispatch.  ``set_param`` is a pytree functional
update — because jax.Arrays are immutable, the reference's 30+ per-iteration
cross-graph ``setParam`` copies (SURVEY.md §3.2) become free reference
assignments here, no device traffic.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from gan_deeplearning4j_tpu.graph.layers import (
    LAYER_TYPES,
    BatchNorm,
    Layer,
    Merge,
    Output,
)
from gan_deeplearning4j_tpu.graph.preprocessors import (
    PREPROCESSOR_TYPES,
    CnnToFeedForward,
    FeedForwardToCnn,
)
from gan_deeplearning4j_tpu.ops import losses as loss_lib
from gan_deeplearning4j_tpu.optim.rmsprop import RmsProp
from gan_deeplearning4j_tpu.optim.updater import GraphUpdater
from gan_deeplearning4j_tpu.runtime import prng


@dataclasses.dataclass(frozen=True)
class InputSpec:
    """DL4J InputType equivalent."""

    kind: str  # 'ff' | 'cnn_flat' | 'cnn'
    shape: Tuple[int, ...]

    @staticmethod
    def feed_forward(n: int) -> "InputSpec":
        return InputSpec("ff", (n,))

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputSpec":
        return InputSpec("cnn_flat", (height, width, channels))

    @staticmethod
    def convolutional(channels: int, height: int, width: int) -> "InputSpec":
        return InputSpec("cnn", (channels, height, width))

    def node_shape(self) -> Tuple[int, ...]:
        if self.kind == "ff":
            return self.shape
        if self.kind == "cnn_flat":
            h, w, c = self.shape
            return (c, h, w)
        return self.shape


@dataclasses.dataclass
class Node:
    name: str
    layer: Layer
    inputs: Tuple[str, ...]
    preprocessor: Optional[object] = None
    in_shape: Optional[Tuple[int, ...]] = None
    out_shape: Optional[Tuple[int, ...]] = None


class GraphBuilder:
    """``NeuralNetConfiguration.Builder()...graphBuilder()`` equivalent."""

    def __init__(
        self,
        seed: int = prng.NUMBER_OF_THE_BEAST,
        l2: float = 0.0,
        activation: str = "identity",
        weight_init: str = "xavier",
        updater: Optional[RmsProp] = None,
        clip_threshold: Optional[float] = None,
    ):
        self.seed = seed
        self.l2 = l2
        self.default_activation = activation
        self.weight_init = weight_init
        self.default_updater = updater
        self.clip_threshold = clip_threshold
        self.input_names: List[str] = []
        self.input_specs: Dict[str, InputSpec] = {}
        self.nodes: Dict[str, Node] = {}
        self.output_names: List[str] = []
        self._preprocessors: Dict[str, object] = {}

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self.input_names.extend(names)
        return self

    def set_input_types(self, *specs: InputSpec) -> "GraphBuilder":
        for name, spec in zip(self.input_names, specs):
            self.input_specs[name] = spec
        return self

    def add_layer(self, name: str, layer: Layer, *inputs: str) -> "GraphBuilder":
        if name in self.nodes or name in self.input_names:
            raise ValueError(f"duplicate node name {name!r}")
        for inp in inputs:
            if inp not in self.nodes and inp not in self.input_names:
                raise ValueError(f"layer {name!r}: unknown input {inp!r}")
        self.nodes[name] = Node(name=name, layer=layer, inputs=tuple(inputs))
        return self

    add_vertex = add_layer  # Merge etc. are layers with has_params=False

    def input_preprocessor(self, layer_name: str, preproc) -> "GraphBuilder":
        self._preprocessors[layer_name] = preproc
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self.output_names = list(names)
        return self

    # -- shape/config resolution -------------------------------------------

    def _infer_input_shape(self, input_name: str) -> Tuple[int, ...]:
        """DL4J infers input size from the first consumer's nIn when no
        InputType is given (the insurance dis graph does this,
        dl4jGANInsurance.java:110-144)."""
        for node in self.nodes.values():
            if input_name in node.inputs:
                n_in = getattr(node.layer, "n_in", None)
                if n_in is None:
                    n_in = getattr(node.layer, "n", None)
                if n_in is not None:
                    return (int(n_in),)
        raise ValueError(
            f"input {input_name!r}: no InputType set and no consumer declares nIn"
        )

    def build(self) -> "ComputationGraph":
        if not self.output_names:
            raise ValueError("set_outputs() not called")
        shapes: Dict[str, Tuple[int, ...]] = {}
        for inp in self.input_names:
            spec = self.input_specs.get(inp)
            if spec is None:
                spec = InputSpec.feed_forward(self._infer_input_shape(inp)[0])
                self.input_specs[inp] = spec
            shapes[inp] = spec.node_shape()

        resolved: Dict[str, Node] = {}
        for name, node in self.nodes.items():
            layer = node.layer.resolved(self.default_activation, self.default_updater)
            if layer.weight_init == "xavier":
                layer = dataclasses.replace(layer, weight_init=self.weight_init)
            pre = self._preprocessors.get(name)
            in_shapes = [shapes[i] for i in node.inputs]
            if layer.multi_input:
                if pre is not None:
                    raise ValueError(
                        f"vertex {name!r}: preprocessors are not supported "
                        "on multi-input vertices (attach one to the "
                        "consuming layer instead)")
                in_shape: Union[Tuple[int, ...], List[Tuple[int, ...]]] = in_shapes
            else:
                if len(in_shapes) != 1:
                    raise ValueError(f"layer {name!r} expects exactly one input")
                in_shape = in_shapes[0]
                if pre is not None:
                    in_shape = pre.out_shape(in_shape)
            out_shape = layer.out_shape(in_shape)
            resolved[name] = Node(
                name=name,
                layer=layer,
                inputs=node.inputs,
                preprocessor=pre,
                in_shape=in_shape,
                out_shape=out_shape,
            )
            shapes[name] = out_shape

        return ComputationGraph(
            nodes=resolved,
            input_names=list(self.input_names),
            input_specs=dict(self.input_specs),
            output_names=list(self.output_names),
            seed=self.seed,
            l2=self.l2,
            clip_threshold=self.clip_threshold,
        )


class ComputationGraph:
    """The runnable graph: topology + params + updater state."""

    def __init__(
        self,
        nodes: Dict[str, Node],
        input_names: List[str],
        input_specs: Dict[str, InputSpec],
        output_names: List[str],
        seed: int,
        l2: float,
        clip_threshold: Optional[float],
        frozen: Optional[frozenset] = None,
    ):
        self.nodes = nodes
        self.input_names = input_names
        self.input_specs = input_specs
        self.output_names = output_names
        self.seed = seed
        self.l2 = l2
        self.clip_threshold = clip_threshold
        self.frozen = frozenset(frozen or ())
        self.updater = GraphUpdater(
            {
                name: node.layer.updater
                for name, node in nodes.items()
                if node.layer.has_params and name not in self.frozen
            },
            l2=l2,
            clip_threshold=clip_threshold,
        )
        self.params: Dict[str, Dict[str, jax.Array]] = {}
        self.opt_state: Dict[str, Dict[str, jax.Array]] = {}
        self.score: float = float("nan")
        self._step_rng = prng.stream(prng.root_key(seed), "graph-step")
        self._step_count = 0
        self.listeners: List = []  # DL4J TrainingListener surface
        self._jit_infer = jax.jit(functools.partial(self._forward_outputs, train=False))
        self._jit_fit = jax.jit(self._train_step)
        self._jit_score = jax.jit(self._score)

    # -- init ---------------------------------------------------------------

    def init(self, seed: Optional[int] = None) -> "ComputationGraph":
        """Deterministic per-layer init: key folded per layer name, so two
        graphs built with the same seed and layer shapes get identical params
        for identically-named layers (the reference relies on same-seed init
        across its three graphs)."""
        key = prng.root_key(self.seed if seed is None else seed)
        params = {}
        for name, node in self.nodes.items():
            if node.layer.has_params:
                params[name] = node.layer.init(prng.stream(key, name), node.in_shape)
            else:
                params[name] = {}
        self.params = params
        self.opt_state = self.updater.init(params)
        return self

    # -- forward ------------------------------------------------------------

    def _forward(self, params, inputs: Dict[str, jax.Array], train: bool, rng,
                 axis_name: Optional[str] = None):
        """Pure forward over the DAG in insertion (topological) order.

        Returns (values, state_updates): all node outputs by name, plus BN
        running-stat updates produced by train-mode layers.  ``axis_name``
        enables cross-replica sync-BN under shard_map (see ops/batchnorm.py).
        """
        from gan_deeplearning4j_tpu.graph.layers import (
            BatchNorm,
            ConditionalBatchNorm,
        )
        from gan_deeplearning4j_tpu.runtime import backend

        # full mixed precision (backend.compute_bf16, the TPU fast mode):
        # run layer math with bf16 params/activations; BatchNorm layers are
        # carved out (f32 params, f32-upcast input) so batch statistics and
        # the running-stat EMAs never round through bf16.  Gradients flow
        # through the casts back to the f32 master params; resolved at
        # TRACE time like matmul_bf16.
        mp = backend.config().compute_bf16
        bf16 = jnp.bfloat16

        def down(t):
            return jax.tree.map(
                lambda a: a.astype(bf16)
                if getattr(a, "dtype", None) == jnp.float32 else a, t)

        values: Dict[str, jax.Array] = {}
        for inp in self.input_names:
            x = inputs[inp]
            spec = self.input_specs[inp]
            if spec.kind == "cnn_flat":
                h, w, c = spec.shape
                x = x.reshape(x.shape[0], c, h, w)
            values[inp] = down(x) if mp else x
        state_updates: Dict[str, Dict[str, jax.Array]] = {}
        for name, node in self.nodes.items():
            is_bn = isinstance(node.layer, (BatchNorm, ConditionalBatchNorm))
            if node.layer.multi_input:
                x = [values[i] for i in node.inputs]
                if mp and is_bn:
                    x = [x[0].astype(jnp.float32)] + x[1:]
            else:
                x = values[node.inputs[0]]
                if node.preprocessor is not None:
                    x = node.preprocessor(x)
                if mp and is_bn:
                    x = x.astype(jnp.float32)
            layer_train = train and name not in self.frozen
            layer_rng = prng.stream(rng, name) if rng is not None else None
            p = params[name]
            if mp and not is_bn:
                p = down(p)
            y, upd = node.layer.apply(p, x, layer_train, layer_rng,
                                      axis_name=axis_name)
            if mp and getattr(y, "dtype", None) == jnp.float32:
                y = y.astype(bf16)
            if upd:
                state_updates[name] = upd
            values[name] = y
        return values, state_updates

    def _forward_outputs(self, params, inputs, rng=None, train: bool = False):
        values, _ = self._forward(params, inputs, train, rng)
        return [values[name] for name in self.output_names]

    def output(self, *xs: jax.Array, params=None) -> List[jax.Array]:
        """Inference forward (running BN stats, no dropout) — DL4J
        ``ComputationGraph.output``.  Returns a list, one per output layer."""
        inputs = dict(zip(self.input_names, xs))
        return self._jit_infer(params if params is not None else self.params, inputs)

    def feed_forward(self, *xs: jax.Array) -> Dict[str, jax.Array]:
        """All intermediate activations by layer name (inference mode)."""
        inputs = dict(zip(self.input_names, xs))
        values, _ = self._forward(self.params, inputs, False, None)
        return values

    # -- training -----------------------------------------------------------

    def _loss(self, outputs: Dict[str, jax.Array], labels: Dict[str, jax.Array]):
        total = 0.0
        for name in self.output_names:
            node = self.nodes[name]
            loss_name = getattr(node.layer, "loss", "mse")
            # f32 loss always: under compute_bf16 the head's probabilities
            # arrive bf16 and the log/reduction must not round further
            # (a no-op cast in the default f32 mode)
            total = total + loss_lib.get(loss_name)(
                outputs[name].astype(jnp.float32), labels[name])
        return total

    def _train_step(self, params, opt_state, rng, inputs, labels, reduce=None,
                    axis_name=None, telemetry=False):
        """One optimization step.  ``reduce`` is the cross-replica hook the
        distributed layer injects (pmean of loss/BN-stats/grads inside
        shard_map) so single-device and DP steps share one source of truth;
        ``axis_name`` additionally makes BN use global-batch stats (sync-BN).
        ``telemetry`` adds a fourth return: the in-graph numerics block
        (grad/param norms, update ratio, NaN/Inf count —
        telemetry/ingraph.py), computed from the reduced grads so its
        values are replica-identical under a mesh."""
        def loss_fn(p):
            values, state_updates = self._forward(p, inputs, True, rng, axis_name)
            outputs = {n: values[n] for n in self.output_names}
            return self._loss(outputs, labels), state_updates

        (loss, state_updates), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        if reduce is not None:
            loss, state_updates, grads = reduce(loss, state_updates, grads)
        new_params, new_opt_state = self.updater.apply(params, grads, opt_state)
        for lname, upd in state_updates.items():
            merged = dict(new_params[lname])
            merged.update(upd)
            new_params[lname] = merged
        if telemetry:
            from gan_deeplearning4j_tpu.telemetry import ingraph

            tel = ingraph.graph_telemetry(params, new_params, grads, loss)
            return new_params, new_opt_state, loss, tel
        return new_params, new_opt_state, loss

    def _score(self, params, inputs, labels):
        values, _ = self._forward(params, inputs, False, None)
        return self._loss({n: values[n] for n in self.output_names}, labels)

    def score_on(self, features, labels) -> float:
        """Inference-mode loss on a batch (no update, running BN stats, no
        dropout) — DL4J ``ComputationGraph.score(DataSet)``."""
        inputs = (
            features if isinstance(features, dict)
            else dict(zip(self.input_names, [features]))
        )
        label_map = (
            labels if isinstance(labels, dict)
            else dict(zip(self.output_names, [labels]))
        )
        return float(self._jit_score(self.params, inputs, label_map))

    def fit(self, features, labels) -> float:
        """One optimization step on a batch — the unit the reference's
        ``SparkComputationGraph.fit(rdd)`` reduces to per worker.  For the
        distributed version see parallel/data_parallel.py."""
        inputs = (
            features if isinstance(features, dict)
            else dict(zip(self.input_names, [features]))
        )
        label_map = (
            labels if isinstance(labels, dict)
            else dict(zip(self.output_names, [labels]))
        )
        self._step_count += 1
        rng = jax.random.fold_in(self._step_rng, self._step_count)
        self.params, self.opt_state, loss = self._jit_fit(
            self.params, self.opt_state, rng, inputs, label_map
        )
        self.score = loss
        for listener in self.listeners:
            listener.iteration_done(self, self._step_count, loss)
        return loss

    def fit_iterator(self, iterator, epochs: int = 1) -> float:
        """DL4J ``ComputationGraph.fit(DataSetIterator, numEpochs)``:
        sweep the iterator ``epochs`` times (reset between epochs, like
        DL4J), one optimization step per batch.  Returns the final
        batch's loss; listeners fire per step as with ``fit``."""
        if epochs < 1:
            raise ValueError(f"epochs must be >= 1, got {epochs}")
        loss = None
        for epoch in range(epochs):
            # DL4J tolerates non-resettable streaming iterators for a
            # single epoch (resetSupported() == false); only a re-sweep
            # REQUIRES reset
            if epoch > 0 or hasattr(iterator, "reset"):
                iterator.reset()
            for ds in iterator:
                loss = self.fit(ds.features, ds.labels)
        if loss is None:
            raise ValueError("iterator produced no batches")
        return loss

    def evaluate(self, iterator, num_classes: Optional[int] = None):
        """DL4J ``ComputationGraph.evaluate(DataSetIterator)``: sweep the
        iterator in inference mode and accumulate a confusion-matrix
        ``Evaluation`` (eval/evaluation.py).  The iterator is reset
        before and after, like DL4J.  ``num_classes`` defaults to the
        label width (binary for a single sigmoid column)."""
        from gan_deeplearning4j_tpu.eval.evaluation import Evaluation

        iterator.reset()
        evaluation = None
        for ds in iterator:
            preds = self.output(ds.features)[0]
            if evaluation is None:
                # class count: explicit > one-hot label width > model
                # output width (covers class-id label columns for
                # multi-class models) > binary sigmoid column
                y = ds.labels
                if num_classes:
                    n = num_classes
                elif y.ndim == 2 and y.shape[1] > 1:
                    n = y.shape[1]
                elif preds.ndim == 2 and preds.shape[1] > 1:
                    n = preds.shape[1]
                else:
                    n = 2
                evaluation = Evaluation(n)
            evaluation.eval(ds.labels, preds)
        iterator.reset()
        if evaluation is None:
            raise ValueError("iterator produced no batches")
        return evaluation

    def set_listeners(self, *listeners) -> "ComputationGraph":
        """DL4J ``setListeners`` (replaces): listeners get
        ``iteration_done(model, iteration, score)`` after each eager
        ``fit``; score arrives as a device scalar (see utils/listeners.py
        for the readback-cost contract)."""
        self.listeners = list(listeners)
        return self

    def add_listeners(self, *listeners) -> "ComputationGraph":
        self.listeners.extend(listeners)
        return self

    # -- param access (the GAN protocol's weight-sync surface) ---------------

    def get_param(self, layer: str, name: str) -> jax.Array:
        return self.params[layer][name]

    def set_param(self, layer: str, name: str, value: jax.Array) -> None:
        new_layer = dict(self.params[layer])
        new_layer[name] = value
        self.params = {**self.params, layer: new_layer}

    def get_layer_params(self, layer: str) -> Dict[str, jax.Array]:
        return dict(self.params[layer])

    def set_layer_params(self, layer: str, values: Dict[str, jax.Array]) -> None:
        new_layer = dict(self.params[layer])
        new_layer.update(values)
        self.params = {**self.params, layer: new_layer}

    def num_params(self) -> int:
        return sum(
            int(v.size) for lp in self.params.values() for v in lp.values()
        )

    def summary(self) -> str:
        """DL4J ``summary()`` equivalent — the reference prints this after
        every init as its de-facto shape test (SURVEY.md §4.1)."""
        lines = ["=" * 76]
        lines.append(f"{'Layer (type)':<40}{'Out shape':<20}{'Params':>10}")
        lines.append("-" * 76)
        for inp in self.input_names:
            spec = self.input_specs[inp]
            lines.append(f"{inp + ' (Input/' + spec.kind + ')':<40}{str(spec.node_shape()):<20}{0:>10}")
        total = 0
        for name, node in self.nodes.items():
            n = sum(int(v.size) for v in self.params.get(name, {}).values())
            total += n
            frozen = " [frozen]" if name in self.frozen else ""
            lines.append(
                f"{name + ' (' + type(node.layer).__name__ + ')' + frozen:<40}"
                f"{str(node.out_shape):<20}{n:>10}"
            )
        lines.append("-" * 76)
        lines.append(f"Total params: {total}")
        lines.append("=" * 76)
        return "\n".join(lines)
