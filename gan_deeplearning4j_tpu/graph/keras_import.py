"""Keras model import — the `deeplearning4j-modelimport` equivalent.

The reference classpath carries DL4J's Keras importer + HDF5
(`dl4jGAN.iml` hdf5 entries; unused by the mains — VERDICT r2 missing-#4
recorded it out of scope, this module closes the row properly).  Like
DL4J's ``KerasModelImport.importKerasSequentialModelAndWeights``, it
turns a saved Keras model file into a native ``ComputationGraph`` with
the weights copied over, so downstream code (transfer surgery,
serialization, ParallelInference, the trainers) sees no difference from
a natively-built graph.

Scope mirrors the framework's layer set: Sequential AND functional
models (multi-input DAGs included — r4 closes VERDICT r3 weak-#7) of
Dense / Conv2D / Conv2DTranspose / BatchNormalization / Dropout /
MaxPooling2D / UpSampling2D / Flatten / Reshape (the Dense→(h,w,c)
generator seam) / Activation / InputLayer, plus the merge layers
Concatenate (→ ``Merge``; feature/channel axis only) and
Add/Average/Maximum/Subtract (→ ``ElementWise``) — enough to import the
cGAN generator pattern (Concatenate of z + one-hot label).
channels_last Keras convs convert to this framework's NCHW layout:

  - Conv kernels ``[kh, kw, in, out]`` -> ``[out, in, kh, kw]``.
  - The Dense layer that follows a Flatten has its kernel's input axis
    re-ordered from Keras's ``(h, w, c)`` flatten order to the NCHW
    ``(c, h, w)`` order this framework flattens in — the same fixup
    DL4J's importer applies.
  - An imported graph therefore takes NCHW input; use
    ``jnp.transpose(x, (0, 3, 1, 2))`` on channels_last batches.

Parity is proven in ``tests/test_keras_import.py`` by comparing forward
outputs against Keras itself on random inputs (both .h5 and .keras
formats).  Import is inference-exact; training uses this framework's
updaters (pass ``updater=`` — DL4J's ``enforceTrainingConfig=False``
behavior).

Keras/TensorFlow are NOT dependencies of this package: they are imported
lazily at call time with a clear error if absent.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from gan_deeplearning4j_tpu.graph.graph import GraphBuilder, InputSpec
from gan_deeplearning4j_tpu.graph.preprocessors import FeedForwardToCnn
from gan_deeplearning4j_tpu.graph.layers import (
    BatchNorm,
    Conv2D,
    ConvTranspose2D,
    Dense,
    Dropout,
    MaxPool2D,
    Upsampling2D,
)

# Keras activation identifier -> ops.activations name.  Only mappings
# whose DEFINITIONS match exactly are listed: Keras 'leaky_relu' (slope
# 0.2 vs DL4J's 0.01), 'hard_sigmoid' (relu6(x+3)/6 vs clip(0.2x+0.5))
# and 'gelu' (exact vs tanh-approximate) differ and must raise, not
# silently approximate.
_ACT = {
    "linear": "identity",
    "relu": "relu",
    "tanh": "tanh",
    "sigmoid": "sigmoid",
    "softmax": "softmax",
    "elu": "elu",
    "selu": "selu",
    "swish": "swish",
    "silu": "swish",
    "softplus": "softplus",
    "softsign": "softsign",
}


def _act_name(keras_act) -> str:
    name = getattr(keras_act, "__name__", None) or str(keras_act)
    try:
        mapped = _ACT[name]
    except KeyError:
        raise NotImplementedError(f"unsupported Keras activation: {name!r}")
    return mapped


def _pair(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (int(v), int(v))


def _layer_inputs(kl) -> list:
    """A layer's input tensors as a list (Keras returns a bare tensor
    for single-input layers)."""
    try:
        k_in = kl.input
    except Exception as e:
        raise NotImplementedError(
            f"layer {kl.name}: cannot resolve inputs (layer reused at "
            "multiple call sites?)") from e
    return list(k_in) if isinstance(k_in, (list, tuple)) else [k_in]


def _kernel_bias(kl, cfg, bias_axis: int = -1):
    """(kernel, bias) with a zeros bias when ``use_bias=False``.
    ``bias_axis`` names the kernel axis holding the output count: the
    last for Dense ``(in, out)`` and Conv2D hwio ``(h, w, in, out)``,
    axis 2 for Conv2DTranspose's reversed ``(h, w, out, in)``."""
    weights = kl.get_weights()
    kernel = np.asarray(weights[0])
    if cfg.get("use_bias", True):
        return kernel, np.asarray(weights[1])
    return kernel, np.zeros(kernel.shape[bias_axis], np.float32)


def _same_padding(kernel, stride, what):
    """Keras 'same' -> symmetric explicit padding; only the symmetric
    cases (odd kernel, stride 1) translate exactly."""
    kh, kw = kernel
    if stride != (1, 1) or kh % 2 == 0 or kw % 2 == 0:
        raise NotImplementedError(
            f"{what}: padding='same' with stride {stride} / kernel "
            f"{kernel} pads asymmetrically in Keras; import supports "
            "'valid', or 'same' with stride 1 and odd kernels")
    return (kh // 2, kw // 2)


def import_keras(path_or_model, *, updater=None, seed: int = 666,
                 name_prefix: str = ""):
    """Import a saved Keras model (``.h5`` or ``.keras``; or a live
    ``keras.Model``) as a ``ComputationGraph`` with identical inference
    behavior (channels-last convs re-laid to NCHW).

    ``updater``: optimizer for subsequent ``fit`` calls (imported graphs
    are inference-exact; training config is NOT imported, as with DL4J's
    ``enforceTrainingConfig=False``).
    """
    try:
        import keras
    except ImportError as e:  # pragma: no cover - env-dependent
        raise ImportError(
            "Keras import needs the 'keras' package (with h5py for .h5 "
            "files); it is not a dependency of this framework") from e

    model = (path_or_model if isinstance(path_or_model, keras.Model)
             else keras.models.load_model(path_or_model, compile=False))

    builder = GraphBuilder(seed=seed, activation="identity")

    layers = [l for l in model.layers
              if l.__class__.__name__ != "InputLayer"]

    # -- model inputs (functional models may have several) ---------------
    def _producer(tensor):
        hist = getattr(tensor, "_keras_history", None)
        op = getattr(hist, "operation", None) if hist else None
        if op is None:
            raise NotImplementedError(
                "tensor without keras history — unsupported model graph")
        return op

    input_ops, input_specs, input_names = [], [], []
    for i, t in enumerate(model.inputs):
        op = _producer(t)
        in_shape = tuple(t.shape)[1:]
        if len(in_shape) == 3:
            h, w, c = in_shape
            input_specs.append(InputSpec.convolutional(c, h, w))
        elif len(in_shape) == 1:
            input_specs.append(InputSpec.feed_forward(in_shape[0]))
        else:
            raise NotImplementedError(f"unsupported input rank: {in_shape}")
        iname = name_prefix + (op.name if len(model.inputs) > 1 else "in")
        input_ops.append(op)
        input_names.append(iname)
    builder.add_inputs(*input_names)
    builder.set_input_types(*input_specs)

    # -- DAG bookkeeping --------------------------------------------------
    # keras operation (layer / InputLayer) id -> graph node name holding
    # its output.  Virtual ops (Flatten/Reshape/Activation) alias their
    # producer's node; their effect is recorded in the side tables below.
    op_node = {id(op): nm for op, nm in zip(input_ops, input_names)}
    weight_ops = []      # (node_name, {param: ndarray}) applied after init
    by_node = {}         # node name -> weight dict (for the Reshape fixup)
    flatten_from = {}    # keras op id -> (h, w, c) a Flatten recorded
    preproc_from = {}    # keras op id -> FeedForwardToCnn a Reshape recorded
    nodes = {}           # node name -> our layer object

    # consumer counts gate Activation folding and the Reshape/Flatten
    # aliases: mutating a producer consumed elsewhere too would corrupt
    # the other branch
    n_consumers = {}
    for kl in layers:
        for t in _layer_inputs(kl):
            n_consumers[id(_producer(t))] = n_consumers.get(
                id(_producer(t)), 0) + 1
    for t in model.outputs:
        n_consumers[id(_producer(t))] = n_consumers.get(
            id(_producer(t)), 0) + 1

    def fresh(name):
        n = name_prefix + name
        return n if n not in nodes and n not in input_names \
            else f"{n}_{len(nodes)}"

    def node_of(kl, what):
        try:
            return op_node[id(kl)]
        except KeyError:
            raise NotImplementedError(
                f"{what}: input produced by an unprocessed or unsupported "
                f"layer {getattr(kl, 'name', kl)!r} — layers must arrive "
                "in topological order") from None

    for kl in layers:
        kind = kl.__class__.__name__
        cfg = kl.get_config()
        producers = [_producer(t) for t in _layer_inputs(kl)]

        if kind in ("Concatenate", "Add", "Average", "Maximum", "Subtract"):
            from gan_deeplearning4j_tpu.graph.layers import (
                ElementWise,
                Merge,
            )

            in_nodes = [node_of(p, kl.name) for p in producers]
            for p in producers:
                if id(p) in flatten_from or id(p) in preproc_from:
                    raise NotImplementedError(
                        f"{kl.name}: merge of a Flatten/Reshape output is "
                        "not supported")
            if kind == "Concatenate":
                axis = cfg.get("axis", -1)
                ranks = {len(tuple(t.shape)) for t in _layer_inputs(kl)}
                if ranks == {2} and axis not in (-1, 1):
                    raise NotImplementedError(
                        f"{kl.name}: Concatenate axis {axis} on 2-D input")
                if ranks == {4} and axis not in (-1, 3):
                    # channels_last channel concat -> our NCHW axis 1
                    raise NotImplementedError(
                        f"{kl.name}: Concatenate axis {axis} on 4-D input")
                layer = Merge()
            else:
                layer = ElementWise(op={"Add": "add", "Average": "average",
                                        "Maximum": "max",
                                        "Subtract": "subtract"}[kind])
            name = fresh(kl.name)
            builder.add_layer(name, layer, *in_nodes)
            nodes[name] = layer
            op_node[id(kl)] = name
            continue

        if len(producers) != 1:
            raise NotImplementedError(
                f"layer {kl.name}: multi-input {kind} is not supported")
        producer = producers[0]
        prev = node_of(producer, kl.name)

        if kind == "Flatten":
            if id(producer) in flatten_from or id(producer) in preproc_from:
                raise NotImplementedError(
                    f"{kl.name}: Flatten after Flatten/Reshape")
            shape = tuple(_layer_inputs(kl)[0].shape)[1:]
            op_node[id(kl)] = prev  # alias: the fixup happens at the Dense
            if len(shape) == 3:
                flatten_from[id(kl)] = shape
            continue
        if kind == "Reshape":
            # the DCGAN-generator seam: Dense -> Reshape((h, w, c)) ->
            # conv stack.  This framework's FeedForwardToCnn interprets
            # the flat vector in (c, h, w) order, so permute the
            # PRODUCING Dense's output columns (and bias) from Keras's
            # (h, w, c) order — the Flatten fixup in reverse.
            tgt = tuple(cfg["target_shape"])
            if len(tgt) != 3:
                raise NotImplementedError(
                    f"{kl.name}: Reshape to non-(h, w, c) {tgt}")
            h, w, c = tgt
            if (id(producer) in flatten_from or id(producer) in preproc_from
                    or not isinstance(nodes.get(prev), Dense)
                    or n_consumers.get(id(producer), 0) > 1):
                raise NotImplementedError(
                    f"{kl.name}: Reshape must directly follow a Dense "
                    "layer with no other consumers (the supported "
                    "generator seam)")
            wd = by_node[prev]
            kern, bias = wd["W"], wd["b"]
            if kern.shape[1] != h * w * c:
                raise ValueError(
                    f"{kl.name}: Reshape target {tgt} does not match the "
                    f"preceding Dense width {kern.shape[1]}")
            wd["W"] = (kern.reshape(-1, h, w, c).transpose(0, 3, 1, 2)
                       .reshape(kern.shape[0], h * w * c))
            wd["b"] = bias.reshape(h, w, c).transpose(2, 0, 1).ravel()
            op_node[id(kl)] = prev
            preproc_from[id(kl)] = FeedForwardToCnn(h, w, c)
            continue
        if kind == "Activation":
            act = _act_name(cfg["activation"])
            target = nodes.get(prev)
            # fold ONLY onto layers whose apply() runs self._act —
            # pool/dropout/upsample ignore .activation entirely, so
            # folding there would silently drop the nonlinearity; a
            # producer with other consumers would leak the activation
            # into their branch
            if (not isinstance(target, (Dense, Conv2D, BatchNorm))
                    or target.activation not in (None, "identity")
                    or n_consumers.get(id(producer), 0) > 1):
                raise NotImplementedError(
                    "standalone Activation layer must directly follow a "
                    "linear Dense/Conv2D/BatchNormalization layer with no "
                    "other consumers")
            target.activation = act
            op_node[id(kl)] = prev
            continue

        consumed_flatten = flatten_from.get(id(producer))
        pending_preproc = preproc_from.get(id(producer))
        name = fresh(kl.name)
        if kind == "Dense":
            kernel, bias = _kernel_bias(kl, cfg)
            if consumed_flatten is not None:
                fh, fw, fc = consumed_flatten
                # Keras flattened (h, w, c); this framework flattens (c, h, w)
                kernel = (kernel.reshape(fh, fw, fc, -1)
                          .transpose(2, 0, 1, 3)
                          .reshape(fh * fw * fc, -1))
            layer = Dense(n_out=cfg["units"],
                          activation=_act_name(cfg["activation"]),
                          updater=updater)
            weight_ops.append((name, {"W": kernel, "b": bias}))
        elif kind == "Conv2D":
            if cfg.get("data_format") not in (None, "channels_last"):
                raise NotImplementedError("channels_first Keras convs")
            if _pair(cfg.get("dilation_rate", 1)) != (1, 1):
                raise NotImplementedError("dilated Keras convs")
            if cfg.get("groups", 1) != 1:
                raise NotImplementedError("grouped Keras convs")
            kernel = _pair(cfg["kernel_size"])
            stride = _pair(cfg["strides"])
            pad = ((0, 0) if cfg["padding"] == "valid"
                   else _same_padding(kernel, stride, kl.name))
            w, b = _kernel_bias(kl, cfg)
            w = w.transpose(3, 2, 0, 1)  # hwio -> oihw
            layer = Conv2D(kernel=kernel, stride=stride, padding=pad,
                           n_out=cfg["filters"],
                           activation=_act_name(cfg["activation"]),
                           updater=updater)
            weight_ops.append((name, {"W": w, "b": b}))
        elif kind == "Conv2DTranspose":
            # the DCGAN-generator layer.  Kernel layout is [kh, kw, OUT,
            # IN] (note: reversed vs Conv2D's [kh, kw, in, out]); Keras
            # 'same' upsamples to exactly h*s, which equals this
            # framework's (h-1)s - 2p + k at p = (k-s)/2 — exact only
            # when k-s is even (parity-tested vs Keras at ulp level).
            if cfg.get("data_format") not in (None, "channels_last"):
                raise NotImplementedError("channels_first Keras convs")
            if _pair(cfg.get("dilation_rate", 1)) != (1, 1):
                raise NotImplementedError("dilated transposed convs")
            if cfg.get("output_padding") not in (None, 0, (0, 0), [0, 0]):
                raise NotImplementedError("explicit output_padding")
            kernel = _pair(cfg["kernel_size"])
            stride = _pair(cfg["strides"])
            kh, kw_ = kernel
            sh, sw = stride
            if kh < sh or kw_ < sw:
                # k < s breaks both translations: 'same' would need
                # negative padding, 'valid' Keras output is in*s +
                # max(k-s, 0) vs this framework's (in-1)s + k
                raise NotImplementedError(
                    f"{kl.name}: Conv2DTranspose kernel {kernel} smaller "
                    f"than stride {stride}")
            if cfg["padding"] == "valid":
                pad = (0, 0)
            else:
                if (kh - sh) % 2 or (kw_ - sw) % 2:
                    raise NotImplementedError(
                        f"{kl.name}: Conv2DTranspose padding='same' with "
                        f"odd kernel-stride difference {kernel}/{stride} "
                        "pads asymmetrically in Keras")
                pad = ((kh - sh) // 2, (kw_ - sw) // 2)
            w, b = _kernel_bias(kl, cfg, bias_axis=2)  # [kh, kw, OUT, in]
            w = w.transpose(2, 3, 0, 1)  # hw-out-in -> [O, I, kh, kw]
            layer = ConvTranspose2D(kernel=kernel, stride=stride,
                                    padding=pad, n_out=cfg["filters"],
                                    activation=_act_name(cfg["activation"]),
                                    updater=updater)
            weight_ops.append((name, {"W": w, "b": b}))
        elif kind == "BatchNormalization":
            axis = cfg.get("axis", -1)
            axis = axis[0] if isinstance(axis, (list, tuple)) else axis
            if len(kl.output.shape) == 4 and axis not in (-1, 3):
                raise NotImplementedError("BatchNorm over a non-channel axis")
            weights = [np.asarray(a) for a in kl.get_weights()]
            # center/scale=False drop beta/gamma from get_weights();
            # synthesize the identity values (zeros beta, ones gamma) so
            # inference stays exact instead of mis-unpacking.
            it = iter(weights)
            g = next(it) if cfg.get("scale", True) else None
            b = next(it) if cfg.get("center", True) else None
            m, v = next(it), next(it)
            if g is None:
                g = np.ones_like(m)
            if b is None:
                b = np.zeros_like(m)
            layer = BatchNorm(decay=cfg["momentum"], eps=cfg["epsilon"],
                              updater=updater)
            weight_ops.append(
                (name, {"gamma": g, "beta": b, "mean": m, "var": v}))
        elif kind == "Dropout":
            layer = Dropout(rate=cfg["rate"])
        elif kind == "MaxPooling2D":
            if cfg["padding"] != "valid":
                raise NotImplementedError("MaxPooling2D padding='same'")
            layer = MaxPool2D(kernel=_pair(cfg["pool_size"]),
                              stride=_pair(cfg["strides"] or cfg["pool_size"]))
        elif kind == "UpSampling2D":
            size = _pair(cfg["size"])
            if size[0] != size[1]:
                raise NotImplementedError("non-square UpSampling2D")
            if cfg.get("interpolation", "nearest") != "nearest":
                # this framework's Upsampling2D is nearest-neighbor only;
                # importing a bilinear config would silently change
                # inference outputs (maxdiff ~0.37 measured), breaking
                # the module's inference-exactness contract.
                raise NotImplementedError(
                    f"{kl.name}: UpSampling2D interpolation="
                    f"{cfg['interpolation']!r}; only 'nearest' is exact")
            layer = Upsampling2D(size=size[0])
        else:
            raise NotImplementedError(
                f"unsupported Keras layer type: {kind} ({kl.name})")

        if consumed_flatten is not None and kind != "Dense":
            raise NotImplementedError(
                f"{kl.name}: only Dense may consume a Flatten output")
        builder.add_layer(name, layer, prev)
        if pending_preproc is not None:
            builder.input_preprocessor(name, pending_preproc)
        nodes[name] = layer
        op_node[id(kl)] = name
        if weight_ops and weight_ops[-1][0] == name:
            by_node[name] = weight_ops[-1][1]

    out_nodes = []
    for t in model.outputs:
        op = _producer(t)
        if id(op) in preproc_from or id(op) in flatten_from:
            raise NotImplementedError(
                "model ends on a Reshape/Flatten with no consumer")
        out_nodes.append(node_of(op, "model output"))
    builder.set_outputs(*out_nodes)
    graph = builder.build().init()
    for name, values in weight_ops:
        for pname, value in values.items():
            expect = graph.params[name][pname].shape
            if tuple(value.shape) != tuple(expect):
                raise ValueError(
                    f"{name}.{pname}: keras weight shape {value.shape} "
                    f"!= graph shape {expect}")
            graph.set_param(name, pname, np.asarray(value, np.float32))
    return graph
