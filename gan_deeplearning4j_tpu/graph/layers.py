"""Layer configuration classes for the named-layer graph API.

Covers every layer type the reference's graphs use (SURVEY.md §2a):
DenseLayer, ConvolutionLayer, SubsamplingLayer (max pool), BatchNormalization,
Upsampling2D, DropoutLayer, OutputLayer — plus ConvTranspose2D and Merge for
the roadmap model families (conditional GAN, WGAN-GP, CelebA DCGAN).

Each config is a plain dataclass with three pure methods:
  out_shape(in_shape)         -- shape inference (batch dim excluded; FF
                                 shapes are (n,), CNN shapes (c, h, w)),
                                 reproducing DL4J's Truncate conv arithmetic
  init(key, in_shape)         -- parameter pytree {name: array}, DL4J names
                                 (W, b, gamma, beta, mean, var) so the
                                 reference's getParam/setParam dance maps 1:1
  apply(params, x, train, rng)-- forward; returns (y, state_updates|None)

A layer's ``activation``/``updater`` of None inherits the graph-level default
(DL4J's NeuralNetConfiguration.Builder global settings,
dl4jGANComputerVision.java:117-125); the builder resolves these before the
graph is built.  Note: like the reference's author assumed
(dl4jGANInsurance.java:228 sets ELU explicitly on a BatchNormalization), the
BN layer applies its (inherited) activation after normalization.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from gan_deeplearning4j_tpu.ops import (
    activations as act_lib,
    batch_norm_inference,
    batch_norm_train,
    conv2d,
    conv2d_out_size,
    initializers,
    max_pool2d,
    upsample2d,
)
from gan_deeplearning4j_tpu.ops.dense import dense as dense_op, dropout as dropout_op
from gan_deeplearning4j_tpu.ops.upsample import conv_transpose2d
from gan_deeplearning4j_tpu.optim.rmsprop import RmsProp

Shape = Tuple[int, ...]
Params = Dict[str, jax.Array]


def _flat_size(shape: Shape) -> int:
    n = 1
    for s in shape:
        n *= s
    return n


def _mxu_bf16(layer_flag: Optional[bool]) -> bool:
    """Resolve a layer's bf16-matmul setting: an explicit layer flag wins;
    None follows the global runtime policy (backend.configure
    (matmul_bf16=True) — the TPU fast path, opt-in because it deviates
    from the reference's fixed float32).  Read at TRACE time: flip the
    policy before the first fit/compile."""
    if layer_flag is not None:
        return layer_flag
    from gan_deeplearning4j_tpu.runtime import backend

    return backend.config().matmul_bf16


def _as_ff(x: jax.Array) -> jax.Array:
    """Auto CnnToFeedForward: flatten trailing dims (DL4J inserts this
    preprocessor when a dense layer follows a conv stack)."""
    if x.ndim > 2:
        return x.reshape(x.shape[0], -1)
    return x


@dataclasses.dataclass
class Layer:
    """Base layer config."""

    activation: Optional[str] = None
    updater: Optional[RmsProp] = None
    weight_init: str = "xavier"

    @property
    def has_params(self) -> bool:
        return True

    @property
    def multi_input(self) -> bool:
        """Vertices taking a LIST of inputs (Merge, ElementWise)."""
        return False

    def resolved(self, default_activation: str, default_updater: Optional[RmsProp]):
        new = dataclasses.replace(self)
        if new.activation is None:
            new.activation = default_activation
        if new.updater is None:
            new.updater = default_updater
        return new

    def _act(self, x):
        return act_lib.get(self.activation or "identity")(x)

    def out_shape(self, in_shape: Shape) -> Shape:
        raise NotImplementedError

    def init(self, key: jax.Array, in_shape: Shape) -> Params:
        return {}

    def apply(self, params: Params, x, train: bool, rng, axis_name=None):
        raise NotImplementedError


@dataclasses.dataclass
class Dense(Layer):
    """DL4J DenseLayer (dl4jGANComputerVision.java:144-148).  W: [nIn, nOut]."""

    n_out: int = 0
    n_in: Optional[int] = None
    # None = follow the runtime policy (backend.configure(matmul_bf16=True));
    # True/False pin this layer regardless of policy
    bf16_matmul: Optional[bool] = None

    def out_shape(self, in_shape):
        return (self.n_out,)

    def init(self, key, in_shape):
        n_in = self.n_in if self.n_in is not None else _flat_size(in_shape)
        k_w, _ = jax.random.split(key)
        if self.weight_init == "xavier":
            w = initializers.xavier(k_w, (n_in, self.n_out), n_in, self.n_out)
        else:
            w = initializers.xavier_uniform(k_w, (n_in, self.n_out), n_in, self.n_out)
        return {"W": w, "b": initializers.zeros((self.n_out,))}

    def apply(self, params, x, train, rng, axis_name=None):
        x = _as_ff(x)
        return self._act(dense_op(
            x, params["W"], params["b"], bf16=_mxu_bf16(self.bf16_matmul))), None


@dataclasses.dataclass
class Output(Dense):
    """DL4J OutputLayer: a dense layer with a loss attached
    (dl4jGANComputerVision.java:150-155)."""

    loss: str = "xent"


@dataclasses.dataclass
class Conv2D(Layer):
    """DL4J ConvolutionLayer, Truncate mode.  W: [nOut, nIn, kh, kw] (OIHW)."""

    kernel: Sequence[int] = (3, 3)
    stride: Sequence[int] = (1, 1)
    padding: Sequence[int] = (0, 0)
    n_in: Optional[int] = None
    n_out: int = 0
    bf16_matmul: Optional[bool] = None  # None = runtime policy

    def out_shape(self, in_shape):
        c, h, w = in_shape
        kh, kw = self.kernel
        sh, sw = self.stride
        ph, pw = self.padding
        return (
            self.n_out,
            conv2d_out_size(h, kh, sh, ph),
            conv2d_out_size(w, kw, sw, pw),
        )

    def init(self, key, in_shape):
        n_in = self.n_in if self.n_in is not None else in_shape[0]
        kh, kw = self.kernel
        fan_in, fan_out = initializers.fan_in_out_conv(n_in, self.n_out, (kh, kw))
        k_w, _ = jax.random.split(key)
        w = initializers.xavier(k_w, (self.n_out, n_in, kh, kw), fan_in, fan_out)
        return {"W": w, "b": initializers.zeros((self.n_out,))}

    def apply(self, params, x, train, rng, axis_name=None):
        y = conv2d(x, params["W"], params["b"], self.stride, self.padding,
                   bf16=_mxu_bf16(self.bf16_matmul))
        return self._act(y), None


@dataclasses.dataclass
class ConvTranspose2D(Layer):
    """Real transposed conv, for roadmap DCGAN variants (not used by the
    reference, whose 'deconv' layers are upsample+conv — SURVEY.md §3.3)."""

    kernel: Sequence[int] = (4, 4)
    stride: Sequence[int] = (2, 2)
    padding: Sequence[int] = (1, 1)
    n_in: Optional[int] = None
    n_out: int = 0
    bf16_matmul: Optional[bool] = None  # None = runtime policy

    def out_shape(self, in_shape):
        c, h, w = in_shape
        kh, kw = self.kernel
        sh, sw = self.stride
        ph, pw = self.padding
        return (
            self.n_out,
            (h - 1) * sh - 2 * ph + kh,
            (w - 1) * sw - 2 * pw + kw,
        )

    def init(self, key, in_shape):
        n_in = self.n_in if self.n_in is not None else in_shape[0]
        kh, kw = self.kernel
        fan_in, fan_out = initializers.fan_in_out_conv(n_in, self.n_out, (kh, kw))
        k_w, _ = jax.random.split(key)
        w = initializers.xavier(k_w, (self.n_out, n_in, kh, kw), fan_in, fan_out)
        return {"W": w, "b": initializers.zeros((self.n_out,))}

    def apply(self, params, x, train, rng, axis_name=None):
        y = conv_transpose2d(x, params["W"], params["b"], self.stride,
                             self.padding, bf16=_mxu_bf16(self.bf16_matmul))
        return self._act(y), None


@dataclasses.dataclass
class MaxPool2D(Layer):
    """DL4J SubsamplingLayer(MAX) — e.g. the unusual 2x2 stride-1 pools
    (dl4jGANComputerVision.java:134-138)."""

    kernel: Sequence[int] = (2, 2)
    stride: Sequence[int] = (2, 2)

    @property
    def has_params(self):
        return False

    def out_shape(self, in_shape):
        c, h, w = in_shape
        kh, kw = self.kernel
        sh, sw = self.stride
        return (c, (h - kh) // sh + 1, (w - kw) // sw + 1)

    def apply(self, params, x, train, rng, axis_name=None):
        return max_pool2d(x, self.kernel, self.stride), None


@dataclasses.dataclass
class Upsampling2D(Layer):
    """DL4J Upsampling2D (dl4jGANComputerVision.java:191-192)."""

    size: int = 2

    @property
    def has_params(self):
        return False

    def out_shape(self, in_shape):
        c, h, w = in_shape
        return (c, h * self.size, w * self.size)

    def apply(self, params, x, train, rng, axis_name=None):
        return upsample2d(x, self.size), None


@dataclasses.dataclass
class BatchNorm(Layer):
    """DL4J BatchNormalization with stats-as-params (mean/var retrievable and
    settable by name — the GAN protocol's cross-graph BN sync,
    dl4jGANComputerVision.java:404-420, depends on this)."""

    n: Optional[int] = None
    decay: float = 0.9
    eps: float = 1e-5

    def out_shape(self, in_shape):
        return in_shape

    def _n(self, in_shape):
        if self.n is not None:
            return self.n
        # 4-D input: per-channel; FF input: per-feature.
        return in_shape[0] if len(in_shape) == 3 else _flat_size(in_shape)

    def init(self, key, in_shape):
        n = self._n(in_shape)
        return {
            "gamma": initializers.ones((n,)),
            "beta": initializers.zeros((n,)),
            "mean": initializers.zeros((n,)),
            "var": initializers.ones((n,)),
        }

    def apply(self, params, x, train, rng, axis_name=None):
        if train:
            from gan_deeplearning4j_tpu.ops import pallas as pallas_lib

            if x.ndim == 2 and pallas_lib.enabled():
                # fused Pallas path: BN + activation in one VMEM pass
                # (under SPMD the moments pmean across the mesh axis
                # between a moments kernel and an apply kernel — same
                # sync-BN semantics as the XLA path below)
                y, bmean, bvar = pallas_lib.fused_bn_act_train(
                    x, params["gamma"], params["beta"], self.eps,
                    self.activation or "identity", False, axis_name)
                return y, {
                    "mean": self.decay * params["mean"] + (1 - self.decay) * bmean,
                    "var": self.decay * params["var"] + (1 - self.decay) * bvar,
                }
            y, new_mean, new_var = batch_norm_train(
                x, params["gamma"], params["beta"], params["mean"], params["var"],
                self.decay, self.eps, axis_name=axis_name,
            )
            return self._act(y), {"mean": new_mean, "var": new_var}
        y = batch_norm_inference(
            x, params["gamma"], params["beta"], params["mean"], params["var"], self.eps
        )
        return self._act(y), None


@dataclasses.dataclass
class Dropout(Layer):
    """DL4J DropoutLayer.  The reference's ``new DropoutLayer()`` has DL4J's
    unset default probability => identity (SURVEY.md appendix quirk); rate=0.0
    reproduces that."""

    rate: float = 0.0

    @property
    def has_params(self):
        return False

    def out_shape(self, in_shape):
        return in_shape

    def apply(self, params, x, train, rng, axis_name=None):
        return dropout_op(x, self.rate, rng, train), None


@dataclasses.dataclass
class Merge(Layer):
    """DL4J MergeVertex equivalent: concat along the feature/channel axis.
    Needed by the conditional-GAN roadmap config (label conditioning)."""

    @property
    def has_params(self):
        return False

    @property
    def multi_input(self):
        return True

    def out_shape(self, in_shape):
        # in_shape is a list of shapes for multi-input vertices.
        shapes = in_shape
        first = shapes[0]
        total = sum(s[0] for s in shapes)
        return (total,) + tuple(first[1:])

    def apply(self, params, xs, train, rng, axis_name=None):
        axis = 1 if xs[0].ndim > 1 else 0
        return jnp.concatenate(xs, axis=axis), None


@dataclasses.dataclass
class ElementWise(Layer):
    """DL4J ElementWiseVertex equivalent: combine same-shaped inputs
    elementwise.  ``op``: "add" | "product" | "subtract" | "average" |
    "max" (subtract requires exactly two inputs, like DL4J).

    DL4J's vertex applies no activation; the explicit "identity" default
    pins that even under a graph-level default activation (an activation
    passed explicitly still applies, as a convenience DL4J lacks)."""

    op: str = "add"
    activation: Optional[str] = "identity"

    @property
    def has_params(self):
        return False

    @property
    def multi_input(self):
        return True

    def out_shape(self, in_shape):
        shapes = in_shape
        if self.op == "subtract" and len(shapes) != 2:
            raise ValueError("subtract takes exactly two inputs")
        first = tuple(shapes[0])
        for s in shapes[1:]:
            if tuple(s) != first:
                raise ValueError(
                    f"ElementWise inputs must share a shape; got {shapes}")
        return first

    def apply(self, params, xs, train, rng, axis_name=None):
        if self.op == "add":
            out = sum(xs[1:], xs[0])
        elif self.op == "product":
            out = xs[0]
            for x in xs[1:]:
                out = out * x
        elif self.op == "subtract":
            if len(xs) != 2:
                raise ValueError("subtract takes exactly two inputs")
            out = xs[0] - xs[1]
        elif self.op == "average":
            out = sum(xs[1:], xs[0]) / len(xs)
        elif self.op == "max":
            out = xs[0]
            for x in xs[1:]:
                out = jnp.maximum(out, x)
        else:
            raise ValueError(f"unknown ElementWise op {self.op!r}")
        return self._act(out), None


@dataclasses.dataclass
class ConditionalBatchNorm(Layer):
    """Conditional BatchNorm (Dumoulin et al. 2017; Miyato et al. 2018's
    cGAN generator norm): batch-stat normalization with PER-CLASS
    gamma/beta selected by a one-hot condition — the standard structural
    fix for conditional-GAN class collapse (the shared affine of plain
    BN lets the generator ignore the label; per-class affines make the
    conditioning load-bearing).  Multi-input vertex: (x, onehot_label).
    Statistics are class-agnostic (one running mean/var, like plain BN);
    at init every class row is gamma=1/beta=0, i.e. exactly plain BN."""

    num_classes: int = 0
    n: Optional[int] = None
    decay: float = 0.9
    eps: float = 1e-5

    @property
    def multi_input(self):
        return True

    def out_shape(self, in_shape):
        return tuple(in_shape[0])

    def _n(self, x_shape):
        if self.n is not None:
            return self.n
        return x_shape[0] if len(x_shape) == 3 else _flat_size(x_shape)

    def init(self, key, in_shape):
        n = self._n(in_shape[0])
        k = self.num_classes
        if k <= 0:
            raise ValueError("ConditionalBatchNorm needs num_classes > 0")
        return {
            "gamma": initializers.ones((k, n)),
            "beta": initializers.zeros((k, n)),
            "mean": initializers.zeros((n,)),
            "var": initializers.ones((n,)),
        }

    def apply(self, params, xs, train, rng, axis_name=None):
        from gan_deeplearning4j_tpu.ops.batchnorm import (
            batch_norm_inference_cond,
            batch_norm_train_cond,
        )

        x, y = xs
        gamma_b = y @ params["gamma"]  # [B, C]: one-hot row select
        beta_b = y @ params["beta"]
        if train:
            out, new_mean, new_var = batch_norm_train_cond(
                x, gamma_b, beta_b, params["mean"], params["var"],
                self.decay, self.eps, axis_name=axis_name)
            return self._act(out), {"mean": new_mean, "var": new_var}
        return self._act(batch_norm_inference_cond(
            x, gamma_b, beta_b, params["mean"], params["var"],
            self.eps)), None


@dataclasses.dataclass
class MinibatchStdDev(Layer):
    """Minibatch standard deviation (Karras et al. 2018): append one
    channel/feature holding the mean of per-position stddevs over small
    CONTIGUOUS groups of samples (StyleGAN's group_size=4), giving the
    discriminator a direct view of sample diversity — the classic
    anti-mode-collapse feature.  Parameter-free.

    Group-wise, not batch-wide, on purpose: the GANPair D-step runs ONE
    forward over the concatenated [real; fake] batch, so a batch-wide
    scalar would be identical for every real AND fake row and carry no
    within-batch signal.  With contiguous groups the halves never share
    a group, so a collapsed fake half shows up as low-std fake groups in
    the same forward.  Under a mesh each shard's contiguous slice
    preserves group boundaries — the per-shard batch must be a group
    multiple (apply() raises otherwise), AND mesh == single-device
    exactness additionally needs every concatenated SEGMENT (the
    D-step's per-shard real/fake halves) to be a group multiple, i.e.
    batch_size/n_shards divisible by ``group_size`` — otherwise a shard
    group straddles the real/fake seam that the single-device grouping
    respects (tests/test_roadmap_models.py pins the aligned case).
    Single-device batches not divisible by ``group_size`` fall back to
    the largest dividing group (documented deviation)."""

    group_size: int = 4
    eps: float = 1e-8

    @property
    def has_params(self):
        return False

    def out_shape(self, in_shape):
        if len(in_shape) == 3:
            c, h, w = in_shape
            return (c + 1, h, w)
        return (_flat_size(in_shape) + 1,)

    def apply(self, params, x, train, rng, axis_name=None):
        B = x.shape[0]
        g = self.group_size
        if B % g:  # static shapes: largest divisor of B within group_size
            if axis_name is not None:
                # under a mesh a silent fallback would give each shard a
                # DIFFERENT grouping than the single-device run — the
                # equivalence this layer documents.  Require divisibility.
                raise ValueError(
                    f"MinibatchStdDev: per-shard batch {B} not divisible "
                    f"by group_size {self.group_size}; pick a batch whose "
                    "shard size is a group multiple (mesh == single-device "
                    "equivalence depends on identical grouping)")
            g = max(d for d in range(1, min(g, B) + 1) if B % d == 0)
        grouped = x.reshape((B // g, g) + x.shape[1:])
        mean = jnp.mean(grouped, axis=1, keepdims=True)
        var = jnp.mean(jnp.square(grouped - mean), axis=1)
        std = jnp.sqrt(var + self.eps)
        # one scalar per group, broadcast to that group's rows
        stat = jnp.mean(std.reshape(B // g, -1), axis=1)
        stat = jnp.repeat(stat, g)
        if x.ndim == 4:
            feat = jnp.broadcast_to(
                stat.reshape(B, 1, 1, 1), (B, 1) + x.shape[2:]).astype(x.dtype)
        else:
            feat = stat.reshape(B, 1).astype(x.dtype)
        return jnp.concatenate([x, feat], axis=1), None


@dataclasses.dataclass
class ProjectionOutput(Layer):
    """Projection discriminator head (Miyato & Koyama 2018):
    ``logit = phi @ W + b + sum(phi * (y @ V), -1)`` — the conditional
    term is an inner product between the feature vector and a learned
    class embedding, which shapes D's decision boundary per class far
    more strongly than concatenating the one-hot onto the features.
    Multi-input vertex: (features, onehot_label).  Carries a ``loss``
    like Output, so it can terminate a discriminator graph."""

    n_in: Optional[int] = None
    num_classes: int = 0
    loss: str = "xent"

    @property
    def multi_input(self):
        return True

    def out_shape(self, in_shape):
        return (1,)

    def init(self, key, in_shape):
        n_in = self.n_in if self.n_in is not None else _flat_size(in_shape[0])
        k = self.num_classes
        if k <= 0:
            raise ValueError("ProjectionOutput needs num_classes > 0")
        k_w, k_v = jax.random.split(key)
        return {
            "W": initializers.xavier(k_w, (n_in, 1), n_in, 1),
            "b": initializers.zeros((1,)),
            "V": initializers.xavier(k_v, (k, n_in), k, n_in),
        }

    def apply(self, params, xs, train, rng, axis_name=None):
        phi, y = xs
        phi = _as_ff(phi)
        logit = phi @ params["W"] + params["b"]
        embed = y @ params["V"]  # [B, n_in]
        logit = logit + jnp.sum(phi * embed, axis=-1, keepdims=True)
        return self._act(logit), None


LAYER_TYPES = {
    cls.__name__: cls
    for cls in [
        Dense, Output, Conv2D, ConvTranspose2D, MaxPool2D, Upsampling2D,
        BatchNorm, Dropout, Merge, ElementWise, ConditionalBatchNorm,
        MinibatchStdDev, ProjectionOutput,
    ]
}
