"""Input preprocessors — DL4J InputPreProcessor equivalents.

The reference uses ``FeedForwardToCnnPreProcessor(7, 7, 128)`` to reshape the
generator's dense output into the conv stack
(dl4jGANComputerVision.java:190); the inverse flatten is auto-inserted by the
graph builder when a dense layer follows a conv output (DL4J's
CnnToFeedForwardPreProcessor).  Pure reshapes — free under XLA.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class FeedForwardToCnn:
    """[B, h*w*c] -> [B, c, h, w] (DL4J argument order: height, width, channels)."""

    height: int
    width: int
    channels: int

    def out_shape(self, in_shape):
        return (self.channels, self.height, self.width)

    def __call__(self, x):
        return x.reshape(x.shape[0], self.channels, self.height, self.width)


@dataclasses.dataclass(frozen=True)
class CnnToFeedForward:
    """[B, c, h, w] -> [B, c*h*w]."""

    def out_shape(self, in_shape):
        n = 1
        for s in in_shape:
            n *= s
        return (n,)

    def __call__(self, x):
        return x.reshape(x.shape[0], -1)


PREPROCESSOR_TYPES = {
    "FeedForwardToCnn": FeedForwardToCnn,
    "CnnToFeedForward": CnnToFeedForward,
}
