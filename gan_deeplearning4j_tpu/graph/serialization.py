"""Model persistence — DL4J ``ModelSerializer`` equivalent.

The reference saves each of its four graphs as a zip (config + params +
updater state, ``ModelSerializer.writeModel(..., saveUpdater=true)``,
dl4jGANComputerVision.java:529-533).  Same shape here: a zip containing
``config.json`` (topology, layer dataclasses with type tags), ``params.npz``
and ``updater.npz`` (flat ``layer/param`` keys).  The reference never loads
its models back (save-only, SURVEY.md §5); we close that gap with
``load_model``.  Training-loop checkpoint/resume (step counter, all nets,
opt state) lives in checkpoint/.
"""

from __future__ import annotations

import dataclasses
import io
import json
import zipfile
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from gan_deeplearning4j_tpu.graph.graph import ComputationGraph, GraphBuilder, InputSpec
from gan_deeplearning4j_tpu.graph.layers import LAYER_TYPES
from gan_deeplearning4j_tpu.graph.preprocessors import PREPROCESSOR_TYPES
from gan_deeplearning4j_tpu.optim.adagrad import AdaGrad
from gan_deeplearning4j_tpu.optim.adam import Adam
from gan_deeplearning4j_tpu.optim.rmsprop import RmsProp
from gan_deeplearning4j_tpu.optim.schedules import (
    ExponentialSchedule,
    PolySchedule,
    Scheduled,
    SigmoidSchedule,
    StepSchedule,
)
from gan_deeplearning4j_tpu.optim.sgd import Nesterovs, Sgd

FORMAT_VERSION = 1

# updater/schedule kinds by type-tag; legacy configs without a tag are
# RmsProp.  Scheduled nests a base updater and a schedule, so encoding
# recurses over dataclass-valued fields.
_UPDATER_TYPES = {
    "RmsProp": RmsProp, "Adam": Adam, "Sgd": Sgd, "Nesterovs": Nesterovs,
    "AdaGrad": AdaGrad, "Scheduled": Scheduled,
    "StepSchedule": StepSchedule, "ExponentialSchedule": ExponentialSchedule,
    "PolySchedule": PolySchedule, "SigmoidSchedule": SigmoidSchedule,
}


def _updater_to_dict(u) -> dict:
    name = type(u).__name__
    if _UPDATER_TYPES.get(name) is not type(u):
        raise TypeError(
            f"cannot serialize updater/schedule {type(u)!r}: register it in "
            "serialization._UPDATER_TYPES (plain-callable schedules are "
            "trainable but not serializable — use a schedule dataclass)")
    d = {"__type__": name}
    for f in dataclasses.fields(u):
        v = getattr(u, f.name)
        if dataclasses.is_dataclass(v):
            d[f.name] = _updater_to_dict(v)
        elif isinstance(v, (int, float, str, bool, type(None))):
            d[f.name] = v
        else:  # e.g. a plain-callable schedule on Scheduled
            raise TypeError(
                f"cannot serialize {name}.{f.name}={v!r}: not a registered "
                "dataclass or JSON scalar (plain-callable schedules are "
                "trainable but not serializable — use a schedule dataclass)")
    return d


def _updater_from_dict(d: dict):
    d = dict(d)
    cls = _UPDATER_TYPES[d.pop("__type__", "RmsProp")]
    return cls(**{
        k: (_updater_from_dict(v)
            if isinstance(v, dict) and "__type__" in v else v)
        for k, v in d.items()
    })


def _layer_to_dict(layer) -> dict:
    d = dataclasses.asdict(layer)
    if d.get("updater") is not None:
        d["updater"] = _updater_to_dict(layer.updater)
    d["__type__"] = type(layer).__name__
    return d


def _layer_from_dict(d: dict):
    d = dict(d)
    cls = LAYER_TYPES[d.pop("__type__")]
    if d.get("updater") is not None:
        d["updater"] = _updater_from_dict(d["updater"])
    return cls(**d)


def _preproc_to_dict(p) -> dict:
    d = dataclasses.asdict(p)
    d["__type__"] = type(p).__name__
    return d


def _preproc_from_dict(d: dict):
    d = dict(d)
    cls = PREPROCESSOR_TYPES[d.pop("__type__")]
    return cls(**d)


def graph_config_to_dict(graph: ComputationGraph) -> dict:
    return {
        "format_version": FORMAT_VERSION,
        "seed": graph.seed,
        "l2": graph.l2,
        "clip_threshold": graph.clip_threshold,
        "frozen": sorted(graph.frozen),
        "inputs": graph.input_names,
        "input_specs": {
            k: {"kind": v.kind, "shape": list(v.shape)}
            for k, v in graph.input_specs.items()
        },
        "outputs": graph.output_names,
        "nodes": [
            {
                "name": name,
                "layer": _layer_to_dict(node.layer),
                "inputs": list(node.inputs),
                "preprocessor": (
                    _preproc_to_dict(node.preprocessor)
                    if node.preprocessor is not None else None
                ),
            }
            for name, node in graph.nodes.items()
        ],
    }


def graph_from_config_dict(cfg: dict) -> ComputationGraph:
    builder = GraphBuilder(
        seed=cfg["seed"],
        l2=cfg["l2"],
        clip_threshold=cfg["clip_threshold"],
    )
    builder.add_inputs(*cfg["inputs"])
    builder.set_input_types(
        *[
            InputSpec(cfg["input_specs"][i]["kind"], tuple(cfg["input_specs"][i]["shape"]))
            for i in cfg["inputs"]
        ]
    )
    for nd in cfg["nodes"]:
        builder.add_layer(nd["name"], _layer_from_dict(nd["layer"]), *nd["inputs"])
        if nd["preprocessor"] is not None:
            builder.input_preprocessor(nd["name"], _preproc_from_dict(nd["preprocessor"]))
    builder.set_outputs(*cfg["outputs"])
    graph = builder.build()
    graph.frozen = frozenset(cfg["frozen"])
    graph.updater.layer_updaters = {
        name: node.layer.updater
        for name, node in graph.nodes.items()
        if node.layer.has_params and name not in graph.frozen
    }
    return graph


def _flatten(tree: Dict, prefix: str = "") -> Dict[str, np.ndarray]:
    """Nested dicts -> '/'-joined flat keys, any depth (params are
    {layer: {param: array}}; Adam updater state adds a third level,
    {layer: {param: {m, v, t}}})."""
    out: Dict[str, np.ndarray] = {}
    for k, v in tree.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "/"))
        else:
            out[key] = np.asarray(v)
    return out


def _unflatten(flat) -> Dict:
    """Inverse of ``_flatten``; accepts an ``np.load`` handle (``.files``)
    or a plain {key: array} mapping."""
    tree: Dict = {}
    for key in (flat.files if hasattr(flat, "files") else flat):
        parts = key.split("/")
        d = tree
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = jnp.asarray(flat[key])
    return tree


# Fixed zip member timestamp: model/checkpoint bytes are a pure function
# of the state they encode, so two serializations of the same state hash
# identically — the property the checkpoint MANIFEST.json (per-file
# SHA-256) and the async-vs-sync save equivalence check rely on.
# (zipfile and np.savez both stamp wall-clock time otherwise.)
_ZIP_EPOCH = (1980, 1, 1, 0, 0, 0)


def _zip_writestr(zf: zipfile.ZipFile, name: str, data,
                  compress_type: Optional[int] = None) -> None:
    info = zipfile.ZipInfo(name, date_time=_ZIP_EPOCH)
    info.compress_type = (zf.compression if compress_type is None
                          else compress_type)
    info.external_attr = 0o600 << 16
    zf.writestr(info, data)


def npz_bytes(flat: Dict[str, np.ndarray]) -> bytes:
    """Deterministic ``.npz`` bytes for a flat {key: array} mapping
    (np.load-compatible; unlike ``np.savez`` the member timestamps are
    fixed, so equal arrays give equal bytes)."""
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for key, arr in flat.items():
            member = io.BytesIO()
            np.lib.format.write_array(member, np.asarray(arr),
                                      allow_pickle=False)
            _zip_writestr(zf, key + ".npy", member.getvalue())
    return buf.getvalue()


def model_zip_bytes(config: dict, flat_params: Dict[str, np.ndarray],
                    flat_updater: Optional[Dict[str, np.ndarray]]) -> bytes:
    """The model-zip format from already-flattened host arrays — the
    worker-thread half of an async checkpoint save (no graph access, no
    device contact; ``snapshot_model_parts`` produces the inputs)."""
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        _zip_writestr(zf, "config.json", json.dumps(config, indent=1))
        # the .npz members are ALREADY deflated (npz_bytes); store them
        # raw — a second DEFLATE pass over incompressible bytes would
        # double the dominant serialization cost for no size gain
        _zip_writestr(zf, "params.npz", npz_bytes(flat_params),
                      compress_type=zipfile.ZIP_STORED)
        if flat_updater is not None:
            _zip_writestr(zf, "updater.npz", npz_bytes(flat_updater),
                          compress_type=zipfile.ZIP_STORED)
    return buf.getvalue()


def snapshot_model_parts(graph: ComputationGraph, save_updater: bool = True):
    """Capture everything ``model_zip_bytes`` needs as host-side values:
    (config_dict, flat_params, flat_updater_or_None).  The flat arrays
    are numpy copies — safe to hand to a background serializer while the
    training thread keeps mutating the live graph."""
    flat_params = {k: np.asarray(v)
                   for k, v in _flatten(graph.params).items()}
    flat_updater = None
    if save_updater:
        flat_updater = {k: np.asarray(v)
                        for k, v in _flatten(graph.opt_state).items()}
    return graph_config_to_dict(graph), flat_params, flat_updater


def write_model(graph: ComputationGraph, path: str, save_updater: bool = True) -> None:
    with open(path, "wb") as f:
        f.write(model_zip_bytes(*snapshot_model_parts(graph, save_updater)))


def read_model(path: str) -> ComputationGraph:
    with zipfile.ZipFile(path) as zf:
        cfg = json.loads(zf.read("config.json"))
        graph = graph_from_config_dict(cfg)
        with zf.open("params.npz") as f:
            loaded = np.load(io.BytesIO(f.read()))
            params = _unflatten(loaded)
        # Layers with no params still need empty slots.
        for name, node in graph.nodes.items():
            params.setdefault(name, {})
        graph.params = params
        if "updater.npz" in zf.namelist():
            with zf.open("updater.npz") as f:
                loaded = np.load(io.BytesIO(f.read()))
                opt = _unflatten(loaded)
            for name in graph.nodes:
                opt.setdefault(name, {})
            graph.opt_state = opt
        else:
            graph.opt_state = graph.updater.init(graph.params)
    return graph
