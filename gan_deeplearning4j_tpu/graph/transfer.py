"""Transfer-learning graph surgery — DL4J ``TransferLearning.GraphBuilder``.

Reproduces the operations the reference performs to build its downstream
classifiers from the GAN discriminator
(dl4jGANComputerVision.java:322-351):

  - ``fine_tune_configuration``: new global defaults for the rebuilt graph
  - ``set_feature_extractor(name)``: freeze every layer up to and including
    ``name`` (no updates; train-mode forward runs them in inference mode)
  - ``remove_vertex_keep_connections(name)``: drop a layer, keep its wiring
  - ``add_layer``: append new (trainable) layers

Params of retained layers are carried over by reference (immutable arrays =
free copy); new layers are freshly initialized from the fine-tune seed.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from gan_deeplearning4j_tpu.graph.graph import ComputationGraph, GraphBuilder, Node
from gan_deeplearning4j_tpu.graph.layers import Layer
from gan_deeplearning4j_tpu.optim.rmsprop import RmsProp
from gan_deeplearning4j_tpu.runtime import prng


@dataclasses.dataclass
class FineTuneConfiguration:
    """The subset of DL4J FineTuneConfiguration the reference uses
    (dl4jGANComputerVision.java:324-336)."""

    seed: int = prng.NUMBER_OF_THE_BEAST
    l2: float = 0.0
    activation: str = "identity"
    weight_init: str = "xavier"
    updater: Optional[RmsProp] = None
    clip_threshold: Optional[float] = None


class TransferLearning:
    """``new TransferLearning.GraphBuilder(graph)`` equivalent."""

    def __init__(self, source: ComputationGraph):
        self.source = source
        self.fine_tune: Optional[FineTuneConfiguration] = None
        self._feature_extractor: Optional[str] = None
        self._removed: List[str] = []
        self._added: List[tuple] = []
        self._new_outputs: Optional[List[str]] = None

    def fine_tune_configuration(self, cfg: FineTuneConfiguration) -> "TransferLearning":
        self.fine_tune = cfg
        return self

    def set_feature_extractor(self, layer_name: str) -> "TransferLearning":
        if layer_name not in self.source.nodes:
            raise ValueError(f"unknown layer {layer_name!r}")
        self._feature_extractor = layer_name
        return self

    def remove_vertex_keep_connections(self, name: str) -> "TransferLearning":
        self._removed.append(name)
        return self

    def add_layer(self, name: str, layer: Layer, *inputs: str) -> "TransferLearning":
        self._added.append((name, layer, inputs))
        return self

    def set_outputs(self, *names: str) -> "TransferLearning":
        self._new_outputs = list(names)
        return self

    def build(self) -> ComputationGraph:
        cfg = self.fine_tune or FineTuneConfiguration()
        builder = GraphBuilder(
            seed=cfg.seed,
            l2=cfg.l2,
            activation=cfg.activation,
            weight_init=cfg.weight_init,
            updater=cfg.updater,
            clip_threshold=cfg.clip_threshold,
        )
        builder.add_inputs(*self.source.input_names)
        builder.set_input_types(
            *[self.source.input_specs[i] for i in self.source.input_names]
        )

        # Frozen set: every layer up to and including the feature extractor,
        # in insertion (topological) order — DL4J setFeatureExtractor semantics.
        frozen = set()
        if self._feature_extractor is not None:
            for name in self.source.nodes:
                frozen.add(name)
                if name == self._feature_extractor:
                    break

        # DL4J removeVertexKeepConnections: consumers of a removed vertex are
        # rewired to the removed vertex's own inputs (transitively, if several
        # removed vertices chain).
        removed_inputs = {
            name: list(self.source.nodes[name].inputs) for name in self._removed
        }

        def _rewire(inputs):
            out: List[str] = []
            for inp in inputs:
                if inp in removed_inputs:
                    out.extend(_rewire(removed_inputs[inp]))
                else:
                    out.append(inp)
            return out

        kept: Dict[str, Node] = {}
        for name, node in self.source.nodes.items():
            if name in self._removed:
                continue
            # Retained layers keep their resolved config (incl. activation) —
            # already resolved, so the new defaults only affect added layers.
            builder.add_layer(name, node.layer, *_rewire(node.inputs))
            if node.preprocessor is not None:
                builder.input_preprocessor(name, node.preprocessor)
            kept[name] = node

        for name, layer, inputs in self._added:
            # A vertex re-added under a removed name (the reference re-adds
            # "dis_output_layer_7") is a real node again from here on.
            removed_inputs.pop(name, None)
            builder.add_layer(name, layer, *_rewire(inputs))

        outputs = self._new_outputs
        if outputs is None:
            # DL4J keeps the original output names if the removed vertex was
            # re-added under the same name (the reference re-adds
            # "dis_output_layer_7" — dl4jGANComputerVision.java:345).
            outputs = [
                n for n in self.source.output_names
                if n in builder.nodes
            ]
            if not outputs:
                outputs = [self._added[-1][0]]
        builder.set_outputs(*outputs)

        graph = builder.build()
        graph.frozen = frozenset(frozen)
        # Rebuild the updater map now that frozen layers are known.
        graph.updater.layer_updaters = {
            name: node.layer.updater
            for name, node in graph.nodes.items()
            if node.layer.has_params and name not in graph.frozen
        }
        graph.init()
        # Carry over source params for retained layers (free: immutable arrays).
        for name in kept:
            graph.params = {**graph.params, name: dict(self.source.params[name])}
        return graph
