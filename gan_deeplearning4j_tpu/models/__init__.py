from gan_deeplearning4j_tpu.models import dcgan_mnist, mlpgan_insurance  # noqa: F401
