from gan_deeplearning4j_tpu.models import (  # noqa: F401
    cgan_cifar10,
    dcgan_celeba,
    dcgan_mnist,
    mlpgan_insurance,
    wgan_gp,
)
