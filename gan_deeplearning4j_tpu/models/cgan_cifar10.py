"""Conditional GAN on CIFAR-10 32x32x3 — roadmap config 3 (BASELINE.json:
"Conditional GAN on CIFAR-10 32x32 (color conv/deconv stack on TPU)").

Not in the reference's code; designed TPU-first for the two-pytree
``train.gan_pair.GANPair`` engine (no stacked graph):

  - generator: Merge(z, one-hot label) -> dense 4*4*256 -> BN -> reshape
    -> ConvTranspose x3 (256->128->64->3, stride 2) -> 32x32x3 tanh.
    Real transposed convs (ops/upsample.py conv_transpose2d, lowered as
    input-dilated convs the MXU likes), not the reference's
    upsample+conv workaround (SURVEY.md §3.3).
  - discriminator: conv stride-2 stack (3->64->128->256, LeakyReLU) ->
    flatten -> Merge with the label -> dense -> sigmoid XENT.  Label
    conditioning merges at the feature level (projection-free cGAN).
"""

from __future__ import annotations

import dataclasses

from gan_deeplearning4j_tpu.graph import (
    BatchNorm,
    ConditionalBatchNorm,
    Conv2D,
    ConvTranspose2D,
    Dense,
    GraphBuilder,
    InputSpec,
    Merge,
    MinibatchStdDev,
    Output,
    ProjectionOutput,
)
from gan_deeplearning4j_tpu.optim.adam import Adam
from gan_deeplearning4j_tpu.runtime import prng


@dataclasses.dataclass(frozen=True)
class CGANConfig:
    seed: int = prng.NUMBER_OF_THE_BEAST
    height: int = 32
    width: int = 32
    channels: int = 3
    num_classes: int = 10
    z_size: int = 64
    base_filters: int = 64
    learning_rate: float = 0.0002
    # TTUR: the discriminator trains slower than the generator (inverse
    # two-timescale) — with the easy synthetic surrogate D otherwise wins
    # outright and the generator gradient starves
    d_learning_rate: float = 0.0001
    # one-sided label smoothing on the real label (Salimans et al. 2016)
    real_label: float = 0.9
    l2: float = 0.0
    clip: float = 1.0
    # hold-then-decay LR horizon for BOTH networks; None = constant.
    # Measured at 5k (RESULTS §6): constant LR collapses conditionally
    # between 2k and 5k; linear decay from step 0 is WORSE (starves the
    # generator before structure forms — the first ~1-2k of the run is
    # still noise); this hold-then-sigmoid-decay shape (DL4J's
    # SigmoidSchedule, negative gamma) lands in between — it does NOT
    # recover the 2k run's class diversity, because the collapse sets in
    # before any safe decay horizon.  (r3 finding; superseded by the
    # structural conditioning below, which survives 5k.)
    decay_steps: int = None
    # r4 structural fixes for the 5k conditional collapse (VERDICT r3
    # weak-#3).  LR schedules only delayed it; these change WHERE the
    # label enters the game:
    #  - conditional_bn: per-class gamma/beta in every generator BN
    #    (plain BN's shared affine lets G ignore the label)
    #  - projection_d: projection discriminator head (label embedding
    #    dotted with features) instead of one-hot concat
    #  - minibatch_stddev: batch-diversity feature before D's dense
    #    stack (a collapsed batch is directly visible to D)
    conditional_bn: bool = True
    projection_d: bool = True
    minibatch_stddev: bool = True
    # mode-seeking regularizer weight (train/gan_pair.py ms_weight —
    # MSGAN): the r5 per-class-FID/diversity metrics measured
    # within-class mode shrinkage (diversity ratio ~0.4) that the
    # structural fixes above don't address; this is the targeted lever.
    # 0 = off (the r4-compatible default).
    ms_weight: float = 0.0


def _lr(rate: float, cfg: CGANConfig):
    from gan_deeplearning4j_tpu.optim.schedules import (
        Scheduled,
        SigmoidSchedule,
    )

    adam = Adam(rate, 0.5, 0.999)
    if cfg.decay_steps:
        # ≈ rate until 0.4·H, rate/2 at 0.7·H, ≈ 0 at H (H = decay_steps)
        return Scheduled(adam, SigmoidSchedule(
            rate, gamma=-1.0 / (0.06 * cfg.decay_steps),
            step=0.7 * cfg.decay_steps))
    return adam


def build_generator(cfg: CGANConfig = CGANConfig()):
    lr = _lr(cfg.learning_rate, cfg)
    f = cfg.base_filters
    b = GraphBuilder(seed=cfg.seed, l2=cfg.l2, activation="relu",
                     weight_init="xavier", clip_threshold=cfg.clip)
    b.add_inputs("z", "label")
    b.set_input_types(InputSpec.feed_forward(cfg.z_size),
                      InputSpec.feed_forward(cfg.num_classes))
    b.add_layer("gen_merge", Merge(), "z", "label")
    b.add_layer("gen_dense", Dense(n_out=4 * 4 * (4 * f), updater=lr), "gen_merge")

    def bn(name, inp, n):
        """Per-class gamma/beta (conditional_bn) or plain BN."""
        if cfg.conditional_bn:
            b.add_layer(name, ConditionalBatchNorm(
                num_classes=cfg.num_classes, n=n, updater=lr), inp, "label")
        else:
            b.add_layer(name, BatchNorm(updater=lr), inp)

    bn("gen_bn0", "gen_dense", 4 * 4 * (4 * f))
    from gan_deeplearning4j_tpu.graph import FeedForwardToCnn

    b.add_layer("gen_deconv1",
                ConvTranspose2D(kernel=(4, 4), stride=(2, 2), padding=(1, 1),
                                n_in=4 * f, n_out=2 * f, updater=lr),
                "gen_bn0")
    b.input_preprocessor("gen_deconv1", FeedForwardToCnn(4, 4, 4 * f))
    bn("gen_bn1", "gen_deconv1", 2 * f)
    b.add_layer("gen_deconv2",
                ConvTranspose2D(kernel=(4, 4), stride=(2, 2), padding=(1, 1),
                                n_in=2 * f, n_out=f, updater=lr),
                "gen_bn1")
    bn("gen_bn2", "gen_deconv2", f)
    b.add_layer("gen_deconv3",
                ConvTranspose2D(kernel=(4, 4), stride=(2, 2), padding=(1, 1),
                                n_in=f, n_out=cfg.channels, activation="tanh",
                                updater=lr),
                "gen_bn2")
    b.set_outputs("gen_deconv3")
    return b.build().init()


def build_discriminator(cfg: CGANConfig = CGANConfig()):
    lr = _lr(cfg.d_learning_rate, cfg)
    f = cfg.base_filters
    b = GraphBuilder(seed=cfg.seed, l2=cfg.l2, activation="leakyrelu",
                     weight_init="xavier", clip_threshold=cfg.clip)
    b.add_inputs("image", "label")
    b.set_input_types(
        InputSpec.convolutional_flat(cfg.height, cfg.width, cfg.channels),
        InputSpec.feed_forward(cfg.num_classes))
    b.add_layer("dis_conv1",
                Conv2D(kernel=(4, 4), stride=(2, 2), padding=(1, 1),
                       n_in=cfg.channels, n_out=f, updater=lr), "image")
    b.add_layer("dis_conv2",
                Conv2D(kernel=(4, 4), stride=(2, 2), padding=(1, 1),
                       n_in=f, n_out=2 * f, updater=lr), "dis_conv1")
    b.add_layer("dis_bn2", BatchNorm(updater=lr), "dis_conv2")
    b.add_layer("dis_conv3",
                Conv2D(kernel=(4, 4), stride=(2, 2), padding=(1, 1),
                       n_in=2 * f, n_out=4 * f, updater=lr), "dis_bn2")
    dense_in = "dis_conv3"
    if cfg.minibatch_stddev:
        # batch-diversity channel: a class-collapsed fake batch becomes
        # directly visible to D
        b.add_layer("dis_mbstd", MinibatchStdDev(), "dis_conv3")
        dense_in = "dis_mbstd"
    b.add_layer("dis_dense", Dense(n_out=512, updater=lr), dense_in)
    if cfg.projection_d:
        b.add_layer("dis_out",
                    ProjectionOutput(n_in=512, num_classes=cfg.num_classes,
                                     loss="xent", activation="sigmoid",
                                     updater=lr),
                    "dis_dense", "label")
    else:
        b.add_layer("dis_merge", Merge(), "dis_dense", "label")
        b.add_layer("dis_out",
                    Output(n_out=1, n_in=512 + cfg.num_classes, loss="xent",
                           activation="sigmoid", updater=lr),
                    "dis_merge")
    b.set_outputs("dis_out")
    return b.build().init()
