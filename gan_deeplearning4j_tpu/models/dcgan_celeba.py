"""CelebA 64x64 DCGAN — roadmap config 5 (BASELINE.json: "CelebA 64x64
DCGAN multi-replica (ParallelWrapper GradientSharing over v5e-8 ICI)").

The reference's classpath carries dormant multi-GPU machinery
(deeplearning4j-parallel-wrapper + Aeron gradient sharing, SURVEY.md §2c)
it never invokes; here "multi-replica" is the same one-line pmean the
whole framework uses: pass a ``Mesh`` to ``GANPair`` and the D/G steps
run SPMD over the replica axis.

Standard 64x64 DCGAN shapes (Radford et al. 2015): z(100) -> 4x4x(8f) ->
four stride-2 transposed convs -> 64x64x3 tanh; mirror conv stack with
LeakyReLU + BN for the discriminator.  ``bf16``: None (default) follows
the global runtime policy (``backend.configure(matmul_bf16=...)``);
True/False pins every layer of this model regardless of policy.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from gan_deeplearning4j_tpu.graph import (
    BatchNorm,
    Conv2D,
    ConvTranspose2D,
    Dense,
    FeedForwardToCnn,
    GraphBuilder,
    InputSpec,
    MinibatchStdDev,
    Output,
)
from gan_deeplearning4j_tpu.optim.adam import Adam
from gan_deeplearning4j_tpu.runtime import prng


@dataclasses.dataclass(frozen=True)
class CelebAConfig:
    seed: int = prng.NUMBER_OF_THE_BEAST
    height: int = 64
    width: int = 64
    channels: int = 3
    z_size: int = 100
    base_filters: int = 64
    learning_rate: float = 0.0002
    # TTUR + one-sided label smoothing (same rationale as cgan_cifar10:
    # without them D wins outright on the easy synthetic surrogate)
    d_learning_rate: float = 0.0001
    real_label: float = 0.9
    clip: float = 1.0
    bf16: Optional[bool] = None  # None = follow runtime policy
    # hold-then-sigmoid-decay horizon for BOTH networks (the cgan_cifar10
    # recipe: ~rate to 0.4·H, rate/2 at 0.7·H, ~0 at H).  The r5 10k
    # acceptance measured the need: constant-LR live FID bottoms at ~109
    # (3k) then DEGRADES to 186 by 10k as D overpowers G (d 0.16, g 10.7)
    # — freezing the game over the horizon pins the endpoint near the
    # optimum instead of past it.
    decay_steps: int = None
    # batch-diversity feature before D's output head (same rationale as
    # cgan_cifar10.minibatch_stddev: a collapsing G is directly visible)
    minibatch_stddev: bool = True
    # mode-seeking regularizer weight (train/gan_pair.py ms_weight): the
    # r5 trajectory diagnosed GEOMETRIC mode collapse (pose/size/mouth
    # attribute diversity lost while renders sharpen) — the same
    # z-to-image diversity failure the cgan family's metrics caught.
    # 0 = off (r4-compatible default).
    ms_weight: float = 0.0


def _lr(rate: float, cfg: CelebAConfig):
    adam = Adam(rate, 0.5, 0.999)
    if cfg.decay_steps:
        from gan_deeplearning4j_tpu.optim.schedules import (
            Scheduled,
            SigmoidSchedule,
        )

        return Scheduled(adam, SigmoidSchedule(
            rate, gamma=-1.0 / (0.06 * cfg.decay_steps),
            step=0.7 * cfg.decay_steps))
    return adam


def build_generator(cfg: CelebAConfig = CelebAConfig()):
    lr = _lr(cfg.learning_rate, cfg)
    f = cfg.base_filters
    b = GraphBuilder(seed=cfg.seed, activation="relu", weight_init="xavier",
                     clip_threshold=cfg.clip)
    b.add_inputs("z")
    b.set_input_types(InputSpec.feed_forward(cfg.z_size))
    b.add_layer("gen_dense",
                Dense(n_out=4 * 4 * 8 * f, updater=lr, bf16_matmul=cfg.bf16),
                "z")
    b.add_layer("gen_bn0", BatchNorm(updater=lr), "gen_dense")
    chans = [8 * f, 4 * f, 2 * f, f]
    prev = "gen_bn0"
    for i in range(3):
        name = f"gen_deconv{i + 1}"
        b.add_layer(name,
                    ConvTranspose2D(kernel=(4, 4), stride=(2, 2), padding=(1, 1),
                                    n_in=chans[i], n_out=chans[i + 1],
                                    updater=lr, bf16_matmul=cfg.bf16),
                    prev)
        if i == 0:
            b.input_preprocessor(name, FeedForwardToCnn(4, 4, 8 * f))
        bn = f"gen_bn{i + 1}"
        b.add_layer(bn, BatchNorm(updater=lr), name)
        prev = bn
    b.add_layer("gen_deconv4",
                ConvTranspose2D(kernel=(4, 4), stride=(2, 2), padding=(1, 1),
                                n_in=f, n_out=cfg.channels, activation="tanh",
                                updater=lr, bf16_matmul=cfg.bf16),
                prev)
    b.set_outputs("gen_deconv4")
    return b.build().init()


def build_discriminator(cfg: CelebAConfig = CelebAConfig()):
    lr = _lr(cfg.d_learning_rate, cfg)
    f = cfg.base_filters
    b = GraphBuilder(seed=cfg.seed, activation="leakyrelu",
                     weight_init="xavier", clip_threshold=cfg.clip)
    b.add_inputs("image")
    b.set_input_types(
        InputSpec.convolutional_flat(cfg.height, cfg.width, cfg.channels))
    chans = [cfg.channels, f, 2 * f, 4 * f, 8 * f]
    prev = "image"
    for i in range(4):
        name = f"dis_conv{i + 1}"
        b.add_layer(name,
                    Conv2D(kernel=(4, 4), stride=(2, 2), padding=(1, 1),
                           n_in=chans[i], n_out=chans[i + 1], updater=lr,
                           bf16_matmul=cfg.bf16),
                    prev)
        prev = name
        if i > 0:
            bn = f"dis_bn{i + 1}"
            b.add_layer(bn, BatchNorm(updater=lr), name)
            prev = bn
    n_in = 8 * f * 4 * 4
    if cfg.minibatch_stddev:
        b.add_layer("dis_mbstd", MinibatchStdDev(), prev)
        prev = "dis_mbstd"
        n_in = (8 * f + 1) * 4 * 4
    b.add_layer("dis_out",
                Output(n_out=1, n_in=n_in, loss="xent",
                       activation="sigmoid", updater=lr,
                       bf16_matmul=cfg.bf16),
                prev)
    b.set_outputs("dis_out")
    return b.build().init()
