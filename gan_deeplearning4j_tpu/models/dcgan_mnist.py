"""DCGAN-on-MNIST model family — the reference's CV workload graphs.

Layer-for-layer capability match with
``Java/src/main/java/org/deeplearning4j/dl4jGANComputerVision.java``:

  - discriminator  (:111-160): 28x28x1 -> BN -> conv5x5 s2 (1->64) ->
    maxpool2x2 s1 -> conv5x5 s2 (64->128) -> maxpool2x2 s1 -> dense 1024 ->
    sigmoid(1), XENT; global TANH, Xavier, per-layer RmsProp(lr, 1e-8, 1e-8),
    elementwise clip 1.0, L2 1e-4.
  - generator      (:162-214): z(2) -> BN -> dense 1024 -> dense 7*7*128 ->
    BN -> reshape 7x7x128 -> upsample x2 -> conv5x5 s1 p2 (128->64) ->
    upsample x2 -> conv5x5 s1 p2 (64->1) sigmoid.
  - stacked gan    (:216-301): generator layers at gen lr, discriminator copy
    at lr 0.0 ("frozen" = zero learning rate — SURVEY.md appendix).
  - transfer classifier (:322-351): freeze through dis_dense_layer_6, replace
    head with BN(1024) + softmax(10), MCXENT.

All hyperparameters default to the reference's constants block (:59-85).
"""

from __future__ import annotations

import dataclasses

from gan_deeplearning4j_tpu.graph import (
    BatchNorm,
    Conv2D,
    Dense,
    FeedForwardToCnn,
    FineTuneConfiguration,
    GraphBuilder,
    InputSpec,
    MaxPool2D,
    Output,
    TransferLearning,
    Upsampling2D,
)
from gan_deeplearning4j_tpu.optim.rmsprop import RmsProp
from gan_deeplearning4j_tpu.runtime import prng


@dataclasses.dataclass(frozen=True)
class CVConfig:
    """The reference's constants block (dl4jGANComputerVision.java:59-85)."""

    seed: int = prng.NUMBER_OF_THE_BEAST
    height: int = 28
    width: int = 28
    channels: int = 1
    num_features: int = 784
    z_size: int = 2
    num_classes: int = 10
    dis_learning_rate: float = 0.002
    gen_learning_rate: float = 0.004
    frozen_learning_rate: float = 0.0
    l2: float = 1e-4
    clip: float = 1.0


def _builder(cfg: CVConfig) -> GraphBuilder:
    return GraphBuilder(
        seed=cfg.seed,
        l2=cfg.l2,
        activation="tanh",
        weight_init="xavier",
        clip_threshold=cfg.clip,
    )


def build_discriminator(cfg: CVConfig = CVConfig()):
    lr = RmsProp(cfg.dis_learning_rate, 1e-8, 1e-8)
    b = _builder(cfg)
    b.add_inputs("dis_input_layer_0")
    b.set_input_types(InputSpec.convolutional_flat(cfg.height, cfg.width, cfg.channels))
    b.add_layer("dis_batch_layer_1", BatchNorm(updater=lr), "dis_input_layer_0")
    b.add_layer("dis_conv2d_layer_2",
                Conv2D(kernel=(5, 5), stride=(2, 2), n_in=1, n_out=64, updater=lr),
                "dis_batch_layer_1")
    b.add_layer("dis_maxpool_layer_3", MaxPool2D(kernel=(2, 2), stride=(1, 1)),
                "dis_conv2d_layer_2")
    b.add_layer("dis_conv2d_layer_4",
                Conv2D(kernel=(5, 5), stride=(2, 2), n_in=64, n_out=128, updater=lr),
                "dis_maxpool_layer_3")
    b.add_layer("dis_maxpool_layer_5", MaxPool2D(kernel=(2, 2), stride=(1, 1)),
                "dis_conv2d_layer_4")
    b.add_layer("dis_dense_layer_6", Dense(n_out=1024, updater=lr),
                "dis_maxpool_layer_5")
    b.add_layer("dis_output_layer_7",
                Output(n_out=1, loss="xent", activation="sigmoid", updater=lr),
                "dis_dense_layer_6")
    b.set_outputs("dis_output_layer_7")
    return b.build().init()


def _add_generator_layers(b: GraphBuilder, cfg: CVConfig, lr: RmsProp,
                          prefix: str, input_name: str) -> str:
    """The generator stack, shared between the standalone gen graph and the
    stacked gan graph (names differ only by prefix, matching the reference)."""
    b.add_layer(f"{prefix}_batch_1", BatchNorm(updater=lr), input_name)
    b.add_layer(f"{prefix}_dense_layer_2", Dense(n_out=1024, updater=lr),
                f"{prefix}_batch_1")
    b.add_layer(f"{prefix}_dense_layer_3", Dense(n_out=7 * 7 * 128, updater=lr),
                f"{prefix}_dense_layer_2")
    b.add_layer(f"{prefix}_batch_4", BatchNorm(updater=lr), f"{prefix}_dense_layer_3")
    b.add_layer(f"{prefix}_deconv2d_5", Upsampling2D(size=2), f"{prefix}_batch_4")
    b.input_preprocessor(f"{prefix}_deconv2d_5", FeedForwardToCnn(7, 7, 128))
    b.add_layer(f"{prefix}_conv2d_6",
                Conv2D(kernel=(5, 5), stride=(1, 1), padding=(2, 2),
                       n_in=128, n_out=64, updater=lr),
                f"{prefix}_deconv2d_5")
    b.add_layer(f"{prefix}_deconv2d_7", Upsampling2D(size=2), f"{prefix}_conv2d_6")
    b.add_layer(f"{prefix}_conv2d_8",
                Conv2D(kernel=(5, 5), stride=(1, 1), padding=(2, 2),
                       n_in=64, n_out=1, activation="sigmoid", updater=lr),
                f"{prefix}_deconv2d_7")
    return f"{prefix}_conv2d_8"


def build_generator(cfg: CVConfig = CVConfig()):
    """Standalone generator, frozen (lr 0.0) — used for synthesis only; its
    weights are overwritten from the gan graph each iteration."""
    lr = RmsProp(cfg.frozen_learning_rate, 1e-8, 1e-8)
    b = _builder(cfg)
    b.add_inputs("gen_input_layer_0")
    b.set_input_types(InputSpec.feed_forward(cfg.z_size))
    out = _add_generator_layers(b, cfg, lr, "gen", "gen_input_layer_0")
    b.set_outputs(out)
    return b.build().init()


def build_gan(cfg: CVConfig = CVConfig()):
    """Stacked G+D: generator at gen lr 0.004, discriminator tail at lr 0.0
    (dl4jGANComputerVision.java:216-301)."""
    gen_lr = RmsProp(cfg.gen_learning_rate, 1e-8, 1e-8)
    frz = RmsProp(cfg.frozen_learning_rate, 1e-8, 1e-8)
    b = _builder(cfg)
    b.add_inputs("gan_input_layer_0")
    b.set_input_types(InputSpec.feed_forward(cfg.z_size))
    gen_out = _add_generator_layers(b, cfg, gen_lr, "gan", "gan_input_layer_0")
    b.add_layer("gan_dis_batch_layer_9", BatchNorm(updater=frz), gen_out)
    b.add_layer("gan_dis_conv2d_layer_10",
                Conv2D(kernel=(5, 5), stride=(2, 2), n_in=1, n_out=64, updater=frz),
                "gan_dis_batch_layer_9")
    b.add_layer("gan_dis_maxpool_layer_11", MaxPool2D(kernel=(2, 2), stride=(1, 1)),
                "gan_dis_conv2d_layer_10")
    b.add_layer("gan_dis_conv2d_layer_12",
                Conv2D(kernel=(5, 5), stride=(2, 2), n_in=64, n_out=128, updater=frz),
                "gan_dis_maxpool_layer_11")
    b.add_layer("gan_dis_maxpool_layer_13", MaxPool2D(kernel=(2, 2), stride=(1, 1)),
                "gan_dis_conv2d_layer_12")
    b.add_layer("gan_dis_dense_layer_14", Dense(n_out=1024, updater=frz),
                "gan_dis_maxpool_layer_13")
    b.add_layer("gan_dis_output_layer_15",
                Output(n_out=1, loss="xent", activation="sigmoid", updater=frz),
                "gan_dis_dense_layer_14")
    b.set_outputs("gan_dis_output_layer_15")
    return b.build().init()


def build_classifier(dis, cfg: CVConfig = CVConfig()):
    """Transfer-learned 10-class classifier on discriminator features
    (dl4jGANComputerVision.java:322-351)."""
    lr = RmsProp(cfg.dis_learning_rate, 1e-8, 1e-8)
    return (
        TransferLearning(dis)
        .fine_tune_configuration(
            FineTuneConfiguration(
                seed=cfg.seed, l2=cfg.l2, activation="tanh",
                weight_init="xavier", updater=lr, clip_threshold=cfg.clip,
            )
        )
        .set_feature_extractor("dis_dense_layer_6")
        .remove_vertex_keep_connections("dis_output_layer_7")
        .add_layer("dis_batch", BatchNorm(n=1024, updater=lr), "dis_dense_layer_6")
        .add_layer("dis_output_layer_7",
                   Output(n_out=cfg.num_classes, n_in=1024, loss="mcxent",
                          activation="softmax", updater=lr),
                   "dis_batch")
        .build()
    )


# Cross-graph weight-sync maps: (dst_layer, src_layer) pairs, with the param
# names each carries — the reference's 30+ setParam copies
# (dl4jGANComputerVision.java:404-471) expressed as data.
BN_PARAMS = ("gamma", "beta", "mean", "var")
WB_PARAMS = ("W", "b")

DIS_TO_GAN = [
    ("gan_dis_batch_layer_9", "dis_batch_layer_1", BN_PARAMS),
    ("gan_dis_conv2d_layer_10", "dis_conv2d_layer_2", WB_PARAMS),
    ("gan_dis_conv2d_layer_12", "dis_conv2d_layer_4", WB_PARAMS),
    ("gan_dis_dense_layer_14", "dis_dense_layer_6", WB_PARAMS),
    ("gan_dis_output_layer_15", "dis_output_layer_7", WB_PARAMS),
]

GAN_TO_GEN = [
    ("gen_batch_1", "gan_batch_1", BN_PARAMS),
    ("gen_dense_layer_2", "gan_dense_layer_2", WB_PARAMS),
    ("gen_dense_layer_3", "gan_dense_layer_3", WB_PARAMS),
    ("gen_batch_4", "gan_batch_4", BN_PARAMS),
    ("gen_conv2d_6", "gan_conv2d_6", WB_PARAMS),
    ("gen_conv2d_8", "gan_conv2d_8", WB_PARAMS),
]

DIS_TO_CLASSIFIER = [
    ("dis_batch_layer_1", "dis_batch_layer_1", BN_PARAMS),
    ("dis_conv2d_layer_2", "dis_conv2d_layer_2", WB_PARAMS),
    ("dis_conv2d_layer_4", "dis_conv2d_layer_4", WB_PARAMS),
    ("dis_dense_layer_6", "dis_dense_layer_6", WB_PARAMS),
]


def sync_params(dst, src, mapping) -> None:
    """Apply a weight-sync map: free pytree reassignment, no device copies."""
    for dst_layer, src_layer, names in mapping:
        dst.set_layer_params(
            dst_layer, {n: src.get_param(src_layer, n) for n in names}
        )
