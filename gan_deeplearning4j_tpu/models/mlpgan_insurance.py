"""MLP-GAN on 4x3 transaction lattices — the reference's insurance workload.

Layer-for-layer capability match with
``Java/src/main/java/org/deeplearning4j/dl4jGANInsurance.java``:

  - discriminator (:110-144): 12 -> BN -> dense 100 (global ELU) -> dropout
    (identity: DL4J default prob) -> sigmoid(1) XENT; RmsProp(2e-4,1e-8,1e-8).
  - generator     (:146-185): z(2) -> BN -> dense 100 x3 -> dense 12 sigmoid;
    global TANH.
  - stacked gan   (:187-243): gen at lr 4e-4, dis copy at lr 0.0 with ELU set
    per-layer (the gan graph's global activation is TANH, so the frozen dis
    tail sets ELU explicitly — :228,233).
  - transfer classifier (:264-293): freeze through dis_dropout_layer_3, new
    BN(100) + sigmoid(1) XENT head.
"""

from __future__ import annotations

import dataclasses

from gan_deeplearning4j_tpu.graph import (
    BatchNorm,
    Dense,
    Dropout,
    FineTuneConfiguration,
    GraphBuilder,
    InputSpec,
    Output,
    TransferLearning,
)
from gan_deeplearning4j_tpu.optim.rmsprop import RmsProp
from gan_deeplearning4j_tpu.runtime import prng


@dataclasses.dataclass(frozen=True)
class InsuranceConfig:
    """The reference's constants block (dl4jGANInsurance.java:58-84)."""

    seed: int = prng.NUMBER_OF_THE_BEAST
    lattice_rows: int = 4     # periods
    lattice_cols: int = 3     # transaction types
    num_features: int = 12
    z_size: int = 2
    hidden: int = 100
    # generator hidden-dense depth (the reference uses 3).  Together
    # with ``hidden`` this is the heterogeneous-fleet cohort key
    # (train/lifecycle.py): tenants share a vmap cohort iff their
    # (hidden, gen_layers) agree.  Non-default depths need the dynamic
    # name map ``gan_to_gen_map(cfg)`` instead of the literal
    # ``GAN_TO_GEN``.
    gen_layers: int = 3
    dis_learning_rate: float = 0.0002
    gen_learning_rate: float = 0.0004
    frozen_learning_rate: float = 0.0
    l2: float = 1e-4
    clip: float = 1.0


def build_discriminator(cfg: InsuranceConfig = InsuranceConfig()):
    lr = RmsProp(cfg.dis_learning_rate, 1e-8, 1e-8)
    b = GraphBuilder(seed=cfg.seed, l2=cfg.l2, activation="elu",
                     weight_init="xavier", clip_threshold=cfg.clip)
    b.add_inputs("dis_input_layer_0")
    # no InputType in the reference: inferred from the BN layer's nIn=12
    b.add_layer("dis_batch_layer_1", BatchNorm(n=cfg.num_features, updater=lr),
                "dis_input_layer_0")
    b.add_layer("dis_dense_layer_2",
                Dense(n_out=cfg.hidden, n_in=cfg.num_features, updater=lr),
                "dis_batch_layer_1")
    b.add_layer("dis_dropout_layer_3", Dropout(rate=0.0), "dis_dense_layer_2")
    b.add_layer("dis_output_layer_4",
                Output(n_out=1, n_in=cfg.hidden, loss="xent",
                       activation="sigmoid", updater=lr),
                "dis_dropout_layer_3")
    b.set_outputs("dis_output_layer_4")
    return b.build().init()


def _add_generator_layers(b, cfg, lr, prefix, input_name) -> str:
    if cfg.gen_layers < 1:
        raise ValueError(f"gen_layers must be >= 1, got {cfg.gen_layers}")
    b.add_layer(f"{prefix}_batch_1", BatchNorm(updater=lr), input_name)
    prev = f"{prefix}_batch_1"
    # hidden dense stack: layers 2..(gen_layers+1); at the default depth
    # of 3 the names (dense_layer_2/3/4 + output dense_layer_5) match
    # the reference graph exactly
    for i in range(2, cfg.gen_layers + 2):
        name = f"{prefix}_dense_layer_{i}"
        b.add_layer(name, Dense(n_out=cfg.hidden, updater=lr), prev)
        prev = name
    out = f"{prefix}_dense_layer_{cfg.gen_layers + 2}"
    b.add_layer(out,
                Dense(n_out=cfg.num_features, n_in=cfg.hidden,
                      activation="sigmoid", updater=lr),
                prev)
    return out


def build_generator(cfg: InsuranceConfig = InsuranceConfig()):
    lr = RmsProp(cfg.frozen_learning_rate, 1e-8, 1e-8)
    b = GraphBuilder(seed=cfg.seed, l2=cfg.l2, activation="tanh",
                     weight_init="xavier", clip_threshold=cfg.clip)
    b.add_inputs("gen_input_layer_0")
    b.set_input_types(InputSpec.feed_forward(cfg.z_size))
    out = _add_generator_layers(b, cfg, lr, "gen", "gen_input_layer_0")
    b.set_outputs(out)
    return b.build().init()


def build_gan(cfg: InsuranceConfig = InsuranceConfig()):
    gen_lr = RmsProp(cfg.gen_learning_rate, 1e-8, 1e-8)
    frz = RmsProp(cfg.frozen_learning_rate, 1e-8, 1e-8)
    b = GraphBuilder(seed=cfg.seed, l2=cfg.l2, activation="tanh",
                     weight_init="xavier", clip_threshold=cfg.clip)
    b.add_inputs("gan_input_layer_0")
    b.set_input_types(InputSpec.feed_forward(cfg.z_size))
    gen_out = _add_generator_layers(b, cfg, gen_lr, "gan", "gan_input_layer_0")
    # frozen dis tail: ELU set explicitly (gan graph's global default is TANH)
    b.add_layer("gan_dis_batch_layer_6",
                BatchNorm(activation="elu", updater=frz), gen_out)
    b.add_layer("gan_dis_dense_layer_7",
                Dense(n_out=cfg.hidden, n_in=cfg.num_features,
                      activation="elu", updater=frz),
                "gan_dis_batch_layer_6")
    b.add_layer("gan_dis_dropout_layer_8", Dropout(rate=0.0),
                "gan_dis_dense_layer_7")
    b.add_layer("gan_dis_output_layer_9",
                Output(n_out=1, loss="xent", activation="sigmoid", updater=frz),
                "gan_dis_dropout_layer_8")
    b.set_outputs("gan_dis_output_layer_9")
    return b.build().init()


def build_classifier(dis, cfg: InsuranceConfig = InsuranceConfig()):
    """Loss-risk classifier on GAN-discriminator features
    (dl4jGANInsurance.java:264-293)."""
    lr = RmsProp(cfg.dis_learning_rate, 1e-8, 1e-8)
    return (
        TransferLearning(dis)
        .fine_tune_configuration(
            FineTuneConfiguration(
                seed=cfg.seed, l2=cfg.l2, activation="elu",
                weight_init="xavier", updater=lr, clip_threshold=cfg.clip,
            )
        )
        .set_feature_extractor("dis_dropout_layer_3")
        .remove_vertex_keep_connections("dis_output_layer_4")
        .add_layer("dis_batch", BatchNorm(n=cfg.hidden, updater=lr),
                   "dis_dropout_layer_3")
        .add_layer("dis_output_layer_4",
                   Output(n_out=1, n_in=cfg.hidden, loss="xent",
                          activation="sigmoid", updater=lr),
                   "dis_batch")
        .build()
    )


BN_PARAMS = ("gamma", "beta", "mean", "var")
WB_PARAMS = ("W", "b")

DIS_TO_GAN = [
    ("gan_dis_batch_layer_6", "dis_batch_layer_1", BN_PARAMS),
    ("gan_dis_dense_layer_7", "dis_dense_layer_2", WB_PARAMS),
    ("gan_dis_output_layer_9", "dis_output_layer_4", WB_PARAMS),
]

def gan_to_gen_map(cfg: InsuranceConfig = InsuranceConfig()):
    """The gan->generator weight-sync name map for ``cfg``'s depth.

    ``GAN_TO_GEN`` is this map at the reference depth (gen_layers=3);
    heterogeneous-fleet cohorts with other depths must build their map
    here so every generator dense layer stays synced."""
    out = [("gen_batch_1", "gan_batch_1", BN_PARAMS)]
    for i in range(2, cfg.gen_layers + 3):
        out.append((f"gen_dense_layer_{i}", f"gan_dense_layer_{i}",
                    WB_PARAMS))
    return out


GAN_TO_GEN = gan_to_gen_map()

DIS_TO_CLASSIFIER = [
    ("dis_batch_layer_1", "dis_batch_layer_1", BN_PARAMS),
    ("dis_dense_layer_2", "dis_dense_layer_2", WB_PARAMS),
]
