"""WGAN-GP — roadmap config 4 (BASELINE.json: "WGAN-GP (gradient penalty —
stresses SameDiff second-order -> XLA)").

The reference's DL4J/SameDiff stack could not express grad-of-grad
(BASELINE.json lists WGAN-GP precisely as the second-order stress test);
here the penalty is ordinary composed autodiff: every op in ops/ keeps a
JVP, so ``jax.grad`` through ``jax.grad`` of the critic's conv stack just
works (ops/losses.py gradient_penalty, used by train.gan_pair.GANPair
with ``mode="wgan-gp"``).

Critic design notes (Gulrajani et al. 2017 conventions): NO BatchNorm in
the critic (the penalty is per-example; batch coupling breaks it), linear
output head, ``wasserstein`` loss with +1/-1 labels, generator identical
to a DCGAN generator.  Defaults target MNIST 28x28 so the workload plugs
into the same data pipeline as the CV main.
"""

from __future__ import annotations

import dataclasses

from gan_deeplearning4j_tpu.graph import (
    BatchNorm,
    Conv2D,
    ConvTranspose2D,
    Dense,
    FeedForwardToCnn,
    GraphBuilder,
    InputSpec,
    Output,
)
from gan_deeplearning4j_tpu.optim.adam import Adam
from gan_deeplearning4j_tpu.runtime import prng


@dataclasses.dataclass(frozen=True)
class WGANGPConfig:
    seed: int = prng.NUMBER_OF_THE_BEAST
    height: int = 28
    width: int = 28
    channels: int = 1
    z_size: int = 64
    base_filters: int = 32
    learning_rate: float = 0.0001
    gp_weight: float = 10.0
    n_critic: int = 5            # critic steps per generator step
    clip: float = 0.0            # no grad clipping; GP regularizes instead


def build_critic(cfg: WGANGPConfig = WGANGPConfig()):
    """Conv critic, NO BatchNorm, linear head, Wasserstein loss."""
    lr = Adam(cfg.learning_rate, 0.5, 0.9)
    f = cfg.base_filters
    b = GraphBuilder(seed=cfg.seed, activation="leakyrelu",
                     weight_init="xavier",
                     clip_threshold=cfg.clip or None)
    b.add_inputs("image")
    b.set_input_types(
        InputSpec.convolutional_flat(cfg.height, cfg.width, cfg.channels))
    b.add_layer("crit_conv1",
                Conv2D(kernel=(5, 5), stride=(2, 2), padding=(2, 2),
                       n_in=cfg.channels, n_out=f, updater=lr), "image")
    b.add_layer("crit_conv2",
                Conv2D(kernel=(5, 5), stride=(2, 2), padding=(2, 2),
                       n_in=f, n_out=2 * f, updater=lr), "crit_conv1")
    b.add_layer("crit_dense", Dense(n_out=256, updater=lr), "crit_conv2")
    b.add_layer("crit_out",
                Output(n_out=1, n_in=256, loss="wasserstein",
                       activation="identity", updater=lr),
                "crit_dense")
    b.set_outputs("crit_out")
    return b.build().init()


def build_generator(cfg: WGANGPConfig = WGANGPConfig()):
    """DCGAN-style generator: z -> dense 7*7*4f -> BN -> deconv x2 -> 28x28."""
    lr = Adam(cfg.learning_rate, 0.5, 0.9)
    f = cfg.base_filters
    b = GraphBuilder(seed=cfg.seed, activation="relu", weight_init="xavier",
                     clip_threshold=cfg.clip or None)
    b.add_inputs("z")
    b.set_input_types(InputSpec.feed_forward(cfg.z_size))
    b.add_layer("gen_dense", Dense(n_out=7 * 7 * 4 * f, updater=lr), "z")
    b.add_layer("gen_bn0", BatchNorm(updater=lr), "gen_dense")
    b.add_layer("gen_deconv1",
                ConvTranspose2D(kernel=(4, 4), stride=(2, 2), padding=(1, 1),
                                n_in=4 * f, n_out=2 * f, updater=lr),
                "gen_bn0")
    b.input_preprocessor("gen_deconv1", FeedForwardToCnn(7, 7, 4 * f))
    b.add_layer("gen_bn1", BatchNorm(updater=lr), "gen_deconv1")
    b.add_layer("gen_deconv2",
                ConvTranspose2D(kernel=(4, 4), stride=(2, 2), padding=(1, 1),
                                n_in=2 * f, n_out=cfg.channels,
                                activation="sigmoid", updater=lr),
                "gen_bn1")
    b.set_outputs("gen_deconv2")
    return b.build().init()
