"""Ops layer — the libnd4j-kernel-set equivalent, lowered to XLA (+ Pallas).

Every op the reference's two workloads hit (SURVEY.md §2b: conv2d, maxpool,
batchnorm, dense GEMM, upsampling2d, dropout, activations, XENT/MCXENT,
RmsProp math, elementwise clip) has a functional jnp/lax implementation here
that XLA fuses and tiles onto the MXU/VPU.
"""

from gan_deeplearning4j_tpu.ops import activations, clipping, initializers, losses
from gan_deeplearning4j_tpu.ops.batchnorm import (
    batch_norm_inference,
    batch_norm_train,
)
from gan_deeplearning4j_tpu.ops.conv import conv2d, conv2d_out_size
from gan_deeplearning4j_tpu.ops.dense import dense, dropout
from gan_deeplearning4j_tpu.ops.pool import avg_pool2d, max_pool2d
from gan_deeplearning4j_tpu.ops.upsample import conv_transpose2d, upsample2d

__all__ = [
    "activations",
    "clipping",
    "initializers",
    "losses",
    "batch_norm_inference",
    "batch_norm_train",
    "conv2d",
    "conv2d_out_size",
    "dense",
    "dropout",
    "avg_pool2d",
    "max_pool2d",
    "conv_transpose2d",
    "upsample2d",
]
