"""Activation functions.

TPU-native equivalent of libnd4j's activation kernels (nd4j-native /
nd4j-cuda-9.0, reference dl4jGAN.iml:255,376): here they are jnp element-wise
ops that XLA fuses into the surrounding matmul/conv — there is no per-op
kernel-dispatch boundary to cross, unlike the reference's JNI-per-op hot path
(SURVEY.md §3.3).

Covers every ``org.nd4j.linalg.activations.Activation`` the reference uses
(TANH/ELU/SIGMOID/SOFTMAX/IDENTITY — dl4jGANComputerVision.java:124,
dl4jGANInsurance.java:120) plus LeakyReLU/ReLU for the roadmap configs
(BASELINE.json: conditional GAN, WGAN-GP).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

Activation = Callable[[jax.Array], jax.Array]


def identity(x):
    return x


def tanh(x):
    return jnp.tanh(x)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def elu(x):
    return jax.nn.elu(x)


def relu(x):
    return jax.nn.relu(x)


def leaky_relu(x, alpha: float = 0.01):
    return jax.nn.leaky_relu(x, negative_slope=alpha)


def softmax(x):
    return jax.nn.softmax(x, axis=-1)


_REGISTRY: dict[str, Activation] = {
    "identity": identity,
    "tanh": tanh,
    "sigmoid": sigmoid,
    "elu": elu,
    "relu": relu,
    "leakyrelu": leaky_relu,
    "softmax": softmax,
}


def get(name) -> Activation:
    if callable(name):
        return name
    try:
        return _REGISTRY[name.lower()]
    except KeyError:
        raise ValueError(f"unknown activation {name!r}; known: {sorted(_REGISTRY)}")
