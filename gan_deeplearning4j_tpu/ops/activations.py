"""Activation functions.

TPU-native equivalent of libnd4j's activation kernels (nd4j-native /
nd4j-cuda-9.0, reference dl4jGAN.iml:255,376): here they are jnp element-wise
ops that XLA fuses into the surrounding matmul/conv — there is no per-op
kernel-dispatch boundary to cross, unlike the reference's JNI-per-op hot path
(SURVEY.md §3.3).

Covers every ``org.nd4j.linalg.activations.Activation`` the reference uses
(TANH/ELU/SIGMOID/SOFTMAX/IDENTITY — dl4jGANComputerVision.java:124,
dl4jGANInsurance.java:120) plus LeakyReLU/ReLU for the roadmap configs
(BASELINE.json: conditional GAN, WGAN-GP).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

Activation = Callable[[jax.Array], jax.Array]


def identity(x):
    return x


def tanh(x):
    return jnp.tanh(x)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def elu(x):
    return jax.nn.elu(x)


def relu(x):
    return jax.nn.relu(x)


def leaky_relu(x, alpha: float = 0.01):
    return jax.nn.leaky_relu(x, negative_slope=alpha)


def softmax(x):
    return jax.nn.softmax(x, axis=-1)


# -- the rest of DL4J's standard Activation enum (beyond what the
# reference's graphs exercise), for drop-in config parity ----------------


def hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


def hardsigmoid(x):
    # DL4J/Theano convention: clip(0.2*x + 0.5, 0, 1)
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def softplus(x):
    return jax.nn.softplus(x)


def softsign(x):
    return jax.nn.soft_sign(x)


def cube(x):
    return x ** 3


def rational_tanh(x):
    """DL4J's RATIONALTANH: 1.7159 * tanh_approx(2x/3), with the rational
    tanh approximation of Anguita et al. (libnd4j's convention)."""
    y = 2.0 * x / 3.0
    ay = jnp.abs(y)
    approx = 1.0 - 1.0 / (1.0 + ay + ay ** 2 + 1.41645 * ay ** 4)
    return 1.7159 * jnp.sign(y) * approx


def selu(x):
    return jax.nn.selu(x)


def swish(x):
    return jax.nn.silu(x)  # x*sigmoid(x) — DL4J's SWISH


def gelu(x):
    return jax.nn.gelu(x)


def relu6(x):
    return jnp.clip(x, 0.0, 6.0)


def thresholded_relu(x, theta: float = 1.0):
    return jnp.where(x > theta, x, 0.0)


_REGISTRY: dict[str, Activation] = {
    "identity": identity,
    "tanh": tanh,
    "sigmoid": sigmoid,
    "elu": elu,
    "relu": relu,
    "leakyrelu": leaky_relu,
    "softmax": softmax,
    "hardtanh": hardtanh,
    "hardsigmoid": hardsigmoid,
    "softplus": softplus,
    "softsign": softsign,
    "cube": cube,
    "rationaltanh": rational_tanh,
    "selu": selu,
    "swish": swish,
    "gelu": gelu,
    "relu6": relu6,
    "thresholdedrelu": thresholded_relu,
}


def get(name) -> Activation:
    if callable(name):
        return name
    try:
        return _REGISTRY[name.lower()]
    except KeyError:
        raise ValueError(f"unknown activation {name!r}; known: {sorted(_REGISTRY)}")
