"""Batch normalization with explicit (gamma, beta, mean, var) state.

DL4J's BatchNormalization layer exposes its running statistics as *parameters*
("mean"/"var") that the reference's three-graph GAN protocol copies between
graphs every iteration (dl4jGANComputerVision.java:404-420, SURVEY.md §7
"hard parts").  To keep that weight-sync semantics exact, the stats live in
the same param tree as gamma/beta — functional state, no hidden mutable
buffers.

DL4J defaults reproduced: decay 0.9 (running = decay*running +
(1-decay)*batch), eps 1e-5, gamma init 1, beta init 0.  Train mode
normalizes by batch stats; inference by running stats — the train/inference
duality the GAN dynamics depend on (generator synthesis runs in inference
mode while the same weights train inside the stacked gan graph).

2-D input [B, F] normalizes per feature; 4-D input [B, C, H, W] per channel.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

DEFAULT_DECAY = 0.9
DEFAULT_EPS = 1e-5


def _reduce_axes(x: jax.Array) -> Tuple[int, ...]:
    if x.ndim == 2:
        return (0,)
    if x.ndim == 4:
        return (0, 2, 3)
    raise ValueError(f"batchnorm expects 2-D or 4-D input, got shape {x.shape}")


def _shaped(p: jax.Array, x: jax.Array) -> jax.Array:
    if x.ndim == 2:
        return p.reshape(1, -1)
    return p.reshape(1, -1, 1, 1)


def batch_norm_train(
    x: jax.Array,
    gamma: jax.Array,
    beta: jax.Array,
    running_mean: jax.Array,
    running_var: jax.Array,
    decay: float = DEFAULT_DECAY,
    eps: float = DEFAULT_EPS,
    axis_name: str | None = None,
):
    """Returns (out, new_running_mean, new_running_var).

    ``axis_name``: cross-replica sync-BN.  Inside ``shard_map`` the batch
    stats become GLOBAL-batch stats (E and E[x^2] pmean-ed over the mesh
    axis), making a data-parallel step bitwise-equivalent to the
    single-device full-batch step — including the between-shard-means
    variance term a naive per-shard pmean would drop.  None = local batch
    stats (single device, and the DL4J param-averaging fidelity mode,
    whose Spark workers each used local stats).
    """
    axes = _reduce_axes(x)
    mean = jnp.mean(x, axis=axes)
    m2 = jnp.mean(jnp.square(x), axis=axes)
    if axis_name is not None:
        mean = jax.lax.pmean(mean, axis_name)
        m2 = jax.lax.pmean(m2, axis_name)
    var = m2 - jnp.square(mean)
    out = (x - _shaped(mean, x)) * jax.lax.rsqrt(_shaped(var, x) + eps)
    out = out * _shaped(gamma, x) + _shaped(beta, x)
    new_mean = decay * running_mean + (1.0 - decay) * mean
    new_var = decay * running_var + (1.0 - decay) * var
    return out, new_mean, new_var


def batch_norm_inference(
    x: jax.Array,
    gamma: jax.Array,
    beta: jax.Array,
    running_mean: jax.Array,
    running_var: jax.Array,
    eps: float = DEFAULT_EPS,
) -> jax.Array:
    out = (x - _shaped(running_mean, x)) * jax.lax.rsqrt(_shaped(running_var, x) + eps)
    return out * _shaped(gamma, x) + _shaped(beta, x)


def _shaped_per_sample(p: jax.Array, x: jax.Array) -> jax.Array:
    """Per-SAMPLE scale/shift [B, C] broadcast against x."""
    if x.ndim == 2:
        return p
    return p.reshape(p.shape[0], p.shape[1], 1, 1)


def batch_norm_train_cond(
    x: jax.Array,
    gamma_b: jax.Array,
    beta_b: jax.Array,
    running_mean: jax.Array,
    running_var: jax.Array,
    decay: float = DEFAULT_DECAY,
    eps: float = DEFAULT_EPS,
    axis_name: str | None = None,
):
    """Conditional BN (Dumoulin et al. 2017): batch-stat normalization
    with per-SAMPLE gamma/beta [B, C] (selected upstream by the
    condition, e.g. one-hot label @ per-class table).  Statistics are
    class-agnostic — one running mean/var like plain BN; only the affine
    transform is conditioned.  Returns (out, new_mean, new_var)."""
    axes = _reduce_axes(x)
    mean = jnp.mean(x, axis=axes)
    m2 = jnp.mean(jnp.square(x), axis=axes)
    if axis_name is not None:
        mean = jax.lax.pmean(mean, axis_name)
        m2 = jax.lax.pmean(m2, axis_name)
    var = m2 - jnp.square(mean)
    out = (x - _shaped(mean, x)) * jax.lax.rsqrt(_shaped(var, x) + eps)
    out = out * _shaped_per_sample(gamma_b, x) + _shaped_per_sample(beta_b, x)
    new_mean = decay * running_mean + (1.0 - decay) * mean
    new_var = decay * running_var + (1.0 - decay) * var
    return out, new_mean, new_var


def batch_norm_inference_cond(
    x: jax.Array,
    gamma_b: jax.Array,
    beta_b: jax.Array,
    running_mean: jax.Array,
    running_var: jax.Array,
    eps: float = DEFAULT_EPS,
) -> jax.Array:
    out = (x - _shaped(running_mean, x)) * jax.lax.rsqrt(
        _shaped(running_var, x) + eps)
    return out * _shaped_per_sample(gamma_b, x) + _shaped_per_sample(beta_b, x)
