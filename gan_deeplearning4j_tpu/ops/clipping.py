"""Gradient normalization.

The reference clips every gradient element to [-t, t]
(``GradientNormalization.ClipElementWiseAbsoluteValue`` with threshold 1.0,
dl4jGANComputerVision.java:120-121) — reproduced as a pytree map.  L2-norm
clipping provided for roadmap configs.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def clip_elementwise(grads, threshold: float = 1.0):
    return jax.tree_util.tree_map(
        lambda g: jnp.clip(g, -threshold, threshold), grads
    )


def clip_by_global_norm(grads, max_norm: float):
    leaves = jax.tree_util.tree_leaves(grads)
    norm = jnp.sqrt(sum(jnp.sum(g.astype(jnp.float32) ** 2) for g in leaves))
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-12))
    return jax.tree_util.tree_map(lambda g: g * scale, grads)
