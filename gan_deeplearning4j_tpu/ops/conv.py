"""2-D convolution with DL4J semantics, lowered to XLA's TPU conv emitter.

Replaces libnd4j's im2col+GEMM conv kernels (the reference's dominant FLOPs,
SURVEY.md §3.2 "hot loops").  On TPU the convolution lowers straight onto the
MXU via ``lax.conv_general_dilated`` — no im2col materialization, no JNI
boundary.

DL4J semantics reproduced exactly (ConvolutionLayer, ConvolutionMode.Truncate
default — dl4jGANComputerVision.java:126-133):
  - data layout NCHW, weights OIHW, explicit symmetric padding (default 0),
  - out = floor((in + 2p - k) / s) + 1  ("Truncate": trailing rows/cols that
    don't fill a window are dropped),
  - bias per output channel.

Shape chain to preserve (SURVEY.md §7 "hard parts"): 28x28 -5x5 s2-> 12x12
-pool 2x2 s1-> 11x11 -5x5 s2-> 4x4 -pool-> 3x3 -> flatten 128*3*3=1152.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

DIMENSION_NUMBERS = ("NCHW", "OIHW", "NCHW")


def conv2d_out_size(in_size: int, kernel: int, stride: int, pad: int) -> int:
    """DL4J Truncate-mode output size (floor division)."""
    return (in_size + 2 * pad - kernel) // stride + 1


def _s2d_eligible(x: jax.Array, w: jax.Array, stride, padding) -> bool:
    return (w.shape[1] == 1 and tuple(stride) == (2, 2)
            and tuple(padding) == (0, 0)
            and x.shape[2] % 2 == 0 and x.shape[3] % 2 == 0
            and w.shape[2] >= 3 and w.shape[3] >= 3)


def _space_to_depth_rewrite(x: jax.Array, w: jax.Array):
    """Exact reindexing of a C_in=1 stride-2 conv as a denser stride-1
    conv on 2x2 space-to-depth blocks (RESULTS r2 §4's named MFU sink:
    at C_in=1 the MXU contraction is kh*kw=25-deep — 1/8-utilized; after
    the rewrite it is ceil(k/2)^2*4=36-deep over a quarter the spatial
    grid, and XLA tiles the denser channel axis onto the MXU lanes).

      y[b,o,i,j] = sum_{p,q} x[b,0,2i+p,2j+q] w[o,0,p,q]
                 = sum_{dy,dx,P,Q} X[b,dy*2+dx,i+P,j+Q] W'[o,dy*2+dx,P,Q]
      with X[b,dy*2+dx,I,J] = x[b,0,2I+dy,2J+dx]  (p = 2P+dy, q = 2Q+dx)

    Pure gather/pad of the SAME tensors at trace time — differentiable,
    weight-layout-invisible to the user; only float summation order
    changes."""
    B, _, H, W = x.shape
    O, _, kh, kw = w.shape
    kh2, kw2 = (kh + 1) // 2, (kw + 1) // 2
    xb = x.reshape(B, H // 2, 2, W // 2, 2).transpose(0, 2, 4, 1, 3)
    xb = xb.reshape(B, 4, H // 2, W // 2)
    planes = []
    for dy in (0, 1):
        for dx in (0, 1):
            sub = w[:, 0, dy::2, dx::2]  # [O, ceil((kh-dy)/2), ...]
            planes.append(jnp.pad(sub, (
                (0, 0), (0, kh2 - sub.shape[1]), (0, kw2 - sub.shape[2]))))
    wb = jnp.stack(planes, axis=1)  # [O, 4, kh2, kw2]
    return xb, wb


def _d2s_eligible(x: jax.Array, w: jax.Array, stride, padding) -> bool:
    """Output-side polyphase rewrite eligibility: stride-1 convs whose
    OUTPUT channel count starves the MXU (the generator's final
    C_out=1 synthesis conv — the mirror of the C_in=1 problem the
    space-to-depth rewrite solves on the input side)."""
    O, I, kh, kw = w.shape
    if not (tuple(stride) == (1, 1) and O <= 4 and I >= 4 * O
            and kh % 2 == 1 and kw % 2 == 1 and kh >= 3 and kw >= 3):
        return False
    ph, pw = padding
    ho = conv2d_out_size(x.shape[2], kh, 1, ph)
    wo = conv2d_out_size(x.shape[3], kw, 1, pw)
    return ho > 0 and wo > 0 and ho % 2 == 0 and wo % 2 == 0


def _d2s_kernel(w: jax.Array) -> jax.Array:
    """Embed the odd k x k kernel at the four (dy, dx) phase offsets of
    an even (k+1) x (k+1) kernel -> [4*O, I, k+1, k+1], phase-major.

      y[b,o,2u+dy,2v+dx] = sum_{c,i,j} xP[b,c,2u+dy+i,2v+dx+j] K[o,c,i,j]
                         = (stride-2 conv of xP with K~_(dy,dx))[b,o,u,v]
      with K~_(dy,dx)[o,c,m,n] = K[o,c,m-dy,n-dx]   (m = i+dy, n = j+dx)

    Exact reindexing of the SAME taps (only float summation order can
    change); the 4x denser output-channel axis tiles onto MXU lanes."""
    planes = [jnp.pad(w, ((0, 0), (0, 0), (dy, 1 - dy), (dx, 1 - dx)))
              for dy in (0, 1) for dx in (0, 1)]
    return jnp.concatenate(planes, axis=0)


def _d2s_reassemble(out4: jax.Array, n_out: int) -> jax.Array:
    """[B, 4*O, Ho/2, Wo/2] phase-major -> [B, O, Ho, Wo]."""
    B, _, hu, wv = out4.shape
    out4 = out4.reshape(B, 2, 2, n_out, hu, wv)
    out4 = out4.transpose(0, 3, 4, 1, 5, 2)  # [B, O, hu, dy, wv, dx]
    return out4.reshape(B, n_out, 2 * hu, 2 * wv)


def conv2d(
    x: jax.Array,
    w: jax.Array,
    b: jax.Array | None = None,
    stride: Sequence[int] = (1, 1),
    padding: Sequence[int] = (0, 0),
    *,
    preferred_dtype=None,
    bf16: bool = False,
) -> jax.Array:
    """x: [B, C, H, W]; w: [O, I, kh, kw]; b: [O] or None.

    ``bf16``: feed the MXU bfloat16 operands — the TPU fast path (the
    reference has no analogue; its dtype is fixed by
    ``Nd4j.setDataType(FLOAT)``).  Opt-in because it deviates from
    reference numerics; params/activations stay float32.  The conv runs
    fully in bf16 and the result is cast back (a mixed
    preferred_element_type would leave the transpose/VJP conv with one
    bf16 and one f32 operand, which lax rejects); the MXU still
    accumulates partial products in f32 internally."""
    from gan_deeplearning4j_tpu.runtime import backend

    d2s_out = None
    if backend.conv_s2d_enabled() and _s2d_eligible(x, w, stride, padding):
        x, w = _space_to_depth_rewrite(x, w)
        stride, padding = (1, 1), (0, 0)
    elif backend.conv_s2d_enabled() and _d2s_eligible(x, w, stride, padding):
        d2s_out = w.shape[0]
        w = _d2s_kernel(w)
        stride = (2, 2)  # padding unchanged: windows cover the same taps
    orig_dtype = x.dtype
    if bf16:
        x = x.astype(jnp.bfloat16)
        w = w.astype(jnp.bfloat16)
    ph, pw = padding
    out = lax.conv_general_dilated(
        x,
        w,
        window_strides=tuple(stride),
        padding=[(ph, ph), (pw, pw)],
        dimension_numbers=DIMENSION_NUMBERS,
        preferred_element_type=preferred_dtype,
    )
    if bf16:
        out = out.astype(orig_dtype)
    if d2s_out is not None:
        out = _d2s_reassemble(out, d2s_out)
    if b is not None:
        out = out + b.reshape(1, -1, 1, 1)
    return out
