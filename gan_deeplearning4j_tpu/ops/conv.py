"""2-D convolution with DL4J semantics, lowered to XLA's TPU conv emitter.

Replaces libnd4j's im2col+GEMM conv kernels (the reference's dominant FLOPs,
SURVEY.md §3.2 "hot loops").  On TPU the convolution lowers straight onto the
MXU via ``lax.conv_general_dilated`` — no im2col materialization, no JNI
boundary.

DL4J semantics reproduced exactly (ConvolutionLayer, ConvolutionMode.Truncate
default — dl4jGANComputerVision.java:126-133):
  - data layout NCHW, weights OIHW, explicit symmetric padding (default 0),
  - out = floor((in + 2p - k) / s) + 1  ("Truncate": trailing rows/cols that
    don't fill a window are dropped),
  - bias per output channel.

Shape chain to preserve (SURVEY.md §7 "hard parts"): 28x28 -5x5 s2-> 12x12
-pool 2x2 s1-> 11x11 -5x5 s2-> 4x4 -pool-> 3x3 -> flatten 128*3*3=1152.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

DIMENSION_NUMBERS = ("NCHW", "OIHW", "NCHW")


def conv2d_out_size(in_size: int, kernel: int, stride: int, pad: int) -> int:
    """DL4J Truncate-mode output size (floor division)."""
    return (in_size + 2 * pad - kernel) // stride + 1


def conv2d(
    x: jax.Array,
    w: jax.Array,
    b: jax.Array | None = None,
    stride: Sequence[int] = (1, 1),
    padding: Sequence[int] = (0, 0),
    *,
    preferred_dtype=None,
    bf16: bool = False,
) -> jax.Array:
    """x: [B, C, H, W]; w: [O, I, kh, kw]; b: [O] or None.

    ``bf16``: feed the MXU bfloat16 operands — the TPU fast path (the
    reference has no analogue; its dtype is fixed by
    ``Nd4j.setDataType(FLOAT)``).  Opt-in because it deviates from
    reference numerics; params/activations stay float32.  The conv runs
    fully in bf16 and the result is cast back (a mixed
    preferred_element_type would leave the transpose/VJP conv with one
    bf16 and one f32 operand, which lax rejects); the MXU still
    accumulates partial products in f32 internally."""
    orig_dtype = x.dtype
    if bf16:
        x = x.astype(jnp.bfloat16)
        w = w.astype(jnp.bfloat16)
    ph, pw = padding
    out = lax.conv_general_dilated(
        x,
        w,
        window_strides=tuple(stride),
        padding=[(ph, ph), (pw, pw)],
        dimension_numbers=DIMENSION_NUMBERS,
        preferred_element_type=preferred_dtype,
    )
    if bf16:
        out = out.astype(orig_dtype)
    if b is not None:
        out = out + b.reshape(1, -1, 1, 1)
    return out
