"""Dense (fully connected) layer op.

Replaces libnd4j's GEMM path (OpenBLAS/MKL on CPU, cuBLAS on GPU —
dl4jGAN.iml:229,244) with ``jnp.dot`` lowered to XLA ``dot_general`` on the
MXU.  Optional bf16 fast path: bfloat16 operands, result rounded through
bf16 and cast back to the input dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def dense(
    x: jax.Array,
    w: jax.Array,
    b: jax.Array | None = None,
    *,
    bf16: bool = False,
) -> jax.Array:
    """x: [B, F_in]; w: [F_in, F_out] (DL4J "W" layout); b: [F_out].

    ``bf16``: bfloat16 operands into the MXU, result cast back (a mixed
    preferred_element_type breaks the dot transpose/VJP dtype agreement
    the same way it does for conv — see ops/conv.py)."""
    if bf16:
        out = jnp.dot(
            x.astype(jnp.bfloat16),
            w.astype(jnp.bfloat16),
        ).astype(x.dtype)
    else:
        out = jnp.dot(x, w)
    if b is not None:
        out = out + b
    return out


def dropout(x: jax.Array, rate: float, rng: jax.Array, train: bool) -> jax.Array:
    """Inverted dropout.

    Note: the reference's ``new DropoutLayer()`` carries DL4J's unset default
    dropout probability, i.e. it is an identity op in practice
    (dl4jGANInsurance.java:134; SURVEY-verified quirk).  rate=0.0 reproduces
    that; nonzero rates are for the roadmap configs.
    """
    if not train or rate <= 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)
