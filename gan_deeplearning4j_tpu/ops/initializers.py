"""Weight initializers.

DL4J ``WeightInit.XAVIER`` (the reference's global choice,
dl4jGANComputerVision.java:125) is a *Gaussian* N(0, 2/(fanIn+fanOut)) — not
Glorot-uniform.  Reproduced exactly; biases init to 0 (DL4J default).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp


def fan_in_out_dense(n_in: int, n_out: int) -> Tuple[int, int]:
    return n_in, n_out


def fan_in_out_conv(n_in: int, n_out: int, kernel: Sequence[int]) -> Tuple[int, int]:
    receptive = 1
    for k in kernel:
        receptive *= k
    return n_in * receptive, n_out * receptive


def xavier(key: jax.Array, shape: Sequence[int], fan_in: int, fan_out: int, dtype=jnp.float32):
    std = jnp.sqrt(2.0 / (fan_in + fan_out))
    return jax.random.normal(key, tuple(shape), dtype) * std


def xavier_uniform(key: jax.Array, shape: Sequence[int], fan_in: int, fan_out: int, dtype=jnp.float32):
    limit = jnp.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, tuple(shape), dtype, -limit, limit)


def zeros(shape: Sequence[int], dtype=jnp.float32):
    return jnp.zeros(tuple(shape), dtype)


def ones(shape: Sequence[int], dtype=jnp.float32):
    return jnp.ones(tuple(shape), dtype)
