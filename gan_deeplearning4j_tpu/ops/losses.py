"""Loss functions.

Reproduces the two DL4J losses the reference exercises
(``LossFunctions.LossFunction.XENT`` — binary cross-entropy on sigmoid
outputs, dl4jGANComputerVision.java:152; ``MCXENT`` — multi-class
cross-entropy on softmax, :345) plus the roadmap losses (Wasserstein /
gradient-penalty for WGAN-GP — BASELINE.json configs).

Convention (matches DL4J scoring): sum over output units, mean over the
minibatch.  All losses are plain jnp compositions, so ``jax.grad`` composes
through them — including second order, which WGAN-GP's gradient penalty
requires (grad-of-grad through the conv stack, SURVEY.md §7).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_EPS = 1e-7


def binary_xent(probs: jax.Array, labels: jax.Array) -> jax.Array:
    """XENT on probabilities (post-sigmoid), as DL4J computes it."""
    p = jnp.clip(probs, _EPS, 1.0 - _EPS)
    per_example = -(labels * jnp.log(p) + (1.0 - labels) * jnp.log(1.0 - p))
    return jnp.mean(jnp.sum(per_example, axis=-1))


def binary_xent_from_logits(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Numerically stable sigmoid+XENT fusion (used by the fused fast path)."""
    per_example = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(
        jnp.exp(-jnp.abs(logits))
    )
    return jnp.mean(jnp.sum(per_example, axis=-1))


def mcxent(probs: jax.Array, labels: jax.Array) -> jax.Array:
    """MCXENT on probabilities (post-softmax), labels one-hot."""
    p = jnp.clip(probs, _EPS, 1.0)
    return jnp.mean(-jnp.sum(labels * jnp.log(p), axis=-1))


def mcxent_from_logits(logits: jax.Array, labels: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits, axis=-1)
    return jnp.mean(-jnp.sum(labels * logp, axis=-1))


def mse(pred: jax.Array, target: jax.Array) -> jax.Array:
    return jnp.mean(jnp.sum((pred - target) ** 2, axis=-1))


def wasserstein(critic_out: jax.Array, labels: jax.Array) -> jax.Array:
    """WGAN critic loss: labels +1 for real, -1 for fake; minimize -label*D(x)."""
    return -jnp.mean(critic_out * labels)


def gradient_penalty(critic_fn, real: jax.Array, fake: jax.Array,
                     rng: jax.Array, alpha: jax.Array = None) -> jax.Array:
    """WGAN-GP penalty E[(||∇_x D(x̂)||₂ - 1)²] on interpolates x̂.

    ``critic_fn`` must be a pure fn of the input batch; second-order autodiff
    flows through it (the reference's SameDiff could not express this —
    BASELINE.json lists it as a stress config).

    ``alpha``: optional pre-drawn interpolation weights [n, 1, ...] — SPMD
    callers draw the GLOBAL batch's alphas and pass each shard its slice so
    replicas don't reuse one replicated key (gan_pair._d_step).
    """
    alpha_shape = (real.shape[0],) + (1,) * (real.ndim - 1)
    if alpha is None:
        alpha = jax.random.uniform(rng, alpha_shape, dtype=real.dtype)
    else:
        alpha = alpha.reshape(alpha_shape).astype(real.dtype)
    interp = alpha * real + (1.0 - alpha) * fake

    def scalar_critic(x_single):
        return jnp.sum(critic_fn(x_single[None, ...]))

    grads = jax.vmap(jax.grad(scalar_critic))(interp)
    norms = jnp.sqrt(jnp.sum(grads.reshape(grads.shape[0], -1) ** 2, axis=-1) + 1e-12)
    return jnp.mean((norms - 1.0) ** 2)


# -- the rest of DL4J's standard LossFunctions enum (beyond what the
# reference's graphs exercise), same sum-over-units mean-over-batch
# convention --------------------------------------------------------------


def l1(pred: jax.Array, target: jax.Array) -> jax.Array:
    return jnp.mean(jnp.sum(jnp.abs(pred - target), axis=-1))


def negative_log_likelihood(probs: jax.Array, labels: jax.Array) -> jax.Array:
    """DL4J NEGATIVELOGLIKELIHOOD == MCXENT on probability outputs."""
    return mcxent(probs, labels)


def hinge(pred: jax.Array, labels: jax.Array) -> jax.Array:
    """Labels in {-1, +1} (DL4J's convention)."""
    return jnp.mean(jnp.sum(jnp.maximum(0.0, 1.0 - labels * pred), axis=-1))


def squared_hinge(pred: jax.Array, labels: jax.Array) -> jax.Array:
    return jnp.mean(jnp.sum(
        jnp.maximum(0.0, 1.0 - labels * pred) ** 2, axis=-1))


def kl_divergence(probs: jax.Array, target: jax.Array) -> jax.Array:
    """KL(target || probs) — DL4J's KL_DIVERGENCE (reconstruction form)."""
    t = jnp.clip(target, _EPS, 1.0)
    p = jnp.clip(probs, _EPS, 1.0)
    return jnp.mean(jnp.sum(t * (jnp.log(t) - jnp.log(p)), axis=-1))


def poisson(pred: jax.Array, target: jax.Array) -> jax.Array:
    """DL4J POISSON: sum(pred - target*log(pred))."""
    p = jnp.clip(pred, _EPS, None)
    return jnp.mean(jnp.sum(p - target * jnp.log(p), axis=-1))


def cosine_proximity(pred: jax.Array, target: jax.Array) -> jax.Array:
    """DL4J COSINE_PROXIMITY: -cos(pred, target) per example."""
    pn = pred / (jnp.linalg.norm(pred, axis=-1, keepdims=True) + _EPS)
    tn = target / (jnp.linalg.norm(target, axis=-1, keepdims=True) + _EPS)
    return jnp.mean(-jnp.sum(pn * tn, axis=-1))


def mean_absolute_percentage_error(pred, target) -> jax.Array:
    return jnp.mean(jnp.sum(
        100.0 * jnp.abs((target - pred) / jnp.clip(jnp.abs(target), _EPS)),
        axis=-1))


_REGISTRY = {
    "xent": binary_xent,
    "mcxent": mcxent,
    "mse": mse,
    "wasserstein": wasserstein,
    "l1": l1,
    "l2": mse,                      # DL4J aliases L2 to squared error
    "negativeloglikelihood": negative_log_likelihood,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "kl_divergence": kl_divergence,
    "poisson": poisson,
    "cosine_proximity": cosine_proximity,
    "mape": mean_absolute_percentage_error,
}


def get(name):
    if callable(name):
        return name
    try:
        return _REGISTRY[name.lower()]
    except KeyError:
        raise ValueError(f"unknown loss {name!r}; known: {sorted(_REGISTRY)}")
