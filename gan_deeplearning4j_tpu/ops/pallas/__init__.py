"""Pallas TPU kernels — hand-written fusions where XLA's stock lowering
leaves HBM bandwidth on the table (SURVEY.md §7 step 2: "Pallas only
where profiling says so"; the north-star names batchnorm and conv).

Currently: fused train-mode BatchNorm+activation (bn_act.py), the fused
RmsProp update chain (fused_update.py), and the double-buffered DMA
pipeline for the upsample backward reduce (dma_pipeline.py).  Kernels
are opt-in (``enable(True)`` or env GAN4J_PALLAS=1) and TPU-only at
runtime; tests exercise them anywhere via ``interpret=True``.
"""

from __future__ import annotations

import os

import jax

from gan_deeplearning4j_tpu.ops.pallas.bn_act import (
    fused_bn_act_train,
    fused_bn_act_train_4d,
)
from gan_deeplearning4j_tpu.ops.pallas.dma_pipeline import (
    supports_upsample_bwd,
    upsample_bwd_dma,
)

_ENABLED = os.environ.get("GAN4J_PALLAS", "0") == "1"


def enable(on: bool = True) -> None:
    """Toggle Pallas kernels.  The flag is read at TRACE time: call this
    (or set GAN4J_PALLAS=1) BEFORE the first fit/compile of a graph —
    already-jitted executables keep whichever path they were traced with
    (jit caches are keyed on code, not on this flag)."""
    global _ENABLED
    _ENABLED = on


def enabled() -> bool:
    """Pallas kernels active: opted in AND running on a TPU backend."""
    if not _ENABLED:
        return False
    try:
        return jax.default_backend() in ("tpu", "axon")
    except RuntimeError:
        return False


__all__ = ["fused_bn_act_train", "fused_bn_act_train_4d",
           "supports_upsample_bwd", "upsample_bwd_dma", "enable", "enabled"]
