"""Pallas TPU kernel: fused train-mode BatchNorm + activation.

The north-star calls out batchnorm as a candidate for hand kernels where
stock XLA lowering isn't enough (BASELINE.json; SURVEY.md §7 step 2).
Train-mode BN is three HBM passes when unfused (reduce for mean, reduce
for var, elementwise normalize); XLA usually fuses the elementwise tail
but keeps separate reduction passes.  This kernel does the whole thing —
E[x], E[x^2], normalize, scale/shift, activation — in ONE VMEM-resident
pass per feature tile: the batch column block is loaded once, reduced and
transformed in registers/VMEM, written once.

Scope: 2-D [B, F] inputs (the models' heavy BNs — the generator's
6272-wide and the dense 1024-wide layers — are 2-D; 4-D per-channel BN
stays on the XLA path).  F is tiled in 128-lane blocks; B and F are
padded to tile multiples and the result sliced back.

Gradients: ``jax.custom_vjp`` with a rematerializing backward through the
plain-jnp reference composition — forward speed from Pallas, backward
correctness from autodiff (Patterns: Custom VJP in the Pallas guide).

Enable via ``ops.pallas.enable(True)`` or env GAN4J_PALLAS=1; runs only
on TPU (or anywhere with ``interpret=True`` for tests).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from gan_deeplearning4j_tpu.ops import activations as act_lib

LANE = 128
SUBLANE = 8


def _kernel(x_ref, gamma_ref, beta_ref, y_ref, mean_ref, var_ref, *,
            eps: float, act_name: str, n_valid_rows: int):
    x = x_ref[:]                                   # [B_pad, TILE_F]
    # padded rows are zero; correct the moments by the true row count
    inv_n = 1.0 / n_valid_rows
    mean = jnp.sum(x, axis=0, keepdims=True) * inv_n
    m2 = jnp.sum(x * x, axis=0, keepdims=True) * inv_n
    var = m2 - mean * mean
    y = (x - mean) * lax.rsqrt(var + eps)
    y = y * gamma_ref[:] + beta_ref[:]
    y_ref[:] = act_lib.get(act_name)(y)
    mean_ref[:] = mean
    var_ref[:] = var


def _pad_to(x, rows, cols):
    pr, pc = rows - x.shape[0], cols - x.shape[1]
    if pr or pc:
        x = jnp.pad(x, ((0, pr), (0, pc)))
    return x


def _reference(x, gamma, beta, eps, act_name):
    mean = jnp.mean(x, axis=0)
    var = jnp.mean(jnp.square(x), axis=0) - jnp.square(mean)
    y = (x - mean[None]) * lax.rsqrt(var[None] + eps)
    y = y * gamma[None] + beta[None]
    return act_lib.get(act_name)(y), mean, var


def _fused_fwd_impl(x, gamma, beta, eps: float, act_name: str,
                    interpret: bool):
    B, F = x.shape
    B_pad = -(-B // SUBLANE) * SUBLANE
    F_pad = -(-F // LANE) * LANE
    xp = _pad_to(x, B_pad, F_pad)
    gp = _pad_to(gamma[None], 1, F_pad)
    bp = _pad_to(beta[None], 1, F_pad)
    grid = (F_pad // LANE,)
    kernel = functools.partial(_kernel, eps=eps, act_name=act_name,
                               n_valid_rows=B)
    y, mean, var = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((B_pad, LANE), lambda i: (0, i)),
            pl.BlockSpec((1, LANE), lambda i: (0, i)),
            pl.BlockSpec((1, LANE), lambda i: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((B_pad, LANE), lambda i: (0, i)),
            pl.BlockSpec((1, LANE), lambda i: (0, i)),
            pl.BlockSpec((1, LANE), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B_pad, F_pad), x.dtype),
            jax.ShapeDtypeStruct((1, F_pad), x.dtype),
            jax.ShapeDtypeStruct((1, F_pad), x.dtype),
        ],
        interpret=interpret,
    )(xp, gp, bp)
    return y[:B, :F], mean[0, :F], var[0, :F]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def fused_bn_act_train(x, gamma, beta, eps: float = 1e-5,
                       act_name: str = "identity",
                       interpret: bool = False):
    """-> (act(bn(x)), batch_mean, batch_var); one fused pass on TPU."""
    return _fused_fwd_impl(x, gamma, beta, eps, act_name, interpret)


def _fwd(x, gamma, beta, eps, act_name, interpret):
    out = _fused_fwd_impl(x, gamma, beta, eps, act_name, interpret)
    return out, (x, gamma, beta)


def _bwd(eps, act_name, interpret, residuals, cotangents):
    x, gamma, beta = residuals
    _, vjp = jax.vjp(lambda a, g, b: _reference(a, g, b, eps, act_name),
                     x, gamma, beta)
    return vjp(cotangents)


fused_bn_act_train.defvjp(_fwd, _bwd)
