"""Pallas TPU kernels: fused train-mode BatchNorm + activation.

The north-star calls out batchnorm as a candidate for hand kernels where
stock XLA lowering isn't enough (BASELINE.json; SURVEY.md §7 step 2).
Train-mode BN is three HBM passes when unfused (reduce for mean, reduce
for var, elementwise normalize); XLA usually fuses the elementwise tail
but keeps separate reduction passes.

Two execution paths, selected by ``axis_name``:

* **Single device (axis_name=None)** — ONE kernel does everything:
  E[x], E[x^2], normalize, scale/shift, activation in a single
  VMEM-resident pass per feature tile.  The batch column block is loaded
  from HBM once, reduced and transformed in registers/VMEM, written once.

* **SPMD (axis_name given)** — batch moments are GLOBAL (sync-BN,
  matching ops/batchnorm.py), so one fused pass is impossible: a
  cross-replica ``pmean`` must sit between the moment reduction and the
  normalization.  The kernel pair brackets it: ``_moments_kernel`` (one
  pass: local E[x] and E[x^2] together — XLA tends to emit separate
  reduce passes), then ``lax.pmean``, then ``_apply_kernel`` (one pass:
  normalize + scale/shift + activation).  Two reads + one write of x —
  the SPMD lower bound.

Scope: 2-D [B, F] inputs (the models' heavy BNs — the generator's
6272-wide and the dense 1024-wide layers — are 2-D; 4-D per-channel BN
stays on the XLA path: the flagship models' 4-D BNs are C=1 over
28x28 maps, a shape XLA's column reduce already handles at bandwidth).
F is tiled in 128-lane blocks; B and F are padded to tile multiples and
the result sliced back.

Gradients: ``jax.custom_vjp`` with a rematerializing backward through the
plain-jnp reference composition (pmean included under SPMD) — forward
speed from Pallas, backward correctness from autodiff (Patterns: Custom
VJP in the Pallas guide).

Enable via ``ops.pallas.enable(True)`` or env GAN4J_PALLAS=1; runs only
on TPU (or anywhere with ``interpret=True`` for tests).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from gan_deeplearning4j_tpu.ops import activations as act_lib

LANE = 128
SUBLANE = 8


def _fused_kernel(x_ref, gamma_ref, beta_ref, y_ref, mean_ref, var_ref, *,
                  eps: float, act_name: str, n_valid_rows: int):
    x = x_ref[:]                                   # [B_pad, TILE_F]
    # padded rows are zero; correct the moments by the true row count
    inv_n = 1.0 / n_valid_rows
    mean = jnp.sum(x, axis=0, keepdims=True) * inv_n
    m2 = jnp.sum(x * x, axis=0, keepdims=True) * inv_n
    var = m2 - mean * mean
    y = (x - mean) * lax.rsqrt(var + eps)
    y = y * gamma_ref[:] + beta_ref[:]
    y_ref[:] = act_lib.get(act_name)(y)
    mean_ref[:] = mean
    var_ref[:] = var


def _moments_kernel(x_ref, mean_ref, m2_ref, *, n_valid_rows: int):
    """One pass: local E[x] and E[x^2] per feature lane (x read ONCE)."""
    x = x_ref[:]
    inv_n = 1.0 / n_valid_rows
    mean_ref[:] = jnp.sum(x, axis=0, keepdims=True) * inv_n
    m2_ref[:] = jnp.sum(x * x, axis=0, keepdims=True) * inv_n


def _apply_kernel(x_ref, mean_ref, var_ref, gamma_ref, beta_ref, y_ref, *,
                  eps: float, act_name: str):
    """One pass: normalize by (given) global moments + scale/shift + act."""
    y = (x_ref[:] - mean_ref[:]) * lax.rsqrt(var_ref[:] + eps)
    y = y * gamma_ref[:] + beta_ref[:]
    y_ref[:] = act_lib.get(act_name)(y)


def _pad_to(x, rows, cols):
    pr, pc = rows - x.shape[0], cols - x.shape[1]
    if pr or pc:
        x = jnp.pad(x, ((0, pr), (0, pc)))
    return x


def _reference(x, gamma, beta, eps, act_name, axis_name=None):
    mean = jnp.mean(x, axis=0)
    m2 = jnp.mean(jnp.square(x), axis=0)
    if axis_name is not None:
        mean = lax.pmean(mean, axis_name)
        m2 = lax.pmean(m2, axis_name)
    var = m2 - jnp.square(mean)
    y = (x - mean[None]) * lax.rsqrt(var[None] + eps)
    y = y * gamma[None] + beta[None]
    return act_lib.get(act_name)(y), mean, var


def _row_spec(B_pad):
    return pl.BlockSpec((B_pad, LANE), lambda i: (0, i))


def _vec_spec():
    return pl.BlockSpec((1, LANE), lambda i: (0, i))


def _local_moments(xp, B, B_pad, F_pad, interpret: bool):
    grid = (F_pad // LANE,)
    mean, m2 = pl.pallas_call(
        functools.partial(_moments_kernel, n_valid_rows=B),
        grid=grid,
        in_specs=[_row_spec(B_pad)],
        out_specs=[_vec_spec(), _vec_spec()],
        out_shape=[jax.ShapeDtypeStruct((1, F_pad), xp.dtype)] * 2,
        interpret=interpret,
    )(xp)
    return mean, m2


def _apply(xp, mean, var, gp, bp, B_pad, F_pad, eps, act_name,
           interpret: bool):
    grid = (F_pad // LANE,)
    return pl.pallas_call(
        functools.partial(_apply_kernel, eps=eps, act_name=act_name),
        grid=grid,
        in_specs=[_row_spec(B_pad), _vec_spec(), _vec_spec(), _vec_spec(),
                  _vec_spec()],
        out_specs=[_row_spec(B_pad)],
        out_shape=[jax.ShapeDtypeStruct((B_pad, F_pad), xp.dtype)],
        interpret=interpret,
    )(xp, mean, var, gp, bp)[0]


def _fused_fwd_impl(x, gamma, beta, eps: float, act_name: str,
                    interpret: bool, axis_name):
    B, F = x.shape
    B_pad = -(-B // SUBLANE) * SUBLANE
    F_pad = -(-F // LANE) * LANE
    xp = _pad_to(x, B_pad, F_pad)
    gp = _pad_to(gamma[None], 1, F_pad)
    bp = _pad_to(beta[None], 1, F_pad)
    if axis_name is None:
        grid = (F_pad // LANE,)
        kernel = functools.partial(_fused_kernel, eps=eps, act_name=act_name,
                                   n_valid_rows=B)
        y, mean, var = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[_row_spec(B_pad), _vec_spec(), _vec_spec()],
            out_specs=[_row_spec(B_pad), _vec_spec(), _vec_spec()],
            out_shape=[
                jax.ShapeDtypeStruct((B_pad, F_pad), x.dtype),
                jax.ShapeDtypeStruct((1, F_pad), x.dtype),
                jax.ShapeDtypeStruct((1, F_pad), x.dtype),
            ],
            interpret=interpret,
        )(xp, gp, bp)
        return y[:B, :F], mean[0, :F], var[0, :F]
    # SPMD: local one-pass moments -> global pmean -> one-pass apply
    mean, m2 = _local_moments(xp, B, B_pad, F_pad, interpret)
    mean = lax.pmean(mean, axis_name)
    m2 = lax.pmean(m2, axis_name)
    var = m2 - mean * mean
    y = _apply(xp, mean, var, gp, bp, B_pad, F_pad, eps, act_name, interpret)
    return y[:B, :F], mean[0, :F], var[0, :F]


# -- 4-D per-channel variant (r4: the CelebA-family shapes) ---------------

CH_BLOCK = 8  # channels per grid step (TPU wants sublane-divisible blocks)


def _fused_kernel_4d(x_ref, gamma_ref, beta_ref, y_ref, mean_ref, var_ref, *,
                     eps: float, act_name: str, n_valid: int):
    """CH_BLOCK channels per grid step: block [B_pad, CH_BLOCK, HW_pad],
    per-channel moments over ALL positions (padded entries are zero;
    corrected by true count), normalize + scale/shift + activation in the
    same VMEM residency."""
    x = x_ref[:]                                   # [B_pad, CB, HW_pad]
    inv_n = 1.0 / n_valid
    mean = jnp.sum(x, axis=(0, 2)) * inv_n         # [CB]
    m2 = jnp.sum(x * x, axis=(0, 2)) * inv_n
    var = m2 - mean * mean
    y = (x - mean[None, :, None]) * lax.rsqrt(var[None, :, None] + eps)
    y = (y * gamma_ref[:, 0][None, :, None]
         + beta_ref[:, 0][None, :, None])
    y_ref[:] = act_lib.get(act_name)(y)
    mean_ref[:] = jnp.broadcast_to(mean[:, None], (CH_BLOCK, LANE))
    var_ref[:] = jnp.broadcast_to(var[:, None], (CH_BLOCK, LANE))


def _reference_4d(x, gamma, beta, eps, act_name):
    mean = jnp.mean(x, axis=(0, 2, 3))
    var = jnp.mean(jnp.square(x), axis=(0, 2, 3)) - jnp.square(mean)
    y = (x - mean[None, :, None, None]) * lax.rsqrt(
        var[None, :, None, None] + eps)
    y = y * gamma[None, :, None, None] + beta[None, :, None, None]
    return act_lib.get(act_name)(y), mean, var


# VMEM budget for one 8-channel block: x and y blocks, each
# double-buffered by the pipeline -> 4 live copies must fit under the
# ~16MB scoped-vmem limit (with headroom for the scalar vectors)
_VMEM_BUDGET = 15 << 20


def supports_4d(shape) -> bool:
    """True iff the one-pass 4-D kernel's block fits VMEM for ``shape``
    [B, C, H, W]; callers fall back to the XLA lowering otherwise."""
    B, _, H, W = shape
    B_pad = -(-B // SUBLANE) * SUBLANE
    HW_pad = -(-(H * W) // LANE) * LANE
    return 4 * (B_pad * CH_BLOCK * HW_pad * 4) <= _VMEM_BUDGET


def _fused_fwd_impl_4d(x, gamma, beta, eps, act_name, interpret):
    B, C, H, W = x.shape
    if not supports_4d(x.shape):
        # block would blow the scoped-vmem limit: XLA path (same math)
        return _reference_4d(x, gamma, beta, eps, act_name)
    hw = H * W
    B_pad = -(-B // SUBLANE) * SUBLANE
    HW_pad = -(-hw // LANE) * LANE
    C_pad = -(-C // CH_BLOCK) * CH_BLOCK
    xp = x.reshape(B, C, hw)
    if B_pad != B or HW_pad != hw or C_pad != C:
        xp = jnp.pad(xp, ((0, B_pad - B), (0, C_pad - C), (0, HW_pad - hw)))
    gp = _pad_to(gamma.reshape(C, 1), C_pad, 1)
    bp = _pad_to(beta.reshape(C, 1), C_pad, 1)
    kernel = functools.partial(_fused_kernel_4d, eps=eps, act_name=act_name,
                               n_valid=B * hw)
    y, mean, var = pl.pallas_call(
        kernel,
        grid=(C_pad // CH_BLOCK,),
        in_specs=[pl.BlockSpec((B_pad, CH_BLOCK, HW_pad),
                               lambda c: (0, c, 0)),
                  pl.BlockSpec((CH_BLOCK, 1), lambda c: (c, 0)),
                  pl.BlockSpec((CH_BLOCK, 1), lambda c: (c, 0))],
        out_specs=[pl.BlockSpec((B_pad, CH_BLOCK, HW_pad),
                                lambda c: (0, c, 0)),
                   pl.BlockSpec((CH_BLOCK, LANE), lambda c: (c, 0)),
                   pl.BlockSpec((CH_BLOCK, LANE), lambda c: (c, 0))],
        out_shape=[
            jax.ShapeDtypeStruct((B_pad, C_pad, HW_pad), x.dtype),
            jax.ShapeDtypeStruct((C_pad, LANE), x.dtype),
            jax.ShapeDtypeStruct((C_pad, LANE), x.dtype),
        ],
        interpret=interpret,
    )(xp, gp, bp)
    return (y[:B, :C, :hw].reshape(B, C, H, W), mean[:C, 0], var[:C, 0])


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def fused_bn_act_train_4d(x, gamma, beta, eps: float = 1e-5,
                          act_name: str = "identity",
                          interpret: bool = False):
    """4-D per-channel fused BN+activation: -> (y, mean[C], var[C]).
    Single-device scope (the SPMD 4-D path stays on XLA sync-BN)."""
    return _fused_fwd_impl_4d(x, gamma, beta, eps, act_name, interpret)


def _fwd_4d(x, gamma, beta, eps, act_name, interpret):
    return _fused_fwd_impl_4d(x, gamma, beta, eps, act_name, interpret), \
        (x, gamma, beta)


def _bwd_4d(eps, act_name, interpret, residuals, cotangents):
    x, gamma, beta = residuals
    _, vjp = jax.vjp(
        lambda a, g, b: _reference_4d(a, g, b, eps, act_name),
        x, gamma, beta)
    return vjp(cotangents)


fused_bn_act_train_4d.defvjp(_fwd_4d, _bwd_4d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def fused_bn_act_train(x, gamma, beta, eps: float = 1e-5,
                       act_name: str = "identity",
                       interpret: bool = False,
                       axis_name=None):
    """-> (act(bn(x)), batch_mean, batch_var).

    One fused VMEM pass on TPU; under SPMD (``axis_name``) the moments are
    pmean-ed across the mesh axis (sync-BN) between a one-pass moments
    kernel and a one-pass normalize+activation kernel."""
    return _fused_fwd_impl(x, gamma, beta, eps, act_name, interpret,
                           axis_name)


def _fwd(x, gamma, beta, eps, act_name, interpret, axis_name):
    out = _fused_fwd_impl(x, gamma, beta, eps, act_name, interpret, axis_name)
    return out, (x, gamma, beta)


def _bwd(eps, act_name, interpret, axis_name, residuals, cotangents):
    x, gamma, beta = residuals
    _, vjp = jax.vjp(
        lambda a, g, b: _reference(a, g, b, eps, act_name, axis_name),
        x, gamma, beta)
    return vjp(cotangents)


fused_bn_act_train.defvjp(_fwd, _bwd)
