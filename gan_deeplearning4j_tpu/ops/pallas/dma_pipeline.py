"""Pallas TPU kernel: double-buffered DMA pipeline for the upsample
backward reduce — the 60.2MB byte sink hlo_cost_r5.json ranks #3 in the
fused step (RESULTS.md "Overlap experiment series").

The adjoint of a nearest-neighbour repeat is a factor-block sum:
``g[B,C,H*sh,W*sw] -> dx[B,C,H,W]`` summing each ``sh x sw`` block.  The
XLA lowering (ops/upsample.py sum-backward) is one fused strided reduce;
whether its HBM reads overlap anything is the scheduler's call.  This
kernel makes the overlap explicit: the cotangent streams HBM -> VMEM
through ``pltpu.make_async_copy`` into a two-slot scratch, and while
chunk ``i`` is being reduced the DMA engine is already fetching chunk
``i+1`` — compute hides under the copy it depends on (Pallas guide,
"Patterns: Double Buffering"; same grid discipline as bn_act.py).

Layout: ``g`` is viewed as ``[R, cols] = [B*C*H*sh, W*sw]`` — a free
contiguous reshape.  Per chunk of ``R``:

* the ``sw`` (lane-interleaved) sum is a dot with a static 0/1
  selection matrix ``S[W*sw, W]`` (``S[w*sw+t, w] = 1``) — the MXU does
  strided lane gathers for free, and at these widths the matmul is
  roofline-invisible (2*chunk*W*sw*W flops vs chunk*W*sw*4 bytes);
* the ``sh`` sum is a sublane-group reshape+sum, which Mosaic lowers
  natively.

Chunks must keep ``sh``-row groups whole, so the chunk size is the
largest divisor of ``R`` that is a multiple of ``lcm(sh, SUBLANE)`` and
fits the two-slot scratch budget; ``supports_upsample_bwd`` returns
False (callers fall back to the XLA path) when no such divisor exists
or the dtype isn't f32.

Opt-in like every kernel here: ``ops.pallas.enable(True)`` /
GAN4J_PALLAS=1, TPU-only at runtime, ``interpret=True`` in tests.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128
SUBLANE = 8
N_SLOTS = 2
# two scratch slots + the blocked output must fit comfortably under the
# ~16MB scoped-vmem limit alongside the selection matrix
_VMEM_BUDGET = 6 << 20


def _chunk_rows(rows: int, cols: int, sh: int) -> int:
    """Largest divisor of ``rows`` that keeps sh-row groups whole, tiles
    the sublanes, and fits N_SLOTS chunks in the scratch budget.
    Returns 0 when none exists."""
    base = (sh * SUBLANE) // math.gcd(sh, SUBLANE)
    cols_pad = -(-cols // LANE) * LANE  # VMEM lane padding is physical
    max_rows = _VMEM_BUDGET // (N_SLOTS * cols_pad * 4)
    max_k = min(max_rows, rows) // base
    for k in range(max_k, 0, -1):
        if rows % (base * k) == 0:
            return base * k
    return 0


def supports_upsample_bwd(g_shape, sh: int, sw: int, dtype) -> bool:
    """True iff the pipeline kernel handles this cotangent; callers fall
    back to the XLA strided-reduce lowering otherwise."""
    if dtype != jnp.float32 or len(g_shape) != 4:
        return False
    B, C, Hs, Wsw = g_shape
    if Hs % sh or Wsw % sw:
        return False
    return _chunk_rows(B * C * Hs, Wsw, sh) > 0


def _select_matrix(W: int, sw: int) -> jax.Array:
    s = np.zeros((W * sw, W), np.float32)
    for w in range(W):
        s[w * sw:(w + 1) * sw, w] = 1.0
    return jnp.asarray(s)


def _bwd_kernel(x_hbm, s_ref, out_ref, scratch, sems, *,
                chunk: int, sh: int, out_w: int):
    i = pl.program_id(0)
    n = pl.num_programs(0)

    def dma(slot, idx):
        return pltpu.make_async_copy(
            x_hbm.at[pl.ds(idx * chunk, chunk), :],
            scratch.at[slot],
            sems.at[slot])

    # warm-up: the first grid step issues its own fetch
    @pl.when(i == 0)
    def _():
        dma(0, 0).start()

    # prefetch chunk i+1 while chunk i is (or finishes) in flight; slot
    # (i+1)%2 was consumed at step i-1, so the overwrite is safe
    @pl.when(i + 1 < n)
    def _():
        dma((i + 1) % N_SLOTS, i + 1).start()

    dma(i % N_SLOTS, i).wait()
    x = scratch[i % N_SLOTS]                       # [chunk, W*sw]
    # sw-sum: lane-interleaved gather as an MXU dot with the 0/1 matrix
    col = jnp.dot(x, s_ref[:], preferred_element_type=jnp.float32)
    # sh-sum: sublane-group reduce
    out_ref[:] = col.reshape(chunk // sh, sh, out_w).sum(axis=1)


def upsample_bwd_dma(g: jax.Array, sh: int, sw: int, *,
                     interpret: bool = False) -> jax.Array:
    """dx[B,C,H,W] = the (sh, sw) block sum of g[B,C,H*sh,W*sw], streamed
    through the double-buffered pipeline.  Caller must have checked
    ``supports_upsample_bwd``."""
    B, C, Hs, Wsw = g.shape
    H, W = Hs // sh, Wsw // sw
    rows = B * C * Hs
    chunk = _chunk_rows(rows, Wsw, sh)
    if chunk <= 0:  # defensive: supports_upsample_bwd gates callers
        return g.reshape(B, C, H, sh, W, sw).sum(axis=(3, 5))
    kernel = functools.partial(_bwd_kernel, chunk=chunk, sh=sh, out_w=W)
    out = pl.pallas_call(
        kernel,
        grid=(rows // chunk,),
        in_specs=[
            # the cotangent stays in HBM; the kernel DMAs its own chunks
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec((Wsw, W), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((chunk // sh, W), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows // sh, W), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((N_SLOTS, chunk, Wsw), jnp.float32),
            pltpu.SemaphoreType.DMA((N_SLOTS,)),
        ],
        interpret=interpret,
    )(g.reshape(rows, Wsw), _select_matrix(W, sw))
    return out.reshape(B, C, H, W)
