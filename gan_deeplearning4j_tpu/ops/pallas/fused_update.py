"""Pallas TPU kernel: the full RmsProp update chain in one VMEM pass.

RESULTS r2 §4 profiled the updater's elementwise chain
(``multiply_subtract_fusion``: L2 -> clip -> cache EMA -> rsqrt scale ->
param subtract, plus BN-stat merges) at 61ms/300 steps ≈ 10% of protocol
device time.  The chain is HBM-bandwidth bound — per leaf it must read
{p, g, cache} and write {p', cache'}, 5N floats — so the kernel's job is
to guarantee the bound is actually met for the big dense leaves: ONE
pallas pass per leaf computes the entire chain in VMEM (XLA usually
fuses this too; where it splits the chain or pads small fusions, the
hand kernel pins the floor).

DL4J chain reproduced exactly (optim/updater.py; RmsProp is the
reference's pinned updater, dl4jGANComputerVision.java:128):

    g   = clip(g + l2*p, +-clip)        # l2 on W-class leaves only
    c'  = rho*c + (1-rho)*g^2
    p'  = p - lr * g * rsqrt(c' + eps)

Used by GraphUpdater.apply for leaves >= ``MIN_FUSED_SIZE`` when
``ops.pallas.enable(True)`` (or GAN4J_PALLAS=1) — same opt-in discipline
as bn_act.py.  Gradients never flow through the updater, so no custom
VJP is needed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

LANE = 128
BLOCK_ROWS = 512          # 512x128 f32 tile = 256KB/operand in VMEM
MIN_FUSED_SIZE = 1 << 16  # leaves below 64K elements stay on XLA's path


def _chain_kernel(p_ref, g_ref, c_ref, p_out, c_out, *,
                  lr: float, rho: float, eps: float, l2: float,
                  clip: float | None):
    g = g_ref[:]
    p = p_ref[:]
    if l2:
        g = g + l2 * p
    if clip is not None:
        g = jnp.clip(g, -clip, clip)
    c = rho * c_ref[:] + (1.0 - rho) * g * g
    p_out[:] = p - lr * g * lax.rsqrt(c + eps)
    c_out[:] = c


def fused_rmsprop_chain(p, g, cache, *, lr: float, rho: float, eps: float,
                        l2: float = 0.0, clip: float | None = None,
                        interpret: bool = False):
    """(new_p, new_cache) for one leaf, any shape — flattened into
    [rows, 128] tiles, one kernel pass.  (No buffer aliasing: the tiling
    pad/reshape makes fresh temporaries anyway, and donation-style
    aliasing under lax.scan crashes the axon runtime — the
    train/fused_step.py caveat.)"""
    shape, dtype = p.shape, p.dtype
    n = p.size
    rows = -(-n // LANE)
    rows_pad = -(-rows // BLOCK_ROWS) * BLOCK_ROWS

    def tile(x):
        flat = x.reshape(-1)
        flat = jnp.pad(flat, (0, rows_pad * LANE - n))
        return flat.reshape(rows_pad, LANE)

    spec = pl.BlockSpec((BLOCK_ROWS, LANE), lambda i: (i, 0))
    kernel = functools.partial(_chain_kernel, lr=lr, rho=rho, eps=eps,
                               l2=l2, clip=clip)
    new_p, new_c = pl.pallas_call(
        kernel,
        grid=(rows_pad // BLOCK_ROWS,),
        in_specs=[spec, spec, spec],
        out_specs=[spec, spec],
        out_shape=[jax.ShapeDtypeStruct((rows_pad, LANE), dtype)] * 2,
        interpret=interpret,
    )(tile(p), tile(g), tile(cache))
    return (new_p.reshape(-1)[:n].reshape(shape),
            new_c.reshape(-1)[:n].reshape(shape))
