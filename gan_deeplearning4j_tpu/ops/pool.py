"""Pooling ops (DL4J SubsamplingLayer equivalents).

The reference uses the unusual max-pool 2x2 **stride 1**
(dl4jGANComputerVision.java:134-138 — kernel (2,2), stride (1,1), Truncate),
which shrinks each spatial dim by exactly 1.  Lowered to
``lax.reduce_window`` which XLA maps onto the VPU.

Backward: by default the recomputed-argmax form (RESULTS.md "Overlap
experiment series") instead of the ``select-and-scatter`` op autodiff
emits — hlo_cost_r5.json names select-and-scatter as a top byte sink
(41.9MB at b200, ~0.5ms of estimated time at b1600) and TPUs lower it as
a slow sequential window walk.  The restructured backward recomputes the
window max from the saved input (no stored argmax, no extra residual) and
scatters each output cotangent to the FIRST window element equal to the
max, walking window offsets in row-major order — exactly
select-and-scatter's ``GE`` tie rule, so gradients match the reference
lowering elementwise.  Every piece is an elementwise/pad op XLA fuses and
overlaps, unlike the opaque select-and-scatter.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

_ARGMAX_BWD = True


def set_argmax_bwd(on: bool) -> None:
    """Toggle the recomputed-argmax backward (trace-time flag); off = the
    select-and-scatter autodiff lowering, kept as the A/B baseline."""
    global _ARGMAX_BWD
    _ARGMAX_BWD = bool(on)


def _reduce_window_max(x, kh, kw, sh, sw, ph, pw):
    return lax.reduce_window(
        x,
        -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
        lax.max,
        window_dimensions=(1, 1, kh, kw),
        window_strides=(1, 1, sh, sw),
        padding=[(0, 0), (0, 0), (ph, ph), (pw, pw)],
    )


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5, 6))
def _max_pool2d_argmax(x, kh, kw, sh, sw, ph, pw):
    return _reduce_window_max(x, kh, kw, sh, sw, ph, pw)


def _max_pool2d_fwd(x, kh, kw, sh, sw, ph, pw):
    return _reduce_window_max(x, kh, kw, sh, sw, ph, pw), x


def _max_pool2d_bwd(kh, kw, sh, sw, ph, pw, x, g):
    B, C, H, W = x.shape
    Hp, Wp = H + 2 * ph, W + 2 * pw
    Ho, Wo = (Hp - kh) // sh + 1, (Wp - kw) // sw + 1
    xp = jnp.pad(x, [(0, 0), (0, 0), (ph, ph), (pw, pw)],
                 constant_values=-jnp.inf) if (ph or pw) else x

    # each window offset (i, j) as a strided view aligned to the output
    # grid: view[b, c, o, p] = xp[b, c, o*sh + i, p*sw + j]
    def view(i, j):
        return lax.slice(
            xp, (0, 0, i, j),
            (B, C, i + (Ho - 1) * sh + 1, j + (Wo - 1) * sw + 1),
            (1, 1, sh, sw))

    offsets = [(i, j) for i in range(kh) for j in range(kw)]
    # recompute the window max from the saved input (elementwise tree of
    # maxes — no reduce_window in the backward, no stored argmax/indices)
    y = view(0, 0)
    for i, j in offsets[1:]:
        y = jnp.maximum(y, view(i, j))

    dxp = jnp.zeros((B, C, Hp, Wp), g.dtype)
    claimed = jnp.zeros((B, C, Ho, Wo), jnp.bool_)
    for i, j in offsets:  # row-major = select-and-scatter's GE tie order
        hit = (view(i, j) == y) & ~claimed
        claimed = claimed | hit
        contrib = jnp.where(hit, g, jnp.zeros((), g.dtype))
        # scatter the output-grid contribution back onto the padded input
        # frame: offset by (i, j), stride via interior padding
        dxp = dxp + lax.pad(
            contrib, jnp.zeros((), g.dtype),
            [(0, 0, 0), (0, 0, 0),
             (i, Hp - (i + (Ho - 1) * sh + 1), sh - 1),
             (j, Wp - (j + (Wo - 1) * sw + 1), sw - 1)])
    dx = dxp[:, :, ph:ph + H, pw:pw + W] if (ph or pw) else dxp
    return (dx,)


_max_pool2d_argmax.defvjp(_max_pool2d_fwd, _max_pool2d_bwd)


def max_pool2d(
    x: jax.Array,
    kernel: Sequence[int] = (2, 2),
    stride: Sequence[int] = (2, 2),
    padding: Sequence[int] = (0, 0),
) -> jax.Array:
    """x: [B, C, H, W]; DL4J Truncate (VALID after explicit padding)."""
    kh, kw = kernel
    sh, sw = stride
    ph, pw = padding
    if _ARGMAX_BWD and jnp.issubdtype(x.dtype, jnp.floating):
        return _max_pool2d_argmax(x, int(kh), int(kw), int(sh), int(sw),
                                  int(ph), int(pw))
    return _reduce_window_max(x, kh, kw, sh, sw, ph, pw)


def avg_pool2d(
    x: jax.Array,
    kernel: Sequence[int] = (2, 2),
    stride: Sequence[int] = (2, 2),
    padding: Sequence[int] = (0, 0),
) -> jax.Array:
    kh, kw = kernel
    sh, sw = stride
    ph, pw = padding
    summed = lax.reduce_window(
        x,
        jnp.zeros((), x.dtype),
        lax.add,
        window_dimensions=(1, 1, kh, kw),
        window_strides=(1, 1, sh, sw),
        padding=[(0, 0), (0, 0), (ph, ph), (pw, pw)],
    )
    return summed / (kh * kw)
