"""Pooling ops (DL4J SubsamplingLayer equivalents).

The reference uses the unusual max-pool 2x2 **stride 1**
(dl4jGANComputerVision.java:134-138 — kernel (2,2), stride (1,1), Truncate),
which shrinks each spatial dim by exactly 1.  Lowered to
``lax.reduce_window`` which XLA maps onto the VPU.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax


def max_pool2d(
    x: jax.Array,
    kernel: Sequence[int] = (2, 2),
    stride: Sequence[int] = (2, 2),
    padding: Sequence[int] = (0, 0),
) -> jax.Array:
    """x: [B, C, H, W]; DL4J Truncate (VALID after explicit padding)."""
    kh, kw = kernel
    sh, sw = stride
    ph, pw = padding
    return lax.reduce_window(
        x,
        -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min,
        lax.max,
        window_dimensions=(1, 1, kh, kw),
        window_strides=(1, 1, sh, sw),
        padding=[(0, 0), (0, 0), (ph, ph), (pw, pw)],
    )


def avg_pool2d(
    x: jax.Array,
    kernel: Sequence[int] = (2, 2),
    stride: Sequence[int] = (2, 2),
    padding: Sequence[int] = (0, 0),
) -> jax.Array:
    kh, kw = kernel
    sh, sw = stride
    ph, pw = padding
    summed = lax.reduce_window(
        x,
        jnp.zeros((), x.dtype),
        lax.add,
        window_dimensions=(1, 1, kh, kw),
        window_strides=(1, 1, sh, sw),
        padding=[(0, 0), (0, 0), (ph, ph), (pw, pw)],
    )
    return summed / (kh * kw)
