"""Upsampling2D — nearest-neighbour repeat, DL4J Upsampling2D equivalent.

The reference's generator "deconv" layers are Upsampling2D(2) followed by a
stride-1 conv (dl4jGANComputerVision.java:191-209), NOT transposed
convolution (SURVEY.md §3.3 note).  ``conv_transpose2d`` is provided for the
roadmap model families that do use real deconvs.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

from gan_deeplearning4j_tpu.ops.conv import DIMENSION_NUMBERS


def upsample2d(x: jax.Array, size: int | Sequence[int] = 2) -> jax.Array:
    """x: [B, C, H, W] -> [B, C, H*sh, W*sw] by nearest-neighbour repeat."""
    if isinstance(size, int):
        sh = sw = size
    else:
        sh, sw = size
    x = jnp.repeat(x, sh, axis=2)
    x = jnp.repeat(x, sw, axis=3)
    return x


def conv_transpose2d(
    x: jax.Array,
    w: jax.Array,
    b: jax.Array | None = None,
    stride: Sequence[int] = (2, 2),
    padding: Sequence[int] = (0, 0),
) -> jax.Array:
    """Real transposed conv (for roadmap DCGAN variants). w: [O, I, kh, kw]."""
    ph, pw = padding
    out = lax.conv_transpose(
        x,
        w,
        strides=tuple(stride),
        padding=[(ph, ph), (pw, pw)],
        dimension_numbers=DIMENSION_NUMBERS,
        transpose_kernel=True,
    )
    if b is not None:
        out = out + b.reshape(1, -1, 1, 1)
    return out
