"""Upsampling2D — nearest-neighbour repeat, DL4J Upsampling2D equivalent.

The reference's generator "deconv" layers are Upsampling2D(2) followed by a
stride-1 conv (dl4jGANComputerVision.java:191-209), NOT transposed
convolution (SURVEY.md §3.3 note).  ``conv_transpose2d`` is provided for the
roadmap model families that do use real deconvs.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

from gan_deeplearning4j_tpu.ops.conv import DIMENSION_NUMBERS

# Rematerialized upsample backward (RESULTS.md "Overlap experiment
# series"): ``jnp.repeat``'s autodiff transpose lowers to the 60.2MB
# broadcast+reduce chain hlo_cost_r5.json names as the #3 byte sink of
# the fused step.  The exact adjoint of a nearest-neighbour repeat is a
# factor-block sum: reshape [B,C,H*sh,W*sw] -> [B,C,H,sh,W,sw] (a free
# bitcast — the split dims are exactly the row-major strides) and sum
# the (sh, sw) axes — ONE fused strided reduce that reads the cotangent
# once.  False = the pre-restructure autodiff lowering, kept as the A/B
# baseline.
_SUM_BWD = True


def set_sum_bwd(on: bool) -> None:
    """Toggle the restructured reshape-sum backward (trace-time flag)."""
    global _SUM_BWD
    _SUM_BWD = bool(on)


def _repeat2d(x: jax.Array, sh: int, sw: int) -> jax.Array:
    x = jnp.repeat(x, sh, axis=2)
    x = jnp.repeat(x, sw, axis=3)
    return x


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _upsample2d_sumbwd(x: jax.Array, sh: int, sw: int) -> jax.Array:
    return _repeat2d(x, sh, sw)


def _upsample2d_fwd(x, sh, sw):
    return _repeat2d(x, sh, sw), x.shape


def _upsample2d_bwd(sh, sw, x_shape, g):
    B, C, H, W = x_shape
    # Opt-in Pallas path (GAN4J_PALLAS=1 / ops.pallas.enable): stream the
    # cotangent through the double-buffered DMA pipeline so the reduce's
    # HBM reads overlap compute explicitly instead of at the scheduler's
    # discretion.  Lazy import: ops.pallas pulls in the kernel stack.
    from gan_deeplearning4j_tpu.ops import pallas as pallas_kernels
    if pallas_kernels.enabled():
        from gan_deeplearning4j_tpu.ops.pallas import dma_pipeline
        if dma_pipeline.supports_upsample_bwd(g.shape, sh, sw, g.dtype):
            return (dma_pipeline.upsample_bwd_dma(g, sh, sw),)
    dx = g.reshape(B, C, H, sh, W, sw).sum(axis=(3, 5))
    return (dx,)


_upsample2d_sumbwd.defvjp(_upsample2d_fwd, _upsample2d_bwd)


def upsample2d(x: jax.Array, size: int | Sequence[int] = 2) -> jax.Array:
    """x: [B, C, H, W] -> [B, C, H*sh, W*sw] by nearest-neighbour repeat."""
    if isinstance(size, int):
        sh = sw = size
    else:
        sh, sw = size
    if _SUM_BWD:
        return _upsample2d_sumbwd(x, int(sh), int(sw))
    return _repeat2d(x, sh, sw)


def conv_transpose2d(
    x: jax.Array,
    w: jax.Array,
    b: jax.Array | None = None,
    stride: Sequence[int] = (2, 2),
    padding: Sequence[int] = (0, 0),
    *,
    bf16: bool = False,
) -> jax.Array:
    """Real transposed conv (for roadmap DCGAN variants). w: [O, I, kh, kw]
    mapping I input channels to O output channels.

    Implemented as the equivalent input-dilated forward conv (the form XLA
    lowers best on the MXU): dilate x by ``stride``, pad by ``k-1-p``, and
    convolve with the spatially-flipped kernel.  Output size per dim:
    ``(in - 1)*stride - 2*pad + kernel`` (torch ConvTranspose2d arithmetic,
    matching layers.ConvTranspose2D.out_shape).
    """
    orig_dtype = x.dtype
    if bf16:
        # bf16 MXU operands, result cast back (same rationale as conv2d)
        x = x.astype(jnp.bfloat16)
        w = w.astype(jnp.bfloat16)
    sh, sw = stride
    ph, pw = padding
    kh, kw = w.shape[2], w.shape[3]
    out = lax.conv_general_dilated(
        x,
        w[:, :, ::-1, ::-1],
        window_strides=(1, 1),
        padding=[(kh - 1 - ph, kh - 1 - ph), (kw - 1 - pw, kw - 1 - pw)],
        lhs_dilation=(sh, sw),
        dimension_numbers=DIMENSION_NUMBERS,
    )
    if bf16:
        out = out.astype(orig_dtype)
    if b is not None:
        out = out + b.reshape(1, -1, 1, 1)
    return out
