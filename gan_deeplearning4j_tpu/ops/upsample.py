"""Upsampling2D — nearest-neighbour repeat, DL4J Upsampling2D equivalent.

The reference's generator "deconv" layers are Upsampling2D(2) followed by a
stride-1 conv (dl4jGANComputerVision.java:191-209), NOT transposed
convolution (SURVEY.md §3.3 note).  ``conv_transpose2d`` is provided for the
roadmap model families that do use real deconvs.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

from gan_deeplearning4j_tpu.ops.conv import DIMENSION_NUMBERS


def upsample2d(x: jax.Array, size: int | Sequence[int] = 2) -> jax.Array:
    """x: [B, C, H, W] -> [B, C, H*sh, W*sw] by nearest-neighbour repeat."""
    if isinstance(size, int):
        sh = sw = size
    else:
        sh, sw = size
    x = jnp.repeat(x, sh, axis=2)
    x = jnp.repeat(x, sw, axis=3)
    return x


def conv_transpose2d(
    x: jax.Array,
    w: jax.Array,
    b: jax.Array | None = None,
    stride: Sequence[int] = (2, 2),
    padding: Sequence[int] = (0, 0),
    *,
    bf16: bool = False,
) -> jax.Array:
    """Real transposed conv (for roadmap DCGAN variants). w: [O, I, kh, kw]
    mapping I input channels to O output channels.

    Implemented as the equivalent input-dilated forward conv (the form XLA
    lowers best on the MXU): dilate x by ``stride``, pad by ``k-1-p``, and
    convolve with the spatially-flipped kernel.  Output size per dim:
    ``(in - 1)*stride - 2*pad + kernel`` (torch ConvTranspose2d arithmetic,
    matching layers.ConvTranspose2D.out_shape).
    """
    orig_dtype = x.dtype
    if bf16:
        # bf16 MXU operands, result cast back (same rationale as conv2d)
        x = x.astype(jnp.bfloat16)
        w = w.astype(jnp.bfloat16)
    sh, sw = stride
    ph, pw = padding
    kh, kw = w.shape[2], w.shape[3]
    out = lax.conv_general_dilated(
        x,
        w[:, :, ::-1, ::-1],
        window_strides=(1, 1),
        padding=[(kh - 1 - ph, kh - 1 - ph), (kw - 1 - pw, kw - 1 - pw)],
        lhs_dilation=(sh, sw),
        dimension_numbers=DIMENSION_NUMBERS,
    )
    if bf16:
        out = out.astype(orig_dtype)
    if b is not None:
        out = out + b.reshape(1, -1, 1, 1)
    return out
