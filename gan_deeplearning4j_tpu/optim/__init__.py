from gan_deeplearning4j_tpu.optim.adagrad import AdaGrad  # noqa: F401
from gan_deeplearning4j_tpu.optim.adam import Adam  # noqa: F401
from gan_deeplearning4j_tpu.optim.rmsprop import (  # noqa: F401
    RmsProp,
    rmsprop_init,
    rmsprop_update,
)
from gan_deeplearning4j_tpu.optim.schedules import (  # noqa: F401
    ExponentialSchedule,
    PolySchedule,
    Scheduled,
    SigmoidSchedule,
    StepSchedule,
)
from gan_deeplearning4j_tpu.optim.sgd import Nesterovs, Sgd  # noqa: F401
from gan_deeplearning4j_tpu.optim.updater import GraphUpdater  # noqa: F401
