"""AdaGrad — DL4J's ``org.nd4j.linalg.learning.config.AdaGrad`` equivalent.

DL4J's AdaGradUpdater accumulates the squared-gradient history and scales
by the root of the (epsilon-shifted) history:

    h' = h + g^2
    update = lr * g / sqrt(h' + eps)

Defaults are DL4J's (lr 1e-1, eps 1e-6).  Same per-leaf updater protocol
as RmsProp/Adam/Sgd — see optim/updater.py.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class AdaGrad:
    learning_rate: float = 0.1
    epsilon: float = 1e-6

    def init_leaf(self, p):
        return jnp.zeros_like(p)

    def update_leaf(self, g, h):
        h_new = h + g * g
        update = self.learning_rate * g * jax.lax.rsqrt(h_new + self.epsilon)
        return update, h_new
