"""Adam — DL4J's ``org.nd4j.linalg.learning.config.Adam`` equivalent.

The reference pins RmsProp(lr, 1e-8, 1e-8) on every layer — an effective
sign-SGD (optim/rmsprop.py) that the two reference workloads are
calibrated around, but which collapses the deeper roadmap GANs
(cGAN-CIFAR10 / WGAN-GP / CelebA-64: measured D-loss -> 0, G-loss -> 16
within 2k iterations).  DL4J itself ships Adam for exactly these cases;
this is its TPU-native counterpart with the standard bias-corrected rule:

    m = b1*m + (1-b1)*g        mhat = m / (1 - b1^t)
    v = b2*v + (1-b2)*g^2      vhat = v / (1 - b2^t)
    update = lr * mhat / (sqrt(vhat) + eps)

Implements the same per-leaf updater protocol as RmsProp, so a graph can
mix both across layers and the whole update stays one fused XLA program.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Adam:
    learning_rate: float = 0.001
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def init_leaf(self, p):
        return {
            "m": jnp.zeros_like(p),
            "v": jnp.zeros_like(p),
            "t": jnp.zeros((), dtype=jnp.float32),
        }

    def update_leaf(self, g, state):
        t = state["t"] + 1.0
        m = self.beta1 * state["m"] + (1.0 - self.beta1) * g
        v = self.beta2 * state["v"] + (1.0 - self.beta2) * g * g
        mhat = m / (1.0 - jnp.power(self.beta1, t))
        vhat = v / (1.0 - jnp.power(self.beta2, t))
        update = self.learning_rate * mhat / (jnp.sqrt(vhat) + self.epsilon)
        return update, {"m": m, "v": v, "t": t}
