"""Generator weight EMA — one rule shared by both training engines.

The protocol trainer (train/fused_step.py) and the roadmap engine
(train/gan_pair.py) carry the same trajectory-averaged generator; the
seeding and update rules live here so the two cannot silently diverge.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def ema_init(gen):
    """Seed an EMA tree from a generator graph: resume from a carried
    ``ema_params`` when present, else the live params.  Fresh buffers,
    NOT aliases of the live params — the carry pytree may be donated,
    and donating the same buffer under two leaves is undefined (observed
    as a wedged CPU collective rendezvous)."""
    src = getattr(gen, "ema_params", None) or gen.params
    return jax.tree_util.tree_map(jnp.copy, src)


def ema_update(ema, params, decay: float):
    """One EMA step: ema <- decay*ema + (1-decay)*params."""
    return jax.tree_util.tree_map(
        lambda e, p: decay * e + (1.0 - decay) * p, ema, params)
