"""RmsProp with DL4J's exact parameterization and update rule.

The reference constructs ``new RmsProp(learningRate, rmsDecay, epsilon)`` with
the odd values (lr, 1e-8, 1e-8) on every layer (e.g.
dl4jGANComputerVision.java:128).  DL4J's RmsPropUpdater computes:

    cache  = rmsDecay * cache + (1 - rmsDecay) * g^2
    update = lr * g / sqrt(cache + eps)

Note eps is added *inside* the sqrt (unlike optax.rmsprop, which adds it
outside) — with rmsDecay=1e-8 the cache is ~g^2, so the update is
~lr * sign(g): effectively signSGD.  Reproducing this exactly matters for
training-dynamics parity; hence a hand-rolled kernel rather than optax.

"Frozen" layers in the reference are lr=0.0 (not DL4J FrozenLayer) —
SURVEY.md appendix; per-leaf lr support makes that a scale, not a branch.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class RmsProp:
    """Per-layer updater config (DL4J constructor argument order).

    Implements the per-leaf updater protocol (``init_leaf`` /
    ``update_leaf``) shared with optim.adam.Adam so GraphUpdater can mix
    updater kinds across layers."""

    learning_rate: float = 0.001
    rms_decay: float = 1e-8
    epsilon: float = 1e-8

    def init_leaf(self, p):
        return jnp.zeros_like(p)

    def update_leaf(self, g, state):
        return rmsprop_update_leaf(
            g, state, self.learning_rate, self.rms_decay, self.epsilon)


def rmsprop_init(params):
    """Cache ("lastGradient") zero-initialized, one slot per param leaf."""
    return jax.tree_util.tree_map(jnp.zeros_like, params)


def rmsprop_update_leaf(g, cache, lr, rms_decay, eps):
    new_cache = rms_decay * cache + (1.0 - rms_decay) * g * g
    update = lr * g * jax.lax.rsqrt(new_cache + eps)
    return update, new_cache


def rmsprop_update(grads, cache, lr_tree, rms_decay: float, eps: float):
    """Apply the DL4J RmsProp rule leaf-wise.

    ``lr_tree`` is either a scalar or a pytree of per-leaf learning rates
    (the per-layer-lr mechanism; frozen = 0.0).
    Returns (updates, new_cache); caller does param -= update.
    """
    if isinstance(lr_tree, (int, float)):
        lr_tree = jax.tree_util.tree_map(lambda g: lr_tree, grads)
    flat = jax.tree_util.tree_map(
        lambda g, c, lr: rmsprop_update_leaf(g, c, lr, rms_decay, eps),
        grads,
        cache,
        lr_tree,
    )
    updates = jax.tree_util.tree_map(lambda t: t[0], flat, is_leaf=lambda t: isinstance(t, tuple))
    new_cache = jax.tree_util.tree_map(lambda t: t[1], flat, is_leaf=lambda t: isinstance(t, tuple))
    return updates, new_cache
