"""Learning-rate schedules — DL4J's ``ISchedule`` set.

DL4J's updaters accept an ``ISchedule`` in place of a fixed learning rate
(``org.nd4j.linalg.schedule``: Step, Exponential, Poly, Sigmoid, Map...);
the reference pins fixed rates, but the stack provides schedules and a
DL4J user expects them.  Schedules here are plain callables ``t -> lr``
(``t`` = iteration count, a traced scalar inside the fused step), and
``Scheduled`` lifts ANY per-leaf updater into a scheduled one by tracking
``t`` in its state and re-parameterizing the base updater each step — so
the schedule enters momentum/cache recurrences exactly as DL4J's do, not
as a post-hoc scaling.

    sched = Scheduled(Nesterovs(momentum=0.9), StepSchedule(0.1, 0.5, 1000))
    GraphUpdater({"layer": sched, ...})
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class StepSchedule:
    """lr * decay^floor(t / step) — DL4J StepSchedule."""

    initial_lr: float
    decay_rate: float
    step: float

    def __call__(self, t):
        return self.initial_lr * jnp.power(
            self.decay_rate, jnp.floor(t / self.step))


@dataclasses.dataclass(frozen=True)
class ExponentialSchedule:
    """lr * gamma^t — DL4J ExponentialSchedule."""

    initial_lr: float
    gamma: float

    def __call__(self, t):
        return self.initial_lr * jnp.power(self.gamma, t)


@dataclasses.dataclass(frozen=True)
class PolySchedule:
    """lr * (1 - t/max_iter)^power — DL4J PolySchedule."""

    initial_lr: float
    power: float
    max_iter: float

    def __call__(self, t):
        frac = jnp.clip(1.0 - t / self.max_iter, 0.0, 1.0)
        return self.initial_lr * jnp.power(frac, self.power)


@dataclasses.dataclass(frozen=True)
class SigmoidSchedule:
    """lr / (1 + exp(-gamma * (t - step))) — DL4J SigmoidSchedule
    (Caffe's sigmoid policy: ramps toward initial_lr past ``step`` for
    positive gamma; pass negative gamma for a sigmoid decay)."""

    initial_lr: float
    gamma: float
    step: float

    def __call__(self, t):
        return self.initial_lr / (
            1.0 + jnp.exp(-self.gamma * (t - self.step)))


@dataclasses.dataclass(frozen=True)
class Scheduled:
    """Wrap a per-leaf updater with a schedule for its learning rate.

    State = {"t": iteration scalar, "inner": base updater state}; each
    step re-parameterizes the base updater with ``schedule(t)`` so the
    scheduled rate flows through the base rule's own recurrence.
    Implements the shared per-leaf protocol, so it slots anywhere a plain
    updater does (GraphUpdater layers, mixed per layer).
    """

    base: object
    schedule: Callable

    @property
    def learning_rate(self) -> float:
        # GraphUpdater.lr_for reports a float; the schedule's t=0 value is
        # the honest scalar summary
        return float(self.schedule(0.0))

    def init_leaf(self, p):
        # int32 counter: a float32 t would stop incrementing at 2^24
        return {"t": jnp.zeros((), dtype=jnp.int32),
                "inner": self.base.init_leaf(p)}

    def update_leaf(self, g, state):
        lr = self.schedule(state["t"].astype(jnp.float32))
        stepped = dataclasses.replace(self.base, learning_rate=lr)
        update, inner = stepped.update_leaf(g, state["inner"])
        return update, {"t": state["t"] + 1, "inner": inner}
