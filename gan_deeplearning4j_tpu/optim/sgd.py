"""Sgd / Nesterovs — DL4J's plain and momentum updaters.

The reference pins RmsProp on every layer, but the stack it exercises
ships the full ``org.nd4j.linalg.learning.config`` updater set (pulled in
via deeplearning4j-nn, Java/pom.xml:100-103) and a DL4J user switching to
this framework expects the standard members.  Rules match DL4J's
implementations:

    Sgd:        update = lr * g
    Nesterovs:  v' = mu * v - lr * g
                update = mu * v - (1 + mu) * v'      (so that
                param -= update  ==  the cs231n/DL4J form
                param += -mu * v + (1 + mu) * v')

Defaults are DL4J's (Sgd lr 1e-1 is DL4J's DEFAULT_SGD_LR; Nesterovs
lr 0.1, momentum 0.9).  Both implement the per-leaf updater protocol
(``init_leaf`` / ``update_leaf``) shared with RmsProp/Adam, so kinds can
mix across the layers of one graph and the whole update stays one fused
XLA program.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Sgd:
    learning_rate: float = 0.1

    def init_leaf(self, p):
        # stateless; a zero scalar keeps the state-tree shape uniform
        return jnp.zeros((), dtype=jnp.float32)

    def update_leaf(self, g, state):
        return self.learning_rate * g, state


@dataclasses.dataclass(frozen=True)
class Nesterovs:
    learning_rate: float = 0.1
    momentum: float = 0.9

    def init_leaf(self, p):
        return jnp.zeros_like(p)

    def update_leaf(self, g, v):
        v_new = self.momentum * v - self.learning_rate * g
        update = self.momentum * v - (1.0 + self.momentum) * v_new
        return update, v_new
