"""Graph-level updater: L2 -> elementwise clip -> per-layer RmsProp.

Reproduces DL4J's update pipeline for the reference's configuration
(dl4jGANComputerVision.java:117-125): L2 weight decay 1e-4 added to the
gradient of weight-class params (W/gamma — not biases/beta, DL4J's default
regularization split), then ClipElementWiseAbsoluteValue at 1.0, then the
per-layer RmsProp rule.  The whole pipeline is pure pytree math, so it lives
inside the jitted train step — one fused XLA computation per step instead of
the reference's per-layer native-updater dispatch.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from gan_deeplearning4j_tpu.optim.rmsprop import RmsProp

# DL4J regularizes "weight" params (W, gamma is excluded in DL4J: BN gamma/beta
# have no L2 by default; biases excluded by default l2Bias=0).
_L2_PARAM_NAMES = frozenset({"W"})

# layers without an explicit updater are frozen (the reference's
# freezing-by-zero-lr mechanism); the default rms_decay/epsilon values
# don't matter at lr 0 but keep DL4J's
_FROZEN = RmsProp(0.0, 1e-8, 1e-8)


class GraphUpdater:
    """Per-layer updater over a {layer: {param: array}} tree.

    Each layer's updater is any object with the per-leaf protocol
    (``init_leaf(p)`` / ``update_leaf(g, state) -> (update, new_state)``)
    — RmsProp (the reference's pinned choice) and Adam (roadmap families)
    both implement it, and kinds can mix across layers of one graph."""

    def __init__(
        self,
        layer_updaters: Dict[str, object],
        l2: float = 0.0,
        clip_threshold: float | None = 1.0,
        rms_decay: float = 1e-8,
        epsilon: float = 1e-8,
    ):
        self.layer_updaters = dict(layer_updaters)
        self.l2 = float(l2)
        self.clip_threshold = clip_threshold
        # kept for backward compatibility of the constructor signature;
        # per-layer updaters carry their own hyperparameters
        self.rms_decay = float(rms_decay)
        self.epsilon = float(epsilon)

    def _updater_for(self, layer: str):
        return self.layer_updaters.get(layer) or _FROZEN

    def _fused_chain(self, up, p, g, c, l2: float):
        """Pallas one-pass update chain for big RmsProp leaves (opt-in via
        ops.pallas.enable; ops/pallas/fused_update.py).  None = take the
        plain-jnp path (small leaf, other updater kind, or Pallas off)."""
        if not isinstance(up, RmsProp):
            return None
        from gan_deeplearning4j_tpu.ops import pallas as pallas_mod

        if not pallas_mod.enabled():
            return None
        from gan_deeplearning4j_tpu.ops.pallas import fused_update

        if p.size < fused_update.MIN_FUSED_SIZE:
            return None
        return fused_update.fused_rmsprop_chain(
            p, g, c, lr=up.learning_rate, rho=up.rms_decay, eps=up.epsilon,
            l2=l2, clip=self.clip_threshold)

    def init(self, params):
        return {
            layer: {
                pname: self._updater_for(layer).init_leaf(p)
                for pname, p in layer_params.items()
            }
            for layer, layer_params in params.items()
        }

    def lr_for(self, layer: str) -> float:
        return float(self._updater_for(layer).learning_rate)

    def apply(self, params, grads, cache):
        """Returns (new_params, new_cache). Pure; call inside jit."""
        new_params = {}
        new_cache = {}
        for layer, layer_grads in grads.items():
            up = self._updater_for(layer)
            new_params[layer] = dict(params[layer])
            new_cache[layer] = dict(cache.get(layer, {}))
            for pname, g in layer_grads.items():
                p = params[layer][pname]
                l2 = self.l2 if pname in _L2_PARAM_NAMES else 0.0
                fused = self._fused_chain(up, p, g, cache[layer][pname], l2)
                if fused is not None:
                    new_params[layer][pname], new_cache[layer][pname] = fused
                    continue
                if l2 > 0.0:
                    g = g + l2 * p
                if self.clip_threshold is not None:
                    g = jnp.clip(g, -self.clip_threshold, self.clip_threshold)
                update, c2 = up.update_leaf(g, cache[layer][pname])
                new_params[layer][pname] = p - update
                new_cache[layer][pname] = c2
            # params without grads (e.g. BN running mean/var) pass through via
            # the dict(params[layer]) copy above.
        # layers with no grads at all (pure-stateless layers) pass through.
        for layer in params:
            if layer not in new_params:
                new_params[layer] = params[layer]
                new_cache[layer] = cache.get(layer, {})
        return new_params, new_cache
