"""Graph-level updater: L2 -> elementwise clip -> per-layer RmsProp.

Reproduces DL4J's update pipeline for the reference's configuration
(dl4jGANComputerVision.java:117-125): L2 weight decay 1e-4 added to the
gradient of weight-class params (W/gamma — not biases/beta, DL4J's default
regularization split), then ClipElementWiseAbsoluteValue at 1.0, then the
per-layer RmsProp rule.  The whole pipeline is pure pytree math, so it lives
inside the jitted train step — one fused XLA computation per step instead of
the reference's per-layer native-updater dispatch.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from gan_deeplearning4j_tpu.ops.clipping import clip_elementwise
from gan_deeplearning4j_tpu.optim.rmsprop import rmsprop_init, rmsprop_update_leaf

# DL4J regularizes "weight" params (W, gamma is excluded in DL4J: BN gamma/beta
# have no L2 by default; biases excluded by default l2Bias=0).
_L2_PARAM_NAMES = frozenset({"W"})


class GraphUpdater:
    """Per-layer-lr updater over a {layer: {param: array}} tree."""

    def __init__(
        self,
        layer_updaters: Dict[str, "RmsProp"],
        l2: float = 0.0,
        clip_threshold: float | None = 1.0,
        rms_decay: float = 1e-8,
        epsilon: float = 1e-8,
    ):
        self.layer_updaters = dict(layer_updaters)
        self.l2 = float(l2)
        self.clip_threshold = clip_threshold
        self.rms_decay = float(rms_decay)
        self.epsilon = float(epsilon)

    def init(self, params):
        return rmsprop_init(params)

    def lr_for(self, layer: str) -> float:
        up = self.layer_updaters.get(layer)
        return 0.0 if up is None else float(up.learning_rate)

    def apply(self, params, grads, cache):
        """Returns (new_params, new_cache). Pure; call inside jit."""
        new_params = {}
        new_cache = {}
        for layer, layer_grads in grads.items():
            up = self.layer_updaters.get(layer)
            lr = 0.0 if up is None else up.learning_rate
            decay = self.rms_decay if up is None else up.rms_decay
            eps = self.epsilon if up is None else up.epsilon
            new_params[layer] = dict(params[layer])
            new_cache[layer] = dict(cache.get(layer, {}))
            for pname, g in layer_grads.items():
                p = params[layer][pname]
                if self.l2 > 0.0 and pname in _L2_PARAM_NAMES:
                    g = g + self.l2 * p
                if self.clip_threshold is not None:
                    g = jnp.clip(g, -self.clip_threshold, self.clip_threshold)
                c = cache[layer][pname]
                update, c2 = rmsprop_update_leaf(g, c, lr, decay, eps)
                new_params[layer][pname] = p - update
                new_cache[layer][pname] = c2
            # params without grads (e.g. BN running mean/var) pass through via
            # the dict(params[layer]) copy above.
        # layers with no grads at all (pure-stateless layers) pass through.
        for layer in params:
            if layer not in new_params:
                new_params[layer] = params[layer]
                new_cache[layer] = cache.get(layer, {})
        return new_params, new_cache
