"""Distributed training — TPU-native replacement for dl4j-spark / Aeron
(SURVEY.md §1 L4/L4b/L0b, §2c).

One ``jax.sharding.Mesh`` + XLA collectives over ICI replace the Spark
cluster runtime, Kryo serialization, parameter-averaging TrainingMaster,
and the Aeron parameter server.  Long-context sequence parallelism lives
here too — first-class, per the framework's scope — in both idioms: ring
attention (ppermute KV rotation) and Ulysses all-to-all head/sequence
re-sharding.
"""

from gan_deeplearning4j_tpu.parallel.mesh import (
    batch_sharding,
    data_mesh,
    make_mesh,
    replicated,
    shard_batch,
)
from gan_deeplearning4j_tpu.parallel.data_parallel import DataParallelGraph

__all__ = [
    "DataParallelGraph",
    "batch_sharding",
    "data_mesh",
    "make_mesh",
    "replicated",
    "shard_batch",
]
