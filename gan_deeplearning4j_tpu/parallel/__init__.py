"""Distributed training — TPU-native replacement for dl4j-spark / Aeron
(SURVEY.md §1 L4/L4b/L0b, §2c).

One ``jax.sharding.Mesh`` + XLA collectives over ICI replace the Spark
cluster runtime, Kryo serialization, parameter-averaging TrainingMaster,
and the Aeron parameter server.  All five sharding axes are carried with
exactness tests: data (pmean grad sync / param averaging), tensor
(Megatron column/row), sequence (ring attention AND Ulysses all-to-all),
pipeline (GPipe microbatch staircase), and expert (all_to_all top-1 MoE).
"""

from gan_deeplearning4j_tpu.parallel.mesh import (
    batch_sharding,
    data_mesh,
    make_mesh,
    replicated,
    shard_batch,
)
from gan_deeplearning4j_tpu.parallel.data_parallel import DataParallelGraph
from gan_deeplearning4j_tpu.parallel.inference import ParallelInference

__all__ = [
    "DataParallelGraph",
    "ParallelInference",
    "batch_sharding",
    "data_mesh",
    "make_mesh",
    "replicated",
    "shard_batch",
]
