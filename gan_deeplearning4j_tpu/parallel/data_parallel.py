"""Data-parallel training over a device mesh — the dl4j-spark replacement.

The reference trains every network through ``SparkComputationGraph.fit`` +
``ParameterAveragingTrainingMaster`` (dl4jGANComputerVision.java:311-320):
each ``fit(rdd)`` broadcasts the driver's parameters to the workers, each
worker fits a local replica on its partition (averaging every
``averagingFrequency`` minibatches within the job), and the averaged
parameters + updater state come back to the driver.  The dormant
alternative on its classpath is asynchronous gradient sharing over Aeron
UDP (SURVEY.md §2c).

Both collapse here into jitted SPMD programs over a ``Mesh``:

  - ``mode="gradient_sync"`` (default, idiomatic): per-shard gradients are
    ``pmean``-ed over the ICI inside ``shard_map``, then one shared RmsProp
    update runs.  With equal shards and mean losses this is EXACTLY a
    single-device fit on the full batch (proved in tests/test_parallel.py)
    — the all-reduce path that obsoletes both Spark param averaging and
    the Aeron parameter server.

  - ``mode="param_averaging"`` (fidelity): DL4J's exact protocol — local
    per-replica RmsProp updates from the broadcast params, then parameter
    AND updater-state averaging (DL4J default ``averageUpdaters=true``).
    ``fit`` averages at job end like the reference's one-batch-per-worker
    jobs; ``fit_batches`` runs k minibatches per replica averaging every
    ``averaging_frequency``, for multi-batch jobs.

  - ``mode="async_gradient_sharing"`` (fidelity): the DORMANT lane on the
    reference's classpath — ``dl4j-spark-parameterserver`` + Aeron UDP
    (Java/pom.xml:114-118; SURVEY.md §2c "async gradient sharing").  There,
    workers push gradient updates computed against STALE parameters and a
    parameter server applies them as they arrive.  The TPU-native
    formulation keeps the defining property (updates computed at stale
    params, applied sequentially) as one deterministic SPMD program:
    every worker grads against its own last-pulled copy in parallel, the
    pushes land on the server state in replica order (Hogwild-style
    within-round interleaving: worker w's gradient predates workers
    <w's pushes), and workers re-pull the server params every
    ``staleness`` rounds — staleness-k bounded asynchrony, reproducible
    run to run (an actual Aeron race would not be).  With one replica and
    staleness 1 this degenerates to exact sequential SGD (tested).

Multi-slice (DCN) topology: pass ``dcn_axis`` (+ a mesh from
``multihost.hybrid_mesh``) and gradient_sync pmeans over both tiers
(XLA splits it into an ICI reduce + a DCN reduce), while
param_averaging runs a HIERARCHICAL schedule — every
``averaging_frequency`` batches resync within the slice on ICI, and
only every ``dcn_every``-th averaging point crosses DCN (the
amortization a slow inter-host fabric needs; proven against a manual
two-tier computation in tests/test_parallel.py).

No host serialization ever happens: arrays stay device-resident and the
"averaging reduce" is an XLA collective riding ICI, not a Spark shuffle.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from gan_deeplearning4j_tpu.compat.jaxver import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gan_deeplearning4j_tpu.graph.graph import ComputationGraph
from gan_deeplearning4j_tpu.parallel import mesh as mesh_lib
from gan_deeplearning4j_tpu.runtime import prng


class DataParallelGraph:
    """``SparkComputationGraph`` equivalent: wraps a ComputationGraph and
    distributes ``fit`` over a mesh axis.

    The wrapped graph's ``params``/``opt_state`` stay the single source of
    truth between fits, so the GAN protocol's per-iteration cross-graph
    ``set_param`` sync (dl4jGANComputerVision.java:404-420) composes with
    distribution exactly as in the reference: driver state in, driver
    state out.
    """

    def __init__(
        self,
        graph: ComputationGraph,
        mesh: Optional[Mesh] = None,
        axis: str = "data",
        mode: str = "gradient_sync",
        averaging_frequency: int = 1,
        staleness: int = 1,
        dcn_axis: Optional[str] = None,
        dcn_every: int = 1,
    ):
        if mode not in ("gradient_sync", "param_averaging",
                        "async_gradient_sharing"):
            raise ValueError(f"unknown mode {mode!r}")
        self.graph = graph
        self.mesh = mesh if mesh is not None else mesh_lib.data_mesh()
        self.axis = axis
        self.mode = mode
        self.averaging_frequency = averaging_frequency
        if staleness < 1:
            raise ValueError(f"staleness must be >= 1, got {staleness}")
        self.staleness = staleness
        # two-tier topology (multi-slice): ``axis`` is the within-slice
        # ICI tier; ``dcn_axis`` the cross-slice tier.  param_averaging
        # then averages over ICI every ``averaging_frequency`` batches
        # but crosses DCN only every ``dcn_every``-th averaging point —
        # the hierarchical schedule that keeps the frequent resyncs on
        # the fast interconnect (multihost.hybrid_mesh's layout rule).
        if dcn_axis is not None and dcn_axis not in self.mesh.shape:
            raise ValueError(f"dcn_axis {dcn_axis!r} not in mesh "
                             f"{dict(self.mesh.shape)}")
        if dcn_every < 1:
            raise ValueError(f"dcn_every must be >= 1, got {dcn_every}")
        self.dcn_axis = dcn_axis
        self.dcn_every = dcn_every
        self.num_replicas = self.mesh.shape[axis] * (
            self.mesh.shape[dcn_axis] if dcn_axis else 1)
        self._fit_count = 0
        self._step_rng = prng.stream(prng.root_key(graph.seed), "dp-step")
        if mode == "gradient_sync":
            self._jit_step = self._build_gradient_sync_step()
        elif mode == "async_gradient_sharing":
            if dcn_axis is not None:
                raise ValueError(
                    "async_gradient_sharing is single-tier; model the "
                    "slow tier with `staleness` instead of dcn_axis")
            self._jit_step = self._build_async_step()
            self._round = 0
            self._local_params = None  # seeded from the server at first fit
        else:
            self._jit_step = self._build_param_avg_step(num_batches=1)
            self._multi_cache = {}

    # -- step builders -------------------------------------------------------

    def _sync_axes(self):
        """The axis name(s) a full resync spans: ICI alone, or (DCN, ICI)
        under a two-tier mesh — lax collectives take either form."""
        return ((self.dcn_axis, self.axis) if self.dcn_axis
                else self.axis)

    def _batch_spec(self, leading_dims: int = 0) -> P:
        """Batch rows split over every replica axis (both tiers);
        ``leading_dims`` unsharded axes (the fit_batches [num_batches]
        axis) come first.  The ONE source of truth for how batch data
        lays out over the mesh."""
        return P(*([None] * leading_dims), self._sync_axes())

    def _replica_index(self):
        idx = lax.axis_index(self.axis)
        if self.dcn_axis:
            idx = idx + lax.axis_index(self.dcn_axis) * self.mesh.shape[self.axis]
        return idx

    def _build_gradient_sync_step(self):
        graph = self.graph
        axes = self._sync_axes()

        def reduce(loss, state_updates, grads):
            # The ICI all-reduce: these pmeans are the entire Spark/Aeron
            # replacement (SURVEY.md §5 "Distributed communication backend").
            # Over a two-tier mesh XLA decomposes the pmean into a
            # within-slice ICI reduce + a cross-slice DCN reduce.
            return (
                lax.pmean(loss, axes),
                lax.pmean(state_updates, axes),
                lax.pmean(grads, axes),
            )

        def step(params, opt_state, rng, inputs, labels):
            # Per-replica stream: dropout masks must be independent across
            # shards (exact single-device equivalence still holds for
            # dropout-free graphs; with dropout the masks differ from the
            # single-device draw either way).  axis_name turns on sync-BN:
            # batch stats are global-batch stats, so BN graphs keep the
            # exact single-device equivalence too (ops/batchnorm.py).
            rng = prng.fold_in_index(rng, self._replica_index())
            return graph._train_step(params, opt_state, rng, inputs, labels,
                                     reduce, axis_name=axes)

        return jax.jit(shard_map(
            step,
            mesh=self.mesh,
            in_specs=(P(), P(), P(), self._batch_spec(), self._batch_spec()),
            out_specs=(P(), P(), P()),
            check_vma=False,
        ))

    def _build_param_avg_step(self, num_batches: int):
        """DL4J job semantics: broadcast params -> ``num_batches`` local
        RmsProp steps per replica (averaging every ``averaging_frequency``
        batches) -> final average of params and updater state.

        ``num_batches``/``averaging_frequency`` are static, so the
        average-points unroll at trace time — no collective-under-cond.
        Batched inputs arrive as [num_batches, local_B, ...] per replica.
        """
        graph, axis, avg_freq = self.graph, self.axis, self.averaging_frequency
        full_axes = self._sync_axes()
        dcn_every = self.dcn_every

        def job(params, opt_state, rng, inputs, labels):
            rng = prng.fold_in_index(rng, self._replica_index())
            avg_point = 0
            for i in range(num_batches):
                x_i = {k: v[i] for k, v in inputs.items()}
                y_i = {k: v[i] for k, v in labels.items()}
                params, opt_state, loss = graph._train_step(
                    params, opt_state, jax.random.fold_in(rng, i), x_i, y_i
                )
                if (i + 1) % avg_freq == 0 and i + 1 < num_batches:
                    # two-tier schedule: every averaging point resyncs
                    # within the slice (ICI); only every dcn_every-th one
                    # crosses slices (DCN) — static unroll, so the tier
                    # choice is baked into the program
                    avg_point += 1
                    tier = (full_axes if avg_point % dcn_every == 0
                            else axis)
                    params = lax.pmean(params, tier)
                    opt_state = lax.pmean(opt_state, tier)
            # Job-end average (the reference's 1-batch-per-worker jobs hit
            # only this one, making every fit() a full resync) — always
            # BOTH tiers, so a job ends globally synced.
            params = lax.pmean(params, full_axes)
            opt_state = lax.pmean(opt_state, full_axes)
            loss = lax.pmean(loss, full_axes)
            return params, opt_state, loss

        batched = self._batch_spec()
        multi = self._batch_spec(leading_dims=1)
        return jax.jit(shard_map(
            job,
            mesh=self.mesh,
            in_specs=(P(), P(), P(), multi, multi),
            out_specs=(P(), P(), P()),
            check_vma=False,
        )) if num_batches > 1 else jax.jit(shard_map(
            lambda p, o, r, x, y: job(
                p, o, r,
                {k: v[None] for k, v in x.items()},
                {k: v[None] for k, v in y.items()},
            ),
            mesh=self.mesh,
            in_specs=(P(), P(), P(), batched, batched),
            out_specs=(P(), P(), P()),
            check_vma=False,
        ))

    def _build_async_step(self):
        """Staleness-k asynchronous gradient sharing as ONE SPMD round.

        Per round: every worker computes a gradient against its own
        last-pulled (stale) parameter copy on its batch shard — in
        parallel — then the pushes are applied to the server params and
        updater state SEQUENTIALLY in replica order (each push was
        computed without knowledge of the pushes landing before it, the
        async-PS property).  The replica-order serialization stands in
        for Aeron's arrival order: deterministic, so convergence under
        staleness is testable.  BN running-stat updates are pmean-ed onto
        the server (a stale-BN per-worker write order would be
        meaningless).  Grads ride an ``all_gather`` over the mesh axis —
        ICI, not UDP."""
        graph, axis = self.graph, self.axis
        n = self.num_replicas

        def round_fn(server_params, opt_state, local_params, rng,
                     inputs, labels):
            mine = jax.tree.map(lambda x: x[0], local_params)  # [1,...] shard
            rng = prng.fold_in_index(rng, lax.axis_index(axis))

            def loss_fn(p):
                values, state_updates = graph._forward(
                    p, inputs, True, rng, axis)
                outputs = {k: values[k] for k in graph.output_names}
                return graph._loss(outputs, labels), state_updates

            (loss, state_updates), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(mine)
            pushes = jax.tree.map(lambda g: lax.all_gather(g, axis), grads)
            params = server_params
            for w in range(n):  # static unroll: n pushes land in order
                g_w = jax.tree.map(lambda g: g[w], pushes)
                params, opt_state = graph.updater.apply(
                    params, g_w, opt_state)
            state_updates = lax.pmean(state_updates, axis)
            for lname, upd in state_updates.items():
                merged = dict(params[lname])
                merged.update(upd)
                params[lname] = merged
            return params, opt_state, lax.pmean(loss, axis)

        return jax.jit(shard_map(
            round_fn,
            mesh=self.mesh,
            in_specs=(P(), P(), P(self.axis), P(), P(self.axis),
                      P(self.axis)),
            out_specs=(P(), P(), P()),
            check_vma=False,
        ))

    def _refresh_locals(self) -> None:
        """The workers' pull: the server params as an [n, ...]-stacked
        pytree, one stale copy per replica, sharded over the mesh axis.

        Built shard-by-shard (every shard of the leading axis is the SAME
        single copy) rather than via ``jnp.stack([x] * n)`` + reshard,
        which would materialize an n-fold replicated intermediate on every
        device before resharding — a transient n-times parameter-memory
        spike on each pull."""
        import numpy as np

        n = self.num_replicas
        mesh = self.mesh

        def stack_sharded(x):
            host = np.asarray(x)  # one host copy, reused for every shard
            return jax.make_array_from_callback(
                (n, *host.shape), NamedSharding(mesh, P(self.axis)),
                lambda idx: host[None])

        self._local_params = jax.tree.map(stack_sharded, self.graph.params)

    # -- public API ----------------------------------------------------------

    @property
    def network(self) -> ComputationGraph:
        """``sparkX.getNetwork()`` — the wrapped graph (driver state)."""
        return self.graph

    def _as_maps(self, features, labels):
        inputs = (
            features if isinstance(features, dict)
            else dict(zip(self.graph.input_names, [features]))
        )
        label_map = (
            labels if isinstance(labels, dict)
            else dict(zip(self.graph.output_names, [labels]))
        )
        return inputs, label_map

    def _next_rng(self):
        self._fit_count += 1
        return jax.random.fold_in(self._step_rng, self._fit_count)

    def fit(self, features, labels) -> jax.Array:
        """One distributed job on a global batch sharded over the mesh —
        ``sparkX.fit(sc.parallelize(...))``."""
        inputs, label_map = self._as_maps(features, labels)
        sh = NamedSharding(self.mesh, self._batch_spec())
        inputs = {k: jax.device_put(jnp.asarray(v), sh) for k, v in inputs.items()}
        label_map = {k: jax.device_put(jnp.asarray(v), sh) for k, v in label_map.items()}
        if self.mode == "async_gradient_sharing":
            if self._local_params is None:
                self._refresh_locals()
            new_params, new_opt, loss = self._jit_step(
                self.graph.params, self.graph.opt_state, self._local_params,
                self._next_rng(), inputs, label_map,
            )
            self.graph.params = new_params
            self.graph.opt_state = new_opt
            self._round += 1
            if self._round % self.staleness == 0:
                self._refresh_locals()  # the workers' periodic pull
        else:
            new_params, new_opt, loss = self._jit_step(
                self.graph.params, self.graph.opt_state, self._next_rng(),
                inputs, label_map,
            )
            self.graph.params = new_params
            self.graph.opt_state = new_opt
        self.graph.score = loss
        return loss

    def fit_batches(self, features, labels) -> jax.Array:
        """Multi-minibatch job (param_averaging mode): features/labels have
        a leading [num_batches] axis; replicas average every
        ``averaging_frequency`` batches and at job end — the full
        ``ParameterAveragingTrainingMaster`` schedule."""
        if self.mode != "param_averaging":
            raise ValueError("fit_batches is a param_averaging-mode API")
        inputs, label_map = self._as_maps(features, labels)
        num_batches = next(iter(inputs.values())).shape[0]
        step = self._multi_cache.get(num_batches)
        if step is None:
            step = self._build_param_avg_step(num_batches)
            self._multi_cache[num_batches] = step
        sh = NamedSharding(self.mesh, self._batch_spec(leading_dims=1))
        inputs = {k: jax.device_put(jnp.asarray(v), sh) for k, v in inputs.items()}
        label_map = {k: jax.device_put(jnp.asarray(v), sh) for k, v in label_map.items()}
        new_params, new_opt, loss = step(
            self.graph.params, self.graph.opt_state, self._next_rng(),
            inputs, label_map,
        )
        self.graph.params = new_params
        self.graph.opt_state = new_opt
        self.graph.score = loss
        return loss
