"""Elastic mesh — reshard-on-restore and world-size-elastic recovery.

The fleet already survives crashes, hangs, NaNs and poisoned data
(PRs 2/4/5) — but only back onto the *same* mesh shape.  This module is
the missing tier (ROADMAP item 4): a checkpoint written on 8 devices
must restore on 4 (or 16), and a preempted fleet must re-form with
whatever hosts come back instead of demanding the original world size.

Three pieces:

* :class:`MeshSpec` — the saving topology, stamped into every
  checkpoint's ``MANIFEST.json``: axis names/sizes, device and process
  counts, and the per-role sharding of the state (params/opt-state are
  replicated under the data-parallel protocol; the batch axis is what
  shards).  A restore compares the saved spec against the restoring
  mesh and reshards on mismatch instead of trusting the topologies
  match (checkpoint/checkpointer.py ``restore(target_mesh=...)``).
* :func:`reshard` — the mechanism: gather every leaf to host (the
  checkpoint already holds host arrays; live arrays take one
  ``device_get``) and ``device_put`` with the *target* mesh's
  ``NamedSharding``.  Values are bit-equal post-gather by construction
  — resharding moves bytes, never rounds them.
* :func:`pack_iter_state` / :func:`unpack_iter_state` /
  :func:`merge_iter_states` / :func:`split_iter_state` — the O(1)
  data-plane cursor (data/csv.py state contract) across world-size
  changes.  Checkpoints stamp the boundary-aligned stash, and under
  SPMD lockstep every host's boundary position is equal by
  construction — so the pack is a broadcast of the local cursor (no
  collective on the save path), and the restore-side merge is
  defensive: it verifies that equality and resolves any disagreement
  (a checkpoint from a writer without the boundary-stash guarantee)
  to the *lagging* position (lexicographic min of (epoch, cursor)),
  so a record can be re-fed to a replica but never dropped.  The
  re-split broadcasts the merged position to the new host count.
  Both directions are pure functions of their inputs — deterministic
  by construction.

The batch-rebucket rule lives with the trainer (train/gan_trainer.py):
the GLOBAL batch is invariant across resumes (it is part of the
protocol's math — changing it would change the trajectory, not just
the layout); the re-formed mesh is the largest divisor of the global
batch that fits the surviving devices, so only the per-device shard
grows or shrinks.  gan4j-prove's bucket contracts key on the global
batch, which is exactly the quantity held fixed.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Dict, List, Optional, Sequence

_log = logging.getLogger(__name__)

# iter-state wrapper version (the packed multi-host form); bumped only
# if the wrapper layout itself changes — the inner states carry the
# data/csv.py shuffle contract and version themselves
ITER_STATE_PACK_VERSION = 1

# sharding-role vocabulary a MeshSpec records.  "replicated" is the
# data-parallel protocol's answer for params/opt-state; the batch role
# names the mesh axis it shards over.
ROLE_PARAMS = "params"
ROLE_OPT_STATE = "opt_state"
ROLE_BATCH = "batch"


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """The topology a checkpoint was written under — everything a
    restore needs to decide "same mesh, load as-is" vs "reshard".

    ``axes`` preserves mesh axis order (dict insertion order);
    ``sharding`` maps state roles to either ``"replicated"`` or the
    axis name their leading dim shards over."""

    axes: Dict[str, int]
    device_count: int
    process_count: int = 1
    sharding: Dict[str, str] = dataclasses.field(default_factory=dict)

    @classmethod
    def from_mesh(cls, mesh, process_count: Optional[int] = None,
                  batch_axis: str = "data") -> "MeshSpec":
        """Spec of a live ``jax.sharding.Mesh`` (``mesh=None`` = the
        single-device, no-mesh trainer) under the data-parallel
        protocol's sharding roles."""
        import jax

        if process_count is None:
            process_count = jax.process_count()
        if mesh is None:
            return cls(axes={batch_axis: 1}, device_count=1,
                       process_count=process_count,
                       sharding={ROLE_PARAMS: "replicated",
                                 ROLE_OPT_STATE: "replicated",
                                 ROLE_BATCH: batch_axis})
        axes = {str(k): int(v) for k, v in dict(mesh.shape).items()}
        return cls(axes=axes, device_count=int(mesh.devices.size),
                   process_count=process_count,
                   sharding={ROLE_PARAMS: "replicated",
                             ROLE_OPT_STATE: "replicated",
                             ROLE_BATCH: batch_axis})

    def to_dict(self) -> Dict:
        return {"axes": dict(self.axes),
                "device_count": int(self.device_count),
                "process_count": int(self.process_count),
                "sharding": dict(self.sharding)}

    @classmethod
    def from_dict(cls, doc: Dict) -> "MeshSpec":
        return cls(axes={str(k): int(v)
                         for k, v in (doc.get("axes") or {}).items()},
                   device_count=int(doc.get("device_count", 1)),
                   process_count=int(doc.get("process_count", 1)),
                   sharding={str(k): str(v) for k, v in
                             (doc.get("sharding") or {}).items()})

    def describe(self) -> str:
        """Human shape for error messages: ``{'data': 8} (8 devices,
        1 process)``."""
        return (f"{self.axes} ({self.device_count} device"
                f"{'s' if self.device_count != 1 else ''}, "
                f"{self.process_count} process"
                f"{'es' if self.process_count != 1 else ''})")

    def same_topology(self, other: "MeshSpec") -> bool:
        """True when a checkpoint written under ``self`` loads onto
        ``other`` without resharding (axis layout and world identical;
        the sharding roles ride along with the axes)."""
        return (self.axes == other.axes
                and self.device_count == other.device_count
                and self.process_count == other.process_count)


def reshard(tree, sharding):
    """Place every leaf of ``tree`` under ``sharding`` (a
    ``NamedSharding`` on the *target* mesh, or any ``jax.sharding``
    placement) via gather-to-host → ``device_put``.

    Leaves already on host (the checkpoint-restore path) transfer
    directly; device-resident leaves are gathered first — ``np.asarray``
    on a sharded jax.Array assembles the full logical value, which is
    exactly the "post-gather" form the bit-equality contract is stated
    over.  No arithmetic happens in either direction."""
    import jax
    import numpy as np

    return jax.tree.map(
        lambda x: jax.device_put(np.asarray(x), sharding), tree)


# -- iterator state across world sizes ----------------------------------------


def pack_iter_state(state: Dict, process_count: int) -> Dict:
    """The checkpoint form of the O(1) iterator state.  Single process
    keeps the bare data/csv.py state dict (bit-compatible with every
    pre-elastic checkpoint); a multi-host fleet wraps ``process_count``
    copies of the BOUNDARY-ALIGNED local cursor.

    Why a broadcast is the fleet truth, not a shortcut: checkpoints
    are only ever stamped from the step-boundary stash
    (gan_trainer._stash_iter_state — the mid-step emergency save reads
    the stash too), and under SPMD lockstep every host's consumed
    position at a boundary is EQUAL by construction, so the local
    cursor IS each host's cursor — no gather needed, no collective on
    the save path.  The merge machinery on the restore side is the
    DEFENSIVE half: it validates that equality on checkpoints from
    writers without the boundary-stash guarantee and resolves any
    disagreement to the lagging position."""
    if process_count <= 1:
        return dict(state)
    return {"__elastic_iter__": ITER_STATE_PACK_VERSION,
            "hosts": int(process_count),
            "states": [dict(state) for _ in range(process_count)]}


def is_packed_iter_state(raw: Dict) -> bool:
    return isinstance(raw, dict) and "__elastic_iter__" in raw


def merge_iter_states(states: Sequence[Dict]) -> Dict:
    """One global position from per-host cursors — deterministic, and
    never past any host's consumed position.

    Under SPMD lockstep the states are equal (every host advances the
    same logical stream at the same boundary); a fleet killed between
    boundaries can disagree by at most the in-flight batches, and the
    safe merge is the LAGGING host's position (lexicographic min of
    (epoch, cursor)): records past it are re-fed to the replicas that
    already saw them — the same replay semantics a plain restart has —
    while nothing is ever skipped.  A shuffle-contract mismatch between
    hosts is a config error, not a merge decision, and raises."""
    if not states:
        raise ValueError("merge_iter_states: no per-host states")
    first = states[0]
    for st in states[1:]:
        if (bool(st.get("shuffle", False))
                != bool(first.get("shuffle", False))
                or int(st.get("shuffle_seed", 0))
                != int(first.get("shuffle_seed", 0))):
            raise ValueError(
                "iterator state shuffle contract differs across hosts: "
                f"{first!r} vs {st!r} — the fleet was not running one "
                "run")
    merged = min(
        states,
        key=lambda st: (int(st.get("epoch", 0)), int(st.get("cursor", 0))))
    if any((int(st.get("epoch", 0)), int(st.get("cursor", 0)))
           != (int(merged.get("epoch", 0)), int(merged.get("cursor", 0)))
           for st in states):
        _log.warning(
            "per-host iterator cursors disagree (fleet killed between "
            "boundaries); merging to the lagging position %r — some "
            "records will be re-fed, none dropped", merged)
    return dict(merged)


def split_iter_state(state: Dict, process_count: int) -> List[Dict]:
    """The merged global position, re-split for ``process_count``
    hosts.  Every host consumes the same logical stream under SPMD
    lockstep, so the split is a broadcast — each new host starts at the
    merged position, and the first boundary re-synchronizes the pack.
    Deterministic: same input, same output, any direction of world
    change (8 hosts -> 4, 4 -> 16, ...)."""
    if process_count < 1:
        raise ValueError(f"process_count must be >= 1, got {process_count}")
    return [dict(state) for _ in range(process_count)]


def unpack_iter_state(raw: Dict, process_count: int,
                      process_index: int = 0) -> Dict:
    """The restoring host's iterator state from a checkpoint's
    (possibly packed) ``iter_state`` — merging across a host-count
    change so no record is dropped.  Bare (pre-elastic / single-host)
    states pass through untouched."""
    if not is_packed_iter_state(raw):
        return dict(raw)
    states = list(raw.get("states") or [])
    if not states:
        raise ValueError("packed iter_state carries no per-host states")
    saved_hosts = int(raw.get("hosts", len(states)))
    if saved_hosts == process_count and process_index < len(states):
        return dict(states[process_index])
    merged = merge_iter_states(states)
    return split_iter_state(merged, process_count)[
        min(process_index, process_count - 1)]
