"""Expert parallelism — top-1 MoE routing over a mesh axis via all_to_all.

The reference has no mixture-of-experts (SURVEY.md §2c marks EP absent /
not required), but EP completes the framework's distributed axis set
(dp/tp/pp/sp/ep).  The ICI idiom, built from XLA collectives:

  - expert ``e`` of ``E`` lives on device ``e`` of the ``expert`` mesh
    axis; tokens are sharded over the same axis (N/E per device)
  - a linear router scores each local token; top-1 expert assignment
  - each device scatters its tokens into an [E, C, F] dispatch buffer
    (C = per-(src,dst) capacity); ONE ``lax.all_to_all`` turns the
    expert axis into the source axis — device ``e`` now holds every
    token routed to expert ``e``
  - the local expert MLP runs on its [E*C, F] buffer; a second
    ``all_to_all`` returns outputs to the token owners, which combine
    them scaled by the router gate

Capacity semantics (standard MoE): a source device can send at most C
tokens to one expert; overflow tokens are DROPPED (output zero for that
token — the gate-weighted combine makes the layer a no-op for them).
Exactness: with C >= the true per-pair demand there are no drops and the
sharded layer equals the dense single-device computation (tested).
"""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
from jax import lax

from gan_deeplearning4j_tpu.compat.jaxver import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def expert_mlp(params: Dict, x: jax.Array) -> jax.Array:
    """The per-expert FFN: dense -> tanh -> dense (params leaves carry a
    leading expert axis OUTSIDE shard_map; inside, it is stripped)."""
    h = jnp.tanh(x @ params["W1"] + params["b1"])
    return h @ params["W2"] + params["b2"]


def _moe_body(router_w, expert_params, x, axis_name: str, n_experts: int,
              capacity: int):
    expert_params = jax.tree.map(lambda a: a[0], expert_params)
    n_local = x.shape[0]
    F = x.shape[1]

    # --- route: top-1 expert + gate per local token --------------------
    logits = x @ router_w                       # [n_local, E]
    gates = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(gates, axis=-1)     # [n_local]
    gate = jnp.take_along_axis(gates, expert_idx[:, None], axis=1)[:, 0]

    # --- dispatch: position of each token within its expert's quota ----
    one_hot = jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.int32)
    pos_in_expert = (jnp.cumsum(one_hot, axis=0) - 1) * one_hot
    pos = jnp.sum(pos_in_expert, axis=-1)       # [n_local]
    keep = pos < capacity                       # overflow tokens drop
    dispatch = jnp.zeros((n_experts, capacity, F), x.dtype)
    dispatch = dispatch.at[
        jnp.where(keep, expert_idx, 0),
        jnp.where(keep, pos, 0)].add(
        jnp.where(keep[:, None], x, 0.0))

    # --- exchange: expert axis <-> source axis -------------------------
    # after all_to_all, slot [src, c] on device e holds source src's
    # c-th token for expert e
    received = lax.all_to_all(
        dispatch, axis_name, split_axis=0, concat_axis=0, tiled=True)

    # --- local expert computation --------------------------------------
    out = expert_mlp(expert_params, received.reshape(-1, F))
    out = out.reshape(n_experts, capacity, F)

    # --- return to the token owners ------------------------------------
    returned = lax.all_to_all(
        out, axis_name, split_axis=0, concat_axis=0, tiled=True)
    # gather each kept token's output back out of its dispatch slot
    token_out = returned[
        jnp.where(keep, expert_idx, 0), jnp.where(keep, pos, 0)]
    token_out = jnp.where(keep[:, None], token_out, 0.0)
    return token_out * gate[:, None]


def moe_apply(router_w, expert_params, x, mesh: Mesh,
              axis: str = "expert", capacity: int | None = None) -> jax.Array:
    """Top-1 MoE layer, tokens and experts sharded over ``axis``.

    ``router_w``: [F, E].  ``expert_params``: pytree with a leading
    expert axis of size E = mesh.shape[axis].  ``x``: [N, F], N divisible
    by E.  ``capacity``: per-(source-device, expert) token quota; the
    default N/E equals each device's WHOLE token count, so no token can
    ever drop (worst-case-skew safe) at the cost of E-times-balanced
    all_to_all volume — production configs pass a tighter capacity
    (e.g. ceil(N/E^2) * slack) and accept dropped-token semantics.
    """
    E = mesh.shape[axis]
    N = x.shape[0]
    if N % E != 0:
        raise ValueError(f"token count {N} not divisible by EP degree {E}")
    if capacity is None:
        capacity = N // E

    return shard_map(
        partial(_moe_body, axis_name=axis, n_experts=E, capacity=capacity),
        mesh=mesh,
        in_specs=(P(), P(axis), P(axis)),
        out_specs=P(axis),
        check_vma=False,
    )(router_w, expert_params, x)


def moe_dense_reference(router_w, expert_params, x) -> jax.Array:
    """Single-device reference: every token through its top-1 expert
    (no capacity, no sharding) — what moe_apply must equal when no
    tokens are dropped."""
    logits = x @ router_w
    gates = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(gates, axis=-1)
    gate = jnp.take_along_axis(gates, expert_idx[:, None], axis=1)[:, 0]
    outs = []
    n_experts = router_w.shape[1]
    for e in range(n_experts):
        p = jax.tree.map(lambda a, e=e: a[e], expert_params)
        outs.append(expert_mlp(p, x))
    stacked = jnp.stack(outs)                   # [E, N, F]
    picked = stacked[expert_idx, jnp.arange(x.shape[0])]
    return picked * gate[:, None]
