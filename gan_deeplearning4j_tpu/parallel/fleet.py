"""Tenant-axis sharding for the GAN fleet — multi-chip fleet scaling.

``train/fleet.py`` turns N tenants into one vmapped program on one
chip; this module spreads the TENANT axis across a mesh: each device
holds ``N / world`` tenants and runs the identical vmapped block, with
**zero collectives** — tenants are independent by construction, so
nothing crosses the ICI (the ``fleet_step`` gan4j-prove contract pins
the collective budget at zero, which is the whole point: fleet scaling
is embarrassingly parallel, unlike the data-parallel protocol's
pmean-per-step).

Elasticity reuses ``parallel/elastic.py`` verbatim: a fleet checkpoint
stores the stacked state as HOST arrays plus a :class:`~gan_deeplearning4j_tpu.parallel.elastic.MeshSpec`
of the writing topology; restoring onto a different world size is one
:func:`~gan_deeplearning4j_tpu.parallel.elastic.reshard` call — gather
to host (already there), ``device_put`` under the new tenant
``NamedSharding``.  Bytes move, values never round, so per-tenant state
is bit-equal across any 8→4→16 world-size change
(tests/test_fleet.py + tests/test_elastic.py fleet matrix case).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gan_deeplearning4j_tpu.compat.jaxver import shard_map
from gan_deeplearning4j_tpu.parallel import elastic
from gan_deeplearning4j_tpu.telemetry import events as telemetry_events
from gan_deeplearning4j_tpu.train import fleet as fleet_lib

# the one mesh axis fleet programs shard over
AXIS = "tenant"


def tenant_mesh(n_devices: Optional[int] = None) -> Mesh:
    """A 1-D ``("tenant",)`` mesh over the first ``n_devices`` devices
    (all of them by default)."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (AXIS,))


def fleet_sharding(mesh: Mesh) -> NamedSharding:
    """Leading-dim tenant sharding — one spec for every state leaf
    (``it`` included: it is an ``(N,)`` per-tenant counter vector)."""
    return NamedSharding(mesh, P(AXIS))


def fleet_mesh_spec(mesh: Optional[Mesh]) -> elastic.MeshSpec:
    """The fleet's :class:`MeshSpec` for checkpoint manifests.  Unlike
    the data-parallel protocol (params replicated, batch sharded), a
    fleet shards EVERY state role over the tenant axis."""
    sharding = {elastic.ROLE_PARAMS: AXIS, elastic.ROLE_OPT_STATE: AXIS,
                elastic.ROLE_BATCH: AXIS}
    if mesh is None:
        return elastic.MeshSpec(axes={AXIS: 1}, device_count=1,
                                process_count=jax.process_count(),
                                sharding=sharding)
    return elastic.MeshSpec(
        axes={str(k): int(v) for k, v in dict(mesh.shape).items()},
        device_count=int(mesh.devices.size),
        process_count=jax.process_count(), sharding=sharding)


def check_divisible(num_tenants: int, mesh: Mesh) -> None:
    world = int(mesh.devices.size)
    if num_tenants % world:
        raise ValueError(
            f"fleet of {num_tenants} tenants does not divide the "
            f"{world}-device tenant mesh — pad the fleet or shrink the "
            "mesh (every device carries num_tenants/world tenants)")


def shard_fleet_state(state, mesh: Mesh):
    """Place a stacked fleet state under the tenant sharding via the
    elastic reshard (gather-to-host → device_put: bit-equal, works the
    same for a fresh stack, a live state, or a restored checkpoint —
    including one written under a DIFFERENT world size)."""
    check_divisible(fleet_lib.fleet_size(state), mesh)
    return elastic.reshard(state, fleet_sharding(mesh))


def make_sharded_fleet_step(
    dis, gen, gan, classifier,
    dis_to_gan, gan_to_gen, dis_to_classifier,
    z_size: int,
    num_features: int,
    mesh: Mesh,
    per_tenant_data: bool = False,
    donate: bool = True,
    data_on_device: bool = False,
    steps_per_call: int = 1,
    ema_decay: float = 0.0,
    carry_dedup: bool = True,
    masked: bool = False,
):
    """The fleet step shard_mapped over the tenant axis: same signature
    and same per-tenant math as ``train/fleet.make_fleet_step`` (each
    shard runs the identical vmapped block on its tenant slice), with
    state and key vectors tenant-sharded and the loop invariants
    replicated.  ``per_tenant_data`` shards the data tables over
    tenants too; otherwise every device holds the shared table.

    ``masked``: the lifecycle form — an ``(N,)`` bool ``mask`` after
    ``rng_keys``, tenant-sharded like the key vectors; masked lanes
    freeze bit-identically on their own shard (still zero collectives:
    the mask select is element-wise per lane)."""
    vstep = fleet_lib.make_fleet_step(
        dis, gen, gan, classifier,
        dis_to_gan, gan_to_gen, dis_to_classifier,
        z_size=z_size, num_features=num_features,
        per_tenant_data=per_tenant_data, data_on_device=data_on_device,
        steps_per_call=steps_per_call, ema_decay=ema_decay,
        carry_dedup=carry_dedup, masked=masked, jit=False)
    data_spec = P(AXIS) if per_tenant_data else P()
    # state + per-tenant key vectors (and the lifecycle mask, when
    # present) sharded over the tenant axis; y_real/y_fake/ones
    # replicated (shared across tenants by the fleet-step convention)
    in_specs = (P(AXIS), data_spec, data_spec, P(AXIS), P(AXIS))
    if masked:
        in_specs += (P(AXIS),)
    in_specs += (P(), P(), P())
    sharded = shard_map(
        vstep,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(P(AXIS), P(AXIS)),
        check_vma=False,
    )
    if steps_per_call > 1 and donate:
        # the repo-wide scan-donation exemption, announced as always
        telemetry_events.instant(
            "donation.disabled", reason="scan-donation",
            steps_per_call=steps_per_call)
        donate = False
    return jax.jit(sharded, donate_argnums=(0,) if donate else ())
