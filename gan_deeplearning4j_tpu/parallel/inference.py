"""Parallel inference — the ``ParallelInference`` / ``ParallelWrapper``
inference path of DL4J's parallel-wrapper module (`dl4jGAN.iml:366`, on the
reference classpath, dormant in the mains).

DL4J's design is worker-centric: N mutable model replicas pinned to N
devices, a request queue, and a batching thread that fuses queued inputs
into per-replica batches.  All of that machinery exists because its
replicas are stateful objects.  The TPU-native version is ONE jitted SPMD
program: parameters live replicated on the mesh, the batch dimension is
sharded over the ``data`` axis, and XLA fans the same forward pass out
across every chip in lockstep — no queue, no replica copies, no
per-worker state to keep coherent.

Exactness: inference mode uses BN running stats and disables dropout, so
there is no cross-batch reduction anywhere in the forward pass — each row's
output is computed by exactly the same op sequence as on one device, and
sharded output == single-device output (proven in
``tests/test_parallel_inference.py``).

Uneven batches are zero-padded up to a multiple of the mesh axis (DL4J's
batching thread pads queued requests the same way) and sliced back before
returning.  ``max_batch`` bounds the per-dispatch global batch — the
analog of ParallelInference's ``batchLimit`` — by splitting oversized
inputs into sequential dispatches.

``buckets`` fixes the COMPLETE set of dispatch shapes: every request
pads up to the smallest declared bucket that holds it (oversized inputs
chunk by the largest), so the compiled-program set is closed and
"recompile per request shape" is impossible by construction.  The
bucket set is a gan4j-prove program contract
(``analysis/contracts/serving_infer.json``): the verifier lowers the
dispatch at every declared bucket and proves request coverage, so a
bucket change is a reviewable contract diff, not a silent recompile
storm under load (docs/STATIC_ANALYSIS.md#program-contracts).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from gan_deeplearning4j_tpu.parallel.mesh import (
    batch_sharding,
    data_mesh,
    replicated,
)

# The canonical serving bucket set (the code side of the gan4j-prove
# bucket-coverage contract).  Every bucket must divide over the mesh
# axis; the largest bucket is the chunking unit for oversized requests.
DEFAULT_SERVING_BUCKETS = (8, 32, 64)


class ParallelInference:
    """Batch-sharded SPMD inference over a mesh for a ``ComputationGraph``.

    Parameters are placed replicated once at construction; call
    ``refresh_params()`` after further training to re-snapshot them.
    """

    def __init__(self, graph, mesh=None, axis: str = "data",
                 max_batch: Optional[int] = None,
                 buckets: Optional[Sequence[int]] = None):
        self.graph = graph
        self.mesh = mesh if mesh is not None else data_mesh()
        self.axis = axis
        if axis not in self.mesh.axis_names:
            raise ValueError(f"mesh has no axis {axis!r}: {self.mesh.axis_names}")
        if max_batch is not None and max_batch < self.mesh.shape[axis]:
            raise ValueError(
                f"max_batch={max_batch} below the mesh axis size "
                f"{self.mesh.shape[axis]} — every dispatch needs one row per shard")
        if max_batch is not None and max_batch % self.mesh.shape[axis]:
            # the chunked path pads every chunk to exactly max_batch, so a
            # non-multiple would pass construction and then fail each
            # dispatch with a device_put divisibility error.
            raise ValueError(
                f"max_batch={max_batch} must be a multiple of the mesh "
                f"axis size {self.mesh.shape[axis]}")
        self.max_batch = max_batch
        self.buckets: Optional[tuple] = None
        if buckets is not None:
            bs = tuple(sorted({int(b) for b in buckets}))
            if not bs:
                raise ValueError("buckets must name at least one shape")
            bad = [b for b in bs if b <= 0 or b % self.mesh.shape[axis]]
            if bad:
                raise ValueError(
                    f"bucket(s) {bad} must be positive multiples of the "
                    f"mesh axis size {self.mesh.shape[axis]} — every "
                    f"bucket shape must shard evenly")
            if max_batch is not None and max_batch != bs[-1]:
                raise ValueError(
                    f"max_batch={max_batch} must equal the largest "
                    f"bucket {bs[-1]} when both are given — the largest "
                    f"bucket IS the chunking unit")
            self.buckets = bs
        self._n = self.mesh.shape[axis]
        self._rep = replicated(self.mesh)
        self._batch_sh = batch_sharding(self.mesh, axis)
        self._jit = jax.jit(functools.partial(graph._forward_outputs, train=False))
        self._params = None
        self.refresh_params()

    def refresh_params(self) -> None:
        """Snapshot the graph's current params onto the mesh (replicated)."""
        self._params = jax.device_put(self.graph.params, self._rep)

    # -- the SPMD dispatch ---------------------------------------------------

    def _dispatch(self, xs, pad_to: Optional[int] = None) -> List[jax.Array]:
        """One SPMD forward.  ``pad_to`` fixes the dispatch shape (the
        chunked path pads every chunk to ``max_batch`` so the program
        compiles once); otherwise pad to the next mesh-axis multiple."""
        b = xs[0].shape[0]
        pad = (pad_to - b) if pad_to is not None else (-b) % self._n
        placed = {}
        for name, x in zip(self.graph.input_names, xs):
            x = jnp.asarray(x)
            if pad:
                # pad on device — no host round trip for committed arrays
                x = jnp.pad(x, [(0, pad)] + [(0, 0)] * (x.ndim - 1))
            placed[name] = jax.device_put(x, self._batch_sh)
        outs = self._jit(self._params, placed)
        return [o[:b] for o in outs] if pad else list(outs)

    def bucket_for(self, b: int) -> Optional[int]:
        """The smallest declared bucket holding a ``b``-row request;
        None when ``b`` exceeds the largest (the chunked path) or no
        buckets are declared."""
        if self.buckets is None:
            return None
        for k in self.buckets:
            if k >= b:
                return k
        return None

    def output(self, *xs: jax.Array) -> List[jax.Array]:
        """Inference forward, batch fanned out over the mesh — the drop-in
        parallel counterpart of ``ComputationGraph.output`` (same return
        shape: one array per output layer).  With ``buckets`` declared,
        every dispatch shape is a bucket: requests pad up to the
        smallest bucket that holds them, oversized requests chunk by
        the largest with the tail padded to ITS covering bucket — the
        compiled-program set stays closed."""
        if not xs:
            raise ValueError("output() needs at least one input array")
        b = xs[0].shape[0]
        if self.buckets is not None:
            bucket = self.bucket_for(b)
            if bucket is not None:
                return self._dispatch(xs, pad_to=bucket)
            chunk = self.buckets[-1]
        elif self.max_batch is None or b <= self.max_batch:
            return self._dispatch(xs)
        else:
            chunk = self.max_batch
        chunks = []
        for lo in range(0, b, chunk):
            part = [x[lo:lo + chunk] for x in xs]
            pad_to = chunk
            if self.buckets is not None:
                # the tail chunk pads to its COVERING bucket, not the
                # chunking unit: a 70-row request dispatches as 64 + 8,
                # not 64 + 64 — fewer dead rows, and every oversized
                # request still lands inside the declared bucket set
                # (the closed-program-set contract holds for tails too)
                pad_to = self.bucket_for(part[0].shape[0]) or chunk
            chunks.append(self._dispatch(part, pad_to=pad_to))
        return [jnp.concatenate(parts) for parts in zip(*chunks)]

    __call__ = output
