"""Device-mesh construction — the cluster-runtime replacement.

The reference's "cluster" is a Spark context over ``local[4]`` threads
(dl4jGANComputerVision.java:303-309) with Kryo-serialized INDArrays crossing
process boundaries.  Here the cluster is a ``jax.sharding.Mesh``: XLA
partitions one program over the devices and inserts ICI collectives — no
serialization layer, no driver/executor round trips (SURVEY.md §2c).

Axis conventions used across the framework:
  ``data``  — batch / data parallelism (the only axis the reference needs)
  ``model`` — tensor parallelism (roadmap)
  ``seq``   — sequence/context parallelism, ring attention (long-context)

The reference's clusterless test trick (Spark ``local[4]``) maps to
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` with a CPU mesh —
the same collective code paths, no TPU required (SURVEY.md §4.4).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def data_mesh(n_devices: Optional[int] = None, axis: str = "data") -> Mesh:
    """1-D mesh over the batch axis — the ParameterAveragingTrainingMaster
    replacement's substrate.  ``n_devices=None`` uses every attached device."""
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(f"asked for {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def make_mesh(shape: Dict[str, int], devices: Optional[Sequence] = None) -> Mesh:
    """N-D mesh from {axis_name: size}, e.g. {"data": 4, "model": 2}.

    Axis order follows dict insertion order; put the fastest-varying
    (innermost, highest-bandwidth ICI) axis last.
    """
    devs = list(devices if devices is not None else jax.devices())
    sizes = list(shape.values())
    total = int(np.prod(sizes))
    if total > len(devs):
        raise ValueError(f"mesh {shape} needs {total} devices, have {len(devs)}")
    arr = np.array(devs[:total]).reshape(sizes)
    return Mesh(arr, tuple(shape.keys()))


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Sharding that splits the leading (batch) dim over ``axis``."""
    return NamedSharding(mesh, PartitionSpec(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def shard_batch(mesh: Mesh, *arrays, axis: str = "data"):
    """Place host arrays with the batch dim split across ``axis`` —
    the ``sc.parallelize(trainDataList)`` moment, minus Kryo."""
    sh = batch_sharding(mesh, axis)
    out = tuple(jax.device_put(a, sh) for a in arrays)
    return out if len(out) > 1 else out[0]
